"""fed-placement: pool-placed fed_maps must not capture driver state.

The PR-6 incident class: a pool-placed ``fed_map`` whose closure
captures a DRIVER-VARYING value (a program input, or an upstream
equation's output) cannot ship it — pool lanes send only mapped
leaves — so ``PoolPlacement.group_executor`` refuses at runtime with a
ValueError, far from the model code that caused it.  Per DrJAX
(PAPERS.md), placement invariants like this are checkable from the
jaxpr without running anything: this rule traces the pool-lane
fixtures registered in :mod:`..fed.lint_fixtures` under the CPU
backend, replays the exact varying-const computation the lowering
performs (``MapSpec.from_eqn`` + the baked-constvar logic of
``lowering._build_executors``), and flags offending equations at CI
time — with the captured operand's provenance chain in the finding.

Introspective, like ``fed-rule-completeness``: it imports jax and the
fed package, so it must (and does) force the CPU backend first — a
lint run can never dial the tunneled TPU plugin (CLAUDE.md environment
pitfalls).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional, Tuple

from .core import Finding, RepoContext, SourceFile, rule

_RULE = "fed-placement"
_FIXTURES = "pytensor_federated_tpu/fed/lint_fixtures.py"


@dataclass(frozen=True)
class CaptureFinding:
    """One driver-varying operand captured by one fed_map equation."""

    fixture: str
    eqn_index: int
    const_index: int
    provenance: Tuple[str, ...]
    lineno: Optional[int]  # user line from jax source_info, if known


def _user_lineno(eqn: Any, rel_hint: str) -> Optional[int]:
    """Best-effort source line for an equation: the innermost traceback
    frame inside the fixture module.  jax's source_info shape is not a
    stable API, so every access is defensive."""
    tb = getattr(getattr(eqn, "source_info", None), "traceback", None)
    if tb is None:
        return None
    try:
        frames = list(tb.frames)
    except Exception:
        return None
    tail = rel_hint.rsplit("/", 1)[-1]
    for frame in frames:
        fname = getattr(frame, "file_name", "") or ""
        if fname.endswith(tail):
            line = getattr(frame, "line_num", None)
            if isinstance(line, int) and line > 0:
                return line
    return None


def placement_findings(
    fn: Any, example_args: Tuple[Any, ...], *, fixture: str = "<fixture>"
) -> List[CaptureFinding]:
    """Trace ``fn`` and report every pool-refusable fed_map operand.
    Separated from the Rule wrapper so tests can run it against
    deliberately-broken programs without a synthetic repo."""
    import jax
    from jax.extend.core import Literal

    from ..fed.primitives import fed_map_p

    closed = jax.make_jaxpr(fn)(*example_args)
    jaxpr = closed.jaxpr
    # Top-level consts are concrete -> baked; under an enclosing trace
    # (not the lint's case) tracer consts would be driver-varying.
    from ..fed.primitives import is_tracer as _is_tracer

    baked = frozenset(
        v
        for v, c in zip(jaxpr.constvars, closed.consts)
        if not _is_tracer(c)
    )
    invar_pos = {v: i for i, v in enumerate(jaxpr.invars)}
    producers: Dict[Any, Tuple[int, Any]] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.outvars:
            producers[v] = (i, eqn)

    def provenance(var: Any) -> Tuple[str, ...]:
        chain: List[str] = []
        cur = var
        for _hop in range(5):  # bounded backward walk
            if cur in invar_pos:
                chain.append(f"program input #{invar_pos[cur]}")
                return tuple(chain)
            if cur in baked:  # pragma: no cover - baked is not varying
                chain.append("baked trace-time constant")
                return tuple(chain)
            prod = producers.get(cur)
            if prod is None:
                chain.append("enclosing-trace value (closure tracer)")
                return tuple(chain)
            idx, eqn = prod
            chain.append(f"output of `{eqn.primitive.name}` (eqn {idx})")
            nxt = next(
                (v for v in eqn.invars if not isinstance(v, Literal)),
                None,
            )
            if nxt is None:
                return tuple(chain)
            cur = nxt
        chain.append("...")
        return tuple(chain)

    out: List[CaptureFinding] = []
    for i, eqn in enumerate(jaxpr.eqns):
        if eqn.primitive is not fed_map_p:
            continue
        n_consts = eqn.params["n_consts"]
        for k, v in enumerate(eqn.invars[:n_consts]):
            if isinstance(v, Literal) or v in baked:
                continue
            out.append(
                CaptureFinding(
                    fixture=fixture,
                    eqn_index=i,
                    const_index=k,
                    provenance=provenance(v),
                    lineno=_user_lineno(eqn, _FIXTURES),
                )
            )
    return out


def _fixture_lines(src: SourceFile) -> Dict[str, int]:
    """fixture name -> line of its ``LintFixture(name=...)`` call."""
    out: Dict[str, int] = {}
    for node in src.nodes(ast.Call):
        callee = getattr(node.func, "id", "") or getattr(
            node.func, "attr", ""
        )
        if callee != "LintFixture":
            continue
        for kw in node.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                out[str(kw.value.value)] = node.lineno
    return out


@rule(
    _RULE,
    "pool-lane fed.program fixtures (fed/lint_fixtures.py) must not "
    "capture driver-varying operands in fed_map closures — traced from "
    "the jaxpr CPU-only, provenance chain in the finding",
    scope="repo",
)
def check_fed_placement(ctx: RepoContext) -> Iterator[Finding]:
    src = ctx.by_rel.get(_FIXTURES)
    if src is None:
        return
    # CPU-only introspection: never let a lint run dial the tunneled
    # TPU plugin (CLAUDE.md environment pitfalls).
    from ..utils import force_cpu_backend

    force_cpu_backend()
    from ..fed import lint_fixtures

    lines = _fixture_lines(src)
    for fixture in lint_fixtures.FIXTURES:
        fn, args = fixture.build()
        for cap in placement_findings(fn, args, fixture=fixture.name):
            prov = " <- ".join(cap.provenance)
            yield Finding(
                rule=_RULE,
                path=_FIXTURES,
                line=cap.lineno or lines.get(fixture.name, 1),
                message=(
                    f"fixture `{fixture.name}`: fed_map (eqn "
                    f"{cap.eqn_index}) closes over driver-varying "
                    f"operand #{cap.const_index} ({prov}) — a pool "
                    "placement ships only MAPPED leaves, so this "
                    "raises PoolPlacement's ValueError at runtime; "
                    "route driver state through fed_broadcast instead "
                    "of closure capture"
                ),
                chain=(f"fed_map eqn {cap.eqn_index}, captured operand "
                       f"#{cap.const_index}",) + cap.provenance,
            )
