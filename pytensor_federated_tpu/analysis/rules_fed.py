"""fed-rule-completeness: every fed primitive carries its full rule set.

The DrJAX-style contract from PR 6 (:mod:`..fed.primitives`): a
federated primitive is only a primitive — rather than a trap — if it
participates in EVERY transformation a model author will reach for.
A primitive missing its transpose silently fails at ``jax.grad``; one
missing batching fails at ``vmap`` inside NUTS; and the failure
surfaces far from the registration site.  This rule is
*introspective*, not textual: it imports the module and asks jax's own
registries, so a rule registered through any helper
(``ad.deflinear2``, direct dict assignment, decorators) counts.

Required per primitive: abstract-eval, JVP, transpose, batching.
(Impl and MLIR lowering are exercised by the tier-1 suite directly —
a primitive with no impl cannot pass a single test — so they are not
re-checked here.)
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Sequence, Tuple

from .core import Finding, SourceFile, rule

_RULE = "fed-rule-completeness"
_FED = "pytensor_federated_tpu/fed/primitives.py"

_REQUIRED = ("abstract_eval", "jvp", "transpose", "batching")


def missing_rules(module: object) -> List[Tuple[str, object, List[str]]]:
    """Introspect ``module`` for jax primitives with incomplete rule
    sets -> ``[(attr_name, primitive, [missing...])]``.  Separated from
    the Rule wrapper so tests can run it against fixture modules."""
    from jax.extend import core as jex_core
    from jax.interpreters import ad, batching

    out: List[Tuple[str, object, List[str]]] = []
    for attr, prim in sorted(vars(module).items()):
        if not isinstance(prim, jex_core.Primitive):
            continue
        missing: List[str] = []
        # def_abstract_eval sets an instance attribute; the class
        # default is a bound method that raises NotImplementedError,
        # so presence must be checked on the instance dict.
        if "abstract_eval" not in vars(prim):
            missing.append("abstract_eval")
        if prim not in ad.primitive_jvps:
            missing.append("jvp")
        if prim not in ad.primitive_transposes:
            missing.append("transpose")
        if prim not in batching.primitive_batchers:
            missing.append("batching")
        if missing:
            out.append((attr, prim, missing))
    return out


def _definition_lines(src: SourceFile) -> Dict[str, int]:
    """attr name -> line of its ``X = ...Primitive(...)`` assignment."""
    out: Dict[str, int] = {}
    for node in src.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name) and isinstance(
                node.value, ast.Call
            ):
                callee = getattr(node.value.func, "attr", "") or getattr(
                    node.value.func, "id", ""
                )
                if callee == "Primitive":
                    out[tgt.id] = node.lineno
    return out


@rule(
    _RULE,
    "every registered primitive in fed/primitives.py has abstract-eval, "
    "JVP, transpose, and batching rules (introspected via jax "
    "registries, not text)",
    scope="repo",
)
def check_fed_rule_completeness(
    sources: Sequence[SourceFile],
) -> Iterator[Finding]:
    by_rel = {s.rel: s for s in sources}
    src = by_rel.get(_FED)
    if src is None:
        return
    # CPU-only introspection: never let a lint run dial the tunneled
    # TPU plugin (CLAUDE.md environment pitfalls).
    from ..utils import force_cpu_backend

    force_cpu_backend()
    from ..fed import primitives as fed_primitives

    lines = _definition_lines(src)
    for attr, prim, missing in missing_rules(fed_primitives):
        yield src.finding(
            _RULE,
            lines.get(attr, 1),
            f"primitive `{prim}` ({attr}) is missing "
            f"{', '.join(missing)} rule(s) — it will fail inside "
            "grad/vmap far from this registration site",
        )
