"""shared-state-lock: cross-context mutation requires a lock.

The concurrency census that motivated graftflow: 38 thread / lock /
executor sites across 19 files, and the two incidents the repo has
already paid for (the PR-5 loop-blocking shim, the PR-7 sync-shim
lanes) were both "code ran in a context its author didn't picture".
This rule checks the mutation half of that hazard: an instance or
module attribute written from TWO OR MORE concurrency contexts —
thread entrypoint (``threading.Thread(target=…)``), event loop
(``async def`` / ``create_task``), executor (``run_in_executor`` /
``submit``) — where at least one write site holds no inferred lock.

Machinery (:mod:`.dataflow`): contexts propagate along the shared call
graph from the discovered entrypoints; write sites are assignments /
augassigns / subscript stores / ``del`` / container-mutator calls on
``self`` attributes and declared module globals (``__init__`` exempt —
construction precedes sharing); a write is locked when it sits in a
``with <lock-ish>:`` region or in a helper whose every in-package
caller is lock-held (one-level fixpoint).  Findings carry a witness
chain per context — how the probe daemon and the serving loop each
reach the write.

Scope: ``routing/``, ``service/``, ``telemetry/``, ``faultinject/`` —
the packages the census counted (seeded against routing/pool.py's
probe daemon and telemetry's registries).  Single-context writes and
everywhere-locked attributes are fine; GIL-atomicity arguments are
deliberately NOT modeled (a `+=` is already two bytecodes), so a
deliberate lock-free design suppresses inline with its justification.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, Iterator, List, Set, Tuple

from .core import Finding, RepoContext, rule
from .dataflow import (
    WriteSite,
    context_chains,
    lock_held_functions,
    mutation_sites,
)

_RULE = "shared-state-lock"

_SCOPE_PREFIXES = (
    "pytensor_federated_tpu/routing/",
    "pytensor_federated_tpu/service/",
    "pytensor_federated_tpu/telemetry/",
    "pytensor_federated_tpu/faultinject/",
)


@rule(
    _RULE,
    "instance/module attributes mutated from >=2 concurrency contexts "
    "(thread / event loop / executor) need a lock on every write path "
    "(routing/, service/, telemetry/, faultinject/)",
    scope="repo",
)
def check_shared_state_lock(ctx: RepoContext) -> Iterator[Finding]:
    graph = ctx.graph
    witness = context_chains(graph)
    lock_held = lock_held_functions(graph)

    # (rel, owner class or "<module>", attr) -> write sites
    groups: Dict[Tuple[str, str, str], List[WriteSite]] = defaultdict(list)
    for src in ctx:
        if not src.is_python or not src.rel.startswith(_SCOPE_PREFIXES):
            continue
        for site in mutation_sites(graph, src.tree, src.rel):
            fn = graph.functions[site.qname]
            owner = (fn.cls or "<module>") if site.is_self else "<module>"
            groups[(site.rel, owner, site.target)].append(site)

    for (rel, owner, target), sites in sorted(groups.items()):
        contexts: Set[str] = set()
        per_site_ctx: List[Tuple[WriteSite, Set[str]]] = []
        for site in sites:
            ctxs = set(witness.get(site.qname, {}))
            per_site_ctx.append((site, ctxs))
            contexts |= ctxs
        if len(contexts) < 2:
            continue
        unlocked = [
            site
            for site, ctxs in per_site_ctx
            if ctxs and not site.locked and site.qname not in lock_held
        ]
        if not unlocked:
            continue
        # One finding per unlocked write site (suppressions are
        # per-line); the chain shows one witness path per context.
        chain_hops: List[str] = []
        for label in sorted(contexts):
            for site, ctxs in per_site_ctx:
                if label in ctxs:
                    root, chain = witness[site.qname][label]
                    root_fn = graph.functions[root]
                    hops = graph.render_chain(chain) or (root_fn.display,)
                    chain_hops.append(
                        f"[{label}] " + " -> ".join(hops)
                        + f" -> writes `{target}` at {site.rel}:{site.lineno}"
                    )
                    break
        for site in unlocked:
            fn = graph.functions[site.qname]
            yield Finding(
                rule=_RULE,
                path=rel,
                line=site.lineno,
                message=(
                    f"`{target}` (owner {owner}) is mutated from "
                    f"{len(contexts)} concurrency contexts "
                    f"({', '.join(sorted(contexts))}) but this write in "
                    f"`{fn.name}` holds no lock — take the owner's lock "
                    "or make the attribute context-private"
                ),
                chain=tuple(chain_hops),
            )
