"""graftlint — the repo's design invariants as machine-checked rules.

Every hard-won invariant in this codebase used to live in CLAUDE.md
prose and reviewer vigilance; the PR-5 chaos harness then caught two
regressions (an event-loop-blocking fault shim, an unclassified
asyncio error) that a static checker could have rejected at commit
time.  This package is that checker: one AST-based rule per invariant,
a driver (``python -m pytensor_federated_tpu.analysis`` /
``tools/graftlint.py``) that walks the package plus
``native/cpp_node.cpp``, per-rule inline suppressions
(``# graftlint: disable=<rule> -- why``), human and ``--json`` output,
and a nonzero exit on findings — wired in front of the CI test matrix
so new I/O lanes inherit the invariants automatically.

Since PR 8 the per-function rules share **graftflow**, an
interprocedural engine: :mod:`.graph` builds one whole-package call
graph (heuristic method resolution + concurrency-entrypoint
discovery: ``Thread(target=…)``, ``run_in_executor``, ``create_task``,
daemon probe loops) and :mod:`.dataflow` propagates contexts along it
(async-ness, thread/loop/executor membership, held locks).  Findings
from the graftflow rules carry the propagation chain.

Rule catalog (docs/static-analysis.md maps each rule to the incident
or invariant that motivated it; the meta-test keeps the two in sync):

- ``async-blocking`` — no blocking primitive *reachable* from an async
  context in service//routing//faultinject/ — transitive over the call
  graph (:mod:`.rules_async`)
- ``loop-affinity`` — grpc.aio channels flow through the
  (token,pid,thread,loop)-keyed cache (:mod:`.rules_loop`)
- ``loop-escape`` — grpc.aio values must not flow into globals,
  instance attributes, or cross-thread containers
  (:mod:`.rules_flow`)
- ``shared-state-lock`` — attributes mutated from >=2 concurrency
  contexts need a lock on every write path (:mod:`.rules_race`)
- ``resource-leak`` — no opened-and-dropped sockets/channels/files
  (:mod:`.rules_resource`)
- ``wire-registry`` — flag bits and field numbers match
  :mod:`..service.wire_registry` across all three wire
  implementations (:mod:`.rules_wire`)
- ``wire-loudness`` — WireError propagates; no swallowed decode
  failures (:mod:`.rules_wire`)
- ``fault-shim-coverage`` — chaos reaches every owned I/O seam
  (:mod:`.rules_shim`; reachability on the shared graph)
- ``fed-rule-completeness`` — every fed primitive has
  abstract-eval/JVP/transpose/batching rules (:mod:`.rules_fed`)
- ``fed-placement`` — pool-lane fed.program fixtures must not capture
  driver-varying operands (jaxpr introspection,
  :mod:`.rules_fedflow`)
- ``observability-drift`` — metric families and flightrec events match
  docs/observability.md both ways (:mod:`.rules_obs`)
- ``unbounded-wait`` — recv/readexactly/stream-read calls in
  service//routing/ arm a timeout or sit under an armed watchdog
  deadline on every path (:mod:`.rules_wait`)
- ``unbounded-spin`` — while-loops around ``time.sleep`` in
  service//routing//gateway/ carry a deadline marker, a TimeoutError
  raise, or a deadline-checking callee (:mod:`.rules_spin`)
"""

from .core import (
    Finding,
    RULES,
    RepoContext,
    Rule,
    SourceFile,
    default_targets,
    load_sources,
    render_human,
    render_json,
    render_sarif,
    repo_root,
    rule,
    run,
)

# Importing the rules modules registers them into RULES.
from . import rules_async  # noqa: F401
from . import rules_fed  # noqa: F401
from . import rules_fedflow  # noqa: F401
from . import rules_flow  # noqa: F401
from . import rules_loop  # noqa: F401
from . import rules_obs  # noqa: F401
from . import rules_race  # noqa: F401
from . import rules_resource  # noqa: F401
from . import rules_shim  # noqa: F401
from . import rules_spin  # noqa: F401
from . import rules_wait  # noqa: F401
from . import rules_wire  # noqa: F401

__all__ = [
    "Finding",
    "RULES",
    "RepoContext",
    "Rule",
    "SourceFile",
    "default_targets",
    "load_sources",
    "render_human",
    "render_json",
    "render_sarif",
    "repo_root",
    "rule",
    "run",
]
