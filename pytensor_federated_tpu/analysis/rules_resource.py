"""resource-leak: handles must close on every exit path.

Sockets, grpc channels, and files opened in a function must either be
scoped (``with`` / ``async with``), be closed in that function, or
visibly transfer ownership (returned, yielded, stored on an object,
or handed to another call).  A handle that does none of these leaks on
EVERY path; the chip-side incidents make this worse than a fd leak —
a leaked half-open TCP connection to a wedged node holds its frame
lock forever (service/tcp.py's lock-step contract), and channels
additionally pin their event loop (``loop-escape``).

Per-function and deliberately modest (no CFG): the rule flags the
"opened and dropped" shape —

- an open call whose result is never bound (``socket.socket().connect``
  chains, probe one-liners);
- a local handle that is never ``close()``-d / ``shutdown()``-d,
  never returned or yielded, never stored, and never passed on.

What it does NOT try to prove: that a present ``close()`` executes on
the exception path (try/finally discipline) — exception-safety of
close is a CFG property; the fixture tests document the gap and
``with`` remains the recommended fix.  Scope: the whole package except
tests (C++ sources are out of scope; the npwire C++ node manages its
fds RAII-style).
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from .core import Finding, SourceFile, rule
from .graph import own_body

_RULE = "resource-leak"

_SCOPE_PREFIX = "pytensor_federated_tpu/"

#: dotted-call suffixes that allocate a closeable handle.
_OPEN_SUFFIXES = (
    "socket.socket",
    "socket.create_connection",
    "socket.socketpair",
    "aio.insecure_channel",
    "aio.secure_channel",
    "grpc.insecure_channel",
    "grpc.secure_channel",
)
_OPEN_EXACT = {"open", "create_connection", "socketpair"}

_CLOSE_METHODS = {"close", "shutdown", "terminate", "aclose"}


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover
        return ""


def _is_open_call(node: ast.AST) -> Optional[str]:
    if not isinstance(node, ast.Call):
        return None
    dotted = _unparse(node.func)
    if dotted.endswith(_OPEN_SUFFIXES) or dotted in _OPEN_EXACT:
        return dotted
    return None


def _function_findings(
    src: SourceFile, fn: ast.AST
) -> Iterator[Finding]:
    nodes = own_body(fn)  # shared walk: nested defs/lambdas excluded
    scoped: Set[int] = set()  # id() of with-item open calls
    for node in nodes:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                expr = item.context_expr
                if isinstance(expr, ast.Await):
                    expr = expr.value
                if _is_open_call(expr) is not None:
                    scoped.add(id(expr))

    # local name -> (open call, dotted) for `h = open(...)` bindings
    bound: dict = {}
    bound_ids: Set[int] = set()
    for node in nodes:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            value = node.value
            if isinstance(value, ast.Await):
                value = value.value
            dotted = _is_open_call(value)
            if dotted is not None and id(value) not in scoped:
                if isinstance(tgt, ast.Name):
                    bound[tgt.id] = (node, dotted)
                    bound_ids.add(id(value))
                else:
                    # h.attr = open(...) / d[k] = open(...): ownership
                    # stored — lifecycle belongs to the container.
                    bound_ids.add(id(value))

    # Inline open calls that are neither scoped nor bound anywhere.
    for node in nodes:
        dotted = _is_open_call(node)
        if (
            dotted is not None
            and id(node) not in scoped
            and id(node) not in bound_ids
            and not _is_consumed(node, nodes)
        ):
            yield src.finding(
                _RULE,
                node.lineno,
                f"`{dotted}(...)` opens a handle that is never bound — "
                "no path can close it; use `with` (or bind and close)",
            )

    for name, (assign, dotted) in bound.items():
        if _name_released(name, nodes):
            continue
        yield src.finding(
            _RULE,
            assign.lineno,
            f"`{name} = {dotted}(...)` is never closed, returned, "
            "stored, or handed off on any path out of this function — "
            "wrap it in `with {name} ...` or close it in a `finally`".replace(
                "{name}", name
            ),
        )


def _is_consumed(call: ast.AST, nodes: List[ast.AST]) -> bool:
    """An unbound open call is consumed when some enclosing expression
    uses its value: returned, awaited into a with, passed as an
    argument, or the receiver of an attribute access that is NOT a
    plain method-chain leak (`socket.socket().connect(...)` still
    leaks — attribute access alone does not count)."""
    for node in nodes:
        if isinstance(node, ast.Return) and _contains(node.value, call):
            return True
        if isinstance(node, ast.Call):
            if any(_contains(a, call) for a in node.args) or any(
                _contains(kw.value, call) for kw in node.keywords
            ):
                return True
        if isinstance(node, (ast.Assign, ast.AnnAssign)) and _contains(
            getattr(node, "value", None), call
        ):
            # bound through a wrapper expression: treated as handed off
            return True
        if isinstance(node, ast.Yield) and _contains(node.value, call):
            return True
    return False


def _contains(tree: Optional[ast.AST], needle: ast.AST) -> bool:
    if tree is None:
        return False
    return any(n is needle for n in ast.walk(tree))


def _name_released(name: str, nodes: List[ast.AST]) -> bool:
    for node in nodes:
        # h.close() / h.shutdown(...)
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _CLOSE_METHODS
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == name
        ):
            return True
        # used as a with context later: `with h:` / contextlib stacks
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if (
                    isinstance(item.context_expr, ast.Name)
                    and item.context_expr.id == name
                ):
                    return True
        # escapes: returned / yielded / stored / passed along
        if isinstance(node, (ast.Return, ast.Yield)) and _mentions(
            node.value, name
        ):
            return True
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(
                    tgt, (ast.Attribute, ast.Subscript)
                ) and _mentions(node.value, name):
                    return True
        if isinstance(node, ast.Call):
            if any(_mentions(a, name) for a in node.args) or any(
                _mentions(kw.value, name) for kw in node.keywords
            ):
                return True
    return False


def _mentions(tree: Optional[ast.AST], name: str) -> bool:
    if tree is None:
        return False
    return any(
        isinstance(n, ast.Name) and n.id == name for n in ast.walk(tree)
    )


@rule(
    _RULE,
    "sockets/grpc channels/files must be scoped with `with`, closed, or "
    "visibly hand off ownership — no opened-and-dropped handles",
)
def check_resource_leak(src: SourceFile) -> Iterator[Finding]:
    if not src.is_python or not src.rel.startswith(_SCOPE_PREFIX):
        return
    for fn in src.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
        yield from _function_findings(src, fn)
