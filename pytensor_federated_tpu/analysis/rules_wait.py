"""unbounded-wait: every reply wait in the I/O stack can be bounded.

ISSUE 10's motivating hole: a server that ACCEPTS a request and then
never replies.  Every failure the transports classified until now —
refused connects, resets, corrupt frames — is an event; silence is
not, so a bare ``recv``/``readexactly``/``stream.read`` blocked until
the process-wide watchdog fired instead of failing over inside the
caller's deadline.  The deadline-aware reads added by ISSUE 10
(``settimeout`` derived from the ambient budget on the sync lanes,
``asyncio.wait_for`` on the stream lane) close the hole; this rule
keeps it closed: a NEW wait primitive in ``service/`` or ``routing/``
must either arm a bound itself or sit under an armed watchdog deadline
on every call path.

Semantics, over the shared graftflow call graph:

- *wait sites*: calls to ``.recv`` / ``.recv_into`` / ``.readexactly``,
  and ``.read`` on a stream-ish receiver (name matching
  ``stream``/``_rfile``/``reader`` — socket-backed readers, not plain
  files).
- *locally bounded*: the enclosing function's own body arms a bound —
  a ``settimeout(...)`` call, an ``asyncio.wait_for(...)`` wrapper,
  the shared ``bounded_reader(...)`` helper (service/deadline.py — it
  re-arms ``settimeout`` from the ambient budget before every chunk),
  or a ``with …armed(…)`` watchdog span.  (Function-granular on
  purpose:
  a function that derives a timeout for SOME paths owns the decision
  for all of them; the deadline tests pin the behavior.)
- *covered by callers* (the interprocedural half, same fixpoint shape
  as graftflow's lock inference): a function whose EVERY in-package
  call edge comes from a bounded/covered caller — or lexically from
  inside a caller's ``with …armed(…)`` span — inherits the bound.
  Functions no in-package caller reaches are entrypoints and inherit
  nothing.

A deliberate exception needs an inline suppression with a reason —
the one shipped case is the SERVER's frame loop, whose idle state IS
an unbounded wait for the next request
(``service/tcp.py::_recv_exact``).  Findings carry the uncovered call
chain from an entrypoint, rendered by the graftflow engine.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .core import Finding, RepoContext, rule
from .graph import CallGraph, own_body

_RULE = "unbounded-wait"

_SCOPE_PREFIXES = (
    "pytensor_federated_tpu/service/",
    "pytensor_federated_tpu/routing/",
    # The gateway accept tier (ISSUE 12): every downstream payload
    # read, upstream round-trip, and reply future must be bounded.
    "pytensor_federated_tpu/gateway/",
)

#: Attribute calls that park the caller until the peer says otherwise.
_WAIT_METHODS = {"recv", "recv_into", "readexactly"}

#: ``.read`` only counts on receivers that look like socket-backed
#: readers — a plain file read terminates on its own.
_STREAMISH = re.compile(r"stream|_rfile|reader", re.IGNORECASE)

#: Body calls that arm a bound for the whole function.
#: ``bounded_reader`` is the shared client-lane helper
#: (service/deadline.py): it re-arms ``settimeout`` from the ambient
#: budget before every chunk — the TCP socket lane and the shm
#: doorbell both read through it, so the arming call the rule used to
#: see inline now lives there.
#: ``recv_budget_s`` derives a concrete recv bound from the ambient
#: deadline (service/deadline.py) — the ring lane passes it straight
#: into ``Ring.recv(timeout_s=...)``, which re-checks liveness every
#: park slice, so calling it is the same arming act as ``settimeout``.
_ARMING_CALLS = {"settimeout", "wait_for", "bounded_reader", "recv_budget_s"}

#: ``with …armed(…)`` — the watchdog deadline span.
_ARMED_ATTR = "armed"


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on exprs
        return ""


def wait_sites(fn_node: ast.AST) -> Iterator[Tuple[ast.Call, str]]:
    """(call, description) for every wait primitive in the function's
    own body."""
    for node in own_body(fn_node):
        if not isinstance(node, ast.Call) or not isinstance(
            node.func, ast.Attribute
        ):
            continue
        name = node.func.attr
        if name in _WAIT_METHODS:
            yield node, f"`{_unparse(node.func)}(...)`"
        elif name == "read" and _STREAMISH.search(
            _unparse(node.func.value)
        ):
            yield node, f"`{_unparse(node.func)}(...)`"


def _armed_spans(fn_node: ast.AST) -> List[Tuple[int, int]]:
    """(start, end) line spans of ``with …armed(…):`` bodies."""
    spans: List[Tuple[int, int]] = []
    for node in own_body(fn_node):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                expr = item.context_expr
                if (
                    isinstance(expr, ast.Call)
                    and isinstance(expr.func, ast.Attribute)
                    and expr.func.attr == _ARMED_ATTR
                ):
                    spans.append(
                        (
                            node.lineno,
                            int(getattr(node, "end_lineno", node.lineno)),
                        )
                    )
                    break
    return spans


def _locally_bounded(fn_node: ast.AST) -> bool:
    """Whether the function's own body arms a bound (settimeout /
    wait_for / an armed watchdog span)."""
    for node in own_body(fn_node):
        if isinstance(node, ast.Call):
            func = node.func
            name = (
                func.attr
                if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else ""
            )
            if name in _ARMING_CALLS:
                return True
    return bool(_armed_spans(fn_node))


def _covered_functions(graph: CallGraph) -> Set[str]:
    """Functions every in-package call path reaches with a bound armed
    — bounded callers, or call sites inside armed watchdog spans —
    fixpoint over the call graph (the lock-inference shape)."""
    bounded = {
        q for q, f in graph.functions.items() if _locally_bounded(f.node)
    }
    span_cache: Dict[str, List[Tuple[int, int]]] = {}

    def spans_of(qname: str) -> List[Tuple[int, int]]:
        if qname not in span_cache:
            span_cache[qname] = _armed_spans(graph.functions[qname].node)
        return span_cache[qname]

    covered: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for qname in graph.functions:
            if qname in covered or qname in bounded:
                continue
            callers = graph.callers_of(qname)
            if not callers:
                continue
            ok = True
            for edge in callers:
                caller_ok = (
                    edge.caller in covered or edge.caller in bounded
                )
                under_armed = any(
                    lo <= edge.lineno <= hi
                    for lo, hi in spans_of(edge.caller)
                )
                if not (caller_ok or under_armed):
                    ok = False
                    break
            if ok:
                covered.add(qname)
                changed = True
    return covered | bounded


def _witness_chain(
    graph: CallGraph, qname: str, safe: Set[str], limit: int = 8
) -> Tuple[str, ...]:
    """One uncovered caller chain up from ``qname`` toward an
    entrypoint (callers outside ``safe``), for the finding's hops."""
    hops: List[str] = []
    seen = {qname}
    cur = qname
    for _ in range(limit):
        unsafe = [
            e
            for e in graph.callers_of(cur)
            if e.caller not in safe and e.caller not in seen
        ]
        if not unsafe:
            break
        edge = unsafe[0]
        caller = graph.functions[edge.caller]
        hops.append(
            f"{caller.display} (calls {graph.functions[cur].name} at "
            f"{caller.rel}:{edge.lineno})"
        )
        seen.add(edge.caller)
        cur = edge.caller
    hops.reverse()
    return tuple(hops)


@rule(
    _RULE,
    "recv/readexactly/stream-read calls in service/ and routing/ must "
    "arm a timeout (settimeout / wait_for) or sit under an armed "
    "watchdog deadline on every call path — a peer that accepts then "
    "never replies must fail inside the caller's budget",
    scope="repo",
)
def check_unbounded_wait(ctx: RepoContext) -> Iterator[Finding]:
    graph = ctx.graph
    safe = _covered_functions(graph)
    for qname in sorted(graph.functions):
        fn = graph.functions[qname]
        if not fn.rel.startswith(_SCOPE_PREFIXES):
            continue
        if qname in safe:
            continue
        for call, desc in wait_sites(fn.node):
            chain = _witness_chain(graph, qname, safe)
            yield Finding(
                rule=_RULE,
                path=fn.rel,
                line=call.lineno,
                message=(
                    f"unbounded wait {desc} in {fn.name}: no timeout "
                    "armed on the path (settimeout / asyncio.wait_for "
                    "/ a `with watchdog.armed(...)` span) — a peer "
                    "that accepts and never replies blocks this call "
                    "forever; derive a bound from the ambient "
                    "deadline (service/deadline.py) or arm the "
                    "watchdog, or suppress with a reason if waiting "
                    "IS the idle state (server frame loops)"
                ),
                chain=chain
                + (f"unbounded wait at {fn.rel}:{call.lineno}",),
            )
