"""loop-escape: grpc.aio values must not outlive their event loop.

``loop-affinity`` (PR 7, :mod:`.rules_loop`) polices where channels
are CREATED.  This rule polices where their values FLOW: a grpc.aio
channel, multicallable, or stream stashed in a module global, an
instance attribute, or a cross-thread container is readable from
another loop — exactly the resurrection of the bug the
(token,pid,thread,loop)-keyed connection cache exists to kill
(CLAUDE.md design invariants; the cache and its purge live in
``service/client.py``, which is therefore the one exempt file).

Dataflow, per function, over the shared graph:

- *taint seeds*: ``grpc.aio.*_channel(...)`` calls; ``.unary_unary`` /
  ``.unary_stream`` / ``.stream_unary`` / ``.stream_stream`` on a
  tainted value (multicallables hold their channel); CALLS of a
  tainted value (the resulting call/stream object is loop-bound);
  ``await`` of a tainted expression; calls to in-package functions
  that RETURN tainted values (computed as a fixpoint over the call
  graph — the interprocedural hop that catches
  ``self.ch = self._make_channel()``).
- *escapes*: assignment to any attribute (``self.x = ch`` /
  ``obj.x = ch``), to a module global, into a subscript of either, or
  handed to a container mutator (``.append`` / ``.put`` / …) whose
  receiver is an attribute or module global.

A scoped ``async with`` channel never escapes by construction and
needs no special case here — its value is consumed by the ``with``
item, and storing it FROM the with body is still flagged.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from .core import Finding, RepoContext, rule
from .dataflow import _MUTATOR_METHODS  # shared container-write table
from .graph import CallGraph, FuncNode, own_body

_RULE = "loop-escape"

_CACHE_FILE = "pytensor_federated_tpu/service/client.py"

_MULTICALLABLE_METHODS = {
    "unary_unary",
    "unary_stream",
    "stream_unary",
    "stream_stream",
}


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover
        return ""


def _is_channel_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    dotted = _unparse(node.func)
    return dotted.endswith(("aio.insecure_channel", "aio.secure_channel"))


class _FnFlow:
    """One function's forward taint pass (order-insensitive fixpoint:
    two sweeps over simple assignments cover the straight-line flows a
    linter should chase)."""

    def __init__(
        self,
        fn: FuncNode,
        graph: CallGraph,
        sources: Set[str],
    ) -> None:
        self.fn = fn
        self.graph = graph
        self.source_fns = sources  # qnames returning tainted values
        self.tainted_names: Set[str] = set()
        self.returns_tainted = False
        self.escapes: List[Tuple[int, str, str]] = []  # (line, target, why)
        self._globals: Optional[Set[str]] = None

    # -- taint ------------------------------------------------------------

    def _call_returns_tainted(self, call: ast.Call) -> bool:
        if _is_channel_call(call):
            return True
        func = call.func
        if isinstance(func, ast.Attribute):
            if func.attr in _MULTICALLABLE_METHODS and self._tainted(
                func.value
            ):
                return True
            # stream = method(); resp = stub(req): call OF a tainted
            # value yields a loop-bound call object.
        if self._tainted(func):
            return True
        edges = [
            e
            for e in self.graph.callees_of(self.fn.qname)
            if e.lineno == call.lineno and e.callee in self.source_fns
        ]
        return bool(edges)

    def _tainted(self, expr: ast.expr) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in self.tainted_names
        if isinstance(expr, ast.Await):
            return self._tainted(expr.value)
        if isinstance(expr, ast.Call):
            return self._call_returns_tainted(expr)
        return False

    # -- walk -------------------------------------------------------------

    def run(self) -> None:
        body = own_body(self.fn.node)  # shared walk (no nested defs)
        for _sweep in range(2):
            for node in body:
                if isinstance(node, ast.Assign) and self._tainted(node.value):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            self.tainted_names.add(tgt.id)
        for node in body:
            self._check(node)

    def _escape(self, lineno: int, target: ast.expr, why: str) -> None:
        self.escapes.append((lineno, _unparse(target), why))

    def _check(self, node: ast.AST) -> None:
        if isinstance(node, ast.Assign):
            if not self._tainted(node.value):
                return
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute):
                    self._escape(
                        node.lineno, tgt, "stored on an instance/object "
                        "attribute readable from another loop"
                    )
                elif isinstance(tgt, ast.Subscript):
                    self._escape(
                        node.lineno,
                        tgt,
                        "stored into a container another loop/thread "
                        "can read",
                    )
                elif isinstance(tgt, ast.Name) and self._is_global(tgt.id):
                    self._escape(
                        node.lineno, tgt, "stored in a module global"
                    )
        elif isinstance(node, ast.Return):
            if node.value is not None and self._tainted(node.value):
                self.returns_tainted = True
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _MUTATOR_METHODS | {"put", "put_nowait"}
            and any(self._tainted(a) for a in node.args)
            and isinstance(node.func.value, (ast.Attribute, ast.Name))
        ):
            receiver = node.func.value
            if isinstance(receiver, ast.Attribute) or (
                isinstance(receiver, ast.Name)
                and self._is_global(receiver.id)
            ):
                self._escape(
                    node.lineno,
                    receiver,
                    f"handed to `.{node.func.attr}(...)` on a shared "
                    "container",
                )

    def _is_global(self, name: str) -> bool:
        if self._globals is None:
            decls: Set[str] = set()
            for n in ast.walk(self.fn.node):
                if isinstance(n, ast.Global):
                    decls.update(n.names)
            self._globals = decls
        return name in self._globals


def _channel_flows(
    graph: CallGraph, skip_rel: str
) -> "dict[str, _FnFlow]":
    """One taint pass per function, fixpoint over channel-RETURNING
    functions driven by a worklist: when a function is discovered to
    be a source, only its CALLERS can change, so only they re-run —
    the full-package pass happens once, not once per round.  Returns
    the final per-function flows so the rule consumes them directly
    instead of re-analyzing."""
    sources: Set[str] = set()
    flows: dict = {}
    pending = {
        q for q, f in graph.functions.items() if f.rel != skip_rel
    }
    while pending:
        new_sources: List[str] = []
        for qname in pending:
            flow = _FnFlow(graph.functions[qname], graph, sources)
            flow.run()
            flows[qname] = flow
            if flow.returns_tainted and qname not in sources:
                sources.add(qname)
                new_sources.append(qname)
        pending = set()
        for src_q in new_sources:
            for edge in graph.callers_of(src_q):
                if graph.functions[edge.caller].rel != skip_rel:
                    pending.add(edge.caller)
    return flows


@rule(
    _RULE,
    "grpc.aio channels/multicallables/streams must not flow into module "
    "globals, instance attributes, or cross-thread containers outside "
    "the (token,pid,thread,loop)-keyed cache (service/client.py)",
    scope="repo",
)
def check_loop_escape(ctx: RepoContext) -> Iterator[Finding]:
    graph = ctx.graph
    flows = _channel_flows(graph, _CACHE_FILE)
    sources = {q for q, fl in flows.items() if fl.returns_tainted}
    for qname in sorted(flows):
        fn = graph.functions[qname]
        flow = flows[qname]
        for lineno, target, why in flow.escapes:
            chain: Tuple[str, ...] = (fn.display,)
            # If the taint arrived through a channel-source call, name
            # the producer in the chain — the interprocedural hop.
            producers = [
                e
                for e in graph.callees_of(qname)
                if e.callee in sources
            ]
            if producers:
                prod = graph.functions[producers[0].callee]
                chain = (
                    prod.display,
                    f"returns a loop-bound grpc.aio value to "
                    f"{fn.rel}:{producers[0].lineno}",
                ) + chain
            yield Finding(
                rule=_RULE,
                path=fn.rel,
                line=lineno,
                message=(
                    f"loop-bound grpc.aio value escapes into `{target}` "
                    f"— {why}; channels are bound to their creation "
                    "loop, so route connections through "
                    "service.client.ClientPrivates (the "
                    "(token,pid,thread,loop)-keyed cache) or keep them "
                    "scoped to one coroutine"
                ),
                chain=chain,
            )
