"""unbounded-spin: every spin/poll loop in the I/O stack terminates.

ISSUE 18's motivating hole: the ring lane replaced blocking socket
reads with POLL LOOPS — a producer polling for ring space, a consumer
spinning on a seqlock word before parking.  ``unbounded-wait`` cannot
see these: there is no ``recv`` to flag, just a ``while`` that
re-checks shared memory (or any other condition) around a
``time.sleep``.  A peer that dies mid-update leaves the condition
false FOREVER, so an unbounded poll loop is the same accept-then-
silence hang the deadline work closed — it just spells itself
differently.

Semantics, over the shared graftflow call graph:

- *spin sites*: ``while`` loops in ``service/`` / ``routing/`` /
  ``gateway/`` whose body calls ``time.sleep(...)`` — the poll-loop
  signature.  (``for`` loops are inherently iteration-bounded;
  connect-retry loops bound themselves by attempt count and carry no
  sleep-in-while shape... unless they do, in which case they must
  bound themselves like everyone else.)
- *locally bounded*: the loop's own subtree (test + body) references a
  deadline-ish name (``deadline``/``budget``/``timeout``/``t_end``/
  ``remaining``/``attempt``/``retries``/``backoff_budget``), raises
  ``TimeoutError``/``DeadlineExceeded``, or iterates a bounded
  counter — any marker showing the loop classifies its own expiry.
- *covered by a checked call* (the interprocedural half, graftflow's
  fixpoint shape): a loop whose body calls an in-package function that
  is itself deadline-checking (its body carries a marker, or
  transitively calls one that does) inherits the bound — e.g. a loop
  around ``closing()`` + a helper that raises past its deadline.

A deliberate exception needs an inline suppression with a reason —
the shipped posture is that NO loop in scope needs one: the ring
lane's loops all carry ``t_end`` bounds or per-slice liveness checks.
Findings carry the caller chain from an entrypoint (the graftflow
engine renders it), so a buried helper's unbounded loop names the
concurrency context that reaches it.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Set, Tuple

from .core import Finding, RepoContext, rule
from .graph import CallGraph, own_body

_RULE = "unbounded-spin"

_SCOPE_PREFIXES = (
    "pytensor_federated_tpu/service/",
    "pytensor_federated_tpu/routing/",
    "pytensor_federated_tpu/gateway/",
)

#: Names whose presence in a loop's subtree marks it as owning its
#: expiry: ambient-deadline derivations, explicit monotonic bounds,
#: and attempt counters all match.
_BOUND_NAME = re.compile(
    r"deadline|budget|timeout|t_end|remaining|attempt|retries|expire",
    re.IGNORECASE,
)

#: Raising one of these inside the loop IS the bound (the loop
#: classifies its own timeout loudly).
_TIMEOUT_RAISES = {"TimeoutError", "DeadlineExceeded"}


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on exprs
        return ""


def _calls_sleep(loop: ast.While) -> bool:
    for node in ast.walk(loop):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "sleep"
        ):
            return True
    return False


def _has_local_bound(loop: ast.While) -> bool:
    """Does the loop's own subtree carry an expiry marker?"""
    for node in ast.walk(loop):
        if isinstance(node, ast.Name) and _BOUND_NAME.search(node.id):
            return True
        if isinstance(node, ast.Attribute) and _BOUND_NAME.search(node.attr):
            return True
        if isinstance(node, ast.arg) and _BOUND_NAME.search(node.arg):
            return True
        if isinstance(node, ast.Raise) and node.exc is not None:
            exc = node.exc
            name = ""
            if isinstance(exc, ast.Call):
                exc = exc.func
            if isinstance(exc, ast.Name):
                name = exc.id
            elif isinstance(exc, ast.Attribute):
                name = exc.attr
            if name in _TIMEOUT_RAISES:
                return True
    return False


def _loop_callees(loop: ast.While) -> Set[str]:
    """Bare names and attribute tails called from inside the loop —
    matched against the call graph's function names for the
    interprocedural bound."""
    out: Set[str] = set()
    for node in ast.walk(loop):
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Name):
                out.add(func.id)
            elif isinstance(func, ast.Attribute):
                out.add(func.attr)
    return out


def _deadline_checking_functions(graph: CallGraph) -> Set[str]:
    """Function NAMES whose body (directly or through in-package
    callees, fixpoint) carries an expiry marker — calling one from a
    poll loop bounds the loop."""
    checking: Set[str] = set()
    for qname, fn in graph.functions.items():
        for node in own_body(fn.node):
            if isinstance(node, ast.Name) and _BOUND_NAME.search(node.id):
                checking.add(qname)
                break
            if isinstance(node, ast.Raise) and node.exc is not None:
                exc = node.exc
                if isinstance(exc, ast.Call):
                    exc = exc.func
                name = (
                    exc.id
                    if isinstance(exc, ast.Name)
                    else getattr(exc, "attr", "")
                )
                if name in _TIMEOUT_RAISES:
                    checking.add(qname)
                    break
    changed = True
    while changed:
        changed = False
        for qname, fn in graph.functions.items():
            if qname in checking:
                continue
            for edge in graph.callees_of(qname):
                if edge.callee in checking:
                    checking.add(qname)
                    changed = True
                    break
    return {graph.functions[q].name for q in checking}


def _witness_chain(
    graph: CallGraph, qname: str, limit: int = 8
) -> Tuple[str, ...]:
    """One caller chain up from ``qname`` toward an entrypoint."""
    hops: List[str] = []
    seen = {qname}
    cur = qname
    for _ in range(limit):
        callers = [e for e in graph.callers_of(cur) if e.caller not in seen]
        if not callers:
            break
        edge = callers[0]
        caller = graph.functions[edge.caller]
        hops.append(
            f"{caller.display} (calls {graph.functions[cur].name} at "
            f"{caller.rel}:{edge.lineno})"
        )
        seen.add(edge.caller)
        cur = edge.caller
    hops.reverse()
    return tuple(hops)


@rule(
    _RULE,
    "while-loops around time.sleep in service/, routing/ and gateway/ "
    "must bound themselves — a deadline/t_end/attempt marker in the "
    "loop, a TimeoutError raise, or a call to a deadline-checking "
    "helper — a peer that dies mid-update leaves a poll condition "
    "false forever",
    scope="repo",
)
def check_unbounded_spin(ctx: RepoContext) -> Iterator[Finding]:
    graph = ctx.graph
    checked_names = _deadline_checking_functions(graph)
    for qname in sorted(graph.functions):
        fn = graph.functions[qname]
        if not fn.rel.startswith(_SCOPE_PREFIXES):
            continue
        for node in own_body(fn.node):
            if not isinstance(node, ast.While):
                continue
            if not _calls_sleep(node):
                continue
            if _has_local_bound(node):
                continue
            if _loop_callees(node) & checked_names:
                continue
            chain = _witness_chain(graph, qname)
            yield Finding(
                rule=_RULE,
                path=fn.rel,
                line=node.lineno,
                message=(
                    f"unbounded spin/poll loop in {fn.name}: the loop "
                    "sleeps and re-checks with no deadline marker, no "
                    "TimeoutError raise, and no deadline-checking "
                    "callee — a dead peer leaves the condition false "
                    "forever; bound it with a monotonic t_end derived "
                    "from the ambient deadline (service/deadline.py) "
                    "or suppress with a reason if polling IS the idle "
                    "state"
                ),
                chain=chain
                + (f"unbounded spin at {fn.rel}:{node.lineno}",),
            )
