"""async-blocking: nothing blocking is REACHABLE from the event loop.

The PR-5 chaos harness found the direct form of this bug class live —
a fault shim calling ``time.sleep`` on the grpc.aio event loop froze
every concurrent RPC, the hedge timer included.  PR 7's rule caught
exactly that shape: a blocking primitive written lexically inside an
``async def``.  graftflow makes it transitive: a blocking call three
frames down a sync helper chain blocks the loop just as hard, and the
old rule provably missed it (tests/test_graftflow.py seeds that
defect).

Semantics: roots are the async contexts of the I/O stack — every
``async def`` in ``service/``, ``routing/``, ``faultinject/`` plus
``create_task``/``ensure_future`` targets spawned there — and the rule
follows the shared call graph (:mod:`.graph`) through plain call
edges; a sync function called from a coroutine still runs ON the loop.
The spawn seams (``run_in_executor`` / ``Thread(target=…)`` /
``submit``) produce no call edge, so the executor-closure pattern
(sync ``def`` handed to a worker thread) stays exempt exactly as
before.  Findings land at the blocking call site — wherever in the
package it lives — and carry the full propagation chain from the async
root.

Blocking primitives: ``time.sleep``, sync socket construction and
socket method calls, anything on the ``subprocess`` module, the sync
fault-shim twins (their delay/stall kinds ``time.sleep`` — the PR-5
class), and a bare ``lock.acquire()`` with neither a timeout nor
``blocking=False`` (``with lock:`` for a short critical section is
idiomatic and exempt).
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Tuple

from .core import Finding, RepoContext, SourceFile, rule
from .dataflow import _LOCKISH, async_reachable
from .graph import FuncNode, own_body

_SCOPE_PREFIXES = (
    "pytensor_federated_tpu/service/",
    "pytensor_federated_tpu/routing/",
    "pytensor_federated_tpu/faultinject/",
)

#: Exact dotted calls that block the calling thread.
_BLOCKING_DOTTED = {
    "time.sleep": "use `await asyncio.sleep(...)`",
    "socket.create_connection": "sync connect blocks the loop; use "
    "asyncio streams or an executor",
    "socket.socket": "sync socket I/O belongs in an executor or "
    "asyncio transport",
    "os.system": "use `asyncio.create_subprocess_*`",
    "os.popen": "use `asyncio.create_subprocess_*`",
}

#: Any attribute call on the ``subprocess`` module blocks (Popen's
#: construction includes a blocking fork/exec handshake).
_SUBPROCESS_MODULE = "subprocess"

#: Sync fault-shim primitives with async twins (faultinject.runtime):
#: their delay/stall kinds ``time.sleep`` — the exact PR-5 bug class.
_SYNC_SHIMS = {
    "filter_bytes": "filter_bytes_async",
    "compute_filter": "compute_filter_async",
    "getload_filter": "getload_filter_async",
    "probe_filter": "probe_filter_async",
    "mangle_batch_result": "mangle_batch_result_async",
}

#: Sync-socket method names: calling these on anything on a loop path
#: is a blocking syscall on the loop.
_SOCKET_METHODS = {"sendall", "recv", "recv_into", "accept"}

_RULE = "async-blocking"


def _call_name(func: ast.expr) -> str:
    try:
        return ast.unparse(func)
    except Exception:  # pragma: no cover - unparse is total on exprs
        return ""


def _is_bare_lock_acquire(call: ast.Call, dotted: str) -> bool:
    """``lock.acquire()`` with no timeout and blocking semantics: the
    caller parks its thread — on a loop path, the whole loop."""
    if not (
        isinstance(call.func, ast.Attribute) and call.func.attr == "acquire"
    ):
        return False
    receiver = _call_name(call.func.value)
    if not _LOCKISH.search(receiver):
        return False
    for kw in call.keywords:
        if kw.arg in ("timeout", "blocking"):
            return False
    return not call.args  # positional blocking/timeout also opt out


def blocking_call_sites(fn_node: ast.AST) -> Iterator[Tuple[ast.Call, str]]:
    """(call, advice) for every blocking primitive in the function's
    own body.  Shared by the transitive rule and the legacy direct scan
    the regression tests compare against."""
    for node in own_body(fn_node):
        if not isinstance(node, ast.Call):
            continue
        dotted = _call_name(node.func)
        if dotted in _BLOCKING_DOTTED:
            yield node, (
                f"blocking call `{dotted}(...)` — {_BLOCKING_DOTTED[dotted]}"
            )
            continue
        head, _, tail = dotted.rpartition(".")
        if head == _SUBPROCESS_MODULE:
            yield node, (
                f"blocking call `{dotted}(...)` — use "
                "`asyncio.create_subprocess_*` or an executor"
            )
            continue
        name = tail or dotted
        if name in _SYNC_SHIMS and (
            head in ("", "_fi", "runtime") or "faultinject" in head
        ):
            yield node, (
                f"sync fault shim `{dotted}(...)` — its delay/stall "
                f"kinds block the event loop; use `{_SYNC_SHIMS[name]}` "
                "(the PR-5 chaos bug class)"
            )
            continue
        if isinstance(node.func, ast.Attribute) and name in _SOCKET_METHODS:
            yield node, (
                f"sync socket call `{dotted}(...)` — blocking syscall "
                "on the event loop; use asyncio streams or an executor"
            )
            continue
        if _is_bare_lock_acquire(node, dotted):
            yield node, (
                f"bare `{dotted}(...)` — an untimed blocking acquire "
                "parks the event loop behind whoever holds the lock; "
                "pass a timeout or keep the critical section under "
                "`with lock:`"
            )


def direct_blocking_sites(src: SourceFile) -> List[Finding]:
    """The PR-7 per-function semantics: blocking primitives lexically
    inside an ``async def`` in the scoped packages.  Kept (not
    registered) so the engine tests can prove the transitive rule's
    reach exceeds it on multi-hop chains."""
    out: List[Finding] = []
    if not src.is_python or not src.rel.startswith(_SCOPE_PREFIXES):
        return out
    for node in src.nodes(ast.AsyncFunctionDef):
        for call, advice in blocking_call_sites(node):
            out.append(
                src.finding(
                    _RULE,
                    call.lineno,
                    f"{advice} (inside `async def {node.name}`)",  # type: ignore[attr-defined]
                )
            )
    return out


@rule(
    _RULE,
    "no blocking primitive (time.sleep, sync sockets, subprocess, sync "
    "fault shims, bare lock.acquire) reachable from an async context in "
    "service/, routing/, faultinject/ — transitive over the call graph, "
    "finding carries the chain",
    scope="repo",
)
def check_async_blocking(ctx: RepoContext) -> Iterator[Finding]:
    graph = ctx.graph
    reach = async_reachable(graph, _SCOPE_PREFIXES)
    for qname, chain in sorted(reach.items()):
        fn: FuncNode = graph.functions[qname]
        src = ctx.by_rel.get(fn.rel)
        if src is None:
            continue
        root = graph.functions[chain[0].caller] if chain else fn
        for call, advice in blocking_call_sites(fn.node):
            hops = graph.render_chain(chain) or (fn.display,)
            where = (
                f"inside `async def {fn.name}`"
                if not chain
                else f"reachable from `async def {root.name}` "
                f"({root.rel}:{root.lineno}) in {len(chain)} call(s)"
            )
            yield Finding(
                rule=_RULE,
                path=fn.rel,
                line=call.lineno,
                message=f"{advice} ({where})",
                chain=hops
                + (f"blocking call at {fn.rel}:{call.lineno}",),
            )
