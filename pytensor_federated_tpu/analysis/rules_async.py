"""async-blocking: no event-loop-blocking calls inside ``async def``.

The PR-5 chaos harness found exactly this bug class live — a fault
shim calling ``time.sleep`` on the grpc.aio event loop froze every
concurrent RPC, the hedge timer included.  The invariant (CLAUDE.md,
:mod:`..faultinject.runtime` docstrings): async bodies in the I/O
stack must await their delays and must call the ``*_async`` twins of
the sync fault-shim primitives; sync-socket/subprocess work belongs in
an executor.

Scope: ``service/``, ``routing/``, ``faultinject/`` — the packages
whose async defs run on the serving event loop.  Nested *sync* ``def``
bodies inside an async function are skipped: a sync closure is
routinely handed to ``run_in_executor`` / ``ctx.run`` and blocks a
worker thread, not the loop.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from .core import Finding, SourceFile, rule

_SCOPE_PREFIXES = (
    "pytensor_federated_tpu/service/",
    "pytensor_federated_tpu/routing/",
    "pytensor_federated_tpu/faultinject/",
)

#: Exact dotted calls that block the calling thread.
_BLOCKING_DOTTED = {
    "time.sleep": "use `await asyncio.sleep(...)`",
    "socket.create_connection": "sync connect blocks the loop; use "
    "asyncio streams or an executor",
    "socket.socket": "sync socket I/O belongs in an executor or "
    "asyncio transport",
    "os.system": "use `asyncio.create_subprocess_*`",
    "os.popen": "use `asyncio.create_subprocess_*`",
}

#: Any attribute call on the ``subprocess`` module blocks (Popen's
#: construction includes a blocking fork/exec handshake).
_SUBPROCESS_MODULE = "subprocess"

#: Sync fault-shim primitives with async twins (faultinject.runtime):
#: their delay/stall kinds ``time.sleep`` — the exact PR-5 bug class.
_SYNC_SHIMS = {
    "filter_bytes": "filter_bytes_async",
    "compute_filter": "compute_filter_async",
    "getload_filter": "getload_filter_async",
    "probe_filter": "probe_filter_async",
    "mangle_batch_result": "mangle_batch_result_async",
}

#: Sync-socket method names: calling these on anything inside an async
#: body is a blocking syscall on the loop.
_SOCKET_METHODS = {"sendall", "recv", "recv_into", "accept"}

_RULE = "async-blocking"


def _call_name(func: ast.expr) -> str:
    try:
        return ast.unparse(func)
    except Exception:  # pragma: no cover - unparse is total on exprs
        return ""


def _iter_async_body(fn: ast.AsyncFunctionDef) -> Iterator[ast.AST]:
    """Walk an async function's own body, not descending into nested
    function definitions (sync closures run in executors; nested async
    defs are visited as roots in their own right)."""
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _check_call(
    src: SourceFile, fn: ast.AsyncFunctionDef, call: ast.Call
) -> Iterator[Finding]:
    dotted = _call_name(call.func)
    where = f"inside `async def {fn.name}`"
    if dotted in _BLOCKING_DOTTED:
        yield src.finding(
            _RULE,
            call.lineno,
            f"blocking call `{dotted}(...)` {where} — "
            f"{_BLOCKING_DOTTED[dotted]}",
        )
        return
    head, _, tail = dotted.rpartition(".")
    if head == _SUBPROCESS_MODULE:
        yield src.finding(
            _RULE,
            call.lineno,
            f"blocking call `{dotted}(...)` {where} — use "
            "`asyncio.create_subprocess_*` or an executor",
        )
        return
    name = tail or dotted
    if name in _SYNC_SHIMS and (
        head in ("", "_fi", "runtime") or "faultinject" in head
    ):
        yield src.finding(
            _RULE,
            call.lineno,
            f"sync fault shim `{dotted}(...)` {where} — its delay/stall "
            f"kinds block the event loop; use `{_SYNC_SHIMS[name]}` "
            "(the PR-5 chaos bug class)",
        )
        return
    if isinstance(call.func, ast.Attribute) and name in _SOCKET_METHODS:
        yield src.finding(
            _RULE,
            call.lineno,
            f"sync socket call `{dotted}(...)` {where} — blocking "
            "syscall on the event loop; use asyncio streams or an "
            "executor",
        )


@rule(
    _RULE,
    "no time.sleep / sync sockets / subprocess / sync fault shims "
    "inside async def bodies in service/, routing/, faultinject/",
)
def check_async_blocking(src: SourceFile) -> Iterator[Finding]:
    if not src.is_python or not src.rel.startswith(_SCOPE_PREFIXES):
        return
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.AsyncFunctionDef):
            continue
        for sub in _iter_async_body(node):
            if isinstance(sub, ast.Call):
                yield from _check_call(src, node, sub)
