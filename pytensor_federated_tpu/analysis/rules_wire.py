"""wire-registry + wire-loudness: the wire formats stay declared and loud.

**wire-registry** — :mod:`..service.wire_registry` is the single
declared source of npwire flag bits and npproto field numbers.  Three
implementations carry their own literals (``service/npwire.py``,
``service/npproto_codec.py``, ``native/cpp_node.cpp`` — the C++ file
cannot import Python); this rule cross-parses all three and fails on:

- an implementation flag/field the registry does not declare
  (undeclared), or a declared one no implementation carries (drift);
- two declarations sharing a bit/number (collision);
- a flag without decoder-side rejection: every npwire decoder must
  enforce the known-flags mask (``flags & ~KNOWN`` raises WireError /
  returns a decode error — silent skipping of an unknown flag is
  exactly the version-skew mis-parse the loud-failure contract
  forbids);
- an npproto extension field without a decode dispatch arm — we must
  never emit a field we cannot read back (plain proto3 unknown fields
  are *skipped* by design; that posture difference is documented in
  the registry module).

**wire-loudness** — corrupt payloads surface as ``WireError``, never
vanish (property-tested contract, CLAUDE.md).  Findings: a bare
``except:`` anywhere in the wire stack, and an ``except`` around a
decode call that neither re-raises nor uses the caught exception (an
error reply built from ``e`` IS in-band propagation; a probe lane
converting corrupt bytes into a failed-probe verdict suppresses this
rule inline with a justification).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from ..service import wire_registry as REG
from .core import Finding, SourceFile, rule

_NPWIRE = "pytensor_federated_tpu/service/npwire.py"
_NPPROTO = "pytensor_federated_tpu/service/npproto_codec.py"
_CPP = "native/cpp_node.cpp"
_SHM = "pytensor_federated_tpu/service/shm.py"
_RING = "pytensor_federated_tpu/service/ring.py"

#: npwire decode entry points that must enforce the known-flags mask.
#: Since ISSUE 13 the full decoders are the ``*_part`` variants (the
#: historical names are thin delegating wrappers over them, so the
#: guard obligation sits on the bodies that actually parse flags).
_NPWIRE_DECODERS = ("decode_arrays_part", "decode_batch_part")

_LOUDNESS_SCOPE = (
    "pytensor_federated_tpu/service/",
    "pytensor_federated_tpu/routing/",
    "pytensor_federated_tpu/faultinject/",
    # The gateway passes frames through whole; its decode seams must
    # stay as loud as the transports it fronts.
    "pytensor_federated_tpu/gateway/",
)


# ---------------------------------------------------------------------------
# wire-registry
# ---------------------------------------------------------------------------


def _eval_int(node: ast.expr, env: Dict[str, int]) -> Optional[int]:
    """Evaluate a constant int expression over ``env`` (names, |, +)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    if isinstance(node, ast.Name):
        return env.get(node.id)
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.BitOr, ast.Add)
    ):
        left = _eval_int(node.left, env)
        right = _eval_int(node.right, env)
        if left is None or right is None:
            return None
        return left | right if isinstance(node.op, ast.BitOr) else left + right
    return None


def _collect_assignments(tree: ast.Module) -> Dict[str, ast.expr]:
    out: Dict[str, ast.expr] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name):
                out[tgt.id] = node.value
    return out


def _check_flag_map(
    src: SourceFile,
    impl: Dict[str, int],
    known_mask: Optional[int],
    decoders_guarded: Dict[str, bool],
    line_of: Dict[str, int],
) -> Iterator[Finding]:
    """Shared flag validation for one implementation (python or C++)."""
    declared = REG.NPWIRE_FLAGS
    seen_bits: Dict[int, str] = {}
    for name, bit in impl.items():
        line = line_of.get(name, 1)
        if name not in declared:
            yield src.finding(
                "wire-registry",
                line,
                f"flag {name!r} (bit {bit}) is not declared in "
                "service/wire_registry.py NPWIRE_FLAGS",
            )
        elif declared[name] != bit:
            yield src.finding(
                "wire-registry",
                line,
                f"flag {name!r} is bit {bit} here but declared as "
                f"{declared[name]} in service/wire_registry.py",
            )
        if bit in seen_bits:
            yield src.finding(
                "wire-registry",
                line,
                f"flag bit {bit} collides: {seen_bits[bit]!r} and {name!r}",
            )
        seen_bits[bit] = name
    for name, bit in declared.items():
        if name not in impl:
            yield src.finding(
                "wire-registry",
                1,
                f"declared flag {name!r} (bit {bit}) is missing from "
                f"{src.rel}",
            )
    if known_mask is None:
        yield src.finding(
            "wire-registry",
            1,
            f"{src.rel} has no known-flags mask — decoders cannot "
            "reject undeclared flag bits (loud-failure contract)",
        )
    elif known_mask != REG.NPWIRE_KNOWN_FLAGS:
        yield src.finding(
            "wire-registry",
            1,
            f"known-flags mask is {known_mask:#x} but the registry "
            f"declares {REG.NPWIRE_KNOWN_FLAGS:#x}",
        )
    for decoder, guarded in decoders_guarded.items():
        if not guarded:
            yield src.finding(
                "wire-registry",
                line_of.get(decoder, 1),
                f"decoder {decoder} does not reject unknown flag bits "
                "(must check flags against the known-flags mask and "
                "fail loudly)",
            )


def _npwire_findings(src: SourceFile) -> Iterator[Finding]:
    tree = src.tree
    assigns = _collect_assignments(tree)
    env: Dict[str, int] = {}
    impl: Dict[str, int] = {}
    line_of: Dict[str, int] = {}
    for name, value in assigns.items():
        v = _eval_int(value, env)
        if v is not None:
            env[name] = v
        if name.startswith("_FLAG_") and v is not None:
            impl[name[len("_FLAG_"):]] = v
            line_of[name[len("_FLAG_"):]] = value.lineno
    known_mask = env.get("_KNOWN_FLAGS")
    guarded: Dict[str, bool] = {}
    for node in tree.body:
        if (
            isinstance(node, ast.FunctionDef)
            and node.name in _NPWIRE_DECODERS
        ):
            line_of[node.name] = node.lineno
            refs = {
                n.id
                for n in ast.walk(node)
                if isinstance(n, ast.Name)
            }
            # The guard is a call to the shared _check_flags helper (or
            # a direct mask reference inside the decoder body).
            guarded[node.name] = bool(
                refs & {"_check_flags", "_KNOWN_FLAGS"}
            )
    for name in _NPWIRE_DECODERS:
        guarded.setdefault(name, False)
    yield from _check_flag_map(src, impl, known_mask, guarded, line_of)


_CPP_FLAG_RE = re.compile(
    r"constexpr\s+uint8_t\s+kFlag(\w+)\s*=\s*(\d+)\s*;"
)
_CPP_KNOWN_RE = re.compile(
    r"constexpr\s+uint8_t\s+kKnownFlags\s*=\s*([^;]+);"
)
#: A column-0 C++ function definition: return type, name, open paren.
_CPP_FUNC_RE = re.compile(
    r"^[A-Za-z_][\w:<>,&*\s]*?\b(\w+)\s*\("
)
#: Both frame parsers in cpp_node.cpp must apply the mask themselves —
#: a guard in one does not protect frames entering through the other.
_CPP_PARSERS = ("decode", "serve_batch")


def _cpp_function_spans(src: SourceFile) -> Dict[str, tuple]:
    """name -> (start_line, end_line) for column-0 function defs, each
    span ending where the next one starts (text-level, good enough for
    "does this parser contain its own guard")."""
    starts = []
    for i, line in enumerate(src.lines, start=1):
        if line[:1].isspace() or not line:
            continue
        m = _CPP_FUNC_RE.match(line)
        if m and "(" in line and ";" not in line.split("(")[0]:
            starts.append((m.group(1), i))
    spans: Dict[str, tuple] = {}
    for idx, (name, start) in enumerate(starts):
        end = (
            starts[idx + 1][1] - 1 if idx + 1 < len(starts) else len(src.lines)
        )
        spans.setdefault(name, (start, end))
    return spans


def _cpp_findings(src: SourceFile) -> Iterator[Finding]:
    impl: Dict[str, int] = {}
    line_of: Dict[str, int] = {}
    for i, line in enumerate(src.lines, start=1):
        m = _CPP_FLAG_RE.search(line)
        if m:
            name = m.group(1).upper()
            impl[name] = int(m.group(2))
            line_of[name] = i
    known_mask: Optional[int] = None
    m = _CPP_KNOWN_RE.search(src.text)
    if m:
        expr = m.group(1)
        mask = 0
        ok = True
        for part in expr.split("|"):
            part = part.strip()
            fm = re.fullmatch(r"kFlag(\w+)", part)
            if fm and fm.group(1).upper() in impl:
                mask |= impl[fm.group(1).upper()]
            elif part.isdigit():
                mask |= int(part)
            else:
                ok = False
        if ok:
            known_mask = mask
    # The rejection must be applied PER PARSER — a file-global search
    # would let serve_batch's guard mask a removed guard in decode().
    spans = _cpp_function_spans(src)
    guarded: Dict[str, bool] = {}
    for parser in _CPP_PARSERS:
        span = spans.get(parser)
        if span is None:
            guarded[parser] = False
            line_of[parser] = 1
            continue
        start, end = span
        body = "\n".join(src.lines[start - 1 : end])
        guarded[parser] = "~kKnownFlags" in body
        line_of[parser] = start
    yield from _check_flag_map(src, impl, known_mask, guarded, line_of)


def _shm_findings(src: SourceFile) -> Iterator[Finding]:
    """The shm doorbell's declarations: frame kinds, flag bits, and
    the arena DESCRIPTOR struct must match service/wire_registry.py,
    and the frame decoder must reject unknown kinds AND flag bits."""
    tree = src.tree
    assigns = _collect_assignments(tree)
    env: Dict[str, int] = {}
    kinds: Dict[str, int] = {}
    flags: Dict[str, int] = {}
    line_of: Dict[str, int] = {}
    for name, value in assigns.items():
        v = _eval_int(value, env)
        if v is not None:
            env[name] = v
        if name.startswith("_KIND_") and v is not None:
            kinds[name[len("_KIND_"):]] = v
            line_of["KIND_" + name[len("_KIND_"):]] = value.lineno
        if name.startswith("_FLAG_") and v is not None:
            flags[name[len("_FLAG_"):]] = v
            line_of["FLAG_" + name[len("_FLAG_"):]] = value.lineno

    def check_table(
        impl: Dict[str, int], declared: Dict[str, int], what: str,
        prefix: str,
    ) -> Iterator[Finding]:
        seen: Dict[int, str] = {}
        for name, num in impl.items():
            line = line_of.get(prefix + name, 1)
            if name not in declared:
                yield src.finding(
                    "wire-registry",
                    line,
                    f"shm {what} {name!r} ({num}) is not declared in "
                    f"service/wire_registry.py",
                )
            elif declared[name] != num:
                yield src.finding(
                    "wire-registry",
                    line,
                    f"shm {what} {name!r} is {num} here but declared "
                    f"as {declared[name]} in service/wire_registry.py",
                )
            if num in seen:
                yield src.finding(
                    "wire-registry",
                    line,
                    f"shm {what} value {num} collides: "
                    f"{seen[num]!r} and {name!r}",
                )
            seen[num] = name
        for name, num in declared.items():
            if name not in impl:
                yield src.finding(
                    "wire-registry",
                    1,
                    f"declared shm {what} {name!r} ({num}) is missing "
                    f"from {src.rel}",
                )

    yield from check_table(kinds, REG.SHMWIRE_KINDS, "frame kind", "KIND_")
    yield from check_table(flags, REG.SHMWIRE_FLAGS, "flag", "FLAG_")
    known_mask = env.get("_KNOWN_FLAGS")
    if known_mask is None:
        yield src.finding(
            "wire-registry",
            1,
            f"{src.rel} has no known-flags mask — the doorbell decoder "
            "cannot reject undeclared flag bits (loud-failure contract)",
        )
    elif known_mask != REG.SHMWIRE_KNOWN_FLAGS:
        yield src.finding(
            "wire-registry",
            1,
            f"shm known-flags mask is {known_mask:#x} but the registry "
            f"declares {REG.SHMWIRE_KNOWN_FLAGS:#x}",
        )
    # The arena descriptor struct: the one fixed layout descriptors
    # are packed/unpacked with, pinned to the registry declaration.
    desc_fmt: Optional[str] = None
    desc_line = 1
    value = assigns.get("_DESC_STRUCT")
    if value is not None:
        desc_line = value.lineno
        if (
            isinstance(value, ast.Call)
            and value.args
            and isinstance(value.args[0], ast.Constant)
            and isinstance(value.args[0].value, str)
        ):
            desc_fmt = value.args[0].value
    if desc_fmt is None:
        yield src.finding(
            "wire-registry",
            desc_line,
            f"{src.rel} does not define _DESC_STRUCT as a "
            "struct.Struct with a literal format — the arena "
            "descriptor layout must be pinned to "
            "service/wire_registry.py SHM_DESC_STRUCT",
        )
    elif desc_fmt != REG.SHM_DESC_STRUCT:
        yield src.finding(
            "wire-registry",
            desc_line,
            f"arena descriptor struct is {desc_fmt!r} here but "
            f"declared as {REG.SHM_DESC_STRUCT!r} in "
            "service/wire_registry.py "
            f"(field order: {', '.join(REG.SHM_DESC_FIELD_ORDER)})",
        )
    # Decoder-side rejection: decode_frame must enforce both the
    # known-kinds set and the known-flags mask.
    for node in tree.body:
        if isinstance(node, ast.FunctionDef) and node.name == "decode_frame":
            refs = {
                n.id for n in ast.walk(node) if isinstance(n, ast.Name)
            }
            if not refs & {"_check_flags", "_KNOWN_FLAGS"}:
                yield src.finding(
                    "wire-registry",
                    node.lineno,
                    "decode_frame does not reject unknown flag bits "
                    "(must check flags against the known-flags mask)",
                )
            if "_KNOWN_KINDS" not in refs:
                yield src.finding(
                    "wire-registry",
                    node.lineno,
                    "decode_frame does not reject unknown frame kinds "
                    "(must check the kind against _KNOWN_KINDS)",
                )
            break
    else:
        yield src.finding(
            "wire-registry",
            1,
            f"{src.rel} has no decode_frame — the doorbell wire has "
            "no guarded decoder",
        )


def _ring_findings(src: SourceFile) -> Iterator[Finding]:
    """The arena ring lane's declarations (ISSUE 18): the seqlock ring
    header/record struct layouts and the in-mapping word offsets must
    match service/wire_registry.py — both ends of a ring read the SAME
    shared bytes, so silent drift here is cross-process corruption."""
    assigns = _collect_assignments(src.tree)

    def struct_literal(name: str) -> Tuple[Optional[str], int]:
        value = assigns.get(name)
        if value is None:
            return None, 1
        if (
            isinstance(value, ast.Call)
            and value.args
            and isinstance(value.args[0], ast.Constant)
            and isinstance(value.args[0].value, str)
        ):
            return value.args[0].value, value.lineno
        return None, value.lineno

    for name, declared, order in (
        ("_RING_HEADER_STRUCT", REG.RING_HEADER_STRUCT,
         REG.RING_HEADER_FIELD_ORDER),
        ("_RING_DESC_STRUCT", REG.RING_DESC_STRUCT,
         REG.RING_DESC_FIELD_ORDER),
    ):
        fmt, line = struct_literal(name)
        if fmt is None:
            yield src.finding(
                "wire-registry",
                line,
                f"{src.rel} does not define {name} as a struct.Struct "
                "with a literal format — the ring layout must be "
                "pinned to service/wire_registry.py",
            )
        elif fmt != declared:
            yield src.finding(
                "wire-registry",
                line,
                f"ring struct {name} is {fmt!r} here but declared as "
                f"{declared!r} in service/wire_registry.py "
                f"(field order: {', '.join(order)})",
            )
    env: Dict[str, int] = {}
    for name, value in assigns.items():
        v = _eval_int(value, env)
        if v is not None:
            env[name] = v
    for name, declared_off in (
        ("_RING_HEADER_OFFSET", REG.RING_HEADER_OFFSET),
        ("_RING_RECORDS_OFFSET", REG.RING_RECORDS_OFFSET),
        ("_RING_FUTEX_WORD_OFFSET", REG.RING_FUTEX_WORD_OFFSET),
        ("_RING_WAITING_WORD_OFFSET", REG.RING_WAITING_WORD_OFFSET),
        ("_RING_EPOCH_WORD_OFFSET", REG.RING_EPOCH_WORD_OFFSET),
    ):
        value = assigns.get(name)
        line = value.lineno if value is not None else 1
        got = env.get(name)
        if got is None:
            yield src.finding(
                "wire-registry",
                line,
                f"{src.rel} does not define {name} as a constant int — "
                "ring word offsets must be pinned to "
                "service/wire_registry.py",
            )
        elif got != declared_off:
            yield src.finding(
                "wire-registry",
                line,
                f"ring offset {name} is {got} here but declared as "
                f"{declared_off} in service/wire_registry.py",
            )


def _npproto_message_of(func_name: str) -> str:
    """Which registry message a codec function's literals belong to —
    by the naming convention the codec module keeps."""
    if "ndarray" in func_name:
        return "ndarray"
    if "get_load" in func_name:
        return "get_load_result"
    return "arrays_msg"


def _npproto_findings(src: SourceFile) -> Iterator[Finding]:
    for msg, fields in REG.NPPROTO_FIELDS.items():
        seen: Dict[int, str] = {}
        for fname, num in fields.items():
            if num in seen:
                yield src.finding(
                    "wire-registry",
                    1,
                    f"registry collision in {msg}: field {num} is both "
                    f"{seen[num]!r} and {fname!r}",
                )
            seen[num] = fname
    # Field-number usage tracked PER MESSAGE (ndarray's field 2 is a
    # different declaration than get_load_result's field 2 — a flat
    # union would let drift in one message hide behind the other).
    used: Dict[str, Dict[int, int]] = {m: {} for m in REG.NPPROTO_FIELDS}
    dispatch: Dict[str, set] = {m: set() for m in REG.NPPROTO_FIELDS}
    for fn in src.tree.body:
        if not isinstance(fn, ast.FunctionDef):
            continue
        msg = _npproto_message_of(fn.name)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                fname = (
                    node.func.id
                    if isinstance(node.func, ast.Name)
                    else getattr(node.func, "attr", "")
                )
                if fname in ("_len_field", "_tag") and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Constant) and isinstance(
                        arg.value, int
                    ):
                        used[msg].setdefault(arg.value, node.lineno)
            elif isinstance(node, ast.Compare):
                # `field == N` decode dispatch arms
                if (
                    isinstance(node.left, ast.Name)
                    and node.left.id == "field"
                    and len(node.ops) == 1
                    and isinstance(node.ops[0], ast.Eq)
                    and isinstance(node.comparators[0], ast.Constant)
                    and isinstance(node.comparators[0].value, int)
                ):
                    num = node.comparators[0].value
                    if num == 0:
                        # `field == 0` is the illegal-field-number
                        # guard (proto3 reserves 0), not a field.
                        continue
                    used[msg].setdefault(num, node.lineno)
                    dispatch[msg].add(num)
    for msg, nums in used.items():
        declared = set(REG.NPPROTO_FIELDS[msg].values())
        for num, line in sorted(nums.items()):
            if num not in declared:
                yield src.finding(
                    "wire-registry",
                    line,
                    f"npproto field number {num} is used in a {msg} "
                    "function but not declared for that message in "
                    "service/wire_registry.py NPPROTO_FIELDS",
                )
    for msg, fields in REG.NPPROTO_FIELDS.items():
        for fname, num in sorted(fields.items(), key=lambda kv: kv[1]):
            if num not in used[msg]:
                yield src.finding(
                    "wire-registry",
                    1,
                    f"declared npproto field {msg}.{fname} ({num}) is "
                    "never encoded or dispatched in npproto_codec.py",
                )
    for num in sorted(REG.NPPROTO_EXTENSION_FIELDS):
        if num not in dispatch["arrays_msg"]:
            yield src.finding(
                "wire-registry",
                1,
                f"extension field {num} has no decode dispatch arm "
                f"(`field == {num}`) — we would emit a field we cannot "
                "read back",
            )


@rule(
    "wire-registry",
    "npwire flag bits, npproto field numbers, and shm doorbell "
    "kinds/flags/descriptor layout must match service/wire_registry.py "
    "across npwire.py, npproto_codec.py, shm.py and native/cpp_node.cpp, "
    "with decoder-side rejection/dispatch",
    scope="repo",
)
def check_wire_registry(sources: Sequence[SourceFile]) -> Iterator[Finding]:
    by_rel = {s.rel: s for s in sources}
    npwire = by_rel.get(_NPWIRE)
    if npwire is not None:
        yield from _npwire_findings(npwire)
    cpp = by_rel.get(_CPP)
    if cpp is not None:
        yield from _cpp_findings(cpp)
    npproto = by_rel.get(_NPPROTO)
    if npproto is not None:
        yield from _npproto_findings(npproto)
    shm = by_rel.get(_SHM)
    if shm is not None:
        yield from _shm_findings(shm)
    ring = by_rel.get(_RING)
    if ring is not None:
        yield from _ring_findings(ring)


# ---------------------------------------------------------------------------
# wire-loudness
# ---------------------------------------------------------------------------


def _body_walk(stmts: Sequence[ast.stmt]) -> Iterator[ast.AST]:
    for stmt in stmts:
        yield from ast.walk(stmt)


def _decode_calls(stmts: Sequence[ast.stmt]) -> List[str]:
    out = []
    for node in _body_walk(stmts):
        if isinstance(node, ast.Call):
            name = (
                node.func.id
                if isinstance(node.func, ast.Name)
                else getattr(node.func, "attr", "")
            )
            if name.startswith("decode_") or name == "_parse_dtype":
                out.append(name)
    return out


@rule(
    "wire-loudness",
    "no bare except in the wire stack; an except around a decode call "
    "must re-raise or use the caught exception (WireError stays loud)",
)
def check_wire_loudness(src: SourceFile) -> Iterator[Finding]:
    if not src.is_python or not src.rel.startswith(_LOUDNESS_SCOPE):
        return
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Try):
            continue
        decodes = _decode_calls(node.body)
        for handler in node.handlers:
            if handler.type is None:
                yield src.finding(
                    "wire-loudness",
                    handler.lineno,
                    "bare `except:` in the wire stack — catches "
                    "everything including WireError and KeyboardInterrupt; "
                    "name the exceptions",
                )
                continue
            if not decodes:
                continue
            raises = any(
                isinstance(n, ast.Raise) for n in _body_walk(handler.body)
            )
            uses_exc = bool(handler.name) and any(
                isinstance(n, ast.Name) and n.id == handler.name
                for n in _body_walk(handler.body)
            )
            if not raises and not uses_exc:
                yield src.finding(
                    "wire-loudness",
                    handler.lineno,
                    f"`except {ast.unparse(handler.type)}` swallows a "
                    f"decode failure ({', '.join(sorted(set(decodes)))}) "
                    "— re-raise, or bind and propagate the error in-band "
                    "(WireError must stay loud)",
                )
