"""graftlint CLI: ``python -m pytensor_federated_tpu.analysis``.

Exit status 0 = clean, 1 = findings, 2 = usage error.  ``--json``
emits a machine-readable report (CI annotation lane); default output
is one ``path:line: [rule] message`` per finding.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    # Lint runs are CPU-only by definition: never let the fed
    # introspection rule (or the package import above it) dial the
    # tunneled TPU plugin (CLAUDE.md environment pitfalls).
    from ..utils import force_cpu_backend

    force_cpu_backend()

    from . import RULES, default_targets, render_human, render_json, run

    parser = argparse.ArgumentParser(
        prog="python -m pytensor_federated_tpu.analysis",
        description="graftlint: the repo's design invariants as "
        "machine-checked static-analysis rules",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        type=Path,
        help="files to check (default: the package, native/cpp_node.cpp, "
        "bench drivers and tools)",
    )
    parser.add_argument(
        "--rule",
        action="append",
        dest="rules",
        metavar="NAME",
        help="run only this rule (repeatable)",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for name in sorted(RULES):
            r = RULES[name]
            print(f"{name} [{r.scope}]: {r.summary}")
        return 0

    unknown = [n for n in (args.rules or []) if n not in RULES]
    if unknown:
        print(
            f"unknown rule(s): {', '.join(unknown)} "
            f"(known: {', '.join(sorted(RULES))})",
            file=sys.stderr,
        )
        return 2

    paths = [p.resolve() for p in args.paths] or default_targets()
    findings = run(rules=args.rules, paths=paths)
    print(render_json(findings) if args.json else render_human(findings))
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
