"""graftlint CLI: ``python -m pytensor_federated_tpu.analysis``.

Exit status 0 = clean, 1 = findings, 2 = usage error.  ``--json``
emits the machine-readable report (schema documented and pinned in
docs/static-analysis.md / tests/test_graftlint.py); ``--sarif`` emits
SARIF 2.1.0 for the CI ``upload-sarif`` annotation lane; default
output is one ``path:line: [rule] message`` per finding (graftflow
findings append their propagation chain).  ``--changed-only`` scopes
file rules to the files git reports as changed against HEAD (repo
rules still see the full target set; only subset findings are
reported).  A one-line timing summary always goes to stderr, so both
JSON lanes stay pure on stdout.
"""

from __future__ import annotations

import argparse
import subprocess
import sys
from pathlib import Path
from typing import Dict, List, Optional


def _changed_paths(root: Path) -> List[Path]:
    """Files changed vs HEAD (worktree + index) plus untracked — the
    pre-commit iteration loop's target set."""
    out: List[Path] = []
    seen = set()
    for args in (
        ["git", "-C", str(root), "diff", "--name-only", "HEAD"],
        [
            "git",
            "-C",
            str(root),
            "ls-files",
            "--others",
            "--exclude-standard",
        ],
    ):
        try:
            text = subprocess.run(
                args, capture_output=True, text=True, check=True
            ).stdout
        except (OSError, subprocess.CalledProcessError) as e:
            print(f"graftlint: --changed-only needs git ({e})", file=sys.stderr)
            raise SystemExit(2)
        for line in text.splitlines():
            p = (root / line.strip()).resolve()
            if line.strip() and p not in seen and p.exists():
                seen.add(p)
                out.append(p)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    # Lint runs are CPU-only by definition: never let the fed
    # introspection rules (or the package import above them) dial the
    # tunneled TPU plugin (CLAUDE.md environment pitfalls).
    from ..utils import force_cpu_backend

    force_cpu_backend()

    from . import (
        RULES,
        default_targets,
        render_human,
        render_json,
        render_sarif,
        repo_root,
        run,
    )

    parser = argparse.ArgumentParser(
        prog="python -m pytensor_federated_tpu.analysis",
        description="graftlint: the repo's design invariants as "
        "machine-checked static-analysis rules (graftflow engine: "
        "interprocedural dataflow over the async/thread/loop seams)",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        type=Path,
        help="files to check (default: the package, native/cpp_node.cpp, "
        "bench drivers and tools)",
    )
    parser.add_argument(
        "--rule",
        action="append",
        dest="rules",
        metavar="NAME",
        help="run only this rule (repeatable)",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    parser.add_argument(
        "--sarif",
        action="store_true",
        help="SARIF 2.1.0 output (CI inline-annotation lane)",
    )
    parser.add_argument(
        "--changed-only",
        action="store_true",
        help="check only files changed vs HEAD (git-scoped subset run)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for name in sorted(RULES):
            r = RULES[name]
            print(f"{name} [{r.scope}]: {r.summary}")
        return 0

    if args.json and args.sarif:
        print("pick one of --json / --sarif", file=sys.stderr)
        return 2

    unknown = [n for n in (args.rules or []) if n not in RULES]
    if unknown:
        print(
            f"unknown rule(s): {', '.join(unknown)} "
            f"(known: {', '.join(sorted(RULES))})",
            file=sys.stderr,
        )
        return 2

    paths = [p.resolve() for p in args.paths]
    if args.changed_only:
        if paths:
            print(
                "--changed-only and explicit paths are exclusive",
                file=sys.stderr,
            )
            return 2
        targets = set(default_targets())
        paths = [p for p in _changed_paths(repo_root()) if p in targets]
        if not paths:
            print(
                "graftlint: no changed target files — clean by vacuity",
                file=sys.stderr,
            )
            print(
                render_json([])
                if args.json
                else render_sarif([])
                if args.sarif
                else "graftlint: clean (0 findings)"
            )
            return 0

    stats: Dict[str, float] = {}
    findings = run(rules=args.rules, paths=paths or None, stats=stats)
    if args.sarif:
        print(render_sarif(findings))
    elif args.json:
        print(render_json(findings))
    else:
        print(render_human(findings))
    print(
        "graftlint: {rules:.0f} rule(s) over {files:.0f} file(s) "
        "in {seconds:.2f}s".format(**stats),
        file=sys.stderr,
    )
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
