"""graftflow call graph: whole-package edges + concurrency entrypoints.

The interprocedural half of the analysis engine (the intraprocedural
context propagation lives in :mod:`.dataflow`).  One
:class:`CallGraph` is built per lint run from the already-parsed
:class:`~.core.SourceFile` set and shared by every graftflow rule, so
all rules agree on a single call-graph semantics — the PR-7 rules each
carried a private hand-rolled reachability and could (and did)
disagree about what "reachable" meant.

Resolution is HEURISTIC, tuned for a linter (prefer a useful edge over
a provable one, but never guess into noise):

- ``f(...)`` — enclosing function's nested defs, then module-level
  functions, then ``from .mod import f`` symbol imports.
- ``self.m(...)`` / ``cls.m(...)`` — the enclosing class, then its
  in-package bases (one level of name resolution per base).
- ``mod.f(...)`` where ``mod`` is an imported package module — that
  module's ``f``.
- ``obj.m(...)`` on an arbitrary value — resolved only when the
  package defines exactly ONE function/method named ``m`` and the name
  is not in :data:`AMBIENT_METHOD_NAMES` (``close``, ``get``, ``run``,
  … — names shared with stdlib objects, where a unique in-package
  match is usually coincidence).  These edges carry ``kind="unique"``
  so rules can weigh them.
- ``SomeClass(...)`` — an edge to ``SomeClass.__init__`` when the
  class is defined in the package.

Unresolvable calls produce no edge: graftflow can report false
negatives through an unresolved indirection (documented in
docs/static-analysis.md "limits"), never a false path.

Concurrency entrypoints are discovered while the edges are built:
``threading.Thread(target=f)``, ``loop.run_in_executor(None, f)`` /
``executor.submit(f)``, ``asyncio.create_task(coro())`` /
``ensure_future`` / ``loop.create_task``, and
``asyncio.run(...)`` / ``run_until_complete(...)`` loop roots — the
seams the transitive rules root their contexts at.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple

from .core import SourceFile

__all__ = [
    "AMBIENT_METHOD_NAMES",
    "CallEdge",
    "CallGraph",
    "Entrypoint",
    "FuncNode",
    "build_graph",
    "own_body",
]


def own_body(fn: ast.AST) -> List[ast.AST]:
    """A function's OWN statements: the subtree minus nested
    defs/lambdas (they are their own call-graph nodes / opaque values,
    analyzed only when actually reached).  The one body-walk every
    graftflow rule shares, so "own" means the same thing everywhere."""
    out: List[ast.AST] = []
    stack: List[ast.AST] = list(fn.body)  # type: ignore[attr-defined]
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out

_PKG = "pytensor_federated_tpu"

#: Method names too generic for the unique-bare-name fallback: the
#: package defining a single ``close`` does not make ``sock.close()``
#: a call to it.
AMBIENT_METHOD_NAMES: FrozenSet[str] = frozenset(
    {
        "acquire",
        "add",
        "append",
        "cancel",
        "clear",
        "close",
        "copy",
        "count",
        "decode",
        "discard",
        "done",
        "encode",
        "extend",
        "get",
        "items",
        "join",
        "keys",
        "pop",
        "put",
        "read",
        "recv",
        "release",
        "remove",
        "result",
        "run",
        "send",
        "set",
        "shutdown",
        "start",
        "stop",
        "submit",
        "update",
        "values",
        "wait",
        "write",
    }
)

#: Call-wrapper names whose first function-valued argument runs in a
#: NEW concurrency context rather than inline (no plain call edge).
_EXECUTOR_METHODS = frozenset({"run_in_executor"})
_SUBMIT_METHODS = frozenset({"submit"})
_TASK_METHODS = frozenset({"create_task", "ensure_future"})
_LOOP_ROOT_METHODS = frozenset({"run_until_complete"})


@dataclass(frozen=True)
class FuncNode:
    """One function/method definition in the package."""

    qname: str  # "<rel>::<Dotted.Path>" — unique per definition
    rel: str
    name: str  # bare name
    cls: Optional[str]  # immediate enclosing class, if any
    is_async: bool
    lineno: int
    end_lineno: int
    node: ast.AST = field(compare=False, repr=False)
    #: bare identifiers loaded anywhere in the body (full subtree,
    #: nested defs included) — cheap fuel for marker checks
    #: (e.g. "does this function reference ``_fi``").
    refs: FrozenSet[str] = field(compare=False, default=frozenset())
    #: bare names of every call in the body (``f(...)`` -> ``f``,
    #: ``x.m(...)`` -> ``m``; full subtree) — the name-level call
    #: relation rules_shim's conservative reachability runs on, where
    #: an unresolvable ``obj.m()`` must still count as possibly
    #: calling any same-module ``m``.
    called_names: FrozenSet[str] = field(compare=False, default=frozenset())

    @property
    def display(self) -> str:
        kind = "async def" if self.is_async else "def"
        short = self.qname.split("::", 1)[1]
        return f"{kind} {short} ({self.rel}:{self.lineno})"


@dataclass(frozen=True)
class CallEdge:
    """caller --(callsite line)--> callee.  ``kind`` records how the
    callee was resolved: "local" (nested def), "module", "self",
    "import", "class" (constructor), "unique" (package-wide bare-name
    heuristic)."""

    caller: str
    callee: str
    lineno: int
    kind: str


@dataclass(frozen=True)
class Entrypoint:
    """A discovered concurrency seam: ``target`` (a FuncNode qname)
    starts executing in a new context of ``kind`` ("thread",
    "executor", "task", "loop_root")."""

    kind: str
    target: str
    rel: str
    lineno: int
    #: the spawning function's qname (None at module level)
    spawner: Optional[str]
    #: thread name= literal when one was given (daemon probe loops
    #: carry their names; useful in findings)
    label: Optional[str] = None


class _ModuleIndex:
    """Per-module symbol tables used during resolution."""

    def __init__(self, rel: str) -> None:
        self.rel = rel
        # bare name -> qname of a module-level function
        self.functions: Dict[str, str] = {}
        # class name -> {method name -> qname}
        self.classes: Dict[str, Dict[str, str]] = {}
        # class name -> base-class name expressions (unparsed)
        self.bases: Dict[str, List[str]] = {}
        # import alias -> ("module", rel) | ("symbol", rel, name)
        self.imports: Dict[str, Tuple[str, ...]] = {}


def _module_name(rel: str) -> str:
    mod = rel[: -len(".py")] if rel.endswith(".py") else rel
    if mod.endswith("/__init__"):
        mod = mod[: -len("/__init__")]
    return mod.replace("/", ".")


def _rel_for_module(dotted: str, known: Set[str]) -> Optional[str]:
    for cand in (
        dotted.replace(".", "/") + ".py",
        dotted.replace(".", "/") + "/__init__.py",
    ):
        if cand in known:
            return cand
    return None


class CallGraph:
    """The package call graph + entrypoints.  Build with
    :func:`build_graph`; one instance is shared per lint run
    (``RepoContext.graph``)."""

    def __init__(self) -> None:
        self.functions: Dict[str, FuncNode] = {}
        self.edges: Dict[str, List[CallEdge]] = {}
        self.in_edges: Dict[str, List[CallEdge]] = {}
        self.entrypoints: List[Entrypoint] = []
        # (rel, bare name) -> [qnames]; bare name -> [qnames]
        self._by_module_name: Dict[Tuple[str, str], List[str]] = {}
        self._by_bare_name: Dict[str, List[str]] = {}

    # -- queries ----------------------------------------------------------

    def node(self, qname: str) -> FuncNode:
        return self.functions[qname]

    def callees_of(self, qname: str) -> List[CallEdge]:
        return self.edges.get(qname, [])

    def callers_of(self, qname: str) -> List[CallEdge]:
        return self.in_edges.get(qname, [])

    def by_name(self, rel: str, bare: str) -> List[str]:
        """qnames of every function named ``bare`` in module ``rel``."""
        return self._by_module_name.get((rel, bare), [])

    def named(self, bare: str) -> List[str]:
        """qnames of every function named ``bare`` package-wide."""
        return self._by_bare_name.get(bare, [])

    def async_defs(self, rel_prefixes: Sequence[str] = ()) -> List[str]:
        return [
            q
            for q, f in self.functions.items()
            if f.is_async
            and (not rel_prefixes or f.rel.startswith(tuple(rel_prefixes)))
        ]

    def reachable_from(
        self,
        roots: Iterable[str],
        *,
        same_module: bool = False,
        follow_kinds: Optional[FrozenSet[str]] = None,
    ) -> Dict[str, Tuple[CallEdge, ...]]:
        """BFS over call edges from ``roots``; returns, for every
        reached function (roots included), the edge chain that reached
        it — the propagation path findings print.  True breadth-first
        (deque, not a stack): the stored chain is a SHORTEST path from
        the nearest root, so "reachable in N call(s)" in a finding is
        the tightest claim, not an arbitrary walk.  ``same_module``
        restricts edges to the root's file (the rules_shim semantics);
        ``follow_kinds`` filters edge resolution kinds."""
        from collections import deque

        chains: Dict[str, Tuple[CallEdge, ...]] = {}
        frontier: deque = deque()
        for root in roots:
            if root in self.functions and root not in chains:
                chains[root] = ()
                frontier.append(root)
        while frontier:
            qname = frontier.popleft()
            chain = chains[qname]
            for edge in self.edges.get(qname, ()):
                if edge.callee in chains:
                    continue
                if follow_kinds is not None and edge.kind not in follow_kinds:
                    continue
                if (
                    same_module
                    and self.functions[edge.callee].rel
                    != self.functions[qname].rel
                ):
                    continue
                chains[edge.callee] = chain + (edge,)
                frontier.append(edge.callee)
        return chains

    def enclosing(self, rel: str, lineno: int) -> Optional[FuncNode]:
        """The innermost function containing ``lineno`` in ``rel``."""
        best: Optional[FuncNode] = None
        for f in self.functions.values():
            if f.rel != rel or not (f.lineno <= lineno <= f.end_lineno):
                continue
            if best is None or f.lineno >= best.lineno:
                best = f
        return best

    def render_chain(self, chain: Sequence[CallEdge]) -> Tuple[str, ...]:
        """Human chain hops for a Finding: root first, callsite lines
        attached to each jump."""
        if not chain:
            return ()
        hops = [self.functions[chain[0].caller].display]
        for edge in chain:
            callee = self.functions[edge.callee]
            hops.append(
                f"{callee.qname.split('::', 1)[1]} "
                f"(called at {self.functions[edge.caller].rel}:{edge.lineno})"
            )
        return tuple(hops)


def build_graph(sources: Sequence[SourceFile]) -> CallGraph:
    """Index every in-package Python source and resolve its calls.
    Non-package files (tools/, bench drivers, C++) are skipped — the
    interprocedural rules reason about the package's runtime seams."""
    graph = CallGraph()
    pkg_sources = [
        s
        for s in sources
        if s.is_python and s.rel.startswith(_PKG + "/")
    ]
    known_rels = {s.rel for s in pkg_sources}
    indexes: Dict[str, _ModuleIndex] = {}

    # Pass 1: definitions + imports.
    for src in pkg_sources:
        idx = _ModuleIndex(src.rel)
        indexes[src.rel] = idx
        _index_module(graph, idx, src, known_rels)

    # Pass 2: calls + entrypoints.
    for src in pkg_sources:
        _Resolver(graph, indexes, src).resolve()

    for edge in (e for edges in graph.edges.values() for e in edges):
        graph.in_edges.setdefault(edge.callee, []).append(edge)
    return graph


def _index_module(
    graph: CallGraph,
    idx: _ModuleIndex,
    src: SourceFile,
    known_rels: Set[str],
) -> None:
    module = _module_name(src.rel)
    # The package relative imports resolve against: an __init__.py IS
    # its package; a plain module's package is its parent.
    if src.rel.endswith("/__init__.py"):
        pkg_parts = module.split(".")
    else:
        pkg_parts = module.split(".")[:-1]

    def register(fn: ast.AST, scope: Tuple[str, ...], cls: Optional[str]) -> None:
        name = fn.name  # type: ignore[attr-defined]
        dotted = ".".join(scope + (name,))
        qname = f"{src.rel}::{dotted}"
        refs = frozenset(
            n.id
            for n in ast.walk(fn)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
        )
        called = frozenset(
            n.func.id
            if isinstance(n.func, ast.Name)
            else n.func.attr
            for n in ast.walk(fn)
            if isinstance(n, ast.Call)
            and isinstance(n.func, (ast.Name, ast.Attribute))
        )
        node = FuncNode(
            qname=qname,
            rel=src.rel,
            name=name,
            cls=cls,
            is_async=isinstance(fn, ast.AsyncFunctionDef),
            lineno=fn.lineno,  # type: ignore[attr-defined]
            end_lineno=int(getattr(fn, "end_lineno", fn.lineno)),  # type: ignore[attr-defined]
            node=fn,
            refs=refs,
            called_names=called,
        )
        graph.functions[qname] = node
        graph._by_module_name.setdefault((src.rel, name), []).append(qname)
        graph._by_bare_name.setdefault(name, []).append(qname)
        if cls is not None and len(scope) == 1:
            idx.classes.setdefault(cls, {})[name] = qname
        elif not scope:
            idx.functions[name] = qname

    def visit(node: ast.AST, scope: Tuple[str, ...], cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                idx.bases[child.name] = [
                    _safe_unparse(b) for b in child.bases
                ]
                visit(child, scope + (child.name,), child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                register(child, scope, cls)
                visit(child, scope + (child.name,), None)
            else:
                visit(child, scope, cls)

    visit(src.tree, (), None)

    for stmt in ast.walk(src.tree):
        if isinstance(stmt, ast.Import):
            for alias in stmt.names:
                if not alias.name.startswith(_PKG):
                    continue
                rel = _rel_for_module(alias.name, known_rels)
                if rel is not None:
                    idx.imports[alias.asname or alias.name.split(".")[0]] = (
                        "module",
                        rel,
                    )
        elif isinstance(stmt, ast.ImportFrom):
            base: List[str]
            if stmt.level:
                if stmt.level > len(pkg_parts):
                    continue
                base = pkg_parts[: len(pkg_parts) - (stmt.level - 1)]
            elif stmt.module and stmt.module.startswith(_PKG):
                base = []
            else:
                continue
            mod_dotted = ".".join(base + (stmt.module.split(".") if stmt.module else []))
            for alias in stmt.names:
                bound = alias.asname or alias.name
                sub_rel = _rel_for_module(
                    f"{mod_dotted}.{alias.name}" if mod_dotted else alias.name,
                    known_rels,
                )
                if sub_rel is not None:
                    idx.imports[bound] = ("module", sub_rel)
                    continue
                mod_rel = _rel_for_module(mod_dotted, known_rels)
                if mod_rel is not None:
                    idx.imports[bound] = ("symbol", mod_rel, alias.name)


def _safe_unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on exprs
        return ""


class _Resolver:
    """Pass 2 over one module: emit edges + entrypoints."""

    def __init__(
        self,
        graph: CallGraph,
        indexes: Dict[str, _ModuleIndex],
        src: SourceFile,
    ) -> None:
        self.graph = graph
        self.indexes = indexes
        self.idx = indexes[src.rel]
        self.src = src

    def resolve(self) -> None:
        self._visit(self.src.tree, scope=(), cls=None)

    # -- scope walk -------------------------------------------------------

    def _visit(
        self,
        node: ast.AST,
        scope: Tuple[str, ...],
        cls: Optional[str],
        in_function: bool = False,
    ) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                self._visit(
                    child, scope + (child.name,), child.name, in_function
                )
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_body(child, scope + (child.name,), cls)
                self._visit(child, scope + (child.name,), None, True)
            else:
                # Calls inside function bodies belong to _scan_body
                # (which attributes them to their caller); only
                # module/class-level calls are handled here.
                if not in_function and isinstance(child, ast.Call):
                    self._handle_call(child, caller=None, cls=cls, scope=scope)
                self._visit(child, scope, cls, in_function)

    def _scan_body(
        self, fn: ast.AST, scope: Tuple[str, ...], cls: Optional[str]
    ) -> None:
        """Walk one function's own statements (nested defs excluded —
        they are their own nodes, reached only via an actual call)."""
        caller = f"{self.src.rel}::{'.'.join(scope)}"
        nested = {
            child.name
            for stmt in fn.body  # type: ignore[attr-defined]
            for child in ast.walk(stmt)
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        stack: List[ast.AST] = list(fn.body)  # type: ignore[attr-defined]
        while stack:
            node = stack.pop()
            # Nested defs are their own graph nodes; a Lambda is a
            # VALUE (handed to executors / shim wrappers), not inline
            # code — neither body belongs to this caller.  (An
            # immediately-invoked lambda is therefore invisible: the
            # documented under-approximation direction.)
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(node, ast.Call):
                self._handle_call(
                    node, caller=caller, cls=cls, scope=scope, nested=nested
                )
            stack.extend(ast.iter_child_nodes(node))

    # -- call handling ----------------------------------------------------

    def _handle_call(
        self,
        call: ast.Call,
        caller: Optional[str],
        cls: Optional[str],
        scope: Tuple[str, ...],
        nested: Optional[Set[str]] = None,
    ) -> None:
        self._maybe_entrypoint(call, caller, cls, scope, nested)
        resolved = self._resolve_callee(call.func, cls, scope, nested)
        if resolved is None or caller is None:
            return
        callee, kind = resolved
        self.graph.edges.setdefault(caller, []).append(
            CallEdge(caller=caller, callee=callee, lineno=call.lineno, kind=kind)
        )

    def _resolve_callee(
        self,
        func: ast.expr,
        cls: Optional[str],
        scope: Tuple[str, ...],
        nested: Optional[Set[str]] = None,
    ) -> Optional[Tuple[str, str]]:
        rel = self.src.rel
        if isinstance(func, ast.Name):
            name = func.id
            if nested and name in nested:
                # Nested def in the current function: qname is
                # scope + name (immediate nesting only).
                qname = f"{rel}::{'.'.join(scope + (name,))}"
                if qname in self.graph.functions:
                    return qname, "local"
                cands = [
                    q
                    for q in self.graph.by_name(rel, name)
                    if q.startswith(f"{rel}::{'.'.join(scope)}.")
                ]
                if len(cands) == 1:
                    return cands[0], "local"
            if name in self.idx.functions:
                return self.idx.functions[name], "module"
            imp = self.idx.imports.get(name)
            if imp is not None and imp[0] == "symbol":
                target = self._symbol_in(imp[1], imp[2])
                if target is not None:
                    return target
            # In-module class constructor: Pool() -> Pool.__init__.
            init = self.idx.classes.get(name, {}).get("__init__")
            if init is not None:
                return init, "class"
            return None
        if isinstance(func, ast.Attribute):
            attr = func.attr
            value = func.value
            if isinstance(value, ast.Name) and value.id in ("self", "cls"):
                if cls is not None:
                    found = self._method_on(rel, cls, attr, set())
                    if found is not None:
                        return found, "self"
                return self._unique_method(attr)
            if isinstance(value, ast.Name):
                imp = self.idx.imports.get(value.id)
                if imp is not None and imp[0] == "module":
                    target = self._symbol_in(imp[1], attr)
                    if target is not None:
                        return target[0], "import"
                    return None
            return self._unique_method(attr)
        return None

    def _symbol_in(self, rel: str, name: str) -> Optional[Tuple[str, str]]:
        idx = self.indexes.get(rel)
        if idx is None:
            return None
        if name in idx.functions:
            return idx.functions[name], "import"
        init = idx.classes.get(name, {}).get("__init__")
        if init is not None:
            return init, "class"
        # Re-exported through this module's own imports (one level).
        imp = idx.imports.get(name)
        if imp is not None and imp[0] == "symbol" and imp[1] != rel:
            return self._symbol_in(imp[1], imp[2])
        return None

    def _method_on(
        self, rel: str, cls: str, attr: str, seen: Set[Tuple[str, str]]
    ) -> Optional[str]:
        """Method lookup on a class, following in-package bases."""
        if (rel, cls) in seen:
            return None
        seen.add((rel, cls))
        idx = self.indexes.get(rel)
        if idx is None:
            return None
        found = idx.classes.get(cls, {}).get(attr)
        if found is not None:
            return found
        for base in idx.bases.get(cls, ()):  # one name-resolution hop
            base_name = base.split(".")[-1]
            if base_name in idx.classes:
                hit = self._method_on(rel, base_name, attr, seen)
                if hit is not None:
                    return hit
            imp = idx.imports.get(base_name) or idx.imports.get(
                base.split(".")[0]
            )
            if imp is not None and imp[0] == "symbol":
                hit = self._method_on(imp[1], imp[2], attr, seen)
                if hit is not None:
                    return hit
        return None

    def _unique_method(self, attr: str) -> Optional[Tuple[str, str]]:
        if attr in AMBIENT_METHOD_NAMES or attr.startswith("__"):
            return None
        cands = self.graph.named(attr)
        if len(cands) == 1:
            return cands[0], "unique"
        return None

    # -- entrypoints ------------------------------------------------------

    def _maybe_entrypoint(
        self,
        call: ast.Call,
        caller: Optional[str],
        cls: Optional[str],
        scope: Tuple[str, ...],
        nested: Optional[Set[str]],
    ) -> None:
        func = call.func
        dotted = _safe_unparse(func)
        tail = dotted.rsplit(".", 1)[-1]

        def resolve_expr(expr: ast.expr) -> Optional[str]:
            r = self._resolve_callee(expr, cls, scope, nested)
            return r[0] if r is not None else None

        def add(kind: str, target: Optional[str], label: Optional[str] = None) -> None:
            if target is None:
                return
            self.graph.entrypoints.append(
                Entrypoint(
                    kind=kind,
                    target=target,
                    rel=self.src.rel,
                    lineno=call.lineno,
                    spawner=caller,
                    label=label,
                )
            )

        if tail == "Thread":
            target_expr = None
            label = None
            for kw in call.keywords:
                if kw.arg == "target":
                    target_expr = kw.value
                elif kw.arg == "name" and isinstance(kw.value, ast.Constant):
                    label = str(kw.value.value)
            if target_expr is not None:
                add("thread", resolve_expr(target_expr), label)
            return
        if tail in _EXECUTOR_METHODS and len(call.args) >= 2:
            add("executor", resolve_expr(call.args[1]))
            return
        if tail in _SUBMIT_METHODS and call.args:
            add("executor", resolve_expr(call.args[0]))
            return
        if tail in _TASK_METHODS and call.args:
            inner = call.args[0]
            if isinstance(inner, ast.Call):
                add("task", resolve_expr(inner.func))
            else:
                add("task", resolve_expr(inner))
            return
        if (tail in _LOOP_ROOT_METHODS or dotted == "asyncio.run") and call.args:
            inner = call.args[0]
            if isinstance(inner, ast.Call):
                add("loop_root", resolve_expr(inner.func))
