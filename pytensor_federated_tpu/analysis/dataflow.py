"""graftflow context propagation along the call graph.

The :mod:`.graph` half answers "who calls whom"; this module answers
"in what context does a function run" — the property the transitive
rules check:

- :func:`async_reachable` — every function reachable from an async
  context (async defs + ``create_task`` targets) through plain call
  edges.  A sync function called from a coroutine still runs ON the
  event loop; the only escapes are the spawn seams
  (``run_in_executor`` / ``Thread(target=...)`` / ``submit``), which
  produce entrypoints, not call edges — so reachability here is
  exactly "code whose blocking blocks a loop", with the edge chain
  preserved for the finding.
- :func:`concurrency_contexts` — the context sets the race rule
  compares: ``loop`` (async defs + task targets and everything they
  call), ``thread:<target>`` per Thread entrypoint, ``executor`` for
  executor/submit targets, each propagated along call edges.  A
  function reachable from two contexts runs in both — that is the
  point, not a conflict.
- :func:`lock_regions` / :func:`WriteSite` — which attribute/global
  mutations happen under which inferred locks.  Lock inference is
  textual-by-design: a ``with`` item whose expression mentions a name
  containing ``lock`` (``self._lock``, ``_mon_lock``, …) counts; a
  bare blocking ``lock.acquire()`` does not create a region (the
  async-blocking rule flags those separately).  One interprocedural
  refinement: a function whose EVERY in-package caller calls it from
  inside a lock region is itself treated as lock-held (fixpoint), so
  ``with self._lock: self._refresh()`` covers the helper's writes.

Limits (docs/static-analysis.md "Engine"): contexts flow only along
resolved edges — an unresolved indirection (callbacks in data
structures, ``getattr`` dispatch) drops the chain, which makes these
rules under-approximate, never spuriously precise.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .graph import CallEdge, CallGraph, own_body

__all__ = [
    "WriteSite",
    "async_reachable",
    "concurrency_contexts",
    "context_chains",
    "lock_held_functions",
    "mutation_sites",
]

_LOCKISH = re.compile(r"lock", re.IGNORECASE)

#: Mutator method names that count as writes to the receiver —
#: registries mutate dicts/deques through these, not assignments.
_MUTATOR_METHODS: FrozenSet[str] = frozenset(
    {
        "append",
        "appendleft",
        "add",
        "clear",
        "discard",
        "extend",
        "insert",
        "pop",
        "popleft",
        "popitem",
        "remove",
        "setdefault",
        "update",
    }
)


def async_reachable(
    graph: CallGraph, root_prefixes: Sequence[str]
) -> Dict[str, Tuple[CallEdge, ...]]:
    """Functions that run on an event loop: every ``async def`` whose
    file matches ``root_prefixes`` plus every ``create_task`` /
    ``ensure_future`` target, and everything transitively called from
    them.  Returns qname -> the call chain from its nearest root."""
    roots = list(graph.async_defs(root_prefixes))
    roots += [
        e.target
        for e in graph.entrypoints
        if e.kind == "task" and e.rel.startswith(tuple(root_prefixes))
    ]
    return graph.reachable_from(roots)


def concurrency_contexts(graph: CallGraph) -> Dict[str, Set[str]]:
    """qname -> the set of concurrency contexts the function can run
    in: ``"loop"``, ``"thread:<target bare name>"``, ``"executor"``.
    Purely-main-thread code gets an empty set."""
    contexts: Dict[str, Set[str]] = {}

    def paint(roots: List[str], label: str) -> None:
        for qname in graph.reachable_from(roots):
            contexts.setdefault(qname, set()).add(label)

    paint(graph.async_defs(), "loop")
    paint(
        [e.target for e in graph.entrypoints if e.kind == "task"], "loop"
    )
    for entry in graph.entrypoints:
        if entry.kind == "thread":
            target = graph.functions.get(entry.target)
            name = target.name if target is not None else entry.target
            paint([entry.target], f"thread:{name}")
        elif entry.kind == "executor":
            paint([entry.target], "executor")
    return contexts


def context_chains(
    graph: CallGraph,
) -> Dict[str, Dict[str, Tuple[str, Tuple[CallEdge, ...]]]]:
    """Like :func:`concurrency_contexts` but keeping, per (function,
    context), one (root, edge chain) witness — the provenance the race
    rule prints."""
    witness: Dict[str, Dict[str, Tuple[str, Tuple[CallEdge, ...]]]] = {}

    def paint(roots: List[str], label: str) -> None:
        for qname, chain in graph.reachable_from(roots).items():
            per = witness.setdefault(qname, {})
            if label not in per:
                root = chain[0].caller if chain else qname
                per[label] = (root, chain)

    paint(
        graph.async_defs()
        + [e.target for e in graph.entrypoints if e.kind == "task"],
        "loop",
    )
    for entry in graph.entrypoints:
        if entry.kind == "thread":
            target = graph.functions.get(entry.target)
            name = target.name if target is not None else entry.target
            paint([entry.target], f"thread:{name}")
        elif entry.kind == "executor":
            paint([entry.target], "executor")
    return witness


@dataclass(frozen=True)
class WriteSite:
    """One mutation of shared state."""

    qname: str  # enclosing function
    rel: str
    lineno: int
    target: str  # "self.<attr>" or "<module global>"
    attr: str  # bare attribute / global name
    is_self: bool
    locked: bool  # lexically under a with-lock region
    via: str  # "assign" | "augassign" | "subscript" | "del" | mutator name


def _with_lock_spans(fn: ast.AST) -> List[Tuple[int, int]]:
    """(start, end) line spans of ``with <something lock-ish>:``
    bodies inside ``fn`` (nested defs excluded — their regions belong
    to them; shared own-body walk from :mod:`.graph`)."""
    spans: List[Tuple[int, int]] = []
    for node in own_body(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                try:
                    text = ast.unparse(item.context_expr)
                except Exception:  # pragma: no cover
                    text = ""
                if _LOCKISH.search(text):
                    spans.append(
                        (node.lineno, int(getattr(node, "end_lineno", node.lineno)))
                    )
                    break
    return spans


def _module_globals(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
        elif isinstance(stmt, ast.AnnAssign) and isinstance(
            stmt.target, ast.Name
        ):
            out.add(stmt.target.id)
    return out


def _self_attr(node: ast.expr) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def mutation_sites(
    graph: CallGraph, tree: ast.Module, rel: str
) -> List[WriteSite]:
    """Every shared-state mutation in module ``rel``:

    - ``self.x = …`` / ``self.x += …`` / ``self.x[k] = …`` /
      ``del self.x[k]`` in methods (``__init__``/``__post_init__``
      excluded: construction precedes sharing);
    - stores to declared module globals (``global x; x = …``) and
      subscript stores / mutator-method calls on module-global
      containers (the registry pattern: ``_pinned[sid] = ev``,
      ``_ring.append(…)``).
    """
    module_globals = _module_globals(tree)
    sites: List[WriteSite] = []
    for fn in [
        f for f in graph.functions.values() if f.rel == rel
    ]:
        if fn.name in ("__init__", "__post_init__", "__new__"):
            continue
        spans = _with_lock_spans(fn.node)

        def locked(lineno: int) -> bool:
            return any(lo <= lineno <= hi for lo, hi in spans)

        declared_global: Set[str] = set()
        body_nodes = own_body(fn.node)
        for node in body_nodes:
            if isinstance(node, ast.Global):
                declared_global.update(node.names)

        def record(
            lineno: int, attr: str, is_self: bool, via: str
        ) -> None:
            sites.append(
                WriteSite(
                    qname=fn.qname,
                    rel=rel,
                    lineno=lineno,
                    target=f"self.{attr}" if is_self else attr,
                    attr=attr,
                    is_self=is_self,
                    locked=locked(lineno),
                    via=via,
                )
            )

        for node in body_nodes:
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                via = (
                    "augassign"
                    if isinstance(node, ast.AugAssign)
                    else "assign"
                )
                for tgt in targets:
                    attr = _self_attr(tgt)
                    if attr is not None:
                        record(node.lineno, attr, True, via)
                        continue
                    if isinstance(tgt, ast.Name) and (
                        tgt.id in declared_global
                        and tgt.id in module_globals
                    ):
                        record(node.lineno, tgt.id, False, via)
                        continue
                    if isinstance(tgt, ast.Subscript):
                        attr = _self_attr(tgt.value)
                        if attr is not None:
                            record(node.lineno, attr, True, "subscript")
                        elif (
                            isinstance(tgt.value, ast.Name)
                            and tgt.value.id in module_globals
                        ):
                            record(
                                node.lineno, tgt.value.id, False, "subscript"
                            )
            elif isinstance(node, ast.Delete):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Subscript):
                        attr = _self_attr(tgt.value)
                        if attr is not None:
                            record(node.lineno, attr, True, "del")
                        elif (
                            isinstance(tgt.value, ast.Name)
                            and tgt.value.id in module_globals
                        ):
                            record(node.lineno, tgt.value.id, False, "del")
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATOR_METHODS
            ):
                attr = _self_attr(node.func.value)
                if attr is not None:
                    record(node.lineno, attr, True, node.func.attr)
                elif (
                    isinstance(node.func.value, ast.Name)
                    and node.func.value.id in module_globals
                ):
                    record(
                        node.lineno,
                        node.func.value.id,
                        False,
                        node.func.attr,
                    )
    return sites


def lock_held_functions(graph: CallGraph) -> Set[str]:
    """Functions whose every in-package call site sits inside a caller
    lock region (or inside another wholly lock-held function) — the
    ``with self._lock: self._helper()`` pattern.  Fixpoint over the
    call graph; functions with no in-package callers are NOT lock-held
    (an entrypoint can reach them bare)."""
    span_cache: Dict[str, List[Tuple[int, int]]] = {}

    def spans_of(qname: str) -> List[Tuple[int, int]]:
        if qname not in span_cache:
            span_cache[qname] = _with_lock_spans(graph.functions[qname].node)
        return span_cache[qname]

    held: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for qname, fn in graph.functions.items():
            if qname in held:
                continue
            callers = graph.callers_of(qname)
            if not callers:
                continue
            ok = True
            for edge in callers:
                caller_held = edge.caller in held
                under_with = any(
                    lo <= edge.lineno <= hi
                    for lo, hi in spans_of(edge.caller)
                )
                if not (caller_held or under_with):
                    ok = False
                    break
            if ok:
                held.add(qname)
                changed = True
    return held
