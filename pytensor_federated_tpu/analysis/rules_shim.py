"""fault-shim-coverage: chaos reaches every owned I/O seam.

PR 5's contract (docs/robustness.md): deterministic fault injection is
threaded through every owned I/O boundary, so the chaos harness
actually exercises the failure paths it claims to.  A raw socket
send/recv added without its shim silently shrinks chaos coverage —
nothing fails, the harness just stops testing that seam.

Two checks, scoped to ``service/`` and ``routing/`` (the owned
transport stack; :mod:`..faultinject` itself is the shim layer and is
exempt):

1. every raw socket ``sendall``/``recv`` callsite must be reachable
   from a function that references the fault runtime (``_fi.…``) in
   the same module — either the enclosing function holds the seam, or
   a shim-bearing function (transitively) calls it.  Pure transport
   helpers (`_recv_exact`, `_send_frame`) pass because their callers
   shim; a NEW raw I/O path with no shimmed caller fails.
2. every public ``encode_*``/``decode_*`` function in the codec
   modules must contain the chaos seam itself or delegate to a
   same-module sibling that does (the codecs are the byte-lane
   injection points: ``npwire.encode``, ``npproto.decode``, …).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set

from .core import Finding, SourceFile, rule

_RULE = "fault-shim-coverage"

_SCOPE_PREFIXES = (
    "pytensor_federated_tpu/service/",
    "pytensor_federated_tpu/routing/",
)

_CODEC_FILES = (
    "pytensor_federated_tpu/service/npwire.py",
    "pytensor_federated_tpu/service/npproto_codec.py",
)

_RAW_SOCKET_METHODS = {"sendall", "recv", "recv_into"}

#: Names whose presence marks a function as holding a chaos seam.
_FI_MARKERS = {"_fi", "faultinject"}


class _FuncInfo:
    __slots__ = ("name", "node", "refs_fi", "calls")

    def __init__(self, name: str, node: ast.AST):
        self.name = name
        self.node = node
        self.refs_fi = False
        self.calls: Set[str] = set()


def _index_functions(tree: ast.Module) -> Dict[str, _FuncInfo]:
    """Flat function index by bare name (methods included — intra-module
    calls are matched by name, `self.x(...)` counts as calling `x`).
    Same-named functions in different classes MERGE: refs_fi is OR-ed
    and call sets union, so a shimmed method never loses its seam to a
    name collision (the conservative direction for a linter)."""
    out: Dict[str, _FuncInfo] = {}

    def walk_fn(fn: ast.AST) -> None:
        name = fn.name  # type: ignore[attr-defined]
        prev = out.get(name)
        info = _FuncInfo(name, fn)
        if prev is not None:
            info.refs_fi = prev.refs_fi
            info.calls |= prev.calls
        out[name] = info
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and node.id in _FI_MARKERS:
                info.refs_fi = True
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Name):
                    info.calls.add(f.id)
                elif isinstance(f, ast.Attribute):
                    info.calls.add(f.attr)
                    if (
                        isinstance(f.value, ast.Name)
                        and f.value.id in _FI_MARKERS
                    ):
                        info.refs_fi = True

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            walk_fn(node)
    return out


def _shim_reachable(funcs: Dict[str, _FuncInfo]) -> Set[str]:
    """Function names reachable (as callees, transitively) from any
    function that references the fault runtime."""
    reached: Set[str] = set()
    frontier: List[str] = [n for n, f in funcs.items() if f.refs_fi]
    reached.update(frontier)
    while frontier:
        name = frontier.pop()
        for callee in funcs[name].calls:
            if callee in funcs and callee not in reached:
                reached.add(callee)
                frontier.append(callee)
    return reached


def _enclosing_function(
    tree: ast.Module, target: ast.AST
) -> str:
    best = "<module>"
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if (
                node.lineno <= target.lineno
                and target.lineno <= max(
                    getattr(node, "end_lineno", node.lineno), node.lineno
                )
            ):
                best = node.name
    return best


def _raw_socket_findings(src: SourceFile) -> Iterator[Finding]:
    funcs = _index_functions(src.tree)
    covered = _shim_reachable(funcs)
    for node in ast.walk(src.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _RAW_SOCKET_METHODS
        ):
            continue
        fn = _enclosing_function(src.tree, node)
        if fn in covered:
            continue
        yield src.finding(
            _RULE,
            node.lineno,
            f"raw socket `.{node.func.attr}(...)` in `{fn}` is not "
            "reachable from any faultinject-shimmed function in this "
            "module — route it through a faultinject.runtime point "
            "(filter_bytes / send_frame_through) so chaos coverage "
            "includes this seam",
        )


def _codec_findings(src: SourceFile) -> Iterator[Finding]:
    funcs: Dict[str, ast.FunctionDef] = {
        node.name: node
        for node in src.tree.body
        if isinstance(node, ast.FunctionDef)
    }

    def has_seam_or_delegates(fn: ast.FunctionDef, seen: Set[str]) -> bool:
        if fn.name in seen:
            return False
        seen.add(fn.name)
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id in _FI_MARKERS
            ):
                return True
            if isinstance(node, ast.Call):
                name = (
                    node.func.id
                    if isinstance(node.func, ast.Name)
                    else getattr(node.func, "attr", "")
                )
                if (
                    name != fn.name
                    and name.startswith(("encode_", "decode_"))
                    and name in funcs
                    and has_seam_or_delegates(funcs[name], seen)
                ):
                    return True
        return False

    # A sub-message helper (encode_ndarray inside encode_arrays_msg)
    # is covered when a seam-bearing sibling transitively CALLS it —
    # the fault fires one frame up and still corrupts these bytes.
    covered_by_caller: Set[str] = set()
    frontier = [
        name
        for name, fn in funcs.items()
        if has_seam_or_delegates(fn, set())
    ]
    seen_callers: Set[str] = set(frontier)
    while frontier:
        caller = frontier.pop()
        for node in ast.walk(funcs[caller]):
            if isinstance(node, ast.Call):
                name = (
                    node.func.id
                    if isinstance(node.func, ast.Name)
                    else getattr(node.func, "attr", "")
                )
                if name in funcs and name not in seen_callers:
                    covered_by_caller.add(name)
                    seen_callers.add(name)
                    frontier.append(name)

    for name, fn in sorted(funcs.items()):
        if not name.startswith(("encode_", "decode_")):
            continue
        if name.startswith("_"):
            continue
        if name in covered_by_caller:
            continue
        if not has_seam_or_delegates(fn, set()):
            yield src.finding(
                _RULE,
                fn.lineno,
                f"codec function `{name}` has no faultinject seam, does "
                "not delegate to one, and no seam-bearing sibling calls "
                "it — byte-lane chaos (corrupt/truncate/delay) cannot "
                "reach it",
            )


@rule(
    _RULE,
    "raw socket send/recv and codec encode/decode paths in service/ and "
    "routing/ must route through a faultinject.runtime injection point",
)
def check_fault_shim_coverage(src: SourceFile) -> Iterator[Finding]:
    if not src.is_python:
        return
    if src.rel in _CODEC_FILES:
        yield from _codec_findings(src)
        yield from _raw_socket_findings(src)
        return
    if not src.rel.startswith(_SCOPE_PREFIXES):
        return
    yield from _raw_socket_findings(src)
