"""fault-shim-coverage: chaos reaches every owned I/O seam.

PR 5's contract (docs/robustness.md): deterministic fault injection is
threaded through every owned I/O boundary, so the chaos harness
actually exercises the failure paths it claims to.  A raw socket
send/recv added without its shim silently shrinks chaos coverage —
nothing fails, the harness just stops testing that seam.

Since PR 8 the reachability behind both checks runs on the shared
graftflow call graph (:mod:`.graph`) instead of a module-private
index, so "reachable" means the same thing here as in every other
rule.  This rule deliberately uses the graph's NAME-LEVEL call
relation (``FuncNode.called_names``) rather than resolved edges: an
unresolvable ``obj.m()`` must still count as possibly calling any
same-module ``m`` — merging same-named functions is the conservative
direction for a coverage check (a shimmed method never loses its seam
to a name collision).

Two checks, scoped to ``service/`` and ``routing/`` (the owned
transport stack; :mod:`..faultinject` itself is the shim layer and is
exempt):

1. every raw socket ``sendall``/``recv`` callsite must be reachable
   from a function that references the fault runtime (``_fi.…``) in
   the same module — either the enclosing function holds the seam, or
   a shim-bearing function (transitively) calls it.
2. every public ``encode_*``/``decode_*`` function in the codec
   modules must contain the chaos seam itself, delegate to a
   same-module sibling that does, or be (transitively) called by a
   seam-bearing sibling — the fault fires one frame up and still
   corrupts these bytes.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Sequence, Set

from .core import Finding, RepoContext, rule
from .graph import CallGraph, FuncNode

_RULE = "fault-shim-coverage"

_SCOPE_PREFIXES = (
    "pytensor_federated_tpu/service/",
    "pytensor_federated_tpu/routing/",
)

_CODEC_FILES = (
    "pytensor_federated_tpu/service/npwire.py",
    "pytensor_federated_tpu/service/npproto_codec.py",
    "pytensor_federated_tpu/service/shm.py",
)

_RAW_SOCKET_METHODS = {"sendall", "recv", "recv_into"}

#: Names whose presence marks a function as holding a chaos seam.
_FI_MARKERS = {"_fi", "faultinject"}


def _module_nodes(graph: CallGraph, rel: str) -> List[FuncNode]:
    return [f for f in graph.functions.values() if f.rel == rel]


def _name_reachable(
    nodes: Sequence[FuncNode], roots: Set[str]
) -> Set[str]:
    """Bare names reachable from ``roots`` over the name-level call
    relation, same-named functions merged (call sets union)."""
    calls: Dict[str, Set[str]] = {}
    defined: Set[str] = set()
    for f in nodes:
        defined.add(f.name)
        calls.setdefault(f.name, set()).update(f.called_names)
    reached = set(roots)
    frontier = list(roots)
    while frontier:
        name = frontier.pop()
        for callee in calls.get(name, ()):
            if callee in defined and callee not in reached:
                reached.add(callee)
                frontier.append(callee)
    return reached


def _fi_roots(nodes: Sequence[FuncNode]) -> Set[str]:
    return {f.name for f in nodes if f.refs & _FI_MARKERS}


def _raw_socket_findings(
    ctx: RepoContext, rel: str
) -> Iterator[Finding]:
    graph = ctx.graph
    src = ctx.by_rel[rel]
    nodes = _module_nodes(graph, rel)
    covered = _name_reachable(nodes, _fi_roots(nodes))
    for node in src.nodes(ast.Call):
        if not (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _RAW_SOCKET_METHODS
        ):
            continue
        enclosing = graph.enclosing(rel, node.lineno)
        fn_name = enclosing.name if enclosing is not None else "<module>"
        if fn_name in covered:
            continue
        yield src.finding(
            _RULE,
            node.lineno,
            f"raw socket `.{node.func.attr}(...)` in `{fn_name}` is not "
            "reachable from any faultinject-shimmed function in this "
            "module — route it through a faultinject.runtime point "
            "(filter_bytes / send_frame_through) so chaos coverage "
            "includes this seam",
        )


def _codec_findings(ctx: RepoContext, rel: str) -> Iterator[Finding]:
    graph = ctx.graph
    src = ctx.by_rel[rel]
    # Module-level codec functions only (methods are helpers of their
    # classes, not the public byte lanes).
    nodes = [f for f in _module_nodes(graph, rel) if f.cls is None]
    by_name = {f.name: f for f in nodes}

    # Seam-bearing: references _fi directly, or delegates (transitively)
    # to a same-module encode_*/decode_* sibling that does.
    seam: Set[str] = _fi_roots(nodes)
    changed = True
    while changed:
        changed = False
        for f in nodes:
            if f.name in seam:
                continue
            if any(
                callee in seam
                and callee != f.name
                and callee.startswith(("encode_", "decode_"))
                and callee in by_name
                for callee in f.called_names
            ):
                seam.add(f.name)
                changed = True

    # A sub-message helper (encode_ndarray inside encode_arrays_msg)
    # is covered when a seam-bearing sibling transitively CALLS it —
    # the fault fires one frame up and still corrupts these bytes.
    covered_by_caller = _name_reachable(nodes, seam)

    for f in sorted(nodes, key=lambda f: f.name):
        name = f.name
        if not name.startswith(("encode_", "decode_")):
            continue
        if name.startswith("_"):
            continue
        if name in seam or name in covered_by_caller:
            continue
        yield src.finding(
            _RULE,
            f.lineno,
            f"codec function `{name}` has no faultinject seam, does "
            "not delegate to one, and no seam-bearing sibling calls "
            "it — byte-lane chaos (corrupt/truncate/delay) cannot "
            "reach it",
        )


@rule(
    _RULE,
    "raw socket send/recv and codec encode/decode paths in service/ and "
    "routing/ must route through a faultinject.runtime injection point "
    "(reachability on the shared graftflow call graph)",
    scope="repo",
)
def check_fault_shim_coverage(ctx: RepoContext) -> Iterator[Finding]:
    for src in ctx:
        if not src.is_python:
            continue
        if src.rel in _CODEC_FILES:
            yield from _codec_findings(ctx, src.rel)
            yield from _raw_socket_findings(ctx, src.rel)
            continue
        if src.rel.startswith(_SCOPE_PREFIXES):
            yield from _raw_socket_findings(ctx, src.rel)
