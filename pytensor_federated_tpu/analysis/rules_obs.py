"""observability-drift: code and docs/observability.md agree.

The metric catalog and flight-recorder event taxonomy in
``docs/observability.md`` are the operator's contract — dashboards and
incident tooling are built against them.  A metric registered in code
but absent from the doc is invisible operational surface; a documented
event no code path emits is a dashboard that can never fire.  This
rule extracts both vocabularies from the code (AST, literal-first-arg
calls) and the doc (backticked tokens) and fails on drift in either
direction.

Dynamic names are matched by prefix: an f-string event like
``f"fault.{rule.kind}"`` covers every documented name under
``fault.``, and a documented wildcard like ``fault.<kind>`` covers any
code emission with that prefix.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Sequence, Tuple

from .core import Finding, SourceFile, rule

_RULE = "observability-drift"
_DOC = "docs/observability.md"

_METRIC_FACTORIES = {"counter", "gauge", "histogram"}
_METRIC_RE = re.compile(r"`(pftpu_[a-z0-9_]+)`")
_EVENT_TOKEN_RE = re.compile(r"`([a-z][a-z0-9_]*\.[a-z0-9_.<>]+)`")

_FLIGHTREC_HEADING = "### `telemetry.flightrec`"


def _doc_metrics(text: str) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for i, line in enumerate(text.splitlines(), start=1):
        for m in _METRIC_RE.finditer(line):
            out.setdefault(m.group(1), i)
    return out


def _doc_events(text: str) -> Dict[str, int]:
    """Event names from the flight-recorder taxonomy table: the first
    cell of each row, split on `` / `` for multi-name rows."""
    out: Dict[str, int] = {}
    lines = text.splitlines()
    in_section = False
    for i, line in enumerate(lines, start=1):
        if line.startswith(_FLIGHTREC_HEADING):
            in_section = True
            continue
        if in_section and line.startswith("### "):
            break
        if not in_section or not line.startswith("|"):
            continue
        cells = [c.strip() for c in line.strip("|").split("|")]
        if not cells or cells[0] in ("kind", "---", ""):
            continue
        for m in _EVENT_TOKEN_RE.finditer(cells[0]):
            out.setdefault(m.group(1), i)
    return out


def _literal_or_prefix(arg: ast.expr) -> Tuple[str, bool]:
    """A string constant -> (name, False); an f-string with a literal
    head -> (prefix, True); anything else -> ("", ...) = unanalyzable."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value, False
    if isinstance(arg, ast.JoinedStr) and arg.values:
        head = arg.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            return head.value, True
    return "", False


def _code_vocab(
    sources: Sequence[SourceFile],
) -> Tuple[
    Dict[str, Tuple[str, int]],
    Dict[str, Tuple[str, int]],
    Dict[str, Tuple[str, int]],
]:
    """-> (metrics, exact events, prefix events), name -> (rel, line)."""
    metrics: Dict[str, Tuple[str, int]] = {}
    events: Dict[str, Tuple[str, int]] = {}
    prefixes: Dict[str, Tuple[str, int]] = {}
    for src in sources:
        if not src.is_python:
            continue
        is_flightrec = src.rel.endswith("telemetry/flightrec.py")
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            fname = (
                node.func.id
                if isinstance(node.func, ast.Name)
                else getattr(node.func, "attr", "")
            )
            loc = (src.rel, node.lineno)
            if fname in _METRIC_FACTORIES:
                name, is_prefix = _literal_or_prefix(node.args[0])
                if name.startswith("pftpu_") and not is_prefix:
                    metrics.setdefault(name, loc)
                continue
            # flightrec.record("kind", ...) everywhere; flightrec.py
            # itself builds events through its private _event helper
            # (the span hooks bypass record()).
            is_record = fname == "record" and isinstance(
                node.func, ast.Attribute
            ) and "flightrec" in ast.unparse(node.func.value)
            is_internal = is_flightrec and fname in ("record", "_event")
            if not (is_record or is_internal):
                continue
            name, is_prefix = _literal_or_prefix(node.args[0])
            if not name:
                continue
            if is_prefix:
                prefixes.setdefault(name, loc)
            else:
                events.setdefault(name, loc)
    return metrics, events, prefixes


@rule(
    _RULE,
    "every pftpu_* metric family and flightrec event name in code "
    "appears in docs/observability.md, and vice versa",
    scope="repo",
)
def check_observability_drift(
    sources: Sequence[SourceFile],
) -> Iterator[Finding]:
    root = sources[0].root if sources else None
    if root is None:
        return
    doc_path = root / _DOC
    if not doc_path.exists():
        yield Finding(_RULE, _DOC, 1, "docs/observability.md is missing")
        return
    text = doc_path.read_text(encoding="utf-8")
    doc_metrics = _doc_metrics(text)
    doc_events_all = _doc_events(text)
    doc_events = {n: l for n, l in doc_events_all.items() if "<" not in n}
    doc_wildcards = {
        n.split("<", 1)[0]: l for n, l in doc_events_all.items() if "<" in n
    }
    code_metrics, code_events, code_prefixes = _code_vocab(sources)

    for name, (rel, line) in sorted(code_metrics.items()):
        if name not in doc_metrics:
            yield Finding(
                _RULE,
                rel,
                line,
                f"metric family `{name}` is registered here but not "
                "documented in docs/observability.md",
            )
    for name, line in sorted(doc_metrics.items()):
        if name not in code_metrics:
            yield Finding(
                _RULE,
                _DOC,
                line,
                f"metric family `{name}` is documented but never "
                "registered in code",
            )

    def doc_covers(name: str) -> bool:
        return name in doc_events or any(
            name.startswith(w) for w in doc_wildcards
        )

    for name, (rel, line) in sorted(code_events.items()):
        if not doc_covers(name):
            yield Finding(
                _RULE,
                rel,
                line,
                f"flightrec event `{name}` is emitted here but missing "
                "from the docs/observability.md event taxonomy",
            )
    for prefix, (rel, line) in sorted(code_prefixes.items()):
        covered = any(
            d.startswith(prefix) for d in doc_events
        ) or any(
            w.startswith(prefix) or prefix.startswith(w)
            for w in doc_wildcards
        )
        if not covered:
            yield Finding(
                _RULE,
                rel,
                line,
                f"dynamic flightrec event `{prefix}…` has no matching "
                "entry in the docs/observability.md event taxonomy",
            )

    def code_covers(doc_name: str) -> bool:
        return doc_name in code_events or any(
            doc_name.startswith(p) for p in code_prefixes
        )

    for name, line in sorted(doc_events.items()):
        if not code_covers(name):
            yield Finding(
                _RULE,
                _DOC,
                line,
                f"documented flightrec event `{name}` is never emitted "
                "by any code path",
            )
    for prefix, line in sorted(doc_wildcards.items()):
        covered = any(
            e.startswith(prefix) for e in code_events
        ) or any(
            p.startswith(prefix) or prefix.startswith(p)
            for p in code_prefixes
        )
        if not covered:
            yield Finding(
                _RULE,
                _DOC,
                line,
                f"documented wildcard event `{prefix}<…>` has no "
                "emitting code path",
            )
