"""graftlint core: sources, rule registry, suppressions, runner.

The shape of the thing: a :class:`Rule` is a named checker function
over either one :class:`SourceFile` (``scope="file"``) or the whole
collected source set (``scope="repo"`` — cross-file registries,
introspective checks).  Rules register themselves into :data:`RULES`
via the :func:`rule` decorator at import time
(:mod:`..analysis` imports every ``rules_*`` module), produce
:class:`Finding` records, and the runner filters findings through
inline suppressions before reporting.

Suppressions are per-line and per-rule::

    do_risky_thing()  # graftlint: disable=wire-loudness -- probe verdict lane

The directive is honored on the finding's own line or the line
immediately above it (so a comment can sit on its own line above a
long statement); ``disable=all`` silences every rule for that line.
Everything after ``--`` is a human justification, encouraged and
ignored by the parser.  The same syntax works in C++ sources behind
``//`` comments — the scanner matches the directive text, not the
comment lexer.

Design constraints honored here:

- No third-party dependencies (the container cannot grow any) — the
  Python rules are :mod:`ast`/:mod:`tokenize` walks, the C++ rule is
  line/regex parsing.
- File rules must not import the package under analysis; only the two
  explicitly introspective repo rules (``fed-rule-completeness``,
  which needs jax's registries, and nothing else) may import, and they
  call :func:`~..utils.force_cpu_backend` first so a wire check can
  never dial the tunneled TPU plugin (CLAUDE.md environment pitfalls).
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Sequence

__all__ = [
    "Finding",
    "SourceFile",
    "Rule",
    "RULES",
    "rule",
    "repo_root",
    "default_targets",
    "load_sources",
    "run",
    "render_human",
    "render_json",
]

#: ``# graftlint: disable=rule-a,rule-b [-- justification]`` (also
#: behind ``//`` in C++).  The justification tail is free text.
_SUPPRESS_RE = re.compile(
    r"(?:#|//)\s*graftlint:\s*disable=([A-Za-z0-9_,\- ]+)"
)


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class SourceFile:
    """One target file: text, line-indexed suppressions, lazy AST."""

    def __init__(self, path: Path, root: Path):
        self.path = path
        self.root = root
        self.rel = path.relative_to(root).as_posix()
        self.text = path.read_text(encoding="utf-8")
        self.lines = self.text.splitlines()
        self.is_python = path.suffix == ".py"
        self._tree: Optional[ast.Module] = None
        # line number (1-based) -> set of rule names disabled there
        self.suppressions: Dict[int, set] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                # Everything after `--` is the human justification.
                spec = m.group(1).split("--", 1)[0]
                names = {
                    part.strip()
                    for part in spec.split(",")
                    if part.strip()
                }
                self.suppressions[i] = names

    @property
    def tree(self) -> ast.Module:
        if self._tree is None:
            if not self.is_python:
                raise ValueError(f"{self.rel} is not a Python source")
            self._tree = ast.parse(self.text, filename=self.rel)
        return self._tree

    def suppressed(self, rule_name: str, line: int) -> bool:
        """Whether ``rule_name`` is disabled on ``line`` (same line or
        the line directly above)."""
        for ln in (line, line - 1):
            names = self.suppressions.get(ln)
            if names and (rule_name in names or "all" in names):
                return True
        return False

    def finding(self, rule_name: str, line: int, message: str) -> Finding:
        return Finding(rule_name, self.rel, line, message)


@dataclass(frozen=True)
class Rule:
    """A registered checker.  ``func`` yields/returns Findings; file
    rules receive one :class:`SourceFile`, repo rules the full list."""

    name: str
    summary: str
    scope: str  # "file" | "repo"
    func: Callable = field(compare=False)


#: name -> Rule; populated by the :func:`rule` decorator when
#: :mod:`..analysis` imports the rules modules.
RULES: Dict[str, Rule] = {}


def rule(name: str, summary: str, scope: str = "file"):
    """Register a checker under ``name`` (kebab-case, the suppression
    and CLI handle)."""
    if scope not in ("file", "repo"):
        raise ValueError(f"scope must be 'file' or 'repo', got {scope!r}")

    def deco(func: Callable) -> Callable:
        if name in RULES:
            raise ValueError(f"duplicate rule name {name!r}")
        RULES[name] = Rule(name=name, summary=summary, scope=scope, func=func)
        return func

    return deco


def repo_root() -> Path:
    """The repository root: parent of the ``pytensor_federated_tpu``
    package directory this module lives in."""
    return Path(__file__).resolve().parent.parent.parent


def default_targets(root: Optional[Path] = None) -> List[Path]:
    """The full-repo target set: every package ``.py`` file, the C++
    node, and the top-level bench drivers + tools scripts the
    observability rule must see (they register metrics and record
    flight events too)."""
    root = root or repo_root()
    pkg = root / "pytensor_federated_tpu"
    targets = sorted(
        p for p in pkg.rglob("*.py") if "__pycache__" not in p.parts
    )
    for extra in ("bench.py", "bench_suite.py"):
        p = root / extra
        if p.exists():
            targets.append(p)
    tools = root / "tools"
    if tools.is_dir():
        targets.extend(sorted(tools.glob("*.py")))
    cpp = root / "native" / "cpp_node.cpp"
    if cpp.exists():
        targets.append(cpp)
    return targets


def load_sources(
    paths: Iterable[Path], root: Optional[Path] = None
) -> List[SourceFile]:
    root = root or repo_root()
    return [SourceFile(Path(p), root) for p in paths]


def run(
    rules: Optional[Sequence[str]] = None,
    paths: Optional[Iterable[Path]] = None,
    root: Optional[Path] = None,
) -> List[Finding]:
    """Run the selected rules (default: all registered) over ``paths``
    (default: the full-repo target set); returns unsuppressed findings
    sorted by location.

    Explicit ``paths`` select a SUBSET: file rules run over just those
    files, while repo-scope rules (cross-file registries, code-vs-docs
    diffs) still see the full target set — comparing the docs against
    three files would report everything else as missing — and only
    their findings that land inside the subset are reported."""
    root = root or repo_root()
    sources = load_sources(paths or default_targets(root), root)
    by_rel = {s.rel: s for s in sources}
    if paths is None:
        subset_rels = None
        repo_sources = sources
    else:
        subset_rels = set(by_rel)
        repo_sources = load_sources(default_targets(root), root)
        by_rel.update({s.rel: s for s in repo_sources})
    selected = [RULES[n] for n in (rules or sorted(RULES))]
    findings: List[Finding] = []
    for r in selected:
        if r.scope == "file":
            for src in sources:
                findings.extend(r.func(src) or [])
        else:
            for f in r.func(repo_sources) or []:
                if subset_rels is None or f.path in subset_rels:
                    findings.append(f)
    kept = []
    for f in findings:
        src = by_rel.get(f.path)
        if src is not None and src.suppressed(f.rule, f.line):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    return kept


def render_human(findings: Sequence[Finding]) -> str:
    if not findings:
        return "graftlint: clean (0 findings)"
    lines = [str(f) for f in findings]
    lines.append(f"graftlint: {len(findings)} finding(s)")
    return "\n".join(lines)


def render_json(findings: Sequence[Finding]) -> str:
    return json.dumps(
        {
            "findings": [f.to_dict() for f in findings],
            "count": len(findings),
        },
        indent=2,
    )
