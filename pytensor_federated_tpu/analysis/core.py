"""graftlint core: sources, rule registry, suppressions, runner.

The shape of the thing: a :class:`Rule` is a named checker function
over either one :class:`SourceFile` (``scope="file"``) or the whole
collected source set (``scope="repo"`` — cross-file registries,
introspective checks).  Rules register themselves into :data:`RULES`
via the :func:`rule` decorator at import time
(:mod:`..analysis` imports every ``rules_*`` module), produce
:class:`Finding` records, and the runner filters findings through
inline suppressions before reporting.

Suppressions are per-line and per-rule::

    do_risky_thing()  # graftlint: disable=wire-loudness -- probe verdict lane

The directive is honored on the finding's own line or the line
immediately above it (so a comment can sit on its own line above a
long statement); ``disable=all`` silences every rule for that line.
Everything after ``--`` is a human justification, encouraged and
ignored by the parser.  The same syntax works in C++ sources behind
``//`` comments — the scanner matches the directive text, not the
comment lexer.

Design constraints honored here:

- No third-party dependencies (the container cannot grow any) — the
  Python rules are :mod:`ast`/:mod:`tokenize` walks, the C++ rule is
  line/regex parsing.
- File rules must not import the package under analysis; only the two
  explicitly introspective repo rules (``fed-rule-completeness``,
  which needs jax's registries, and nothing else) may import, and they
  call :func:`~..utils.force_cpu_backend` first so a wire check can
  never dial the tunneled TPU plugin (CLAUDE.md environment pitfalls).
"""

from __future__ import annotations

import ast
import json
import re
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import (
    TYPE_CHECKING,
    Callable,
    Dict,
    Iterable,
    List,
    Optional,
    Sequence,
    Tuple,
    Type,
)

if TYPE_CHECKING:  # graph imports core; the cycle exists only for types
    from .graph import CallGraph

__all__ = [
    "Finding",
    "SourceFile",
    "RepoContext",
    "Rule",
    "RULES",
    "rule",
    "repo_root",
    "default_targets",
    "load_sources",
    "run",
    "render_human",
    "render_json",
    "render_sarif",
]

#: ``# graftlint: disable=rule-a,rule-b [-- justification]`` (also
#: behind ``//`` in C++).  The justification tail is free text.
_SUPPRESS_RE = re.compile(
    r"(?:#|//)\s*graftlint:\s*disable=([A-Za-z0-9_,\- ]+)"
)


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location.  ``chain`` is the
    interprocedural propagation path (root context first) for graftflow
    rules; per-function rules leave it empty.  The JSON/SARIF schema
    always carries the key (pinned by tests/test_graftlint.py)."""

    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str
    chain: Tuple[str, ...] = ()

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "chain": list(self.chain),
        }

    def __str__(self) -> str:
        base = f"{self.path}:{self.line}: [{self.rule}] {self.message}"
        if self.chain:
            hops = "\n".join(f"    {i}. {hop}" for i, hop in enumerate(self.chain))
            base = f"{base}\n{hops}"
        return base


class SourceFile:
    """One target file: text, line-indexed suppressions, lazy AST."""

    def __init__(self, path: Path, root: Path) -> None:
        self.path = path
        self.root = root
        self.rel = path.relative_to(root).as_posix()
        self.text = path.read_text(encoding="utf-8")
        self.lines = self.text.splitlines()
        self.is_python = path.suffix == ".py"
        self._tree: Optional[ast.Module] = None
        self._walked: Optional[List[ast.AST]] = None
        # line number (1-based) -> set of rule names disabled there
        self.suppressions: Dict[int, set] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                # Everything after `--` is the human justification.
                spec = m.group(1).split("--", 1)[0]
                names = {
                    part.strip()
                    for part in spec.split(",")
                    if part.strip()
                }
                self.suppressions[i] = names

    @property
    def tree(self) -> ast.Module:
        if self._tree is None:
            if not self.is_python:
                raise ValueError(f"{self.rel} is not a Python source")
            self._tree = ast.parse(self.text, filename=self.rel)
        return self._tree

    def walk(self) -> List[ast.AST]:
        """The full node walk, computed ONCE and shared by every rule
        (the single-pass contract: core parses and walks each file one
        time per run; rules filter with :meth:`nodes`)."""
        if self._walked is None:
            self._walked = list(ast.walk(self.tree))
        return self._walked

    def nodes(self, *types: Type[ast.AST]) -> List[ast.AST]:
        """All AST nodes of the given types, from the shared walk."""
        return [n for n in self.walk() if isinstance(n, types)]

    def suppressed(self, rule_name: str, line: int) -> bool:
        """Whether ``rule_name`` is disabled on ``line`` (same line or
        the line directly above)."""
        for ln in (line, line - 1):
            names = self.suppressions.get(ln)
            if names and (rule_name in names or "all" in names):
                return True
        return False

    def finding(self, rule_name: str, line: int, message: str) -> Finding:
        return Finding(rule_name, self.rel, line, message)


class RepoContext(List[SourceFile]):
    """What a repo-scope rule receives: the full source list (it IS a
    list, so pre-graftflow rules iterate it unchanged) plus the shared
    analysis state — the call graph is built lazily ONCE per run and
    reused by every interprocedural rule, so all rules agree on one
    call-graph semantics."""

    def __init__(self, sources: Iterable[SourceFile]) -> None:
        super().__init__(sources)
        self._graph: Optional["CallGraph"] = None
        self._by_rel: Optional[Dict[str, SourceFile]] = None

    @property
    def by_rel(self) -> Dict[str, SourceFile]:
        if self._by_rel is None:
            self._by_rel = {s.rel: s for s in self}
        return self._by_rel

    @property
    def graph(self) -> "CallGraph":  # late import: graph imports core
        if self._graph is None:
            from .graph import build_graph

            self._graph = build_graph(self)
        return self._graph


@dataclass(frozen=True)
class Rule:
    """A registered checker.  ``func`` yields/returns Findings; file
    rules receive one :class:`SourceFile`, repo rules a
    :class:`RepoContext` (a list of every SourceFile, carrying the
    shared call graph)."""

    name: str
    summary: str
    scope: str  # "file" | "repo"
    func: Callable = field(compare=False)


#: name -> Rule; populated by the :func:`rule` decorator when
#: :mod:`..analysis` imports the rules modules.
RULES: Dict[str, Rule] = {}


def rule(
    name: str, summary: str, scope: str = "file"
) -> Callable[[Callable], Callable]:
    """Register a checker under ``name`` (kebab-case, the suppression
    and CLI handle)."""
    if scope not in ("file", "repo"):
        raise ValueError(f"scope must be 'file' or 'repo', got {scope!r}")

    def deco(func: Callable) -> Callable:
        if name in RULES:
            raise ValueError(f"duplicate rule name {name!r}")
        RULES[name] = Rule(name=name, summary=summary, scope=scope, func=func)
        return func

    return deco


def repo_root() -> Path:
    """The repository root: parent of the ``pytensor_federated_tpu``
    package directory this module lives in."""
    return Path(__file__).resolve().parent.parent.parent


def default_targets(root: Optional[Path] = None) -> List[Path]:
    """The full-repo target set: every package ``.py`` file, the C++
    node, and the top-level bench drivers + tools scripts the
    observability rule must see (they register metrics and record
    flight events too)."""
    root = root or repo_root()
    pkg = root / "pytensor_federated_tpu"
    targets = sorted(
        p for p in pkg.rglob("*.py") if "__pycache__" not in p.parts
    )
    for extra in ("bench.py", "bench_suite.py"):
        p = root / extra
        if p.exists():
            targets.append(p)
    tools = root / "tools"
    if tools.is_dir():
        targets.extend(sorted(tools.glob("*.py")))
    cpp = root / "native" / "cpp_node.cpp"
    if cpp.exists():
        targets.append(cpp)
    return targets


def load_sources(
    paths: Iterable[Path], root: Optional[Path] = None
) -> List[SourceFile]:
    root = root or repo_root()
    return [SourceFile(Path(p), root) for p in paths]


def run(
    rules: Optional[Sequence[str]] = None,
    paths: Optional[Iterable[Path]] = None,
    root: Optional[Path] = None,
    stats: Optional[Dict[str, float]] = None,
) -> List[Finding]:
    """Run the selected rules (default: all registered) over ``paths``
    (default: the full-repo target set); returns unsuppressed findings
    sorted by location.

    Explicit ``paths`` select a SUBSET: file rules run over just those
    files, while repo-scope rules (cross-file registries, code-vs-docs
    diffs, the call graph) still see the full target set — comparing
    the docs against three files would report everything else as
    missing — and only their findings that land inside the subset are
    reported.

    Single-pass contract: every file is read and parsed ONCE per run —
    subset sources are reused inside the full repo set, the AST walk is
    cached on the SourceFile, and the call graph is built once on the
    shared :class:`RepoContext`.  ``stats``, when given, receives
    ``files``/``rules``/``seconds`` for the driver's timing line."""
    t0 = time.perf_counter()
    root = root or repo_root()
    if paths is None:
        sources = load_sources(default_targets(root), root)
        subset_rels = None
        repo_sources = RepoContext(sources)
        by_rel = repo_sources.by_rel
    else:
        sources = load_sources(paths, root)
        subset_rels = {s.rel for s in sources}
        # Reuse the subset's SourceFile objects (and their cached
        # trees) inside the full repo set: one parse per file per run.
        subset_by_rel = {s.rel: s for s in sources}
        repo_sources = RepoContext(
            subset_by_rel.get(s.rel, s)
            for s in load_sources(
                [
                    p
                    for p in default_targets(root)
                    if Path(p).relative_to(root).as_posix()
                    not in subset_by_rel
                ],
                root,
            )
        )
        repo_sources.extend(sources)
        by_rel = repo_sources.by_rel
    selected = [RULES[n] for n in (rules or sorted(RULES))]
    findings: List[Finding] = []
    for r in selected:
        if r.scope == "file":
            for src in sources:
                findings.extend(r.func(src) or [])
        else:
            for f in r.func(repo_sources) or []:
                if subset_rels is None or f.path in subset_rels:
                    findings.append(f)
    kept = []
    for f in findings:
        src = by_rel.get(f.path)
        if src is not None and src.suppressed(f.rule, f.line):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    if stats is not None:
        stats["files"] = float(len(repo_sources))
        stats["rules"] = float(len(selected))
        stats["seconds"] = time.perf_counter() - t0
    return kept


def render_human(findings: Sequence[Finding]) -> str:
    if not findings:
        return "graftlint: clean (0 findings)"
    lines = [str(f) for f in findings]
    lines.append(f"graftlint: {len(findings)} finding(s)")
    return "\n".join(lines)


def render_json(findings: Sequence[Finding]) -> str:
    return json.dumps(
        {
            "findings": [f.to_dict() for f in findings],
            "count": len(findings),
        },
        indent=2,
    )


def render_sarif(findings: Sequence[Finding]) -> str:
    """SARIF 2.1.0 for the CI annotation lane
    (``github/codeql-action/upload-sarif`` renders results as inline
    PR comments).  One result per finding; the propagation chain rides
    both the message text and ``relatedLocations`` — codeFlows would
    need per-hop file/line pairs the chain strings only carry
    textually."""
    rules_meta = [
        {
            "id": name,
            "shortDescription": {"text": RULES[name].summary},
            "helpUri": (
                "https://github.com/pytensor-federated-tpu/"
                "pytensor-federated-tpu/blob/main/docs/static-analysis.md"
            ),
        }
        for name in sorted(RULES)
    ]
    results = []
    for f in findings:
        text = f.message
        if f.chain:
            text += "\n\ncall chain:\n" + "\n".join(
                f"  {i}. {hop}" for i, hop in enumerate(f.chain)
            )
        results.append(
            {
                "ruleId": f.rule,
                "level": "error",
                "message": {"text": text},
                "locations": [
                    {
                        "physicalLocation": {
                            "artifactLocation": {
                                "uri": f.path,
                                "uriBaseId": "%SRCROOT%",
                            },
                            "region": {"startLine": max(f.line, 1)},
                        }
                    }
                ],
            }
        )
    doc = {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "graftlint",
                        "informationUri": (
                            "https://github.com/pytensor-federated-tpu/"
                            "pytensor-federated-tpu"
                        ),
                        "rules": rules_meta,
                    }
                },
                "results": results,
            }
        ],
    }
    return json.dumps(doc, indent=2)
