"""pytensor_federated_tpu — a TPU-native federated-likelihood framework.

Brand-new framework with the capabilities of ``pytensor-federated``
(reference: /root/reference), re-designed for TPU: federated shards live
on mesh devices, logp+grad aggregation is a ``lax.psum`` over ICI inside
one XLA program, and samplers run on-device — zero gRPC in the hot loop.
A host-RPC service layer (:mod:`.service`) preserves true cross-trust-
domain federation as an explicit off-hot-path capability.

Public API parity map (reference: pytensor_federated/__init__.py:1-22):
every reference export has an equivalent here; TPU-native additions are
the ``parallel`` (mesh/sharding) and ``samplers`` subpackages.
"""

from .ops import (
    ArraysToArraysOp,
    AsyncArraysToArraysOp,
    AsyncLogpGradOp,
    AsyncLogpOp,
    LogpGradOp,
    LogpOp,
    ParallelLogpGrad,
    blackbox_compute,
    blackbox_logp_grad,
    from_logp_fn,
    fuse,
    parallel_host_call,
)
from .parallel import (
    CHAINS_AXIS,
    SEQ_AXIS,
    SHARDS_AXIS,
    FederatedLogp,
    ShardedData,
    get_load,
    healthy_devices,
    make_mesh,
    pack_shards,
    sharded_compute,
    single_device_mesh,
)
from . import diagnostics
from . import fed
from . import ppl
from . import precision
from .checkpoint import load_pytree, sample_checkpointed, save_pytree
from .diagnostics import instrument_logp, profile_trace
from .precision import pdot, split_dot, wrap_policy
from .signatures import ArraysSpec, ComputeFn, LogpFn, LogpGradFn, spec_of
from .version import __version__
from .wrappers import logp_grad_from_logp, wrap_logp_fn, wrap_logp_grad_fn

__all__ = [
    "ArraysSpec",
    "ArraysToArraysOp",
    "AsyncArraysToArraysOp",
    "AsyncLogpGradOp",
    "AsyncLogpOp",
    "CHAINS_AXIS",
    "ComputeFn",
    "FederatedLogp",
    "LogpFn",
    "LogpGradFn",
    "LogpGradOp",
    "LogpOp",
    "ParallelLogpGrad",
    "SEQ_AXIS",
    "SHARDS_AXIS",
    "ShardedData",
    "__version__",
    "blackbox_compute",
    "blackbox_logp_grad",
    "diagnostics",
    "fed",
    "from_logp_fn",
    "fuse",
    "get_load",
    "healthy_devices",
    "instrument_logp",
    "load_pytree",
    "logp_grad_from_logp",
    "make_mesh",
    "pack_shards",
    "parallel_host_call",
    "pdot",
    "ppl",
    "precision",
    "profile_trace",
    "sample_checkpointed",
    "save_pytree",
    "sharded_compute",
    "single_device_mesh",
    "spec_of",
    "split_dot",
    "wrap_policy",
    "wrap_logp_fn",
    "wrap_logp_grad_fn",
]
