"""True-float32 contraction policy for TPU.

The TPU MXU multiplies in bfloat16; on this package's target hardware a
*plain* float32 ``a @ b`` was measured computing at bf16-level accuracy
(~1.4e-3 relative error on a 512-term dot, tools/diag_tpu.out), and the
``jax.default_matmul_precision("highest")`` context did NOT change the
plain-matmul case — though it verifiably did engage for the
``dot_general``\\ s inside composite linear algebra (the Kalman filter's
15.5 ms -> 220 ms shift).  The reference framework never faces this: its
exchange dtype is float64 on CPU/GPU (reference: common.py de-facto
float64 arrays end-to-end).  A TPU-first framework must answer with an
explicit, *verifiable* mechanism rather than a default.

This module is that answer.  Two mechanisms, one policy knob:

- ``"highest"`` — per-site ``precision=lax.Precision.HIGHEST`` plus the
  global context for composite-op internals.  Relies on the XLA
  backend honoring the request (multi-pass bf16 emulation).
- ``"split"`` — a 6-pass bf16x3 split performed in *user code*: each
  operand is decomposed into three exactly-bf16-representable pieces
  ``x = x1 + x2 + x3`` (8 mantissa bits each, 24 total = f32), and the
  six partial products above the 2^-27 line are accumulated in f32 —
  the same decomposition XLA's "bf16x6" f32 emulation uses, but issued
  by this module so it holds on ANY backend whose matmul is at least
  bf16-multiply/f32-accumulate.  It cannot be silently ignored by a
  compiler flag, which is the measured failure mode of ``"highest"``.
  (A 2-piece Dekker split is NOT enough: its dropped ``lo·lo`` term is
  O(2^-18) ≈ 4e-6 *per product* and the accumulated error measured
  3e-3 max relerr on the 512-dot acceptance test — the 3-piece split
  is what actually clears 1e-5.)
- ``"strict"`` (the default for ``float32_strict`` model options) —
  split for the explicit contraction sites AND the highest-precision
  context for composite internals (Cholesky / triangular-solve blocks).

Error budget of the split: pieces satisfy ``|x2| <= 2^-9 |x|``,
``|x3| <= 2^-18 |x|``, and the residual ``|x - x1-x2-x3| <= 2^-27 |x|``
is below f32 epsilon; the dropped cross terms (``x2·y3`` and smaller)
are ``<= 2^-27`` relative, so the result carries only f32-accumulation
error — the same budget as an honest f32 matmul.  Verified against a
simulated bf16-multiply backend in tests/test_precision.py and on the
live chip by tools/diag_tpu.py section 1b.

Env override: ``PFTPU_F32_POLICY`` (``default``/``highest``/``split``/
``strict``) rebinds what ``policy=None`` resolves to, so a whole run
can be flipped without touching model code.
"""

from __future__ import annotations

import os
from contextlib import nullcontext
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "POLICIES",
    "resolve_policy",
    "split_dot",
    "pdot",
    "matmul_precision_ctx",
    "wrap_policy",
]

POLICIES = ("default", "highest", "split", "strict")


def resolve_policy(policy: Optional[str] = None) -> str:
    """``policy`` if given, else ``$PFTPU_F32_POLICY``, else "default".

    Raises on unknown names — a typo'd policy silently meaning
    "default" would defeat the entire point of an explicit mechanism.
    """
    if policy is None:
        policy = os.environ.get("PFTPU_F32_POLICY", "default")
    if policy not in POLICIES:
        raise ValueError(
            f"unknown f32 policy {policy!r}; choose from {POLICIES}"
        )
    return policy


def _split3(x):
    """Exact 3-piece split ``x ~= x1 + x2 + x3``, each piece
    bf16-representable.

    Each round-trip cast is exact for its piece by construction, and
    each f32 subtraction is exact (the minuend and subtrahend agree in
    the leading mantissa bits), so the residual after three pieces is
    ``<= 2^-27 |x|`` — below f32 epsilon.
    """
    x1 = x.astype(jnp.bfloat16).astype(jnp.float32)
    r1 = x - x1
    x2 = r1.astype(jnp.bfloat16).astype(jnp.float32)
    r2 = r1 - x2
    x3 = r2.astype(jnp.bfloat16).astype(jnp.float32)
    return x1, x2, x3


def split_dot(a, b, base_dot: Optional[Callable] = None):
    """6-pass bf16x3-split contraction, true-f32 accurate on bf16 MXUs.

    ``base_dot`` is the underlying (hardware) contraction —
    ``jnp.matmul`` by default; injectable so tests can substitute a
    simulated bf16-multiply backend and measure the recovery exactly.
    Supports every operand-rank combination ``jnp.matmul`` does.

    The six kept partial products are the terms above the 2^-27 line:
    ``a1·b1`` (1), ``a1·b2 + a2·b1`` (2^-9), ``a1·b3 + a2·b2 + a3·b1``
    (2^-18); everything dropped is ``<= 2^-27`` relative.  Summation
    order is smallest-magnitude first to keep the accumulation error at
    honest-f32 level.  ~6x the matmul FLOPs of a single bf16 pass —
    the price of correctness where ``precision=HIGHEST`` is ignored.
    """
    if base_dot is None:
        base_dot = partial(jnp.matmul, preferred_element_type=jnp.float32)
    a = jnp.asarray(a, jnp.float32)
    b = jnp.asarray(b, jnp.float32)
    a1, a2, a3 = _split3(a)
    b1, b2, b3 = _split3(b)
    return (
        (base_dot(a1, b3) + base_dot(a2, b2) + base_dot(a3, b1))
        + (base_dot(a1, b2) + base_dot(a2, b1))
    ) + base_dot(a1, b1)


def pdot(a, b, policy: Optional[str] = None):
    """Policy-routed matmul/matvec (``jnp.matmul`` semantics).

    The ONE contraction entry point for f32-strict model options: every
    accuracy-critical explicit ``@`` routes here so the mitigation
    cannot drift per call site.
    """
    policy = resolve_policy(policy)
    if policy == "default":
        return jnp.matmul(a, b)
    if policy == "highest":
        return jnp.matmul(
            a,
            b,
            precision=lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
    # split / strict: the user-code bf16 split, immune to the compiler
    # ignoring precision requests (the measured plain-@ failure mode).
    return split_dot(a, b)


def matmul_precision_ctx(policy: Optional[str] = None):
    """Context manager for composite-op internals (Cholesky blocks,
    triangular solves) under ``policy``.

    Must be active while the function is TRACED (wrap the call, not the
    already-jitted executable) — see :func:`wrap_policy`.
    """
    policy = resolve_policy(policy)
    if policy in ("highest", "strict"):
        return jax.default_matmul_precision("highest")
    return nullcontext()


def wrap_policy(fn: Callable, policy: Optional[str] = None) -> Callable:
    """Return ``fn`` traced under :func:`matmul_precision_ctx`.

    For ``"default"``/``"split"`` this is ``fn`` unchanged (split sites
    are handled inside the model via :func:`pdot`; there is nothing to
    do globally).
    """
    policy = resolve_policy(policy)
    if policy not in ("highest", "strict"):
        return fn

    def wrapped(*args, **kwargs):
        with matmul_precision_ctx(policy):
            return fn(*args, **kwargs)

    return wrapped
