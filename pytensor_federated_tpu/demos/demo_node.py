"""Worker-node pool demo/CLI (reference: demo_node.py).

Starts one gRPC node process per port, each owning a private linear-
regression dataset and serving its logp+grad over the wire — the
*true-federation* deployment where data cannot leave the node.  (When
the data CAN live on the pod, use the demos in ``demo_model.py --local``
instead: the shards collapse onto the mesh, zero gRPC.)

Run:  python -m pytensor_federated_tpu.demos.demo_node --ports 50000 50001 50002
"""

from __future__ import annotations

import argparse
import logging
import multiprocessing as mp
from typing import Sequence

import numpy as np

_log = logging.getLogger(__name__)


def make_node_compute(port: int, *, delay: float = 0.0, seed: int = 123):
    """Build one node's private compute function.

    Each node generates its own seeded dataset (reference:
    demo_node.py:58-61) and serves ``[intercept, slope] -> [logp,
    dlogp/dintercept, dlogp/dslope]`` — gradients via JAX autodiff of
    the node-local likelihood (the reference compiles a PyTensor dlogp
    graph instead, reference: demo_node.py:39-42).
    """
    import time

    import jax
    import jax.numpy as jnp

    from ..wrappers import logp_grad_from_logp, wrap_logp_grad_fn

    rng = np.random.default_rng(seed + port)
    x = rng.uniform(-3, 3, size=96).astype(np.float32)
    y = (1.5 + 2.0 * x + 0.5 * rng.normal(size=x.size)).astype(np.float32)
    xj, yj = jnp.asarray(x), jnp.asarray(y)

    def logp(intercept, slope):
        resid = yj - (intercept + slope * xj)
        return jnp.sum(-0.5 * (resid / 0.5) ** 2)

    flat = jax.jit(wrap_logp_grad_fn(logp_grad_from_logp(logp)))

    def compute(*arrays):
        if delay:
            time.sleep(delay)
        return [np.asarray(o) for o in flat(*arrays)]

    return compute


def _run_one(
    bind: str, port: int, delay: float, getload_wire: str = "npwire"
) -> None:
    logging.basicConfig(level=logging.INFO)
    from ..service import run_node

    run_node(
        make_node_compute(port, delay=delay),
        bind,
        port,
        getload_wire=getload_wire,
    )


def run_node_pool(
    bind: str = "127.0.0.1",
    ports: Sequence[int] = tuple(range(50000, 50003)),
    delay: float = 0.0,
    *,
    getload_wire: str = "npwire",
) -> None:
    """One server process per port (reference: demo_node.py:98-108).

    ``getload_wire="npproto"`` serves reference-protobuf GetLoad
    replies, so UNMODIFIED reference clients can balance over this
    pool (Evaluate auto-detects the wire per request either way).

    Side effect: installs a process-wide SIGTERM handler for the
    lifetime of the pool so a signal tears down every child.  A
    previously installed callable handler is chained (called after the
    children are terminated) and the original disposition is restored
    when the pool shuts down normally.
    """
    ctx = mp.get_context("spawn")
    # daemon=True: node servers must die WITH the pool manager.  A
    # killed manager otherwise orphans live servers that keep ports
    # bound and inherited pipes open (observed: a test harness hanging
    # on the orphans' stdout after pytest itself had finished).
    procs = [
        ctx.Process(
            target=_run_one, args=(bind, p, delay, getload_wire),
            daemon=True,
        )
        for p in ports
    ]
    # SIGTERM must tear the whole pool down, not just this manager:
    # the daemon flag is only honored at a GRACEFUL parent exit, so a
    # signal-killed manager would orphan live servers holding ports
    # and inherited pipes.  Converting the signal to SystemExit runs
    # the terminations and multiprocessing's atexit cleanup.
    # Installed BEFORE the first start() so no child can outlive a
    # signal landing mid-startup; exits 128+signum, the conventional
    # killed-by-signal status (a supervisor must not read a SIGTERM'd
    # pool as a clean run).
    import signal

    prev_handler = signal.getsignal(signal.SIGTERM)

    def _terminate_pool(signum, frame):
        for p in procs:
            p.terminate()
        # A host application's own SIGTERM cleanup must not be silently
        # discarded by this API: chain to it before exiting — but its
        # exit path must not either REPLACE the killed-by-signal status
        # (a chained handler calling sys.exit(0) would otherwise make a
        # supervisor read a SIGTERM'd pool as a clean run).
        if callable(prev_handler):
            try:
                prev_handler(signum, frame)
            except SystemExit:
                pass
            except Exception:
                _log.exception("chained SIGTERM handler failed")
        raise SystemExit(128 + signum)

    installed = False
    try:
        signal.signal(signal.SIGTERM, _terminate_pool)
        installed = True
    except ValueError:  # pragma: no cover - non-main-thread caller
        pass
    try:
        for p in procs:
            p.start()
        _log.info(
            "node pool: %d servers on %s:%s", len(procs), bind, list(ports)
        )
        try:
            for p in procs:
                p.join()
        except KeyboardInterrupt:
            for p in procs:
                p.terminate()
    finally:
        # getsignal() returns None for a handler installed from outside
        # Python (C extension / embedding host); signal.signal(...,
        # None) would raise, so in that case leave ours in place.
        if (
            installed
            and prev_handler is not None
            and signal.getsignal(signal.SIGTERM) is _terminate_pool
        ):
            signal.signal(signal.SIGTERM, prev_handler)


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--bind", default="127.0.0.1")
    parser.add_argument(
        "--ports", type=int, nargs="+", default=list(range(50000, 50003))
    )
    parser.add_argument("--delay", type=float, default=0.0)
    parser.add_argument(
        "--getload-wire",
        choices=("npwire", "npproto"),
        default="npwire",
        help="GetLoad reply format: npproto serves unmodified "
        "reference clients (service.proto GetLoadResult)",
    )
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    run_node_pool(
        args.bind, args.ports, args.delay, getload_wire=args.getload_wire
    )


if __name__ == "__main__":
    main()
