"""Worker-node pool demo/CLI (reference: demo_node.py).

Starts one gRPC node process per port, each owning a private linear-
regression dataset and serving its logp+grad over the wire — the
*true-federation* deployment where data cannot leave the node.  (When
the data CAN live on the pod, use the demos in ``demo_model.py --local``
instead: the shards collapse onto the mesh, zero gRPC.)

Run:  python -m pytensor_federated_tpu.demos.demo_node --ports 50000 50001 50002
"""

from __future__ import annotations

import argparse
import logging
import multiprocessing as mp
from typing import Sequence

import numpy as np

_log = logging.getLogger(__name__)


def make_node_compute(port: int, *, delay: float = 0.0, seed: int = 123):
    """Build one node's private compute function.

    Each node generates its own seeded dataset (reference:
    demo_node.py:58-61) and serves ``[intercept, slope] -> [logp,
    dlogp/dintercept, dlogp/dslope]`` — gradients via JAX autodiff of
    the node-local likelihood (the reference compiles a PyTensor dlogp
    graph instead, reference: demo_node.py:39-42).
    """
    import time

    import jax
    import jax.numpy as jnp

    from ..wrappers import logp_grad_from_logp, wrap_logp_grad_fn

    rng = np.random.default_rng(seed + port)
    x = rng.uniform(-3, 3, size=96).astype(np.float32)
    y = (1.5 + 2.0 * x + 0.5 * rng.normal(size=x.size)).astype(np.float32)
    xj, yj = jnp.asarray(x), jnp.asarray(y)

    def logp(intercept, slope):
        resid = yj - (intercept + slope * xj)
        return jnp.sum(-0.5 * (resid / 0.5) ** 2)

    flat = jax.jit(wrap_logp_grad_fn(logp_grad_from_logp(logp)))

    def compute(*arrays):
        if delay:
            time.sleep(delay)
        return [np.asarray(o) for o in flat(*arrays)]

    return compute


def _run_one(bind: str, port: int, delay: float) -> None:
    logging.basicConfig(level=logging.INFO)
    from ..service import run_node

    run_node(make_node_compute(port, delay=delay), bind, port)


def run_node_pool(
    bind: str = "127.0.0.1",
    ports: Sequence[int] = tuple(range(50000, 50003)),
    delay: float = 0.0,
) -> None:
    """One server process per port (reference: demo_node.py:98-108)."""
    ctx = mp.get_context("spawn")
    procs = [
        ctx.Process(target=_run_one, args=(bind, p, delay), daemon=False)
        for p in ports
    ]
    for p in procs:
        p.start()
    _log.info("node pool: %d servers on %s:%s", len(procs), bind, list(ports))
    try:
        for p in procs:
            p.join()
    except KeyboardInterrupt:
        for p in procs:
            p.terminate()


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--bind", default="127.0.0.1")
    parser.add_argument(
        "--ports", type=int, nargs="+", default=list(range(50000, 50003))
    )
    parser.add_argument("--delay", type=float, default=0.0)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    run_node_pool(args.bind, args.ports, args.delay)


if __name__ == "__main__":
    main()
