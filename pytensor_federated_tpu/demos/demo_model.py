"""Driver demo/CLI (reference: demo_model.py).

Two modes:

- ``--local`` (default, the TPU-native path): the federated shards live
  on the device mesh; logp+grad is one fused XLA program; MAP + NUTS run
  on device.  This is demo_node+demo_model collapsed into one process
  (SURVEY §7, BASELINE.json north star).
- ``--remote``: connect to a running node pool (``demo_node.py``) over
  gRPC, embed each remote node as a differentiable blackbox op, fan the
  nodes out concurrently per evaluation, and sample — the reference's
  deployment, kept for true cross-trust-domain federation.

Run:  python -m pytensor_federated_tpu.demos.demo_model --local
      python -m pytensor_federated_tpu.demos.demo_model --remote --ports 50000 50001 50002
"""

from __future__ import annotations

import argparse
import logging

import numpy as np

_log = logging.getLogger(__name__)


def run_local(n_shards: int = 8, draws: int = 300):
    import jax

    from ..models.linear import FederatedLinearRegression, generate_node_data
    from ..parallel import make_mesh

    data, _ = generate_node_data(n_shards, n_obs=96)
    n_dev = len(jax.devices())
    mesh = make_mesh({"shards": n_dev}) if n_shards % n_dev == 0 else None
    model = FederatedLinearRegression(data, mesh=mesh)

    est = model.find_map(num_steps=1000)
    _log.info(
        "MAP: intercept=%.3f slope=%.3f",
        float(est["intercept"]),
        float(est["slope"]),
    )
    res = model.sample(
        key=jax.random.PRNGKey(0),
        num_warmup=draws,
        num_samples=draws,
        num_chains=2,
        jitter=0.1,
    )
    slope = np.asarray(res.samples["slope"])
    _log.info(
        "posterior slope: median=%.3f sd=%.3f (truth 2.0)",
        float(np.median(slope)),
        float(slope.std()),
    )
    return res


def run_remote(host: str, ports, draws: int = 200, parallel: bool = True):
    """Sample against remote gRPC nodes (reference: demo_model.py:15-45).

    Each node is one term of the posterior; with ``parallel`` the nodes
    evaluate concurrently through one fused fan-out op
    (the reference's AsyncLogpGradOp + fuse_asyncs rewrite,
    reference: demo_model.py:19-22).
    """
    import jax
    import jax.numpy as jnp

    from ..ops import ParallelLogpGrad, blackbox_logp_grad
    from ..samplers import sample
    from ..service import LogpGradServiceClient

    cpu = jax.devices("cpu")[0]
    spec = (
        jax.ShapeDtypeStruct((), jnp.float32),
        jax.ShapeDtypeStruct((), jnp.float32),
    )
    clients = [
        LogpGradServiceClient(host, p, use_stream=True) for p in ports
    ]

    if parallel:
        fanout = ParallelLogpGrad(
            [c.evaluate for c in clients], [spec] * len(clients)
        )

        def likelihood(params):
            args = [(params["intercept"], params["slope"])] * len(clients)
            return fanout.total_logp(args)

    else:
        ops = [blackbox_logp_grad(c.evaluate, spec) for c in clients]

        def likelihood(params):
            return sum(
                op(params["intercept"], params["slope"])[0] for op in ops
            )

    def logp(params):
        prior = -0.5 * (params["intercept"] ** 2 + params["slope"] ** 2) / 100.0
        return prior + likelihood(params)

    with jax.default_device(cpu):
        res = sample(
            logp,
            {"intercept": jnp.zeros(()), "slope": jnp.zeros(())},
            key=jax.random.PRNGKey(0),
            num_warmup=draws,
            num_samples=draws,
            num_chains=1,
            kernel="metropolis",  # gradient kernels also work; RWM keeps
            # the demo's RPC volume small
            jitter=0.5,
        )
    slope = np.asarray(res.samples["slope"])
    _log.info(
        "remote posterior slope: median=%.3f (truth 2.0)",
        float(np.median(slope)),
    )
    return res


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--local", action="store_true")
    parser.add_argument("--remote", action="store_true")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument(
        "--ports", type=int, nargs="+", default=list(range(50000, 50003))
    )
    parser.add_argument("--draws", type=int, default=300)
    parser.add_argument("--sequential", action="store_true")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    if args.remote:
        run_remote(
            args.host, args.ports, args.draws, parallel=not args.sequential
        )
    else:
        run_local(draws=args.draws)


if __name__ == "__main__":
    main()
