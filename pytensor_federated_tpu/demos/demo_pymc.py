"""PyMC driver demo — the reference's headline workflow, end-to-end.

The reference's flagship demo is a PyMC model whose likelihood is a
federated op: ``pm.Potential`` over a ``LogpGradOp`` fanning out to
worker processes, then ``pm.find_MAP`` + NUTS (reference:
demo_model.py:15-45).  This demo builds the same hierarchical linear
regression as a ``pm.Model`` whose data likelihood is this framework's
federated evaluation:

- priors live in PyMC (so transforms/Jacobians are PyMC's business,
  identical between the federated and natively-built models);
- the per-shard data log-likelihood is one jitted SPMD evaluation over
  the packed shards (models/linear.py machinery), exposed to PyTensor
  through :func:`bridge.federated_potential` both as a host callable
  (C/py linkers — ``perform``) and as a ``jax_fn`` (PyTensor->JAX
  linker: the whole NUTS step compiles to one XLA program, SURVEY §7
  step 4).

Dtype seam (SURVEY §7 "hard parts"): PyMC computes in float64; the
federated boundary is float32 by TPU-first design.  Values cross the
boundary as float32 and are cast back — parity with a native float64
PyMC model holds to ~1e-5 relative on O(100) log-densities
(tests/test_pymc_e2e.py pins the tolerances).

Run: ``pft-demo-pymc`` or ``python -m pytensor_federated_tpu.demos.demo_pymc``
(requires pymc; the package deliberately does not depend on it —
reference pyproject.toml keeps pymc a test/demo extra too).
"""

from __future__ import annotations

import argparse
from typing import Optional

import numpy as np

from ..models.linear import generate_node_data
from ..parallel.packing import ShardedData
from ..utils import LOG_2PI


def make_federated_data_logp(data: ShardedData):
    """``(jax_fn, host_fn)`` computing the shard-summed data
    log-likelihood ``sum_i logN(y_i | A_i + slope * x_i, sigma)`` and
    its gradients w.r.t. ``(A, slope, sigma)``.

    ``A`` is the per-shard intercept vector (global intercept + shard
    offset), matching the reference demo's per-worker intercept design
    (reference: demo_model.py:26-36).  All shards evaluate in one
    vmapped (shard-batched) program; the host variant jits it and
    crosses the numpy boundary (the C/py-linker ``perform`` path).
    """
    import jax
    import jax.numpy as jnp

    (x, y), mask = data.tree()

    def data_logp(A, slope, sigma):
        def shard_ll(xi, yi, mi, Ai):
            z = (yi - (Ai + slope * xi)) / sigma
            ll = -0.5 * z * z - jnp.log(sigma) - 0.5 * LOG_2PI
            return jnp.sum(ll * mi)

        return jnp.sum(jax.vmap(shard_ll)(x, y, mask, A))

    def jax_value_and_grads(A, slope, sigma):
        val, grads = jax.value_and_grad(data_logp, argnums=(0, 1, 2))(
            A, slope, sigma
        )
        return val, list(grads)

    jitted = jax.jit(jax_value_and_grads)  # lazy: compiles on first call

    def host_fn(A, slope, sigma):
        val, grads = jitted(
            jnp.asarray(A), jnp.asarray(slope), jnp.asarray(sigma)
        )
        return np.asarray(val), [np.asarray(g) for g in grads]

    return jax_value_and_grads, host_fn


def build_model(
    data: ShardedData,
    *,
    use_jax_fn: bool = True,
    prior_scale: float = 10.0,
    offset_scale: float = 0.3,
):
    """A ``pm.Model`` with the federated data likelihood as a Potential.

    Matches the reference driver model shape (reference:
    demo_model.py:26-42): global intercept + per-shard offsets + shared
    slope + noise scale, likelihood behind the federated boundary.
    """
    import pymc as pm

    from ..bridge import federated_potential

    jax_fn, host_fn = make_federated_data_logp(data)
    n_shards = data.tree()[1].shape[0]

    with pm.Model() as model:
        intercept = pm.Normal("intercept", 0.0, prior_scale)
        offsets = pm.Normal("offsets", 0.0, offset_scale, shape=n_shards)
        slope = pm.Normal("slope", 0.0, prior_scale)
        sigma = pm.HalfNormal("sigma", 1.0)
        pm.Potential(
            "federated_loglik",
            federated_potential(
                host_fn,
                intercept + offsets,
                slope,
                sigma,
                jax_fn=jax_fn if use_jax_fn else None,
            ),
        )
    return model


def build_native_model(
    data: ShardedData,
    *,
    prior_scale: float = 10.0,
    offset_scale: float = 0.3,
):
    """The SAME posterior built natively in PyMC (no federated op) —
    the parity oracle, like the reference's natively-built comparison
    model (reference: test_demo_node.py:68-110)."""
    import pymc as pm

    (x, y), mask = data.tree()
    x = np.asarray(x)
    y = np.asarray(y)
    mask = np.asarray(mask).astype(bool)
    n_shards = x.shape[0]

    with pm.Model() as model:
        intercept = pm.Normal("intercept", 0.0, prior_scale)
        offsets = pm.Normal("offsets", 0.0, offset_scale, shape=n_shards)
        slope = pm.Normal("slope", 0.0, prior_scale)
        sigma = pm.HalfNormal("sigma", 1.0)
        for i in range(n_shards):
            pm.Normal(
                f"y_{i}",
                mu=(intercept + offsets[i]) + slope * x[i][mask[i]],
                sigma=sigma,
                observed=y[i][mask[i]],
            )
    return model


def main(argv: Optional[list] = None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--n-shards", type=int, default=8)
    parser.add_argument("--n-obs", type=int, default=64)
    parser.add_argument("--draws", type=int, default=200)
    parser.add_argument("--tune", type=int, default=200)
    parser.add_argument("--chains", type=int, default=2)
    parser.add_argument(
        "--perform-path",
        action="store_true",
        help="use the host-callable perform path instead of jax_fn",
    )
    args = parser.parse_args(argv)

    import pymc as pm

    data, offsets_true = generate_node_data(
        args.n_shards, n_obs=args.n_obs, seed=123
    )
    model = build_model(data, use_jax_fn=not args.perform_path)
    with model:
        map_est = pm.find_MAP(progressbar=False)
        print(
            "MAP: intercept=%.3f slope=%.3f sigma=%.3f"
            % (map_est["intercept"], map_est["slope"], map_est["sigma"])
        )
        idata = pm.sample(
            draws=args.draws,
            tune=args.tune,
            chains=args.chains,
            cores=1,
            progressbar=False,
            random_seed=42,
        )
    post = idata.posterior
    print(
        "posterior: slope median=%.3f intercept median=%.3f"
        % (
            float(post["slope"].median()),
            float(post["intercept"].median()),
        )
    )
    return idata


if __name__ == "__main__":
    main()
