"""graftlint: fixture pairs per rule (bad flagged / good clean /
suppression honored), the registry meta-test, and the full-repo gate.

Fixtures are synthesized mini-repos under ``tmp_path`` so each rule is
exercised against code written to violate exactly one invariant —
independent of the real package, which the final gate test requires to
be CLEAN (the same invocation CI runs)."""

import textwrap

import pytest

from pytensor_federated_tpu import analysis
from pytensor_federated_tpu.analysis import core
from pytensor_federated_tpu.analysis.rules_fed import missing_rules
from pytensor_federated_tpu.analysis.__main__ import main as cli_main


def run_on(tmp_path, files, rules):
    """Materialize ``files`` (rel -> source) under a synthetic repo
    root and run the selected rules over it (default discovery, so
    repo-scope rules see the whole synthetic repo)."""
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return core.run(rules=rules, paths=None, root=tmp_path)


def rules_of(findings):
    return {f.rule for f in findings}


# -- async-blocking ---------------------------------------------------------


class TestAsyncBlocking:
    REL = "pytensor_federated_tpu/service/fixture_mod.py"

    def test_bad_blocking_calls_flagged(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                import time, subprocess
                async def f(sock):
                    time.sleep(1)
                    subprocess.run(["x"])
                    sock.sendall(b"")
                    _fi.filter_bytes("p", b"")
                """
            },
            ["async-blocking"],
        )
        assert len(findings) == 4
        assert rules_of(findings) == {"async-blocking"}
        messages = " ".join(f.message for f in findings)
        assert "time.sleep" in messages
        assert "filter_bytes_async" in messages  # names the async twin

    def test_good_async_and_executor_closure_clean(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                import asyncio, time
                async def g(loop):
                    await asyncio.sleep(0)
                    await _fi.filter_bytes_async("p", b"")
                    def worker():
                        time.sleep(1)  # runs on an executor thread
                    await loop.run_in_executor(None, worker)
                def sync_path():
                    time.sleep(1)  # not async: out of scope
                """
            },
            ["async-blocking"],
        )
        assert findings == []

    def test_out_of_scope_package_is_clean(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                "pytensor_federated_tpu/samplers/fixture_mod.py": """
                import time
                async def f():
                    time.sleep(1)
                """
            },
            ["async-blocking"],
        )
        assert findings == []

    def test_suppression_honored(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                import time
                async def f():
                    time.sleep(1)  # graftlint: disable=async-blocking -- fixture
                """
            },
            ["async-blocking"],
        )
        assert findings == []


# -- loop-affinity ----------------------------------------------------------


class TestLoopAffinity:
    def test_stored_channel_flagged(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                "pytensor_federated_tpu/routing/fixture_mod.py": """
                import grpc
                class C:
                    def __init__(self):
                        self.ch = grpc.aio.insecure_channel("a:1")
                """
            },
            ["loop-affinity"],
        )
        assert len(findings) == 1
        assert "connection cache" in findings[0].message

    def test_scoped_async_with_clean(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                "pytensor_federated_tpu/routing/fixture_mod.py": """
                import grpc
                async def ok():
                    async with grpc.aio.insecure_channel("a:1") as ch:
                        return ch
                """
            },
            ["loop-affinity"],
        )
        assert findings == []

    def test_cache_constructor_site_allowed(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                "pytensor_federated_tpu/service/client.py": """
                import grpc
                class ClientPrivates:
                    @staticmethod
                    async def connect(host, port):
                        return grpc.aio.insecure_channel(f"{host}:{port}")
                """
            },
            ["loop-affinity"],
        )
        assert findings == []

    def test_suppression_honored(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                "pytensor_federated_tpu/routing/fixture_mod.py": """
                import grpc
                def make():
                    # graftlint: disable=loop-affinity -- fixture
                    return grpc.aio.insecure_channel("a:1")
                """
            },
            ["loop-affinity"],
        )
        assert findings == []


# -- wire-registry ----------------------------------------------------------

NPWIRE_CLEAN = """
_FLAG_ERROR = 1
_FLAG_TRACE = 2
_FLAG_SPANS = 4
_FLAG_BATCH = 8
_FLAG_DEADLINE = 16
_FLAG_TENANT = 32
_FLAG_PARTITION = 64
_FLAG_VERSION = 128
_KNOWN_FLAGS = (
    _FLAG_ERROR | _FLAG_TRACE | _FLAG_SPANS | _FLAG_BATCH
    | _FLAG_DEADLINE | _FLAG_TENANT | _FLAG_PARTITION | _FLAG_VERSION
)


def _check_flags(flags):
    pass


def decode_arrays_part(buf):
    _check_flags(0)


def decode_batch_part(buf):
    _check_flags(0)
"""

NPWIRE_REL = "pytensor_federated_tpu/service/npwire.py"
CPP_REL = "native/cpp_node.cpp"

CPP_CLEAN = """
constexpr uint8_t kFlagError = 1;
constexpr uint8_t kFlagTrace = 2;
constexpr uint8_t kFlagSpans = 4;
constexpr uint8_t kFlagBatch = 8;
constexpr uint8_t kFlagDeadline = 16;
constexpr uint8_t kFlagTenant = 32;
constexpr uint8_t kFlagPartition = 64;
constexpr uint8_t kFlagVersion = 128;
constexpr uint8_t kKnownFlags =
    kFlagError | kFlagTrace | kFlagSpans | kFlagBatch | kFlagDeadline |
    kFlagTenant | kFlagPartition | kFlagVersion;
bool decode(const Buf& b) {
  if (flags & ~kKnownFlags) return false;
  return true;
}
std::vector<uint8_t> serve_batch(const Buf& b) {
  if (flags & ~kKnownFlags) return batch_error_reply("unknown flags");
  return {};
}
"""


SHM_REL = "pytensor_federated_tpu/service/shm.py"

SHM_CLEAN = """
import struct

_KIND_ATTACH = 1
_KIND_ATTACH_OK = 2
_KIND_EVAL = 3
_KIND_REPLY = 4
_KIND_EVAL_BATCH = 5
_KIND_REPLY_BATCH = 6
_KIND_ACK = 7
_KIND_GETLOAD = 8
_KIND_LOAD = 9
_KIND_PING = 10
_KIND_PONG = 11
_KIND_ERROR = 12
_KNOWN_KINDS = frozenset(range(1, 13))
_FLAG_ERROR = 1
_FLAG_TRACE = 2
_FLAG_DEADLINE = 4
_FLAG_TENANT = 8
_FLAG_PARTITION = 16
_FLAG_VERSION = 32
_KNOWN_FLAGS = (
    _FLAG_ERROR | _FLAG_TRACE | _FLAG_DEADLINE | _FLAG_TENANT
    | _FLAG_PARTITION | _FLAG_VERSION
)
_DESC_STRUCT = struct.Struct("<QIQQ")


def _check_flags(flags):
    pass


def decode_frame(buf):
    _check_flags(0)
    if 0 not in _KNOWN_KINDS:
        raise ValueError
"""


class TestWireRegistry:
    def test_clean_fixture(self, tmp_path):
        findings = run_on(
            tmp_path,
            {NPWIRE_REL: NPWIRE_CLEAN, CPP_REL: CPP_CLEAN},
            ["wire-registry"],
        )
        assert findings == []

    def test_undeclared_flag_flagged(self, tmp_path):
        findings = run_on(
            tmp_path,
            {NPWIRE_REL: NPWIRE_CLEAN + "_FLAG_ZSTD = 16\n"},
            ["wire-registry"],
        )
        assert any("ZSTD" in f.message for f in findings)

    def test_value_mismatch_flagged(self, tmp_path):
        findings = run_on(
            tmp_path,
            {NPWIRE_REL: NPWIRE_CLEAN.replace("_FLAG_TRACE = 2", "_FLAG_TRACE = 3")},
            ["wire-registry"],
        )
        assert any(
            "TRACE" in f.message and "declared as 2" in f.message
            for f in findings
        )

    def test_missing_known_mask_flagged(self, tmp_path):
        src = NPWIRE_CLEAN.replace(
            "_KNOWN_FLAGS = (\n"
            "    _FLAG_ERROR | _FLAG_TRACE | _FLAG_SPANS | _FLAG_BATCH\n"
            "    | _FLAG_DEADLINE | _FLAG_TENANT | _FLAG_PARTITION"
            " | _FLAG_VERSION\n)",
            "",
        )
        assert "_KNOWN_FLAGS" not in src  # the replace target must track
        findings = run_on(tmp_path, {NPWIRE_REL: src}, ["wire-registry"])
        assert any("known-flags mask" in f.message for f in findings)

    def test_unguarded_decoder_flagged(self, tmp_path):
        src = NPWIRE_CLEAN.replace(
            "def decode_batch_part(buf):\n    _check_flags(0)",
            "def decode_batch_part(buf):\n    return buf",
        )
        findings = run_on(tmp_path, {NPWIRE_REL: src}, ["wire-registry"])
        assert any(
            "decode_batch_part" in f.message and "reject" in f.message
            for f in findings
        )

    def test_cpp_without_mask_flagged(self, tmp_path):
        src = CPP_CLEAN.replace("constexpr uint8_t kKnownFlags =\n", "// ")
        findings = run_on(tmp_path, {CPP_REL: src}, ["wire-registry"])
        assert any(
            f.path == CPP_REL and "known-flags mask" in f.message
            for f in findings
        )

    def test_cpp_guard_checked_per_parser(self, tmp_path):
        """Removing the guard from ONE C++ parser must be flagged even
        while the other parser's guard keeps the mask string present
        in the file (regression: the check was file-global)."""
        src = CPP_CLEAN.replace(
            "bool decode(const Buf& b) {\n"
            "  if (flags & ~kKnownFlags) return false;\n",
            "bool decode(const Buf& b) {\n",
        )
        findings = run_on(tmp_path, {CPP_REL: src}, ["wire-registry"])
        assert any(
            f.path == CPP_REL
            and "decode" in f.message
            and "reject" in f.message
            for f in findings
        ), findings

    def test_undeclared_npproto_field_flagged(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                "pytensor_federated_tpu/service/npproto_codec.py": """
                def encode(x):
                    return _len_field(99, x)
                """
            },
            ["wire-registry"],
        )
        assert any(
            "field number 99" in f.message and "not declared" in f.message
            for f in findings
        )


    # -- shm doorbell / arena descriptor table (ISSUE 9) ------------------

    def test_shm_clean_fixture(self, tmp_path):
        findings = run_on(tmp_path, {SHM_REL: SHM_CLEAN}, ["wire-registry"])
        assert findings == []

    def test_shm_undeclared_kind_flagged(self, tmp_path):
        findings = run_on(
            tmp_path,
            {SHM_REL: SHM_CLEAN + "_KIND_STREAM = 13\n"},
            ["wire-registry"],
        )
        assert any("STREAM" in f.message for f in findings)

    def test_shm_kind_value_drift_flagged(self, tmp_path):
        findings = run_on(
            tmp_path,
            {SHM_REL: SHM_CLEAN.replace("_KIND_EVAL = 3", "_KIND_EVAL = 9")},
            ["wire-registry"],
        )
        assert any(
            "EVAL" in f.message and "declared as 3" in f.message
            for f in findings
        )

    def test_shm_desc_struct_drift_flagged(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                SHM_REL: SHM_CLEAN.replace(
                    'struct.Struct("<QIQQ")', 'struct.Struct("<QQQQ")'
                )
            },
            ["wire-registry"],
        )
        assert any("descriptor struct" in f.message for f in findings)

    def test_shm_unguarded_decoder_flagged(self, tmp_path):
        src = SHM_CLEAN.replace(
            "def decode_frame(buf):\n"
            "    _check_flags(0)\n"
            "    if 0 not in _KNOWN_KINDS:\n"
            "        raise ValueError",
            "def decode_frame(buf):\n    return buf",
        )
        findings = run_on(tmp_path, {SHM_REL: src}, ["wire-registry"])
        assert any("unknown flag bits" in f.message for f in findings)
        assert any("unknown frame kinds" in f.message for f in findings)

# -- wire-loudness ----------------------------------------------------------


class TestWireLoudness:
    REL = "pytensor_federated_tpu/service/fixture_mod.py"

    def test_swallowed_decode_flagged(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                def f(buf):
                    try:
                        return decode_arrays(buf)
                    except Exception:
                        return None
                """
            },
            ["wire-loudness"],
        )
        assert len(findings) == 1
        assert "swallows a decode failure" in findings[0].message

    def test_bare_except_flagged(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                def f(buf):
                    try:
                        return int(buf)
                    except:
                        return None
                """
            },
            ["wire-loudness"],
        )
        assert len(findings) == 1
        assert "bare" in findings[0].message

    def test_reraise_and_inband_use_clean(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                def f(buf):
                    try:
                        return decode_arrays(buf)
                    except WireError as e:
                        return error_reply(str(e))
                def g(buf):
                    try:
                        return decode_arrays(buf)
                    except ValueError:
                        raise
                """
            },
            ["wire-loudness"],
        )
        assert findings == []

    def test_suppression_honored(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                def probe(buf):
                    try:
                        return decode_arrays(buf)
                    except Exception:  # graftlint: disable=wire-loudness -- verdict lane
                        return None
                """
            },
            ["wire-loudness"],
        )
        assert findings == []


# -- fault-shim-coverage ----------------------------------------------------


class TestFaultShimCoverage:
    REL = "pytensor_federated_tpu/service/fixture_mod.py"

    def test_unshimmed_raw_socket_flagged(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                def send(sock, b):
                    sock.sendall(b)
                """
            },
            ["fault-shim-coverage"],
        )
        assert len(findings) == 1
        assert "faultinject" in findings[0].message

    def test_shimmed_and_transitively_covered_clean(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                from ..faultinject import runtime as _fi
                def send(sock, b):
                    if _fi.active_plan is not None:
                        _fi.send_frame_through("p", sock.sendall, b)
                    else:
                        sock.sendall(b)
                def _helper(sock, n):
                    return sock.recv(n)
                def recv_shimmed(sock, n):
                    data = _helper(sock, n)
                    return _fi.filter_bytes("p", data)
                """
            },
            ["fault-shim-coverage"],
        )
        assert findings == []

    def test_codec_without_seam_flagged(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                "pytensor_federated_tpu/service/npwire.py": """
                def encode_arrays(arrays):
                    return b"x"
                """
            },
            ["fault-shim-coverage"],
        )
        assert len(findings) == 1
        assert "encode_arrays" in findings[0].message

    def test_codec_delegation_clean(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                "pytensor_federated_tpu/service/npwire.py": """
                from ..faultinject import runtime as _fi
                def decode_arrays_all(buf):
                    if _fi.active_plan is not None:
                        buf = _fi.filter_bytes("npwire.decode", buf)
                    return buf
                def decode_arrays(buf):
                    return decode_arrays_all(buf)
                """
            },
            ["fault-shim-coverage"],
        )
        assert findings == []

    def test_suppression_honored(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                def send(sock, b):
                    sock.sendall(b)  # graftlint: disable=fault-shim-coverage -- fixture
                """
            },
            ["fault-shim-coverage"],
        )
        assert findings == []


# -- fed-rule-completeness --------------------------------------------------


class TestFedRuleCompleteness:
    def test_incomplete_primitive_reported(self):
        import types

        from jax.extend import core as jex_core

        mod = types.SimpleNamespace(
            incomplete_p=jex_core.Primitive("graftlint_test_incomplete")
        )
        out = missing_rules(mod)
        assert len(out) == 1
        attr, _prim, missing = out[0]
        assert attr == "incomplete_p"
        assert set(missing) == {
            "abstract_eval",
            "jvp",
            "transpose",
            "batching",
        }

    def test_real_fed_primitives_complete(self):
        from pytensor_federated_tpu.fed import primitives as fed_primitives

        assert missing_rules(fed_primitives) == []


# -- observability-drift ----------------------------------------------------

OBS_DOC = """
# Observability

| `pftpu_good_total` | counter | a documented family |

### `telemetry.flightrec` — the black box

| kind | emitted by |
|---|---|
| `good.event` | the fixture |
| `dyn.<kind>` | the fixture's dynamic emitter |
"""

OBS_CODE_CLEAN = """
from .telemetry import metrics, flightrec as _flightrec

_C = metrics.counter("pftpu_good_total", "help")


def f(kind):
    _flightrec.record("good.event", a=1)
    _flightrec.record(f"dyn.{kind}", b=2)
"""


class TestObservabilityDrift:
    REL = "pytensor_federated_tpu/fixture_mod.py"
    DOC = "docs/observability.md"

    def _run(self, tmp_path, code, doc=OBS_DOC):
        (tmp_path / "docs").mkdir(parents=True, exist_ok=True)
        (tmp_path / self.DOC).write_text(textwrap.dedent(doc))
        return run_on(tmp_path, {self.REL: code}, ["observability-drift"])

    def test_clean_fixture(self, tmp_path):
        assert self._run(tmp_path, OBS_CODE_CLEAN) == []

    def test_unregistered_metric_and_event_flagged(self, tmp_path):
        code = OBS_CODE_CLEAN + (
            '\n_B = metrics.gauge("pftpu_rogue_depth", "h")\n'
            '\ndef g():\n    _flightrec.record("rogue.event")\n'
        )
        findings = self._run(tmp_path, code)
        assert any("pftpu_rogue_depth" in f.message for f in findings)
        assert any("rogue.event" in f.message for f in findings)
        assert all(f.path == self.REL for f in findings)

    def test_documented_but_dead_flagged(self, tmp_path):
        doc = OBS_DOC + (
            "| `ghost.event` | nothing emits this |\n"
        ) + "\nprose mention of `pftpu_ghost_total` counts as documented\n"
        findings = self._run(tmp_path, OBS_CODE_CLEAN, doc)
        assert any(
            f.path == self.DOC and "ghost.event" in f.message
            for f in findings
        )
        assert any(
            f.path == self.DOC and "pftpu_ghost_total" in f.message
            for f in findings
        )

    def test_dynamic_prefix_covers_wildcard(self, tmp_path):
        # remove the dynamic emitter -> the documented wildcard is dead
        code = OBS_CODE_CLEAN.replace(
            '    _flightrec.record(f"dyn.{kind}", b=2)\n', ""
        )
        findings = self._run(tmp_path, code)
        assert any("dyn.<" in f.message for f in findings)


# -- suppression mechanics --------------------------------------------------


class TestUnboundedWait:
    REL = "pytensor_federated_tpu/service/fixture_mod.py"

    def test_bare_recv_flagged_with_chain(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                def read_reply(sock):
                    return sock.recv(4)

                def evaluate(sock):
                    return read_reply(sock)
                """
            },
            ["unbounded-wait"],
        )
        assert rules_of(findings) == {"unbounded-wait"}
        assert len(findings) == 1
        assert "sock.recv" in findings[0].message
        # The graftflow chain names the uncovered caller.
        assert any("evaluate" in hop for hop in findings[0].chain)

    def test_settimeout_wait_for_and_armed_watchdog_clean(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                import asyncio

                def bounded_recv(sock, timeout):
                    sock.settimeout(timeout)
                    return sock.recv(4)

                async def bounded_stream(stream, remaining):
                    return await asyncio.wait_for(
                        stream.read(), timeout=remaining
                    )

                def raw_recv(sock):
                    return sock.recv(4)

                def window(sock, _watchdog):
                    with _watchdog.armed("batch_window"):
                        return raw_recv(sock)
                """
            },
            ["unbounded-wait"],
        )
        assert findings == []

    def test_caller_fixpoint_covers_helper(self, tmp_path):
        """A helper whose EVERY caller arms a bound inherits it — the
        read-helper-under-a-bounded-caller shape."""
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                def read_exact(sock, n):
                    return sock.recv(n)

                def read_frame(sock, timeout):
                    sock.settimeout(timeout)
                    return read_exact(sock, 4)
                """
            },
            ["unbounded-wait"],
        )
        assert findings == []

    def test_shared_bounded_reader_helper_counts_as_arming(self, tmp_path):
        """The deadline.bounded_reader with-helper is the canonical
        bounded read on the client lanes — a body reading under it is
        locally bounded even though the settimeout re-arming lives in
        the helper."""
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                def read_frame(sock, rfile, deadline):
                    with deadline.bounded_reader(
                        sock, rfile, 0.5, sock.close
                    ) as read_exact:
                        header = rfile.read(4)
                        return header + read_exact(16)
                """
            },
            ["unbounded-wait"],
        )
        assert findings == []

    def test_plain_file_read_out_of_scope(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                def load(fh):
                    return fh.read()
                """
            },
            ["unbounded-wait"],
        )
        assert findings == []

    def test_suppression_honored(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                def serve_loop(sock):
                    return sock.recv(4)  # graftlint: disable=unbounded-wait -- fixture: server idle state
                """
            },
            ["unbounded-wait"],
        )
        assert findings == []


class TestUnboundedSpin:
    REL = "pytensor_federated_tpu/service/fixture_mod.py"

    def test_bare_poll_loop_flagged_with_chain(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                import time

                def wait_for_slot(ring):
                    while not ring.has_space():
                        time.sleep(0.001)

                def produce(ring, frame):
                    wait_for_slot(ring)
                    ring.put(frame)
                """
            },
            ["unbounded-spin"],
        )
        assert rules_of(findings) == {"unbounded-spin"}
        assert len(findings) == 1
        assert "wait_for_slot" in findings[0].message
        # The graftflow chain names the caller that reaches the loop.
        assert any("produce" in hop for hop in findings[0].chain)

    def test_t_end_marker_and_timeout_raise_clean(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                import time

                def wait_marker(ring, t_end):
                    while not ring.has_space():
                        if time.monotonic() >= t_end:
                            break
                        time.sleep(0.001)

                def wait_raise(ring, limit):
                    while not ring.has_space():
                        if time.monotonic() >= limit:
                            raise TimeoutError("ring full")
                        time.sleep(0.001)
                """
            },
            ["unbounded-spin"],
        )
        assert findings == []

    def test_deadline_checking_callee_bounds_loop(self, tmp_path):
        """The interprocedural half: a poll loop with no marker of its
        own is bounded by calling a helper that raises past ITS
        deadline (transitively, fixpoint over the callee relation)."""
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                import time

                def check_expiry(t_end):
                    if time.monotonic() >= t_end:
                        raise TimeoutError("expired")

                def outer_check(bound):
                    check_expiry(bound)

                def wait_for_slot(ring, bound):
                    while not ring.has_space():
                        outer_check(bound)
                        time.sleep(0.001)
                """
            },
            ["unbounded-spin"],
        )
        assert findings == []

    def test_sleepless_while_and_for_loops_out_of_scope(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                import time

                def drain(ring):
                    while ring.pop() is not None:
                        pass

                def retry(ring):
                    for _ in range(3):
                        time.sleep(0.001)
                """
            },
            ["unbounded-spin"],
        )
        assert findings == []

    def test_suppression_honored(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                import time

                def idle(server):
                    # graftlint: disable=unbounded-spin -- fixture: foreground idle state
                    while True:
                        time.sleep(3600.0)
                """
            },
            ["unbounded-spin"],
        )
        assert findings == []


class TestSuppressions:
    def test_line_above_and_all_keyword(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                "pytensor_federated_tpu/service/fixture_mod.py": """
                import time
                async def f():
                    # graftlint: disable=all -- fixture: directive on the line above
                    time.sleep(1)
                """
            },
            ["async-blocking"],
        )
        assert findings == []

    def test_wrong_rule_name_does_not_suppress(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                "pytensor_federated_tpu/service/fixture_mod.py": """
                import time
                async def f():
                    time.sleep(1)  # graftlint: disable=wire-loudness -- wrong rule
                """
            },
            ["async-blocking"],
        )
        assert len(findings) == 1


# -- driver + registry ------------------------------------------------------


class TestDriver:
    def test_list_rules_exits_zero(self, capsys):
        assert cli_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for name in analysis.RULES:
            assert name in out

    def test_unknown_rule_is_usage_error(self, capsys):
        assert cli_main(["--rule", "no-such-rule"]) == 2

    def test_json_output_shape(self, tmp_path, capsys):
        """Pins the --json schema (documented in
        docs/static-analysis.md): top level {findings, count}, each
        finding exactly {rule, path, line, message, chain} — chain
        always present (empty list for per-function rules), so SARIF
        conversion and CI annotation scripts can rely on it."""
        bad = tmp_path / "pytensor_federated_tpu" / "service" / "m.py"
        bad.parent.mkdir(parents=True)
        bad.write_text(
            "import time\nasync def f():\n    time.sleep(1)\n"
        )
        findings = core.run(
            rules=["async-blocking"], paths=[bad], root=tmp_path
        )
        import json

        payload = json.loads(core.render_json(findings))
        assert set(payload) == {"findings", "count"}
        assert payload["count"] == 1
        record = payload["findings"][0]
        assert set(record) == {"rule", "path", "line", "message", "chain"}
        assert record["rule"] == "async-blocking"
        assert record["line"] == 3
        assert isinstance(record["chain"], list) and record["chain"]

    def test_rule_catalog_shape(self):
        assert set(analysis.RULES) == {
            "async-blocking",
            "loop-affinity",
            "loop-escape",
            "shared-state-lock",
            "resource-leak",
            "wire-registry",
            "wire-loudness",
            "fault-shim-coverage",
            "fed-rule-completeness",
            "fed-placement",
            "observability-drift",
            "unbounded-wait",
            "unbounded-spin",
        }
        for r in analysis.RULES.values():
            assert r.scope in ("file", "repo")
            assert r.summary


class TestDocsCatalogMetaTest:
    def test_docs_rule_catalog_matches_registry(self):
        """docs/static-analysis.md documents exactly the registered
        rules — a new checker lands with its catalog entry, a removed
        one takes its entry along."""
        import re

        doc = (core.repo_root() / "docs" / "static-analysis.md").read_text()
        documented = set(re.findall(r"^###\s+`([a-z-]+)`", doc, re.M))
        assert documented == set(analysis.RULES)


class TestSubsetRuns:
    def test_explicit_path_subset_has_no_repo_rule_false_positives(self):
        """`tools/graftlint.py <one file>` must not report the rest of
        the repo as missing: repo-scope rules still see the full target
        set and only subset-local findings are reported (regression —
        a single-file run used to emit ~70 bogus observability-drift
        findings)."""
        target = (
            core.repo_root()
            / "pytensor_federated_tpu"
            / "routing"
            / "policies.py"
        )
        findings = core.run(paths=[target])
        assert findings == [], "\n" + core.render_human(findings)


class TestSarif:
    def _findings(self, tmp_path):
        bad = tmp_path / "pytensor_federated_tpu" / "service" / "m.py"
        bad.parent.mkdir(parents=True)
        bad.write_text("import time\nasync def f():\n    time.sleep(1)\n")
        return core.run(
            rules=["async-blocking"], paths=[bad], root=tmp_path
        )

    def test_sarif_2_1_0_shape(self, tmp_path):
        import json

        doc = json.loads(core.render_sarif(self._findings(tmp_path)))
        assert doc["version"] == "2.1.0"
        (run,) = doc["runs"]
        driver = run["tool"]["driver"]
        assert driver["name"] == "graftlint"
        assert {r["id"] for r in driver["rules"]} == set(analysis.RULES)
        (result,) = run["results"]
        assert result["ruleId"] == "async-blocking"
        loc = result["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uriBaseId"] == "%SRCROOT%"
        assert loc["artifactLocation"]["uri"].endswith("m.py")
        assert loc["region"]["startLine"] == 3
        assert "call chain" in result["message"]["text"]

    def test_empty_sarif_still_valid(self):
        import json

        doc = json.loads(core.render_sarif([]))
        assert doc["runs"][0]["results"] == []

    def test_cli_sarif_and_json_exclusive(self, capsys):
        assert cli_main(["--sarif", "--json"]) == 2


class TestSinglePassAndTiming:
    def test_stats_reported(self, tmp_path):
        bad = tmp_path / "pytensor_federated_tpu" / "m.py"
        bad.parent.mkdir(parents=True)
        bad.write_text("x = 1\n")
        stats = {}
        core.run(paths=None, root=tmp_path, stats=stats)
        assert stats["files"] >= 1
        assert stats["rules"] == len(analysis.RULES)
        assert stats["seconds"] > 0

    def test_subset_run_reuses_parsed_sources(self, monkeypatch):
        """Single-pass contract: an explicit-path run must not parse
        any file twice (the subset sources are reused inside the full
        repo set)."""
        import ast as ast_mod

        parsed = []
        real_parse = ast_mod.parse

        def counting_parse(source, filename="<unknown>", *a, **kw):
            parsed.append(filename)
            return real_parse(source, filename, *a, **kw)

        monkeypatch.setattr(ast_mod, "parse", counting_parse)
        target = (
            core.repo_root()
            / "pytensor_federated_tpu"
            / "routing"
            / "policies.py"
        )
        core.run(rules=["loop-affinity"], paths=[target])
        dupes = {f for f in parsed if parsed.count(f) > 1}
        assert dupes == set()

    def test_full_repo_run_stays_under_budget(self):
        """The CI graftlint gate must not creep: the whole-repo run
        (every rule, call graph, fed trace) stays well under a minute.
        Local measurements sit around 2-3 s; the budget leaves a wide
        margin for slow CI machines while still catching an accidental
        O(files^2) regression."""
        stats = {}
        core.run(stats=stats)
        assert stats["seconds"] < 30.0, stats


class TestChangedOnly:
    def test_changed_only_runs_clean(self, capsys):
        """--changed-only lints the git-changed subset of the default
        targets (empty diff = clean by vacuity).  At HEAD the repo is
        clean, so either way this exits 0."""
        assert cli_main(["--changed-only"]) == 0
        out = capsys.readouterr()
        assert "graftlint" in out.out or "graftlint" in out.err

    def test_changed_only_rejects_explicit_paths(self, capsys):
        assert cli_main(["--changed-only", "bench.py"]) == 2


# -- the gate: the real repo is clean --------------------------------------


class TestFullRepo:
    def test_full_repo_is_clean(self):
        """The exact check CI runs: every rule over the real package,
        the C++ node, the bench drivers, and tools."""
        findings = core.run()
        assert findings == [], "\n" + core.render_human(findings)
