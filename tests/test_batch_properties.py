"""Property-based batch-frame tests (hypothesis; own file so the
importorskip cannot skip the non-hypothesis batching suite —
tests/test_batching.py — alongside it, mirroring the
test_npwire_properties.py split)."""

import numpy as np
import pytest

pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402
from hypothesis.extra import numpy as hnp  # noqa: E402

from pytensor_federated_tpu.service import npproto_codec
from pytensor_federated_tpu.service.npwire import (
    WireError,
    decode_arrays_all,
    decode_batch,
    encode_arrays,
    encode_batch,
)


COMMON = settings(max_examples=50, deadline=None)

_dtypes = st.one_of(
    hnp.integer_dtypes(endianness="="),
    hnp.floating_dtypes(endianness="=", sizes=(32, 64)),
    hnp.complex_number_dtypes(endianness="="),
    st.just(np.dtype("bool")),
)
_arrays = _dtypes.flatmap(
    lambda dt: hnp.arrays(
        dtype=dt,
        shape=hnp.array_shapes(min_dims=0, max_dims=3, min_side=0,
                               max_side=6),
    )
)
_requests = st.lists(st.lists(_arrays, min_size=0, max_size=3),
                     min_size=0, max_size=5)


@COMMON
@given(reqs=_requests, err=st.none() | st.text(max_size=80))
def test_batch_frames_roundtrip_ragged_mixes(reqs, err):
    """(a) of the interop checklist: any mix of shapes/dtypes across
    items — including zero-size and 0-d arrays — round-trips item-
    and byte-exactly through a batch frame."""
    items = [
        encode_arrays(arrs, uuid=bytes([i]) * 16)
        for i, arrs in enumerate(reqs)
    ]
    frame = encode_batch(items, uuid=b"o" * 16, error=err)
    dec_items, uuid, error, _tid, _spans = decode_batch(frame)
    assert dec_items == items and uuid == b"o" * 16 and error == err
    for arrs, item in zip(reqs, dec_items):
        dec, _u, _e, _t, _s = decode_arrays_all(item)
        assert len(dec) == len(arrs)
        for a, b in zip(arrs, dec):
            assert a.dtype == b.dtype and a.shape == b.shape
            np.testing.assert_array_equal(a, b)


@COMMON
@given(
    reqs=_requests,
    trace=st.none() | st.binary(min_size=16, max_size=16),
    cut=st.floats(min_value=0.0, max_value=0.999),
)
def test_batch_truncation_never_silently_wrong(reqs, trace, cut):
    items = [encode_arrays(arrs) for arrs in reqs]
    frame = encode_batch(items, trace_id=trace)
    prefix = frame[: int(len(frame) * cut)]
    if prefix == frame:  # pragma: no cover - cut<1 guarantees strictness
        return
    with pytest.raises(WireError):
        decode_batch(prefix)


# npwire batch header: magic(4) version(1) flags(1) uuid(16) count(4)
_NPW_BATCH_HDR = 26


def _npwire_item_offsets(frame, n_items):
    """Byte offsets of each item's u32 length field in a batch frame
    encoded with no error/trace blocks."""
    import struct

    offs, off = [], _NPW_BATCH_HDR
    for _ in range(n_items):
        offs.append(off)
        (ln,) = struct.unpack_from("<I", frame, off)
        off += 4 + ln
    return offs


@COMMON
@given(reqs=_requests, cut=st.integers(min_value=1,
                                       max_value=_NPW_BATCH_HDR - 1))
def test_batch_header_truncation_raises_wire_error(reqs, cut):
    """Mid-stream HEADER truncation (flag bit 8): any prefix that ends
    inside the outer batch header must raise WireError — never a
    partial decode."""
    frame = encode_batch([encode_arrays(arrs) for arrs in reqs])
    with pytest.raises(WireError):
        decode_batch(frame[:cut])


@COMMON
@given(
    reqs=st.lists(st.lists(_arrays, min_size=0, max_size=3),
                  min_size=1, max_size=5),
    data=st.data(),
)
def test_batch_item_length_overflow_raises_wire_error(reqs, data):
    """Per-item length overflow: an item length field promising more
    bytes than the frame holds must raise WireError, never partial-
    decode the items before it as a shorter batch."""
    import struct

    items = [
        encode_arrays(arrs, uuid=bytes([i]) * 16)
        for i, arrs in enumerate(reqs)
    ]
    frame = encode_batch(items, uuid=b"o" * 16)
    idx = data.draw(st.integers(0, len(items) - 1), label="item")
    extra = data.draw(st.integers(1, 2**31), label="extra")
    off = _npwire_item_offsets(frame, len(items))[idx]
    (ln,) = struct.unpack_from("<I", frame, off)
    bad = (
        frame[:off]
        + struct.pack("<I", min(ln + extra, 0xFFFFFFFF))
        + frame[off + 4:]
    )
    with pytest.raises(WireError):
        decode_batch(bad)


@COMMON
@given(
    reqs=st.lists(st.lists(_arrays, min_size=0, max_size=2),
                  min_size=1, max_size=4),
    data=st.data(),
)
def test_npproto_batch_item_overflow_and_truncation(reqs, data):
    """The npproto twin (field 17): an inflated item-length varint, and
    a truncation landing INSIDE an item's payload, must both raise
    WireError.  (Truncation at an exact field boundary is proto3-
    indistinguishable from a shorter message — the uuid correlation
    and item-count checks own that case at the transport layer.)"""
    try:
        items = [
            npproto_codec.encode_arrays_msg(arrs, uuid=f"u{i}")
            for i, arrs in enumerate(reqs)
        ]
    except WireError:
        return  # dtype outside the reference wire's str() round trip
    frame = npproto_codec.encode_batch_msg(items, uuid="outer")

    # (a) per-item length overflow: re-emit the last item with a
    # length varint promising more bytes than follow.
    extra = data.draw(st.integers(1, 2**31), label="extra")
    head = npproto_codec.encode_batch_msg(items[:-1], uuid="outer")
    last = items[-1]
    bad = (
        head
        + npproto_codec._tag(17, 2)
        + npproto_codec._encode_varint(len(last) + extra)
        + last
    )
    with pytest.raises(WireError):
        npproto_codec.decode_batch_msg(bad)

    # (b) truncation inside the LAST item's payload (field 17 is the
    # final field emitted, so chopping 1..len-1 of its bytes leaves
    # its length header lying about the remainder).
    if len(last) >= 2:
        cut = data.draw(st.integers(1, len(last) - 1), label="cut")
        with pytest.raises(WireError):
            npproto_codec.decode_batch_msg(frame[:-cut])


@COMMON
@given(arrs=st.lists(_arrays, min_size=0, max_size=3))
def test_unbatched_encode_unchanged_by_feature(arrs):
    """(b): the plain frame under BOTH codecs is byte-identical to the
    PR-2 format — encode with every new knob at its default equals the
    layout-spec manual encoding (npwire) / the no-extension proto
    encoding (npproto)."""
    uuid = b"q" * 16
    frame = encode_arrays(arrs, uuid=uuid)
    assert frame[5] == 0  # no flag bits: no error/trace/spans/batch
    # npproto: error=None emits nothing new
    try:
        msg = npproto_codec.encode_arrays_msg(arrs, uuid="qq")
    except WireError:
        return  # dtype outside the reference wire's str() round trip
    assert msg == npproto_codec.encode_arrays_msg(
        arrs, uuid="qq", trace_id=None, error=None
    )
    assert not npproto_codec.has_batch_items(msg)


