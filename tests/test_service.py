"""Transport tests: multi-process localhost servers, balancing, failover.

Same strategy as the reference (reference: test_service.py:109-283):
"multi-node" is multiprocessing servers on localhost ports, so the
distributed path runs on one machine.
"""

import asyncio
import multiprocessing as mp
import time

import numpy as np
import pytest
from conftest import scrubbed_child_env, wait_nodes_up

from pytensor_federated_tpu.service import (
    ArraysToArraysService,
    ArraysToArraysServiceClient,
    LogpGradServiceClient,
    get_loads_async,
)
from pytensor_federated_tpu.service.client import _privates, thread_pid_id


def _conn_of(client):
    """The (sole) live connection for this client identity; the full
    cache key also carries the driving loop id, so scan by prefix."""
    prefix = thread_pid_id(client)
    matches = [v for k, v in _privates.items() if k[:3] == prefix]
    assert len(matches) == 1, f"expected one connection, got {len(matches)}"
    return matches[0]

BASE_PORT = 29500


def _quad_compute(x):
    """logp+grad of -(x-3)^2 — flat [logp, grad] convention."""
    x = np.asarray(x)
    return [
        np.asarray(-np.sum((x - 3.0) ** 2)),
        (-2.0 * (x - 3.0)).astype(x.dtype),
    ]


def _serve_node(port, delay=0.0):
    import logging

    logging.basicConfig(level=logging.WARNING)

    def compute(*arrays):
        if delay:
            time.sleep(delay)
        return _quad_compute(*arrays)

    from pytensor_federated_tpu.service import run_node

    run_node(compute, "127.0.0.1", port)


def _spawn_nodes(ports):
    from conftest import spawn_node_procs

    return spawn_node_procs(_serve_node, [(p,) for p in ports])


@pytest.fixture(scope="module")
def node_pool():
    """Three server processes (reference: run_node_pool, demo_node.py:98-108)."""
    ports = [BASE_PORT, BASE_PORT + 1, BASE_PORT + 2]
    procs = _spawn_nodes(ports)
    wait_nodes_up(ports, timeout=30)
    yield ports, procs
    for p in procs:
        p.terminate()
    for p in procs:
        p.join(timeout=5)


def test_evaluate_roundtrip(node_pool):
    ports, _ = node_pool
    client = ArraysToArraysServiceClient("127.0.0.1", ports[0])
    x = np.array([1.0, 5.0])
    logp, grad = client.evaluate(x)
    np.testing.assert_allclose(logp, -8.0)
    np.testing.assert_allclose(grad, [4.0, -4.0])
    # Stream reuse: second call over the same bidi stream.
    logp2, _ = client.evaluate(x + 1.0)
    np.testing.assert_allclose(logp2, -(1.0 + 9.0))


def test_unary_mode(node_pool):
    ports, _ = node_pool
    client = ArraysToArraysServiceClient(
        "127.0.0.1", ports[0], use_stream=False
    )
    logp, _ = client.evaluate(np.array([3.0]))
    np.testing.assert_allclose(logp, 0.0)


def test_get_loads_with_offline_port(node_pool):
    """Offline server maps to None (reference: test_service.py:109-141)."""
    ports, _ = node_pool
    loads = asyncio.run(
        get_loads_async(
            [("127.0.0.1", ports[0]), ("127.0.0.1", 59999)], timeout=2.0
        )
    )
    assert loads[0] is not None
    assert {"n_clients", "percent_cpu", "percent_ram"} <= set(loads[0])
    assert loads[1] is None


def test_get_load_rejects_garbled_replies():
    """Garbage from a misbehaving server must map to None, never to a
    load dict.  proto3 decoding is lenient — the empty buffer and any
    unknown-fields-only buffer decode to the all-zero (i.e. maximally
    attractive) load — so the client only attempts the proto path when
    the reply leads with a tag GetLoadResult can actually emit
    (round-4 advisor finding)."""
    import grpc

    from pytensor_federated_tpu.service.client import get_load_async

    garbled = [
        b"\x20\x01",  # unknown field 4 ONLY: lenient decode would yield zeros
        b"\xff\xff\xff",  # outright garbage
        b"not json",
    ]
    # NOT garbage: b"" is the legitimate proto3 encoding of an
    # all-defaults GetLoadResult (writers omit default fields, so this
    # is what a genuinely idle proto-wire server replies), and a
    # schema-evolved reply may lead with an unknown field as long as a
    # known one follows (forward compatibility).
    valid = [b"", b"\x20\x01\x08\x02"]
    payloads = garbled + valid
    replies = iter(payloads)

    async def get_load(request, context):
        return next(replies)

    async def main():
        ident = lambda b: b  # noqa: E731
        server = grpc.aio.server()
        handlers = {
            "GetLoad": grpc.unary_unary_rpc_method_handler(
                get_load,
                request_deserializer=ident,
                response_serializer=ident,
            ),
        }
        server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(
                "ArraysToArraysService", handlers
            ),
        ))
        port = server.add_insecure_port("127.0.0.1:0")
        await server.start()
        try:
            return [
                await get_load_async("127.0.0.1", port, timeout=5.0)
                for _ in payloads
            ]
        finally:
            await server.stop(None)

    loads = asyncio.run(main())
    assert loads[: len(garbled)] == [None] * len(garbled)
    assert loads[len(garbled)] == {
        "n_clients": 0,
        "percent_cpu": 0.0,
        "percent_ram": 0.0,
    }
    assert loads[len(garbled) + 1]["n_clients"] == 2


class TestEvaluateMany:
    """Pipelined batch evaluation: the windowed throughput mode the
    reference's one-in-flight lock-step design cannot express
    (reference: service.py:150-158)."""

    def test_matches_sequential(self, node_pool):
        ports, _ = node_pool
        client = ArraysToArraysServiceClient("127.0.0.1", ports[0])
        reqs = [(np.array([float(i), float(2 * i)]),) for i in range(23)]
        batch = client.evaluate_many(reqs, window=7)
        assert len(batch) == 23
        for args, out in zip(reqs, batch):
            seq = client.evaluate(*args)
            for a, b in zip(seq, out):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b))

    def test_empty_batch(self, node_pool):
        ports, _ = node_pool
        client = ArraysToArraysServiceClient("127.0.0.1", ports[0])
        assert client.evaluate_many([]) == []
        with pytest.raises(ValueError, match="window"):
            client.evaluate_many([(np.zeros(1),)], window=0)

    def test_unary_mode_batch(self, node_pool):
        ports, _ = node_pool
        client = ArraysToArraysServiceClient(
            "127.0.0.1", ports[0], use_stream=False
        )
        reqs = [(np.array([float(i)]),) for i in range(9)]
        batch = client.evaluate_many(reqs, window=4)
        assert len(batch) == 9
        ref = client.evaluate(*reqs[3])
        np.testing.assert_allclose(
            np.asarray(batch[3][0]), np.asarray(ref[0])
        )

    def test_large_messages_degrade_to_lockstep(self, node_pool):
        """Requests bigger than the in-flight byte cap must still
        complete (one at a time) — the cap exists so HTTP/2 flow
        control can never deadlock a write-only burst."""
        ports, _ = node_pool
        client = ArraysToArraysServiceClient("127.0.0.1", ports[0])
        big = np.linspace(0.0, 1.0, 50_000).astype(np.float32)  # 200 KB
        reqs = [(big + i,) for i in range(3)]
        batch = client.evaluate_many(reqs, window=8)
        assert len(batch) == 3
        ref = client.evaluate(*reqs[1])
        np.testing.assert_allclose(
            np.asarray(batch[1][0]), np.asarray(ref[0])
        )

    def test_midbatch_server_error_leaves_stream_usable(self):
        """A compute error inside a pipelined batch raises, but the
        drained stream stays correlated: the NEXT call still works."""
        import socket

        import grpc

        from pytensor_federated_tpu.service.server import (
            ArraysToArraysService,
            serve,
        )

        def compute(x):
            x = np.asarray(x)
            if x.shape == (2,):
                raise ValueError("poison shape")
            return [np.asarray(float(np.sum(x)))]

        async def main():
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                port = s.getsockname()[1]
            service = ArraysToArraysService(compute, inline_compute=True)
            server = await serve(None, "127.0.0.1", port, service=service)
            try:
                client = ArraysToArraysServiceClient("127.0.0.1", port)
                reqs = [
                    (np.ones(1),),
                    (np.ones(2),),  # poison: mid-batch error
                    (np.ones(3),),
                    (np.ones(4),),
                ]
                with pytest.raises(RuntimeError, match="poison shape"):
                    await client.evaluate_many_async(reqs, window=4)
                # stream survived and stayed correlated
                out = await client.evaluate_async(np.ones(5))
                np.testing.assert_allclose(float(np.asarray(out[0])), 5.0)
                # and a clean batch works end-to-end afterwards
                ok = await client.evaluate_many_async(
                    [(np.ones(1),), (np.ones(3),)], window=2
                )
                np.testing.assert_allclose(float(np.asarray(ok[1][0])), 3.0)
            finally:
                await server.stop(None)

        asyncio.run(main())

    def test_adapter_batch_shapes(self, node_pool):
        """The typed adapters apply their shape contracts per batched
        reply (vectorized SMC/ensemble consumers)."""
        ports, _ = node_pool
        client = LogpGradServiceClient("127.0.0.1", ports[0])
        reqs = [(np.array([float(i), 1.0]),) for i in range(7)]
        batch = client.evaluate_many(reqs, window=3)
        assert len(batch) == 7
        for (args,), (logp, grads) in zip(reqs, batch):
            assert np.shape(logp) == ()
            assert len(grads) == 1
            ref_logp, ref_grads = -np.sum((args - 3.0) ** 2), -2.0 * (
                args - 3.0
            )
            np.testing.assert_allclose(float(logp), ref_logp)
            np.testing.assert_allclose(np.asarray(grads[0]), ref_grads)

    def test_batch_failover_to_surviving_server(self, node_pool):
        """Transport failover is all-or-nothing: kill the connected
        server mid-session; the next batch lands on a survivor."""
        ports, procs = node_pool
        client = ArraysToArraysServiceClient(
            hosts_and_ports=[("127.0.0.1", p) for p in ports]
        )
        first = client.evaluate_many([(np.zeros(2),)])
        assert len(first) == 1
        victim_port = _conn_of(client).port
        idx = ports.index(victim_port)
        victim = procs[idx]
        victim.terminate()
        victim.join(timeout=10)
        try:
            batch = client.evaluate_many(
                [(np.array([1.0, 2.0]),) for _ in range(5)], window=3
            )
            assert len(batch) == 5
            assert _conn_of(client).port != victim_port
        finally:
            # Respawn the victim: the pool is module-scoped and later
            # tests connect to this port directly.
            procs[idx] = _spawn_nodes([victim_port])[0]
            wait_nodes_up([victim_port], timeout=30)


def test_inline_compute_roundtrip_and_error():
    """inline_compute=True serves the same contract as the executor
    path — results AND the error-in-reply encoding (a failing compute
    must not tear down the stream)."""
    import grpc

    from pytensor_federated_tpu.service import ArraysToArraysServiceClient
    from pytensor_federated_tpu.service.server import (
        ArraysToArraysService,
        serve,
    )

    calls = {"n": 0}

    def compute(x):
        calls["n"] += 1
        if np.asarray(x).shape == (1,):
            raise ValueError("shard refused")
        return [np.asarray(-np.sum(np.asarray(x) ** 2))]

    async def main():
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        service = ArraysToArraysService(compute, inline_compute=True)
        server = await serve(None, "127.0.0.1", port, service=service)
        try:
            client = ArraysToArraysServiceClient("127.0.0.1", port)
            out = await client.evaluate_async(np.array([1.0, 2.0]))
            np.testing.assert_allclose(float(np.asarray(out[0])), -5.0)
            with pytest.raises(RuntimeError, match="shard refused"):
                await client.evaluate_async(np.zeros(1))
            # stream survived the error: next call still works
            out = await client.evaluate_async(np.array([3.0, 0.0]))
            np.testing.assert_allclose(float(np.asarray(out[0])), -9.0)
        finally:
            await server.stop(None)

    asyncio.run(main())
    assert calls["n"] >= 3  # compute really ran inline in-process


def test_balanced_connect_picks_idle_server(node_pool):
    """With a client camped on one server, a new client must connect to
    another (reference: test_service.py:144-177)."""
    ports, _ = node_pool
    hp = [("127.0.0.1", p) for p in ports]
    busy = ArraysToArraysServiceClient("127.0.0.1", ports[0])
    busy.evaluate(np.zeros(2))  # opens a stream -> n_clients=1 on ports[0]
    fresh = ArraysToArraysServiceClient(hosts_and_ports=hp)
    fresh.evaluate(np.zeros(2))
    connected_port = _conn_of(fresh).port
    assert connected_port in ports[1:], (
        f"balanced connect chose the busy server {connected_port}"
    )


def test_logp_grad_service_client(node_pool):
    ports, _ = node_pool
    client = LogpGradServiceClient("127.0.0.1", ports[0])
    logp, grads = client(np.array([2.0]))
    np.testing.assert_allclose(logp, -1.0)
    np.testing.assert_allclose(grads[0], [2.0])


def test_server_error_propagates(node_pool):
    """compute errors come back in-band, stream survives."""
    ports, _ = node_pool
    client = ArraysToArraysServiceClient("127.0.0.1", ports[0])
    with pytest.raises(RuntimeError, match="server error"):
        client.evaluate(np.zeros(1), np.zeros(1))  # arity mismatch in node
    # The same client still works after the error.
    logp, _ = client.evaluate(np.array([3.0]))
    np.testing.assert_allclose(logp, 0.0)


def test_failover_to_surviving_server(node_pool):
    """Kill the connected server; retry must rebalance to a survivor
    (reference: test_service.py:234-283)."""
    ports, procs = node_pool
    hp = [("127.0.0.1", p) for p in ports]
    client = ArraysToArraysServiceClient(hosts_and_ports=hp, retries=3)
    client.evaluate(np.zeros(2))
    first_port = _conn_of(client).port
    idx = ports.index(first_port)
    victim = procs[idx]
    victim.terminate()
    victim.join(timeout=5)
    try:
        logp, _ = client.evaluate(np.array([3.0]))  # must failover
        np.testing.assert_allclose(logp, 0.0)
        second_port = _conn_of(client).port
        assert second_port != first_port
    finally:
        # Respawn the victim and wait for readiness: the pool is
        # module-scoped, so later tests connect to this port directly.
        procs[idx] = _spawn_nodes([first_port])[0]
        wait_nodes_up([first_port], timeout=30)


def test_client_picklable_across_processes(node_pool):
    """The client must survive pickling into worker processes
    (reference: test_service.py:180-224)."""
    ports, _ = node_pool
    client = ArraysToArraysServiceClient("127.0.0.1", ports[0])
    with scrubbed_child_env():
        ctx = mp.get_context("spawn")
        with ctx.Pool(2) as pool:
            results = pool.map(_eval_in_worker, [client, client])
    for logp in results:
        np.testing.assert_allclose(logp, -8.0)


def _eval_in_worker(client):
    logp, _ = client.evaluate(np.array([1.0, 5.0]))
    return float(logp)


def test_all_servers_dead_raises():
    client = ArraysToArraysServiceClient(
        hosts_and_ports=[("127.0.0.1", 59997), ("127.0.0.1", 59998)]
    )
    with pytest.raises(TimeoutError):
        client.evaluate(np.zeros(1))


def test_arg_validation():
    with pytest.raises(ValueError, match="host"):
        ArraysToArraysServiceClient()
    with pytest.raises(ValueError, match="not both"):
        ArraysToArraysServiceClient(
            "h", 1, hosts_and_ports=[("h", 1)]
        )


def test_many_threads_one_client(node_pool):
    """Concurrent evaluate() from many threads on ONE client object.

    The connection cache keys on (client token, pid, thread id), so
    every thread gets a private lock-step stream — interleaving two
    threads on one stream would desynchronize the uuid correlation.
    The reference guarantees this by the same construction
    (reference: service.py:266-275); this hammers it for real.
    (fork-context pools are deliberately not tested: grpcio's C core
    is not fork-safe with live channels in the parent, unlike the
    reference's pure-Python grpclib.)
    """
    import concurrent.futures

    ports, _ = node_pool
    client = ArraysToArraysServiceClient("127.0.0.1", ports[0])

    def hammer(i):
        x = np.array([1.0, float(i)])
        logp, grad = client.evaluate(x)
        # node computes -(x-3)^2 summed (see _quad_compute)
        want = -(4.0 + (float(i) - 3.0) ** 2)
        np.testing.assert_allclose(grad, -2.0 * (x - 3.0), rtol=1e-6)
        return float(logp), want

    with concurrent.futures.ThreadPoolExecutor(max_workers=8) as ex:
        results = list(ex.map(hammer, range(32)))
    for got, want in results:
        np.testing.assert_allclose(got, want, rtol=1e-6)


def test_channel_never_crosses_loops(node_pool):
    """Mixed sync/async use on one thread: the sync wrapper's cached
    loop and an asyncio.run loop must each get their OWN connection —
    a grpc.aio channel driven from a foreign loop errors or hangs."""
    import asyncio

    from pytensor_federated_tpu.service.client import thread_pid_id

    ports, _ = node_pool
    client = ArraysToArraysServiceClient("127.0.0.1", ports[0])
    logp1, _ = client.evaluate(np.array([1.0, 2.0]))  # sync (cached loop)

    async def go():
        return await client.evaluate_async(np.array([1.0, 2.0]))

    logp2, _ = asyncio.run(go())  # fresh loop, same thread
    np.testing.assert_allclose(logp1, logp2)
    prefix = thread_pid_id(client)
    keys = [k for k in _privates if k[:3] == prefix]
    assert len(keys) == 2, keys  # one connection per loop


def test_closed_loop_entries_are_purged(node_pool):
    """Each asyncio.run leaves a dead loop behind; its cache entry must
    be evicted on the next connect instead of accumulating (and risking
    an id(loop) collision handing a new loop a dead channel)."""
    import asyncio

    from pytensor_federated_tpu.service.client import thread_pid_id

    ports, _ = node_pool
    client = ArraysToArraysServiceClient("127.0.0.1", ports[0])
    for _ in range(3):
        asyncio.run(client.evaluate_async(np.array([1.0])))
    # One more call triggers the purge sweep before connecting.
    logp, _ = client.evaluate(np.array([2.0]))
    np.testing.assert_allclose(float(logp), -1.0)
    prefix = thread_pid_id(client)
    live = [k for k in _privates if k[:3] == prefix]
    assert len(live) == 1, live  # only the (live) sync-wrapper loop entry
