"""docs/api.md stays in sync with the live public surface."""

import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def test_api_docs_fresh():
    # Scrub the tunneled-TPU env vars: the child must never dial the
    # plugin (conftest's in-process force_cpu_backend does not protect
    # subprocesses), and a wedged relay must fail the test, not hang it.
    env = {**os.environ, "PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "gen_api_docs.py"), "--check"],
        capture_output=True,
        text=True,
        cwd=REPO,
        env=env,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
