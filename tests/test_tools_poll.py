"""Unit tests for the capture poller's loop mode (tools/tpu_poll.py).

The loop must keep attempting while captures fail, exit 0 on the first
success, and log each attempt — pinned here with a mocked attempt so
no TPU (or subprocess) is involved.
"""

import importlib
import os
import sys

import pytest

TOOLS = os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")


@pytest.fixture()
def tpu_poll(monkeypatch):
    monkeypatch.syspath_prepend(TOOLS)
    mod = importlib.import_module("tpu_poll")
    return mod


def test_loop_exits_zero_on_first_success(tpu_poll, monkeypatch, tmp_path):
    attempts = []
    sleeps = []

    def fake_attempt(args):
        attempts.append(1)
        return 1 if len(attempts) < 3 else 0

    monkeypatch.setattr(tpu_poll, "_attempt", fake_attempt)
    monkeypatch.setattr(tpu_poll, "LOG", str(tmp_path / "log"))
    import time as time_mod

    monkeypatch.setattr(time_mod, "sleep", lambda s: sleeps.append(s))
    rc = tpu_poll.main(["--loop-every-s", "123"])
    assert rc == 0
    assert len(attempts) == 3
    assert sleeps == [123.0, 123.0]


def test_single_attempt_mode_returns_attempt_code(tpu_poll, monkeypatch,
                                                  tmp_path):
    monkeypatch.setattr(tpu_poll, "_attempt", lambda args: 4)
    monkeypatch.setattr(tpu_poll, "LOG", str(tmp_path / "log"))
    assert tpu_poll.main([]) == 4


def test_dry_run_dead_probe_logs_incident_bundle(tpu_poll, monkeypatch,
                                                 tmp_path):
    """ISSUE 2 satellite: a liveness-probe timeout must leave FORENSICS
    — the incident bundle's path lands in capture_attempts.log."""
    import pytensor_federated_tpu.utils as utils

    monkeypatch.setattr(utils, "probe_backend",
                        lambda **kw: (False, False))
    monkeypatch.setattr(tpu_poll, "REPO", str(tmp_path))
    log = tmp_path / "capture_attempts.log"
    monkeypatch.setattr(tpu_poll, "LOG", str(log))
    rc = tpu_poll.main(["--dry-run"])
    assert rc == 1
    text = log.read_text()
    assert "probe: DEAD" in text and "incident bundle -> " in text
    rel = text.split("incident bundle -> ")[1].split()[0]
    bundle = tmp_path / rel
    assert bundle.exists()
    import json

    data = json.loads(bundle.read_text())
    assert data["reason"] == "tpu-liveness-probe-timeout"
    assert data["attrs"]["probe_timeout_s"] == 150.0
    assert "threads" in data and "flightrec" in data


def test_dry_run_live_probe_logs_no_incident(tpu_poll, monkeypatch,
                                             tmp_path):
    import pytensor_federated_tpu.utils as utils

    monkeypatch.setattr(utils, "probe_backend", lambda **kw: (True, False))
    monkeypatch.setattr(tpu_poll, "REPO", str(tmp_path))
    log = tmp_path / "capture_attempts.log"
    monkeypatch.setattr(tpu_poll, "LOG", str(log))
    assert tpu_poll.main(["--dry-run"]) == 0
    assert "incident" not in log.read_text()


def test_attempt_probe_timeout_exit_logs_incident(tpu_poll, monkeypatch,
                                                  tmp_path):
    """Capture exit code 1 (= DEAD, probe timed out) in the real
    attempt path also writes the bundle path into the log."""
    import subprocess as subprocess_mod
    import types

    monkeypatch.setattr(tpu_poll, "REPO", str(tmp_path))
    log = tmp_path / "capture_attempts.log"
    monkeypatch.setattr(tpu_poll, "LOG", str(log))
    # tools/ is already on sys.path via the fixture; fake the capture
    # subprocess so no TPU (or bench) is involved.
    monkeypatch.setattr(
        subprocess_mod,
        "run",
        lambda *a, **kw: types.SimpleNamespace(returncode=1),
    )
    args = tpu_poll.main([])  # single-attempt mode returns attempt code
    assert args == 1
    text = log.read_text()
    assert "exit=1" in text and "incident bundle -> " in text
    rel = text.split("incident bundle -> ")[1].split()[0]
    assert (tmp_path / rel).exists()
