"""Unit tests for the capture poller's loop mode (tools/tpu_poll.py).

The loop must keep attempting while captures fail, exit 0 on the first
success, and log each attempt — pinned here with a mocked attempt so
no TPU (or subprocess) is involved.
"""

import importlib
import os
import sys

import pytest

TOOLS = os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")


@pytest.fixture()
def tpu_poll(monkeypatch):
    monkeypatch.syspath_prepend(TOOLS)
    mod = importlib.import_module("tpu_poll")
    return mod


def test_loop_exits_zero_on_first_success(tpu_poll, monkeypatch, tmp_path):
    attempts = []
    sleeps = []

    def fake_attempt(args):
        attempts.append(1)
        return 1 if len(attempts) < 3 else 0

    monkeypatch.setattr(tpu_poll, "_attempt", fake_attempt)
    monkeypatch.setattr(tpu_poll, "LOG", str(tmp_path / "log"))
    import time as time_mod

    monkeypatch.setattr(time_mod, "sleep", lambda s: sleeps.append(s))
    rc = tpu_poll.main(["--loop-every-s", "123"])
    assert rc == 0
    assert len(attempts) == 3
    assert sleeps == [123.0, 123.0]


def test_single_attempt_mode_returns_attempt_code(tpu_poll, monkeypatch,
                                                  tmp_path):
    monkeypatch.setattr(tpu_poll, "_attempt", lambda args: 4)
    monkeypatch.setattr(tpu_poll, "LOG", str(tmp_path / "log"))
    assert tpu_poll.main([]) == 4
