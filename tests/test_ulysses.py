"""Ulysses all-to-all sequence parallelism (parallel/ulysses.py).

Golden model is single-device dense multi-head attention (same pattern
as test_ring.py; reference: test_demo_node.py:29-65).  Cross-checked
against ring_attention, which must produce identical numbers head by
head.  Runs on the virtual 8-device CPU mesh from conftest.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu.parallel import make_mesh
from pytensor_federated_tpu.parallel.ring import ring_attention
from pytensor_federated_tpu.parallel.ulysses import ulysses_attention


@pytest.fixture(scope="module")
def seq_mesh(devices8):
    return make_mesh({"seq": 4}, devices=devices8[:4])


def dense_mha(q, k, v, *, causal=False):
    """(T, H, d) dense multi-head attention, head at a time."""

    def one(qh, kh, vh):
        s = (qh @ kh.T) / jnp.sqrt(jnp.asarray(qh.shape[-1], qh.dtype))
        if causal:
            t = qh.shape[0]
            s = jnp.where(jnp.tril(jnp.ones((t, t), dtype=bool)), s, -jnp.inf)
        return jax.nn.softmax(s, axis=-1) @ vh

    return jax.vmap(one, in_axes=1, out_axes=1)(q, k, v)


def _qkv(seed, t=32, h=8, d=16):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(size=(t, h, d)).astype(np.float32))
        for _ in range(3)
    )


class TestUlyssesAttention:
    def test_matches_dense(self, seq_mesh):
        q, k, v = _qkv(0)
        out = ulysses_attention(q, k, v, mesh=seq_mesh, axis="seq")
        ref = dense_mha(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_causal_matches_dense(self, seq_mesh):
        q, k, v = _qkv(1)
        out = ulysses_attention(q, k, v, mesh=seq_mesh, axis="seq", causal=True)
        ref = dense_mha(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_matches_ring_attention(self, seq_mesh):
        """The two SP schemes are different routings of the same math."""
        q, k, v = _qkv(2, t=16, h=4, d=8)
        out_u = ulysses_attention(q, k, v, mesh=seq_mesh, axis="seq", causal=True)
        out_r = jax.vmap(
            lambda qh, kh, vh: ring_attention(
                qh, kh, vh, mesh=seq_mesh, axis="seq", causal=True
            ),
            in_axes=1,
            out_axes=1,
        )(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out_u), np.asarray(out_r), atol=1e-5
        )

    def test_differentiable(self, seq_mesh):
        q, k, v = _qkv(3, t=16, h=4, d=8)

        def loss_u(q):
            return jnp.sum(
                ulysses_attention(q, k, v, mesh=seq_mesh, axis="seq") ** 2
            )

        def loss_d(q):
            return jnp.sum(dense_mha(q, k, v) ** 2)

        g_u = jax.grad(loss_u)(q)
        g_d = jax.grad(loss_d)(q)
        np.testing.assert_allclose(
            np.asarray(g_u), np.asarray(g_d), atol=1e-4
        )

    def test_seq_not_divisible_raises(self, seq_mesh):
        q, k, v = _qkv(4, t=30, h=4, d=8)
        with pytest.raises(ValueError, match="not divisible"):
            ulysses_attention(q, k, v, mesh=seq_mesh, axis="seq")

    def test_heads_not_divisible_raises(self, seq_mesh):
        q, k, v = _qkv(5, t=16, h=6, d=8)
        with pytest.raises(ValueError, match="head count"):
            ulysses_attention(q, k, v, mesh=seq_mesh, axis="seq")

    def test_bad_axis_raises(self, seq_mesh):
        q, k, v = _qkv(6, t=16, h=4, d=8)
        with pytest.raises(ValueError, match="no axis"):
            ulysses_attention(q, k, v, mesh=seq_mesh, axis="nope")

    def test_shape_mismatch_raises(self, seq_mesh):
        q, k, v = _qkv(7, t=16, h=4, d=8)
        with pytest.raises(ValueError, match="shapes differ"):
            ulysses_attention(q, k[:, :2], v, mesh=seq_mesh, axis="seq")

    @pytest.mark.parametrize(
        "t,h,d,causal",
        [
            (4, 4, 1, False),  # one position per device, scalar head dim
            (4, 4, 1, True),
            (8, 8, 2, True),  # head count > mesh, minimal blocks
            (64, 4, 4, True),  # long sequence, few heads
            (16, 12, 3, False),  # non-power-of-two head count (12 % 4 == 0)
        ],
    )
    def test_dimension_corners(self, seq_mesh, t, h, d, causal):
        """Both SP schemes == dense MHA across shape corners (the
        degenerate block sizes are where index arithmetic breaks)."""
        q, k, v = _qkv(hash((t, h, d, causal)) % 2**31, t=t, h=h, d=d)
        ref = dense_mha(q, k, v, causal=causal)
        out_u = ulysses_attention(
            q, k, v, mesh=seq_mesh, axis="seq", causal=causal
        )
        np.testing.assert_allclose(
            np.asarray(out_u), np.asarray(ref), atol=2e-5
        )
        out_r = jax.vmap(
            lambda qh, kh, vh: ring_attention(
                qh, kh, vh, mesh=seq_mesh, axis="seq", causal=causal
            ),
            in_axes=1,
            out_axes=1,
        )(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out_r), np.asarray(ref), atol=2e-5
        )
