"""2-D (chains x shards) mesh sampling test — the multi-chip scale path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu.parallel import make_mesh
from pytensor_federated_tpu.parallel.multichain import multichain_sample


def per_shard_logp(params, shard):
    x = shard
    return jnp.sum(-0.5 * (x - params["mu"]) ** 2)


@pytest.mark.parametrize("kernel", ["nuts", "hmc"])
def test_multichain_2d_mesh(devices8, kernel):
    mesh = make_mesh({"chains": 2, "shards": 4}, devices=devices8)
    rng = np.random.default_rng(0)
    # 4 shards of 32 obs from N(2, 1): posterior of mu ~ N(~2, 1/128)
    data = jnp.asarray(rng.normal(2.0, 1.0, size=(4, 32)).astype(np.float32))

    draws, accept, unravel = multichain_sample(
        per_shard_logp,
        data,
        {"mu": jnp.zeros(())},
        mesh=mesh,
        key=jax.random.PRNGKey(0),
        num_samples=300,
        step_size=0.08,
        kernel=kernel,
        jitter=0.2,
    )
    assert draws.shape == (2, 300, 1)
    mu = np.asarray(draws)[:, 100:, 0]
    post_mean = float(np.asarray(data).mean())
    assert abs(mu.mean() - post_mean) < 0.1
    # chains must differ (independent RNG per chain)
    assert abs(mu[0].mean() - mu[1].mean()) < 0.2
    assert not np.allclose(mu[0], mu[1])
    assert np.asarray(accept).mean() > 0.5


def test_multichain_warmup_adapts(devices8):
    """num_warmup > 0 runs the Stan-style warmup INSIDE the shard_map:
    the adapted run must recover the posterior from a deliberately bad
    initial step size (which the fixed-step path cannot)."""
    mesh = make_mesh({"chains": 2, "shards": 4}, devices=devices8)
    rng = np.random.default_rng(1)
    data = jnp.asarray(
        rng.normal(2.0, 1.0, size=(4, 32)).astype(np.float32)
    )

    draws, accept, _ = multichain_sample(
        per_shard_logp,
        data,
        {"mu": jnp.zeros(())},
        mesh=mesh,
        key=jax.random.PRNGKey(3),
        num_samples=300,
        num_warmup=300,
        step_size=50.0,  # ignored: warmup finds its own
        kernel="nuts",
        jitter=0.2,
    )
    assert draws.shape == (2, 300, 1)
    mu = np.asarray(draws)[..., 0]
    post_mean = float(np.asarray(data).mean())
    # posterior sd is 1/sqrt(128) ~ 0.088
    assert abs(mu.mean() - post_mean) < 0.1
    # adapted acceptance should be in a healthy band, not ~0 or ~1
    acc = float(np.asarray(accept).mean())
    assert 0.5 < acc <= 1.0


def test_multichain_dense_mass_on_mesh(devices8):
    """Dense-mass warmup inside the shard_map: a correlated posterior
    (two shards observing the SUM of params induce correlation) is
    recovered on the 2-D mesh."""
    mesh = make_mesh({"chains": 2, "shards": 4}, devices=devices8)
    rng = np.random.default_rng(5)
    data = jnp.asarray(
        rng.normal(1.0, 1.0, size=(4, 24)).astype(np.float32)
    )

    def corr_shard_logp(params, shard):
        # observations of mu1 + mu2: the posterior correlates them
        return jnp.sum(-0.5 * (shard - (params["a"] + params["b"])) ** 2)

    def prior(params):
        return -0.5 * (params["a"] ** 2 + params["b"] ** 2)

    draws, accept, _ = multichain_sample(
        corr_shard_logp,
        data,
        {"a": jnp.zeros(()), "b": jnp.zeros(())},
        mesh=mesh,
        key=jax.random.PRNGKey(9),
        num_samples=300,
        num_warmup=300,
        dense_mass=True,
        kernel="nuts",
        prior_logp=prior,
        jitter=0.2,
    )
    d = np.asarray(draws).reshape(-1, 2)
    # a + b is tightly determined; a - b only by the prior
    s_sum = (d[:, 0] + d[:, 1]).std()
    s_diff = (d[:, 0] - d[:, 1]).std()
    assert s_sum < 0.35 * s_diff  # strong negative correlation captured
    assert np.all(np.isfinite(d))
