"""graftflow: engine unit tests (call graph, entrypoints, contexts)
and fixture pairs for the interprocedural rules.

The seeded-defect fixtures here are the PR's acceptance criteria: a
3-hop transitive blocking call from an async handler (which the old
per-function scan provably misses), a cross-loop channel escape, an
unlocked cross-thread mutation, and a driver-varying pool-placed
fed_map — each flagged WITH its propagation chain."""

import textwrap

import pytest

from pytensor_federated_tpu.analysis import core
from pytensor_federated_tpu.analysis.graph import build_graph
from pytensor_federated_tpu.analysis import dataflow
from pytensor_federated_tpu.analysis.rules_async import (
    direct_blocking_sites,
)
from pytensor_federated_tpu.analysis.rules_fedflow import (
    placement_findings,
)


def make_repo(tmp_path, files):
    """Materialize ``files`` (rel -> source) under a synthetic root."""
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return tmp_path


def run_on(tmp_path, files, rules):
    make_repo(tmp_path, files)
    return core.run(rules=rules, paths=None, root=tmp_path)


def ctx_of(tmp_path, files):
    make_repo(tmp_path, files)
    return core.RepoContext(
        core.load_sources(core.default_targets(tmp_path), tmp_path)
    )


# -- engine: call graph -----------------------------------------------------


GRAPH_MOD = """
import threading
from .helpers import imported_fn
from . import helpers

class Pool:
    def __init__(self):
        self.x = 1

    def start(self):
        threading.Thread(
            target=self._loop, name="pool-probe", daemon=True
        ).start()

    def _loop(self):
        self.step()

    def step(self):
        local_helper()
        imported_fn()
        helpers.other_fn()
        unique_method_target()

def local_helper():
    def inner():
        pass
    inner()

def unique_method_target():
    pass

def spawn(loop, executor, obj):
    loop.run_in_executor(None, local_helper)
    executor.submit(unique_method_target)
    obj.unique_method_target()

async def task_root():
    import asyncio
    asyncio.create_task(coro_child())

async def coro_child():
    pass

def build():
    return Pool()
"""

GRAPH_HELPERS = """
def imported_fn():
    pass

def other_fn():
    pass
"""


class TestCallGraph:
    REL = "pytensor_federated_tpu/routing/mod.py"
    HELPERS = "pytensor_federated_tpu/routing/helpers.py"

    @pytest.fixture()
    def graph(self, tmp_path):
        ctx = ctx_of(
            tmp_path, {self.REL: GRAPH_MOD, self.HELPERS: GRAPH_HELPERS}
        )
        return ctx.graph

    def edge_kinds(self, graph, caller_q):
        return {
            (graph.functions[e.callee].name, e.kind)
            for e in graph.callees_of(caller_q)
        }

    def test_edge_resolution_kinds(self, graph):
        step = f"{self.REL}::Pool.step"
        kinds = self.edge_kinds(graph, step)
        assert ("local_helper", "module") in kinds
        assert ("imported_fn", "import") in kinds  # from .helpers import
        assert ("other_fn", "import") in kinds  # helpers.other_fn(...)
        assert ("unique_method_target", "module") in kinds

    def test_self_method_and_nested_and_unique(self, graph):
        loop_q = f"{self.REL}::Pool._loop"
        assert ("step", "self") in self.edge_kinds(graph, loop_q)
        lh = f"{self.REL}::local_helper"
        assert ("inner", "local") in self.edge_kinds(graph, lh)
        spawn = f"{self.REL}::spawn"
        # obj.unique_method_target(): exactly one in-package match.
        assert ("unique_method_target", "unique") in self.edge_kinds(
            graph, spawn
        )

    def test_constructor_edge(self, graph):
        build = f"{self.REL}::build"
        assert ("__init__", "class") in self.edge_kinds(graph, build)

    def test_thread_entrypoint_discovery(self, graph):
        threads = [e for e in graph.entrypoints if e.kind == "thread"]
        assert len(threads) == 1
        e = threads[0]
        assert e.target == f"{self.REL}::Pool._loop"
        assert e.label == "pool-probe"
        assert e.spawner == f"{self.REL}::Pool.start"

    def test_executor_and_task_entrypoints(self, graph):
        kinds = {
            (e.kind, graph.functions[e.target].name)
            for e in graph.entrypoints
        }
        assert ("executor", "local_helper") in kinds  # run_in_executor
        assert ("executor", "unique_method_target") in kinds  # submit
        assert ("task", "coro_child") in kinds  # create_task

    def test_reachability_chain(self, graph):
        chains = graph.reachable_from([f"{self.REL}::Pool._loop"])
        inner = f"{self.REL}::local_helper.inner"
        assert inner in chains  # _loop -> step -> local_helper -> inner
        assert [e.callee for e in chains[inner]] == [
            f"{self.REL}::Pool.step",
            f"{self.REL}::local_helper",
            inner,
        ]

    def test_concurrency_contexts(self, graph):
        contexts = dataflow.concurrency_contexts(graph)
        step = contexts[f"{self.REL}::Pool.step"]
        assert "thread:_loop" in step  # via the Thread entrypoint
        assert contexts[f"{self.REL}::local_helper"] >= {
            "thread:_loop",
            "executor",
        }
        assert "loop" in contexts[f"{self.REL}::coro_child"]


# -- async-blocking: transitive -------------------------------------------


THREE_HOP = """
import time

async def handler():
    a()

def a():
    b()

def b():
    c()

def c():
    time.sleep(1)
"""


class TestTransitiveAsyncBlocking:
    REL = "pytensor_federated_tpu/service/mod.py"

    def test_three_hop_chain_flagged_and_direct_scan_misses(
        self, tmp_path
    ):
        """The acceptance fixture: the PR-7 per-function rule provably
        misses a blocking call three frames down; graftflow flags it
        with the full propagation chain."""
        root = make_repo(tmp_path, {self.REL: THREE_HOP})
        findings = core.run(
            rules=["async-blocking"], paths=None, root=root
        )
        assert len(findings) == 1
        f = findings[0]
        assert f.path == self.REL
        assert "time.sleep" in f.message
        assert "reachable from `async def handler`" in f.message
        # chain: handler -> a -> b -> c -> the blocking line
        assert len(f.chain) == 5
        assert "handler" in f.chain[0]
        assert f.chain[-1].endswith(f"{self.REL}:{f.line}")
        # ... and the legacy direct-pattern scan sees nothing.
        src = core.SourceFile(root / self.REL, root)
        assert direct_blocking_sites(src) == []

    def test_executor_seam_breaks_the_chain(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                import time

                async def handler(loop):
                    await loop.run_in_executor(None, worker)

                def worker():
                    time.sleep(1)  # runs on a worker thread: fine
                """
            },
            ["async-blocking"],
        )
        assert findings == []

    def test_lambda_is_a_value_not_inline_code(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                import time

                async def handler(shim):
                    await shim(lambda: slow())

                def slow():
                    time.sleep(1)
                """
            },
            ["async-blocking"],
        )
        assert findings == []

    def test_bare_lock_acquire_flagged_with_lock_exempt(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                async def handler(obj):
                    obj._lock.acquire()
                    with obj._lock:
                        pass
                    obj._lock.acquire(timeout=1.0)
                """
            },
            ["async-blocking"],
        )
        assert len(findings) == 1
        assert "untimed blocking acquire" in findings[0].message

    def test_suppression_honored_at_blocking_site(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                import time

                async def handler():
                    helper()

                def helper():
                    time.sleep(1)  # graftlint: disable=async-blocking -- fixture
                """
            },
            ["async-blocking"],
        )
        assert findings == []


# -- loop-escape ------------------------------------------------------------


class TestLoopEscape:
    REL = "pytensor_federated_tpu/routing/mod.py"

    def test_direct_attribute_escape_flagged(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                import grpc

                class C:
                    async def connect(self):
                        self.ch = grpc.aio.insecure_channel("a:1")
                """
            },
            ["loop-escape"],
        )
        assert len(findings) == 1
        assert "self.ch" in findings[0].message

    def test_interprocedural_source_escape_flagged_with_chain(
        self, tmp_path
    ):
        """The acceptance fixture: the channel is created two calls
        away; the escape carries the producer in its chain."""
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                import grpc

                def _make():
                    return grpc.aio.insecure_channel("a:1")

                def _indirect():
                    return _make()

                class C:
                    async def connect(self):
                        self.ch = _indirect()
                """
            },
            ["loop-escape"],
        )
        assert len(findings) == 1
        f = findings[0]
        assert "self.ch" in f.message
        assert any("_indirect" in hop for hop in f.chain)

    def test_multicallable_and_global_and_container(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                import grpc

                _CACHE = {}

                async def stash(registry):
                    ch = grpc.aio.insecure_channel("a:1")
                    stub = ch.unary_unary("/svc/Do")
                    registry["k"] = stub
                    global _CH
                    _CH = ch

                async def enqueue(q):
                    ch = grpc.aio.insecure_channel("a:1")
                    q.put(ch)
                """
            },
            ["loop-escape"],
        )
        # subscript store of the stub, global store of the channel
        assert len(findings) >= 2
        msgs = " ".join(f.message for f in findings)
        assert "registry" in msgs

    def test_scoped_and_local_use_clean(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                import grpc

                async def ok():
                    async with grpc.aio.insecure_channel("a:1") as ch:
                        method = ch.unary_unary("/svc/Do")
                        return await method(b"")
                """
            },
            ["loop-escape"],
        )
        assert findings == []

    def test_cache_file_exempt(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                "pytensor_federated_tpu/service/client.py": """
                import grpc

                class ClientPrivates:
                    async def connect(self):
                        self.channel = grpc.aio.insecure_channel("a:1")
                """
            },
            ["loop-escape"],
        )
        assert findings == []

    def test_suppression_honored(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                import grpc

                class C:
                    async def connect(self):
                        self.ch = grpc.aio.insecure_channel("a:1")  # graftlint: disable=loop-escape -- fixture
                """
            },
            ["loop-escape"],
        )
        assert findings == []


# -- shared-state-lock ------------------------------------------------------


RACE_BAD = """
import threading

class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def start(self):
        threading.Thread(
            target=self._probe_loop, name="probe", daemon=True
        ).start()

    def _probe_loop(self):
        self.count += 1

    async def handle(self):
        self.count += 1
"""


class TestSharedStateLock:
    REL = "pytensor_federated_tpu/telemetry/mod.py"

    def test_unlocked_cross_context_mutation_flagged_with_witness(
        self, tmp_path
    ):
        """The acceptance fixture: one attribute written from the
        probe daemon thread AND the event loop, no lock anywhere —
        both writes flagged, each carrying a witness chain per
        context."""
        findings = run_on(tmp_path, {self.REL: RACE_BAD}, ["shared-state-lock"])
        assert len(findings) == 2
        for f in findings:
            assert "self.count" in f.message
            joined = " ".join(f.chain)
            assert "[loop]" in joined
            assert "[thread:_probe_loop]" in joined

    def test_locked_writes_clean(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: RACE_BAD.replace(
                    "        self.count += 1",
                    "        with self._lock:\n"
                    "            self.count += 1",
                )
            },
            ["shared-state-lock"],
        )
        assert findings == []

    def test_lock_held_helper_covers_callee_writes(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                import threading

                class Registry:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self.count = 0

                    def start(self):
                        threading.Thread(target=self._loop).start()

                    def _loop(self):
                        with self._lock:
                            self._bump()

                    async def handle(self):
                        with self._lock:
                            self._bump()

                    def _bump(self):
                        self.count += 1
                """
            },
            ["shared-state-lock"],
        )
        assert findings == []

    def test_single_context_writes_clean(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                import threading

                class OnlyThread:
                    def start(self):
                        threading.Thread(target=self._loop).start()

                    def _loop(self):
                        self.n = 1
                """
            },
            ["shared-state-lock"],
        )
        assert findings == []

    def test_module_global_registry_mutation(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                import threading

                _REGISTRY = {}

                def start():
                    threading.Thread(target=_loop).start()

                def _loop():
                    _REGISTRY["k"] = 1

                async def handle():
                    _REGISTRY["k"] = 2
                """
            },
            ["shared-state-lock"],
        )
        assert len(findings) == 2
        assert all("_REGISTRY" in f.message for f in findings)

    def test_suppression_honored(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: RACE_BAD.replace(
                    "    def _probe_loop(self):\n        self.count += 1",
                    "    def _probe_loop(self):\n"
                    "        self.count += 1  # graftlint: disable=shared-state-lock -- fixture",
                ).replace(
                    "    async def handle(self):\n        self.count += 1",
                    "    async def handle(self):\n"
                    "        self.count += 1  # graftlint: disable=shared-state-lock -- fixture",
                )
            },
            ["shared-state-lock"],
        )
        assert findings == []


# -- resource-leak ----------------------------------------------------------


class TestResourceLeak:
    REL = "pytensor_federated_tpu/service/mod.py"

    def test_dropped_and_unbound_handles_flagged(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                import socket

                def probe(host):
                    s = socket.create_connection((host, 1), timeout=1)
                    return True

                def chain(host):
                    socket.socket().connect((host, 1))
                """
            },
            ["resource-leak"],
        )
        assert len(findings) == 2
        msgs = " ".join(f.message for f in findings)
        assert "never closed" in msgs
        assert "never bound" in msgs

    def test_scoped_closed_and_escaping_clean(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                import socket

                def scoped(host):
                    with socket.create_connection((host, 1)) as s:
                        return s.recv(1)

                def closed(host):
                    s = socket.create_connection((host, 1))
                    try:
                        return s.recv(1)
                    finally:
                        s.close()

                def escapes(host):
                    s = socket.create_connection((host, 1))
                    return s

                def stored(self_like, host):
                    s = socket.create_connection((host, 1))
                    self_like.sock = s

                def handed_off(host, pool):
                    s = socket.create_connection((host, 1))
                    pool.adopt(s)
                """
            },
            ["resource-leak"],
        )
        assert findings == []

    def test_suppression_honored(self, tmp_path):
        findings = run_on(
            tmp_path,
            {
                self.REL: """
                import socket

                def probe(host):
                    s = socket.create_connection((host, 1))  # graftlint: disable=resource-leak -- fixture
                    return True
                """
            },
            ["resource-leak"],
        )
        assert findings == []


# -- fed-placement ----------------------------------------------------------


class TestFedPlacement:
    def test_driver_varying_capture_flagged_with_provenance(self):
        """The acceptance fixture: a pool-refusable fed_map (closure
        captures an upstream product of a program input) is caught
        from the jaxpr with the operand's provenance chain."""
        import jax.numpy as jnp
        import numpy as np

        from pytensor_federated_tpu.fed.primitives import (
            fed_map,
            fed_sum,
        )

        data = jnp.asarray(np.ones((4, 3), np.float32))

        def bad(params):
            scale = params * 2.0  # upstream eqn output
            lps = fed_map(
                lambda shard: jnp.sum(shard[0] * scale), (data,)
            )
            return fed_sum(lps)

        caps = placement_findings(
            bad, (jnp.ones((3,), jnp.float32),), fixture="bad"
        )
        assert len(caps) == 1
        cap = caps[0]
        assert cap.fixture == "bad"
        prov = " ".join(cap.provenance)
        assert "output of `mul`" in prov
        assert "program input #0" in prov

    def test_broadcast_routed_program_clean(self):
        import jax.numpy as jnp
        import numpy as np

        from pytensor_federated_tpu.fed.primitives import (
            fed_broadcast,
            fed_map,
            fed_sum,
        )

        data = jnp.asarray(np.ones((4, 3), np.float32))

        def good(params):
            pb = fed_broadcast((params * 2.0,), 4)
            lps = fed_map(
                lambda shard: jnp.sum(shard[0][0] * shard[1]), (pb, data)
            )
            return fed_sum(lps)

        assert placement_findings(good, (jnp.ones((3,), jnp.float32),)) == []

    def test_shipped_fixtures_are_clean(self):
        from pytensor_federated_tpu.fed import lint_fixtures

        for fixture in lint_fixtures.FIXTURES:
            fn, args = fixture.build()
            assert placement_findings(fn, args, fixture=fixture.name) == []


# -- the migrated shim reachability matches the old semantics ---------------


class TestShimOnSharedGraph:
    def test_conservative_name_merge_preserved(self, tmp_path):
        """Two same-named methods in different classes: the shimmed
        one keeps its seam coverage for both (the conservative
        direction the old module-private index guaranteed)."""
        findings = run_on(
            tmp_path,
            {
                "pytensor_federated_tpu/service/mod.py": """
                class A:
                    def send(self, sock, b):
                        if _fi.active_plan is not None:
                            _fi.send_frame_through("p", sock.sendall, b)
                        else:
                            sock.sendall(b)

                class B:
                    def send(self, sock, b):
                        sock.sendall(b)
                """
            },
            ["fault-shim-coverage"],
        )
        assert findings == []
