"""Ring collectives + sequence parallelism (parallel/ring.py).

Ground truth for every test is the single-device dense computation —
the golden-model equivalence pattern (reference: test_demo_node.py:29-65)
applied to the net-new sequence axis.  Runs on the virtual 8-device CPU
mesh from conftest.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu.parallel import make_mesh
from pytensor_federated_tpu.parallel.ring import (
    ring_all_pairs_sum,
    ring_attention,
    seq_sharded_markov_logp,
)


@pytest.fixture(scope="module")
def seq_mesh(devices8):
    return make_mesh({"seq": 4}, devices=devices8[:4])


def dense_attention(q, k, v, *, causal=False):
    s = (q @ k.T) / jnp.sqrt(jnp.asarray(q.shape[-1], q.dtype))
    if causal:
        t = q.shape[0]
        mask = jnp.tril(jnp.ones((t, t), dtype=bool))
        s = jnp.where(mask, s, -jnp.inf)
    return jax.nn.softmax(s, axis=-1) @ v


class TestRingAttention:
    def test_matches_dense(self, seq_mesh):
        rng = np.random.default_rng(0)
        t, d = 32, 16
        q, k, v = (
            jnp.asarray(rng.normal(size=(t, d)).astype(np.float32))
            for _ in range(3)
        )
        out = ring_attention(q, k, v, mesh=seq_mesh, axis="seq")
        ref = dense_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_causal_matches_dense(self, seq_mesh):
        rng = np.random.default_rng(1)
        t, d = 32, 8
        q, k, v = (
            jnp.asarray(rng.normal(size=(t, d)).astype(np.float32))
            for _ in range(3)
        )
        out = ring_attention(q, k, v, mesh=seq_mesh, axis="seq", causal=True)
        ref = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_differentiable(self, seq_mesh):
        rng = np.random.default_rng(2)
        t, d = 16, 4
        q, k, v = (
            jnp.asarray(rng.normal(size=(t, d)).astype(np.float32))
            for _ in range(3)
        )

        def loss_ring(q, k, v):
            return jnp.sum(
                ring_attention(q, k, v, mesh=seq_mesh, axis="seq", causal=True)
                ** 2
            )

        def loss_dense(q, k, v):
            return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for gr, gd in zip(g_ring, g_dense):
            np.testing.assert_allclose(
                np.asarray(gr), np.asarray(gd), atol=2e-4
            )

    def test_indivisible_raises(self, seq_mesh):
        q = jnp.zeros((30, 4))
        with pytest.raises(ValueError, match="not divisible"):
            ring_attention(q, q, q, mesh=seq_mesh, axis="seq")


class TestRingAllPairs:
    def test_pairwise_sum_matches_dense(self, seq_mesh):
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.normal(size=(16, 3)).astype(np.float32))

        def pair_fn(a, b):
            # squared-exponential cross-block energy
            d2 = jnp.sum((a[:, None, :] - b[None, :, :]) ** 2, axis=-1)
            return jnp.sum(jnp.exp(-0.5 * d2))

        got = ring_all_pairs_sum(pair_fn, x, mesh=seq_mesh, axis="seq")
        want = pair_fn(x, x)  # dense all-pairs over the full set
        np.testing.assert_allclose(float(got), float(want), rtol=1e-5)

    def test_exclude_self(self, seq_mesh):
        x = jnp.asarray(np.arange(8, dtype=np.float32).reshape(8, 1))

        def pair_fn(a, b):
            return jnp.sum(a[:, None, :] * b[None, :, :])

        got = ring_all_pairs_sum(
            pair_fn, x, mesh=seq_mesh, axis="seq", include_self=False
        )
        # dense minus the block-diagonal (blocks of 2 rows on 4 devices)
        blocks = x.reshape(4, 2, 1)
        diag = sum(float(pair_fn(b, b)) for b in blocks)
        want = float(pair_fn(x, x)) - diag
        np.testing.assert_allclose(float(got), want, rtol=1e-5)


class TestSeqShardedMarkov:
    def test_ar1_logp_matches_single_device(self, devices8):
        from pytensor_federated_tpu.models.timeseries import (
            SeqShardedAR1,
            generate_ar1_data,
        )

        y = generate_ar1_data(256, seed=11)
        mesh = make_mesh({"seq": 8}, devices=devices8)
        sharded = SeqShardedAR1(y, mesh=mesh)
        dense = SeqShardedAR1(y, mesh=None)
        params = {
            "mu": jnp.asarray(0.4),
            "arctanh_phi": jnp.asarray(0.9),
            "log_sigma": jnp.asarray(-1.0),
        }
        np.testing.assert_allclose(
            float(sharded.logp(params)), float(dense.logp(params)), rtol=1e-5
        )

    def test_ar1_grad_matches_single_device(self, devices8):
        from pytensor_federated_tpu.models.timeseries import (
            SeqShardedAR1,
            generate_ar1_data,
        )

        y = generate_ar1_data(128, seed=12)
        mesh = make_mesh({"seq": 4}, devices=devices8[:4])
        sharded = SeqShardedAR1(y, mesh=mesh)
        dense = SeqShardedAR1(y, mesh=None)
        params = sharded.init_params()
        v_s, g_s = sharded.logp_and_grad(params)
        v_d, g_d = dense.logp_and_grad(params)
        np.testing.assert_allclose(float(v_s), float(v_d), rtol=1e-5)
        for k in params:
            np.testing.assert_allclose(
                float(g_s[k]), float(g_d[k]), rtol=1e-4, atol=1e-5
            )

    def test_posterior_recovers_truth(self, devices8):
        """End-to-end: NUTS over the sequence-sharded likelihood recovers
        the generating parameters (pattern: reference test_wrapper_ops.py
        posterior-accuracy assertions)."""
        from pytensor_federated_tpu.models.timeseries import (
            SeqShardedAR1,
            generate_ar1_data,
        )
        from pytensor_federated_tpu.samplers import sample

        y = generate_ar1_data(2048, mu=0.5, phi=0.8, sigma=0.3, seed=21)
        mesh = make_mesh({"seq": 4}, devices=devices8[:4])
        model = SeqShardedAR1(y, mesh=mesh)
        res = sample(
            model.logp,
            model.init_params(),
            key=jax.random.PRNGKey(0),
            num_warmup=300,
            num_samples=300,
            kernel="nuts",
            max_depth=6,
        )
        mu = float(jnp.median(res.samples["mu"]))
        phi = float(jnp.median(jnp.tanh(res.samples["arctanh_phi"])))
        sigma = float(jnp.median(jnp.exp(res.samples["log_sigma"])))
        assert abs(mu - 0.5) < 0.15, mu
        assert abs(phi - 0.8) < 0.1, phi
        assert abs(sigma - 0.3) < 0.05, sigma
