"""The driver metric's sizing/chaining machinery, unit-tested.

bench.py is the one artifact the driver captures every round; a silent
regression in `make_chained` (e.g. the chain becoming DCE-able) or in
`measure_rate`'s two-stage sizing would corrupt the headline number
without failing any test.  These tests pin the contracts:

- the chained runner really performs n *dependent* evaluations;
- one compiled executable serves every chain length (the dynamic trip
  count exists because each static length would cost a 20-40 s remote
  TPU compile, CLAUDE.md);
- measure_rate returns a rate consistent with its own measured wall.
"""

import jax
import jax.numpy as jnp
import numpy as np

from bench import make_chained, measure_rate


def _counting_logp_grad():
    # value = -x.x/2, grad = -x ; the chained update x + 1e-6*g decays
    # toward 0, so the final carry encodes how many steps really ran.
    def fn(x):
        return -0.5 * jnp.sum(x * x), -x

    return fn


def test_chained_runs_n_dependent_evals():
    chained = make_chained(_counting_logp_grad())
    x0 = jnp.ones((4,))
    (x_out, acc), _ = (
        chained(x0, jnp.asarray(1000, jnp.int32)),
        None,
    )
    # each step multiplies x by (1 - 1e-6): after n steps, norm shrinks
    # by (1 - 1e-6)^n — detectably different from 0 or 1 steps.
    expected = (1.0 - 1e-6) ** 1000
    np.testing.assert_allclose(float(x_out[0]), expected, rtol=1e-4)
    # the accumulated value must be ~ -0.5*4 per step x 1000 steps
    assert acc < -1000.0


def test_one_executable_serves_all_lengths():
    chained = make_chained(_counting_logp_grad())
    x0 = jnp.ones((4,))
    jax.block_until_ready(chained(x0, jnp.asarray(10, jnp.int32)))
    # Different trip counts must not retrace/recompile: jit cache size 1.
    sizes = chained._cache_size() if hasattr(chained, "_cache_size") else None
    jax.block_until_ready(chained(x0, jnp.asarray(1000, jnp.int32)))
    if sizes is not None:
        assert chained._cache_size() == sizes


def test_measure_rate_consistent():
    chained = make_chained(_counting_logp_grad())
    x0 = jnp.ones((4,))
    rate, n, wall = measure_rate(
        chained, x0, n_cal=100, floor=500, mid_wall=0.05, target_wall=0.15
    )
    assert n >= 500
    assert rate > 0
    np.testing.assert_allclose(rate, n / wall, rtol=1e-6)


def test_bench_json_contract_fields():
    # The driver parses ONE json line with these fields; pin the schema
    # without paying a full bench run (bench.main is exercised by the
    # driver itself every round).
    import bench

    assert bench.NORTH_STAR == 50_000.0


def test_unroll_numerics_identical():
    # The unrolled chain must be bit-identical to unroll=1 for any n,
    # including n not divisible by the unroll factor.
    fn = _counting_logp_grad()
    c1 = make_chained(fn, unroll=1)
    c8 = make_chained(fn, unroll=8)
    x0 = jnp.arange(1.0, 5.0)
    for n in (0, 1, 7, 8, 9, 1003):
        a = c1(x0, jnp.asarray(n, jnp.int32))
        b = c8(x0, jnp.asarray(n, jnp.int32))
        np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))
        np.testing.assert_array_equal(np.asarray(a[1]), np.asarray(b[1]))


def test_measure_rate_rejects_nan_chain():
    # A NaN-producing eval degenerates the chain into a constant loop
    # (round-3: the first live TPU capture recorded 6.8e11 "evals/s");
    # measure_rate must refuse loudly, not produce a number.
    import pytest

    def bad(x):
        return jnp.nan * jnp.sum(x), x * jnp.nan

    chained = make_chained(bad)
    with pytest.raises(RuntimeError, match="degenerate"):
        measure_rate(chained, jnp.ones((4,)), n_cal=10, floor=20,
                     mid_wall=0.01, target_wall=0.02)


def test_measure_rate_rejects_zero_gradient_chain():
    import pytest

    def frozen(x):
        return jnp.sum(x), jnp.zeros_like(x)

    chained = make_chained(frozen)
    with pytest.raises(RuntimeError, match="degenerate"):
        measure_rate(chained, jnp.ones((4,)), n_cal=10, floor=20,
                     mid_wall=0.01, target_wall=0.02)
