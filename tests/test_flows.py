"""RealNVP flow VI — non-Gaussian posteriors beyond any Gaussian family.

The banana (Rosenbrock-style) target is the standard demonstration: a
curved ridge no Gaussian q can follow.  Pinned: the flow's ELBO beats
the full-rank Gaussian's on the banana, flow samples follow the curve
(E[x2 | x1] ≈ x1²), and sample_with_logq's density is consistent with
the change-of-variables (checked against a long-run importance
identity).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu.samplers import fullrank_advi_fit
from pytensor_federated_tpu.samplers.flows import realnvp_advi_fit


def banana_logp(p):
    x = p["x"]
    return -0.5 * x[0] ** 2 - 0.5 * ((x[1] - x[0] ** 2) / 0.5) ** 2


def test_flow_fits_banana_better_than_gaussian():
    kw = dict(key=jax.random.PRNGKey(0), num_steps=2500)
    res_flow, unravel = realnvp_advi_fit(
        banana_logp, {"x": jnp.zeros(2)}, **kw
    )
    res_fr, _ = fullrank_advi_fit(banana_logp, {"x": jnp.zeros(2)}, **kw)
    tail = lambda r: float(jnp.mean(r.elbo_trace[-200:]))
    assert tail(res_flow) > tail(res_fr)

    # flow samples follow the curved ridge: E[x2 | x1] ~ x1^2
    draws = res_flow.sample(jax.random.PRNGKey(1), 4000, unravel)
    xs = np.asarray(draws["x"])
    resid = xs[:, 1] - xs[:, 0] ** 2
    assert abs(resid.mean()) < 0.2
    assert resid.std() < 1.0  # conditional sd is 0.5; Gaussian q can't
    assert abs(xs[:, 0].mean()) < 0.25


def test_sample_with_logq_is_a_density():
    # Importance identity: E_q[exp(logp - logq)] = Z (here the banana's
    # normalizer, a finite constant) — a WRONG logq (e.g. missing
    # logdet) makes the weights blow up or collapse by orders of
    # magnitude.  Check the log-weights are tight around a constant.
    res, _ = realnvp_advi_fit(
        banana_logp,
        {"x": jnp.zeros(2)},
        key=jax.random.PRNGKey(3),
        num_steps=2500,
    )
    x, logq = res.sample_with_logq(jax.random.PRNGKey(4), 4000)
    logp = jax.vmap(lambda v: banana_logp({"x": v}))(x)
    lw = np.asarray(logp - logq)
    # a well-fit flow keeps the weights in a narrow band
    assert np.std(lw) < 1.0


def test_dim1_rejected():
    with pytest.raises(ValueError, match="d >= 2"):
        realnvp_advi_fit(
            lambda p: -0.5 * p["x"] ** 2,
            {"x": jnp.zeros(())},
            key=jax.random.PRNGKey(0),
        )
