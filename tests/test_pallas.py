"""Pallas fused logp+grad kernel — equivalence vs the plain-JAX path.

Mirrors the reference's golden-model pattern: the blackbox/kernel path is
asserted numerically identical to a natively built graph of the same
model (reference: test_demo_node.py:29-65).  Runs the kernel in Pallas
interpreter mode so the identical kernel code executes on the CPU test
mesh (SURVEY §4 pattern (d)).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu.ops.pallas_kernels import (
    LOG_2PI,
    linreg_logp_grad_fn,
    linreg_reductions,
)


def _make_case(S, N, seed=0, mask_p=0.25):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(S, N)).astype(np.float32)
    y = (1.0 + 2.0 * x + 0.3 * rng.normal(size=(S, N))).astype(np.float32)
    mask = (rng.uniform(size=(S, N)) > mask_p).astype(np.float32)
    params = {
        "intercept": jnp.float32(0.7),
        "slope": jnp.float32(1.8),
        "log_sigma": jnp.float32(-0.2),
        "offsets": jnp.asarray(rng.normal(size=S).astype(np.float32)),
    }
    return x, y, mask, params


def _ref_logp(params, x, y, mask):
    mu = (params["intercept"] + params["offsets"][:, None]) + params["slope"] * x
    z = (y - mu) * jnp.exp(-params["log_sigma"])
    ll = -0.5 * z * z - params["log_sigma"] - 0.5 * LOG_2PI
    return jnp.sum(ll * mask)


@pytest.mark.parametrize(
    "S,N",
    [
        (1, 8),  # smaller than one block in both dims
        (5, 70),  # ragged: exercises shard+obs padding
        (8, 512),  # exact block grid
        (12, 700),  # multi-block with remainder
    ],
)
def test_kernel_matches_jax(S, N):
    x, y, mask, params = _make_case(S, N)
    fn = linreg_logp_grad_fn(x, y, mask, interpret=True)
    v, g = fn(params)
    rv, rg = jax.value_and_grad(
        lambda p: _ref_logp(p, jnp.asarray(x), jnp.asarray(y), jnp.asarray(mask))
    )(params)
    np.testing.assert_allclose(v, rv, rtol=5e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-4, atol=5e-4), g, rg
    )


def test_reductions_padding_is_inert():
    """Padded rows/cols must contribute exactly zero (mask==0 there)."""
    x, y, mask, params = _make_case(3, 17)
    scal = jnp.stack(
        [params["intercept"], params["slope"], params["log_sigma"]]
    )
    ll, gmu, gx, gz = linreg_reductions(
        scal, params["offsets"], x, y, mask, interpret=True
    )
    assert ll.shape == (3,)
    ll2, *_ = linreg_reductions(
        scal,
        params["offsets"],
        np.pad(x, ((0, 0), (0, 40))),
        np.pad(y, ((0, 0), (0, 40))),
        np.pad(mask, ((0, 0), (0, 40))),
        interpret=True,
    )
    np.testing.assert_allclose(ll, ll2[:3], rtol=1e-6)


def test_kernel_composes_with_custom_vjp():
    """The kernel's value feeds a larger differentiable expression
    (prior + likelihood), the way NUTS consumes it."""
    x, y, mask, params = _make_case(4, 33)
    fn = linreg_logp_grad_fn(x, y, mask, interpret=True)

    def posterior(p):
        prior = -0.5 * (p["slope"] ** 2) - 0.5 * jnp.sum(p["offsets"] ** 2)
        return prior + fn.data_logp(p)

    v, g = jax.value_and_grad(posterior)(params)
    rv, rg = jax.value_and_grad(
        lambda p: -0.5 * (p["slope"] ** 2)
        - 0.5 * jnp.sum(p["offsets"] ** 2)
        + _ref_logp(p, jnp.asarray(x), jnp.asarray(y), jnp.asarray(mask))
    )(params)
    np.testing.assert_allclose(v, rv, rtol=5e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-4, atol=5e-4), g, rg
    )


def test_second_order_unsupported():
    """Same boundary contract as the reference's LogpGradOp.grad
    (reference: wrapper_ops.py:123-125): no second-order autodiff
    through the kernel boundary."""
    x, y, mask, params = _make_case(2, 16)
    fn = linreg_logp_grad_fn(x, y, mask, interpret=True)
    with pytest.raises(Exception):
        jax.hessian(lambda p: fn.data_logp(p))(params)
