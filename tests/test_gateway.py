"""Gateway tier (ISSUE 12): accept tier, per-tenant fairness, autoscaler.

End-to-end over real sockets on localhost: stock TcpArraysClients dial
the gateway exactly as they would a node (including the zero-item
batch probe and pipelined evaluate_many); behind it a NodePool of
serve_tcp_once replicas.  Fairness and autoscaling are additionally
unit-tested with injected clocks so the hysteresis/starvation
contracts are pinned deterministically (the hypothesis no-starvation
property lives here too, skipping where hypothesis is absent).
"""

import random
import socket
import struct
import threading
import time

import numpy as np
import pytest

from pytensor_federated_tpu.gateway import (
    Autoscaler,
    GatewayThread,
    TenantFairness,
    TokenBucket,
    WeightedFairQueue,
    is_overload_error,
)
from pytensor_federated_tpu.routing import NodePool
from pytensor_federated_tpu.service.deadline import (
    DeadlineExceeded,
    deadline_scope,
)
from pytensor_federated_tpu.service.npwire import (
    decode_arrays_all,
    encode_arrays,
    peek_tenant,
)
from pytensor_federated_tpu.service.tcp import (
    RemoteComputeError,
    TcpArraysClient,
    serve_tcp_once,
)


def _sum_compute(*arrays):
    return [np.asarray(sum(float(np.asarray(a).sum()) for a in arrays))]


def _start_node(compute=_sum_compute):
    got = []
    threading.Thread(
        target=serve_tcp_once,
        args=(compute,),
        kwargs=dict(ready_callback=got.append, concurrent=True),
        daemon=True,
    ).start()
    deadline = time.time() + 10.0
    while not got and time.time() < deadline:
        time.sleep(0.005)
    assert got, "node did not come up"
    return got[0]


@pytest.fixture(scope="module")
def node_ports():
    return [_start_node() for _ in range(2)]


@pytest.fixture()
def pool(node_ports):
    p = NodePool(
        [("127.0.0.1", pt) for pt in node_ports], transport="tcp"
    )
    yield p
    p.close()


# ---------------------------------------------------------------------------
# fairness primitives
# ---------------------------------------------------------------------------


class TestTokenBucket:
    def test_burst_then_denial_then_refill(self):
        t = [0.0]
        bucket = TokenBucket(rate_per_s=10.0, burst=3.0, clock=lambda: t[0])
        assert all(bucket.try_spend() for _ in range(3))
        assert not bucket.try_spend()
        t[0] += 0.2  # +2 tokens
        assert bucket.try_spend() and bucket.try_spend()
        assert not bucket.try_spend()

    def test_never_exceeds_burst(self):
        t = [0.0]
        bucket = TokenBucket(rate_per_s=100.0, burst=5.0, clock=lambda: t[0])
        t[0] += 1e6
        assert bucket.tokens() == pytest.approx(5.0)


class TestWeightedFairQueue:
    def test_fifo_within_tenant(self):
        q = WeightedFairQueue()
        for i in range(5):
            q.push("a", i)
        assert [q.pop()[1] for _ in range(5)] == [0, 1, 2, 3, 4]
        assert q.pop() is None

    def test_equal_weights_round_robin_bound(self):
        """With equal weights a backlogged tenant is served at least
        once every n_tenants pops — the DRR no-starvation bound."""
        q = WeightedFairQueue()
        tenants = ["a", "b", "c", "d"]
        for t in tenants:
            for i in range(20):
                q.push(t, (t, i))
        last_seen = {t: -1 for t in tenants}
        for k in range(4 * 20):
            tenant, _item = q.pop()
            for t in tenants:
                if q.depth(t):
                    assert k - last_seen[t] <= len(tenants), (
                        f"{t} starved for {k - last_seen[t]} pops"
                    )
            last_seen[tenant] = k

    def test_hog_cannot_starve_mouse(self):
        """A hog tenant with a 1000-deep backlog cannot delay another
        tenant's single queued request beyond the DRR bound."""
        q = WeightedFairQueue()
        for i in range(1000):
            q.push("hog", i)
        q.push("mouse", "hello")
        served_at = None
        for k in range(10):
            tenant, item = q.pop()
            if tenant == "mouse":
                served_at = k
                break
        assert served_at is not None and served_at <= 2

    def test_weights_bias_service(self):
        q = WeightedFairQueue(weights={"gold": 3.0, "free": 1.0})
        for i in range(300):
            q.push("gold", i)
            q.push("free", i)
        counts = {"gold": 0, "free": 0}
        for _ in range(200):
            tenant, _ = q.pop()
            counts[tenant] += 1
        # 3:1 weights => roughly 3:1 service while both are backlogged.
        assert counts["gold"] >= 2 * counts["free"]

    def test_weight_floor_prevents_configured_starvation(self):
        q = WeightedFairQueue(weights={"z": 0.0})
        assert q.weight_of("z") == WeightedFairQueue.MIN_WEIGHT
        q.push("z", 1)
        assert q.pop() == ("z", 1)

    def test_no_starvation_property_seeded(self):
        """Deterministic sweep of the hypothesis property (runs in
        containers without hypothesis): under any arrival pattern,
        any tenant with backlog is served within the DRR bound."""
        for seed in range(20):
            rng = random.Random(seed)
            tenants = [f"t{i}" for i in range(rng.randint(2, 6))]
            weights = {t: rng.choice([0.25, 0.5, 1.0, 2.0]) for t in tenants}
            q = WeightedFairQueue(weights=weights)
            # Worst-case pops between services of t: each OTHER tenant
            # can take ~(1 + w_i*quantum) services per ring pass, and t
            # may need ceil(1/(w_t*quantum)) passes to bank deficit.
            def gap_bound(t):
                passes = int(np.ceil(1.0 / (weights[t] * q.quantum)))
                per_pass = sum(
                    1 + int(np.ceil(weights[o] * q.quantum))
                    for o in tenants if o != t
                )
                return passes * max(per_pass, 1) + per_pass + 1

            for t in tenants:
                for i in range(rng.randint(1, 40)):
                    q.push(t, (t, i))
            last = {t: 0 for t in tenants}
            k = 0
            while True:
                popped = q.pop()
                if popped is None:
                    break
                tenant, _ = popped
                for t in tenants:
                    if q.depth(t):
                        assert k - last[t] <= gap_bound(t), (
                            f"seed {seed}: {t} starved "
                            f"{k - last[t]} > {gap_bound(t)}"
                        )
                last[tenant] = k
                k += 1

    def test_no_starvation_property_hypothesis(self):
        """The same bound under hypothesis-generated arrival patterns
        (the ISSUE-12 property-test requirement)."""
        hypothesis = pytest.importorskip("hypothesis")
        st = hypothesis.strategies

        @hypothesis.settings(max_examples=50, deadline=None)
        @hypothesis.given(
            backlogs=st.dictionaries(
                st.sampled_from(["a", "b", "c", "d", "e"]),
                st.integers(1, 30),
                min_size=2,
            ),
            weights=st.dictionaries(
                st.sampled_from(["a", "b", "c", "d", "e"]),
                st.floats(0.1, 4.0, allow_nan=False),
            ),
        )
        def prop(backlogs, weights):
            q = WeightedFairQueue(weights=weights)
            tenants = sorted(backlogs)

            def gap_bound(t):
                w = q.weight_of(t)
                passes = int(np.ceil(1.0 / (w * q.quantum)))
                per_pass = sum(
                    1 + int(np.ceil(q.weight_of(o) * q.quantum))
                    for o in tenants if o != t
                )
                return passes * max(per_pass, 1) + per_pass + 1

            for t in tenants:
                for i in range(backlogs[t]):
                    q.push(t, (t, i))
            last = {t: 0 for t in tenants}
            k = 0
            while True:
                popped = q.pop()
                if popped is None:
                    break
                tenant, _ = popped
                for t in tenants:
                    if q.depth(t):
                        assert k - last[t] <= gap_bound(t)
                last[tenant] = k
                k += 1

        prop()


class TestTenantFairnessAdmission:
    def test_quota_denial_names_tenant(self):
        fairness = TenantFairness(
            quota_rate_per_s=1.0, quota_burst=1.0
        )
        assert fairness.admit("acme") is None
        denial = fairness.admit("acme")
        assert denial is not None
        assert is_overload_error(denial)
        assert "acme" in denial

    def test_backlog_denial(self):
        fairness = TenantFairness(max_backlog_per_tenant=2)
        assert fairness.admit("t") is None
        fairness.queue.push("t", 1)
        fairness.queue.push("t", 2)
        denial = fairness.admit("t")
        assert denial is not None and "backlog" in denial

    def test_tenant_cardinality_cap_denies_rotating_ids(self):
        """Rotating fresh tenant ids must not mint unlimited fresh
        quota buckets: past max_tenants with no idle slot, the
        request is denied loudly (the anti-quota-evasion bound)."""
        fairness = TenantFairness(
            quota_rate_per_s=0.001, quota_burst=5.0, max_tenants=2
        )
        assert fairness.admit("a") is None
        assert fairness.admit("b") is None
        denial = fairness.admit("c")
        assert denial is not None and "tenant table full" in denial
        assert is_overload_error(denial)
        # Tracked tenants keep admitting inside their own quota.
        assert fairness.admit("a") is None

    def test_tenant_cardinality_cap_holds_without_quotas(self):
        """With quotas DISABLED (the GatewayServer default) the cap
        must key off queued-backlog tenants, or rotating ids would
        mint unlimited per-tenant backlog allowances (regression:
        the cap was keyed on quota buckets alone and inert)."""
        fairness = TenantFairness(max_tenants=3)  # no quota
        for t in ("a", "b", "c"):
            assert fairness.admit(t) is None
            fairness.queue.push(t, t)
        denial = fairness.admit("d")
        assert denial is not None and "tenant table full" in denial
        # A drained tenant frees its slot.
        while fairness.queue.pop() is not None:
            pass
        assert fairness.admit("d") is None

    def test_tenant_cardinality_cap_reclaims_idle_slots(self):
        """A bucket back at full burst is an idle tenant: its slot is
        reclaimed for a new id instead of denying forever."""
        fairness = TenantFairness(
            quota_rate_per_s=1e6, quota_burst=5.0, max_tenants=2
        )
        assert fairness.admit("a") is None
        assert fairness.admit("b") is None
        # a/b refill instantly at this rate -> idle -> c evicts one.
        assert fairness.admit("c") is None

    def test_drained_tenant_state_is_pruned(self):
        """WFQ bookkeeping must not accumulate one _TenantState per
        distinct id forever (the id is attacker-controlled input)."""
        q = WeightedFairQueue()
        for i in range(50):
            q.push(f"tenant-{i}", i)
        while q.pop() is not None:
            pass
        assert q._states == {} and q.depth() == 0

    def test_push_front_preserves_fifo(self):
        """The window byte-cap re-insert goes to the HEAD of the
        tenant's queue — per-tenant FIFO order survives a deferral."""
        q = WeightedFairQueue()
        q.push("a", 1)
        q.push("a", 2)
        tenant, item = q.pop()
        assert (tenant, item) == ("a", 1)
        q.push_front("a", 1)                 # deferred, not dispatched
        assert [q.pop()[1] for _ in range(2)] == [1, 2]


# ---------------------------------------------------------------------------
# tenant field, example-based (the hypothesis suite is
# tests/test_tenant_wire.py; these run even without hypothesis)
# ---------------------------------------------------------------------------


class TestTenantFieldExamples:
    def test_npwire_examples(self):
        x = np.arange(3.0)
        buf = encode_arrays([x], uuid=b"u" * 16, tenant="acme/eu-1")
        assert peek_tenant(buf) == "acme/eu-1"
        arrays, _, _, _, _ = decode_arrays_all(buf)
        np.testing.assert_array_equal(arrays[0], x)
        assert peek_tenant(encode_arrays([x], uuid=b"u" * 16)) is None

    def test_client_stamps_tenant(self, node_ports):
        """A tenant-stamped TcpArraysClient works against a PLAIN node
        (which consumes and drops the block) — tenancy is optional
        metadata end to end."""
        client = TcpArraysClient(
            "127.0.0.1", node_ports[0], tenant="acme"
        )
        out = client.evaluate(np.arange(4.0))
        assert float(np.asarray(out[0])) == 6.0
        client.close()


# ---------------------------------------------------------------------------
# the accept tier, end to end
# ---------------------------------------------------------------------------


class TestGatewayE2E:
    def test_evaluate_and_pipelined_many(self, pool):
        with GatewayThread(pool) as gw:
            client = TcpArraysClient("127.0.0.1", gw.port, tenant="t1")
            out = client.evaluate(np.arange(4.0))
            assert float(np.asarray(out[0])) == 6.0
            reqs = [(np.asarray([float(i)]),) for i in range(40)]
            res = client.evaluate_many(reqs, window=16)
            assert [float(np.asarray(r[0])) for r in res] == [
                float(i) for i in range(40)
            ]
            client.close()

    def test_gateway_answers_liveness_probe(self, pool):
        """The pool's zero-item batch probe must get the empty-batch
        echo from the gateway itself — a gateway can be pooled."""
        from pytensor_federated_tpu.routing.pool import _tcp_probe

        with GatewayThread(pool) as gw:
            assert _tcp_probe("127.0.0.1", gw.port, timeout=5.0)

    def test_many_connections_multiplex(self, pool):
        """Dozens of concurrent downstream connections (each its own
        client) multiplex onto the 2-replica pool and all get exact
        results."""
        with GatewayThread(pool) as gw:
            errors = []

            def one(k):
                try:
                    c = TcpArraysClient(
                        "127.0.0.1", gw.port, tenant=f"t{k % 5}"
                    )
                    out = c.evaluate(np.asarray([float(k), 1.0]))
                    assert float(np.asarray(out[0])) == float(k) + 1.0
                    c.close()
                except Exception as e:  # noqa: BLE001 - collected
                    errors.append(e)

            threads = [
                threading.Thread(target=one, args=(k,)) for k in range(48)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert not errors, errors[:3]

    def test_quota_denial_is_loud_retryable(self, pool):
        fairness = TenantFairness(quota_rate_per_s=1.0, quota_burst=2.0)
        with GatewayThread(pool, fairness=fairness) as gw:
            client = TcpArraysClient(
                "127.0.0.1", gw.port, tenant="burster"
            )
            outcomes = []
            for i in range(6):
                try:
                    client.evaluate(np.asarray([1.0]))
                    outcomes.append("ok")
                except RemoteComputeError as e:
                    assert is_overload_error(str(e))
                    assert "burster" in str(e)
                    outcomes.append("denied")
            assert "denied" in outcomes and "ok" in outcomes
            client.close()
        from pytensor_federated_tpu.telemetry.metrics import REGISTRY

        fam = REGISTRY.get("pftpu_gateway_denials_total")
        assert fam is not None
        assert fam.labelnames == ("tenant", "reason")
        assert ("burster", "quota") in fam._children

    def test_expired_deadline_shed_at_gateway(self, pool):
        """A frame whose budget expired in flight is shed IN-BAND at
        the gateway (pre-coalesce), classified as DeadlineExceeded."""
        with GatewayThread(pool) as gw:
            frame = encode_arrays(
                [np.asarray([1.0])], uuid=b"d" * 16, deadline_s=1e-9
            )
            time.sleep(0.01)
            with socket.create_connection(
                ("127.0.0.1", gw.port), timeout=10.0
            ) as s:
                s.settimeout(10.0)
                s.sendall(struct.pack("<I", len(frame)) + frame)
                (n,) = struct.unpack("<I", _recv_exact(s, 4))
                reply = _recv_exact(s, n)
            _arrays, uuid, error, _tid, _sp = decode_arrays_all(reply)
            assert uuid == b"d" * 16
            assert error is not None and "deadline exceeded" in error

    def test_denial_pause_scales_with_batch_denials(self, pool):
        """A batch frame of K denied items must earn ~K pauses, not
        one — otherwise wrapping the flood in batch frames amortizes
        denial pacing away (the reopened-DoS regression)."""
        from pytensor_federated_tpu.gateway.server import GatewayServer

        server = GatewayServer(pool, denial_pause_s=0.05)
        assert server._denial_pause_for(0) == 0.0
        assert server._denial_pause_for(1) == pytest.approx(0.05)
        assert server._denial_pause_for(10) == pytest.approx(0.5)
        assert (
            server._denial_pause_for(10_000)
            == GatewayServer.MAX_DENIAL_PAUSE_S
        )
        quiet = GatewayServer(pool, denial_pause_s=0.0)
        assert quiet._denial_pause_for(100) == 0.0

    def test_client_deadline_scope_propagates(self, pool):
        with GatewayThread(pool) as gw:
            client = TcpArraysClient("127.0.0.1", gw.port)
            with deadline_scope(30.0):
                out = client.evaluate(np.asarray([2.0, 3.0]))
            assert float(np.asarray(out[0])) == 5.0
            with pytest.raises(DeadlineExceeded):
                with deadline_scope(1e-9):
                    client.evaluate(np.asarray([1.0]))
            client.close()

    def test_failover_around_dead_replica(self, node_ports):
        """A pool seeded with one dead address: the gateway's window
        fails over to the live replica and the caller still gets exact
        results."""
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            dead_port = s.getsockname()[1]
        pool = NodePool(
            [("127.0.0.1", dead_port), ("127.0.0.1", node_ports[0])],
            transport="tcp",
        )
        try:
            with GatewayThread(pool) as gw:
                client = TcpArraysClient("127.0.0.1", gw.port, retries=2)
                for i in range(6):
                    out = client.evaluate(np.asarray([float(i)]))
                    assert float(np.asarray(out[0])) == float(i)
                client.close()
        finally:
            pool.close()

    def test_hog_tenant_does_not_starve_mouse(self, node_ports):
        """Goodput isolation end to end: a hog tenant floods 300
        pipelined requests; a mouse tenant's 15 sequential calls must
        complete while the hog's flood is still in flight (DRR service
        + the hog queuing behind its own backlog)."""

        def slow_compute(*arrays):
            time.sleep(0.002)
            return _sum_compute(*arrays)

        port = _start_node(slow_compute)
        pool = NodePool([("127.0.0.1", port)], transport="tcp")
        fairness = TenantFairness(max_backlog_per_tenant=1000)
        try:
            with GatewayThread(
                pool, fairness=fairness, frame_items=8
            ) as gw:
                hog_done = []
                mouse_lat = []

                def hog():
                    c = TcpArraysClient(
                        "127.0.0.1", gw.port, tenant="hog"
                    )
                    reqs = [(np.asarray([float(i)]),) for i in range(300)]
                    c.evaluate_many(reqs, window=64)
                    hog_done.append(time.monotonic())
                    c.close()

                def mouse():
                    c = TcpArraysClient(
                        "127.0.0.1", gw.port, tenant="mouse"
                    )
                    for i in range(15):
                        t0 = time.monotonic()
                        out = c.evaluate(np.asarray([float(i)]))
                        mouse_lat.append(time.monotonic() - t0)
                        assert float(np.asarray(out[0])) == float(i)
                    c.close()

                ht = threading.Thread(target=hog)
                mt = threading.Thread(target=mouse)
                ht.start()
                time.sleep(0.1)  # the hog's backlog is in place
                mt.start()
                mt.join(timeout=60)
                mouse_finished = time.monotonic()
                assert not mt.is_alive(), "mouse starved"
                ht.join(timeout=120)
                assert not ht.is_alive()
                # The mouse must not have waited for the hog's flood.
                assert hog_done, "hog never finished"
                assert mouse_finished <= hog_done[0] + 1.0
                # And each mouse call stayed interactive (well under
                # the hog's ~0.6 s of total backlogged compute).
                assert max(mouse_lat) < 0.5, mouse_lat
        finally:
            pool.close()


def _recv_exact(sock, n):
    out = b""
    while len(out) < n:
        b = sock.recv(n - len(out))
        if not b:
            raise ConnectionError("peer closed")
        out += b
    return out


# ---------------------------------------------------------------------------
# autoscaler
# ---------------------------------------------------------------------------


class _FakeCollector:
    def __init__(self):
        self.added = []
        self.removed = []

    def add_http_target(self, record_as, target):
        self.added.append((record_as, target))

    def remove_http_target(self, record_as):
        self.removed.append(record_as)


class TestAutoscaler:
    def _make(self, pool, sig, monkeypatch, **kwargs):
        from pytensor_federated_tpu.gateway import autoscale as asc

        monkeypatch.setattr(asc, "_tcp_probe", lambda *a, **k: True)
        spawned = []
        stopped = []

        def spawn():
            port = 40000 + len(spawned)
            spawned.append(port)
            return ("127.0.0.1", port, port)

        def stop(handle):
            stopped.append(handle)

        clock = {"t": 0.0}
        scaler = Autoscaler(
            pool,
            lambda: dict(sig),
            spawn,
            stop,
            min_replicas=1,
            max_replicas=3,
            scale_up_queue_depth=10.0,
            scale_down_queue_depth=1.0,
            consecutive=2,
            cooldown_up_s=5.0,
            cooldown_down_s=5.0,
            drain_grace_s=0.0,
            clock=lambda: clock["t"],
            **kwargs,
        )
        return scaler, sig, spawned, stopped, clock

    def test_scale_up_needs_consecutive_pressure_and_cooldown(
        self, monkeypatch
    ):
        pool = NodePool([("127.0.0.1", 1)], transport="tcp")
        try:
            sig = {"queue_depth": 50.0, "shed": 0.0, "denied": 0.0}
            scaler, sig, spawned, _stopped, clock = self._make(
                pool, sig, monkeypatch
            )
            assert scaler.step() is None  # streak 1: no action yet
            assert scaler.step() == "up"  # streak 2: scale up
            assert len(pool) == 2 and spawned == [40000]
            # Cooldown holds even under sustained pressure.
            assert scaler.step() is None
            assert scaler.step() is None
            clock["t"] += 6.0
            assert scaler.step() == "up"
            assert len(pool) == 3
            # max_replicas is a hard ceiling.
            clock["t"] += 6.0
            scaler.step()
            assert scaler.step() is None and len(pool) == 3
        finally:
            pool.close()

    def test_scale_down_drains_owned_only(self, monkeypatch):
        pool = NodePool([("127.0.0.1", 1)], transport="tcp")
        try:
            sig = {"queue_depth": 50.0, "shed": 0.0, "denied": 0.0}
            scaler, sig, spawned, stopped, clock = self._make(
                pool, sig, monkeypatch
            )
            scaler.step()
            assert scaler.step() == "up"
            sig["queue_depth"] = 0.0
            clock["t"] += 6.0
            assert scaler.step() is None  # cold streak 1
            assert scaler.step() == "down"
            assert len(pool) == 1 and stopped == [40000]
            # The seed replica is never drained below min_replicas.
            clock["t"] += 6.0
            scaler.step()
            assert scaler.step() is None and len(pool) == 1
        finally:
            pool.close()

    def test_flap_hysteresis_dead_band(self, monkeypatch):
        """A signal oscillating INSIDE the dead band (between the down
        and up thresholds) causes no actions at all."""
        pool = NodePool([("127.0.0.1", 1)], transport="tcp")
        try:
            sig = {"queue_depth": 5.0, "shed": 0.0, "denied": 0.0}
            scaler, sig, spawned, stopped, clock = self._make(
                pool, sig, monkeypatch
            )
            for k in range(10):
                sig["queue_depth"] = 5.0 if k % 2 else 8.0
                clock["t"] += 1.0
                assert scaler.step() is None
            assert not spawned and not stopped
        finally:
            pool.close()

    def test_collector_follows_scale_events(self, monkeypatch):
        pool = NodePool([("127.0.0.1", 1)], transport="tcp")
        try:
            collector = _FakeCollector()
            sig = {"queue_depth": 50.0, "shed": 0.0, "denied": 0.0}
            scaler, sig, spawned, stopped, clock = self._make(
                pool, sig, monkeypatch,
                collector=collector,
                exporter_of=lambda h, p: (h, p + 1),
            )
            scaler.step()
            scaler.step()
            assert collector.added == [
                ("127.0.0.1:40000", ("127.0.0.1", 40001))
            ]
            sig["queue_depth"] = 0.0
            clock["t"] += 6.0
            scaler.step()
            scaler.step()
            assert collector.removed == ["127.0.0.1:40000"]
        finally:
            pool.close()

    def test_real_scale_up_serves_traffic(self, monkeypatch, node_ports):
        """An autoscaler spawning a REAL node under queue pressure:
        the new replica joins the pool after its liveness probe and
        the gateway routes to it."""
        pool = NodePool(
            [("127.0.0.1", node_ports[0])], transport="tcp"
        )
        try:
            with GatewayThread(pool) as gw:
                def spawn():
                    port = _start_node()
                    return ("127.0.0.1", port, port)

                scaler = Autoscaler(
                    pool,
                    gw.server.signals,
                    spawn,
                    lambda handle: None,
                    min_replicas=1,
                    max_replicas=2,
                    scale_up_queue_depth=0.0,  # always hot
                    scale_down_queue_depth=-1.0,
                    consecutive=1,
                    cooldown_up_s=0.0,
                )
                assert scaler.step() == "up"
                assert len(pool) == 2
                client = TcpArraysClient("127.0.0.1", gw.port)
                for i in range(8):
                    out = client.evaluate(np.asarray([float(i)]))
                    assert float(np.asarray(out[0])) == float(i)
                client.close()
        finally:
            pool.close()


# ---------------------------------------------------------------------------
# FleetCollector target tracking (the ISSUE-12 fix)
# ---------------------------------------------------------------------------


class TestCollectorTargetTracking:
    def test_departed_replica_alias_is_gcd(self):
        from pytensor_federated_tpu.telemetry.collector import (
            FleetCollector,
        )

        pool = NodePool(
            [("127.0.0.1", 7001), ("127.0.0.1", 7002)], transport="tcp"
        )
        try:
            collector = FleetCollector(pool=pool, include_local=False)
            collector.add_http_target(
                "127.0.0.1:7001", ("127.0.0.1", 8001)
            )
            collector.add_http_target(
                "127.0.0.1:7002", ("127.0.0.1", 8002)
            )
            targets, unscraped = collector._sweep_targets()
            assert {t[3] for t in targets} == {
                "127.0.0.1:7001", "127.0.0.1:7002"
            }
            assert unscraped == []
            # THE FIX: a departed replica's alias is dropped, not
            # scraped forever.
            pool.remove_replica("127.0.0.1", 7002)
            targets, _ = collector._sweep_targets()
            assert {t[3] for t in targets} == {"127.0.0.1:7001"}
            # and the GC is permanent (the alias map itself shrank)
            assert "127.0.0.1:7002" not in collector._http_aliases
        finally:
            pool.close()

    def test_remove_http_target_idempotent(self):
        from pytensor_federated_tpu.telemetry.collector import (
            FleetCollector,
        )

        collector = FleetCollector(include_local=False)
        collector.add_http_target("a:1", ("127.0.0.1", 9001))
        collector.remove_http_target("a:1")
        collector.remove_http_target("a:1")
        targets, _ = collector._sweep_targets()
        assert targets == []

    def test_static_aliases_without_pool_kept(self):
        from pytensor_federated_tpu.telemetry.collector import (
            FleetCollector,
        )

        collector = FleetCollector(
            http_targets={"n1:1": ("127.0.0.1", 9101)},
            include_local=False,
        )
        targets, _ = collector._sweep_targets()
        assert [t[3] for t in targets] == ["n1:1"]

    def test_static_alias_with_pool_never_gcd(self):
        """Constructor-passed aliases are configuration: attaching a
        pool must not garbage-collect a static alias naming a
        non-pool exporter (only add_http_target aliases follow pool
        membership)."""
        from pytensor_federated_tpu.telemetry.collector import (
            FleetCollector,
        )

        pool = NodePool([("127.0.0.1", 7005)], transport="tcp")
        try:
            collector = FleetCollector(
                http_targets={"external:9": ("127.0.0.1", 9109)},
                pool=pool,
                include_local=False,
            )
            targets, _ = collector._sweep_targets()
            assert any(t[3] == "external:9" for t in targets)
            targets, _ = collector._sweep_targets()   # and stays
            assert any(t[3] == "external:9" for t in targets)
        finally:
            pool.close()
