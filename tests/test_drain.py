"""Graceful server drain: in-flight work finishes, new work is refused
with a RETRYABLE status, and a replica pool fails over cleanly — the
clean half of a rolling restart (the chaotic half lives in
tests/test_chaos_e2e.py / tools/chaos_run.py)."""

import asyncio
import socket
import time

import numpy as np
import pytest

from pytensor_federated_tpu.service.server import (
    ArraysToArraysService,
    serve,
)
from pytensor_federated_tpu.service.npwire import (
    decode_arrays_all,
    encode_arrays,
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _slow_compute(delay=0.1):
    def compute(x):
        time.sleep(delay)
        return [2.0 * np.asarray(x)]

    return compute


class TestDrainDirect:
    def test_inflight_completes_new_work_rejected_then_undrain(self):
        service = ArraysToArraysService(_slow_compute(0.15))
        x = np.arange(3.0)
        request = encode_arrays([x], uuid=b"d" * 16)

        async def main():
            inflight = asyncio.ensure_future(
                service.evaluate(request, None)
            )
            await asyncio.sleep(0.03)  # the request is genuinely in flight
            drain_task = asyncio.ensure_future(service.drain(timeout_s=10))
            await asyncio.sleep(0.01)
            assert service.draining
            # NEW work is refused loudly (context=None direct-call path
            # raises; over real gRPC this is an UNAVAILABLE abort).
            with pytest.raises(ConnectionError, match="draining"):
                await service.evaluate(request, None)
            # ... while the in-flight request runs to completion.
            reply = await inflight
            arrays, uuid, error, _t, _s = decode_arrays_all(reply)
            assert error is None and uuid == b"d" * 16
            np.testing.assert_array_equal(arrays[0], 2.0 * x)
            assert await drain_task is True  # went idle within timeout
            service.undrain()
            reply = await service.evaluate(request, None)
            assert decode_arrays_all(reply)[2] is None

        asyncio.run(main())

    def test_drain_timeout_reports_dirty(self):
        service = ArraysToArraysService(_slow_compute(0.5))
        request = encode_arrays([np.ones(2)], uuid=b"e" * 16)

        async def main():
            inflight = asyncio.ensure_future(
                service.evaluate(request, None)
            )
            await asyncio.sleep(0.03)
            assert await service.drain(timeout_s=0.05) is False
            await inflight  # still completes; drain only reported

        asyncio.run(main())


class TestDrainOverGrpc:
    def test_drain_racing_a_pipelined_window_is_retryable(self):
        """A drain landing MID pipelined window: requests already
        accepted complete; the rejected tail surfaces as UNAVAILABLE —
        the transient classification failover keys on — and the
        partial-pass results that did arrive are correct."""
        from pytensor_federated_tpu.service.client import (
            ArraysToArraysServiceClient,
            _is_retryable,
        )

        service = ArraysToArraysService(_slow_compute(0.05))
        port = _free_port()

        async def main():
            server = await serve(None, "127.0.0.1", port, service=service)
            try:
                client = ArraysToArraysServiceClient(
                    "127.0.0.1", port, retries=0
                )
                reqs = [(np.full(2, float(i)),) for i in range(8)]

                async def drain_soon():
                    await asyncio.sleep(0.12)
                    await service.drain(timeout_s=10)

                drainer = asyncio.ensure_future(drain_soon())
                results, exc = await client.evaluate_many_partial_async(
                    reqs, window=2, batch=False
                )
                await drainer
                served = [i for i, r in enumerate(results) if r is not None]
                for i in served:
                    np.testing.assert_array_equal(
                        results[i][0], 2.0 * np.full(2, float(i))
                    )
                if exc is None:
                    assert len(served) == len(reqs)
                else:
                    # the drain cut the window: the error must be the
                    # RETRYABLE kind (a pool would fail the tail over)
                    assert _is_retryable(exc), exc
                    assert len(served) < len(reqs)
                    import grpc

                    if isinstance(exc, grpc.aio.AioRpcError):
                        assert exc.code() == grpc.StatusCode.UNAVAILABLE
            finally:
                await server.stop(None)

        asyncio.run(main())

    def test_pool_fails_over_cleanly_across_drain(self):
        """Two replicas; one drains mid-window: every request still
        gets exactly one correct reply (the tail re-queues onto the
        survivor), and the drained node refuses direct work until
        undrained."""
        from pytensor_federated_tpu.routing import (
            NodePool,
            PooledArraysClient,
        )

        service_a = ArraysToArraysService(_slow_compute(0.02))
        service_b = ArraysToArraysService(_slow_compute(0.02))
        port_a, port_b = _free_port(), _free_port()

        async def main():
            server_a = await serve(
                None, "127.0.0.1", port_a, service=service_a
            )
            server_b = await serve(
                None, "127.0.0.1", port_b, service=service_b
            )
            pool = NodePool(
                [("127.0.0.1", port_a), ("127.0.0.1", port_b)],
                breaker_kwargs=dict(failure_threshold=3, backoff_s=0.2),
            )
            client = PooledArraysClient(pool)
            try:
                n = 24
                reqs = [(np.full(2, float(i)),) for i in range(n)]

                async def drain_soon():
                    await asyncio.sleep(0.05)
                    await service_a.drain(timeout_s=10)

                drainer = asyncio.ensure_future(drain_soon())
                results = await asyncio.wait_for(
                    client.evaluate_many_async(reqs, window=4),
                    timeout=60,
                )
                await drainer
                assert len(results) == n
                for i, out in enumerate(results):
                    assert out is not None, f"request {i} lost in drain"
                    np.testing.assert_array_equal(
                        out[0], 2.0 * np.full(2, float(i))
                    )
                # the drained node refuses new work...
                from pytensor_federated_tpu.service.client import (
                    ArraysToArraysServiceClient,
                )
                import grpc

                pinned = ArraysToArraysServiceClient(
                    "127.0.0.1", port_a, retries=0, use_stream=False
                )
                with pytest.raises(grpc.aio.AioRpcError) as ei:
                    await pinned.evaluate_async(np.ones(2))
                assert ei.value.code() == grpc.StatusCode.UNAVAILABLE
                # ...and serves again after undrain.
                service_a.undrain()
                out = await pinned.evaluate_async(np.ones(2))
                np.testing.assert_array_equal(out[0], 2.0 * np.ones(2))
            finally:
                pool.close()
                await server_a.stop(None)
                await server_b.stop(None)

        asyncio.run(main())
