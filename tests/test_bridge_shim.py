"""Execute the bridge glue (pytensor_ops.py + fusion.py) under the shim.

These tests drive the REAL glue modules — imported under the in-repo
fake pytensor (tests/pytensor_shim.py) — through the flows the
reference exercises in its own CI:

- Op construction / perform numerics / raw-scalar coercion
  (reference: test_wrapper_ops.py:80-118, 284-289);
- the symbolic ``.grad`` bridge incl. the second-order rejection
  (reference: wrapper_ops.py:119-132);
- the fusion rewrite end-to-end on a function graph, with graph-shape
  assertions and numeric equality (reference: test_op_async.py:122-150)
  and the wall-clock max-not-sum contract (test_op_async.py:153-195);
- the pickle/rebuild path of the fused op;
- the optdb and jax_funcify registrations.

They prove OUR-side logic executes correctly against the pinned API
shapes — NOT compatibility with real pytensor (see the shim docstring
for exactly what is pinned from the reference's usage).
"""

import pickle
import time

import numpy as np
import pytest

from pytensor_shim import bridge_under_shim


@pytest.fixture()
def env():
    with bridge_under_shim() as ns:
        yield ns


def _quad_logp_grad(target):
    """logp(x) = -sum((x-target)^2), grad = -2(x-target) — closed-form
    oracle used throughout."""

    def fn(*inputs):
        logp = 0.0
        grads = []
        for x in inputs:
            x = np.asarray(x, dtype=np.float64)
            logp -= np.sum((x - target) ** 2)
            grads.append(-2.0 * (x - target))
        return np.asarray(logp), grads

    return fn


def _quad_at_zero(*inputs):
    return _quad_logp_grad(0.0)(*inputs)


def _quad_at_one(*inputs):
    return _quad_logp_grad(1.0)(*inputs)


# ---------------------------------------------------------------------------
# FederatedLogpGradOp
# ---------------------------------------------------------------------------


class TestLogpGradOp:
    def test_make_node_shapes_and_dtypes(self, env):
        op = env.pytensor_ops.FederatedLogpGradOp(_quad_logp_grad(0.0))
        x = env.TensorType("float32", (3,))()
        node = op.make_node(x, 2)  # raw python int coerces (issue #24)
        assert len(node.inputs) == 2
        assert len(node.outputs) == 3  # logp + one grad per input
        assert node.outputs[0].type.shape == ()
        assert node.outputs[1].type.dtype == "float32"
        # int input's grad upcasts to floatX, not int (core policy)
        assert node.outputs[2].type.dtype == env.config.floatX

    def test_perform_numerics(self, env):
        op = env.pytensor_ops.FederatedLogpGradOp(_quad_logp_grad(1.0))
        x = env.TensorType("float64", (3,))()
        logp, g = op(x)
        xv = np.array([0.0, 1.0, 3.0])
        lv, gv = env.eval_graph([logp, g], {x: xv})
        np.testing.assert_allclose(lv, -(1.0 + 0.0 + 4.0))
        np.testing.assert_allclose(gv, -2.0 * (xv - 1.0))

    def test_grad_is_scaled_product(self, env):
        """``.grad`` returns ``g_logp * grad_i`` evaluated through the
        re-applied op (reference wrapper_ops.py:119-132)."""
        op = env.pytensor_ops.FederatedLogpGradOp(_quad_logp_grad(0.0))
        x = env.TensorType("float64", (2,))()
        outputs = op(x)
        g_logp = env.scalar()
        disconnected = env.DisconnectedType()()
        (gx,) = op.grad([x], [g_logp, disconnected])
        xv = np.array([1.0, -2.0])
        (gxv,) = env.eval_graph([gx], {x: xv, g_logp: np.asarray(3.0)})
        np.testing.assert_allclose(gxv, 3.0 * (-2.0 * xv))

    def test_second_order_rejected(self, env):
        op = env.pytensor_ops.FederatedLogpGradOp(_quad_logp_grad(0.0))
        x = env.TensorType("float64", (2,))()
        op(x)
        g_logp = env.scalar()
        connected = env.TensorType("float64", (2,))()  # NOT disconnected
        with pytest.raises(NotImplementedError, match="second-order"):
            op.grad([x], [g_logp, connected])

    def test_connection_pattern(self, env):
        op = env.pytensor_ops.FederatedLogpGradOp(_quad_logp_grad(0.0))
        x = env.TensorType("float64", (2,))()
        y = env.TensorType("float64", ())()
        node = op.make_node(x, y)
        assert op.connection_pattern(node) == [
            [True, False, False],
            [True, False, False],
        ]

    def test_scalar_logp_contract(self, env):
        def bad(*inputs):
            return np.ones(3), [np.zeros_like(i) for i in inputs]

        op = env.pytensor_ops.FederatedLogpGradOp(bad)
        x = env.TensorType("float64", (2,))()
        logp, _ = op(x)
        with pytest.raises(ValueError, match="scalar"):
            env.eval_graph([logp], {x: np.zeros(2)})

    def test_grad_arity_contract(self, env):
        def bad(*inputs):
            return np.asarray(0.0), []  # no grads for one input

        op = env.pytensor_ops.FederatedLogpGradOp(bad)
        x = env.TensorType("float64", (2,))()
        logp, _ = op(x)
        with pytest.raises(ValueError, match="grads"):
            env.eval_graph([logp], {x: np.zeros(2)})

    def test_federated_potential_front_door(self, env):
        x = env.TensorType("float64", (2,))()
        logp = env.pytensor_ops.federated_potential(
            _quad_logp_grad(0.0), x
        )
        assert isinstance(
            logp.owner.op, env.pytensor_ops.FederatedLogpGradOp
        )
        assert logp.index == 0


# ---------------------------------------------------------------------------
# FederatedLogpOp / FederatedArraysToArraysOp
# ---------------------------------------------------------------------------


class TestOtherOps:
    def test_logp_op(self, env):
        op = env.pytensor_ops.FederatedLogpOp(
            lambda x: np.asarray(-np.sum(x**2))
        )
        x = env.TensorType("float64", (3,))()
        logp = op(x)
        (lv,) = env.eval_graph([logp], {x: np.array([1.0, 2.0, 3.0])})
        np.testing.assert_allclose(lv, -14.0)

    def test_arrays_op_output_types_and_arity(self, env):
        op = env.pytensor_ops.FederatedArraysToArraysOp(
            lambda a, b: [a + b, a * b],
            [env.TensorType("float64", (2,)), env.TensorType("float64", (2,))],
        )
        a = env.TensorType("float64", (2,))()
        b = env.TensorType("float64", (2,))()
        s, p = op(a, b)
        sv, pv = env.eval_graph(
            [s, p], {a: np.array([1.0, 2.0]), b: np.array([3.0, 4.0])}
        )
        np.testing.assert_allclose(sv, [4.0, 6.0])
        np.testing.assert_allclose(pv, [3.0, 8.0])

        bad = env.pytensor_ops.FederatedArraysToArraysOp(
            lambda a: [a, a, a],
            [env.TensorType("float64", (2,))],
        )
        out = bad(a)
        with pytest.raises(ValueError, match="outputs"):
            env.eval_graph([out], {a: np.zeros(2)})

    def test_distinct_instances_never_equal(self, env):
        """No __props__: two ops over different fns must not compare
        equal (merge-optimizer safety, reference wrapper_ops.py:20-23)."""
        mk = env.pytensor_ops.FederatedLogpOp
        assert mk(lambda x: x) != mk(lambda x: x)


# ---------------------------------------------------------------------------
# jax_funcify dispatch
# ---------------------------------------------------------------------------


class TestJaxDispatch:
    def test_member_dispatch_matches_perform(self, env):
        import jax.numpy as jnp

        def jax_fn(x):
            return -jnp.sum((x - 1.0) ** 2), [-2.0 * (x - 1.0)]

        op = env.pytensor_ops.FederatedLogpGradOp(
            _quad_logp_grad(1.0), jax_fn=jax_fn
        )
        x = env.TensorType("float64", (3,))()
        logp, g = op(x)
        fn = env.compile_graph_to_jax([logp, g], [x], env.jax_funcify)
        xv = np.array([0.0, 1.0, 3.0])
        lv, gv = fn(jnp.asarray(xv))
        pl, pg = env.eval_graph([logp, g], {x: xv})
        np.testing.assert_allclose(np.asarray(lv), pl)
        np.testing.assert_allclose(np.asarray(gv), pg)

    def test_missing_jax_fn_is_loud(self, env):
        op = env.pytensor_ops.FederatedLogpOp(lambda x: np.asarray(0.0))
        with pytest.raises(NotImplementedError, match="FederatedLogpOp"):
            env.jax_funcify(op)

    def test_jittable_end_to_end(self, env):
        import jax
        import jax.numpy as jnp

        def jax_fn(x):
            return -jnp.sum(x**2)

        op = env.pytensor_ops.FederatedLogpOp(
            lambda x: np.asarray(-np.sum(x**2)), jax_fn=jax_fn
        )
        x = env.TensorType("float64", (3,))()
        logp = op(x)
        fn = env.compile_graph_to_jax([logp], [x], env.jax_funcify)
        jitted = jax.jit(lambda xv: fn(xv)[0])
        np.testing.assert_allclose(
            float(jitted(jnp.array([1.0, 2.0, 3.0]))), -14.0
        )


# ---------------------------------------------------------------------------
# The fusion rewrite, end-to-end on a FunctionGraph
# ---------------------------------------------------------------------------


def _build_two_member_graph(env, delay=0.0):
    """Two INDEPENDENT federated applies + a downstream consumer
    combining their logps — the reference's manual-rewrite test graph
    shape (test_op_async.py:122-150)."""

    def slow(target):
        base = _quad_logp_grad(target)

        def fn(*inputs):
            if delay:
                time.sleep(delay)
            return base(*inputs)

        return fn

    opA = env.pytensor_ops.FederatedLogpGradOp(slow(0.0))
    opB = env.pytensor_ops.FederatedLogpGradOp(slow(1.0))
    x = env.TensorType("float64", (2,))()
    y = env.TensorType("float64", (2,))()
    logpA, gA = opA(x)
    logpB, gB = opB(y)
    total = logpA + logpB
    fg = env.FunctionGraph([x, y], [total, gA, gB])
    return fg, (x, y)


class TestFusionRewrite:
    def test_rewrite_fuses_independent_applies(self, env):
        fg, (x, y) = _build_two_member_graph(env)
        xv, yv = np.array([1.0, 2.0]), np.array([3.0, 4.0])
        before = env.eval_graph(fg.outputs, {x: xv, y: yv})

        env.fusion.FederatedFusionRewriter().rewrite(fg)

        fused = [
            n
            for n in fg.toposort()
            if isinstance(n.op, env.fusion.ParallelFederatedOp)
        ]
        assert len(fused) == 1, "expected exactly one fused apply"
        assert len(fused[0].op.members) == 2
        # No federated member applies survive outside the fused one.
        leftovers = [
            n
            for n in fg.toposort()
            if isinstance(
                n.op, env.pytensor_ops.FederatedLogpGradOp
            )
        ]
        assert not leftovers
        after = env.eval_graph(fg.outputs, {x: xv, y: yv})
        for b, a in zip(before, after):
            np.testing.assert_allclose(a, b)

    def test_rewrite_leaves_dependent_chain_alone(self, env):
        """B consumes A's output: fusing would deadlock/cycle — the
        grouping must keep them separate applies."""
        opA = env.pytensor_ops.FederatedLogpGradOp(_quad_logp_grad(0.0))
        opB = env.pytensor_ops.FederatedLogpGradOp(_quad_logp_grad(1.0))
        x = env.TensorType("float64", (2,))()
        logpA, gA = opA(x)
        logpB, gB = opB(gA)  # dependent!
        fg = env.FunctionGraph([x], [logpB])
        env.fusion.FederatedFusionRewriter().rewrite(fg)
        fused = [
            n
            for n in fg.toposort()
            if isinstance(n.op, env.fusion.ParallelFederatedOp)
        ]
        assert not fused
        xv = np.array([0.5, -0.5])
        (lv,) = env.eval_graph([fg.outputs[0]], {x: xv})
        gAv = -2.0 * xv
        np.testing.assert_allclose(lv, -np.sum((gAv - 1.0) ** 2))

    def test_fused_wallclock_is_max_not_sum(self, env):
        """The reference's load-bearing proof (test_op_async.py:153-195):
        two 0.35 s members through the fused perform must take ~0.35 s,
        not ~0.7 s."""
        fg, (x, y) = _build_two_member_graph(env, delay=0.35)
        env.fusion.FederatedFusionRewriter().rewrite(fg)
        xv, yv = np.zeros(2), np.zeros(2)
        env.eval_graph(fg.outputs, {x: xv, y: yv})  # warm the pool
        t0 = time.perf_counter()
        env.eval_graph(fg.outputs, {x: xv, y: yv})
        wall = time.perf_counter() - t0
        assert wall < 0.6, f"members ran sequentially: {wall:.3f}s"

    def test_replace_requires_validate_feature(self, env):
        """add_requirements is load-bearing: replacement without the
        ReplaceValidate feature must refuse."""
        fg, (x, y) = _build_two_member_graph(env)
        rewriter = env.fusion.FederatedFusionRewriter()
        with pytest.raises(RuntimeError, match="ReplaceValidate"):
            rewriter.apply(fg)  # no add_requirements first

    def test_fused_jax_path_matches_perform(self, env):
        import jax.numpy as jnp

        def jax_fn(target):
            def fn(x):
                return -jnp.sum((x - target) ** 2), [-2.0 * (x - target)]

            return fn

        opA = env.pytensor_ops.FederatedLogpGradOp(
            _quad_logp_grad(0.0), jax_fn=jax_fn(0.0)
        )
        opB = env.pytensor_ops.FederatedLogpGradOp(
            _quad_logp_grad(1.0), jax_fn=jax_fn(1.0)
        )
        x = env.TensorType("float64", (2,))()
        y = env.TensorType("float64", (2,))()
        logpA, gA = opA(x)
        logpB, gB = opB(y)
        fg = env.FunctionGraph([x, y], [logpA + logpB, gA, gB])
        env.fusion.FederatedFusionRewriter().rewrite(fg)
        xv, yv = np.array([1.0, 2.0]), np.array([3.0, 4.0])
        perform_vals = env.eval_graph(fg.outputs, {x: xv, y: yv})
        fn = env.compile_graph_to_jax(fg.outputs, [x, y], env.jax_funcify)
        jax_vals = fn(jnp.asarray(xv), jnp.asarray(yv))
        for p, j in zip(perform_vals, jax_vals):
            np.testing.assert_allclose(np.asarray(j), p)

    def test_fused_pickle_roundtrip(self, env):
        """__getstate__ drops the member templates and executor pool;
        both must rebuild lazily on the unpickled op (the cross-process
        compile-cache path).  Members wrap MODULE-LEVEL compute fns —
        closures don't pickle, and real deployments ship importable
        fns for exactly this reason."""
        opA = env.pytensor_ops.FederatedLogpGradOp(_quad_at_zero)
        opB = env.pytensor_ops.FederatedLogpGradOp(_quad_at_one)
        x = env.TensorType("float64", (2,))()
        y = env.TensorType("float64", (2,))()
        logpA, gA = opA(x)
        logpB, gB = opB(y)
        fg = env.FunctionGraph([x, y], [logpA + logpB, gA, gB])
        env.fusion.FederatedFusionRewriter().rewrite(fg)
        (fused_node,) = [
            n
            for n in fg.toposort()
            if isinstance(n.op, env.fusion.ParallelFederatedOp)
        ]
        op2 = pickle.loads(pickle.dumps(fused_node.op))
        assert not hasattr(op2, "_member_nodes")
        assert not hasattr(op2, "_pool")
        x2 = env.TensorType("float64", (2,))()
        y2 = env.TensorType("float64", (2,))()
        outs = op2(x2, y2)
        xv, yv = np.array([1.0, 2.0]), np.array([3.0, 4.0])
        vals = env.eval_graph(outs, {x2: xv, y2: yv})
        np.testing.assert_allclose(vals[0], -np.sum(xv**2))
        np.testing.assert_allclose(vals[2], -np.sum((yv - 1.0) ** 2))

    def test_fused_input_arity_check(self, env):
        op = env.fusion.ParallelFederatedOp(
            [env.pytensor_ops.FederatedLogpOp(lambda x: np.asarray(0.0))],
            [1],
            [1],
        )
        a = env.TensorType("float64", (2,))()
        b = env.TensorType("float64", (2,))()
        with pytest.raises(ValueError, match="inputs"):
            op.make_node(a, b)

    def test_optdb_registration_matches_reference_slot(self, env):
        """Importing fusion registers at the reference's optdb slot
        (op_async.py:228-234): fast_run tag, position 90, idempotent."""
        assert "federated_parallel_fusion" in env.optdb
        rec = env.optdb.query("federated_parallel_fusion")
        assert "fast_run" in rec["tags"]
        assert rec["position"] == 90
        assert isinstance(
            rec["obj"], env.fusion.FederatedFusionRewriter
        )
