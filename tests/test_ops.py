"""Op contract tests (reference: test_wrapper_ops.py Op-contract section).

Uses an in-process quadratic model with hand-derived gradients as ground
truth — the reference's ``dummy_quadratic_model`` pattern
(reference: test_wrapper_ops.py:34-45).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu import (
    ArraysToArraysOp,
    LogpGradOp,
    LogpOp,
    blackbox_compute,
    blackbox_logp_grad,
    from_logp_fn,
)


def quad_logp(x, y):
    return -jnp.sum((x - 1.0) ** 2) - jnp.sum((y + 2.0) ** 2)


def quad_logp_grad(x, y):
    return quad_logp(x, y), (-2 * (x - 1.0), -2 * (y + 2.0))


def test_arrays_to_arrays_op_coerces_ints():
    """Raw python ints must coerce ('issue #24' regression,
    reference: test_wrapper_ops.py:284-289)."""
    op = ArraysToArraysOp(lambda a, b: [a + b, a * b])
    s, p = op(2, 3)
    np.testing.assert_allclose(s, 5)
    np.testing.assert_allclose(p, 6)


def test_logp_op_scalar_contract():
    op = LogpOp(quad_logp)
    out = op(jnp.zeros(3), jnp.zeros(2))
    assert out.shape == ()
    np.testing.assert_allclose(out, -3.0 - 8.0)


def test_logp_op_rejects_nonscalar():
    op = LogpOp(lambda x: x)
    with pytest.raises(ValueError, match="scalar"):
        op(jnp.zeros(3))


def test_logp_grad_op_outputs():
    op = LogpGradOp(quad_logp_grad)
    x, y = jnp.array([0.0, 2.0]), jnp.array(1.0)
    logp, (gx, gy) = op(x, y)
    np.testing.assert_allclose(logp, -2.0 - 9.0)
    np.testing.assert_allclose(gx, [2.0, -2.0])
    np.testing.assert_allclose(gy, -6.0)


def test_logp_grad_op_vjp_matches_hand_gradients():
    """jax.grad through the op must use the forward-supplied grads
    (reference: test_wrapper_ops.py:224-237)."""
    op = LogpGradOp(quad_logp_grad)

    def scalar_loss(x, y):
        logp, _ = op(x, y)
        return 3.0 * logp  # non-trivial cotangent

    x, y = jnp.array([0.5, -1.0]), jnp.array(0.25)
    gx, gy = jax.grad(scalar_loss, argnums=(0, 1))(x, y)
    np.testing.assert_allclose(gx, 3.0 * (-2 * (x - 1.0)), rtol=1e-6)
    np.testing.assert_allclose(gy, 3.0 * (-2 * (y + 2.0)), rtol=1e-6)


def test_logp_grad_op_under_jit_and_grad():
    op = LogpGradOp(quad_logp_grad)
    g = jax.jit(jax.grad(lambda x: op(x, jnp.float32(0.0))[0]))
    np.testing.assert_allclose(g(jnp.float32(0.0)), 2.0, rtol=1e-6)


def test_from_logp_fn_derives_grads():
    op = from_logp_fn(quad_logp)
    x, y = jnp.array([2.0]), jnp.array(0.0)
    logp, (gx, gy) = op(x, y)
    ref_logp, (ref_gx, ref_gy) = quad_logp_grad(x, y)
    np.testing.assert_allclose(logp, ref_logp)
    np.testing.assert_allclose(gx, ref_gx)
    np.testing.assert_allclose(gy, ref_gy)


# ---- blackbox (host callback) path ----


def test_blackbox_compute_roundtrip():
    """Host numpy fn runs under jit with a declared out signature."""

    def host(a, b):
        return [np.asarray(a) + np.asarray(b)]

    spec = (jax.ShapeDtypeStruct((3,), jnp.float32),)
    fn = blackbox_compute(host, spec)
    out = jax.jit(lambda a, b: fn(a, b)[0])(jnp.ones(3), jnp.full(3, 2.0))
    np.testing.assert_allclose(out, 3.0)


def test_blackbox_logp_grad_differentiable():
    """A pure-NumPy node (the reference's true federated case) is
    differentiable via forward-supplied grads."""

    def host(x):
        x = np.asarray(x)
        return -np.sum((x - 3.0) ** 2), [-2.0 * (x - 3.0)]

    spec = (jax.ShapeDtypeStruct((2,), jnp.float32),)
    op = blackbox_logp_grad(host, spec)
    x = jnp.array([1.0, 5.0])
    logp, (gx,) = op(x)
    np.testing.assert_allclose(logp, -8.0)
    np.testing.assert_allclose(gx, [4.0, -4.0])
    g = jax.grad(lambda x: op(x)[0])(x)
    np.testing.assert_allclose(g, [4.0, -4.0])
    g_jit = jax.jit(jax.grad(lambda x: op(x)[0]))(x)
    np.testing.assert_allclose(g_jit, [4.0, -4.0])


class TestSecondOrderContract:
    """The federated boundary is first-order only, and violations fail
    LOUDLY (reference: wrapper_ops.py:123-125 raises; round-1 VERDICT
    flagged the silent-zero here).  ``symbolic_zeros=True`` lets the
    VJP distinguish "nothing differentiates the grad outputs" (fine)
    from "a connected cotangent reached them" (error)."""

    def _op(self):
        from pytensor_federated_tpu.ops.ops import LogpGradOp

        def lg(a, b):
            logp = -((a - 1.0) ** 2) - 2.0 * jnp.sum((b - 3.0) ** 2)
            return logp, [-2.0 * (a - 1.0), -4.0 * (b - 3.0)]

        return LogpGradOp(lg)

    def test_grad_wrt_grads_output_raises(self):
        op = self._op()
        b = jnp.asarray([1.0, 2.0])
        with pytest.raises(NotImplementedError, match="first-order"):
            jax.grad(lambda a: op(a, b)[1][0])(jnp.asarray(0.5))

    def test_reverse_over_reverse_hessian_raises(self):
        op = self._op()
        b = jnp.asarray([1.0, 2.0])
        with pytest.raises(NotImplementedError, match="first-order"):
            jax.jacrev(jax.jacrev(lambda a: op(a, b)[0]))(jnp.asarray(0.5))

    def test_first_order_unaffected(self):
        op = self._op()
        b = jnp.asarray([1.0, 2.0])
        g = jax.jit(jax.grad(lambda a: op(a, b)[0]))(jnp.asarray(0.5))
        np.testing.assert_allclose(g, 1.0)

    def test_stop_gradient_escape_hatch(self):
        # Using the grads output as *data* is legal via stop_gradient.
        op = self._op()
        b = jnp.asarray([1.0, 2.0])
        g = jax.grad(
            lambda a: jax.lax.stop_gradient(op(a, b)[1][0]) * a
        )(jnp.asarray(0.5))
        np.testing.assert_allclose(g, 1.0)
