"""Simulation-based calibration harness (samplers/sbc.py).

Positive control: NUTS on a conjugate normal model is calibrated, so
ranks must pass the chi-square uniformity screen.  Negative control:
ranking against deliberately over-concentrated draws must FAIL the
same screen — otherwise the test tests nothing.
"""

import jax
import jax.numpy as jnp
import numpy as np

from pytensor_federated_tpu.samplers.sbc import (
    SBCResult,
    sbc_ranks,
    sbc_uniformity,
)

N_OBS = 16


def prior_sample(key):
    return {"mu": jax.random.normal(key)}


def simulate(key, params):
    return params["mu"] + jax.random.normal(key, (N_OBS,))


def logp(params, data):
    mu = params["mu"]
    return -0.5 * mu**2 - 0.5 * jnp.sum((data - mu) ** 2)


def test_calibrated_sampler_passes_uniformity():
    res = sbc_ranks(
        prior_sample,
        simulate,
        logp,
        key=jax.random.PRNGKey(0),
        n_sims=128,
        num_warmup=150,
        num_samples=128,
        thin=4,
    )
    assert res.ranks.shape == (128, 1)
    assert res.n_levels == 33
    r = np.asarray(res.ranks)
    assert r.min() >= 0 and r.max() <= 32
    stats, dof = sbc_uniformity(res)
    assert stats[0] < dof + 4.0 * np.sqrt(2.0 * dof), stats


def test_negative_control_fails_uniformity():
    # Over-concentrated "posterior": shrink calibrated ranks' spread by
    # faking draws that hug the posterior mean — theta* lands in the
    # tails too often and the rank histogram U-shapes.
    rng = np.random.default_rng(0)
    n_sims, levels = 128, 33
    # U-shaped ranks: half at the bottom bins, half at the top
    bad = np.where(
        rng.uniform(size=n_sims) < 0.5,
        rng.integers(0, 4, size=n_sims),
        rng.integers(levels - 4, levels, size=n_sims),
    )[:, None]
    res = SBCResult(
        ranks=jnp.asarray(bad), n_levels=levels, param_names=["mu"]
    )
    stats, dof = sbc_uniformity(res)
    assert stats[0] > dof + 4.0 * np.sqrt(2.0 * dof)


def test_thin_larger_than_samples_rejected():
    import pytest

    with pytest.raises(ValueError, match="no draws"):
        sbc_ranks(
            prior_sample,
            simulate,
            logp,
            key=jax.random.PRNGKey(0),
            n_sims=2,
            num_samples=2,
            thin=4,
        )


def test_uniformity_unequal_bin_coverage_not_inflated():
    """33 integer levels over 8 bins: bins cover 4 vs 5 levels.  A
    PERFECTLY uniform rank sample (every level equally often) must
    score a chi-square of ~0 — the expected counts must be
    proportional to each bin's integer-level coverage, not n_sims/8
    (round-3 ADVICE finding)."""
    levels = 33
    reps = 4
    ranks = np.tile(np.arange(levels), reps)[:, None]
    res = SBCResult(
        ranks=jnp.asarray(ranks), n_levels=levels, param_names=["mu"]
    )
    stats, dof = sbc_uniformity(res, n_bins=8)
    assert stats[0] == 0.0


def test_uniformity_fewer_levels_than_bins_finite():
    """n_levels < n_bins: zero-coverage bins must be dropped (dof
    shrinks), not divided 0/0 into NaN."""
    ranks = np.tile(np.arange(5), 10)[:, None]
    res = SBCResult(
        ranks=jnp.asarray(ranks), n_levels=5, param_names=["mu"]
    )
    stats, dof = sbc_uniformity(res, n_bins=8)
    assert np.isfinite(stats).all()
    assert stats[0] == 0.0
    assert dof == 4  # 5 occupied bins - 1
