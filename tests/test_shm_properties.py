"""Property-based arena-codec tests (hypothesis) — ISSUE 9 satellite.

The zero-copy lane's loud-failure surface, explored exhaustively:
random corruption of descriptor fields (slot offset, delta, length,
generation, dtype bits), random byte flips anywhere in a doorbell
frame, and torn/truncated arena slots must ALL surface as
:class:`WireError` (or, for frame-header damage, the frame-level loud
classifications) — never a partially-decoded, torn, or silently wrong
array.  The payload-integrity property the TCP wire gets from length
prefixes, the arena gets from the generation protocol; these tests are
its pin.
"""

import numpy as np
import pytest

pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402
from hypothesis.extra import numpy as hnp  # noqa: E402

from pytensor_federated_tpu.service.arena import Arena  # noqa: E402
from pytensor_federated_tpu.service.npwire import WireError  # noqa: E402
from pytensor_federated_tpu.service.shm import (  # noqa: E402
    _KIND_EVAL,
    decode_descs,
    decode_frame,
    encode_descs,
    encode_frame,
)

COMMON = settings(max_examples=50, deadline=None)

_payloads = st.lists(
    st.binary(min_size=0, max_size=512), min_size=1, max_size=4
)


@pytest.fixture(scope="module")
def arena():
    a = Arena.create(1 << 20)
    yield a
    a.close(unlink=True)


@COMMON
@given(bufs=_payloads)
def test_arena_roundtrip_any_payloads(arena, bufs):
    slot, gen, deltas = arena.write_many(bufs)
    try:
        for buf, delta in zip(bufs, deltas):
            assert arena.read_bytes(slot, delta, len(buf), gen) == buf
    finally:
        arena.free(slot)


@COMMON
@given(
    bufs=_payloads,
    field=st.integers(0, 3),
    bump=st.integers(1, 2**32 - 1),
)
def test_corrupt_descriptor_field_is_loud(arena, bufs, field, bump):
    """Perturbing ANY descriptor field (slot, delta, length,
    generation) yields WireError or the exact original bytes — never
    torn or silently wrong data."""
    slot, gen, deltas = arena.write_many(bufs)
    try:
        idx = len(bufs) - 1
        desc = [slot, deltas[idx], len(bufs[idx]), gen]
        desc[field] = (desc[field] + bump) % (2**64 if field != 1 else 2**32)
        try:
            data = arena.read_bytes(*desc)
        except WireError:
            return  # loud: the contract
        # The only non-loud outcome allowed: the perturbed descriptor
        # still passed FULL validation against the live slot — which
        # requires the original slot and generation (both are unique),
        # i.e. only a delta/length perturbation that stays inside this
        # slot's own validated payload can survive.  The read must
        # then be stable (deterministic bytes, no tearing).
        s, d, ln, g = desc
        assert g == gen and s == slot
        assert len(data) == ln
        assert data == arena.read_bytes(*desc)
    finally:
        arena.free(slot)


@COMMON
@given(
    payload=st.binary(min_size=1, max_size=256),
    cut=st.integers(0, 300),
)
def test_truncated_slot_is_loud(arena, payload, cut):
    """A slot whose tail generation never landed (torn write) must
    read as WireError for every in-range descriptor."""
    slot, gen, deltas = arena.write_many([payload])
    try:
        arena.scribble_tail(slot)
        with pytest.raises(WireError):
            arena.read_bytes(slot, 0, min(cut, len(payload)), gen)
    finally:
        arena.free(slot)


@COMMON
@given(stale=st.integers(1, 2**32))
def test_stale_generation_is_loud(arena, stale):
    slot, gen, _d = arena.write_many([b"live"])
    try:
        with pytest.raises(WireError):
            arena.read_view(slot, 0, 4, gen + stale)
    finally:
        arena.free(slot)


_dtypes = st.one_of(
    hnp.integer_dtypes(endianness="="),
    hnp.floating_dtypes(endianness="=", sizes=(32, 64)),
    st.just(np.dtype("bool")),
)


@COMMON
@given(
    descs=st.lists(
        st.tuples(
            st.integers(0, 2**40),
            st.integers(0, 2**30),
            st.integers(0, 2**40),
            st.integers(0, 2**40),
            _dtypes,
            st.lists(st.integers(0, 64), max_size=3).map(tuple),
        ),
        max_size=4,
    )
)
def test_desc_block_roundtrip(descs):
    buf = encode_descs(descs)
    out, off = decode_descs(buf, 0)
    assert off == len(buf)
    assert out == descs


@COMMON
@given(
    descs=st.lists(
        st.tuples(
            st.integers(0, 2**30),
            st.integers(0, 2**20),
            st.integers(0, 2**30),
            st.integers(0, 2**30),
            _dtypes,
            st.lists(st.integers(0, 8), max_size=2).map(tuple),
        ),
        min_size=1,
        max_size=3,
    ),
    data=st.data(),
)
def test_mutated_frame_never_partial(descs, data):
    """Flip any byte (or truncate anywhere) in a full EVAL doorbell
    frame: the decode path either raises WireError or yields
    structurally valid descriptors — never a crash of another type,
    never a half-parsed success that mixes frames."""
    body = np.uint64(7).tobytes() + encode_descs(descs)
    frame = encode_frame(_KIND_EVAL, b"u" * 16, body)
    mode = data.draw(st.sampled_from(["flip", "truncate"]))
    if mode == "flip":
        pos = data.draw(st.integers(0, len(frame) - 1))
        mutated = bytearray(frame)
        mutated[pos] ^= data.draw(st.integers(1, 255))
        mutated = bytes(mutated)
    else:
        mutated = frame[: data.draw(st.integers(0, len(frame) - 1))]
    try:
        kind, uid, err, tid, _dl, _part, _ver, off, eff = decode_frame(mutated)
        parsed, _end = decode_descs(eff, off + 8)
    except WireError:
        return  # loud: the contract
    # Non-loud survival is allowed only when the mutation landed in
    # bytes the parse kept VALID (e.g. inside the opaque uuid, caught
    # later by correlation; or inside a slot/gen field, caught by the
    # arena's generation validation) — every parsed descriptor must
    # still be structurally sound, and no OTHER exception type may
    # escape (unclassified internals fail the property above by
    # propagating out of the try).
    for slot, delta, length, gen, dtype, shape in parsed:
        assert isinstance(dtype, np.dtype)
        assert all(s >= 0 for s in shape)
