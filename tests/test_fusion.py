"""Automatic fan-out rewrite tests — skip cleanly without pytensor.

Mirrors the reference's optimizer coverage: default-mode compiles must
auto-parallelize independent federated applies (reference:
op_async.py:216-234 registration; wall-clock overlap proof at
test_op_async.py:153-195 — a 2-layer delay graph runs in ~max, not
~sum, of the member delays).
"""

import time

import numpy as np
import pytest

pytensor = pytest.importorskip("pytensor")

import pytensor.tensor as pt  # noqa: E402

from pytensor_federated_tpu.bridge import (  # noqa: E402
    FederatedLogpGradOp,
    ParallelFederatedOp,
)


def make_delay_logp_grad(delay, offset):
    def logp_grad(x):
        time.sleep(delay)
        return np.asarray(-((x - offset) ** 2).sum()), [-2.0 * (x - offset)]

    return logp_grad


def _compiled_ops(fn):
    return [node.op for node in fn.maker.fgraph.toposort()]


class TestFusionRewrite:
    def test_independent_applies_fuse_to_one_parallel_op(self):
        x = pt.vector("x")
        ops = [FederatedLogpGradOp(make_delay_logp_grad(0.0, float(k)))
               for k in range(3)]
        total = sum(op(x)[0] for op in ops)
        f = pytensor.function([x], total)
        fused = [
            op for op in _compiled_ops(f) if isinstance(op, ParallelFederatedOp)
        ]
        assert len(fused) == 1
        assert len(fused[0].members) == 3
        # numerics survive the rewrite
        xv = np.array([1.0, 2.0], dtype=x.dtype)
        expected = sum(-((xv - k) ** 2).sum() for k in range(3))
        np.testing.assert_allclose(f(xv), expected, rtol=1e-6)

    def test_dependent_applies_do_not_fuse(self):
        # B consumes A's logp: fusing them would deadlock/cycle.
        x = pt.vector("x")
        op_a = FederatedLogpGradOp(make_delay_logp_grad(0.0, 0.0))
        op_b = FederatedLogpGradOp(make_delay_logp_grad(0.0, 1.0))
        a_logp = op_a(x)[0]
        b_logp = op_b(pt.stack([a_logp, a_logp]))[0]
        f = pytensor.function([x], b_logp)
        assert not [
            op for op in _compiled_ops(f) if isinstance(op, ParallelFederatedOp)
        ]
        xv = np.array([0.5, -0.5], dtype=x.dtype)
        a = -(xv**2).sum()
        expected = -((np.array([a, a]) - 1.0) ** 2).sum()
        np.testing.assert_allclose(f(xv), expected, rtol=1e-6)

    def test_wall_clock_is_max_not_sum(self):
        # Reference pattern (test_op_async.py:153-195): two independent
        # 0.6 s delays plus one 0.3 s delay dependent on both.  Fused
        # layer-1 runs in ~0.6, total ~0.9; sequential would be ~1.5.
        # Margins sized for loaded CI runners (sleep overshoot +
        # dispatch overhead << the 0.6 s separating the two outcomes).
        x = pt.vector("x")
        op1 = FederatedLogpGradOp(make_delay_logp_grad(0.6, 0.0))
        op2 = FederatedLogpGradOp(make_delay_logp_grad(0.6, 1.0))
        op3 = FederatedLogpGradOp(make_delay_logp_grad(0.3, 2.0))
        layer1 = pt.stack([op1(x)[0], op2(x)[0]])
        total = op3(layer1)[0]
        f = pytensor.function([x], total)
        xv = np.array([0.1, 0.2], dtype=x.dtype)
        f(xv)  # warm (first call may pay lazy setup)
        t0 = time.perf_counter()
        f(xv)
        wall = time.perf_counter() - t0
        assert wall < 1.25, f"sequential-like wall {wall:.3f}s"
        assert wall > 0.85, f"impossibly fast wall {wall:.3f}s"

    def test_gradient_through_fused_graph(self):
        # The rewrite runs on the *compiled* fgraph after pt.grad built
        # the symbolic gradient, so grads must survive fusion intact.
        x = pt.vector("x")
        ops = [FederatedLogpGradOp(make_delay_logp_grad(0.0, float(k)))
               for k in (1, 3)]
        total = sum(op(x)[0] for op in ops)
        g = pytensor.function([x], pt.grad(total, x))
        xv = np.array([0.0, 2.0], dtype=x.dtype)
        expected = sum(-2.0 * (xv - k) for k in (1, 3))
        np.testing.assert_allclose(g(xv), expected, rtol=1e-6)
