"""Dense mass-matrix adaptation (samplers: hmc helpers + warmup).

A full covariance mass matrix is the standard cure for strongly
correlated posteriors (Stan's ``metric=dense_e``); the reference has no
sampler of its own, so this is net-new capability.  Pinned here:

- the polymorphic helpers reduce EXACTLY to the diagonal path when the
  matrix is diagonal;
- ``sample_momentum`` draws with covariance ``inv(inv_mass)``;
- dense warmup learns the correlation (off-diagonal mass) and the
  posterior moments match the closed form;
- dense beats diagonal on min-ESS for a high-correlation Gaussian —
  the reason the feature exists.
"""

import jax
import jax.numpy as jnp
import numpy as np

from pytensor_federated_tpu.samplers.hmc import (
    IntegratorState,
    kinetic_energy,
    leapfrog,
    mass_velocity,
    sample_momentum,
)
from pytensor_federated_tpu.samplers.mcmc import sample
from pytensor_federated_tpu.samplers.util import (
    welford_covariance,
    welford_init,
    welford_update,
)


def test_helpers_match_diagonal_path():
    d = 4
    diag = jnp.asarray([0.5, 2.0, 1.0, 3.0])
    mat = jnp.diag(diag)
    r = jnp.asarray([0.3, -1.2, 0.7, 0.1])
    np.testing.assert_allclose(
        np.asarray(mass_velocity(mat, r)),
        np.asarray(mass_velocity(diag, r)),
        rtol=1e-6,
    )
    np.testing.assert_allclose(
        float(kinetic_energy(r, mat)),
        float(kinetic_energy(r, diag)),
        rtol=1e-6,
    )
    # same key => identical momentum draws for diag-matrix vs vector
    key = jax.random.PRNGKey(0)
    x = jnp.zeros((d,))
    np.testing.assert_allclose(
        np.asarray(sample_momentum(key, x, mat)),
        np.asarray(sample_momentum(key, x, diag)),
        rtol=1e-5,
    )

    def lg(x):
        return -0.5 * jnp.sum(x**2), -x

    st = IntegratorState(x + 1.0, r, *lg(x + 1.0))
    end_m = leapfrog(lg, st, 0.1, mat)
    end_d = leapfrog(lg, st, 0.1, diag)
    np.testing.assert_allclose(
        np.asarray(end_m.x), np.asarray(end_d.x), rtol=1e-6
    )


def test_sample_momentum_covariance_dense():
    # r ~ N(0, M) with M = inv(inv_mass): check empirically.
    inv_mass = jnp.asarray([[2.0, 0.6], [0.6, 1.0]])
    want = np.linalg.inv(np.asarray(inv_mass))
    keys = jax.random.split(jax.random.PRNGKey(1), 20_000)
    draws = jax.vmap(
        lambda k: sample_momentum(k, jnp.zeros(2), inv_mass)
    )(keys)
    got = np.cov(np.asarray(draws).T)
    np.testing.assert_allclose(got, want, atol=0.05)


def _correlated_gaussian(rho=0.95):
    cov = jnp.asarray([[1.0, rho], [rho, 1.0]])
    prec = jnp.linalg.inv(cov)

    def logp(p):
        return -0.5 * p["x"] @ prec @ p["x"]

    return logp, np.asarray(cov)


def test_dense_warmup_learns_correlation_and_moments():
    logp, cov = _correlated_gaussian(0.95)
    res = sample(
        logp,
        {"x": jnp.zeros(2)},
        key=jax.random.PRNGKey(3),
        num_warmup=400,
        num_samples=400,
        num_chains=2,
        dense_mass=True,
    )
    assert res.inv_mass.shape == (2, 2, 2)
    # adapted inv_mass ~ posterior covariance: off-diagonal present
    # with the right sign and a sane magnitude.
    im = np.asarray(res.inv_mass).mean(axis=0)
    assert im[0, 1] > 0.3 * np.sqrt(im[0, 0] * im[1, 1])
    draws = np.asarray(res.samples["x"]).reshape(-1, 2)
    np.testing.assert_allclose(draws.mean(axis=0), 0.0, atol=0.15)
    got_cov = np.cov(draws.T)
    np.testing.assert_allclose(got_cov, cov, atol=0.25)
    summ = res.summary()
    assert float(np.max(np.asarray(summ["rhat"]["x"]))) < 1.1


def test_dense_beats_diag_on_min_ess():
    logp, _ = _correlated_gaussian(0.99)
    kw = dict(
        key=jax.random.PRNGKey(7),
        num_warmup=500,
        num_samples=500,
        num_chains=2,
    )
    res_dense = sample(logp, {"x": jnp.zeros(2)}, dense_mass=True, **kw)
    res_diag = sample(logp, {"x": jnp.zeros(2)}, **kw)

    def min_ess(res):
        return float(np.min(np.asarray(res.summary()["ess"]["x"])))

    assert min_ess(res_dense) > min_ess(res_diag)


def test_welford_dense_covariance():
    rng = np.random.default_rng(0)
    cov = np.array([[2.0, -0.8], [-0.8, 1.0]])
    xs = rng.multivariate_normal([1.0, -2.0], cov, size=4000).astype(
        np.float32
    )
    st = welford_init(2, dense=True)
    for x in xs[:500]:
        st = welford_update(st, jnp.asarray(x))
    got = np.asarray(welford_covariance(st, regularize=False))
    np.testing.assert_allclose(got, cov, atol=0.35)


def test_checkpointed_dense_mass_resume(tmp_path):
    # The resumable path supports dense mass too, and a dense run is
    # bit-identical across an interrupt/resume (the checkpoint carries
    # the (chains, dim, dim) mass).
    from pytensor_federated_tpu.checkpoint import sample_checkpointed

    logp, _ = _correlated_gaussian(0.9)
    kw = dict(
        key=jax.random.PRNGKey(11),
        num_warmup=100,
        num_samples=60,
        num_chains=2,
        checkpoint_every=20,
        dense_mass=True,
    )
    path = str(tmp_path / "ck.npz")
    res_full = sample_checkpointed(
        logp, {"x": jnp.zeros(2)}, checkpoint_path=path, **kw
    )
    assert res_full.inv_mass.shape == (2, 2, 2)
    # Resume from the final checkpoint: must reproduce bit-identically.
    res_resumed = sample_checkpointed(
        logp, {"x": jnp.zeros(2)}, checkpoint_path=path, **kw
    )
    np.testing.assert_array_equal(
        np.asarray(res_full.samples["x"]),
        np.asarray(res_resumed.samples["x"]),
    )
