"""Per-process driver for the REAL 2-process ``jax.distributed`` test.

Launched as ``python multihost_proc.py <proc_id> <nprocs> <coord>
<flag_dir>`` by tests/test_multihost_procs.py (a FILE on purpose:
spawned children need a ``__main__`` file, and the pytest process must
never itself call ``jax.distributed.initialize`` — CLAUDE.md).

Phase A (both processes): join the distributed runtime, build the
host-spanning mesh (``make_multihost_mesh``), evaluate one psum'd
federated logp+grad whose shards live on BOTH processes' devices, and
print the value — the reference's sum-of-node-replies crossing the
network (reference: service.py:75-115), here a gloo all-reduce over the
process boundary.

Phase B (survivor only): process 1 exits; the launcher confirms it is
dead and drops a flag file; process 0 then exercises
``remesh_after_failure`` on the now half-dead mesh and rebuilds the
federated evaluator over the shrunken mesh from host-resident data,
checking the SAME logp value comes back (reference failover analog:
service.py:408-416 drops the dead server and re-sends; SURVEY §7
step 5).

What phase B proves — precisely: SURVIVOR CONTINUITY.  After a real
peer death the surviving process's distributed runtime stays usable,
remesh returns promptly (no hang probing the dead half), and local
re-jit reproduces the value.  It does NOT prove dead-peer *detection*:
remesh is local-view (a peer's devices are never addressable from
here, dead or alive — see ``remesh_after_failure``'s docstring), so
the same 4-device mesh would come back with the peer still up.  The
kill is load-bearing for the continuity claim only.

Exits via ``os._exit`` so a dead-peer distributed shutdown barrier in
atexit cannot hang the test.
"""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def log(proc_id, msg):
    print(f"[proc {proc_id}] {msg}", flush=True)


def main():
    proc_id, nprocs = int(sys.argv[1]), int(sys.argv[2])
    coord, flag_dir = sys.argv[3], sys.argv[4]
    sys.path.insert(0, REPO)
    from pytensor_federated_tpu.utils import force_cpu_backend

    force_cpu_backend()
    from pytensor_federated_tpu.parallel.multihost import (
        initialize_multihost,
        make_multihost_mesh,
        remesh_after_failure,
    )

    n = initialize_multihost(
        coord, num_processes=nprocs, process_id=proc_id
    )
    import jax
    import jax.numpy as jnp
    import numpy as np

    assert n == nprocs, n
    assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4

    from pytensor_federated_tpu.parallel.packing import pack_shards
    from pytensor_federated_tpu.parallel.sharded import FederatedLogp

    # Deterministic data, identical in both processes (the multi-host
    # contract: every process feeds the same global arrays and jax
    # slices out its addressable shards).
    rng = np.random.default_rng(42)
    shards = []
    for _ in range(8):
        X = rng.normal(size=(16, 3)).astype(np.float32)
        w_true = np.array([1.0, -2.0, 0.5], np.float32)
        y = (X @ w_true + 0.1 * rng.normal(size=16)).astype(np.float32)
        shards.append((X, y))
    data = pack_shards(shards)

    def per_shard_logp(params, shard):
        (X, y), mask = shard
        r = y - X @ params["w"]
        return -0.5 * jnp.sum(r * r * mask)

    params = {"w": jnp.zeros(3)}

    # Local (no-mesh) golden value: vmap + sum on this process alone.
    fed_local = FederatedLogp(per_shard_logp, data.tree(), mesh=None)
    v_ref, g_ref = fed_local.logp_and_grad(params)
    v_ref = float(v_ref)

    mesh = make_multihost_mesh()
    assert mesh.shape["shards"] == 8
    n_procs_in_mesh = len(
        {d.process_index for d in mesh.devices.flat}
    )
    assert n_procs_in_mesh == 2, "mesh must span both processes"
    fed = FederatedLogp(per_shard_logp, data.tree(), mesh=mesh)
    v, g = fed.logp_and_grad(params)
    v = float(v)
    assert abs(v - v_ref) <= 1e-4 * abs(v_ref), (v, v_ref)
    gerr = float(
        jnp.max(jnp.abs(g["w"] - g_ref["w"]))
        / jnp.max(jnp.abs(g_ref["w"]))
    )
    assert gerr <= 1e-4, gerr
    log(proc_id, f"PHASE-A OK logp={v:.6f}")

    if proc_id != 0:
        # "Die": hard-exit without any distributed shutdown handshake.
        os._exit(0)

    # --- Phase B: survivor. Wait for the launcher to confirm the peer
    # is dead, then recover on what remains.
    deadline = time.time() + 60.0
    flag = os.path.join(flag_dir, "peer_dead")
    while not os.path.exists(flag):
        if time.time() > deadline:
            log(0, "FAIL: peer-death flag never appeared")
            os._exit(2)
        time.sleep(0.1)

    survivors_mesh = remesh_after_failure(mesh, axis="shards")
    n_dev = len(list(survivors_mesh.devices.flat))
    assert n_dev == 4, f"expected the 4 local survivors, got {n_dev}"
    assert survivors_mesh.shape["shards"] == 4

    # Re-place host-resident data over the shrunken mesh and re-jit:
    # 8 shards over 4 devices -> 2 per device, same logp.
    fed2 = FederatedLogp(per_shard_logp, data.tree(), mesh=survivors_mesh)
    v2 = float(fed2.logp(params))
    assert abs(v2 - v_ref) <= 1e-4 * abs(v_ref), (v2, v_ref)
    log(0, f"PHASE-B OK logp={v2:.6f}")
    os._exit(0)


if __name__ == "__main__":
    main()
