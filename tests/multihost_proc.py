"""Per-process driver for the REAL 2-process ``jax.distributed`` test.

Launched as ``python multihost_proc.py <proc_id> <nprocs> <coord>
<hb_base_port>`` by tests/test_multihost_procs.py (a FILE on purpose:
spawned children need a ``__main__`` file, and the pytest process must
never itself call ``jax.distributed.initialize`` — CLAUDE.md).

Phase A (both processes): join the distributed runtime, start a
:class:`HeartbeatServer` on ``hb_base_port + proc_id``, build the
host-spanning mesh (``make_multihost_mesh``), evaluate one psum'd
federated logp+grad whose shards live on BOTH processes' devices, and
print the value — the reference's sum-of-node-replies crossing the
network (reference: service.py:75-115), here a gloo all-reduce over the
process boundary.

Phase B: process 1 enters a work loop (serving its heartbeat, running
local evaluations) and the LAUNCHER SIGKILLs it mid-loop — a hard
kill, no shutdown handshake, no exit path.  Process 0 gets NO hint:
it first confirms the peer answers liveness probes (``PEER-ALIVE``),
then polls :func:`detect_dead_peers` until the peer fails three
consecutive probes (``PEER-DEAD``), and only then exercises
``remesh_after_failure(dead_process_ids=...)`` and rebuilds the
federated evaluator over the shrunken mesh from host-resident data,
checking the SAME logp value comes back.

What this proves: in-band dead-peer DETECTION (the survivor discovers
the death through the framework's own liveness probes — the mesh
analog of the reference's StreamTerminatedError -> rebalance,
service.py:407-416) plus SURVIVOR CONTINUITY (the surviving process's
runtime stays usable, remesh returns promptly, local re-jit reproduces
the value).  Still LOCAL-VIEW recovery: the rebuilt mesh holds only
the survivor's addressable devices (see ``remesh_after_failure``'s
docstring).

Exits via ``os._exit`` so a dead-peer distributed shutdown barrier in
atexit cannot hang the test.

Backend-capability escape hatch: some container jaxlibs reject
cross-process collectives outright ("Multiprocess computations aren't
implemented on the CPU backend" out of the phase-A device_put — the
gloo/DCN path simply is not compiled in).  That is an environment
limitation, not a code failure, so the child prints
``SKIP-UNSUPPORTED: <reason>`` and exits 3; the launcher turns it into
a pytest skip instead of a red.
"""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def log(proc_id, msg):
    print(f"[proc {proc_id}] {msg}", flush=True)


def main():
    proc_id, nprocs = int(sys.argv[1]), int(sys.argv[2])
    coord, hb_base = sys.argv[3], int(sys.argv[4])
    sys.path.insert(0, REPO)
    from pytensor_federated_tpu.utils import force_cpu_backend

    force_cpu_backend()
    from pytensor_federated_tpu.parallel.multihost import (
        HeartbeatServer,
        detect_dead_peers,
        initialize_multihost,
        make_multihost_mesh,
        probe_peer,
        remesh_after_failure,
    )

    n = initialize_multihost(
        coord, num_processes=nprocs, process_id=proc_id
    )
    import jax
    import jax.numpy as jnp
    import numpy as np

    assert n == nprocs, n
    assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4

    hb = HeartbeatServer(
        "127.0.0.1", hb_base + proc_id, process_index=proc_id
    )
    log(proc_id, f"heartbeat on {hb.address[0]}:{hb.address[1]}")

    from pytensor_federated_tpu.parallel.packing import pack_shards
    from pytensor_federated_tpu.parallel.sharded import FederatedLogp

    # Deterministic data, identical in both processes (the multi-host
    # contract: every process feeds the same global arrays and jax
    # slices out its addressable shards).
    rng = np.random.default_rng(42)
    shards = []
    for _ in range(8):
        X = rng.normal(size=(16, 3)).astype(np.float32)
        w_true = np.array([1.0, -2.0, 0.5], np.float32)
        y = (X @ w_true + 0.1 * rng.normal(size=16)).astype(np.float32)
        shards.append((X, y))
    data = pack_shards(shards)

    def per_shard_logp(params, shard):
        (X, y), mask = shard
        r = y - X @ params["w"]
        return -0.5 * jnp.sum(r * r * mask)

    params = {"w": jnp.zeros(3)}

    # Local (no-mesh) golden value: vmap + sum on this process alone.
    fed_local = FederatedLogp(per_shard_logp, data.tree(), mesh=None)
    v_ref, g_ref = fed_local.logp_and_grad(params)
    v_ref = float(v_ref)

    mesh = make_multihost_mesh()
    assert mesh.shape["shards"] == 8
    n_procs_in_mesh = len(
        {d.process_index for d in mesh.devices.flat}
    )
    assert n_procs_in_mesh == 2, "mesh must span both processes"
    fed = FederatedLogp(per_shard_logp, data.tree(), mesh=mesh)
    v, g = fed.logp_and_grad(params)
    v = float(v)
    assert abs(v - v_ref) <= 1e-4 * abs(v_ref), (v, v_ref)
    gerr = float(
        jnp.max(jnp.abs(g["w"] - g_ref["w"]))
        / jnp.max(jnp.abs(g_ref["w"]))
    )
    assert gerr <= 1e-4, gerr
    log(proc_id, f"PHASE-A OK logp={v:.6f}")

    if proc_id != 0:
        # Work loop: keep computing until the launcher's SIGKILL lands
        # mid-run.  No exit path exists on purpose — only the kill ends
        # this process.
        log(proc_id, "SERVING")
        while True:
            fed_local.logp(params)
            time.sleep(0.1)

    # --- Phase B: survivor. NO launcher hint — discover the death
    # through the framework's own liveness probes.
    peer = {1: ("127.0.0.1", hb_base + 1)}

    deadline = time.time() + 60.0
    while not probe_peer(peer[1], timeout=0.5):
        if time.time() > deadline:
            log(0, "FAIL: peer heartbeat never came up")
            os._exit(2)
        time.sleep(0.2)
    log(0, "PEER-ALIVE")

    deadline = time.time() + 120.0
    while True:
        dead = detect_dead_peers(
            peer, timeout=0.5, retries=3, retry_wait=0.3
        )
        if dead == [1]:
            break
        if time.time() > deadline:
            log(0, "FAIL: peer death never detected")
            os._exit(2)
        time.sleep(0.2)
    log(0, "PEER-DEAD")

    survivors_mesh = remesh_after_failure(
        mesh, axis="shards", dead_process_ids=dead
    )
    n_dev = len(list(survivors_mesh.devices.flat))
    assert n_dev == 4, f"expected the 4 local survivors, got {n_dev}"
    assert survivors_mesh.shape["shards"] == 4

    # Re-place host-resident data over the shrunken mesh and re-jit:
    # 8 shards over 4 devices -> 2 per device, same logp.
    fed2 = FederatedLogp(per_shard_logp, data.tree(), mesh=survivors_mesh)
    v2 = float(fed2.logp(params))
    assert abs(v2 - v_ref) <= 1e-4 * abs(v_ref), (v2, v_ref)
    hb.stop()
    log(0, f"PHASE-B OK logp={v2:.6f}")
    os._exit(0)


# Substrings that mark "this jaxlib cannot run cross-process
# collectives at all" — the documented environment drift this container
# exhibits, not any bug in the code under test.
_UNSUPPORTED_MARKERS = (
    "Multiprocess computations aren't implemented",
    "multiprocess computations aren't implemented",
)


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 — capability triage, then re-raise
        msg = f"{type(e).__name__}: {e}"
        if any(m in msg for m in _UNSUPPORTED_MARKERS):
            log(
                sys.argv[1] if len(sys.argv) > 1 else "?",
                f"SKIP-UNSUPPORTED: {msg.splitlines()[0][:300]}",
            )
            os._exit(3)
        raise
