"""L0 utility tests — mirrors the reference's test_utils.py
(reference: test_utils.py:7-48 covers argmin-over-optionals and
event-loop acquisition semantics)."""

import asyncio

import jax
import numpy as np

from pytensor_federated_tpu.utils import (
    argmin_none_or_func,
    force_cpu_backend,
    get_event_loop,
)


class TestArgminNoneOrFunc:
    def test_all_none(self):
        assert argmin_none_or_func([None, None, None], lambda x: x) is None

    def test_empty(self):
        assert argmin_none_or_func([], lambda x: x) is None

    def test_mixed(self):
        # None entries are skipped, not treated as zero.
        assert argmin_none_or_func([None, 5.0, 2.0, None, 9.0], lambda x: x) == 2

    def test_key_function(self):
        loads = [{"n": 3}, None, {"n": 1}, {"n": 2}]
        assert argmin_none_or_func(loads, lambda l: l["n"]) == 2

    def test_first_wins_ties(self):
        assert argmin_none_or_func([1.0, 1.0], lambda x: x) == 0


class TestGetEventLoop:
    def test_returns_usable_loop(self):
        loop = get_event_loop()
        assert loop.run_until_complete(_answer()) == 42

    def test_survives_closed_loop(self):
        loop = get_event_loop()
        loop.close()
        loop2 = get_event_loop()
        assert not loop2.is_closed()
        assert loop2.run_until_complete(_answer()) == 42

    def test_inside_running_loop_returns_it(self):
        async def inner():
            return get_event_loop() is asyncio.get_running_loop()

        assert asyncio.run(inner())


async def _answer():
    return 42


def test_force_cpu_backend_idempotent():
    """Safe to call repeatedly; the session is already CPU-pinned
    (conftest), so this must not disturb the running backend."""
    force_cpu_backend()
    force_cpu_backend()
    assert jax.default_backend() == "cpu"
    assert float(jax.numpy.ones(()).sum()) == 1.0


def test_healthy_devices_and_get_load():
    """Mesh-plane control surface: all virtual CPU devices are healthy
    and report load stats (the GetLoad analog, reference:
    service.py:88-96)."""
    from pytensor_federated_tpu.parallel import get_load, healthy_devices

    cpus = jax.devices("cpu")
    alive = healthy_devices(cpus)
    assert alive == list(cpus)
    loads = get_load(cpus)
    assert len(loads) == len(cpus)
    for d, l in zip(cpus, loads):
        assert l.device_id == d.id
        assert l.platform == "cpu"


def test_find_reasonable_step_size_gaussian():
    """On a standard Gaussian the heuristic lands in a sane bracket."""
    import jax.numpy as jnp

    from pytensor_federated_tpu.samplers import find_reasonable_step_size

    lg = jax.value_and_grad(lambda x: -0.5 * jnp.sum(x**2))
    eps = find_reasonable_step_size(
        lambda x: lg(x),
        jnp.zeros((4,)),
        jax.random.PRNGKey(0),
        jnp.ones((4,)),
    )
    assert 0.01 < float(eps) < 10.0


def test_event_loop_stable_per_thread():
    """The same thread must get the same loop across calls (an aio
    channel is bound to its creation loop), and different threads must
    get different loops."""
    import threading

    loops = {}

    def grab(name):
        l1 = get_event_loop()
        l2 = get_event_loop()
        loops[name] = (l1, l2)

    t1 = threading.Thread(target=grab, args=("a",))
    t2 = threading.Thread(target=grab, args=("b",))
    t1.start(); t2.start(); t1.join(); t2.join()
    assert loops["a"][0] is loops["a"][1]
    assert loops["b"][0] is loops["b"][1]
    assert loops["a"][0] is not loops["b"][0]


def test_enable_compilation_cache_sets_config(tmp_path):
    import jax

    from pytensor_federated_tpu.utils import enable_compilation_cache

    target = str(tmp_path / "xla_cache")
    enable_compilation_cache(target)
    assert jax.config.jax_compilation_cache_dir == target
    import os

    assert os.path.isdir(target)
