"""Multi-host mesh layout + elastic recovery (parallel/multihost.py).

Single-process tests: multi-host init itself needs a cluster, but the
layout policy, degradation to one host, and the failover-by-remesh path
(reference analog: service.py:408-416 retry/rebalance; all-dead ->
TimeoutError, reference: service.py:257-260) are all testable on the
virtual CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu.parallel import make_mesh
from pytensor_federated_tpu.parallel.multihost import (
    HeartbeatServer,
    detect_dead_peers,
    initialize_multihost,
    make_multihost_mesh,
    probe_peer,
    remesh_after_failure,
)


class TestInitialize:
    def test_single_process_noop(self):
        assert initialize_multihost() == jax.process_count() == 1


class TestMultihostMesh:
    def test_single_host_degrades(self, devices8):
        mesh = make_multihost_mesh(devices=devices8)
        assert mesh.shape == {"shards": 8}

    def test_inner_axes(self, devices8):
        mesh = make_multihost_mesh({"chains": 2}, devices=devices8)
        assert mesh.shape == {"shards": 4, "chains": 2}
        assert mesh.axis_names == ("shards", "chains")

    def test_indivisible_inner_raises(self, devices8):
        with pytest.raises(ValueError, match="do not divide"):
            make_multihost_mesh({"chains": 3}, devices=devices8)

    def test_host_axis_collision_raises(self, devices8):
        with pytest.raises(ValueError, match="host axis"):
            make_multihost_mesh({"shards": 2}, devices=devices8)


class TestRemeshAfterFailure:
    def test_shrinks_to_survivors(self, devices8):
        mesh = make_mesh({"shards": 8}, devices=devices8)
        # Simulate 3 dead devices by offering only 5 candidates.
        new = remesh_after_failure(mesh, devices=devices8[:5])
        assert new.shape == {"shards": 5}

    def test_preserves_other_axes_and_order(self, devices8):
        mesh = make_mesh({"chains": 2, "shards": 4}, devices=devices8)
        new = remesh_after_failure(mesh, axis="shards", devices=devices8[:6])
        assert new.shape["chains"] == 2
        assert new.shape["shards"] == 3
        # Axis order encodes the DCN/ICI layout — must survive recovery.
        assert new.axis_names == mesh.axis_names

    def test_all_dead_raises(self, devices8):
        mesh = make_mesh({"shards": 8}, devices=devices8)
        with pytest.raises(TimeoutError, match="no healthy devices"):
            remesh_after_failure(mesh, devices=[])

    def test_end_to_end_recovery(self, devices8):
        """The full failover story: evaluate on 8 devices, 'lose' 4,
        remesh, rebuild the evaluator from host data, same answer."""
        from pytensor_federated_tpu.models.linear import (
            FederatedLinearRegression,
            generate_node_data,
        )

        data, _ = generate_node_data(8, n_obs=32, seed=5)
        mesh8 = make_mesh({"shards": 8}, devices=devices8)
        model8 = FederatedLinearRegression(data, mesh=mesh8)
        p = model8.init_params()
        before = float(model8.logp(p))

        mesh_new = remesh_after_failure(mesh8, devices=devices8[:4])
        assert mesh_new.shape == {"shards": 4}
        # Re-place + re-jit from host-resident shard data (nodes are
        # stateless; 8 shards now live 2-per-device).
        model4 = FederatedLinearRegression(data, mesh=mesh_new)
        after = float(model4.logp(p))
        np.testing.assert_allclose(after, before, rtol=1e-6)


class TestHeartbeat:
    """In-band failure detection (round-4 verdict item 3): the mesh
    analog of the reference's StreamTerminatedError -> rebalance
    (reference: service.py:407-416).  The REAL SIGKILL-a-peer proof is
    tests/test_multihost_procs.py; these pin the primitives."""

    def test_probe_live_server(self):
        hb = HeartbeatServer()
        try:
            assert probe_peer(hb.address, timeout=2.0)
            # repeated probes keep answering (accept loop, not one-shot)
            assert probe_peer(hb.address, timeout=2.0)
        finally:
            hb.stop()

    def test_stopped_server_is_dead(self):
        hb = HeartbeatServer()
        addr = hb.address
        hb.stop()
        assert not probe_peer(addr, timeout=0.5)

    def test_detect_dead_peers_split_verdict(self):
        hb = HeartbeatServer()
        dead_addr = ("127.0.0.1", 1)  # port 1: nothing listens
        try:
            dead = detect_dead_peers(
                {0: hb.address, 7: dead_addr},
                timeout=0.3,
                retries=2,
                retry_wait=0.05,
            )
        finally:
            hb.stop()
        assert dead == [7]

    def test_detection_feeds_remesh(self, devices8):
        """The composed story on one process: a death verdict for a
        (hypothetical) peer process id leaves the local mesh intact,
        while a verdict against our own process' devices would be
        rejected by the healthy-device probe downstream."""
        mesh = make_mesh({"shards": 8}, devices=devices8)
        # Verdict names a process id that owns none of these devices:
        # nothing is dropped, mesh rebuilds at full size.
        rebuilt = remesh_after_failure(
            mesh, axis="shards", dead_process_ids=[999]
        )
        assert rebuilt.shape == {"shards": 8}

    def test_wrong_identity_is_dead(self):
        """A port recycled by a DIFFERENT process index (supervisor
        restart, another mesh's heartbeat) must not impersonate the
        expected peer."""
        hb = HeartbeatServer(process_index=3)
        try:
            assert probe_peer(hb.address, timeout=2.0)
            assert probe_peer(
                hb.address, timeout=2.0, expect_process_index=3
            )
            assert not probe_peer(
                hb.address, timeout=2.0, expect_process_index=1
            )
            dead = detect_dead_peers(
                {1: hb.address}, timeout=0.3, retries=2, retry_wait=0.05
            )
        finally:
            hb.stop()
        assert dead == [1]

    def test_unknown_identity_accepted_on_prefix(self):
        """A server started without process_index (banner -1) cannot be
        identity-checked; prefix acceptance is documented behavior."""
        hb = HeartbeatServer()
        try:
            assert probe_peer(
                hb.address, timeout=2.0, expect_process_index=5
            )
        finally:
            hb.stop()

    def test_non_alive_banner_is_dead(self):
        """A port that ACCEPTS but answers garbage (port reuse by an
        unrelated service) must not count as a live peer."""
        import socket
        import threading

        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)

        def answer_garbage():
            conn, _ = srv.accept()
            conn.sendall(b"HTTP/1.1 200 OK\r\n\r\n")
            conn.close()

        t = threading.Thread(target=answer_garbage, daemon=True)
        t.start()
        try:
            assert not probe_peer(srv.getsockname(), timeout=2.0)
        finally:
            srv.close()
