"""Property-based reference-wire codec tests (hypothesis).

Split out of test_npproto_codec.py so the example-based and interop
suites there stay collectable on containers without hypothesis; this
module skips itself instead.  The loud-WireError invariant (CLAUDE.md
design invariants) over the npproto lane: any truncation, bit flip, or
junk must raise WireError or decode self-consistently — and the
telemetry trace id (field 15) must be ignorable by the reference
schema under the official protobuf runtime.
"""

import numpy as np
import pytest

pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402
from hypothesis.extra import numpy as hnp  # noqa: E402

from pytensor_federated_tpu.service.npproto_codec import (  # noqa: E402
    decode_arrays_msg,
    decode_arrays_msg_ex,
    decode_ndarray,
    encode_arrays_msg,
    encode_ndarray,
)
from pytensor_federated_tpu.service.npwire import WireError  # noqa: E402

from test_npproto_codec import _official_messages  # noqa: E402

_PROP = settings(max_examples=50, deadline=None)

_simple_dtypes = st.one_of(
    hnp.integer_dtypes(endianness="="),
    hnp.unsigned_integer_dtypes(endianness="="),
    hnp.floating_dtypes(endianness="=", sizes=(32, 64)),
    hnp.complex_number_dtypes(endianness="="),
    # str(dtype)/np.dtype round-trips datetime64/timedelta64, so the
    # reference wire carries them (unlike structured dtypes).
    hnp.datetime64_dtypes(endianness="="),
    hnp.timedelta64_dtypes(endianness="="),
    st.just(np.dtype("bool")),
)

_prop_arrays = _simple_dtypes.flatmap(
    lambda dt: hnp.arrays(
        dtype=dt,
        shape=hnp.array_shapes(
            min_dims=0, max_dims=3, min_side=0, max_side=6
        ),
    )
)


@_PROP
@given(arr=_prop_arrays, uuid=st.text(max_size=24))
def test_property_roundtrip(arr, uuid):
    out, u = decode_arrays_msg(encode_arrays_msg([arr], uuid=uuid))
    assert u == uuid
    assert out[0].dtype == arr.dtype and out[0].shape == arr.shape
    np.testing.assert_array_equal(out[0], arr)


@_PROP
@given(
    arr=_prop_arrays,
    uuid=st.text(max_size=24),
    trace=st.binary(min_size=16, max_size=16),
)
def test_property_trace_id_ignorable_by_reference_schema(arr, uuid, trace):
    """Telemetry extension field 15 must round-trip through
    decode_arrays_msg_ex, be skipped by this codec's historical
    2-tuple decoder, AND be skipped by the OFFICIAL protobuf runtime
    parsing under the reference schema (which has no field 15) — for
    any array, any uuid, any 16-byte id."""
    buf = encode_arrays_msg([arr], uuid=uuid, trace_id=trace)
    out, u, tid = decode_arrays_msg_ex(buf)
    assert u == uuid and tid == trace
    np.testing.assert_array_equal(out[0], arr)
    out2, u2 = decode_arrays_msg(buf)
    assert u2 == uuid
    np.testing.assert_array_equal(out2[0], arr)
    _nd, InputArrays, _gl = _official_messages()
    msg = InputArrays()
    msg.ParseFromString(buf)  # unknown field skipped by wire type
    assert msg.uuid == uuid
    assert len(msg.items) == 1
    # and with NO trace id the bytes are identical to the pre-telemetry
    # encoder's output (byte-level reference parity preserved)
    assert encode_arrays_msg([arr], uuid=uuid) == encode_arrays_msg(
        [arr], uuid=uuid, trace_id=None
    )


@_PROP
@given(
    arr=_prop_arrays,
    cut=st.integers(min_value=0, max_value=200),
)
def test_property_truncation_never_silently_wrong(arr, cut):
    """Any prefix of a valid single-item message must either raise
    WireError or decode to a PREFIX of the truth: cutting at a field
    boundary legitimately drops tail fields (proto3), so the only legal
    successful decodes are ([], "") — cut before the item — or
    ([exactly arr], "" or "u"); a cut INSIDE the item's length-framed
    payload must overrun and raise.  Never another exception type,
    never a corrupted array."""
    buf = encode_arrays_msg([arr], uuid="u")
    prefix = buf[: min(cut, len(buf))]
    try:
        out, uuid = decode_arrays_msg(prefix)
    except WireError:
        return
    assert uuid in ("", "u")
    assert len(out) in (0, 1)
    for a in out:
        assert a.dtype == arr.dtype and a.shape == arr.shape
        np.testing.assert_array_equal(a, arr)


@_PROP
@given(
    arr=_prop_arrays,
    pos=st.integers(min_value=0),
    bit=st.integers(min_value=0, max_value=7),
)
def test_property_bitflip_loud_or_consistent(arr, pos, bit):
    """A single bit flip must produce WireError or a SELF-CONSISTENT
    decode — no other exception type escapes (the npwire contract,
    CLAUDE.md design invariants).  proto3 carries no payload checksum,
    so a flip inside the data bytes legitimately decodes to different
    VALUES; what must still hold is codec self-consistency: the result
    re-encodes and round-trips to an identical array."""
    buf = bytearray(encode_arrays_msg([arr], uuid="u"))
    if not buf:
        return
    buf[pos % len(buf)] ^= 1 << bit
    try:
        out, _ = decode_arrays_msg(bytes(buf))
    except WireError:
        return
    for a in out:
        again = decode_ndarray(encode_ndarray(a))
        assert again.dtype == a.dtype and again.shape == a.shape
        np.testing.assert_array_equal(again, a)


@_PROP
@given(junk=st.binary(max_size=160))
def test_property_junk_loud_or_valid(junk):
    """Arbitrary bytes: WireError or a decode whose arrays survive this
    codec's own round trip — never any other exception type."""
    try:
        out, u = decode_arrays_msg(junk)
    except WireError:
        return
    assert isinstance(u, str)
    for a in out:
        again = decode_ndarray(encode_ndarray(a))
        assert again.dtype == a.dtype and again.shape == a.shape
        np.testing.assert_array_equal(again, a)
