"""The ZeRO-style sharded optimizer (ISSUE 16): the step-version wire
feature on all codecs, the node-owned shard lifecycle, exactly-once
recovery, and the StreamingSVI sharded lane.

The contracts under test:

- version-free frames stay BYTE-IDENTICAL on every codec (the
  pre-feature wire is untouched); the reference protobuf runtime skips
  extension field 21;
- driver-centric and sharded optimization produce BIT-IDENTICAL
  parameter trajectories on CPU for the same RNG stream (adam is
  elementwise, so slice-of-adam == adam-of-slice — property-tested
  over partition geometries including width-1 and uneven tails);
- the driver never materializes a full gradient or moment buffer
  (``max_reply_elems`` is the O(model/N) residency witness);
- a version mismatch is a LOUD machine-parseable refusal; a lost
  reply recovers via the refresh lane without double-stepping
  (``opt_steps == accepted`` per shard).
"""

import tempfile
import threading

import numpy as np
import pytest

try:
    from hypothesis import given, settings, strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:  # the container may lack hypothesis; the seeded
    HAVE_HYPOTHESIS = False  # twins below still run everywhere

optax = pytest.importorskip("optax")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from pytensor_federated_tpu.optim import (  # noqa: E402
    ShardStore,
    ShardedOptimizer,
    StaleShardError,
    make_update_compute,
    parse_stale_error,
    stale_message,
)
from pytensor_federated_tpu.routing.partition import (  # noqa: E402
    GradPartition,
    PartitionError,
    Reassembler,
    plan_partitions,
)
from pytensor_federated_tpu.service import shm as shm_mod  # noqa: E402
from pytensor_federated_tpu.service.npproto_codec import (  # noqa: E402
    decode_arrays_msg_full,
    encode_arrays_msg,
    peek_version_msg,
)
from pytensor_federated_tpu.service.npwire import (  # noqa: E402
    WireError,
    decode_arrays_part,
    decode_batch_part,
    encode_arrays,
    encode_batch,
    peek_version,
)

# Zero is a MEANINGFUL stamp (the init handshake): presence rides the
# flag/field, never the value; the max is the u64 ceiling.
_SEED_VERSIONS = [0, 1, 255, 2**32, 2**64 - 1]
_SEED_ARRAYS = [
    np.zeros(0, np.float32),
    np.arange(5, dtype=np.float32),
    np.arange(6, dtype=np.float64).reshape(2, 3),
]


# ---------------------------------------------------------------------------
# the step-version wire feature, all codecs
# ---------------------------------------------------------------------------


class TestNpwireVersion:
    @pytest.mark.parametrize("version", _SEED_VERSIONS)
    @pytest.mark.parametrize("arr", _SEED_ARRAYS, ids=["e", "v", "m"])
    def test_roundtrip_and_peek(self, arr, version):
        buf = encode_arrays([arr], uuid=b"u" * 16, version=version)
        assert peek_version(buf) == version
        arrays, uuid, error, _tid, _sp, _part, ver = decode_arrays_part(
            buf
        )
        assert uuid == b"u" * 16 and error is None and ver == version
        np.testing.assert_array_equal(arrays[0], arr)

    @pytest.mark.parametrize("arr", _SEED_ARRAYS, ids=["e", "v", "m"])
    def test_no_version_byte_identical(self, arr):
        assert encode_arrays([arr], uuid=b"u" * 16) == encode_arrays(
            [arr], uuid=b"u" * 16, version=None
        )
        assert peek_version(encode_arrays([arr], uuid=b"u" * 16)) is None

    @pytest.mark.parametrize("version", _SEED_VERSIONS)
    def test_composes_with_partition(self, version):
        part = (1, 4, 8, 8, 32)
        arr = np.arange(4, dtype=np.float32)
        buf = encode_arrays(
            [arr], uuid=b"u" * 16, partition=part, version=version,
            deadline_s=1.5,
        )
        assert peek_version(buf) == version
        _a, _u, _e, _t, _s, rpart, ver = decode_arrays_part(buf)
        assert tuple(rpart) == part and ver == version

    @pytest.mark.parametrize("version", _SEED_VERSIONS)
    def test_batch_roundtrip(self, version):
        arr = np.arange(3, dtype=np.float32)
        item = encode_arrays([arr], uuid=b"i" * 16, version=version)
        buf = encode_batch([item], uuid=b"b" * 16, version=version)
        assert peek_version(buf) == version
        items, uuid, error, _tid, _sp, _part, ver = decode_batch_part(buf)
        assert uuid == b"b" * 16 and ver == version and items == [item]
        assert encode_batch([item], uuid=b"b" * 16) == encode_batch(
            [item], uuid=b"b" * 16, version=None
        )

    def test_truncated_version_block_loud(self):
        buf = encode_arrays([], uuid=b"u" * 16, version=3)
        with pytest.raises(WireError):
            decode_arrays_part(buf[:-4])
        with pytest.raises(WireError):
            encode_arrays([], uuid=b"u" * 16, version=2**64)
        with pytest.raises(WireError):
            encode_arrays([], uuid=b"u" * 16, version=-1)


class TestNpprotoVersion:
    @pytest.mark.parametrize("version", _SEED_VERSIONS)
    @pytest.mark.parametrize("arr", _SEED_ARRAYS, ids=["e", "v", "m"])
    def test_roundtrip_and_peek(self, arr, version):
        buf = encode_arrays_msg([arr], "uu", version=version)
        assert peek_version_msg(buf) == version
        arrays, uuid, _err, _tid, _sp = decode_arrays_msg_full(buf)
        assert uuid == "uu"
        np.testing.assert_array_equal(arrays[0], arr)

    @pytest.mark.parametrize("arr", _SEED_ARRAYS, ids=["e", "v", "m"])
    def test_no_version_byte_identical(self, arr):
        assert encode_arrays_msg([arr], "uu") == encode_arrays_msg(
            [arr], "uu", version=None
        )
        assert peek_version_msg(encode_arrays_msg([arr], "uu")) is None

    @pytest.mark.parametrize("version", _SEED_VERSIONS)
    def test_reference_runtime_skips_field_21(self, version):
        """The OFFICIAL protobuf runtime parsing under the reference
        schema (no field 21) must skip the version stamp by wire type
        — the forward-compatibility pin fields 14-20 carry."""
        from test_npproto_codec import _official_messages

        _nd, InputArrays, _gl = _official_messages()
        buf = encode_arrays_msg(
            [np.arange(4, dtype=np.float32)], "uu", version=version
        )
        msg = InputArrays()
        msg.ParseFromString(buf)
        assert msg.uuid == "uu"
        assert len(msg.items) == 1


class TestShmVersion:
    @pytest.mark.parametrize("version", _SEED_VERSIONS)
    @pytest.mark.parametrize("body", [b"", b"payload-bytes"])
    def test_roundtrip(self, version, body):
        frame = shm_mod.encode_frame(
            shm_mod._KIND_EVAL, b"u" * 16, body, version=version
        )
        k, _u, err, _t, _d, _part, ver, off, buf = shm_mod.decode_frame(
            frame
        )
        assert k == shm_mod._KIND_EVAL and err is None
        assert ver == version
        assert buf[off:] == body  # the version block never eats body

    @pytest.mark.parametrize("body", [b"", b"payload-bytes"])
    def test_no_version_byte_identical(self, body):
        a = shm_mod.encode_frame(shm_mod._KIND_EVAL, b"u" * 16, body)
        b = shm_mod.encode_frame(
            shm_mod._KIND_EVAL, b"u" * 16, body, version=None
        )
        assert a == b
        assert shm_mod.decode_frame(a)[6] is None

    def test_truncated_version_block_loud(self):
        frame = shm_mod.encode_frame(
            shm_mod._KIND_EVAL, b"u" * 16, b"", version=9
        )
        with pytest.raises(WireError):
            shm_mod.decode_frame(frame[:-3])


# ---------------------------------------------------------------------------
# the shard store + the stale protocol
# ---------------------------------------------------------------------------


class TestShardStore:
    def test_save_load_roundtrip_and_version(self, tmp_path):
        store = ShardStore(str(tmp_path))
        part = plan_partitions(10, 3)[1]
        assert store.load(part) is None and store.version(part) is None
        params = np.arange(part.length, dtype=np.float32)
        leaves = [np.ones(part.length), np.zeros(part.length)]
        store.save(part, 4, params, leaves)
        state = store.load(part)
        assert state.version == 4 and store.version(part) == 4
        np.testing.assert_array_equal(state.params, params)
        assert len(state.opt_leaves) == 2
        store.save(part, 5, params + 1, leaves)
        assert store.load(part).version == 5  # atomic overwrite
        store.drop(part)
        assert store.load(part) is None

    def test_geometry_collision_is_loud(self, tmp_path):
        store = ShardStore(str(tmp_path))
        part = plan_partitions(10, 2)[0]
        store.save(part, 1, np.zeros(part.length), [])
        with pytest.raises(PartitionError):
            store.save(part, 2, np.zeros(part.length + 1), [])

    def test_corrupt_checkpoint_is_loud(self, tmp_path):
        store = ShardStore(str(tmp_path))
        part = plan_partitions(6, 2)[0]
        store.save(part, 1, np.zeros(part.length), [])
        path = store._path(part)
        with open(path, "wb") as f:
            f.write(b"not an npz")
        with pytest.raises(WireError, match="corrupt shard checkpoint"):
            store.load(part)

    def test_stale_message_parse_roundtrip(self):
        part = GradPartition(2, 4, 10, 5, 20)
        msg = stale_message(part, holds=7, expected=6)
        assert parse_stale_error(msg) == (2, 4, 7, 6)
        assert "offset=10" in msg and "length=5" in msg
        assert parse_stale_error("some other error") is None
        err = StaleShardError(part, 7, 6)
        assert isinstance(err, WireError)
        assert parse_stale_error(str(err)) == (2, 4, 7, 6)


# ---------------------------------------------------------------------------
# shard-local update equivalence (no transport): hypothesis geometries
# ---------------------------------------------------------------------------


def _quad_loss(params, x):
    return jnp.sum((params - x) ** 2) + jnp.sum(jnp.sin(params))


def _quad_grad_fn(params, x):
    loss, g = jax.value_and_grad(_quad_loss)(
        jnp.asarray(params), jnp.asarray(x)
    )
    return np.asarray(loss), np.asarray(g)


def _check_bit_identical(total, count, steps, seed):
    """Driver-centric adam and the sharded node update produce the
    SAME floats for any geometry — width 1, even, uneven tails."""
    store = ShardStore(tempfile.mkdtemp())
    compute = make_update_compute(
        _quad_grad_fn,
        optax.adam(0.05),
        store,
        params_of=lambda arrays: np.asarray(arrays[0]).ravel(),
    )
    plan = plan_partitions(total, count)

    opt = optax.adam(0.05)
    params_ref = jnp.zeros(total, jnp.float32)
    opt_state = opt.init(params_ref)

    params = np.zeros(total, np.float32)
    rng = np.random.default_rng(seed)
    for step in range(steps):
        x = rng.normal(size=total).astype(np.float32)
        new = params.copy()
        for part in plan:
            outputs, rv = compute.versioned_update(
                [params, x], tuple(part), step
            )
            assert rv == step + 1
            sl = np.asarray(outputs[1])
            assert sl.size == part.length  # O(model/N) replies
            new[part.offset : part.offset + part.length] += sl
        params = new

        _, g = jax.value_and_grad(_quad_loss)(
            params_ref, jnp.asarray(x)
        )
        upd, opt_state = opt.update(g, opt_state)
        params_ref = optax.apply_updates(params_ref, upd)
        np.testing.assert_array_equal(params, np.asarray(params_ref))


_SEED_GEOMETRIES = [
    (1, 1),   # the whole vector on one owner
    (5, 5),   # width-1 shards
    (13, 3),  # uneven tail
    (8, 2),   # even split
    (40, 6),  # uneven, larger
]


class TestUpdateEquivalence:
    @pytest.mark.parametrize("total,count", _SEED_GEOMETRIES)
    def test_bit_identical_trajectories_seeded(self, total, count):
        _check_bit_identical(total, count, steps=3, seed=total * 31 + count)

    def test_plain_call_refused(self):
        compute = make_update_compute(
            _quad_grad_fn,
            optax.adam(0.05),
            ShardStore(tempfile.mkdtemp()),
            params_of=lambda arrays: np.asarray(arrays[0]).ravel(),
        )
        with pytest.raises(RuntimeError, match="versioned"):
            compute(np.zeros(3))
        with pytest.raises(WireError, match="partition"):
            compute.versioned_update([np.zeros(3)], None, 0)

    def test_stale_and_recovery_protocol(self):
        """The exactly-once story at the handler: a repeated stamp
        refuses holds == expected + 1; the refresh lane serves the
        applied slice; an uninitialized refresh and a rewound refresh
        are refused."""
        store = ShardStore(tempfile.mkdtemp())
        compute = make_update_compute(
            _quad_grad_fn,
            optax.adam(0.05),
            store,
            params_of=lambda arrays: np.asarray(arrays[0]).ravel(),
        )
        (part,) = plan_partitions(5, 1)
        x = np.ones(5, np.float32)

        with pytest.raises(WireError, match="no checkpoint"):
            compute.versioned_update([], tuple(part), 0)

        outputs, rv = compute.versioned_update(
            [np.zeros(5, np.float32), x], tuple(part), 0
        )
        assert rv == 1

        # The retry after a lost reply: same stamp, already applied.
        with pytest.raises(StaleShardError) as ei:
            compute.versioned_update(
                [np.zeros(5, np.float32), x], tuple(part), 0
            )
        assert ei.value.holds == 1 and ei.value.expected == 0

        # Recovery: refresh at the node's version.
        ref, ver = compute.versioned_update([], tuple(part), 1)
        assert ver == 1
        state = store.load(part)
        np.testing.assert_array_equal(ref[0], state.params)

        # A refresh ASKING for newer state than the shard holds is
        # refused — serving the old slice would silently rewind.
        with pytest.raises(StaleShardError):
            compute.versioned_update([], tuple(part), 2)

        # A non-zero expectation against a dropped store is divergence.
        store.drop(part)
        with pytest.raises(StaleShardError) as ei:
            compute.versioned_update(
                [np.zeros(5, np.float32), x], tuple(part), 1
            )
        assert ei.value.holds == 0


# ---------------------------------------------------------------------------
# end to end over real transports
# ---------------------------------------------------------------------------


def _start_tcp(compute):
    from pytensor_federated_tpu.service.tcp import serve_tcp_once

    holder = {}
    ready = threading.Event()
    threading.Thread(
        target=serve_tcp_once,
        args=(compute,),
        kwargs=dict(
            port=0,
            ready_callback=lambda p: (holder.update(p=p), ready.set()),
            concurrent=True,
        ),
        daemon=True,
    ).start()
    assert ready.wait(10)
    return holder["p"]


def _make_clients(n, store):
    from pytensor_federated_tpu.service.tcp import TcpArraysClient

    computes = [
        make_update_compute(
            _quad_grad_fn,
            optax.adam(0.05),
            store,
            params_of=lambda arrays: np.asarray(arrays[0]).ravel(),
        )
        for _ in range(n)
    ]
    return [
        TcpArraysClient("127.0.0.1", _start_tcp(c)) for c in computes
    ]


class TestShardedOptimizerTcp:
    def test_uneven_shards_bit_identical_and_residency(self):
        DIM, N = 13, 3
        store = ShardStore(tempfile.mkdtemp())
        clients = _make_clients(N, store)
        try:
            opt = ShardedOptimizer(DIM, clients=clients)
            params = np.zeros(DIM, np.float32)
            oref = optax.adam(0.05)
            params_ref = jnp.zeros(DIM, jnp.float32)
            oref_state = oref.init(params_ref)
            rng = np.random.default_rng(0)
            for _ in range(4):
                x = rng.normal(size=DIM).astype(np.float32)
                results = opt.step([params, x])
                assert all(r.status == "applied" for r in results)
                params, accepted = opt.apply(params, results)
                assert accepted == [0, 1, 2]
                _, g = jax.value_and_grad(_quad_loss)(
                    params_ref, jnp.asarray(x)
                )
                upd, oref_state = oref.update(g, oref_state)
                params_ref = optax.apply_updates(params_ref, upd)
                np.testing.assert_array_equal(
                    params, np.asarray(params_ref)
                )
            assert opt.versions == [4, 4, 4]
            # The residency witness: the driver never saw more than one
            # shard's elements in a reply — O(model/N), not O(model).
            assert opt.max_reply_elems == 5  # ceil(13/3)
            assert opt.max_reply_elems < DIM
        finally:
            for c in clients:
                c.close()

    def test_lost_reply_recovers_without_double_step(self):
        DIM, N = 8, 2
        store = ShardStore(tempfile.mkdtemp())
        clients = _make_clients(N, store)
        try:
            opt = ShardedOptimizer(DIM, clients=clients)
            params = np.zeros(DIM, np.float32)
            x = np.ones(DIM, np.float32)
            results = opt.step([params, x])
            params, _ = opt.apply(params, results)
            # Simulate a lost reply: the driver forgets shard 0's
            # version and re-sends the old stamp.
            opt.versions[0] -= 1
            results = opt.step([params, x])
            assert results[0].status == "recovered"
            assert results[1].status == "applied"
            params2, accepted = opt.apply(params, results)
            assert accepted == [0, 1]
            # Shard 0 stepped exactly ONCE total: the node refused the
            # repeated stamp and recovery handed back the version-1
            # slice (idempotent overwrite, never a double-apply), and
            # the driver ADOPTED the node's version.
            assert opt.versions == [1, 2]
            p0 = opt.parts[0]
            state = store.load(p0)
            assert state.version == 1
            np.testing.assert_array_equal(
                params2[p0.offset : p0.offset + p0.length], state.params
            )
            # The trajectory resynchronizes: the next step applies on
            # both shards from the adopted versions.
            results = opt.step([params2, x])
            assert [r.status for r in results] == ["applied", "applied"]
            assert opt.versions == [2, 3]
        finally:
            for c in clients:
                c.close()

    def test_fresh_driver_divergence_is_loud(self):
        DIM, N = 6, 2
        store = ShardStore(tempfile.mkdtemp())
        clients = _make_clients(N, store)
        try:
            opt = ShardedOptimizer(DIM, clients=clients)
            params = np.zeros(DIM, np.float32)
            x = np.ones(DIM, np.float32)
            # Two steps: a fresh driver's stamp 0 against a node at
            # version 1 is INDISTINGUISHABLE from a lost first reply
            # (and recovers); at version >= 2 it is divergence.
            params, _ = opt.apply(params, opt.step([params, x]))
            params, _ = opt.apply(params, opt.step([params, x]))
            opt2 = ShardedOptimizer(DIM, clients=clients)
            with pytest.raises(WireError, match="diverged"):
                opt2.step([params, x])
        finally:
            for c in clients:
                c.close()

    def test_pool_failover_rebinds_and_restores(self):
        """A dead owner's shard re-binds onto a live replica which
        restores the shard from the SHARED store — optimizer state
        survives replica death."""
        from pytensor_federated_tpu.routing.pool import NodePool

        DIM = 6
        store = ShardStore(tempfile.mkdtemp())
        clients = _make_clients(2, store)  # two live owner replicas
        live_ports = [c.port for c in clients]
        for c in clients:
            c.close()
        pool = NodePool(
            [("127.0.0.1", p) for p in live_ports],
            transport="tcp",
            probe_interval_s=60.0,
        )
        try:
            opt = ShardedOptimizer(DIM, pool=pool, count=1)
            params = np.zeros(DIM, np.float32)
            x = np.ones(DIM, np.float32)
            params, _ = opt.apply(params, opt.step([params, x]))
            bound = opt._owners[0]
            assert bound is not None
            # Force the shard onto a DEAD replica: next step must fail
            # over to the live one and continue from the checkpoint.
            dead = pool.add_replica("127.0.0.1", 1, transport="tcp")
            opt._owners[0] = dead
            results = opt.step([params, x])
            assert results[0].status == "applied"
            assert opt._owners[0].address != dead.address
            assert opt.versions == [2]
        finally:
            pool.close()

    def test_grpc_replica_refused_loudly(self):
        class FakeGrpcClient:
            def evaluate(self, *a, **k):  # pragma: no cover
                return []

        opt = ShardedOptimizer(4, clients=[FakeGrpcClient()])
        with pytest.raises(TypeError, match="versioned"):
            opt.step([np.zeros(4, np.float32)])


# ---------------------------------------------------------------------------
# the StreamingSVI sharded lane
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def radon_compiled():
    from pytensor_federated_tpu import ppl
    from pytensor_federated_tpu.ppl.radon import make_radon_example

    model, args, _true = make_radon_example(8, mean_obs=8, seed=3)
    return ppl.compile(model, args)


def _svi_clients(compiled, n, store):
    from pytensor_federated_tpu.ppl.svi import make_sharded_update_compute
    from pytensor_federated_tpu.service.tcp import TcpArraysClient

    computes = [
        make_sharded_update_compute(
            compiled, store, learning_rate=0.05, n_mc=2
        )
        for _ in range(n)
    ]
    return [
        TcpArraysClient("127.0.0.1", _start_tcp(c)) for c in computes
    ]


class TestStreamingSVISharded:
    def test_bit_identical_to_driver_centric(self, radon_compiled):
        from pytensor_federated_tpu.ppl.svi import StreamingSVI

        ref = StreamingSVI(
            radon_compiled,
            key=jax.random.PRNGKey(7),
            learning_rate=0.05,
            n_mc=2,
        )
        store = ShardStore(tempfile.mkdtemp())
        clients = _svi_clients(radon_compiled, 2, store)
        try:
            opt = ShardedOptimizer(2 * ref.dim, clients=clients)
            svi = StreamingSVI(
                radon_compiled,
                key=jax.random.PRNGKey(7),
                learning_rate=0.05,
                n_mc=2,
                sharded=opt,
            )
            # The driver holds NO optimizer state in sharded mode.
            assert svi._opt is None and svi._opt_state is None
            rng = np.random.default_rng(0)
            for _ in range(3):
                batch = rng.choice(8, size=4, replace=False).astype(
                    np.int32
                )
                assert ref.step(batch) == svi.step(batch) == "accepted"
                np.testing.assert_array_equal(
                    np.asarray(ref.mu), np.asarray(svi.mu)
                )
                np.testing.assert_array_equal(
                    np.asarray(ref.log_sd), np.asarray(svi.log_sd)
                )
            np.testing.assert_array_equal(
                ref.elbo_trace, svi.elbo_trace
            )
            assert svi.opt_steps == svi.accepted == 3
            assert svi.shard_opt_steps == svi.shard_accepted == [3, 3]
            # Residency: one shard's slice, never the 2*dim vector.
            assert opt.max_reply_elems <= -(-2 * ref.dim // 2)
        finally:
            for c in clients:
                c.close()

    def test_split_mode_per_shard_accounting(self, radon_compiled):
        from pytensor_federated_tpu.ppl.svi import StreamingSVI

        store = ShardStore(tempfile.mkdtemp())
        clients = _svi_clients(radon_compiled, 2, store)
        try:
            dim = StreamingSVI(
                radon_compiled, key=jax.random.PRNGKey(0)
            ).dim
            svi = StreamingSVI(
                radon_compiled,
                key=jax.random.PRNGKey(9),
                learning_rate=0.05,
                n_mc=2,
                sharded=ShardedOptimizer(2 * dim, clients=clients),
                minibatch_mode="split",
            )
            for _ in range(3):
                assert svi.step(np.arange(6, dtype=np.int32)) == "accepted"
            assert svi.shard_opt_steps == svi.shard_accepted == [3, 3]
            assert svi.offered == svi.accepted == 3
        finally:
            for c in clients:
                c.close()

    def test_geometry_mismatch_refused_at_construction(
        self, radon_compiled
    ):
        from pytensor_federated_tpu.ppl.svi import StreamingSVI

        with pytest.raises(ValueError, match="covers"):
            StreamingSVI(
                radon_compiled,
                key=jax.random.PRNGKey(0),
                sharded=ShardedOptimizer(3, clients=[object()]),
            )


# ---------------------------------------------------------------------------
# the Reassembler identity satellite (ISSUE 16)
# ---------------------------------------------------------------------------


class TestReassemblerShardIdentity:
    def test_errors_name_geometry_and_iuid(self):
        plan = plan_partitions(10, 2)
        asm = Reassembler(10, 2, np.dtype(np.float64))
        asm.add(plan[0], np.zeros(plan[0].length), iuid="aaaa01")
        with pytest.raises(PartitionError) as ei:
            asm.add(plan[0], np.zeros(plan[0].length), iuid="bbbb02")
        msg = str(ei.value)
        assert "duplicate" in msg
        assert "declared offset=0" in msg and "iuid=bbbb02" in msg
        assert "iuid=aaaa01" in msg  # the first sighting is named too

        with pytest.raises(PartitionError) as ei:
            asm.add(plan[1], np.zeros(3), iuid="cccc03")
        assert "declares length" in str(ei.value)
        assert "iuid=cccc03" in str(ei.value)

    def test_overlap_names_both_shards(self):
        asm = Reassembler(10, 3, np.dtype(np.float64))
        asm.add(GradPartition(0, 3, 0, 5, 10), np.zeros(5), iuid="x1")
        with pytest.raises(PartitionError, match="overlaps"):
            asm.add(
                GradPartition(1, 3, 4, 3, 10), np.zeros(3), iuid="x2"
            )

# ---------------------------------------------------------------------------
# hypothesis twins: drawn payloads/versions and drawn geometries
# ---------------------------------------------------------------------------


if HAVE_HYPOTHESIS:
    _PROP = settings(max_examples=40, deadline=None)
    _h_arrays = st.lists(
        st.integers(min_value=0, max_value=255), max_size=8
    ).map(lambda xs: np.asarray(xs, dtype=np.float32))
    _h_versions = st.integers(min_value=0, max_value=2**64 - 1)

    class TestVersionWireProperties:
        @_PROP
        @given(arr=_h_arrays, version=_h_versions)
        def test_npwire_roundtrip(self, arr, version):
            buf = encode_arrays([arr], uuid=b"u" * 16, version=version)
            assert peek_version(buf) == version
            arrays, _u, err, _t, _s, _p, ver = decode_arrays_part(buf)
            assert err is None and ver == version
            np.testing.assert_array_equal(arrays[0], arr)

        @_PROP
        @given(arr=_h_arrays, version=_h_versions)
        def test_npproto_roundtrip(self, arr, version):
            buf = encode_arrays_msg([arr], "uu", version=version)
            assert peek_version_msg(buf) == version
            arrays, uuid, _e, _t, _s = decode_arrays_msg_full(buf)
            assert uuid == "uu"
            np.testing.assert_array_equal(arrays[0], arr)

        @_PROP
        @given(version=_h_versions, body=st.binary(max_size=32))
        def test_shm_roundtrip(self, version, body):
            frame = shm_mod.encode_frame(
                shm_mod._KIND_EVAL, b"u" * 16, body, version=version
            )
            out = shm_mod.decode_frame(frame)
            assert out[6] == version and out[8][out[7]:] == body

        @_PROP
        @given(arr=_h_arrays)
        def test_absent_version_byte_identity_everywhere(self, arr):
            assert encode_arrays([arr], uuid=b"u" * 16) == encode_arrays(
                [arr], uuid=b"u" * 16, version=None
            )
            assert encode_arrays_msg([arr], "uu") == encode_arrays_msg(
                [arr], "uu", version=None
            )
            body = arr.tobytes()
            assert shm_mod.encode_frame(
                shm_mod._KIND_EVAL, b"u" * 16, body
            ) == shm_mod.encode_frame(
                shm_mod._KIND_EVAL, b"u" * 16, body, version=None
            )

    class TestShardGeometryProperty:
        @settings(max_examples=15, deadline=None)
        @given(
            total=st.integers(min_value=1, max_value=30),
            count=st.integers(min_value=1, max_value=6),
            seed=st.integers(min_value=0, max_value=2**16),
        )
        def test_bit_identical_trajectories(self, total, count, seed):
            _check_bit_identical(total, count, steps=2, seed=seed)
