"""End-to-end flagship model test: federated NUTS recovers the truth.

The reference's accuracy bar: posterior median slope = 2 +/- 0.1 after
MCMC over the federated likelihood (reference: test_wrapper_ops.py:105-117)
and golden-model equivalence of federated vs native logp
(reference: test_demo_node.py:68-110).
"""

import jax
import jax.numpy as jnp
import numpy as np

from pytensor_federated_tpu.models.linear import (
    FederatedLinearRegression,
    generate_node_data,
)


def test_federated_matches_unsharded_logp(mesh8):
    data, _ = generate_node_data(8, n_obs=32)
    on_mesh = FederatedLinearRegression(data, mesh=mesh8)
    single = FederatedLinearRegression(data, mesh=None)
    p = on_mesh.init_params()
    p = jax.tree_util.tree_map(lambda x: x + 0.1, p)
    np.testing.assert_allclose(on_mesh.logp(p), single.logp(p), rtol=1e-5)
    v1, g1 = on_mesh.logp_and_grad(p)
    v2, g2 = single.logp_and_grad(p)
    np.testing.assert_allclose(v1, v2, rtol=1e-5)
    for k in g1:
        np.testing.assert_allclose(g1[k], g2[k], rtol=1e-4, atol=1e-5)


def test_map_recovers_truth():
    data, _ = generate_node_data(8, n_obs=64, seed=1)
    model = FederatedLinearRegression(data)
    est = model.find_map(num_steps=1500, learning_rate=0.05)
    assert abs(float(est["slope"]) - 2.0) < 0.1
    assert abs(float(est["intercept"]) - 1.5) < 0.2
    assert abs(float(jnp.exp(est["log_sigma"])) - 0.5) < 0.15


def test_nuts_posterior_recovers_slope(mesh8):
    """Full federated NUTS on the mesh: slope = 2 +/- 0.1."""
    data, _ = generate_node_data(8, n_obs=64, seed=2)
    model = FederatedLinearRegression(data, mesh=mesh8)
    res = model.sample(
        key=jax.random.PRNGKey(3),
        num_warmup=400,
        num_samples=400,
        num_chains=2,
        jitter=0.1,
    )
    slope = np.asarray(res.samples["slope"])
    assert abs(np.median(slope) - 2.0) < 0.1
    intercept = np.asarray(res.samples["intercept"])
    assert abs(np.median(intercept) - 1.5) < 0.25
    assert np.asarray(res.stats["diverging"]).mean() < 0.1


def test_heterogeneous_node_sizes():
    """Different private dataset sizes per node (reference capability)."""
    data, _ = generate_node_data(4, n_obs=[10, 33, 57, 8], seed=4)
    model = FederatedLinearRegression(data)
    est = model.find_map(num_steps=1200)
    assert abs(float(est["slope"]) - 2.0) < 0.15


def test_suffstats_matches_raw_logp():
    """Sufficient-statistics representation evaluates the identical
    posterior: same logp and grads as the raw-data likelihood, at
    several parameter points, including heterogeneous shard sizes."""
    data, _ = generate_node_data(6, n_obs=[7, 64, 33, 12, 50, 1], seed=5)
    raw = FederatedLinearRegression(data)
    ss = FederatedLinearRegression(data, use_suffstats=True)
    p0 = raw.init_params()
    for shift in (0.0, 0.3, -1.1):
        p = jax.tree_util.tree_map(lambda x: x + shift, p0)
        np.testing.assert_allclose(
            float(ss.logp(p)), float(raw.logp(p)), rtol=2e-4
        )
        v1, g1 = ss.logp_and_grad(p)
        v2, g2 = raw.logp_and_grad(p)
        np.testing.assert_allclose(float(v1), float(v2), rtol=2e-4)
        for k in g1:
            np.testing.assert_allclose(
                np.asarray(g1[k]), np.asarray(g2[k]), rtol=2e-3, atol=1e-3
            )


def test_suffstats_on_mesh(mesh8):
    """Suffstat shards ride the mesh exactly like raw shards."""
    data, _ = generate_node_data(8, n_obs=16, seed=6)
    on_mesh = FederatedLinearRegression(data, mesh=mesh8, use_suffstats=True)
    single = FederatedLinearRegression(data, use_suffstats=True)
    p = jax.tree_util.tree_map(lambda x: x + 0.2, on_mesh.init_params())
    np.testing.assert_allclose(
        float(on_mesh.logp(p)), float(single.logp(p)), rtol=1e-5
    )


def test_suffstats_posterior_sampling():
    """NUTS over the suffstat likelihood recovers the slope — the
    reference's accuracy bar (test_wrapper_ops.py:105-117) holds on the
    compressed representation too."""
    data, _ = generate_node_data(8, n_obs=64, seed=7)
    model = FederatedLinearRegression(data, use_suffstats=True)
    res = model.sample(
        key=jax.random.PRNGKey(8),
        num_warmup=300,
        num_samples=300,
        num_chains=2,
        jitter=0.1,
    )
    slope = np.median(np.asarray(res.samples["slope"]))
    assert abs(slope - 2.0) < 0.1
