"""docs/tutorial.md's code blocks actually run.

Extracts every ```python block and execs them in order in one shared
namespace (the tutorial is written as a single continuous session).
Sampling sizes are shrunk by regex so the test stays fast — everything
else runs exactly as printed, so a renamed API breaks this test before
it breaks a user.
"""

import math
import re
from pathlib import Path

DOC = Path(__file__).resolve().parent.parent / "docs" / "tutorial.md"


def _blocks():
    text = DOC.read_text()
    return re.findall(r"```python\n(.*?)```", text, flags=re.S)


def test_tutorial_blocks_execute():
    ns: dict = {}
    blocks = _blocks()
    assert len(blocks) >= 5
    shrinks = {
        "num_warmup=500": "num_warmup=50",
        "num_samples=500": "num_samples=50",
        "num_chains=4": "num_chains=2",
        "num_draws=200": "num_draws=10",
    }
    seen = set()
    for i, block in enumerate(blocks):
        # shrink the expensive sampling calls; leave everything else
        for old, new in shrinks.items():
            if old in block:
                seen.add(old)
                block = block.replace(old, new)
        exec(compile(block, f"{DOC.name}:block{i}", "exec"), ns)
    # every shrink must have matched — a drifted literal would silently
    # run the full-size sampler
    assert seen == set(shrinks), f"unmatched shrinks: {set(shrinks) - seen}"
    # spot-check the session produced what the prose claims
    assert ns["sims"].shape[0] == 10
    assert math.isfinite(float(ns["logp"]))
