"""Fleet observability plane (ISSUE 11): collector merge semantics,
loud staleness, clock-aligned timelines, critical-path attribution,
and the SLO burn-rate engine.

The pure halves (merge math, stage decomposition, burn windows) run on
synthetic data; the e2e half spawns real gRPC nodes (the
test_service.py spawn pattern) and exercises the GetLoad
``b"telemetry"`` pull lane, the HTTP ``/snapshot`` fallback lane, and
the SIGKILL-mid-collection staleness contract across real process
boundaries.
"""

import os
import time

import numpy as np
import pytest
from conftest import spawn_node_procs, wait_nodes_up

from pytensor_federated_tpu import telemetry
from pytensor_federated_tpu.telemetry import (
    collector as coll_mod,
    critpath,
    flightrec,
    metrics as metrics_mod,
    reunion,
    slo as slo_mod,
)
from pytensor_federated_tpu.telemetry.collector import (
    LOCAL_REPLICA,
    FleetCollector,
    FleetMergeError,
    merge_metric_snapshots,
    merged_quantile,
)
from pytensor_federated_tpu.telemetry.slo import BurnRateEngine, Slo

BASE_PORT = 29720


@pytest.fixture(autouse=True)
def _telemetry_isolation():
    prev = telemetry.set_enabled(True)
    telemetry.REGISTRY.reset()
    telemetry.clear_traces()
    flightrec.clear()
    reunion.clear()
    yield
    telemetry.REGISTRY.reset()
    telemetry.clear_traces()
    flightrec.clear()
    reunion.clear()
    telemetry.set_enabled(prev)


# ---------------------------------------------------------------------------
# merge semantics (pure)
# ---------------------------------------------------------------------------


def _mk_registry():
    return metrics_mod.Registry()


class TestMergeSnapshots:
    def test_counters_sum_histograms_merge_gauges_split(self):
        r1, r2 = _mk_registry(), _mk_registry()
        for r, inc, obs in ((r1, 3, [0.002, 0.3]), (r2, 2, [0.004])):
            c = r.counter("pftpu_t_total", "t", ("k",))
            c.labels(k="a").inc(inc)
            h = r.histogram("pftpu_t_seconds", "t")
            for v in obs:
                h.observe(v)
            g = r.gauge("pftpu_t_inflight", "t")
            g.set(inc)
        merged = merge_metric_snapshots(
            {
                "n1": metrics_mod.snapshot(r1),
                "n2": metrics_mod.snapshot(r2),
            }
        )
        (counter_child,) = merged["pftpu_t_total"]["children"]
        assert counter_child == {"labels": {"k": "a"}, "value": 5.0}
        (hist_child,) = merged["pftpu_t_seconds"]["children"]
        assert hist_child["count"] == 3
        assert hist_child["sum"] == pytest.approx(0.306)
        assert sum(hist_child["buckets"].values()) == 3
        gauges = {
            child["labels"]["replica"]: child["value"]
            for child in merged["pftpu_t_inflight"]["children"]
        }
        assert gauges == {"n1": 3.0, "n2": 2.0}

    def test_gauge_with_existing_replica_label_keeps_it(self):
        r = _mk_registry()
        g = r.gauge("pftpu_t_up", "t", ("replica",))
        g.labels(replica="10.0.0.1:50052").set(1)
        merged = merge_metric_snapshots(
            {"driver": metrics_mod.snapshot(r)}
        )
        (child,) = merged["pftpu_t_up"]["children"]
        assert child["labels"]["replica"] == "10.0.0.1:50052"
        assert child["labels"]["source"] == "driver"

    def test_bucket_ladder_mismatch_is_loud(self):
        r1, r2 = _mk_registry(), _mk_registry()
        r1.histogram("pftpu_t_seconds", "t", buckets=(0.1, 1.0)).observe(
            0.5
        )
        r2.histogram("pftpu_t_seconds", "t", buckets=(0.2, 2.0)).observe(
            0.5
        )
        with pytest.raises(FleetMergeError, match="bucket ladder"):
            merge_metric_snapshots(
                {
                    "n1": metrics_mod.snapshot(r1),
                    "n2": metrics_mod.snapshot(r2),
                }
            )

    def test_type_conflict_is_loud(self):
        r1, r2 = _mk_registry(), _mk_registry()
        r1.counter("pftpu_t_thing", "t").inc()
        r2.gauge("pftpu_t_thing", "t").set(1)
        with pytest.raises(FleetMergeError, match="type"):
            merge_metric_snapshots(
                {
                    "n1": metrics_mod.snapshot(r1),
                    "n2": metrics_mod.snapshot(r2),
                }
            )

    def test_merged_quantile(self):
        r1, r2 = _mk_registry(), _mk_registry()
        for _ in range(99):
            r1.histogram("pftpu_t_seconds", "t").observe(0.002)
        r2.histogram("pftpu_t_seconds", "t").observe(0.3)
        merged = merge_metric_snapshots(
            {
                "n1": metrics_mod.snapshot(r1),
                "n2": metrics_mod.snapshot(r2),
            }
        )
        fam = merged["pftpu_t_seconds"]
        assert merged_quantile(fam, 0.5) == pytest.approx(0.0025)
        assert merged_quantile(fam, 0.999) == pytest.approx(0.5)
        assert np.isnan(merged_quantile(None, 0.5))


# ---------------------------------------------------------------------------
# critical-path decomposition (pure)
# ---------------------------------------------------------------------------


def _span(name, dur, children=(), **attrs):
    d = {"name": name, "duration_s": dur, "trace_id": "aa" * 16}
    if attrs:
        d["attrs"] = attrs
    if children:
        d["children"] = list(children)
    return d


def _merged_trace(queue_wait=0.004):
    node = _span(
        "node.evaluate",
        0.0061,
        [
            _span("compute", 0.005 + queue_wait, queue_wait_s=queue_wait),
            _span("encode", 0.001),
        ],
        decode_s=0.0004,
    )
    driver = _span(
        "rpc.evaluate",
        0.0105,
        [
            _span("encode", 0.001),
            _span("call", 0.008),
            _span("decode", 0.001),
        ],
    )
    return {"trace_id": "aa" * 16, "driver": [driver], "remote": [node]}


class TestCritpath:
    def test_stage_attribution(self):
        rec = critpath.decompose_trace(_merged_trace())
        assert rec["driver_encode"] == pytest.approx(0.001)
        assert rec["driver_decode"] == pytest.approx(0.001)
        assert rec["node_decode"] == pytest.approx(0.0004)
        assert rec["node_queue"] == pytest.approx(0.004)
        assert rec["node_compute"] == pytest.approx(0.005)
        assert rec["node_encode"] == pytest.approx(0.001)
        # wire = call (0.008) - node total (0.0061 + 0.0004)
        assert rec["wire"] == pytest.approx(0.0015)
        assert rec["dominant"] == "node_compute"
        assert rec["coverage_frac"] > 0.9

    def test_pool_wrapped_trace_uses_innermost_call(self):
        inner = _span(
            "rpc.evaluate",
            0.009,
            [_span("encode", 0.001), _span("call", 0.007),
             _span("decode", 0.0005)],
        )
        attempt = _span(
            "pool.attempt", 0.0095, [inner], replica="127.0.0.1:1"
        )
        driver = _span("pool.evaluate", 0.01, [attempt])
        merged = {"trace_id": "bb", "driver": [driver], "remote": []}
        rec = critpath.decompose_trace(merged)
        # No node tree came home: the whole call interval stays wire.
        assert rec["wire"] == pytest.approx(0.007)
        assert rec["replicas"] == {
            "127.0.0.1:1": pytest.approx(0.0095)
        }

    def test_node_only_trace_is_skipped_not_invented(self):
        merged = {
            "trace_id": "cc",
            "driver": [],
            "remote": [_span("node.evaluate", 0.005)],
        }
        assert critpath.decompose_trace(merged) is None
        report = critpath.analyze([merged, _merged_trace()])
        assert report["n_skipped"] == 1
        assert report["n_traces"] == 1

    def test_report_aggregation_and_format(self):
        traces = [_merged_trace(queue_wait=q) for q in
                  (0.001, 0.002, 0.02)]
        report = critpath.analyze(traces)
        assert report["dominant_stage"]  # non-empty
        assert 0.0 < report["coverage_frac"] <= 1.0
        text = critpath.format_report(report)
        assert "node_queue" in text and "coverage" in text

    def test_fanout_straggler_diagnosis(self):
        members = [
            _span("fanout.member", d, idx=i)
            for i, d in enumerate((0.001, 0.001, 0.009))
        ]
        fan = _span(
            "fanout", 0.0095, members, width=3, straggler_gap_s=0.008
        )
        driver = _span("rpc.evaluate", 0.01,
                       [_span("call", 0.0096), fan])
        report = critpath.analyze(
            [{"trace_id": "dd", "driver": [driver], "remote": []}]
        )
        fanout = report["fanout"]
        assert fanout["n_fanouts"] == 1
        assert fanout["straggler_gap_p99_s"] == pytest.approx(0.008)
        assert fanout["slowest_member_counts"] == {"2": 1}


# ---------------------------------------------------------------------------
# SLO burn-rate engine (pure)
# ---------------------------------------------------------------------------


class _FakeScrape:
    def __init__(self, m):
        self.ok = True
        self.metrics = m


class _FakeSnapshot:
    def __init__(self, ts, per_replica):
        self.ts = ts
        self.replicas = {
            a: _FakeScrape(m) for a, m in per_replica.items()
        }


def _node_metrics_snapshot(requests, bad, total, sheds=0):
    r = _mk_registry()
    c = r.counter("pftpu_server_requests_total", "x", ("method",))
    c.labels(method="evaluate").inc(requests)
    s = r.counter("pftpu_admission_shed_total", "x", ("reason",))
    if sheds:
        s.labels(reason="expired").inc(sheds)
    h = r.histogram(
        "pftpu_client_call_seconds", "x", ("transport", "mode")
    )
    child = h.labels(transport="grpc", mode="unary")
    for _ in range(bad):
        child.observe(0.3)
    for _ in range(total - bad):
        child.observe(0.002)
    return metrics_mod.snapshot(r)


class TestBurnRateEngine:
    def test_requires_an_objective(self):
        with pytest.raises(ValueError, match="objective"):
            Slo()

    def test_burn_spikes_then_reconverges(self):
        engine = BurnRateEngine(
            Slo(p99_s=0.05, goodput_min=1.0), windows_s=(10.0,)
        )
        engine.observe(
            _FakeSnapshot(100.0, {"n": _node_metrics_snapshot(10, 0, 10)})
        )
        degraded = engine.observe(
            _FakeSnapshot(
                105.0, {"n": _node_metrics_snapshot(30, 10, 30)}
            )
        )
        assert degraded["burn_rate"] > 1.0
        assert degraded["violating"]
        assert degraded["windows"]["10s"]["objectives"]["p99"] > 1.0
        # slo.burn is flight-recorded on violation
        assert any(
            e["kind"] == "slo.burn" for e in flightrec.events()
        )
        healed = engine.observe(
            _FakeSnapshot(
                114.0, {"n": _node_metrics_snapshot(90, 10, 90)}
            )
        )
        assert healed["burn_rate"] is not None
        assert healed["burn_rate"] <= 1.0
        assert not healed["violating"]

    def test_shed_objective(self):
        engine = BurnRateEngine(
            Slo(shed_frac_max=0.05), windows_s=(10.0,)
        )
        engine.observe(
            _FakeSnapshot(0.0, {"n": _node_metrics_snapshot(10, 0, 10)})
        )
        rep = engine.observe(
            _FakeSnapshot(
                5.0,
                {"n": _node_metrics_snapshot(30, 0, 30, sheds=10)},
            )
        )
        # 10 sheds / 20 requests = 0.5 shed frac over a 0.05 budget
        assert rep["windows"]["10s"]["objectives"]["shed"] == (
            pytest.approx(10.0)
        )

    def test_replica_death_cannot_go_negative(self):
        engine = BurnRateEngine(Slo(goodput_min=1.0), windows_s=(10.0,))
        engine.observe(
            _FakeSnapshot(
                0.0,
                {
                    "a": _node_metrics_snapshot(100, 0, 100),
                    "b": _node_metrics_snapshot(100, 0, 100),
                },
            )
        )
        # replica b died: only a remains, and its counter moved on
        rep = engine.observe(
            _FakeSnapshot(
                5.0, {"a": _node_metrics_snapshot(110, 0, 110)}
            )
        )
        window = rep["windows"]["10s"]
        assert window["requests"] == pytest.approx(10.0)
        assert window["goodput_rps"] == pytest.approx(2.0)

    def test_counter_reset_counts_new_history(self):
        engine = BurnRateEngine(Slo(goodput_min=1.0), windows_s=(10.0,))
        engine.observe(
            _FakeSnapshot(0.0, {"a": _node_metrics_snapshot(100, 0, 100)})
        )
        rep = engine.observe(
            _FakeSnapshot(5.0, {"a": _node_metrics_snapshot(4, 0, 4)})
        )
        # restart: the new process's whole history (4) is the window's
        # increase — never a negative delta
        assert rep["windows"]["10s"]["requests"] == pytest.approx(4.0)
        assert rep["windows"]["10s"]["burn_rate"] is not None

    def test_p99_line_inside_a_bucket_counts_straddlers_bad(self):
        # bounds 0.1 / 0.25 / 0.5; every call lands in (0.1, 0.25]
        hist = (10, {0.1: 0, 0.25: 10, 0.5: 0})
        # a line ON a bucket bound: that bucket's calls are good
        assert slo_mod._frac_over(hist, 0.25) == 0.0
        # a line INSIDE a bucket: conservative — the whole straddling
        # bucket counts against the budget (0.24 s calls violate a
        # 0.2 s line; rounding the line up instead would report zero
        # burn for a fleet that is 100% out of SLO)
        assert slo_mod._frac_over(hist, 0.2) == 1.0

    def test_single_sample_has_no_burn(self):
        engine = BurnRateEngine(Slo(goodput_min=1.0), windows_s=(10.0,))
        rep = engine.observe(
            _FakeSnapshot(0.0, {"a": _node_metrics_snapshot(1, 0, 1)})
        )
        assert rep["burn_rate"] is None
        assert not rep["violating"]


# ---------------------------------------------------------------------------
# e2e: real nodes, real lanes, real SIGKILL
# ---------------------------------------------------------------------------


def _serve_plain_node(port):
    import logging

    import numpy as _np

    logging.basicConfig(level=logging.WARNING)

    def compute(x):
        x = _np.asarray(x)
        return [
            _np.asarray(-_np.sum((x - 3.0) ** 2)),
            (-2.0 * (x - 3.0)).astype(x.dtype),
        ]

    from pytensor_federated_tpu.service import run_node

    run_node(compute, "127.0.0.1", port, inline_compute=True)


@pytest.mark.slow
def test_fleet_collector_e2e_scrape_merge_timeline_and_sigkill():
    from pytensor_federated_tpu.routing import (
        NodePool,
        PooledArraysClient,
    )

    ports = [BASE_PORT, BASE_PORT + 1]
    procs = spawn_node_procs(_serve_plain_node, [(p,) for p in ports])
    pool = None
    collector = None
    try:
        wait_nodes_up(ports)
        pool = NodePool(
            [("127.0.0.1", p) for p in ports],
            policy="round_robin",
            client_kwargs=dict(use_stream=False),
        )
        client = PooledArraysClient(pool)
        x = np.zeros(3, np.float32)
        for _ in range(10):
            client.evaluate(x)

        engine = BurnRateEngine(
            Slo(p99_s=0.05, goodput_min=0.1), windows_s=(5.0,)
        )
        collector = pool.start_collector(
            interval_s=0.2, observers=[engine.observe]
        )
        deadline = time.time() + 30.0
        while collector.latest() is None and time.time() < deadline:
            time.sleep(0.05)
        snap = collector.latest()
        assert snap is not None and snap.complete, (
            None if snap is None else (snap.stale, snap.unscraped)
        )
        addrs = {f"127.0.0.1:{p}" for p in ports}
        assert addrs | {LOCAL_REPLICA} == set(snap.replicas)

        # merged: node counters summed across both replicas, and the
        # driver's own client families present via the local replica
        req = snap.merged["pftpu_server_requests_total"]
        total = sum(
            c["value"]
            for c in req["children"]
            if c["labels"].get("method") == "evaluate"
        )
        assert total >= 10
        assert "pftpu_client_call_seconds" in snap.merged

        # clock offsets estimated, loopback-small
        for addr in addrs:
            offset = snap.replicas[addr].clock_offset_s
            assert offset is not None and abs(offset) < 1.0

        # the timeline interleaves node events with driver events
        timeline = snap.timeline()
        sources = {e["replica"] for e in timeline}
        assert LOCAL_REPLICA in sources
        assert sources & addrs, sources
        fleet_ts = [e["ts_fleet"] for e in timeline]
        assert fleet_ts == sorted(fleet_ts)

        # critical-path over the reunion store: ≥ 90% attributed
        report = critpath.analyze_recent()
        assert report["n_traces"] >= 10
        assert report["coverage_frac"] >= 0.9, report

        # incident bundles embed the fleet picture while a collector
        # is live, and the renderer shows it
        bundle_path = telemetry.write_incident_bundle(
            "test-fleet", dir=str(_tmp_incident_dir())
        )
        import json

        with open(bundle_path) as fh:
            bundle = json.load(fh)
        assert "fleet" in bundle
        # Always a list (one entry per live collector), so bundle
        # consumers never shape-switch on collector count.
        (fleet,) = bundle["fleet"]
        assert fleet["timeline"], "bundle timeline empty"
        import subprocess
        import sys

        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(
                    os.path.dirname(__file__), "..", "tools",
                    "incident_report.py",
                ),
                bundle_path,
            ],
            capture_output=True,
            text=True,
            timeout=60,
        )
        assert proc.returncode == 0, proc.stderr
        assert "Fleet (clock-aligned cross-process timeline)" in (
            proc.stdout
        )

        # SIGKILL one replica, then sweep: loud staleness, merged view
        # excludes the corpse, flightrec records the verdict
        procs[0].kill()
        procs[0].join(timeout=10)
        dead = f"127.0.0.1:{ports[0]}"
        flightrec.clear()
        snap2 = collector.scrape_once()
        assert dead in snap2.stale
        assert not snap2.complete
        assert not snap2.replicas[dead].ok
        assert snap2.replicas[dead].error
        stale_events = [
            e
            for e in flightrec.events()
            if e["kind"] == "collector.replica_stale"
        ]
        assert any(e.get("replica") == dead for e in stale_events)
        # the dead replica contributes nothing to the merged registry
        for child in snap2.merged.get(
            "pftpu_collector_clock_offset_seconds", {}
        ).get("children", ()):
            assert child["labels"].get("replica") != dead or (
                child["labels"].get("source") == LOCAL_REPLICA
            )
        # the engine keeps observing without torn aggregates
        report2 = engine.observe(snap2)
        for window in report2["windows"].values():
            if window.get("requests") is not None:
                assert window["requests"] >= 0.0
    finally:
        if collector is not None:
            collector.stop()
        if pool is not None:
            pool.close()
        for p in procs:
            p.terminate()
        for p in procs:
            p.join(timeout=10)


def _tmp_incident_dir():
    import tempfile

    d = os.path.join(tempfile.gettempdir(), "pftpu-test-fleet")
    os.makedirs(d, exist_ok=True)
    return d


def test_http_fallback_lane_scrapes_snapshot_endpoint():
    from pytensor_federated_tpu.service import _node_metrics

    _node_metrics.REQUESTS.labels(method="evaluate").inc(7)
    exporter = telemetry.start_exporter(port=0)
    try:
        collector = FleetCollector(
            http_targets=[("127.0.0.1", exporter.port)],
            include_local=False,
        )
        snap = collector.scrape_once()
        addr = f"127.0.0.1:{exporter.port}"
        assert snap.complete
        scrape = snap.replicas[addr]
        assert scrape.ok and scrape.lane == "http"
        assert scrape.clock_offset_s is not None
        # /snapshot carries the flight-record tail (same composition
        # as the GetLoad lane) so http-scraped replicas contribute
        # events to the fleet timeline, not an empty list
        flightrec.record("unit.http_lane", hint=1)
        # free-form attrs (numpy scalars included) must degrade to
        # strings in the /snapshot JSON, never fail the scrape
        with telemetry.span("http.numpy", value=np.float32(1.5)):
            pass
        snap2 = collector.scrape_once()
        assert snap2.replicas[addr].ok, snap2.replicas[addr].error
        events = snap2.replicas[addr].flightrec
        assert any(e["kind"] == "unit.http_lane" for e in events)
        total = sum(
            c["value"]
            for c in snap.merged["pftpu_server_requests_total"][
                "children"
            ]
        )
        assert total >= 7
    finally:
        exporter.close()


def test_http_alias_records_under_serving_address():
    """The mapping form of http_targets: a tcp/shm pool replica's
    exporter (necessarily a different socket) is scraped but recorded
    under the replica's SERVING address — joining the fleet view under
    its own name instead of sitting in unscraped forever."""
    exporter = telemetry.start_exporter(port=0)
    try:
        serving = "127.0.0.1:5000"  # never dialed: only the exporter is
        collector = FleetCollector(
            http_targets={serving: ("127.0.0.1", exporter.port)},
            include_local=False,
        )
        snap = collector.scrape_once()
        assert serving in snap.replicas
        assert snap.replicas[serving].ok
        assert snap.replicas[serving].lane == "http"
        assert snap.complete
    finally:
        exporter.close()


def test_collector_unreachable_http_target_is_stale_not_hung():
    collector = FleetCollector(
        http_targets=[("127.0.0.1", 1)],
        include_local=False,
        timeout_s=1.0,
    )
    t0 = time.monotonic()
    snap = collector.scrape_once()
    assert time.monotonic() - t0 < 10.0
    assert snap.stale == ["127.0.0.1:1"]
    assert not snap.complete


def test_zero_item_probe_frames_count_as_probe_not_goodput():
    """A zero-item batch frame is the pool's capability/health probe:
    it must count under method="probe" (excluded from the SLO engine's
    goodput objective) so an idle-but-probed tcp/shm fleet never
    pages on a goodput floor."""
    from pytensor_federated_tpu.service import _node_metrics
    from pytensor_federated_tpu.service.npwire import encode_batch
    from pytensor_federated_tpu.service.tcp import serve_npwire_payload

    def compute(x):
        return [np.asarray(x)]

    def count(method):
        return sum(
            v
            for _n, labels, v in _node_metrics.REQUESTS.samples()
            if labels.get("method") == method
        )

    before_probe = count("probe")
    before_batch = count("evaluate_batch")
    before_hist = (
        _node_metrics.DECODE_S.count,
        _node_metrics.QUEUE_S.count,
        _node_metrics.COMPUTE_S.count,
        _node_metrics.ENCODE_S.count,
    )
    serve_npwire_payload(compute, encode_batch([], uuid=b"\0" * 16))
    assert count("probe") == before_probe + 1
    assert count("evaluate_batch") == before_batch
    assert "probe" not in slo_mod._EVALUATE_METHODS
    # probes must not dilute the latency quantiles the fleet merges
    assert before_hist == (
        _node_metrics.DECODE_S.count,
        _node_metrics.QUEUE_S.count,
        _node_metrics.COMPUTE_S.count,
        _node_metrics.ENCODE_S.count,
    )


def test_tcp_template_node_emits_server_histograms():
    """Satellite: serve_tcp_once now records the shared pftpu_server_*
    families (previously a documented gap), so TCP/shm template nodes
    aggregate into the fleet view like gRPC nodes."""
    import threading

    from pytensor_federated_tpu.service import (
        TcpArraysClient,
        serve_tcp_once,
    )
    from pytensor_federated_tpu.service import _node_metrics

    def compute(x):
        return [np.asarray(x) * 2.0]

    before_req = sum(
        v for _n, _l, v in _node_metrics.REQUESTS.samples()
    )
    before_compute = _node_metrics.COMPUTE_S.count
    ports = []
    thread = threading.Thread(
        target=serve_tcp_once,
        args=(compute,),
        kwargs=dict(ready_callback=ports.append, max_connections=1),
        daemon=True,
    )
    thread.start()
    deadline = time.time() + 15.0
    while not ports and time.time() < deadline:
        time.sleep(0.01)
    assert ports, "tcp node did not come up"
    client = TcpArraysClient("127.0.0.1", ports[0])
    try:
        (out,) = client.evaluate(np.ones(4, np.float32))
        np.testing.assert_allclose(out, 2.0 * np.ones(4))
    finally:
        client.close()
    thread.join(timeout=10)
    after_req = sum(
        v for _n, _l, v in _node_metrics.REQUESTS.samples()
    )
    assert after_req > before_req
    assert _node_metrics.COMPUTE_S.count > before_compute
    assert _node_metrics.DECODE_S.count > 0
    assert _node_metrics.ENCODE_S.count > 0
