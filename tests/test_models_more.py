"""GLM / logistic / ODE model tests: recovery + mesh equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu.models import (
    FederatedLogisticRegression,
    HierarchicalRadonGLM,
    generate_logistic_data,
    generate_radon_data,
    make_lv_model,
    rk4_integrate,
)


# ---- hierarchical radon GLM ----


def test_radon_mesh_matches_single(mesh8):
    data, _ = generate_radon_data(16, seed=5)
    m1 = HierarchicalRadonGLM(data, mesh=mesh8)
    m0 = HierarchicalRadonGLM(data)
    p = jax.tree_util.tree_map(lambda x: x + 0.05, m0.init_params())
    np.testing.assert_allclose(m1.logp(p), m0.logp(p), rtol=1e-5)
    v1, g1 = m1.logp_and_grad(p)
    v0, g0 = m0.logp_and_grad(p)
    for k in g0:
        np.testing.assert_allclose(g1[k], g0[k], rtol=1e-4, atol=1e-5)


def test_radon_map_recovers_beta():
    data, true = generate_radon_data(16, mean_obs=40, seed=6)
    model = HierarchicalRadonGLM(data)
    est = model.find_map(num_steps=2000, learning_rate=0.02)
    assert abs(float(est["beta"]) - true["beta"]) < 0.15
    assert abs(float(est["mu_alpha"]) - true["mu_alpha"]) < 0.3


def test_radon_nuts_short_chain():
    data, true = generate_radon_data(8, mean_obs=24, seed=7)
    model = HierarchicalRadonGLM(data)
    res = model.sample(
        key=jax.random.PRNGKey(0),
        num_warmup=300,
        num_samples=300,
        num_chains=2,
        jitter=0.1,
    )
    beta = np.asarray(res.samples["beta"])
    assert abs(np.median(beta) - true["beta"]) < 0.3
    assert np.asarray(res.stats["diverging"]).mean() < 0.1


# ---- federated logistic regression ----


def test_logistic_map_recovers_weights(mesh8):
    data, true = generate_logistic_data(n_shards=16, n_obs=64, n_features=4)
    model = FederatedLogisticRegression(data, mesh=mesh8)
    est = model.find_map(num_steps=2000, learning_rate=0.05)
    np.testing.assert_allclose(est["w"], true["w"], atol=0.25)
    assert abs(float(est["b"]) - true["b"]) < 0.25


def test_logistic_64_shards_single_device():
    data, true = generate_logistic_data(n_shards=64, n_obs=32, n_features=4)
    model = FederatedLogisticRegression(data)
    v, g = model.logp_and_grad(model.init_params())
    assert np.isfinite(float(v))
    assert g["w"].shape == (4,)


# ---- hierarchical logistic regression ----


def test_hier_logistic_golden_logp():
    """Hand-computed log-posterior on a tiny case (golden-model
    pattern, reference: test_demo_node.py:29-65)."""
    from pytensor_federated_tpu.models.logistic import (
        HierarchicalLogisticRegression,
        generate_hier_logistic_data,
    )

    data, _ = generate_hier_logistic_data(n_shards=4, n_obs=8, n_features=2)
    model = HierarchicalLogisticRegression(data)
    rng = np.random.default_rng(0)
    params = {
        "w": jnp.asarray(rng.normal(size=2).astype(np.float32)),
        "b0": jnp.asarray(0.3, jnp.float32),
        "log_tau": jnp.asarray(-0.2, jnp.float32),
        "b_raw": jnp.asarray(rng.normal(size=4).astype(np.float32)),
    }
    (X, y), mask = data.tree()
    Xn, yn, mn = (np.asarray(a, np.float64) for a in (X, y, mask))
    w = np.asarray(params["w"], np.float64)
    b0, log_tau = 0.3, -0.2
    tau = np.exp(log_tau)
    b_raw = np.asarray(params["b_raw"], np.float64)
    b = b0 + tau * b_raw
    want = 0.0
    for i in range(4):
        logits = Xn[i] @ w + b[i]
        want += np.sum(
            mn[i] * (yn[i] * logits - np.logaddexp(0.0, logits))
        )
    s = 5.0
    want += np.sum(
        -0.5 * (w / s) ** 2 - np.log(s) - 0.5 * np.log(2 * np.pi)
    )
    want += -0.5 * (b0 / s) ** 2 - np.log(s) - 0.5 * np.log(2 * np.pi)
    want += -0.5 * tau**2 + log_tau
    want += np.sum(-0.5 * b_raw**2 - 0.5 * np.log(2 * np.pi))
    np.testing.assert_allclose(float(model.logp(params)), want, rtol=1e-5)


def test_hier_logistic_map_recovers(mesh8):
    from pytensor_federated_tpu.models.logistic import (
        HierarchicalLogisticRegression,
        generate_hier_logistic_data,
    )

    data, true = generate_hier_logistic_data(
        n_shards=16, n_obs=128, n_features=4, tau=0.8
    )
    model = HierarchicalLogisticRegression(data, mesh=mesh8)
    est = model.find_map(num_steps=2500, learning_rate=0.05)
    np.testing.assert_allclose(est["w"], true["w"], atol=0.3)
    # Per-shard intercepts track the generating ones (partial pooling
    # shrinks them, so correlation is the right check, not closeness).
    b_est = np.asarray(model.intercepts(est))
    r = np.corrcoef(b_est, true["b"])[0, 1]
    assert r > 0.8, r


# ---- Lotka-Volterra ODE ----


def test_rk4_conserves_lv_cycles():
    """LV orbits are closed; RK4 at small dt should nearly return."""
    theta = jnp.array([1.0, 0.5, 1.0, 0.5])
    y0 = jnp.array([1.2, 0.8])
    traj = rk4_integrate(theta, y0, 0.01, 2000)
    assert np.all(np.asarray(traj) > 0)
    # V = delta*u - gamma*ln u + beta*v - alpha*ln v is conserved.
    u, v = np.asarray(traj[:, 0]), np.asarray(traj[:, 1])
    V = 0.5 * u - 1.0 * np.log(u) + 0.5 * v - 1.0 * np.log(v)
    assert np.abs(V - V[0]).max() < 1e-3


def test_lv_logp_and_grad_finite(mesh8):
    model, _ = make_lv_model(8, mesh=mesh8)
    v, g = model.logp_and_grad(model.init_params())
    assert np.isfinite(float(v))
    assert np.all(np.isfinite(np.asarray(g["log_theta"])))


def test_lv_map_recovers_theta():
    model, meta = make_lv_model(8, n_obs=32)
    est = model.find_map(num_steps=3000, learning_rate=0.02)
    theta_est = np.exp(np.asarray(est["log_theta"]))
    np.testing.assert_allclose(theta_est, meta["theta"], rtol=0.2)


class TestLogisticSuffstats:
    """use_suffstats folds the y-linear term into build-time constants;
    the posterior must be EXACTLY the same (logp and grads), on and off
    a mesh."""

    def test_equality_single_device(self):
        from pytensor_federated_tpu.models.logistic import (
            FederatedLogisticRegression,
            generate_logistic_data,
        )

        data, _ = generate_logistic_data(n_shards=8, n_obs=48, n_features=5)
        base = FederatedLogisticRegression(data)
        fast = FederatedLogisticRegression(data, use_suffstats=True)
        for shift in (0.0, 0.3):
            p = jax.tree_util.tree_map(
                lambda a: a + shift, base.init_params()
            )
            np.testing.assert_allclose(
                float(base.logp(p)), float(fast.logp(p)), rtol=2e-4
            )
            _, g1 = base.logp_and_grad(p)
            _, g2 = fast.logp_and_grad(p)
            for k in g1:
                np.testing.assert_allclose(
                    np.asarray(g1[k]), np.asarray(g2[k]),
                    rtol=2e-3, atol=1e-3,
                )

    def test_equality_on_mesh(self, devices8):
        from pytensor_federated_tpu.models.logistic import (
            FederatedLogisticRegression,
            generate_logistic_data,
        )
        from pytensor_federated_tpu.parallel.mesh import make_mesh

        mesh = make_mesh({"shards": 8}, devices=devices8)
        data, _ = generate_logistic_data(n_shards=8, n_obs=32, n_features=4)
        base = FederatedLogisticRegression(data)
        fast = FederatedLogisticRegression(data, mesh=mesh, use_suffstats=True)
        p = base.init_params()
        np.testing.assert_allclose(
            float(base.logp(p)), float(fast.logp(p)), rtol=5e-4
        )

    def test_flatten_equality(self):
        """flatten=True collapses the shard axis into one matvec; the
        posterior (logp AND grads) must be exactly the vmapped one."""
        from pytensor_federated_tpu.models.logistic import (
            FederatedLogisticRegression,
            generate_logistic_data,
        )

        data, _ = generate_logistic_data(n_shards=8, n_obs=48, n_features=5)
        base = FederatedLogisticRegression(data)
        flat = FederatedLogisticRegression(data, flatten=True)
        for shift in (0.0, 0.3):
            p = jax.tree_util.tree_map(
                lambda a: a + shift, base.init_params()
            )
            np.testing.assert_allclose(
                float(base.logp(p)), float(flat.logp(p)), rtol=2e-4
            )
            _, g1 = base.logp_and_grad(p)
            _, g2 = flat.logp_and_grad(p)
            for k in g1:
                np.testing.assert_allclose(
                    np.asarray(g1[k]), np.asarray(g2[k]),
                    rtol=2e-3, atol=1e-3,
                )

    def test_flatten_respects_padding_mask(self):
        """Ragged shards: flatten must drop padded rows exactly like the
        masked vmapped path does."""
        from pytensor_federated_tpu.models.logistic import (
            FederatedLogisticRegression,
        )
        from pytensor_federated_tpu.parallel.packing import pack_shards

        rng = np.random.default_rng(5)
        shards = []
        for n in (7, 12, 3):
            X = rng.normal(size=(n, 4)).astype(np.float32)
            y = (rng.uniform(size=n) < 0.5).astype(np.float32)
            shards.append((X, y))
        data = pack_shards(shards)
        base = FederatedLogisticRegression(data)
        flat = FederatedLogisticRegression(data, flatten=True)
        p = jax.tree_util.tree_map(lambda a: a + 0.2, base.init_params())
        np.testing.assert_allclose(
            float(base.logp(p)), float(flat.logp(p)), rtol=2e-4
        )
        _, g1 = base.logp_and_grad(p)
        _, g2 = flat.logp_and_grad(p)
        for k in g1:
            np.testing.assert_allclose(
                np.asarray(g1[k]), np.asarray(g2[k]), rtol=2e-3, atol=1e-3
            )

    def test_flatten_rejects_mesh(self, devices8):
        import pytest

        from pytensor_federated_tpu.models.logistic import (
            FederatedLogisticRegression,
            generate_logistic_data,
        )
        from pytensor_federated_tpu.parallel.mesh import make_mesh

        mesh = make_mesh({"shards": 8}, devices=devices8)
        data, _ = generate_logistic_data(n_shards=8, n_obs=16, n_features=3)
        with pytest.raises(ValueError, match="flatten"):
            FederatedLogisticRegression(data, mesh=mesh, flatten=True)


class TestNoFederatedShardsSentinel:
    def test_flatten_fed_access_raises_targeted_message(self):
        import pytest

        from pytensor_federated_tpu.models.logistic import (
            FederatedLogisticRegression,
            generate_logistic_data,
        )

        data, _ = generate_logistic_data(n_shards=4, n_obs=8, n_features=3)
        flat = FederatedLogisticRegression(data, flatten=True)
        # Falsy, so `if model.fed:` guards keep working...
        assert not flat.fed
        # ...but any attribute use fails with a targeted message, not
        # an opaque AttributeError on None (round-3 ADVICE finding).
        with pytest.raises(
            AttributeError, match="no federated shard axis"
        ):
            flat.fed.logp_minibatch
