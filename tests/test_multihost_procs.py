"""REAL multi-process ``jax.distributed`` run on CPU (round-3 verdict
item 5: the in-process virtual mesh never crossed the process boundary
``parallel/multihost.py`` exists for).

Two OS processes x 4 virtual CPU devices join one distributed runtime
(gloo collectives over localhost — the DCN stand-in), run a psum'd
federated logp+grad spanning both, then one process is confirmed dead
and the survivor exercises ``remesh_after_failure`` + re-jit.  The
pytest process itself never touches ``jax.distributed`` (children are
spawned from a real script file; CLAUDE.md heredoc/spawn pitfall).
"""

import os
import socket
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
DRIVER = os.path.join(HERE, "multihost_proc.py")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_distributed_logp_and_failover(tmp_path):
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    # The children force the CPU backend themselves; scrub anything
    # that could point them at the tunneled TPU plugin, and give each
    # 4 virtual devices (2 procs x 4 = 8 global).
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

    procs = [
        subprocess.Popen(
            [sys.executable, DRIVER, str(i), "2", coord, str(tmp_path)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    try:
        # Process 1 runs phase A then exits on its own ("dies").
        out1, _ = procs[1].communicate(timeout=240)
        assert procs[1].returncode == 0, out1
        assert "PHASE-A OK" in out1, out1
        # Only once it is REALLY dead, let the survivor recover.
        (tmp_path / "peer_dead").write_text("1")
        out0, _ = procs[0].communicate(timeout=240)
        assert procs[0].returncode == 0, out0
        assert "PHASE-A OK" in out0, out0
        assert "PHASE-B OK" in out0, out0
        # Both processes computed the same distributed value...
        a0 = [l for l in out0.splitlines() if "PHASE-A OK" in l][0]
        a1 = [l for l in out1.splitlines() if "PHASE-A OK" in l][0]
        assert a0.split("logp=")[1] == a1.split("logp=")[1]
        # ...and the survivor reproduced it after the remesh.
        b0 = [l for l in out0.splitlines() if "PHASE-B OK" in l][0]
        assert a0.split("logp=")[1] == b0.split("logp=")[1]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
