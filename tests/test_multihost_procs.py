"""REAL multi-process ``jax.distributed`` run on CPU (round-3 verdict
item 5 + round-4 verdict item 3: in-band dead-peer detection).

Two OS processes x 4 virtual CPU devices join one distributed runtime
(gloo collectives over localhost — the DCN stand-in) and run a psum'd
federated logp+grad spanning both.  Then the launcher SIGKILLs process
1 MID work loop — a hard kill, not a voluntary exit — and process 0,
given no hint, detects the death through the framework's heartbeat
probes (``detect_dead_peers``) and exercises
``remesh_after_failure(dead_process_ids=...)`` + re-jit.  The pytest
process itself never touches ``jax.distributed`` (children are spawned
from a real script file; CLAUDE.md heredoc/spawn pitfall).
"""

import os
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
DRIVER = os.path.join(HERE, "multihost_proc.py")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _free_port_pair():
    """Base port with base+1 also bindable (one heartbeat per child)."""
    for _ in range(50):
        base = _free_port()
        try:
            with socket.socket() as s:
                s.bind(("127.0.0.1", base + 1))
            return base
        except OSError:
            continue
    raise RuntimeError("no consecutive free port pair found")


class _LineReader:
    """Drain a child's stdout on a thread so sequential waits on two
    pipes can't deadlock on a full buffer."""

    def __init__(self, proc):
        self.proc = proc
        self.lines = []
        self._cond = threading.Condition()
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    def _drain(self):
        for line in self.proc.stdout:
            with self._cond:
                self.lines.append(line.rstrip("\n"))
                self._cond.notify_all()

    def wait_for(self, needle, timeout):
        self.wait_for_any((needle,), timeout)

    def wait_for_any(self, needles, timeout):
        """Block until ANY needle appears; returns the matched one."""
        deadline = time.time() + timeout
        with self._cond:
            while True:
                for needle in needles:
                    if any(needle in l for l in self.lines):
                        return needle
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise AssertionError(
                        f"timed out waiting for any of {needles!r}; "
                        f"output so far:\n" + "\n".join(self.lines)
                    )
                self._cond.wait(remaining)

    def text(self):
        with self._cond:
            return "\n".join(self.lines)


@pytest.mark.slow
def test_two_process_distributed_logp_and_sigkill_failover():
    coord = f"127.0.0.1:{_free_port()}"
    hb_base = _free_port_pair()
    env = dict(os.environ)
    # The children force the CPU backend themselves; scrub anything
    # that could point them at the tunneled TPU plugin, and give each
    # 4 virtual devices (2 procs x 4 = 8 global).
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

    procs = [
        subprocess.Popen(
            [sys.executable, DRIVER, str(i), "2", coord, str(hb_base)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    readers = [_LineReader(p) for p in procs]
    try:
        # Both processes finish the distributed phase A — unless the
        # container's jaxlib rejects cross-process collectives outright
        # (environment drift, CHANGES.md PR 3): the children detect
        # that capability gap themselves and report SKIP-UNSUPPORTED,
        # which is a skip with the backend's own reason, not a red.
        sentinels = ("PHASE-A OK", "SKIP-UNSUPPORTED")

        def skip_with_reason():
            # Skip the moment EITHER child reports the capability gap:
            # the sibling may be wedged inside the collective waiting
            # for its now-dead peer, so it must not be waited on.
            out = readers[0].text() + "\n" + readers[1].text()
            reason = next(
                (
                    l.split("SKIP-UNSUPPORTED:", 1)[1].strip()
                    for l in out.splitlines()
                    if "SKIP-UNSUPPORTED:" in l
                ),
                "unknown",
            )
            pytest.skip(
                "jax.distributed multiprocess collectives unsupported "
                f"by this container's backend: {reason}"
            )

        if readers[1].wait_for_any(sentinels, timeout=240) != "PHASE-A OK":
            skip_with_reason()
        if readers[0].wait_for_any(sentinels, timeout=240) != "PHASE-A OK":
            skip_with_reason()
        # ...the peer enters its work loop, and the survivor confirms
        # it is probe-ably alive (so the later death verdict can only
        # come from the kill, not from a server that never started).
        readers[1].wait_for("SERVING", timeout=60)
        readers[0].wait_for("PEER-ALIVE", timeout=60)

        # Hard-kill the peer MID work loop.  No flag file, no exit
        # path: the only signal the survivor gets is its own probes
        # going connection-refused.
        procs[1].send_signal(signal.SIGKILL)
        procs[1].wait(timeout=30)
        assert procs[1].returncode == -signal.SIGKILL

        readers[0].wait_for("PEER-DEAD", timeout=120)
        procs[0].wait(timeout=240)
        out0 = readers[0].text()
        assert procs[0].returncode == 0, out0
        assert "PHASE-B OK" in out0, out0
        # Both processes computed the same distributed value...
        out1 = readers[1].text()
        a0 = [l for l in out0.splitlines() if "PHASE-A OK" in l][0]
        a1 = [l for l in out1.splitlines() if "PHASE-A OK" in l][0]
        assert a0.split("logp=")[1] == a1.split("logp=")[1]
        # ...and the survivor reproduced it after detect + remesh.
        b0 = [l for l in out0.splitlines() if "PHASE-B OK" in l][0]
        assert a0.split("logp=")[1] == b0.split("logp=")[1]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
