"""Test-only fake ``pytensor`` — executes the bridge glue without pytensor.

pytensor/pymc are uninstallable in this environment (no package index),
so the Apply/optdb adapter code in ``bridge/pytensor_ops.py`` and
``bridge/fusion.py`` could never run here — four rounds of "written,
never executed" (docs/migrating.md "Pytensor-gated bridge surface").  This
module is the next-best evidence: a minimal in-repo implementation of
exactly the pytensor API surface that glue touches, injected via
``sys.modules`` so the REAL bridge modules import and execute.

WHAT THIS PROVES — and what it does not.  Tests running under this shim
prove *our-side* logic: that the glue's make_node/perform/grad/rewrite
code paths execute, agree with the pure cores they delegate to, and
honor the reference's behavioral contracts.  They do NOT prove
compatibility with real pytensor (a signature drift in pytensor itself
would be invisible here).  The API shapes below are pinned from the
reference's OWN usage so that drift is at least anchored:

- ``Apply(op=..., inputs=..., outputs=...)`` keyword construction and
  ``Op.__call__ -> make_node -> outputs`` (reference:
  wrapper_ops.py:97-105, op_async.py:186-188);
- ``Op.perform(node, inputs, output_storage)`` with per-output
  ``storage[0] = value`` slots (reference: wrapper_ops.py:107-117);
- ``Op.grad`` returning symbolic ``g_logp * grad`` products and
  ``DisconnectedType`` checks (reference: wrapper_ops.py:119-132);
- ``FunctionGraph.replace_all_validate(pairs)`` guarded by an attached
  ``ReplaceValidate`` feature (reference: op_async.py:189-194,
  AsyncFusionOptimizer.add_requirements at op_async.py:219-226);
- ``optdb.register(name, rewriter, "fast_run", position=90)`` and the
  ``"name" in optdb`` idempotence check (reference: op_async.py:228-234);
- ``jax_funcify.register(OpClass)`` single-dispatch registration
  (pytensor.link.jax.dispatch, used by bridge/pytensor_ops.py:222-232).

The shim also provides what pytensor's backends would: a tiny
``eval_graph`` interpreter (the C/py linker stand-in, driving
``perform``) and a ``compile_graph_to_jax`` compiler (the JAX linker
stand-in, driving the ``jax_funcify`` registry) — so tests execute the
glue end-to-end instead of merely importing it.
"""

from __future__ import annotations

import functools
import importlib
import sys
import types
from contextlib import contextmanager

import numpy as np

# ---------------------------------------------------------------------------
# Types and variables
# ---------------------------------------------------------------------------


class TensorType:
    """dtype + shape pair; calling an instance makes a fresh variable
    (pytensor: ``i.type()``, used at reference wrapper_ops.py:98)."""

    def __init__(self, dtype, shape=()):
        self.dtype = str(dtype)
        self.shape = tuple(shape)

    def __call__(self, name=None):
        return Variable(self, name=name)

    def __eq__(self, other):
        return (
            isinstance(other, TensorType)
            and self.dtype == other.dtype
            and len(self.shape) == len(other.shape)
        )

    def __hash__(self):
        return hash((self.dtype, len(self.shape)))

    def __repr__(self):
        return f"TensorType({self.dtype}, shape={self.shape})"


class DisconnectedType:
    """Marker type of disconnected gradient variables (pytensor:
    pytensor.gradient.DisconnectedType; isinstance-checked at reference
    wrapper_ops.py:125)."""

    def __call__(self, name=None):
        return Variable(self, name=name)

    def __eq__(self, other):
        return isinstance(other, DisconnectedType)

    def __hash__(self):
        return hash(DisconnectedType)


class Variable:
    """Graph variable: a type plus its producing apply (owner/index).

    Supports the arithmetic the bridge's ``grad`` emits (``g_logp *
    grad``, reference wrapper_ops.py:132) and what the pymc-shim demo
    graphs need (add/sub/getitem)."""

    def __init__(self, type, name=None):
        self.type = type
        self.name = name
        self.owner = None  # Apply that produces this variable
        self.index = None  # position among owner's outputs

    # -- arithmetic builds small elemwise applies ---------------------------
    def __mul__(self, other):
        return _elemwise(Mul, self, other)

    def __rmul__(self, other):
        return _elemwise(Mul, other, self)

    def __add__(self, other):
        return _elemwise(Add, self, other)

    def __radd__(self, other):
        return _elemwise(Add, other, self)

    def __sub__(self, other):
        return _elemwise(Sub, self, other)

    def __rsub__(self, other):
        return _elemwise(Sub, other, self)

    def __getitem__(self, idx):
        return Subtensor(idx)(self)

    def __repr__(self):
        nm = self.name or "var"
        return f"<{nm}:{self.type!r}>"


class Constant(Variable):
    def __init__(self, type, data, name=None):
        super().__init__(type, name=name)
        self.data = data


def as_tensor_variable(x):
    """pytensor.tensor.as_tensor_variable — accepts variables and raw
    python/numpy values (the reference's issue-#24 coercion path,
    reference wrapper_ops.py:25-31 / test_wrapper_ops.py:284-289)."""
    if isinstance(x, Variable):
        return x
    arr = np.asarray(x)
    return Constant(TensorType(arr.dtype, arr.shape), arr)


as_tensor = as_tensor_variable  # reference spells it at.as_tensor


class Apply:
    """One op application; wires ``owner``/``index`` into its outputs
    (constructed with keywords at reference wrapper_ops.py:100-104)."""

    def __init__(self, op=None, inputs=None, outputs=None):
        self.op = op
        self.inputs = list(inputs)
        self.outputs = list(outputs)
        for i, out in enumerate(self.outputs):
            out.owner = self
            out.index = i


class Op:
    """Base op: ``__call__`` -> ``make_node`` -> outputs (single var for
    one output, list otherwise — pytensor's convention, relied on by
    ``self(*inputs)`` re-application at reference wrapper_ops.py:129)."""

    def make_node(self, *inputs):  # pragma: no cover - abstract
        raise NotImplementedError

    def perform(self, node, inputs, output_storage):  # pragma: no cover
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        node = self.make_node(*inputs, **kwargs)
        if len(node.outputs) == 1:
            return node.outputs[0]
        return list(node.outputs)


# -- tiny elemwise ops the shim graphs need ---------------------------------


def _result_type(a, b):
    return TensorType(
        np.result_type(a.type.dtype, b.type.dtype),
        a.type.shape if len(a.type.shape) >= len(b.type.shape) else b.type.shape,
    )


def _elemwise(op_cls, a, b):
    return op_cls()(as_tensor_variable(a), as_tensor_variable(b))


class Mul(Op):
    def make_node(self, a, b):
        return Apply(self, [a, b], [_result_type(a, b)()])

    def perform(self, node, inputs, output_storage):
        output_storage[0][0] = np.asarray(inputs[0] * inputs[1])


class Add(Op):
    def make_node(self, a, b):
        return Apply(self, [a, b], [_result_type(a, b)()])

    def perform(self, node, inputs, output_storage):
        output_storage[0][0] = np.asarray(inputs[0] + inputs[1])


class Sub(Op):
    def make_node(self, a, b):
        return Apply(self, [a, b], [_result_type(a, b)()])

    def perform(self, node, inputs, output_storage):
        output_storage[0][0] = np.asarray(inputs[0] - inputs[1])


class Subtensor(Op):
    def __init__(self, idx):
        self.idx = idx

    def make_node(self, x):
        x = as_tensor_variable(x)
        # Shape inference: index a dummy of the input's shape.
        dummy = np.empty(x.type.shape)[self.idx]
        return Apply(self, [x], [TensorType(x.type.dtype, dummy.shape)()])

    def perform(self, node, inputs, output_storage):
        output_storage[0][0] = np.asarray(inputs[0][self.idx])


def scalar(name=None):
    """pytensor.tensor.scalar() — floatX 0-d variable (reference
    wrapper_ops.py:97)."""
    return TensorType(config.floatX, ())(name=name)


# ---------------------------------------------------------------------------
# FunctionGraph + rewriting machinery
# ---------------------------------------------------------------------------


class ReplaceValidate:
    """Feature whose presence licenses ``replace_all_validate``
    (attached by rewriters' add_requirements, reference
    op_async.py:221-223)."""


class GraphRewriter:
    """Base rewriter: ``rewrite`` = add_requirements then apply
    (pytensor.graph.rewriting.basic.GraphRewriter)."""

    def add_requirements(self, fgraph):
        pass

    def apply(self, fgraph):  # pragma: no cover - abstract
        raise NotImplementedError

    def rewrite(self, fgraph):
        self.add_requirements(fgraph)
        return self.apply(fgraph)


def _walk_applies(outputs):
    """All applies reachable from ``outputs``, topologically ordered
    (parents first)."""
    order, seen = [], set()

    def visit(var):
        node = var.owner
        if node is None or id(node) in seen:
            return
        seen.add(id(node))
        for inp in node.inputs:
            visit(inp)
        order.append(node)

    for out in outputs:
        visit(out)
    return order


class FunctionGraph:
    """Just enough of pytensor.graph.fg.FunctionGraph for the fusion
    rewriter: toposort, feature attachment, validated replacement."""

    def __init__(self, inputs, outputs, clone=False):
        if clone:  # keep the shim honest about what it implements
            raise NotImplementedError("shim FunctionGraph does not clone")
        self.inputs = list(inputs)
        self.outputs = list(outputs)
        self._features = []

    def attach_feature(self, feature):
        self._features.append(feature)

    def toposort(self):
        return _walk_applies(self.outputs)

    def replace_all_validate(self, pairs, reason=None):
        """Swap each (old, new) variable throughout the graph, validating
        type compatibility first — mismatches raise and nothing is
        replaced (the safety contract the reference opts into via
        ReplaceValidate, op_async.py:189-194)."""
        if not any(isinstance(f, ReplaceValidate) for f in self._features):
            raise RuntimeError(
                "replace_all_validate requires the ReplaceValidate feature "
                "(rewriter.add_requirements not run?)"
            )
        for old, new in pairs:
            if not (
                isinstance(old.type, type(new.type))
                and old.type == new.type
            ):
                raise TypeError(
                    f"replacement type mismatch: {old.type!r} vs {new.type!r} "
                    f"(reason={reason})"
                )
        mapping = {id(old): new for old, new in pairs}
        for node in self.toposort():
            node.inputs = [
                mapping.get(id(i), i) for i in node.inputs
            ]
        self.outputs = [mapping.get(id(o), o) for o in self.outputs]


# ---------------------------------------------------------------------------
# optdb
# ---------------------------------------------------------------------------


class _OptDB:
    """pytensor.compile.optdb stand-in: named registration with tags and
    a position, duplicate names rejected; ``in`` checks registration
    (the reference's idempotence guard, op_async.py:228)."""

    def __init__(self):
        self._db = {}

    def __contains__(self, name):
        return name in self._db

    def register(self, name, obj, *tags, position=None, **kwargs):
        if name in self._db:
            raise ValueError(f"{name!r} already registered")
        self._db[name] = {
            "obj": obj,
            "tags": tags,
            "position": position,
            **kwargs,
        }

    def query(self, name):
        return self._db[name]


# ---------------------------------------------------------------------------
# JAX dispatch registry (pytensor.link.jax.dispatch.jax_funcify)
# ---------------------------------------------------------------------------


def _make_jax_funcify():
    @functools.singledispatch
    def jax_funcify(op, **kwargs):
        raise NotImplementedError(f"no jax_funcify for {type(op).__name__}")

    return jax_funcify


# ---------------------------------------------------------------------------
# Backend stand-ins: graph interpreter (py linker) and JAX compiler
# ---------------------------------------------------------------------------


def eval_graph(outputs, givens):
    """Evaluate variables by running ``perform`` in topological order —
    the py-linker stand-in.  ``givens`` maps input Variables to values."""
    values = {id(v): np.asarray(val) for v, val in givens.items()}

    def value_of(var):
        if id(var) in values:
            return values[id(var)]
        if isinstance(var, Constant):
            return np.asarray(var.data)
        raise KeyError(f"no value for {var!r}")

    for node in _walk_applies(outputs):
        in_vals = [value_of(i) for i in node.inputs]
        storage = [[None] for _ in node.outputs]
        node.op.perform(node, in_vals, storage)
        for out, st in zip(node.outputs, storage):
            values[id(out)] = st[0]
    return [value_of(o) for o in outputs]


def compile_graph_to_jax(outputs, inputs, jax_funcify):
    """Compile variables into one jax-traceable python callable of
    ``inputs`` — the JAX-linker stand-in.  Each apply is lowered through
    the ``jax_funcify`` registry, exactly how pytensor's JAX backend
    consumes the bridge's registrations (bridge/pytensor_ops.py:222-232,
    bridge/fusion.py:206-221)."""

    def fn(*args):
        values = {id(v): a for v, a in zip(inputs, args)}

        def value_of(var):
            if id(var) in values:
                return values[id(var)]
            if isinstance(var, Constant):
                return var.data
            raise KeyError(f"no value for {var!r}")

        for node in _walk_applies(outputs):
            member = jax_funcify(node.op)
            res = member(*[value_of(i) for i in node.inputs])
            if not isinstance(res, (tuple, list)):
                res = (res,)
            if len(res) != len(node.outputs):
                raise ValueError(
                    f"{type(node.op).__name__} jax callable returned "
                    f"{len(res)} outputs for {len(node.outputs)} vars"
                )
            for out, r in zip(node.outputs, res):
                values[id(out)] = r
        return [value_of(o) for o in outputs]

    return fn


# Elemwise lowering for the shim's own ops so mixed graphs (federated op
# products, demo models) compile through the same registry.
def _register_shim_elemwise(jax_funcify):
    import jax.numpy as jnp

    @jax_funcify.register(Mul)
    def _(op, **kw):
        return lambda a, b: jnp.multiply(a, b)

    @jax_funcify.register(Add)
    def _(op, **kw):
        return lambda a, b: jnp.add(a, b)

    @jax_funcify.register(Sub)
    def _(op, **kw):
        return lambda a, b: jnp.subtract(a, b)

    @jax_funcify.register(Subtensor)
    def _(op, **kw):
        return lambda x, _idx=None: x[op.idx]


# ---------------------------------------------------------------------------
# sys.modules injection
# ---------------------------------------------------------------------------

config = types.SimpleNamespace(floatX="float64")

_SHIM_MODULES = [
    "pytensor",
    "pytensor.tensor",
    "pytensor.gradient",
    "pytensor.graph",
    "pytensor.graph.basic",
    "pytensor.graph.op",
    "pytensor.graph.features",
    "pytensor.graph.fg",
    "pytensor.graph.rewriting",
    "pytensor.graph.rewriting.basic",
    "pytensor.compile",
    "pytensor.link",
    "pytensor.link.jax",
    "pytensor.link.jax.dispatch",
]

_BRIDGE_MODULES = [
    "pytensor_federated_tpu.bridge.pytensor_ops",
    "pytensor_federated_tpu.bridge.fusion",
]

# The bridge PACKAGE may already be imported with HAS_PYTENSOR=False
# (its import gate ran without pytensor).  Under the shim it must
# re-import so the gate flips and ``from ..bridge import
# federated_potential`` works (demo_pymc.py:98) — saved and restored so
# the rest of the session sees the original module object again.
_REIMPORT_MODULES = [
    "pytensor_federated_tpu.bridge",
    "pytensor_federated_tpu.demos.demo_pymc",
]


def build_modules():
    """Fresh fake-module tree (new optdb and jax_funcify registry each
    install, so repeated test runs never see stale registrations)."""
    mods = {name: types.ModuleType(name) for name in _SHIM_MODULES}
    jax_funcify = _make_jax_funcify()
    _register_shim_elemwise(jax_funcify)
    optdb = _OptDB()

    pt = mods["pytensor"]
    pt.config = config
    pt.tensor = mods["pytensor.tensor"]
    pt.gradient = mods["pytensor.gradient"]
    pt.graph = mods["pytensor.graph"]
    pt.compile = mods["pytensor.compile"]
    pt.link = mods["pytensor.link"]
    pt.__path__ = []  # mark as package for "import pytensor.tensor"

    t = mods["pytensor.tensor"]
    t.as_tensor_variable = as_tensor_variable
    t.as_tensor = as_tensor
    t.scalar = scalar
    t.TensorType = TensorType

    mods["pytensor.gradient"].DisconnectedType = DisconnectedType

    g = mods["pytensor.graph"]
    g.__path__ = []
    g.basic = mods["pytensor.graph.basic"]
    g.op = mods["pytensor.graph.op"]
    g.features = mods["pytensor.graph.features"]
    g.fg = mods["pytensor.graph.fg"]
    g.rewriting = mods["pytensor.graph.rewriting"]
    g.FunctionGraph = FunctionGraph
    mods["pytensor.graph.basic"].Apply = Apply
    mods["pytensor.graph.basic"].Variable = Variable
    mods["pytensor.graph.basic"].Constant = Constant
    mods["pytensor.graph.op"].Op = Op
    mods["pytensor.graph.features"].ReplaceValidate = ReplaceValidate
    mods["pytensor.graph.fg"].FunctionGraph = FunctionGraph
    mods["pytensor.graph.rewriting"].__path__ = []
    mods["pytensor.graph.rewriting"].basic = mods[
        "pytensor.graph.rewriting.basic"
    ]
    mods["pytensor.graph.rewriting.basic"].GraphRewriter = GraphRewriter

    mods["pytensor.compile"].optdb = optdb

    mods["pytensor.link"].__path__ = []
    mods["pytensor.link"].jax = mods["pytensor.link.jax"]
    mods["pytensor.link.jax"].__path__ = []
    mods["pytensor.link.jax"].dispatch = mods["pytensor.link.jax.dispatch"]
    mods["pytensor.link.jax.dispatch"].jax_funcify = jax_funcify

    return mods, optdb, jax_funcify


@contextmanager
def bridge_under_shim():
    """Install the shim, import the REAL bridge glue modules under it,
    yield a namespace, then remove shim + glue from ``sys.modules`` so
    no other test can observe a fake pytensor."""
    present = [
        name
        for name in _SHIM_MODULES + _BRIDGE_MODULES + ["pymc"]
        if name in sys.modules
    ]
    if present:
        # Real pytensor/pymc imported in this process (e.g. the
        # real-dependency suites ran first after an install finally
        # succeeds): the shim must NOT shadow it — defer to the real
        # tests instead of turning a green suite into errors.
        import pytest

        pytest.skip(
            f"real modules already imported ({present[0]}…); shim tests "
            "defer to the real-dependency suite"
        )
    saved = {
        name: sys.modules.pop(name)
        for name in _REIMPORT_MODULES
        if name in sys.modules
    }
    mods, optdb, jax_funcify = build_modules()
    sys.modules.update(mods)
    try:
        bridge = importlib.import_module("pytensor_federated_tpu.bridge")
        assert bridge.HAS_PYTENSOR, "shim failed to satisfy the import gate"
        pytensor_ops = sys.modules[
            "pytensor_federated_tpu.bridge.pytensor_ops"
        ]
        fusion = sys.modules["pytensor_federated_tpu.bridge.fusion"]
        yield types.SimpleNamespace(
            bridge=bridge,
            pytensor_ops=pytensor_ops,
            fusion=fusion,
            optdb=optdb,
            jax_funcify=jax_funcify,
            # shim surface handed to tests
            Apply=Apply,
            Op=Op,
            Variable=Variable,
            Constant=Constant,
            TensorType=TensorType,
            DisconnectedType=DisconnectedType,
            FunctionGraph=FunctionGraph,
            ReplaceValidate=ReplaceValidate,
            as_tensor_variable=as_tensor_variable,
            scalar=scalar,
            config=config,
            eval_graph=eval_graph,
            compile_graph_to_jax=compile_graph_to_jax,
        )
    finally:
        for name in _SHIM_MODULES + _BRIDGE_MODULES + _REIMPORT_MODULES:
            sys.modules.pop(name, None)
        sys.modules.update(saved)
        # Re-point (or clear) parent-package attributes so
        # ``pytensor_federated_tpu.bridge`` keeps meaning the original —
        # a stale attribute would satisfy ``from pkg import bridge``
        # without consulting sys.modules.
        for name in _REIMPORT_MODULES:
            parent, _, child = name.rpartition(".")
            if parent not in sys.modules:
                continue
            if name in saved:
                setattr(sys.modules[parent], child, saved[name])
            elif hasattr(sys.modules[parent], child):
                delattr(sys.modules[parent], child)
