"""ChEES-HMC: posterior correctness, adaptation behavior, NUTS parity."""

import jax
import jax.numpy as jnp
import numpy as np

from pytensor_federated_tpu.samplers.chees import _halton, chees_sample


def test_halton_covers_unit_interval():
    vals = np.asarray(
        [float(_halton(jnp.asarray(i))) for i in range(64)]
    )
    assert np.all((vals > 0) & (vals < 1))
    # low-discrepancy: every eighth of (0,1) visited within 64 draws
    hist, _ = np.histogram(vals, bins=8, range=(0, 1))
    assert np.all(hist > 0)


def test_gaussian_posterior_moments():
    # Correlated Gaussian target: mean and marginal sds must match.
    cov = jnp.asarray([[2.0, 0.8], [0.8, 1.0]])
    prec = jnp.linalg.inv(cov)
    mu = jnp.asarray([1.0, -2.0])

    def logp(p):
        d = p["x"] - mu
        return -0.5 * d @ prec @ d

    res = chees_sample(
        logp,
        {"x": jnp.zeros(2)},
        key=jax.random.PRNGKey(0),
        num_warmup=400,
        num_samples=400,
        num_chains=16,
    )
    draws = np.asarray(res.samples["x"]).reshape(-1, 2)
    np.testing.assert_allclose(draws.mean(axis=0), mu, atol=0.15)
    np.testing.assert_allclose(
        draws.std(axis=0), np.sqrt(np.diag(cov)), rtol=0.15
    )
    assert float(np.mean(np.asarray(res.stats["accept_prob"]))) > 0.5


def test_trajectory_adapts_to_preconditioned_optimum():
    # For a Gaussian target the ChEES-optimal trajectory time is
    # ~pi/2 * sd.  The cross-chain mass adaptation normalizes every
    # axis to unit scale, so for N(0, s^2 I) at ANY s the adapted
    # integrated time eps * E[L] must land near pi/2 — scale
    # invariance through preconditioning plus criterion convergence,
    # the paper's Gaussian prediction.
    def make(scale):
        def logp(p):
            return -0.5 * jnp.sum((p["x"] / scale) ** 2)

        return logp

    for scale in (0.1, 10.0):
        res = chees_sample(
            make(scale),
            {"x": jnp.zeros(4)},
            key=jax.random.PRNGKey(1),
            num_warmup=300,
            num_samples=100,
            num_chains=16,
        )
        n = float(np.mean(np.asarray(res.stats["n_steps"])))
        eps = float(np.asarray(res.step_size[0]))
        t_integrated = n * eps  # ~ mean trajectory time ~ T
        assert 0.5 < t_integrated < 5.0, (scale, t_integrated)
        # and the mass matrix must carry the scale: inv_mass ~ s^2
        im = float(np.mean(np.asarray(res.inv_mass)))
        assert 0.2 * scale**2 < im < 5.0 * scale**2, (scale, im)


def test_matches_nuts_on_federated_posterior():
    from pytensor_federated_tpu.models.logistic import (
        FederatedLogisticRegression,
        generate_logistic_data,
    )
    from pytensor_federated_tpu.samplers import sample

    data, _ = generate_logistic_data(n_shards=8, n_obs=48, n_features=3)
    m = FederatedLogisticRegression(data)
    res_c = chees_sample(
        m.logp,
        m.init_params(),
        key=jax.random.PRNGKey(2),
        num_warmup=400,
        num_samples=400,
        num_chains=8,
        jitter=0.1,
    )
    res_n = sample(
        m.logp,
        m.init_params(),
        key=jax.random.PRNGKey(3),
        num_warmup=400,
        num_samples=400,
        num_chains=4,
        jitter=0.1,
    )
    w_c = np.asarray(res_c.samples["w"]).reshape(-1, 3)
    w_n = np.asarray(res_n.samples["w"]).reshape(-1, 3)
    sd = w_n.std(axis=0)
    tol = np.maximum(3 * sd / 10, 0.08)
    assert np.all(np.abs(w_c.mean(axis=0) - w_n.mean(axis=0)) < tol)
    np.testing.assert_allclose(w_c.std(axis=0), sd, rtol=0.35)


def test_stats_shapes_and_summary():
    def logp(p):
        return -0.5 * jnp.sum(p["x"] ** 2)

    res = chees_sample(
        logp,
        {"x": jnp.zeros(3)},
        key=jax.random.PRNGKey(4),
        num_warmup=100,
        num_samples=50,
        num_chains=4,
    )
    assert res.samples["x"].shape == (4, 50, 3)
    assert res.stats["accept_prob"].shape == (4, 50)
    summ = res.summary()
    assert float(np.max(np.asarray(summ["rhat"]["x"]))) < 1.2


def test_halton_no_exact_zero_at_power_of_two():
    # 16-bit truncation returned exactly 0.0 at i+1 = 2^16 (round-2
    # review); 32 bits must stay strictly positive there.
    v = float(_halton(jnp.asarray(2**16 - 1)))
    assert 0.0 < v < 1.0


def test_divergence_does_not_poison_adaptation():
    # An ill-scaled warmup start produces divergent (NaN-endpoint)
    # trajectories; adaptation must survive and the run must still
    # return finite draws with a finite adapted trajectory.
    def logp(p):
        # extremely stiff quadratic: early big steps diverge
        return -0.5 * jnp.sum((p["x"] * 1e4) ** 2)

    res = chees_sample(
        logp,
        {"x": jnp.ones(2)},
        key=jax.random.PRNGKey(5),
        num_warmup=200,
        num_samples=50,
        num_chains=8,
        jitter=2.0,
    )
    draws = np.asarray(res.samples["x"])
    assert np.all(np.isfinite(draws))
    assert np.all(np.isfinite(np.asarray(res.step_size)))


def test_chain_sharding_over_mesh(devices8):
    """Chains sharded over an 8-device mesh: the run must stay
    distributed end-to-end (draws sharded over the chains axis) and
    produce a correct posterior — the cross-chain adaptation
    reductions become XLA collectives, nothing else changes."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytensor_federated_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"chains": 8}, devices=devices8)

    def logp(p):
        return -0.5 * jnp.sum((p["x"] - 1.5) ** 2)

    res = chees_sample(
        logp,
        {"x": jnp.zeros(2)},
        key=jax.random.PRNGKey(2),
        num_warmup=150,
        num_samples=150,
        num_chains=16,  # two chains per device
        chain_sharding=NamedSharding(mesh, P("chains")),
    )
    draws = np.asarray(res.samples["x"])  # (chains, samples, 2)
    assert draws.shape == (16, 150, 2)
    assert np.all(np.isfinite(draws))
    np.testing.assert_allclose(draws.mean(axis=(0, 1)), 1.5, atol=0.2)
    # the distributed run must not have silently de-sharded mid-way
    leaf = res.samples["x"]
    assert not leaf.sharding.is_fully_replicated
