"""Parallel tempering (samplers/tempering.py).

Positive control: a well-separated bimodal mixture where single-ladder
HMC provably sticks in one mode — tempering must recover BOTH modes
with the right weights.  Negative control inside the same test: the
cold chain alone (what NUTS/HMC would do) stays unimodal, so the
bimodality the sampler reports is earned by the ladder, not by the
kernel.  Plus a conjugate-normal moment check (exactness) and ladder
diagnostics contracts.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu.samplers import (
    effective_sample_size,
    pt_sample,
    sample,
)


def bimodal_logp(params):
    """Equal mixture of N(-4, 0.5^2) and N(+4, 0.5^2): 16-sigma gap."""
    x = params["x"]
    la = -0.5 * ((x + 4.0) / 0.5) ** 2
    lb = -0.5 * ((x - 4.0) / 0.5) ** 2
    return jnp.sum(jnp.logaddexp(la, lb))


class TestBimodal:
    def test_recovers_both_modes(self):
        res = pt_sample(
            bimodal_logp,
            {"x": jnp.zeros(1)},
            key=jax.random.PRNGKey(0),
            num_warmup=800,
            num_samples=2000,
            num_temps=8,
            beta_min=0.01,
        )
        draws = np.asarray(res.samples["x"])[0, :, 0]
        frac_right = float(np.mean(draws > 0))
        # both modes populated near 50/50
        assert 0.25 < frac_right < 0.75, frac_right
        # and the modes are where they should be
        assert abs(np.mean(draws[draws > 0]) - 4.0) < 0.3
        assert abs(np.mean(draws[draws < 0]) + 4.0) < 0.3

    def test_negative_control_hmc_sticks(self):
        """The same budget of plain HMC/NUTS starting at one mode must
        NOT cross — otherwise the test above proves nothing."""
        res = sample(
            bimodal_logp,
            {"x": jnp.full((1,), -4.0)},
            key=jax.random.PRNGKey(0),
            num_warmup=400,
            num_samples=1000,
            num_chains=1,
            jitter=0.1,
        )
        draws = np.asarray(res.samples["x"])[0, :, 0]
        assert np.mean(draws > 0) < 0.01

    def test_swap_diagnostics(self):
        res = pt_sample(
            bimodal_logp,
            {"x": jnp.zeros(1)},
            key=jax.random.PRNGKey(1),
            num_warmup=300,
            num_samples=301,  # ODD on purpose: rates must stay <= 1
            num_temps=6,
            beta_min=0.02,
        )
        per_pair = np.asarray(res.extra["swap_rate_per_pair"])
        assert per_pair.shape == (1, 5)  # leading chains axis
        assert np.all(per_pair >= 0) and np.all(per_pair <= 1.0)
        # a geometric ladder on this target must actually exchange
        assert per_pair.min() > 0.05
        assert res.extra["betas"].shape == (1, 6)
        assert float(res.extra["betas"][0, 0]) == 1.0
        # stats stays strictly (chains, draws): the arviz export must
        # accept a pt_sample result unmodified
        from pytensor_federated_tpu.samplers import to_dataset_dict

        dd = to_dataset_dict(res)
        assert "sample_stats" in dd


def test_conjugate_normal_moments():
    """Exactness: unimodal conjugate target, moments must match."""

    def logp(p):
        return -0.5 * jnp.sum((p["mu"] - 1.5) ** 2 / 0.25)

    res = pt_sample(
        logp,
        {"mu": jnp.zeros(2)},
        key=jax.random.PRNGKey(2),
        num_warmup=500,
        num_samples=2000,
        num_temps=4,
    )
    draws = np.asarray(res.samples["mu"])[0]
    np.testing.assert_allclose(draws.mean(axis=0), 1.5, atol=0.1)
    np.testing.assert_allclose(draws.std(axis=0), 0.5, atol=0.1)


def test_rejects_single_temperature():
    with pytest.raises(ValueError, match="2 temperatures"):
        pt_sample(
            bimodal_logp,
            {"x": jnp.zeros(1)},
            key=jax.random.PRNGKey(0),
            num_temps=1,
        )


def test_rejects_bad_beta_min():
    for bad in (0.0, -0.1, 1.5):
        with pytest.raises(ValueError, match="beta_min"):
            pt_sample(
                bimodal_logp,
                {"x": jnp.zeros(1)},
                key=jax.random.PRNGKey(0),
                beta_min=bad,
            )


def test_temp_sharding_on_mesh(devices8):
    """Temperatures across an 8-device mesh: computation follows
    sharding (the chees chain_sharding pattern); moments stay exact."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytensor_federated_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"temps": 8}, devices=devices8)

    def logp(p):
        return -0.5 * jnp.sum((p["mu"] - 1.5) ** 2 / 0.25)

    res = pt_sample(
        logp,
        {"mu": jnp.zeros(2)},
        key=jax.random.PRNGKey(3),
        num_warmup=400,
        num_samples=1500,
        num_temps=8,
        temp_sharding=NamedSharding(mesh, P("temps")),
    )
    draws = np.asarray(res.samples["mu"])[0]
    np.testing.assert_allclose(draws.mean(axis=0), 1.5, atol=0.1)
    np.testing.assert_allclose(draws.std(axis=0), 0.5, atol=0.1)


def test_temp_sharding_indivisible_raises(devices8):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytensor_federated_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"temps": 8}, devices=devices8)
    with pytest.raises(ValueError, match="not shardable"):
        pt_sample(
            bimodal_logp,
            {"x": jnp.zeros(1)},
            key=jax.random.PRNGKey(0),
            num_temps=6,
            temp_sharding=NamedSharding(mesh, P("temps")),
        )


class TestAdaptiveLadder:
    def test_still_exact_on_conjugate(self):
        def logp(p):
            return -0.5 * jnp.sum((p["mu"] - 1.5) ** 2 / 0.25)

        res = pt_sample(
            logp,
            {"mu": jnp.zeros(2)},
            key=jax.random.PRNGKey(4),
            num_warmup=600,
            num_samples=2000,
            num_temps=4,
            adapt_ladder=True,
        )
        draws = np.asarray(res.samples["mu"])[0]
        np.testing.assert_allclose(draws.mean(axis=0), 1.5, atol=0.1)
        np.testing.assert_allclose(draws.std(axis=0), 0.5, atol=0.1)
        betas = np.asarray(res.extra["betas"])[0]
        assert betas[0] == 1.0 and np.all(np.diff(betas) < 0)

    def test_rescues_a_disconnected_ladder(self):
        """In high dimension the energy spread scales with dim, so a
        wide geometric ladder DISCONNECTS (measured: all swap rates
        exactly 0 on a 64-d Gaussian with 4 rungs to beta=0.001 —
        tempering silently useless).  Adaptation must find a connected
        spacing (deterministic seeds)."""

        def gauss64(p):
            return -0.5 * jnp.sum(p["x"] ** 2)

        kw = dict(
            key=jax.random.PRNGKey(5),
            num_warmup=800,
            num_samples=600,
            num_temps=4,
            beta_min=0.001,
        )
        fixed = pt_sample(gauss64, {"x": jnp.zeros(64)}, **kw)
        adapted = pt_sample(
            gauss64, {"x": jnp.zeros(64)}, adapt_ladder=True, **kw
        )
        assert float(
            np.asarray(fixed.extra["swap_rate_per_pair"]).max()
        ) < 0.05  # the fixed ladder really is disconnected here
        assert float(
            np.asarray(adapted.extra["swap_rate_per_pair"]).min()
        ) > 0.2  # every adapted rung exchanges
        # beta_1 stays pinned; the ladder stays ordered
        betas = np.asarray(adapted.extra["betas"])[0]
        assert betas[0] == 1.0 and np.all(np.diff(betas) < 0)


def test_mass_adaptation_learns_anisotropy():
    """100x scale mismatch between coordinates: the adapted per-rung
    diagonal mass must learn each coordinate's variance (cold rung
    ~= the target's), and the moments must still come out right —
    identity mass would need a 100x smaller step for the narrow
    coordinate and mix the wide one glacially."""

    def logp(p):
        x = p["x"]
        return -0.5 * (
            (x[0] / 0.05) ** 2 + (x[1] / 5.0) ** 2
        )

    res = pt_sample(
        logp,
        {"x": jnp.zeros(2)},
        key=jax.random.PRNGKey(6),
        num_warmup=1000,
        num_samples=3000,
        num_temps=4,
        jitter=0.1,
    )
    draws = np.asarray(res.samples["x"])[0]
    np.testing.assert_allclose(draws[:, 0].std(), 0.05, rtol=0.25)
    np.testing.assert_allclose(draws[:, 1].std(), 5.0, rtol=0.25)
    # the COLD rung's mass reflects the target's variances
    inv_mass = np.asarray(res.inv_mass)[0]
    ratio = inv_mass[1] / inv_mass[0]
    assert ratio > 100.0, ratio  # true variance ratio is 10_000

    # identity mass, same budget: the wide coordinate must mix WORSE
    # (negative control so the assertion above means something)
    res_id = pt_sample(
        logp,
        {"x": jnp.zeros(2)},
        key=jax.random.PRNGKey(6),
        num_warmup=1000,
        num_samples=3000,
        num_temps=4,
        jitter=0.1,
        adapt_mass=False,
    )
    draws_id = np.asarray(res_id.samples["x"])[0]
    # "Mixes worse" measured as ESS, not raw std: a random-walking
    # wide coordinate can land over- or under-dispersed depending on
    # seed/XLA version, but its autocorrelation (hence ESS) is
    # robustly far worse than the adapted chain's.
    ess_id = float(np.asarray(effective_sample_size(draws_id[None, :, 1])))
    ess_ad = float(np.asarray(effective_sample_size(draws[None, :, 1])))
    assert ess_id < 0.5 * ess_ad, (ess_id, ess_ad)


def test_num_chains_independent_stacks():
    """num_chains=2: two independent tempering stacks make split-R-hat
    meaningful; on a well-behaved target both converge and agree."""

    def logp(p):
        return -0.5 * jnp.sum((p["mu"] - 1.5) ** 2 / 0.25)

    res = pt_sample(
        logp,
        {"mu": jnp.zeros(2)},
        key=jax.random.PRNGKey(7),
        num_chains=2,
        num_warmup=500,
        num_samples=1000,
        num_temps=4,
    )
    assert res.samples["mu"].shape == (2, 1000, 2)
    assert res.stats["accept_prob"].shape == (2, 1000)
    assert res.extra["swap_rate_per_pair"].shape == (2, 3)
    summ = res.summary()
    assert float(np.asarray(summ["rhat"]["mu"]).max()) < 1.05
    draws = np.asarray(res.samples["mu"]).reshape(-1, 2)
    np.testing.assert_allclose(draws.mean(axis=0), 1.5, atol=0.1)


def test_num_chains_rejects_temp_sharding(devices8):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytensor_federated_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"temps": 8}, devices=devices8)
    with pytest.raises(ValueError, match="incompatible"):
        pt_sample(
            bimodal_logp,
            {"x": jnp.zeros(1)},
            key=jax.random.PRNGKey(0),
            num_chains=2,
            temp_sharding=NamedSharding(mesh, P("temps")),
        )


def test_rejects_zero_chains():
    with pytest.raises(ValueError, match="num_chains"):
        pt_sample(
            bimodal_logp,
            {"x": jnp.zeros(1)},
            key=jax.random.PRNGKey(0),
            num_chains=0,
        )


def test_forward_supplied_gradients_federated():
    """The federated node contract: pt_sample consumes a fused
    (logp, grads) callable — FederatedLogp.logp_and_grad — instead of
    autodiffing, exactly like samplers.sample does."""
    import pytensor_federated_tpu as pft

    rng = np.random.default_rng(2)
    shards = [
        (
            rng.normal(size=(16, 2)).astype(np.float32),
            rng.normal(size=16).astype(np.float32),
        )
        for _ in range(4)
    ]
    packed = pft.pack_shards(shards)

    def per_shard(params, shard):
        (X, y), mask = shard
        r = y - X @ params["w"]
        return -0.5 * jnp.sum(r * r * mask)

    fed = pft.FederatedLogp(per_shard, packed.tree(), mesh=None)

    def logp_no_autodiff(params):
        # Same VALUES as fed.logp, but autodiff through it yields zero
        # gradients — so this test passes ONLY if pt_sample actually
        # consumes the supplied fused callable (a refactor that falls
        # back to autodiffing logp_fn leaves the chains stuck at their
        # init and the OLS assertion fails loudly).
        return fed.logp(jax.lax.stop_gradient(params))

    res = pt_sample(
        logp_no_autodiff,
        {"w": jnp.zeros(2)},
        key=jax.random.PRNGKey(9),
        num_warmup=300,
        num_samples=500,
        num_temps=4,
        logp_and_grad_fn=fed.logp_and_grad,
    )
    draws = np.asarray(res.samples["w"])[0]
    # OLS solution of the pooled data = posterior mode (flat prior)
    X = np.concatenate([s[0] for s in shards])
    y = np.concatenate([s[1] for s in shards])
    w_ols = np.linalg.lstsq(X, y, rcond=None)[0]
    np.testing.assert_allclose(draws.mean(axis=0), w_ols, atol=0.1)
