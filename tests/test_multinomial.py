"""Federated multinomial (softmax) regression family."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu.models.multinomial import (
    FederatedSoftmaxRegression,
    generate_multinomial_data,
)


def _manual_logp(model, params):
    """Hand-built ground truth: per-observation categorical loglik via
    explicit softmax, plus the Normal priors."""
    (X, y), mask = model.data.tree()
    X = np.asarray(X)
    yv = np.asarray(y).astype(int)
    m = np.asarray(mask)
    W = np.asarray(params["W"])
    b = np.asarray(params["b"])
    total = 0.0
    for s in range(X.shape[0]):
        logits = np.concatenate(
            [np.zeros((X.shape[1], 1)), X[s] @ W + b], axis=1
        )
        logits -= logits.max(axis=1, keepdims=True)
        logp_obs = logits[np.arange(X.shape[1]), yv[s]] - np.log(
            np.exp(logits).sum(axis=1)
        )
        total += float((logp_obs * m[s]).sum())
    scale = model.prior_scale
    for arr in (W, b):
        total += float(
            (-0.5 * (arr / scale) ** 2
             - 0.5 * np.log(2 * np.pi * scale**2)).sum()
        )
    return total


def test_logp_matches_manual_ground_truth():
    data, _ = generate_multinomial_data(4, n_obs=24, n_features=3,
                                        n_classes=4)
    model = FederatedSoftmaxRegression(data, n_classes=4)
    params = jax.tree_util.tree_map(
        lambda a: a + 0.3, model.init_params()
    )
    np.testing.assert_allclose(
        float(model.logp(params)), _manual_logp(model, params),
        rtol=1e-5,
    )


def test_mesh_matches_local(devices8):
    from pytensor_federated_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"shards": 8}, devices=devices8)
    data, _ = generate_multinomial_data(8, n_obs=16, n_features=3)
    local = FederatedSoftmaxRegression(data, n_classes=3)
    sharded = FederatedSoftmaxRegression(data, n_classes=3, mesh=mesh)
    p = jax.tree_util.tree_map(
        lambda a: a + 0.2, local.init_params()
    )
    np.testing.assert_allclose(
        float(local.logp(p)), float(sharded.logp(p)), rtol=5e-5
    )
    _, g1 = local.logp_and_grad(p)
    _, g2 = sharded.logp_and_grad(p)
    np.testing.assert_allclose(
        np.asarray(g1["W"]), np.asarray(g2["W"]), rtol=1e-4, atol=1e-5
    )


def test_map_recovers_coefficients():
    data, truth = generate_multinomial_data(
        16, n_obs=128, n_features=3, n_classes=3, seed=41
    )
    model = FederatedSoftmaxRegression(data, n_classes=3)
    est = model.find_map(num_steps=2000, learning_rate=0.05)
    W_est = np.asarray(est["W"])
    # enough data that coefficient direction + scale recover
    np.testing.assert_allclose(W_est, truth["W"], atol=0.5)


def test_pointwise_and_predictive():
    data, _ = generate_multinomial_data(4, n_obs=16, n_features=3)
    model = FederatedSoftmaxRegression(data, n_classes=3)
    p = model.init_params()
    ll = np.asarray(model.pointwise_loglik(p))
    (X, y), mask = model.data.tree()
    assert ll.shape == (np.asarray(X).shape[0] * np.asarray(X).shape[1],)
    # at init all classes are equiprobable: ll = -log 3 on real slots
    real = np.asarray(mask).reshape(-1) > 0
    np.testing.assert_allclose(ll[real], -np.log(3.0), rtol=1e-5)
    sims = model.predictive(p, jax.random.PRNGKey(0))
    assert sims.shape == np.asarray(y).shape
    assert set(np.unique(np.asarray(sims))) <= {0.0, 1.0, 2.0}


def test_rejects_k1():
    data, _ = generate_multinomial_data(2, n_obs=8)
    with pytest.raises(ValueError, match="n_classes"):
        FederatedSoftmaxRegression(data, n_classes=1)


def test_posterior_sampling_converges():
    data, _ = generate_multinomial_data(
        8, n_obs=48, n_features=2, n_classes=3, seed=43
    )
    model = FederatedSoftmaxRegression(data, n_classes=3)
    res = model.sample(
        key=jax.random.PRNGKey(2),
        num_warmup=200,
        num_samples=200,
        num_chains=2,
        jitter=0.2,
    )
    summ = res.summary()
    assert float(np.max(np.asarray(summ["rhat"]["W"]))) < 1.1


class TestHierarchicalSoftmax:
    def test_truth_recovery_and_shrinkage(self):
        from pytensor_federated_tpu.models.multinomial import (
            HierarchicalSoftmaxRegression,
            generate_hier_multinomial_data,
        )

        data, truth = generate_hier_multinomial_data(
            12, n_obs=96, n_features=2, n_classes=3, tau=0.8, seed=51
        )
        model = HierarchicalSoftmaxRegression(data, n_classes=3)
        est = model.find_map(num_steps=2500, learning_rate=0.05)
        np.testing.assert_allclose(
            np.asarray(est["w"]), truth["W"], atol=0.6
        )
        # the group scale is estimated in a sane band around 0.8
        tau_hat = float(np.exp(np.asarray(est["log_tau"])))
        assert 0.2 < tau_hat < 2.5

    def test_mesh_matches_local(self, devices8):
        from pytensor_federated_tpu.models.multinomial import (
            HierarchicalSoftmaxRegression,
            generate_hier_multinomial_data,
        )
        from pytensor_federated_tpu.parallel.mesh import make_mesh

        mesh = make_mesh({"shards": 8}, devices=devices8)
        data, _ = generate_hier_multinomial_data(8, n_obs=16)
        local = HierarchicalSoftmaxRegression(data, n_classes=3)
        sharded = HierarchicalSoftmaxRegression(
            data, n_classes=3, mesh=mesh
        )
        p = jax.tree_util.tree_map(
            lambda a: a + 0.1, local.init_params()
        )
        np.testing.assert_allclose(
            float(local.logp(p)), float(sharded.logp(p)), rtol=5e-5
        )

    def test_base_machinery_works_for_vector_columns(self):
        """pointwise/predictive/sample_prior come from the generalized
        base; pin their shapes and init-value semantics for the vector
        (_coef_cols = K-1) case."""
        from pytensor_federated_tpu.models.multinomial import (
            HierarchicalSoftmaxRegression,
            generate_hier_multinomial_data,
        )

        data, _ = generate_hier_multinomial_data(
            4, n_obs=12, n_classes=3
        )
        model = HierarchicalSoftmaxRegression(data, n_classes=3)
        p = model.init_params()
        assert p["w"].shape == (3, 2)
        assert p["b0"].shape == (2,)
        assert p["b_raw"].shape == (4, 2)
        ll = np.asarray(model.pointwise_loglik(p))
        (X, y), mask = model.data.tree()
        assert ll.shape == np.asarray(y).shape
        real = np.asarray(mask) > 0
        np.testing.assert_allclose(ll[real], -np.log(3.0), rtol=1e-5)
        sims = model.predictive(p, jax.random.PRNGKey(0))
        assert sims.shape == np.asarray(y).shape
        prior = model.sample_prior(jax.random.PRNGKey(1))
        assert prior["w"].shape == (3, 2)
        assert prior["b0"].shape == (2,)
        assert np.isfinite(float(model.logp(prior)))


def test_suffstats_equality():
    """use_suffstats folds the picked-logit term to build-time
    constants; logp and grads must match the plain path exactly,
    including with ragged (masked) shards."""
    from pytensor_federated_tpu.parallel.packing import pack_shards

    rng = np.random.default_rng(9)
    shards = []
    for n in (11, 7, 16):
        X = rng.normal(size=(n, 4)).astype(np.float32)
        y = rng.integers(0, 3, size=n).astype(np.float32)
        shards.append((X, y))
    data = pack_shards(shards)
    base = FederatedSoftmaxRegression(data, n_classes=3)
    fast = FederatedSoftmaxRegression(data, n_classes=3,
                                      use_suffstats=True)
    for shift in (0.0, 0.3):
        p = jax.tree_util.tree_map(
            lambda a: a + shift, base.init_params()
        )
        np.testing.assert_allclose(
            float(base.logp(p)), float(fast.logp(p)), rtol=2e-5
        )
        _, g1 = base.logp_and_grad(p)
        _, g2 = fast.logp_and_grad(p)
        for k in g1:
            np.testing.assert_allclose(
                np.asarray(g1[k]), np.asarray(g2[k]),
                rtol=1e-4, atol=1e-5,
            )
