"""Zero-syscall ring lane (service/ring.py, ISSUE 18).

Covers the seqlock ring protocol on a raw arena (roundtrip, spanning
frames, wraparound laps, full-ring refusal, torn/recycled/stale/zeroed
records all loud ``WireError``), the client/server pair
(``RingArraysClient``/``serve_ring``: evaluate, pipelined + batched
windows, GetLoad, ping), graceful degradation both ways (ring client vs
plain shm node, shm client vs ring node), the npwire pool-probe
regression on a ring-attached doorbell, pool integration (pure ring +
mixed transports), chaos classification, and abrupt peer death (SIGKILL
classified transient within a bounded wait, never a hang).
"""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from pytensor_federated_tpu import faultinject as fi
from pytensor_federated_tpu.service.arena import Arena
from pytensor_federated_tpu.service.npwire import (
    WireError,
    decode_batch,
    encode_batch,
    is_batch_frame,
)
from pytensor_federated_tpu.service.ring import (
    DEFAULT_RING_RECORD_BYTES,
    DEFAULT_RING_SLOTS,
    Ring,
    RingArraysClient,
    _PRODUCED_OFF,
    _RING_RECORDS_OFFSET,
    _U64,
    futex_available,
    init_ring_header,
    reset_syscall_counts,
    serve_ring,
    syscall_counts,
)
from pytensor_federated_tpu.service.shm import ShmArraysClient, serve_shm


def quad_compute(x):
    x = np.asarray(x)
    return [
        np.asarray(-np.sum((x - 3.0) ** 2)),
        (-2.0 * (x - 3.0)).astype(x.dtype),
    ]


def expected(i):
    return -((i - 3.0) ** 2 + 4.0)


def _ring_arena(tmp_path, *, slots=8, record_bytes=128, name="r.shm"):
    arena = Arena.create(
        1 << 20,
        path=str(tmp_path / name),
        ring_slots=slots,
        ring_record_bytes=record_bytes,
    )
    init_ring_header(arena)
    return arena


def _pair(arena):
    return (
        Ring(arena, role="producer"),
        Ring(arena, role="consumer"),
    )


@pytest.fixture()
def ring_node():
    """One in-process ring node (daemon thread) -> (host, port)."""
    ports = []
    threading.Thread(
        target=serve_ring,
        args=(quad_compute,),
        kwargs=dict(ready_callback=ports.append),
        daemon=True,
    ).start()
    deadline = time.time() + 10
    while not ports and time.time() < deadline:
        time.sleep(0.01)
    assert ports, "ring node did not come up"
    yield "127.0.0.1", ports[0]


@pytest.fixture()
def client(ring_node):
    c = RingArraysClient(*ring_node)
    yield c
    c.close()


# ---------------------------------------------------------------------------
# the seqlock ring protocol
# ---------------------------------------------------------------------------


class TestRingProtocol:
    def test_roundtrip_single_record(self, tmp_path):
        arena = _ring_arena(tmp_path)
        prod, cons = _pair(arena)
        assert prod.try_produce(b"hello ring")
        assert cons.recv(timeout_s=2.0) == b"hello ring"
        arena.close(unlink=True)

    def test_spanning_frame_roundtrip(self, tmp_path):
        """A frame bigger than one record spans K records; record 0
        carries the total, continuations their chunk length."""
        arena = _ring_arena(tmp_path, slots=8, record_bytes=128)
        prod, cons = _pair(arena)
        frame = bytes(range(256)) * 2  # 512 B > 112 B payload cap
        assert prod.try_produce(frame)
        assert cons.recv(timeout_s=2.0) == frame
        arena.close(unlink=True)

    def test_wraparound_many_laps(self, tmp_path):
        """Sequences stay monotone across laps: 10x the slot count of
        varied-size frames round-trip in order."""
        arena = _ring_arena(tmp_path, slots=4, record_bytes=128)
        prod, cons = _pair(arena)
        for i in range(40):
            frame = bytes([i % 251]) * (1 + (i * 37) % 300)
            assert prod.try_produce(frame)
            assert cons.recv(timeout_s=2.0) == frame
        arena.close(unlink=True)

    def test_full_ring_refuses_never_blocks(self, tmp_path):
        arena = _ring_arena(tmp_path, slots=4, record_bytes=128)
        prod, cons = _pair(arena)
        for _ in range(4):
            assert prod.try_produce(b"x" * 100)
        assert not prod.try_produce(b"x")  # full: doorbell territory
        assert cons.recv(timeout_s=2.0) == b"x" * 100
        assert prod.try_produce(b"y")  # one drained slot frees one
        arena.close(unlink=True)

    def test_oversized_frame_refused(self, tmp_path):
        arena = _ring_arena(tmp_path, slots=4, record_bytes=128)
        prod, _cons = _pair(arena)
        cap = prod.payload_cap * prod.slots
        assert not prod.try_produce(b"z" * (cap + 1))
        with pytest.raises(WireError, match="exceeds"):
            prod.produce_blocking(b"z" * (cap + 1), timeout_s=0.1)
        arena.close(unlink=True)

    def test_recv_timeout_is_loud(self, tmp_path):
        arena = _ring_arena(tmp_path)
        _prod, cons = _pair(arena)
        with pytest.raises(TimeoutError, match="timed out"):
            cons.recv(timeout_s=0.1)
        arena.close(unlink=True)

    def test_torn_record_is_wire_error(self, tmp_path):
        """A record left mid-write (odd seq) under a PUBLISHED produced
        counter can never be a slow producer — loud, not a hang (the
        chaos torn_ring_word scenario)."""
        arena = _ring_arena(tmp_path)
        prod, cons = _pair(arena)
        assert prod.try_produce(b"torn")
        _U64.pack_into(arena.mm, _RING_RECORDS_OFFSET, 1)  # re-tear seq
        t0 = time.monotonic()
        with pytest.raises(WireError, match="torn"):
            cons.recv(timeout_s=30.0)
        assert time.monotonic() - t0 < 5.0  # detected, not waited out
        arena.close(unlink=True)

    def test_future_lap_seq_is_wire_error(self, tmp_path):
        arena = _ring_arena(tmp_path, slots=8)
        prod, cons = _pair(arena)
        assert prod.try_produce(b"stale")
        _U64.pack_into(arena.mm, _RING_RECORDS_OFFSET, 2 * 8 + 2)
        with pytest.raises(WireError, match="recycled"):
            cons.recv(timeout_s=2.0)
        arena.close(unlink=True)

    def test_wrong_slot_residue_is_wire_error(self, tmp_path):
        """Second lap: a sub-``want`` stamp belonging to ANOTHER slot
        is scribble, not an older lap of this slot."""
        arena = _ring_arena(tmp_path, slots=8)
        prod, cons = _pair(arena)
        for _ in range(8):  # advance both ends one full lap
            assert prod.try_produce(b"lap")
            assert cons.recv(timeout_s=2.0) == b"lap"
        assert prod.try_produce(b"slot")  # pos 8 -> slot 0, seq 18
        _U64.pack_into(arena.mm, _RING_RECORDS_OFFSET, 2 * 1 + 2)
        with pytest.raises(WireError, match="slot"):
            cons.recv(timeout_s=2.0)
        arena.close(unlink=True)

    def test_zeroed_after_first_lap_is_wire_error(self, tmp_path):
        arena = _ring_arena(tmp_path, slots=8)
        prod, cons = _pair(arena)
        for _ in range(8):
            assert prod.try_produce(b"lap")
            assert cons.recv(timeout_s=2.0) == b"lap"
        assert prod.try_produce(b"zero")
        _U64.pack_into(arena.mm, _RING_RECORDS_OFFSET, 0)
        with pytest.raises(WireError, match="zeroed"):
            cons.recv(timeout_s=2.0)
        arena.close(unlink=True)

    def test_producer_close_unparks_consumer(self, tmp_path):
        """Clean close zeroes the epoch + wakes: a PARKED consumer
        classifies the departure as ConnectionError within a slice."""
        arena = _ring_arena(tmp_path)
        prod, cons = _pair(arena)
        timer = threading.Timer(0.2, prod.close)
        timer.start()
        t0 = time.monotonic()
        with pytest.raises(ConnectionError, match="epoch zeroed"):
            cons.recv(timeout_s=30.0)
        assert time.monotonic() - t0 < 5.0
        timer.cancel()
        arena.close(unlink=True)

    def test_v1_arena_has_no_ring(self, tmp_path):
        arena = Arena.create(1 << 20, path=str(tmp_path / "v1.shm"))
        with pytest.raises(WireError, match="ring"):
            Ring(arena, role="producer")
        with pytest.raises(WireError, match="ring"):
            init_ring_header(arena)
        arena.close(unlink=True)

    def test_foreign_geometry_is_loud(self, tmp_path):
        arena = _ring_arena(tmp_path, slots=8, record_bytes=128)
        struct.pack_into("<I", arena.mm, _PRODUCED_OFF + 28, 16)
        with pytest.raises(WireError, match="geometry"):
            Ring(arena, role="consumer")
        arena.close(unlink=True)

    def test_syscall_counters_account_parks(self, tmp_path):
        """The shim counters ARE the syscalls/eval measurement (no
        strace in this container): a parked wait increments exactly
        one wait counter family."""
        arena = _ring_arena(tmp_path)
        prod, cons = _pair(arena)
        reset_syscall_counts()
        with pytest.raises(TimeoutError):
            cons.recv(timeout_s=0.12)
        counts = syscall_counts()
        if futex_available():
            assert counts["futex_wait"] >= 1
            assert counts["fallback_poll"] == 0
        else:
            assert counts["fallback_poll"] >= 1
        prod.close()
        arena.close(unlink=True)


# ---------------------------------------------------------------------------
# client/server surface
# ---------------------------------------------------------------------------


class TestRingClient:
    def test_evaluate_rides_the_ring(self, client):
        assert client.evaluate(np.array([2.0, 5.0]))  # attaches
        assert client._com_ring is not None  # rings really negotiated
        out = client.evaluate(np.array([1.0, 5.0]))
        assert float(out[0]) == expected(1.0)
        assert np.allclose(out[1], [4.0, -4.0])

    def test_evaluate_many_pipelined_and_batched(self, client):
        reqs = [(np.array([float(i), 5.0]),) for i in range(12)]
        for kw in (dict(window=4), dict(window=4, batch=True)):
            res = client.evaluate_many(reqs, **kw)
            for i, r in enumerate(res):
                assert float(r[0]) == expected(float(i))

    def test_get_load_and_ping(self, client):
        load = client.get_load()
        assert load is not None and load["transport"] == "ring"
        rtt = client.ping()
        assert 0 < rtt < 5.0

    def test_ring_client_against_plain_shm_node(self):
        """No ring spec in ATTACH_OK -> every frame takes the doorbell,
        behavior identical to the parent class."""
        ports = []
        threading.Thread(
            target=serve_shm, args=(quad_compute,),
            kwargs=dict(ready_callback=ports.append), daemon=True,
        ).start()
        while not ports:
            time.sleep(0.01)
        c = RingArraysClient("127.0.0.1", ports[0])
        try:
            out = c.evaluate(np.array([2.0, 5.0]))
            assert float(out[0]) == expected(2.0)
            assert c._com_ring is None and c._sub_ring is None
        finally:
            c.close()

    def test_plain_shm_client_against_ring_node(self, ring_node):
        """A ring node serves doorbell-only clients unchanged."""
        c = ShmArraysClient(*ring_node)
        try:
            out = c.evaluate(np.array([4.0, 5.0]))
            assert float(out[0]) == expected(4.0)
        finally:
            c.close()

    def test_tiny_ring_falls_back_and_correlates(self):
        """Frames that outgrow a tiny ring take the tcp doorbell; the
        per-channel FIFO tags keep mixed-channel correlation straight
        across a pipelined window."""
        ports = []
        threading.Thread(
            target=serve_ring, args=(quad_compute,),
            kwargs=dict(
                ready_callback=ports.append,
                ring_slots=2, ring_record_bytes=64,
            ),
            daemon=True,
        ).start()
        while not ports:
            time.sleep(0.01)
        c = RingArraysClient("127.0.0.1", ports[0])
        try:
            reqs = [(np.array([float(i), 5.0]),) for i in range(10)]
            res = c.evaluate_many(reqs, window=5)
            for i, r in enumerate(res):
                assert float(r[0]) == expected(float(i))
        finally:
            c.close()

    def test_npwire_probe_on_ring_attached_doorbell(self, ring_node):
        """REGRESSION (satellite 3): the pool's zero-item npwire batch
        probe must keep working on a ring node's doorbell socket."""
        host, port = ring_node
        uid = b"p" * 16
        frame = encode_batch([], uuid=uid)
        with socket.create_connection((host, port), timeout=5) as s:
            s.sendall(struct.pack("<I", len(frame)) + frame)
            (n,) = struct.unpack("<I", s.recv(4))
            payload = b""
            while len(payload) < n:
                payload += s.recv(n - len(payload))
        assert is_batch_frame(payload)
        items, ruid, err, _t, _sp = decode_batch(payload)
        assert ruid == uid and err is None and items == []

    def test_sigkill_peer_classified_transient_no_hang(self):
        """Abrupt node death: the parked client's doorbell EOF probe
        classifies a ConnectionError within a bounded wait."""
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        proc = ctx.Process(
            target=_serve_ring_slow_node, args=(port,), daemon=True
        )
        proc.start()
        try:
            c = RingArraysClient(
                "127.0.0.1", port, retries=0,
                connect_timeout_s=2.0, connect_retries=30,
                connect_backoff_s=0.2,
            )
            deadline = time.time() + 60
            while time.time() < deadline:
                try:
                    c.evaluate(np.array([0.0, 5.0]))
                    break
                except (ConnectionError, OSError):
                    time.sleep(0.2)
            assert c._com_ring is not None
            killer = threading.Timer(0.1, proc.kill)
            killer.start()
            t0 = time.monotonic()
            with pytest.raises((ConnectionError, OSError, TimeoutError)):
                for i in range(50):
                    c.evaluate(np.array([float(i), 5.0]))
            killer.cancel()
            assert time.monotonic() - t0 < 30.0  # bounded, never hung
            c.close()
        finally:
            if proc.is_alive():
                proc.kill()
            proc.join(timeout=10)


# ---------------------------------------------------------------------------
# pool integration
# ---------------------------------------------------------------------------


class TestRingPool:
    def test_ring_pool_evaluate_many(self, ring_node):
        from pytensor_federated_tpu.routing import (
            NodePool,
            PooledArraysClient,
        )

        pool = NodePool(transport="ring", probe_timeout_s=2.0)
        pool.add_replica(*ring_node)
        try:
            assert pool.probe_once() == 1
            client = PooledArraysClient(pool)
            reqs = [(np.array([float(i), 5.0]),) for i in range(12)]
            res = client.evaluate_many(reqs, window=4)
            for i in range(12):
                assert float(res[i][0]) == expected(float(i))
        finally:
            pool.close()

    def test_mixed_ring_shm_pool(self, ring_node):
        from pytensor_federated_tpu.routing import (
            NodePool,
            PooledArraysClient,
        )

        sports = []
        threading.Thread(
            target=serve_shm, args=(quad_compute,),
            kwargs=dict(ready_callback=sports.append), daemon=True,
        ).start()
        while not sports:
            time.sleep(0.01)
        pool = NodePool(transport="ring", probe_timeout_s=2.0)
        pool.add_replica(*ring_node)
        pool.add_replica("127.0.0.1", sports[0], transport="shm")
        try:
            assert pool.probe_once() == 2
            assert {r.transport for r in pool.replicas} == {"ring", "shm"}
            client = PooledArraysClient(pool)
            reqs = [(np.array([float(i), 5.0]),) for i in range(16)]
            res = client.evaluate_many(reqs, window=4)
            for i in range(16):
                assert float(res[i][0]) == expected(float(i))
        finally:
            pool.close()


# ---------------------------------------------------------------------------
# chaos seams
# ---------------------------------------------------------------------------


class TestRingChaos:
    def test_torn_ring_word_classified_and_recovers(self, ring_node):
        """A torn completion record is loud (never a hang, never bad
        data) and the next attach serves cleanly."""
        plan = fi.FaultPlan(
            [fi.FaultRule("torn_ring_word", point="ring.record", nth=2)],
            seed=18,
        )
        c = RingArraysClient(*ring_node, retries=0)
        out = c.evaluate(np.array([1.0, 5.0]))  # attach + warm call
        assert float(out[0]) == expected(1.0)
        fi.install(plan)
        try:
            t0 = time.monotonic()
            with pytest.raises(
                (WireError, ConnectionError, TimeoutError, RuntimeError)
            ):
                for i in range(8):
                    c.evaluate(np.array([float(i), 5.0]))
            assert time.monotonic() - t0 < 40.0
        finally:
            fi.uninstall()
        out = c.evaluate(np.array([2.0, 5.0]))  # fresh attach, clean
        assert float(out[0]) == expected(2.0)
        c.close()

    def test_stale_generation_classified(self, ring_node):
        plan = fi.FaultPlan(
            [fi.FaultRule(
                "stale_generation", point="ring.record", nth=2
            )],
            seed=19,
        )
        c = RingArraysClient(*ring_node, retries=0)
        c.evaluate(np.array([1.0, 5.0]))
        fi.install(plan)
        try:
            with pytest.raises(
                (WireError, ConnectionError, TimeoutError, RuntimeError)
            ):
                for i in range(8):
                    c.evaluate(np.array([float(i), 5.0]))
        finally:
            fi.uninstall()
        out = c.evaluate(np.array([3.0, 5.0]))
        assert float(out[0]) == expected(3.0)
        c.close()


def _serve_ring_slow_node(port):
    """Module-level (spawn target): a ring node whose compute sleeps,
    so a SIGKILL lands while the client is parked on the ring."""
    import time as _time

    import numpy as _np

    from pytensor_federated_tpu.service.ring import serve_ring as _serve

    def compute(x):
        _time.sleep(0.05)
        x = _np.asarray(x)
        return [
            _np.asarray(-_np.sum((x - 3.0) ** 2)),
            (-2.0 * (x - 3.0)).astype(x.dtype),
        ]

    _serve(compute, "127.0.0.1", port)
