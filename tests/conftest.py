"""Test harness configuration.

Distributed tests run on a virtual 8-device CPU mesh
(``--xla_force_host_platform_device_count=8``) — the TPU analog of the
reference's localhost multi-process "multi-node" servers (reference:
test_service.py:180-224; SURVEY §4) — so the full sharded path executes
without TPU hardware.

This environment may pre-register a TPU PJRT plugin at interpreter
startup (sitecustomize), before pytest loads this file.  Backends
initialize lazily, so this file can still force a pure-CPU session: it
restricts ``jax_platforms`` to cpu AND drops the plugin's backend
factory before the first device query.  Both steps matter — the suite
must never *dial* the TPU plugin: tests are CPU-only, and a test
process that opens (or merely half-opens, e.g. when killed by a
timeout) a tunneled-chip session can orphan its claim and wedge the
chip for every later process on the machine, including the real
benchmark run.
"""

import contextlib
import multiprocessing as mp
import os
import sys
import time

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

# Make the repo root importable regardless of cwd.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytensor_federated_tpu.utils import force_cpu_backend  # noqa: E402

force_cpu_backend()
_CPUS = jax.devices("cpu")
jax.config.update("jax_default_device", _CPUS[0])


@contextlib.contextmanager
def scrubbed_child_env():
    """Env scrub for child processes: children must not initialize any
    TPU plugin (sitecustomize keys off PALLAS_AXON_POOL_IPS; the chip may
    be held by the parent) — they are pure-CPU gRPC nodes, like the
    reference's worker pool (reference: demo_node.py:98-108)."""
    saved = {
        k: os.environ.get(k) for k in ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS")
    }
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def spawn_node_procs(target, args_per_proc):
    """Start one daemon process per args tuple under a scrubbed env."""
    with scrubbed_child_env():
        ctx = mp.get_context("spawn")
        procs = [
            ctx.Process(target=target, args=a, daemon=True)
            for a in args_per_proc
        ]
        for p in procs:
            p.start()
    return procs


def wait_nodes_up(ports, *, timeout=60.0, host="127.0.0.1"):
    """Poll GetLoad until every port answers (server readiness barrier)."""
    import asyncio

    from pytensor_federated_tpu.service import get_loads_async

    deadline = time.time() + timeout

    async def wait_up():
        while time.time() < deadline:
            loads = await get_loads_async(
                [(host, p) for p in ports], timeout=1.0
            )
            if all(l is not None for l in loads):
                return
            await asyncio.sleep(0.2)
        raise TimeoutError(f"nodes on ports {ports} failed to start")

    asyncio.run(wait_up())


@pytest.fixture(scope="session")
def devices8():
    if len(_CPUS) < 8:
        pytest.skip(f"needs 8 CPU devices, have {len(_CPUS)}")
    return _CPUS[:8]


@pytest.fixture(scope="session")
def mesh8(devices8):
    from pytensor_federated_tpu.parallel import make_mesh

    return make_mesh({"shards": 8}, devices=devices8)


@pytest.fixture(scope="module", autouse=True)
def _clear_jax_caches_between_modules():
    """Bound in-process compile-state accumulation.

    A full-suite run compiles thousands of distinct XLA programs in one
    process; after ~500 tests the CPU backend_compile was observed
    SEGFAULTING non-deterministically (fullsuite_final*.log: 'Fatal
    Python error' inside backend_compile_and_load, twice, at different
    tests ~80% in — while every module passes standalone and an
    11-file tail subset passes together).  Dropping the jit/pjit
    caches after each module releases the accumulated executables;
    per-module recompiles cost a little wall time and remove the
    unbounded growth.
    """
    yield
    jax.clear_caches()
