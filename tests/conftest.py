"""Test harness configuration.

Distributed tests run on a virtual 8-device CPU mesh
(``--xla_force_host_platform_device_count=8``) — the TPU analog of the
reference's localhost multi-process "multi-node" servers (reference:
test_service.py:180-224; SURVEY §4) — so the full sharded path executes
without TPU hardware.

This environment may pre-register a TPU PJRT plugin at interpreter
startup (sitecustomize), before pytest loads this file.  JAX's *CPU*
backend initializes lazily, so it is still possible to (a) request 8
virtual CPU devices via XLA_FLAGS and (b) route all un-placed
computation to CPU via ``jax_default_device`` — no re-exec needed.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

_CPUS = jax.devices("cpu")
jax.config.update("jax_default_device", _CPUS[0])

# Make the repo root importable regardless of cwd.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture(scope="session")
def devices8():
    if len(_CPUS) < 8:
        pytest.skip(f"needs 8 CPU devices, have {len(_CPUS)}")
    return _CPUS[:8]


@pytest.fixture(scope="session")
def mesh8(devices8):
    from pytensor_federated_tpu.parallel import make_mesh

    return make_mesh({"shards": 8}, devices=devices8)
