"""Micro-batching engine + batched wire frames (ISSUE 3).

Three layers under test, mirroring the feature's structure:

- the :class:`MicroBatcher` coalescing engine and the vmapped
  padded-bucket compute variant (pure in-process);
- the batch frame formats (npwire flag bit 8 / npproto field 17) —
  round trips, loud failure, and the PR-2 byte-identity invariant for
  unbatched frames;
- end-to-end over real transports: a spawned gRPC node (capability
  advertisement, batched evaluate_many, per-item error isolation for a
  corrupt request inside a batch) and the in-thread TCP server (probe
  negotiation, adaptive in-flight cap regression).
"""

import asyncio
import socket
import struct
import threading
import time

import grpc
import numpy as np
import pytest
from conftest import spawn_node_procs, wait_nodes_up

from pytensor_federated_tpu.service import npproto_codec
from pytensor_federated_tpu.service.batching import (
    MicroBatcher,
    _bucket,
    batched_compute_fn,
)
from pytensor_federated_tpu.service.npwire import (
    WireError,
    decode_arrays_all,
    decode_arrays_ex,
    decode_batch,
    encode_arrays,
    encode_batch,
    is_batch_frame,
)

BASE_PORT = 29700


# ---------------------------------------------------------------------------
# MicroBatcher engine
# ---------------------------------------------------------------------------


def _quad(x):
    x = np.asarray(x)
    if np.any(x > 1e6):
        raise ValueError("poisoned input")
    return [
        np.asarray(-np.sum((x - 3.0) ** 2)),
        (-2.0 * (x - 3.0)).astype(x.dtype),
    ]


class _CountingBatch:
    """Vectorized twin of _quad that counts its invocations."""

    def __init__(self):
        self.calls = 0
        self.sizes = []

    def __call__(self, requests):
        self.calls += 1
        self.sizes.append(len(requests))
        xs = np.stack([np.asarray(r[0]) for r in requests])
        if np.any(xs > 1e6):
            raise ValueError("poisoned batch")
        return [
            [np.asarray(-np.sum((x - 3.0) ** 2)),
             (-2.0 * (x - 3.0)).astype(x.dtype)]
            for x in xs
        ]


def test_idle_single_request_dispatches_immediately():
    """A lone request must not wait for max_wait_us: with a huge
    configured wait, the submit still returns in a fraction of it."""
    batch_fn = _CountingBatch()
    mb = MicroBatcher(
        _quad, batch_fn, max_batch=8, max_wait_us=200_000.0, inline=True
    )

    async def run():
        t0 = time.perf_counter()
        out = await mb.submit((np.array([1.0, 5.0]),))
        return time.perf_counter() - t0, out

    elapsed, out = asyncio.run(run())
    np.testing.assert_allclose(out[0], -8.0)
    assert elapsed < 0.05  # 200 ms wait would trip this 40x over
    assert batch_fn.calls == 0  # single request takes the scalar path


def test_window_coalesces_into_one_vmapped_call():
    batch_fn = _CountingBatch()
    mb = MicroBatcher(_quad, batch_fn, max_batch=32, inline=True)
    reqs = [(np.array([float(i), 5.0]),) for i in range(6)]

    async def run():
        return await mb.submit_many(reqs)

    res = asyncio.run(run())
    assert batch_fn.calls == 1 and batch_fn.sizes == [6]
    for i, out in enumerate(res):
        np.testing.assert_allclose(out[0], -((i - 3.0) ** 2 + 4.0))


def test_poisoned_item_fails_only_its_own_slot():
    """Batched execution fails -> scalar re-execution isolates the
    poison: siblings get results, the poisoned slot gets ITS error."""
    batch_fn = _CountingBatch()
    mb = MicroBatcher(_quad, batch_fn, max_batch=32, inline=True)
    reqs = [(np.array([float(i), 5.0]),) for i in range(5)]
    reqs[2] = (np.array([np.inf, 5.0]) * 1e7,)

    async def run():
        return await mb.submit_many(reqs)

    res = asyncio.run(run())
    assert isinstance(res[2], ValueError)
    assert mb.n_fallbacks == 1
    for i in (0, 1, 3, 4):
        np.testing.assert_allclose(
            res[i][0], -((i - 3.0) ** 2 + 4.0)
        )


def test_mixed_signatures_group_separately():
    batch_fn = _CountingBatch()
    mb = MicroBatcher(_quad, batch_fn, max_batch=32, max_wait_us=0.0,
                      inline=True)
    reqs = [
        (np.array([0.0, 5.0]),),
        (np.array([1.0, 2.0, 3.0]),),
        (np.array([1.0, 5.0]),),
        (np.array([4.0, 5.0, 6.0]),),
    ]

    async def run():
        return await mb.submit_many(reqs)

    res = asyncio.run(run())
    # Two signature groups of two -> two vmapped calls, results in
    # the ORIGINAL order despite the regrouping.
    assert batch_fn.sizes == [2, 2]
    np.testing.assert_allclose(res[1][0], _quad(reqs[1][0])[0])
    np.testing.assert_allclose(res[3][0], _quad(reqs[3][0])[0])


def test_max_batch_splits_oversized_windows():
    batch_fn = _CountingBatch()
    mb = MicroBatcher(_quad, batch_fn, max_batch=4, max_wait_us=0.0,
                      inline=True)
    reqs = [(np.array([float(i), 5.0]),) for i in range(10)]
    asyncio.run(mb.submit_many(reqs))
    assert all(s <= 4 for s in batch_fn.sizes)
    assert sum(batch_fn.sizes) + (mb.n_dispatched - sum(batch_fn.sizes)) == 10


def test_stats_shape():
    mb = MicroBatcher(_quad, None, max_batch=16, inline=True)
    asyncio.run(mb.submit((np.zeros(2),)))
    stats = mb.stats()
    assert stats["max_batch"] == 16
    assert stats["dispatched_total"] == 1
    assert stats["queue_depth"] == 0


def test_bucket_ladder():
    assert [_bucket(k, 32) for k in (1, 2, 3, 5, 9, 31, 32)] == [
        1, 2, 4, 8, 16, 32, 32,
    ]
    # cap below k: never shrinks below k itself
    assert _bucket(7, 4) == 7


def test_batched_compute_fn_matches_scalar():
    import jax.numpy as jnp

    def fn(x):
        return [jnp.sum((x - 3.0) ** 2), x * 2.0]

    bfn = batched_compute_fn(fn, max_batch=16)
    for k in (1, 2, 3, 5, 8):  # ragged sizes across bucket boundaries
        reqs = [(np.arange(4.0) + i,) for i in range(k)]
        outs = bfn(reqs)
        assert len(outs) == k
        for i, out in enumerate(outs):
            np.testing.assert_allclose(
                out[0], np.sum((np.arange(4.0) + i - 3.0) ** 2)
            )
            np.testing.assert_allclose(out[1], (np.arange(4.0) + i) * 2)


def test_batched_compute_fn_chunks_oversized_windows():
    """A window larger than the fn's own max_batch (e.g. a service
    configured with a bigger cap) chunks instead of leaking
    non-power-of-two padded shapes into the jit cache."""
    import jax.numpy as jnp

    bfn = batched_compute_fn(lambda x: [x * 2.0], max_batch=4)
    reqs = [(np.arange(3.0) + i,) for i in range(10)]
    outs = bfn(reqs)
    assert len(outs) == 10
    for i, out in enumerate(outs):
        np.testing.assert_allclose(out[0], (np.arange(3.0) + i) * 2)


def test_tcp_server_survives_wrong_count_batch_fn():
    """A user batch_fn returning the wrong result count must trigger
    the scalar fallback (correct per-item replies), not crash the
    node."""

    def compute(x):
        return _quad(x)

    def bad_batch(requests):  # returns padded-bucket count, not k
        xs = np.stack([np.asarray(r[0]) for r in requests])
        return [[np.asarray(0.0)]] * (len(requests) + 3)

    compute.batch = bad_batch
    port, _t = _tcp_server(compute)
    from pytensor_federated_tpu.service import TcpArraysClient

    client = TcpArraysClient("127.0.0.1", port)
    reqs = [(np.array([float(i), 5.0]),) for i in range(5)]
    res = client.evaluate_many(reqs, window=8, batch=True)
    for i in range(5):  # fallback produced the SCALAR path's results
        np.testing.assert_allclose(res[i][0], -((i - 3.0) ** 2 + 4.0))
    client.close()


# ---------------------------------------------------------------------------
# Wire formats
# ---------------------------------------------------------------------------


def test_batch_frame_roundtrip_and_plain_decoder_rejects():
    items = [
        encode_arrays([np.arange(3.0)], uuid=b"a" * 16),
        encode_arrays([], uuid=b"b" * 16, error="boom"),
    ]
    frame = encode_batch(items, uuid=b"o" * 16, trace_id=b"t" * 16)
    assert is_batch_frame(frame) and not is_batch_frame(items[0])
    dec, uuid, err, tid, spans = decode_batch(frame)
    assert dec == items and uuid == b"o" * 16 and err is None
    assert tid == b"t" * 16 and spans is None
    with pytest.raises(WireError, match="batch frame"):
        decode_arrays_all(frame)
    with pytest.raises(WireError):
        decode_batch(items[0])  # a plain frame is not a batch


def test_zero_item_batch_is_legal_probe():
    frame = encode_batch([], uuid=b"p" * 16)
    items, uuid, err, tid, spans = decode_batch(frame)
    assert items == [] and uuid == b"p" * 16 and err is None


def test_unbatched_frame_byte_identical_to_pr2_layout():
    """The PR-2 wire, re-derived from its documented layout by hand:
    an encode_arrays frame with no error/trace/spans must be byte-
    identical — growing batch support cannot have moved a single byte
    of the plain format."""
    arrays = [np.arange(6, dtype=np.float32).reshape(2, 3),
              np.asarray(3.5)]
    uuid = b"u" * 16
    manual = [struct.pack("<4sBB16sI", b"NPW1", 1, 0, uuid, len(arrays))]
    for a in arrays:
        dt = a.dtype.str.encode("ascii")
        manual.append(struct.pack("<H", len(dt)))
        manual.append(dt)
        manual.append(struct.pack("<B", a.ndim))
        manual.append(struct.pack(f"<{a.ndim}Q", *a.shape))
        data = a.tobytes()
        manual.append(struct.pack("<Q", len(data)))
        manual.append(data)
    assert encode_arrays(arrays, uuid=uuid) == b"".join(manual)


def test_npproto_plain_msg_byte_identical_without_batch_fields():
    """encode_arrays_msg with error=None must emit the exact pre-batch
    bytes (no field 14/17 anywhere)."""
    arrays = [np.arange(4.0)]
    enc = npproto_codec.encode_arrays_msg(arrays, uuid="u-1")
    # No field-14 (tag 0x72) / field-17 (tag 0x8a 0x01) headers appear:
    # decode sees no error and no batch items.
    _a, _u, err, _t, _s = npproto_codec.decode_arrays_msg_full(enc)
    assert err is None
    assert not npproto_codec.has_batch_items(enc)


def test_npproto_batch_msg_roundtrip():
    items = [
        npproto_codec.encode_arrays_msg([np.arange(3.0)], uuid="i0"),
        npproto_codec.encode_arrays_msg([], uuid="i1", error="bad"),
    ]
    msg = npproto_codec.encode_batch_msg(items, uuid="outer",
                                         trace_id=b"t" * 16)
    assert npproto_codec.has_batch_items(msg)
    dec, uuid, tid, spans = npproto_codec.decode_batch_msg(msg)
    assert dec == items and uuid == "outer" and tid == b"t" * 16
    _arrs, u1, err1, _t, _s = npproto_codec.decode_arrays_msg_full(
        items[1]
    )
    assert u1 == "i1" and err1 == "bad"


# ---------------------------------------------------------------------------
# Official protobuf runtime interop while batching is active
# ---------------------------------------------------------------------------

official = pytest.importorskip("google.protobuf", reason="cross-check")


def _official_output_arrays():
    from google.protobuf import (
        descriptor_pb2,
        descriptor_pool,
        message_factory,
    )

    pool = descriptor_pool.DescriptorPool()
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.name = "batchx.proto"
    fdp.package = "batchx"
    fdp.syntax = "proto3"
    F = descriptor_pb2.FieldDescriptorProto
    nd = fdp.message_type.add()
    nd.name = "ndarray"
    for name, num, ftype, label in [
        ("data", 1, F.TYPE_BYTES, F.LABEL_OPTIONAL),
        ("dtype", 2, F.TYPE_STRING, F.LABEL_OPTIONAL),
        ("shape", 3, F.TYPE_INT64, F.LABEL_REPEATED),
        ("strides", 4, F.TYPE_INT64, F.LABEL_REPEATED),
    ]:
        f = nd.field.add()
        f.name, f.number, f.type, f.label = name, num, ftype, label
    m = fdp.message_type.add()
    m.name = "OutputArrays"
    f = m.field.add()
    f.name, f.number, f.type, f.label = (
        "items", 1, F.TYPE_MESSAGE, F.LABEL_REPEATED,
    )
    f.type_name = ".batchx.ndarray"
    f = m.field.add()
    f.name, f.number, f.type, f.label = (
        "uuid", 2, F.TYPE_STRING, F.LABEL_OPTIONAL,
    )
    pool.Add(fdp)
    return message_factory.GetMessageClass(
        pool.FindMessageTypeByName("batchx.OutputArrays")
    )


def test_official_runtime_parses_replies_with_batching_active():
    """(c): while batching is active, every npproto artifact a
    reference runtime could see still parses under the OFFICIAL
    protobuf runtime with the known fields intact — per-item error
    (14), trace (15), spans (16) and batch items (17) are all skipped
    as unknown fields."""
    Out = _official_output_arrays()
    # A batch reply item carrying results + the error extension.
    item = npproto_codec.encode_arrays_msg(
        [np.arange(3.0)], uuid="item-0", error="err text"
    )
    msg = Out()
    msg.ParseFromString(item)
    assert msg.uuid == "item-0" and len(msg.items) == 1
    # A whole batch reply: unknown field 17 only + uuid.
    batch = npproto_codec.encode_batch_msg(
        [item, item], uuid="outer-1", trace_id=b"t" * 16
    )
    msg2 = Out()
    msg2.ParseFromString(batch)
    assert msg2.uuid == "outer-1" and len(msg2.items) == 0
    # With piggybacked spans appended (field 16), still parseable.
    with_spans = npproto_codec.append_spans_msg(batch, [{"name": "s"}])
    msg3 = Out()
    msg3.ParseFromString(with_spans)
    assert msg3.uuid == "outer-1"


# ---------------------------------------------------------------------------
# End-to-end: gRPC node
# ---------------------------------------------------------------------------


def _serve_batched_node(port):
    import logging

    logging.basicConfig(level=logging.WARNING)
    import numpy as np  # noqa: F811 (spawned child)

    def compute(x):
        x = np.asarray(x)
        if np.any(x < -1e6):
            raise ValueError("poisoned input")
        return [
            np.asarray(-np.sum((x - 3.0) ** 2)),
            (-2.0 * (x - 3.0)).astype(x.dtype),
        ]

    def compute_batch(requests):
        xs = np.stack([np.asarray(r[0]) for r in requests])
        if np.any(xs < -1e6):
            raise ValueError("poisoned batch")
        logps = -np.sum((xs - 3.0) ** 2, axis=1)
        grads = (-2.0 * (xs - 3.0)).astype(xs.dtype)
        return [[np.asarray(lp), g] for lp, g in zip(logps, grads)]

    compute.batch = compute_batch

    from pytensor_federated_tpu.service import run_node

    run_node(compute, "127.0.0.1", port, inline_compute=True)


@pytest.fixture(scope="module")
def batched_node():
    port = BASE_PORT
    procs = spawn_node_procs(_serve_batched_node, [(port,)])
    wait_nodes_up([port], timeout=60)
    yield port
    for p in procs:
        p.terminate()
    for p in procs:
        p.join(timeout=5)


def test_server_advertises_batch_capability(batched_node):
    from pytensor_federated_tpu.service import get_load_async

    load = asyncio.run(get_load_async("127.0.0.1", batched_node))
    assert isinstance(load.get("batch"), dict)
    assert load["batch"]["max_batch"] == 32
    assert "queue_depth" in load["batch"]
    assert "dispatched_total" in load["batch"]


def test_batched_evaluate_many_matches_per_call(batched_node):
    from pytensor_federated_tpu.service import ArraysToArraysServiceClient

    client = ArraysToArraysServiceClient("127.0.0.1", batched_node)
    reqs = [(np.array([float(i), 5.0]),) for i in range(20)]
    per_call = [client.evaluate(*args) for args in reqs[:3]]
    batched = client.evaluate_many(reqs, window=8, batch=True)
    plain = client.evaluate_many(reqs, window=8, batch=False)
    for i in range(3):
        np.testing.assert_allclose(batched[i][0], per_call[i][0])
    for b, p in zip(batched, plain):
        np.testing.assert_allclose(b[0], p[0])
        np.testing.assert_allclose(b[1], p[1])


def test_auto_mode_batches_and_connection_survives_compute_error(
    batched_node,
):
    from pytensor_federated_tpu.service import ArraysToArraysServiceClient

    client = ArraysToArraysServiceClient("127.0.0.1", batched_node)
    reqs = [(np.array([float(i), 5.0]),) for i in range(6)]
    ok = client.evaluate_many(reqs, window=4)  # auto -> batched
    np.testing.assert_allclose(ok[5][0], -(4.0 + 4.0))
    poisoned = list(reqs)
    poisoned[2] = (np.array([-1e9, 5.0]),)
    with pytest.raises(RuntimeError, match="server error"):
        client.evaluate_many(poisoned, window=4)
    # The connection stays correlated for the NEXT call.
    again = client.evaluate_many(reqs, window=4)
    np.testing.assert_allclose(again[0][0], -(9.0 + 4.0))


def test_npproto_codec_batches_toward_own_node(batched_node):
    from pytensor_federated_tpu.service import ArraysToArraysServiceClient

    client = ArraysToArraysServiceClient(
        "127.0.0.1", batched_node, codec="npproto"
    )
    reqs = [(np.array([float(i), 5.0]),) for i in range(7)]
    res = client.evaluate_many(reqs, window=4, batch=True)
    np.testing.assert_allclose(res[6][0], -(9.0 + 4.0))


def test_corrupt_item_in_batch_fails_only_its_own_reply(batched_node):
    """The e2e isolation acceptance: a batch frame with one CORRUPT
    item (truncated npwire bytes) comes back with that slot carrying a
    decode error and every sibling carrying real results."""
    good0 = encode_arrays([np.array([0.0, 5.0])], uuid=b"0" * 16)
    good1 = encode_arrays([np.array([1.0, 5.0])], uuid=b"1" * 16)
    corrupt = good0[: len(good0) - 3]  # truncated mid-payload
    frame = encode_batch([good0, corrupt, good1], uuid=b"o" * 16)

    async def call():
        async with grpc.aio.insecure_channel(
            f"127.0.0.1:{batched_node}"
        ) as channel:
            method = channel.unary_unary(
                "/ArraysToArraysService/Evaluate",
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b,
            )
            return await method(frame)

    reply = asyncio.run(call())
    items, uuid, err, _tid, _spans = decode_batch(reply)
    assert uuid == b"o" * 16 and err is None and len(items) == 3
    out0, u0, e0, _, _ = decode_arrays_all(items[0])
    out1, u1, e1, _, _ = decode_arrays_all(items[1])
    out2, u2, e2, _, _ = decode_arrays_all(items[2])
    assert e0 is None and u0 == b"0" * 16
    np.testing.assert_allclose(out0[0], -(9.0 + 4.0))
    assert e1 is not None and "decode error" in e1
    assert e2 is None and u2 == b"1" * 16
    np.testing.assert_allclose(out2[0], -(4.0 + 4.0))


def test_reference_wire_client_interops_unchanged(batched_node):
    """Acceptance: an official-runtime-style plain npproto request
    against a batching-enabled server gets a plain npproto reply (no
    batch fields), exactly as before the feature."""
    Out = _official_output_arrays()
    request = npproto_codec.encode_arrays_msg(
        [np.array([1.0, 5.0])], uuid="ref-1"
    )

    async def call():
        async with grpc.aio.insecure_channel(
            f"127.0.0.1:{batched_node}"
        ) as channel:
            method = channel.unary_unary(
                "/ArraysToArraysService/Evaluate",
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b,
            )
            return await method(request)

    reply = asyncio.run(call())
    msg = Out()
    msg.ParseFromString(reply)
    assert msg.uuid == "ref-1" and len(msg.items) == 2
    logp = np.ndarray(buffer=msg.items[0].data, shape=(),
                      dtype=msg.items[0].dtype)
    np.testing.assert_allclose(logp, -8.0)


def _serve_plain_executor_node(port):
    """A node with NO vectorized variant and NO inline_compute: the
    coalescing engine does not engage (slow computes keep per-request
    executor concurrency), but batch frames are still advertised and
    served."""
    import logging

    logging.basicConfig(level=logging.WARNING)
    import numpy as np  # noqa: F811 (spawned child)

    def compute(x):
        x = np.asarray(x)
        return [np.asarray(-np.sum((x - 3.0) ** 2)),
                (-2.0 * (x - 3.0)).astype(x.dtype)]

    from pytensor_federated_tpu.service import run_node

    run_node(compute, "127.0.0.1", port)


@pytest.fixture(scope="module")
def plain_executor_node():
    port = BASE_PORT + 1
    procs = spawn_node_procs(_serve_plain_executor_node, [(port,)])
    wait_nodes_up([port], timeout=60)
    yield port
    for p in procs:
        p.terminate()
    for p in procs:
        p.join(timeout=5)


def test_unengaged_engine_still_serves_batch_frames(plain_executor_node):
    from pytensor_federated_tpu.service import (
        ArraysToArraysServiceClient,
        get_load_async,
    )

    load = asyncio.run(get_load_async("127.0.0.1", plain_executor_node))
    assert load["batch"]["max_batch"] == 32  # capability advertised
    assert "dispatched_total" not in load["batch"]  # engine not engaged
    client = ArraysToArraysServiceClient("127.0.0.1", plain_executor_node)
    reqs = [(np.array([float(i), 5.0]),) for i in range(9)]
    res = client.evaluate_many(reqs, window=4, batch=True)
    for i in range(9):
        np.testing.assert_allclose(res[i][0], -((i - 3.0) ** 2 + 4.0))


# ---------------------------------------------------------------------------
# TCP lane: probe negotiation + adaptive in-flight cap
# ---------------------------------------------------------------------------


def _tcp_server(compute, n_conn=1):
    from pytensor_federated_tpu.service import serve_tcp_once

    ready = {}
    ev = threading.Event()

    def cb(p):
        ready["port"] = p
        ev.set()

    t = threading.Thread(
        target=serve_tcp_once,
        args=(compute,),
        kwargs=dict(ready_callback=cb, max_connections=n_conn),
        daemon=True,
    )
    t.start()
    assert ev.wait(10)
    return ready["port"], t


def test_tcp_probe_and_batched_window():
    from pytensor_federated_tpu.service import TcpArraysClient

    port, _t = _tcp_server(_quad)
    client = TcpArraysClient("127.0.0.1", port)
    reqs = [(np.array([float(i), 5.0]),) for i in range(9)]
    res = client.evaluate_many(reqs, window=4)  # auto -> probe -> batch
    assert client._batch_ok is True
    np.testing.assert_allclose(res[8][0], -(25.0 + 4.0))
    client.close()
    assert client._batch_ok is None  # re-probed after reconnect


def test_tcp_vmapped_batch_on_server_side():
    """serve_tcp_once drives the compute's .batch variant for a same-
    signature window (counted), with results identical to scalar."""
    batch_fn = _CountingBatch()

    def compute(x):
        return _quad(x)

    compute.batch = batch_fn
    port, _t = _tcp_server(compute)
    from pytensor_federated_tpu.service import TcpArraysClient

    client = TcpArraysClient("127.0.0.1", port)
    reqs = [(np.array([float(i), 5.0]),) for i in range(6)]
    res = client.evaluate_many(reqs, window=8, batch=True)
    np.testing.assert_allclose(res[3][0], -4.0)
    assert batch_fn.calls >= 1 and max(batch_fn.sizes) > 1
    client.close()


def test_tcp_large_requests_still_overlap():
    """Regression for the hardcoded 32 KiB cap: a window of requests
    each LARGER than 32 KiB must still pipeline (>1 frame in flight).
    The server reads TWO frames before sending the first reply — a
    lock-stepped client (old cap) can never satisfy that and would
    time out; the adaptive cap ships both frames up front."""
    result = {}

    def server(sock_ready):
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        sock_ready(srv.getsockname()[1])
        conn, _ = srv.accept()
        conn.settimeout(10.0)

        def read_frame():
            hdr = b""
            while len(hdr) < 4:
                hdr += conn.recv(4 - len(hdr))
            (n,) = struct.unpack("<I", hdr)
            buf = b""
            while len(buf) < n:
                buf += conn.recv(min(65536, n - len(buf)))
            return buf

        try:
            frames = [read_frame(), read_frame()]  # BOTH before reply
            result["overlapped"] = True
        except socket.timeout:  # pragma: no cover - the failure mode
            result["overlapped"] = False
            conn.close()
            srv.close()
            return
        for payload in frames:
            _arrays, uid, _e, _t = decode_arrays_ex(payload)
            reply = encode_arrays([np.asarray(0.0)], uuid=uid)
            conn.sendall(struct.pack("<I", len(reply)) + reply)
        conn.close()
        srv.close()

    ready = {}
    ev = threading.Event()
    t = threading.Thread(
        target=server,
        args=(lambda p: (ready.update(p=p), ev.set()),),
        daemon=True,
    )
    t.start()
    assert ev.wait(10)
    from pytensor_federated_tpu.service import TcpArraysClient

    client = TcpArraysClient("127.0.0.1", ready["p"])
    big = np.zeros(20_000, dtype=np.float64)  # ~160 KiB per request
    res = client.evaluate_many([(big,), (big,)], window=2, batch=False)
    t.join(timeout=10)
    assert result.get("overlapped") is True
    assert len(res) == 2
    client.close()


def test_tcp_explicit_inflight_knob_restores_lockstep():
    """max_inflight_bytes as a constructor knob: pinning it small
    forces the proven-safe lock-step mode (one frame in flight)."""
    from pytensor_federated_tpu.service import TcpArraysClient

    port, _t = _tcp_server(_quad)
    client = TcpArraysClient(
        "127.0.0.1", port, max_inflight_bytes=1
    )
    reqs = [(np.array([float(i), 5.0]),) for i in range(4)]
    res = client.evaluate_many(reqs, window=4, batch=False)
    np.testing.assert_allclose(res[3][0], -4.0)
    client.close()


# ---------------------------------------------------------------------------
# Fanout coalescing
# ---------------------------------------------------------------------------


def test_coalescing_caller_merges_member_threads():
    from pytensor_federated_tpu.fanout_exec import CoalescingCaller

    calls = []

    def evaluate_many(reqs):
        calls.append(len(reqs))
        return [np.sum(args[0]) for args in reqs]

    caller = CoalescingCaller(evaluate_many, width=4, max_wait_s=2.0)
    results = [None] * 4

    def member(i):
        results[i] = caller.evaluate(np.full(3, float(i)))

    threads = [
        threading.Thread(target=member, args=(i,)) for i in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert calls == [4]  # ONE batched call for four member threads
    for i in range(4):
        np.testing.assert_allclose(results[i], 3.0 * i)


def test_coalescing_caller_propagates_errors_to_all_members():
    from pytensor_federated_tpu.fanout_exec import CoalescingCaller

    def evaluate_many(reqs):
        raise RuntimeError("node down")

    caller = CoalescingCaller(evaluate_many, width=2, max_wait_s=0.5)
    errors = []

    def member():
        try:
            caller.evaluate(np.zeros(2))
        except RuntimeError as e:
            errors.append(str(e))

    threads = [threading.Thread(target=member) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert errors == ["node down", "node down"]


def test_coalescing_caller_lone_call_after_timeout():
    from pytensor_federated_tpu.fanout_exec import CoalescingCaller

    caller = CoalescingCaller(
        lambda reqs: [len(r) for r in reqs], width=8, max_wait_s=0.01
    )
    t0 = time.perf_counter()
    assert caller.evaluate(np.zeros(1), np.zeros(1)) == 2
    assert time.perf_counter() - t0 < 5.0  # timed out the window, ran solo
