"""ISSUE 4 acceptance e2e: 3-replica pool, one replica SIGKILLed
MID-pipelined-window.

Real process boundaries (multiprocessing-spawn server nodes, the
test_service.py pattern): a :class:`PooledArraysClient` spreads a
pipelined ``evaluate_many`` over three localhost replicas, the
launcher SIGKILLs one while its shard is in flight, and the contract
under test is

- **exactly one reply per request** — the un-replied tail of the dead
  replica's window re-queues onto the survivors; nothing is lost,
  nothing double-assigned, nothing hangs;
- **the breaker trips** on the killed replica and — once a
  replacement node is back on the same port — **half-open-recovers**
  through a single probe call;
- **the trace of the failed-over call shows both replicas' spans**:
  the driver's ``pool.evaluate_many`` root holds ``pool.window``
  children for the killed replica AND the survivors that absorbed its
  tail.
"""

import asyncio
import signal
import time

import numpy as np
import pytest
from conftest import spawn_node_procs, wait_nodes_up

from pytensor_federated_tpu import telemetry
from pytensor_federated_tpu.routing import NodePool, PooledArraysClient
from pytensor_federated_tpu.telemetry import flightrec

BASE_PORT = 29560
COMPUTE_DELAY_S = 0.005


def _serve_slow_node(port, delay):
    """Module-level (spawn needs a picklable target): the quad compute
    with a per-call delay so a pipelined window is genuinely in flight
    for a while — the kill must land MID window."""
    import logging
    import time as _time

    import numpy as _np

    logging.basicConfig(level=logging.WARNING)

    def compute(x):
        _time.sleep(delay)
        x = _np.asarray(x)
        return [
            _np.asarray(-_np.sum((x - 3.0) ** 2)),
            (-2.0 * (x - 3.0)).astype(x.dtype),
        ]

    from pytensor_federated_tpu.service import run_node

    run_node(compute, "127.0.0.1", port)


def _expected(i):
    return -((i - 3.0) ** 2 + 4.0)


@pytest.mark.slow
def test_sigkill_mid_window_failover_breaker_and_trace():
    ports = [BASE_PORT, BASE_PORT + 1, BASE_PORT + 2]
    procs = spawn_node_procs(
        _serve_slow_node, [(p, COMPUTE_DELAY_S) for p in ports]
    )
    telemetry.clear_traces()
    flightrec.clear()
    pool = NodePool(
        [("127.0.0.1", p) for p in ports],
        policy="round_robin",
        breaker_kwargs=dict(
            failure_threshold=1, backoff_s=0.5, jitter_frac=0.1
        ),
    )
    client = PooledArraysClient(pool)
    victim_port = ports[2]
    victim_addr = f"127.0.0.1:{victim_port}"
    try:
        wait_nodes_up(ports, timeout=60)

        n = 240
        reqs = [(np.array([float(i), 5.0], np.float32),) for i in range(n)]

        async def run_with_kill():
            # Fire the kill while the spread window is mid-flight:
            # every replica owns an ~80-request shard at ~5 ms/call,
            # so 0.15 s in, the victim's shard is far from drained.
            loop = asyncio.get_running_loop()
            loop.call_later(
                0.15, lambda: procs[2].kill()  # SIGKILL, no shutdown
            )
            return await asyncio.wait_for(
                client.evaluate_many_async(reqs, window=8, batch=False),
                timeout=120,
            )

        results = asyncio.run(run_with_kill())
        procs[2].join(timeout=30)
        assert procs[2].exitcode == -signal.SIGKILL

        # -- every request got exactly one, correct reply (positional
        # assignment makes duplicates structurally impossible; holes
        # would be None; correlation is uuid-checked per transport).
        assert len(results) == n
        for i, out in enumerate(results):
            assert out is not None, f"request {i} never got a reply"
            np.testing.assert_allclose(
                float(np.asarray(out[0])), _expected(i), rtol=1e-6
            )

        # -- the breaker tripped on the killed replica, and the
        # failover landed in the flight record.
        victim = pool.replica_at("127.0.0.1", victim_port)
        assert victim.breaker.state == "open"
        events = flightrec.events()
        failovers = [
            e for e in events
            if e["kind"] == "pool.failover"
            and e.get("replica") == victim_addr
        ]
        assert failovers, "no pool.failover event for the killed replica"
        assert any(e.get("requeued", 0) > 0 for e in failovers), (
            "the failover should have re-queued an un-replied tail"
        )
        assert any(
            e["kind"] == "pool.breaker_open"
            and e.get("replica") == victim_addr
            for e in events
        )

        # -- the failed-over call's trace shows BOTH replicas' spans:
        # pool.window children for the victim and for survivors.
        traces = telemetry.recent_traces()
        root = next(
            t for t in reversed(traces)
            if t["name"] == "pool.evaluate_many"
        )

        def windows(tree, out):
            for child in tree.get("children", []):
                if child["name"] == "pool.window":
                    out.append(child["attrs"]["replica"])
                windows(child, out)
            return out

        replicas_in_trace = set(windows(root, []))
        assert victim_addr in replicas_in_trace
        assert len(replicas_in_trace) >= 2, (
            f"expected spans from the victim AND a survivor, got "
            f"{replicas_in_trace}"
        )

        # -- half-open recovery: a replacement node on the SAME port;
        # once the backoff expires the breaker reads half_open, and the
        # single admitted probe call closes it again.
        procs[2] = spawn_node_procs(
            _serve_slow_node, [(victim_port, COMPUTE_DELAY_S)]
        )[0]
        wait_nodes_up([victim_port], timeout=60)
        deadline = time.time() + 10
        while victim.breaker.state == "open":
            assert time.time() < deadline, "backoff never expired"
            time.sleep(0.05)
        assert victim.breaker.state == "half_open"

        async def drive_until_closed():
            deadline = time.time() + 30
            while victim.breaker.state != "closed":
                assert time.time() < deadline, (
                    "half-open probe never closed the breaker"
                )
                out = await client.evaluate_async(
                    np.array([1.0, 5.0], np.float32)
                )
                np.testing.assert_allclose(
                    float(np.asarray(out[0])), -8.0
                )

        asyncio.run(drive_until_closed())
        assert any(
            e["kind"] == "pool.breaker_closed"
            and e.get("replica") == victim_addr
            for e in flightrec.events()
        )
    finally:
        for p in procs:
            if p.is_alive():
                p.terminate()
        for p in procs:
            p.join(timeout=10)
