"""Pure bridge cores (bridge/core.py) — executed WITHOUT pytensor.

pytensor/pymc cannot be installed here, so tests/test_bridge.py and
test_fusion.py skip; this file covers everything those modules'
skipped code DELEGATES to: perform-layer coercion contracts, the
grad-dtype policy, fusion replacement planning, and the JAX-dispatch
composition (run against real jax functions, jitted)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu.bridge.core import (
    coerce_logp,
    coerce_logp_grads,
    coerce_outputs,
    fused_jax_callable,
    grad_output_dtype,
    member_jax_callable,
    plan_fusion,
)


class TestPerformContracts:
    def test_coerce_outputs_casts(self):
        out = coerce_outputs([np.float64(1.5), [1, 2]], ["float32", "int64"])
        assert out[0].dtype == np.float32 and out[1].dtype == np.int64

    def test_coerce_outputs_arity(self):
        with pytest.raises(ValueError, match="returned 1 outputs, expected 2"):
            coerce_outputs([np.zeros(2)], ["float32", "float32"])

    def test_coerce_logp_scalar_contract(self):
        assert coerce_logp(2.5, "float64").shape == ()
        with pytest.raises(ValueError, match="scalar"):
            coerce_logp(np.zeros(3), "float64")

    def test_coerce_logp_grads(self):
        logp, grads = coerce_logp_grads(
            1.0, [np.ones(2), 3.0], "float32", ["float32", "float64"]
        )
        assert logp.dtype == np.float32
        assert grads[1].dtype == np.float64
        with pytest.raises(ValueError, match="1 grads for 2"):
            coerce_logp_grads(1.0, [np.ones(2)], "f", ["float32", "float32"])

    def test_grad_dtype_policy(self):
        """Int/bool inputs upcast to floatX (the reference types the
        grad ``i.type()`` unconditionally — silent truncation,
        reference: wrapper_ops.py:97-105); floats keep their dtype."""
        assert grad_output_dtype("int64", "float32") == "float32"
        assert grad_output_dtype("uint8", "float64") == "float64"
        assert grad_output_dtype("bool", "float32") == "float32"
        assert grad_output_dtype("float32", "float64") == "float32"

    def test_int_grad_truncation_prevented_end_to_end(self):
        """The actual trap: a 0.7 gradient through an int-typed output
        would cast to 0.  The policy + coercion together keep it 0.7."""
        dt = grad_output_dtype("int64", "float32")
        _, grads = coerce_logp_grads(0.0, [0.7], "float32", [dt])
        assert float(grads[0]) == pytest.approx(0.7)


class _Node:
    """Minimal stand-in for a pytensor Apply in planning tests."""

    def __init__(self, op, inputs, outputs):
        self.op, self.inputs, self.outputs = op, inputs, outputs


class TestPlanFusion:
    def test_plan_shapes_and_order(self):
        a = _Node("opA", ["x", "y"], ["a0"])
        b = _Node("opB", ["z"], ["b0", "b1"])
        plan = plan_fusion(
            [a, b],
            op_of=lambda n: n.op,
            inputs_of=lambda n: n.inputs,
            outputs_of=lambda n: n.outputs,
        )
        assert plan["members"] == ["opA", "opB"]
        assert plan["in_counts"] == [2, 1]
        assert plan["out_counts"] == [1, 2]
        assert plan["all_inputs"] == ["x", "y", "z"]
        assert plan["replacements"] == [("a0", 0), ("b0", 1), ("b1", 2)]


class TestJaxDispatch:
    def test_member_logp_grad_flattens(self):
        fn = member_jax_callable(
            "logp_grad", lambda x: (jnp.sum(x), [2.0 * x])
        )
        out = jax.jit(fn)(jnp.arange(3.0))
        assert len(out) == 2
        np.testing.assert_allclose(out[1], [0.0, 2.0, 4.0])

    def test_member_logp_passthrough(self):
        fn = member_jax_callable("logp", lambda x: -jnp.sum(x**2))
        assert float(jax.jit(fn)(jnp.ones(2))) == -2.0

    def test_member_arrays_tuples(self):
        fn = member_jax_callable("arrays", lambda x: [x + 1, x - 1])
        out = jax.jit(fn)(jnp.zeros(2))
        assert isinstance(out, tuple) and len(out) == 2

    def test_missing_jax_fn_raises(self):
        with pytest.raises(NotImplementedError, match="jax_fn"):
            member_jax_callable("logp", None)

    def test_unknown_kind_raises(self):
        with pytest.raises(ValueError, match="unknown member kind"):
            member_jax_callable("weird", lambda x: x)

    def test_fused_inlines_in_order(self):
        m1 = member_jax_callable(
            "logp_grad", lambda x: (jnp.sum(x), [jnp.ones_like(x)])
        )
        m2 = member_jax_callable("logp", lambda a, b: a * b)
        m3 = member_jax_callable("arrays", lambda x: [x * 10])
        fused = fused_jax_callable([m1, m2, m3], [1, 2, 1])
        out = jax.jit(fused)(
            jnp.arange(2.0), jnp.float32(3.0), jnp.float32(4.0),
            jnp.float32(5.0),
        )
        # m1 -> (sum, grad), m2 -> scalar, m3 -> (x*10,): 4 outputs flat
        assert len(out) == 4
        assert float(out[0]) == 1.0
        assert float(out[2]) == 12.0
        assert float(out[3]) == 50.0

    def test_fused_arity_validated(self):
        fused = fused_jax_callable([lambda x: (x,)], [1])
        with pytest.raises(ValueError, match="members consume 1"):
            fused(1.0, 2.0)
        with pytest.raises(ValueError, match="in_counts"):
            fused_jax_callable([lambda x: (x,)], [1, 2])
