"""Sampler correctness on analytically known posteriors.

Pattern from the reference: end-to-end sampling with posterior-accuracy
assertions under fixed seeds (reference: test_wrapper_ops.py:105-117
asserts posterior median slope = 2 +/- 0.1).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu.samplers import find_map, sample
from pytensor_federated_tpu.samplers.hmc import hmc_init, hmc_step
from pytensor_federated_tpu.samplers.nuts import nuts_step
from pytensor_federated_tpu.samplers.util import AdaptSchedule


def gaussian_logp(mu, sigma):
    def logp(params):
        z = (params["x"] - mu) / sigma
        return jnp.sum(-0.5 * z**2)

    return logp


def test_adapt_schedule_covers_warmup():
    s = AdaptSchedule.make(500)
    assert s.update_mass.shape == (500,)
    assert int(jnp.sum(s.update_mass)) >= 2
    # mass updates only inside slow windows
    assert bool(jnp.all(~s.update_mass | s.in_slow))


def test_nuts_step_moves_and_conserves():
    lg = jax.value_and_grad(lambda x: -0.5 * jnp.sum(x**2))
    state = hmc_init(lg, jnp.array([2.0, -1.5]))
    key = jax.random.PRNGKey(0)
    inv_mass = jnp.ones(2)
    new, info = jax.jit(
        lambda s, k: nuts_step(lg, s, k, step_size=0.3, inv_mass=inv_mass)
    )(state, key)
    assert new.x.shape == (2,)
    assert not bool(info.diverging)
    assert float(info.accept_prob) > 0.5
    assert int(info.num_leaves) >= 1


def test_nuts_detects_divergence():
    # A pathologically sharp density with a huge step size must diverge.
    lg = jax.value_and_grad(lambda x: -0.5 * jnp.sum((x * 100.0) ** 2))
    state = hmc_init(lg, jnp.array([1.0]))
    _, info = nuts_step(
        lg, state, jax.random.PRNGKey(1), step_size=10.0, inv_mass=jnp.ones(1)
    )
    assert bool(info.diverging)


def test_hmc_step_runs():
    lg = jax.value_and_grad(lambda x: -0.5 * jnp.sum(x**2))
    state = hmc_init(lg, jnp.array([1.0, 1.0]))
    new, info = hmc_step(
        lg,
        state,
        jax.random.PRNGKey(0),
        step_size=0.2,
        inv_mass=jnp.ones(2),
        num_steps=8,
    )
    assert float(info.accept_prob) > 0.3


@pytest.mark.parametrize("kernel", ["nuts", "hmc", "metropolis"])
def test_sample_recovers_gaussian(kernel):
    """Posterior mean/sd of N(3, 2) target recovered by every kernel."""
    mu, sigma = 3.0, 2.0
    logp = gaussian_logp(mu, sigma)
    init = {"x": jnp.zeros(3)}
    # RWM mixes much slower than gradient kernels: give it more draws.
    n = 3000 if kernel == "metropolis" else 600
    res = sample(
        logp,
        init,
        key=jax.random.PRNGKey(42),
        num_warmup=400,
        num_samples=n,
        num_chains=2,
        kernel=kernel,
    )
    draws = np.asarray(res.samples["x"])  # (chains, draws, 3)
    assert draws.shape == (2, n, 3)
    np.testing.assert_allclose(draws.mean(axis=(0, 1)), mu, atol=0.35)
    np.testing.assert_allclose(draws.std(axis=(0, 1)), sigma, rtol=0.25)


def test_sample_correlated_gaussian_nuts():
    """NUTS handles correlation that would cripple Metropolis."""
    cov = jnp.array([[1.0, 0.9], [0.9, 1.0]])
    prec = jnp.linalg.inv(cov)

    def logp(p):
        return -0.5 * p["z"] @ prec @ p["z"]

    res = sample(
        logp,
        {"z": jnp.zeros(2)},
        key=jax.random.PRNGKey(0),
        num_warmup=500,
        num_samples=1000,
        num_chains=2,
        kernel="nuts",
    )
    z = np.asarray(res.samples["z"]).reshape(-1, 2)
    emp_cov = np.cov(z.T)
    np.testing.assert_allclose(emp_cov, cov, atol=0.25)
    assert np.asarray(res.stats["diverging"]).mean() < 0.05


def test_sample_with_supplied_logp_and_grad():
    """Fused value+grad path (FederatedLogp.logp_and_grad plug-in)."""

    def logp(p):
        return -0.5 * jnp.sum((p["x"] - 1.0) ** 2)

    def lg(p):
        return logp(p), {"x": -(p["x"] - 1.0)}

    res = sample(
        logp,
        {"x": jnp.zeros(2)},
        key=jax.random.PRNGKey(7),
        num_warmup=300,
        num_samples=400,
        num_chains=2,
        logp_and_grad_fn=lg,
    )
    draws = np.asarray(res.samples["x"])
    np.testing.assert_allclose(draws.mean(axis=(0, 1)), 1.0, atol=0.3)


def test_find_map():
    def logp(p):
        return -jnp.sum((p["a"] - 2.0) ** 2) - jnp.sum((p["b"] + 1.0) ** 2)

    est = find_map(logp, {"a": jnp.zeros(2), "b": jnp.zeros(())}, num_steps=800)
    np.testing.assert_allclose(est["a"], 2.0, atol=0.05)
    np.testing.assert_allclose(est["b"], -1.0, atol=0.05)


def test_sample_chain_sharding_over_mesh(devices8):
    """chain_sharding partitions the vmapped chains across devices;
    posterior contract unchanged and the draws stay sharded."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytensor_federated_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"chains": 8}, devices=devices8)

    def logp(p):
        return -0.5 * jnp.sum((p["x"] - 2.0) ** 2)

    res = sample(
        logp,
        {"x": jnp.zeros(2)},
        key=jax.random.PRNGKey(5),
        num_warmup=150,
        num_samples=150,
        num_chains=8,
        chain_sharding=NamedSharding(mesh, P("chains")),
    )
    draws = np.asarray(res.samples["x"])
    assert draws.shape == (8, 150, 2)
    np.testing.assert_allclose(draws.mean(axis=(0, 1)), 2.0, atol=0.2)
    assert not res.samples["x"].sharding.is_fully_replicated

    import pytest

    with pytest.raises(ValueError, match="not shardable"):
        sample(
            logp,
            {"x": jnp.zeros(2)},
            key=jax.random.PRNGKey(5),
            num_warmup=5,
            num_samples=5,
            num_chains=6,
            chain_sharding=NamedSharding(mesh, P("chains")),
        )
