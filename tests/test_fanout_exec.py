"""Direct tests of the fused-apply scheduling core (no pytensor).

VERDICT r2 item 5a: the parts of ``ParallelFederatedOp.perform`` most
likely to be wrong — threading, error propagation, storage slicing —
must be testable without pytensor.  ``bridge/fanout_exec.py`` is that
extraction; these tests pin its contracts (which mirror the reference's
``ParallelAsyncOp.perform``, reference: op_async.py:107-132, and its
wall-clock overlap proof, reference: test_op_async.py:75-106).
"""

import gc
import threading
import time

import pytest

from pytensor_federated_tpu.fanout_exec import (
    MemberExecutorPool,
    member_spans,
    run_members,
)


def _writer(value):
    def fn(sub_in, sub_storage):
        for j, cell in enumerate(sub_storage):
            cell[0] = (value, j, list(sub_in))

    return fn


def _storage(n):
    return [[None] for _ in range(n)]


def test_member_spans():
    assert member_spans([2, 1, 3]) == [(0, 2), (2, 3), (3, 6)]
    assert member_spans([]) == []


def test_slicing_routes_inputs_and_storage():
    # members with ragged in/out arity: slicing must route member i's
    # inputs and land its writes in exactly its own storage cells.
    pool = MemberExecutorPool(3)
    inputs = ["a", "b", "c", "d"]  # member arities 2, 1, 1
    storage = _storage(4)  # member out arities 1, 2, 1
    run_members(
        [_writer("m0"), _writer("m1"), _writer("m2")],
        [2, 1, 1],
        [1, 2, 1],
        inputs,
        storage,
        pool,
    )
    assert storage[0][0] == ("m0", 0, ["a", "b"])
    assert storage[1][0] == ("m1", 0, ["c"])
    assert storage[2][0] == ("m1", 1, ["c"])
    assert storage[3][0] == ("m2", 0, ["d"])


def test_arity_mismatches_raise():
    pool = MemberExecutorPool(1)
    with pytest.raises(ValueError, match="arity mismatch"):
        run_members([_writer(0)], [1, 1], [1], ["x"], _storage(1), pool)
    with pytest.raises(ValueError, match="consume"):
        run_members([_writer(0)], [2], [1], ["x"], _storage(1), pool)
    with pytest.raises(ValueError, match="storage has"):
        run_members([_writer(0)], [1], [2], ["x"], _storage(1), pool)


def test_members_overlap_not_sum():
    # Two 0.3 s members must take ~max not ~sum: the latency-hiding
    # contract the reference proves at test_op_async.py:98-105.
    pool = MemberExecutorPool(2)

    def sleeper(sub_in, sub_storage):
        time.sleep(0.3)
        sub_storage[0][0] = "done"

    t0 = time.perf_counter()
    run_members([sleeper, sleeper], [0, 0], [1, 1], [], _storage(2), pool)
    wall = time.perf_counter() - t0
    assert wall < 0.55, wall  # sum would be >= 0.6
    assert wall >= 0.3


def test_member_thread_pinning():
    # member i must see the SAME thread every evaluation (client caches
    # key on thread identity), and distinct members distinct threads.
    pool = MemberExecutorPool(2)
    seen = {0: set(), 1: set()}

    def make(idx):
        def fn(sub_in, sub_storage):
            seen[idx].add(threading.get_ident())
            sub_storage[0][0] = idx

        return fn

    for _ in range(5):
        run_members(
            [make(0), make(1)], [0, 0], [1, 1], [], _storage(2), pool
        )
    assert len(seen[0]) == 1
    assert len(seen[1]) == 1
    assert seen[0] != seen[1]


def test_first_error_raised_after_all_settle():
    # Member 1 fails fast, member 2 fails slow, member 0 is slow+ok: the
    # FIRST (member-order) failure is raised, and every member settled
    # first — no half-set sibling storage.
    pool = MemberExecutorPool(3)
    settled = []

    def ok_slow(sub_in, sub_storage):
        time.sleep(0.25)
        sub_storage[0][0] = "ok"
        settled.append("ok_slow")

    def boom_fast(sub_in, sub_storage):
        settled.append("boom_fast")
        raise RuntimeError("member-1 failure")

    def boom_slow(sub_in, sub_storage):
        time.sleep(0.15)
        settled.append("boom_slow")
        raise ValueError("member-2 failure")

    storage = _storage(3)
    with pytest.raises(RuntimeError, match="member-1 failure"):
        run_members(
            [ok_slow, boom_fast, boom_slow],
            [0, 0, 0],
            [1, 1, 1],
            [],
            storage,
            pool,
        )
    assert sorted(settled) == ["boom_fast", "boom_slow", "ok_slow"]
    assert storage[0][0] == "ok"  # the healthy member's write survived


def test_rebinding_storage_cell_is_loud():
    # The pytensor convention is cell[0] = value; a member REBINDING the
    # cell would silently lose its output through the slice aliasing —
    # the runner must turn that into a loud error.
    pool = MemberExecutorPool(1)

    def rebinder(sub_in, sub_storage):
        sub_storage[0] = ["lost"]

    with pytest.raises(RuntimeError, match="rebound storage cell"):
        run_members([rebinder], [0], [1], [], _storage(1), pool)


def test_pool_finalizer_stops_threads():
    # Round-2 advisor finding: persistent executors leaked threads for
    # the process lifetime.  The pool must shut its threads down when
    # collected (weakref.finalize) and on explicit shutdown().
    pool = MemberExecutorPool(2, name="pft-finalize-test")
    run_members(
        [_writer(0), _writer(1)], [0, 0], [1, 1], [], _storage(2), pool
    )
    assert pool.alive

    def our_threads():
        return [
            t
            for t in threading.enumerate()
            if t.name.startswith("pft-finalize-test")
        ]

    assert len(our_threads()) == 2
    del pool
    gc.collect()
    deadline = time.time() + 5.0
    while our_threads() and time.time() < deadline:
        time.sleep(0.01)
    assert not our_threads()

    # explicit shutdown is idempotent and also stops threads
    pool2 = MemberExecutorPool(1, name="pft-finalize-test")
    pool2.submit(0, lambda: None).result()
    pool2.shutdown()
    pool2.shutdown()
    assert not pool2.alive
    deadline = time.time() + 5.0
    while our_threads() and time.time() < deadline:
        time.sleep(0.01)
    assert not our_threads()


def test_import_gate_only_swallows_third_party_loss():
    # Only a missing THIRD-PARTY dep (pytensor/pymc) may soft-disable
    # the bridge; losing one of our OWN modules (file dropped from a
    # wheel) must stay loud — otherwise a packaging mistake silently
    # stubs out every Op even where pytensor IS installed.
    import subprocess
    import sys

    code = """
import sys, builtins
orig = builtins.__import__
def fake(name, *a, **k):
    if name.endswith('pytensor_ops') or name == 'pytensor':
        raise ModuleNotFoundError(
            "No module named %r" % (RAISE_NAME,), name=RAISE_NAME)
    return orig(name, *a, **k)
builtins.__import__ = fake
try:
    import pytensor_federated_tpu.bridge as b
    print('SOFT', b.HAS_PYTENSOR)
except ModuleNotFoundError as e:
    print('RAISED', e.name)
"""
    def run(raise_name):
        return subprocess.run(
            [sys.executable, "-c",
             f"RAISE_NAME = {raise_name!r}\n" + code],
            capture_output=True,
            text=True,
            check=True,
        ).stdout.strip()

    assert run("pytensor") == "SOFT False"
    own = "pytensor_federated_tpu.bridge.pytensor_ops"
    assert run(own) == f"RAISED {own}"


def test_import_guard_without_pytensor():
    # VERDICT r2 item 5c: the package must import cleanly without
    # pytensor and the bridge must raise a HELPFUL error, not an
    # AttributeError or a deep traceback.  (In an env WITH pytensor the
    # second half is vacuous; the xfail-style gate keeps it honest.)
    import pytensor_federated_tpu  # noqa: F401  (must not raise)
    from pytensor_federated_tpu import bridge

    try:
        import pytensor  # noqa: F401

        has_pt = True
    except ModuleNotFoundError:
        has_pt = False

    assert bridge.HAS_PYTENSOR is has_pt
    if not has_pt:
        with pytest.raises(ImportError, match="pytensor"):
            bridge.FederatedLogpOp
        with pytest.raises(ImportError, match="extra"):
            bridge.ParallelFederatedOp
        with pytest.raises(AttributeError):
            bridge.not_a_real_name


def test_pool_shutdown_before_use_stays_shut():
    # shutdown() before lazy creation must not be a silent no-op that a
    # later submit resurrects (round-3 review): closed means closed.
    pool = MemberExecutorPool(2)
    pool.shutdown()
    with pytest.raises(RuntimeError, match="shut down"):
        pool.submit(0, lambda: 1)


# ---------------------------------------------------------------------------
# ISSUE 4 satellite: member failures route through the replica pool's
# retry/failover policy when a pool is supplied (previously the FIRST
# member error surfaced with no retry at all).
# ---------------------------------------------------------------------------


def _make_node_pool(**breaker_kwargs):
    from pytensor_federated_tpu.routing import NodePool

    return NodePool(
        [("127.0.0.1", 1)],
        member_retries=2,
        breaker_kwargs=dict(failure_threshold=1, **breaker_kwargs),
    )


def test_transient_then_healthy_member_retries_through_pool():
    # Regression (fanout_exec surfaced the first member error without
    # retry): a member that raises ONE transient transport error and
    # then succeeds must not fail the fanout when a pool is supplied.
    from pytensor_federated_tpu.telemetry import flightrec

    flightrec.clear()
    pool = MemberExecutorPool(2)
    attempts = {"n": 0}

    def flaky(sub_in, sub_storage):
        attempts["n"] += 1
        if attempts["n"] == 1:
            raise ConnectionError("replica vanished mid-call")
        sub_storage[0][0] = "recovered"

    storage = _storage(2)
    run_members(
        [flaky, _writer("ok")],
        [0, 0],
        [1, 1],
        [],
        storage,
        pool,
        node_pool=_make_node_pool(),
    )
    assert attempts["n"] == 2
    assert storage[0][0] == "recovered"
    assert storage[1][0] == ("ok", 0, [])
    kinds = [e["kind"] for e in flightrec.events()]
    assert "fanout.member_retry" in kinds


def test_member_retries_exhaust_then_raise():
    pool = MemberExecutorPool(1)
    attempts = {"n": 0}

    def always_down(sub_in, sub_storage):
        attempts["n"] += 1
        raise ConnectionError("still down")

    with pytest.raises(ConnectionError, match="still down"):
        run_members(
            [always_down], [0], [1], [], _storage(1), pool,
            node_pool=_make_node_pool(),
        )
    assert attempts["n"] == 3  # 1 + member_retries


def test_deterministic_member_error_is_not_retried():
    # A compute error is the request's own fault: retrying would
    # re-execute a failure that cannot succeed anywhere.
    pool = MemberExecutorPool(1)
    attempts = {"n": 0}

    def poison(sub_in, sub_storage):
        attempts["n"] += 1
        raise RuntimeError("server error: poison input")

    with pytest.raises(RuntimeError, match="poison"):
        run_members(
            [poison], [0], [1], [], _storage(1), pool,
            node_pool=_make_node_pool(),
        )
    assert attempts["n"] == 1


def test_no_pool_keeps_no_retry_contract():
    pool = MemberExecutorPool(1)
    attempts = {"n": 0}

    def flaky(sub_in, sub_storage):
        attempts["n"] += 1
        raise ConnectionError("transient")

    with pytest.raises(ConnectionError):
        run_members([flaky], [0], [1], [], _storage(1), pool)
    assert attempts["n"] == 1
