"""ISSUE 19: distributed block-partitioned linear algebra.

The contract under test: tile geometry and the block-store protocol
fail LOUDLY (``BlockError`` ⊂ ``WireError``) on any mismatch — never a
silently mis-assembled matrix or a silently wrong factor; the blocked
Cholesky matches ``np.linalg.cholesky`` (f64 at machine precision, f32
at f32-strict tolerance) on the clientless, multi-replica, and
recovery lanes; a replica failure re-ships ONLY the dead replica's
tiles; the fed-lane ops (GEMM / quadratic form / triangular solve)
agree with their dense references eagerly and over a real TCP pool;
and repeated blocked GEMM over shm/ring moves ZERO request payload
bytes once the PR-9 pin cache promotes the panels (satellite 3's
``pftpu_wire_bytes_copied_total`` accounting).
"""

import threading

import numpy as np
import pytest

from pytensor_federated_tpu.linalg import (
    BlockedCholesky,
    BlockedMatmul,
    BlockError,
    BlockLayout,
    LocalBlockClient,
    block_quadratic_form,
    cholesky,
    make_block_store_compute,
    matmul,
    matmul_per_shard,
    quadratic_per_shard,
    triangular_solve,
)
from pytensor_federated_tpu.linalg.blocks import (
    OPCODES,
    decode_op_header,
    encode_op_header,
    pack_coords,
    unpack_coords,
)
from pytensor_federated_tpu.linalg.service import (
    chol_kernel,
    dot_kernel,
    trsm_kernel,
)
from pytensor_federated_tpu.service.npwire import WireError


def _spd(n, dtype=np.float64, seed=0):
    rng = np.random.default_rng(seed)
    m = rng.normal(size=(n, n))
    return (m @ m.T / n + np.eye(n)).astype(dtype)


def _start_tcp(compute):
    from pytensor_federated_tpu.service.tcp import serve_tcp_once

    holder = {}
    ready = threading.Event()
    threading.Thread(
        target=serve_tcp_once,
        args=(compute,),
        kwargs=dict(
            port=0,
            ready_callback=lambda p: (holder.update(p=p), ready.set()),
            concurrent=True,
        ),
        daemon=True,
    ).start()
    assert ready.wait(10)
    return holder["p"]


def _start_shm(compute):
    from pytensor_federated_tpu.service.shm import serve_shm

    holder = {}
    ready = threading.Event()
    threading.Thread(
        target=serve_shm,
        args=(compute,),
        kwargs=dict(
            port=0,
            ready_callback=lambda p: (holder.update(p=p), ready.set()),
        ),
        daemon=True,
    ).start()
    assert ready.wait(10)
    return holder["p"]


def _start_ring(compute):
    from pytensor_federated_tpu.service.ring import serve_ring

    holder = {}
    ready = threading.Event()
    threading.Thread(
        target=serve_ring,
        args=(compute,),
        kwargs=dict(
            port=0,
            ready_callback=lambda p: (holder.update(p=p), ready.set()),
        ),
        daemon=True,
    ).start()
    assert ready.wait(10)
    return holder["p"]


# ---------------------------------------------------------------------------
# wire headers
# ---------------------------------------------------------------------------


class TestHeaders:
    def test_blockerror_is_a_wireerror(self):
        assert issubclass(BlockError, WireError)

    def test_op_header_roundtrip(self):
        hdr = encode_op_header(OPCODES["SYRK_UPDATE"], 3, 7)
        assert hdr.dtype == np.uint8 and hdr.nbytes == 16
        assert decode_op_header(hdr) == (OPCODES["SYRK_UPDATE"], 3, 7)

    def test_unknown_opcode_is_loud_both_ways(self):
        with pytest.raises(BlockError, match="unknown linalg opcode"):
            encode_op_header(99)
        bad = encode_op_header(OPCODES["PUT"]).copy()
        bad[0] = 250
        with pytest.raises(BlockError, match="unknown linalg opcode"):
            decode_op_header(bad)

    def test_reserved_flag_bits_are_loud(self):
        hdr = encode_op_header(OPCODES["GET"]).copy()
        hdr[12] = 1  # flags word
        with pytest.raises(BlockError, match="unknown flag bits"):
            decode_op_header(hdr)

    def test_malformed_op_header_is_loud(self):
        with pytest.raises(BlockError, match="uint8"):
            decode_op_header(np.zeros(16, np.float32))
        with pytest.raises(BlockError, match="uint8"):
            decode_op_header(np.zeros(5, np.uint8))

    def test_tile_header_roundtrip_and_validation(self):
        lay = BlockLayout(10, 10, 4, 4)
        hdr = lay.encode_tile_header(2, 1)
        assert lay.decode_tile_header(hdr) == (2, 1)
        # A header stamped by a DIFFERENT geometry refuses loudly.
        other = BlockLayout(10, 10, 5, 5)
        with pytest.raises(BlockError, match="grid"):
            other.decode_tile_header(hdr)
        # Truncation refuses loudly.
        with pytest.raises(BlockError, match="uint8"):
            lay.decode_tile_header(hdr[:-1])

    def test_tile_header_shape_claim_checked(self):
        lay = BlockLayout(10, 10, 4, 4)
        # Hand-forge a header claiming a full tile at the (2, 2) edge
        # (the real edge tile is 2x2).
        import struct

        from pytensor_federated_tpu.service.wire_registry import (
            LINALG_TILE_STRUCT,
        )

        forged = np.frombuffer(
            struct.pack(LINALG_TILE_STRUCT, 3, 3, 2, 2, 4, 4), dtype=np.uint8
        ).copy()
        with pytest.raises(BlockError, match="claims shape"):
            lay.decode_tile_header(forged)

    def test_coords_roundtrip(self):
        coords = [(0, 0), (2, 1), (3, 3)]
        arr = pack_coords(coords)
        assert arr.dtype == np.int64 and arr.shape == (3, 2)
        assert unpack_coords(arr) == coords
        assert pack_coords([]).shape == (0, 2)
        with pytest.raises(BlockError, match="int64"):
            unpack_coords(np.zeros((2, 2), np.int32))


# ---------------------------------------------------------------------------
# layout geometry
# ---------------------------------------------------------------------------


class TestLayout:
    def test_uneven_edge_tiles_never_padded(self):
        lay = BlockLayout(10, 7, 4, 3)
        assert (lay.grid_rows, lay.grid_cols) == (3, 3)
        assert lay.tile_shape(0, 0) == (4, 3)
        assert lay.tile_shape(2, 2) == (2, 1)
        with pytest.raises(BlockError, match="outside"):
            lay.tile_shape(3, 0)

    def test_bad_layout_params_are_loud(self):
        with pytest.raises(BlockError):
            BlockLayout(0, 4, 1, 1)
        with pytest.raises(BlockError):
            BlockLayout(4, 4, 8, 4)

    def test_for_matrix_clamps_block(self):
        lay = BlockLayout.for_matrix(np.zeros((3, 5)), 64)
        assert (lay.block_rows, lay.block_cols) == (3, 5)
        with pytest.raises(BlockError, match="2-D"):
            BlockLayout.for_matrix(np.zeros(3), 2)

    def test_split_assemble_roundtrip(self):
        a = np.arange(70.0).reshape(10, 7)
        lay = BlockLayout(10, 7, 4, 3)
        tiles = lay.split(a)
        assert all(t.flags["C_CONTIGUOUS"] for t in tiles.values())
        np.testing.assert_array_equal(lay.assemble(tiles), a)

    def test_assemble_missing_and_extra_tiles_are_loud(self):
        a = np.arange(16.0).reshape(4, 4)
        lay = BlockLayout(4, 4, 2, 2)
        tiles = lay.split(a)
        del tiles[(1, 0)]
        with pytest.raises(BlockError, match="missing tiles"):
            lay.assemble(tiles)
        tiles = lay.split(a)
        tiles[(7, 7)] = np.zeros((2, 2))
        with pytest.raises(BlockError, match="unexpected tiles"):
            lay.assemble(tiles)

    def test_assemble_mixed_dtype_and_bad_shape_are_loud(self):
        lay = BlockLayout(4, 4, 2, 2)
        tiles = lay.split(np.zeros((4, 4)))
        tiles[(0, 0)] = tiles[(0, 0)].astype(np.float32)
        with pytest.raises(BlockError, match="mixed dtypes"):
            lay.assemble(tiles)
        tiles = lay.split(np.zeros((4, 4)))
        tiles[(0, 1)] = np.zeros((3, 3))
        with pytest.raises(BlockError, match="shape"):
            lay.assemble(tiles)

    def test_lower_only_assembly(self):
        lay = BlockLayout(4, 4, 2, 2)
        l = np.tril(np.arange(1.0, 17.0).reshape(4, 4))
        tiles = {c: l[lay.tile_slice(*c)].copy() for c in lay.lower_coords()}
        np.testing.assert_array_equal(
            lay.assemble(tiles, lower_only=True), l
        )
        # The full coordinate set is refused under lower_only.
        with pytest.raises(BlockError, match="unexpected tiles"):
            lay.assemble(lay.split(l), lower_only=True)

    def test_row_cyclic_owner_partitions_rows(self):
        lay = BlockLayout(20, 20, 4, 4)  # 5x5 grid
        for n in (1, 2, 3):
            owned = [lay.rows_owned(p, n) for p in range(n)]
            flat = sorted(i for rows in owned for i in rows)
            assert flat == list(range(lay.grid_rows))
            for i, j in lay.lower_coords():
                assert lay.owner(i, j, n) == i % n
        with pytest.raises(BlockError, match="n_replicas"):
            lay.owner(0, 0, 0)


# ---------------------------------------------------------------------------
# the block store protocol
# ---------------------------------------------------------------------------


def _put_request(lay, tiles, step=0):
    coords = sorted(tiles)
    req = [encode_op_header(OPCODES["PUT"], step, len(coords))]
    for c in coords:
        req.append(lay.encode_tile_header(*c))
        req.append(np.ascontiguousarray(tiles[c]))
    return req


class TestBlockStore:
    def test_put_get_stats_reset(self):
        lay = BlockLayout(6, 6, 3, 3)
        a = _spd(6)
        client = LocalBlockClient(lay)
        tiles = {c: a[lay.tile_slice(*c)] for c in lay.lower_coords()}
        (n,) = client.evaluate(*_put_request(lay, tiles))
        assert int(n) == len(tiles)
        got = client.evaluate(
            encode_op_header(OPCODES["GET"]), pack_coords([(1, 0)])
        )
        np.testing.assert_array_equal(got[0], tiles[(1, 0)])
        count, nbytes = client.evaluate(encode_op_header(OPCODES["STATS"]))
        assert int(count) == len(tiles)
        assert int(nbytes) == sum(t.nbytes for t in tiles.values())
        client.evaluate(encode_op_header(OPCODES["RESET"]))
        with pytest.raises(BlockError, match="does not hold"):
            client.evaluate(
                encode_op_header(OPCODES["GET"]), pack_coords([(1, 0)])
            )

    def test_put_count_mismatch_and_duplicate_are_loud(self):
        lay = BlockLayout(4, 4, 2, 2)
        client = LocalBlockClient(lay)
        hdr = lay.encode_tile_header(0, 0)
        tile = np.zeros((2, 2))
        with pytest.raises(BlockError, match="claims 2 tiles"):
            client.evaluate(
                encode_op_header(OPCODES["PUT"], 0, 2), hdr, tile
            )
        with pytest.raises(BlockError, match="twice"):
            client.evaluate(
                encode_op_header(OPCODES["PUT"], 0, 2), hdr, tile, hdr, tile
            )

    def test_gemm_panel(self):
        lay = BlockLayout(4, 4, 2, 2)
        client = LocalBlockClient(lay)
        a = np.arange(6.0).reshape(2, 3)
        b = np.arange(12.0).reshape(3, 4)
        (out,) = client.evaluate(
            encode_op_header(OPCODES["GEMM_PANEL"]), a, b
        )
        np.testing.assert_allclose(out, a @ b)
        with pytest.raises(BlockError, match="do not contract"):
            client.evaluate(encode_op_header(OPCODES["GEMM_PANEL"]), a, a)

    def test_step_guards(self):
        """The applied_step clock: retried updates are idempotent,
        missed updates and mismatched panel steps are loud."""
        lay = BlockLayout(6, 6, 2, 2)  # 3x3 grid, one replica owns all
        a = _spd(6)
        client = LocalBlockClient(lay)
        tiles = {c: a[lay.tile_slice(*c)] for c in lay.lower_coords()}
        client.evaluate(*_put_request(lay, tiles, step=0))

        # CHOL_PANEL at the wrong step refuses before touching state.
        with pytest.raises(BlockError, match="trailing updates applied"):
            client.evaluate(encode_op_header(OPCODES["CHOL_PANEL"], 1))
        # Missing the step-0 update before step 1 is loud too.
        with pytest.raises(BlockError, match="updates applied"):
            client.evaluate(
                encode_op_header(OPCODES["SYRK_UPDATE"], 1, 0),
                np.zeros(0, np.int64),
            )

        reply = client.evaluate(encode_op_header(OPCODES["CHOL_PANEL"], 0))
        l_kk, rows = np.asarray(reply[0]), np.asarray(reply[1])
        assert list(rows) == [1, 2]
        panel = list(reply[2:])
        req = [
            encode_op_header(OPCODES["SYRK_UPDATE"], 0, len(panel)),
            rows,
            *panel,
        ]
        (updated,) = client.evaluate(*req)
        assert int(updated) == 3  # (1,1), (2,1), (2,2)
        # A RETRIED update (reply lost) is an idempotent no-op.
        (sentinel,) = client.evaluate(*req)
        assert int(sentinel) == -1
        # TRSM against the already-updated store refuses the old step.
        with pytest.raises(BlockError, match="trailing updates applied"):
            client.evaluate(
                encode_op_header(OPCODES["TRSM_PANEL"], 0), l_kk
            )

    def test_syrk_missing_panel_row_is_loud(self):
        lay = BlockLayout(6, 6, 2, 2)
        a = _spd(6)
        client = LocalBlockClient(lay)
        tiles = {c: a[lay.tile_slice(*c)] for c in lay.lower_coords()}
        client.evaluate(*_put_request(lay, tiles, step=0))
        reply = client.evaluate(encode_op_header(OPCODES["CHOL_PANEL"], 0))
        # Ship only panel row 1; row 2's stored tiles need row 2 too.
        with pytest.raises(BlockError, match="needs panel rows"):
            client.evaluate(
                encode_op_header(OPCODES["SYRK_UPDATE"], 0, 1),
                np.asarray([1], np.int64),
                np.asarray(reply[2]),
            )

    def test_chol_refuses_non_pd(self):
        lay = BlockLayout(2, 2, 2, 2)
        client = LocalBlockClient(lay)
        bad = np.array([[1.0, 2.0], [2.0, 1.0]])  # indefinite
        client.evaluate(
            *_put_request(lay, {(0, 0): bad}, step=0)
        )
        with pytest.raises(BlockError, match="positive definite"):
            client.evaluate(encode_op_header(OPCODES["CHOL_PANEL"], 0))

    def test_headerless_request_is_loud(self):
        client = LocalBlockClient(BlockLayout(2, 2, 2, 2))
        with pytest.raises(BlockError, match="op header"):
            client.evaluate()


class TestKernels:
    def test_dot_kernel_f64_exact(self):
        rng = np.random.default_rng(1)
        a, b = rng.normal(size=(5, 7)), rng.normal(size=(7, 3))
        np.testing.assert_array_equal(dot_kernel(a, b), a @ b)

    def test_trsm_kernel_inverts_the_panel_solve(self):
        l = np.linalg.cholesky(_spd(4, seed=2))
        a_ik = np.random.default_rng(3).normal(size=(4, 4))
        x = trsm_kernel(a_ik, l)
        np.testing.assert_allclose(x @ l.T, a_ik, atol=1e-12)

    def test_chol_kernel_matches_numpy(self):
        a = _spd(8, seed=4)
        np.testing.assert_allclose(
            chol_kernel(a), np.linalg.cholesky(a), atol=1e-13
        )
        a32 = _spd(8, np.float32, seed=4)
        l32 = chol_kernel(a32)
        assert l32.dtype == np.float32
        np.testing.assert_allclose(
            l32, np.linalg.cholesky(a32.astype(np.float64)), atol=1e-5
        )


# ---------------------------------------------------------------------------
# blocked Cholesky: equality, distribution accounting, recovery
# ---------------------------------------------------------------------------


class TestCholesky:
    def test_f64_matches_numpy_with_uneven_edge(self):
        a = _spd(10, seed=5)
        l = cholesky(a, block=4)  # 3x3 grid, 2x2 edge tiles
        np.testing.assert_allclose(l, np.linalg.cholesky(a), atol=1e-12)

    def test_f32_matches_at_strict_tolerance(self):
        a = _spd(24, np.float32, seed=6)
        l = cholesky(a, block=8)
        assert l.dtype == np.float32
        ref = np.linalg.cholesky(a.astype(np.float64))
        np.testing.assert_allclose(l, ref, rtol=1e-4, atol=1e-5)

    def test_multi_replica_matches_and_ships_each_tile_once(self):
        a = _spd(12, seed=7)
        lay = BlockLayout(12, 12, 3, 3)
        clients = [LocalBlockClient(lay) for _ in range(3)]
        bc = BlockedCholesky(lay, clients)
        l = bc.factor(a)
        np.testing.assert_allclose(l, np.linalg.cholesky(a), atol=1e-12)
        assert sorted(c for _, c in bc.shipped) == sorted(lay.lower_coords())
        assert bc.reshipped == [] and bc.restores == 0
        # Placement is row-cyclic: every shipped coord went to its owner.
        for p, (i, j) in bc.shipped:
            assert p == lay.owner(i, j, 3)

    def test_single_vs_multi_replica_identical(self):
        a = _spd(12, seed=8)
        lay = BlockLayout(12, 12, 4, 4)
        l1 = BlockedCholesky(lay, [LocalBlockClient(lay)]).factor(a)
        l3 = BlockedCholesky(
            lay, [LocalBlockClient(lay) for _ in range(3)]
        ).factor(a)
        np.testing.assert_array_equal(l1, l3)

    def test_geometry_refusals(self):
        with pytest.raises(BlockError, match="square"):
            cholesky(np.zeros((4, 6)))
        with pytest.raises(BlockError, match="square"):
            BlockedCholesky(BlockLayout(8, 8, 4, 2))
        lay = BlockLayout(8, 8, 4, 4)
        with pytest.raises(BlockError, match="does not match layout"):
            BlockedCholesky(lay).factor(np.eye(6))
        with pytest.raises(BlockError):
            BlockedCholesky(lay, [])

    def test_wrong_geometry_store_is_loud_not_retried(self):
        """A deterministic in-band refusal (layout disagreement) must
        propagate — retrying it would re-send the same wrong request."""
        lay = BlockLayout(8, 8, 4, 4)
        other = LocalBlockClient(BlockLayout(8, 8, 2, 2))
        bc = BlockedCholesky(lay, [other])
        with pytest.raises(BlockError, match="grid"):
            bc.factor(_spd(8))
        assert bc.restores == 0


class _DyingClient:
    """A block-store replica that dies with a transient error at a
    chosen evaluate() call and stays dead until `reconnect` replaces
    it.  ``after=True`` applies the op first (the reply-lost case)."""

    def __init__(self, layout, die_at, after=False):
        self._inner = LocalBlockClient(layout)
        self.die_at = die_at
        self.after = after
        self.calls = 0
        self.dead = False

    def evaluate(self, *arrays):
        if self.dead:
            raise ConnectionError("replica down")
        self.calls += 1
        if self.calls == self.die_at:
            self.dead = True
            if self.after:
                self._inner.evaluate(*arrays)  # applied, reply lost
            raise ConnectionError("replica killed")
        return self._inner.evaluate(*arrays)

    def close(self):
        pass


class TestRecovery:
    def _run(self, die_at, after):
        a = _spd(15, seed=9)
        lay = BlockLayout(15, 15, 3, 3)  # 5x5 grid
        victim = _DyingClient(lay, die_at, after)
        clients = [LocalBlockClient(lay), victim]
        bc = BlockedCholesky(
            lay, clients, reconnect=lambda p: LocalBlockClient(lay)
        )
        l = bc.factor(a)
        np.testing.assert_allclose(l, np.linalg.cholesky(a), atol=1e-12)
        return lay, bc

    def test_mid_factorization_death_recovers_locally(self):
        # Victim (replica 1, rows {1, 3}) dies at its CHOL_PANEL(1).
        lay, bc = self._run(die_at=4, after=False)
        assert bc.restores == 1
        assert bc.reshipped, "recovery must re-ship the victim's tiles"
        victim_rows = set(lay.rows_owned(1, 2))
        for p, (i, j) in bc.reshipped:
            assert p == 1, "only the dead replica re-ships"
            assert i in victim_rows
            assert j >= 1, "finalized columns never re-ship"
        # Healthy replicas shipped exactly their initial distribution.
        assert all(p == 1 for p, _ in bc.reshipped)

    def test_reply_lost_after_apply_recovers(self):
        # The op applied node-side but the reply was lost: the restore
        # overwrites the trailing state at the retry step, so the
        # re-applied update is correct (not double-subtracted).
        _, bc = self._run(die_at=3, after=True)
        assert bc.restores >= 1

    def test_unreachable_reconnect_is_a_bounded_loud_failure(self):
        a = _spd(6, seed=10)
        lay = BlockLayout(6, 6, 3, 3)
        dead = _DyingClient(lay, die_at=1)

        def reconnect(p):
            raise ConnectionError("still down")

        bc = BlockedCholesky(
            lay, [dead], reconnect=reconnect, reconnect_timeout_s=0.5
        )
        with pytest.raises(BlockError, match="could not reconnect"):
            bc.factor(a)


class _ResendingClient:
    """Transparent-retry twin of the transport clients: every panel op
    is delivered TWICE (the reply-lost + reconnect + re-send path the
    TCP client's ``retries=2`` takes), and the caller sees only the
    second reply.  Exactly the duplication the node's replay cache must
    absorb — without it the second delivery re-solves solved tiles in
    place and the factor is silently wrong."""

    def __init__(self, layout):
        self._inner = LocalBlockClient(layout)
        self.duplicated = 0

    def evaluate(self, *arrays):
        opcode, _, _ = decode_op_header(np.asarray(arrays[0]))
        if opcode in (OPCODES["CHOL_PANEL"], OPCODES["TRSM_PANEL"]):
            self._inner.evaluate(*arrays)  # delivered; reply "lost"
            self.duplicated += 1
        return self._inner.evaluate(*arrays)

    def close(self):
        pass


class _ColdRestartClient:
    """A replica that is silently REPLACED by a cold restart at call
    ``restart_at`` — no transport error ever reaches the driver (the
    transparent-reconnect case); the next panel op bounces off the cold
    store's state guards in-band instead."""

    def __init__(self, layout, restart_at):
        self.layout = layout
        self._inner = LocalBlockClient(layout)
        self.restart_at = restart_at
        self.calls = 0

    def evaluate(self, *arrays):
        self.calls += 1
        if self.calls == self.restart_at:
            self._inner = LocalBlockClient(self.layout)
        return self._inner.evaluate(*arrays)

    def close(self):
        pass


class TestResendIdempotence:
    """The chaos lane's round-19 findings: panel ops must be
    exactly-once under transparent client re-sends, and an in-band
    cold-store refusal must heal like a transport loss."""

    def test_chol_panel_replay_returns_cached_reply(self):
        lay = BlockLayout(6, 6, 3, 3)
        a = _spd(6)
        client = LocalBlockClient(lay)
        tiles = {c: a[lay.tile_slice(*c)] for c in lay.lower_coords()}
        client.evaluate(*_put_request(lay, tiles))
        first = client.evaluate(encode_op_header(OPCODES["CHOL_PANEL"], 0))
        replay = client.evaluate(encode_op_header(OPCODES["CHOL_PANEL"], 0))
        assert len(first) == len(replay)
        for x, y in zip(first, replay):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    def test_trsm_panel_replay_returns_cached_reply(self):
        lay = BlockLayout(6, 6, 3, 3)
        a = _spd(6, seed=3)
        client = LocalBlockClient(lay)
        tiles = {c: a[lay.tile_slice(*c)] for c in lay.lower_coords()}
        client.evaluate(*_put_request(lay, tiles))
        l_kk = np.linalg.cholesky(tiles[(0, 0)])
        first = client.evaluate(
            encode_op_header(OPCODES["TRSM_PANEL"], 0), l_kk
        )
        replay = client.evaluate(
            encode_op_header(OPCODES["TRSM_PANEL"], 0), l_kk
        )
        for x, y in zip(first, replay):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    def test_put_invalidates_the_replay_cache(self):
        # A restore replaces the tiles; a replay from before the
        # restore must recompute, not resurrect the stale reply.
        lay = BlockLayout(3, 3, 3, 3)
        a = _spd(3, seed=4)
        client = LocalBlockClient(lay)
        client.evaluate(*_put_request(lay, {(0, 0): a}))
        stale = client.evaluate(encode_op_header(OPCODES["CHOL_PANEL"], 0))
        a2 = a + np.eye(3)
        client.evaluate(*_put_request(lay, {(0, 0): a2}))
        fresh = client.evaluate(encode_op_header(OPCODES["CHOL_PANEL"], 0))
        assert not np.allclose(np.asarray(stale[0]), np.asarray(fresh[0]))
        np.testing.assert_allclose(
            np.asarray(fresh[0]), np.linalg.cholesky(a2), atol=1e-12
        )

    def test_factor_exact_under_transparent_resends(self):
        a = _spd(15, seed=11)
        lay = BlockLayout(15, 15, 3, 3)
        clients = [_ResendingClient(lay), _ResendingClient(lay)]
        bc = BlockedCholesky(lay, clients)
        l = bc.factor(a)
        assert clients[0].duplicated + clients[1].duplicated > 0
        np.testing.assert_allclose(l, np.linalg.cholesky(a), atol=1e-12)
        assert bc.restores == 0

    def test_cold_restart_without_transport_error_heals(self):
        # The respawned-behind-a-reconnecting-client case: the driver
        # must classify the in-band state refusal as restore-needed.
        a = _spd(15, seed=12)
        lay = BlockLayout(15, 15, 3, 3)
        victim = _ColdRestartClient(lay, restart_at=4)
        clients = [LocalBlockClient(lay), victim]
        bc = BlockedCholesky(lay, clients, reconnect=lambda p: victim)
        l = bc.factor(a)
        np.testing.assert_allclose(l, np.linalg.cholesky(a), atol=1e-12)
        assert bc.restores >= 1
        assert all(p == 1 for p, _ in bc.reshipped)

    def test_geometry_refusals_never_classify_as_restorable(self):
        from pytensor_federated_tpu.linalg.service import is_restore_needed

        assert is_restore_needed(
            BlockError("tile (1, 1) this store does not hold — a "
                       "restarted replica must be restored with PUT first")
        )
        assert is_restore_needed(
            RuntimeError("CHOL_PANEL step 2 but this store has 0 "
                         "trailing updates applied — the driver must "
                         "restore before retrying")
        )
        assert not is_restore_needed(
            BlockError("tile header claims grid 4x4 but this layout is 2x2")
        )
        assert not is_restore_needed(
            BlockError("diagonal tile is not positive definite: boom")
        )


# ---------------------------------------------------------------------------
# fed-lane ops
# ---------------------------------------------------------------------------


class TestFedOps:
    def test_matmul_eager_with_k_padding(self):
        rng = np.random.default_rng(11)
        a = rng.normal(size=(9, 13)).astype(np.float32)
        b = rng.normal(size=(13, 5)).astype(np.float32)
        out = np.asarray(matmul(a, b, n_shards=4))
        np.testing.assert_allclose(
            out, a.astype(np.float64) @ b, rtol=1e-5, atol=1e-6
        )

    def test_matmul_refusals(self):
        with pytest.raises(BlockError, match="do not contract"):
            matmul(np.zeros((2, 3)), np.zeros((4, 2)), n_shards=2)
        with pytest.raises(BlockError, match="n_shards"):
            matmul(np.zeros((2, 3)), np.zeros((3, 2)), n_shards=0)

    def test_matmul_over_tcp_pool(self):
        from pytensor_federated_tpu.fed.placements import (
            PoolPlacement,
            make_node_compute,
        )
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        port = _start_tcp(
            make_node_compute(matmul_per_shard(), grads=False)
        )
        client = TcpArraysClient("127.0.0.1", port)
        try:
            rng = np.random.default_rng(12)
            a = rng.normal(size=(8, 16)).astype(np.float32)
            b = rng.normal(size=(16, 6)).astype(np.float32)
            out = np.asarray(
                matmul(
                    a, b, n_shards=4,
                    placement=PoolPlacement(client, window=4),
                )
            )
            np.testing.assert_allclose(
                out, a.astype(np.float64) @ b, rtol=1e-4, atol=1e-5
            )
        finally:
            client.close()

    def test_quadratic_form_eager(self):
        rng = np.random.default_rng(13)
        a = _spd(11, np.float32, seed=13)
        x = rng.normal(size=11).astype(np.float32)
        out = float(block_quadratic_form(a, x, n_shards=3))
        ref = float(x.astype(np.float64) @ a.astype(np.float64) @ x)
        np.testing.assert_allclose(out, ref, rtol=1e-4)

    def test_quadratic_form_over_reduced_tcp_window(self):
        """The block-row round lowers through PoolPlacement(reduce=True)
        — the PR-13 reduce window — and still matches the dense value."""
        from pytensor_federated_tpu.fed.placements import (
            PoolPlacement,
            make_node_compute,
        )
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        per_shard = quadratic_per_shard()

        def node_fn(x, panel, x_rows):
            return per_shard(x, (panel, x_rows))

        port = _start_tcp(make_node_compute(node_fn))
        client = TcpArraysClient("127.0.0.1", port)
        try:
            rng = np.random.default_rng(14)
            a = _spd(12, np.float32, seed=14)
            x = rng.normal(size=12).astype(np.float32)
            out = float(
                block_quadratic_form(
                    a, x, n_shards=4,
                    placement=PoolPlacement(client, window=4, reduce=True),
                )
            )
            ref = float(x.astype(np.float64) @ a.astype(np.float64) @ x)
            np.testing.assert_allclose(out, ref, rtol=1e-4)
        finally:
            client.close()

    def test_quadratic_refusals(self):
        with pytest.raises(BlockError, match="do not contract"):
            block_quadratic_form(np.zeros((3, 3)), np.zeros(4), n_shards=2)


class TestTriangularSolve:
    def test_forward_and_backward_f64(self):
        l = np.linalg.cholesky(_spd(13, seed=15))
        rng = np.random.default_rng(15)
        b = rng.normal(size=13)
        x = triangular_solve(l, b, block=4)
        np.testing.assert_allclose(l @ x, b, atol=1e-11)
        xt = triangular_solve(l, b, block=4, trans=True)
        np.testing.assert_allclose(l.T @ xt, b, atol=1e-11)

    def test_matrix_rhs(self):
        l = np.linalg.cholesky(_spd(8, seed=16))
        b = np.random.default_rng(16).normal(size=(8, 3))
        x = triangular_solve(l, b, block=3)
        np.testing.assert_allclose(l @ x, b, atol=1e-11)

    def test_refusals(self):
        with pytest.raises(BlockError, match="square"):
            triangular_solve(np.zeros((3, 4)), np.zeros(3))
        with pytest.raises(BlockError, match="rows"):
            triangular_solve(np.eye(3), np.zeros(4))

    def test_row_update_over_tcp_pool(self):
        from pytensor_federated_tpu.fed.placements import (
            PoolPlacement,
            make_node_compute,
        )
        from pytensor_federated_tpu.linalg.ops import (
            triangular_update_per_shard,
        )
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        port = _start_tcp(
            make_node_compute(triangular_update_per_shard(), grads=False)
        )
        client = TcpArraysClient("127.0.0.1", port)
        try:
            l = np.linalg.cholesky(_spd(12, np.float32, seed=17))
            b = np.random.default_rng(17).normal(size=12).astype(np.float32)
            x = triangular_solve(
                l.astype(np.float32), b, block=4,
                placement=PoolPlacement(client, window=4), n_shards=2,
            )
            ref = np.linalg.solve(
                np.tril(l).astype(np.float64), b.astype(np.float64)
            )
            np.testing.assert_allclose(x, ref, rtol=1e-3, atol=1e-4)
        finally:
            client.close()


# ---------------------------------------------------------------------------
# satellite 3: pin-cache reuse accounting (zero re-ship on shm + ring)
# ---------------------------------------------------------------------------


def _arena_write_bytes():
    from pytensor_federated_tpu.service.npwire import WIRE_BYTES_COPIED

    return WIRE_BYTES_COPIED.labels(lane="shm", stage="arena_write").value


class TestPinAccounting:
    """Repeated blocked GEMM over a pinned lane must stop moving the
    panels: after the PR-9 pin cache promotes the stable request
    objects (second sighting), per-iteration ``pftpu_wire_bytes_
    copied_total{lane=shm, stage=arena_write}`` growth is flat at the
    REPLY payload — the request side copies zero bytes.  Runs on both
    arena transports (shm doorbell and the r18 ring)."""

    def _measure(self, start, make_client):
        lay = BlockLayout(4, 4, 2, 2)  # unused by GEMM_PANEL
        port = start(make_block_store_compute(lay))
        client = make_client(port)
        try:
            rng = np.random.default_rng(18)
            a = rng.normal(size=(64, 64)).astype(np.float32)
            b = rng.normal(size=(64, 8)).astype(np.float32)
            mm = BlockedMatmul(a, b, client, n_panels=4, window=4)
            req_bytes = sum(
                arr.nbytes for r in mm._requests for arr in r[1:]
            )
            ref = a.astype(np.float64) @ b
            deltas = []
            for _ in range(4):
                before = _arena_write_bytes()
                out = mm.run()
                deltas.append(_arena_write_bytes() - before)
                np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
            return req_bytes, deltas
        finally:
            client.close()

    def _check(self, req_bytes, deltas):
        # Iteration 1 ships the panels (O(matrix) request payload).
        assert deltas[0] >= req_bytes
        # Steady state is flat...
        assert deltas[2] == deltas[3]
        # ...and below the panel payload: the replies are all that
        # moves (requests ride pinned descriptors, zero copy-bytes).
        assert deltas[2] < req_bytes // 2

    def test_shm_lane_pins_the_panels(self):
        from pytensor_federated_tpu.service.shm import ShmArraysClient

        req_bytes, deltas = self._measure(
            _start_shm, lambda p: ShmArraysClient("127.0.0.1", p, retries=0)
        )
        self._check(req_bytes, deltas)

    def test_ring_lane_pins_the_panels(self):
        from pytensor_federated_tpu.service.ring import RingArraysClient

        req_bytes, deltas = self._measure(
            _start_ring, lambda p: RingArraysClient("127.0.0.1", p)
        )
        self._check(req_bytes, deltas)


# ---------------------------------------------------------------------------
# block-store nodes over real transports
# ---------------------------------------------------------------------------


class TestTransportIntegration:
    def test_cholesky_over_tcp_replicas(self):
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        a = _spd(12, seed=19)
        lay = BlockLayout(12, 12, 3, 3)
        ports = [
            _start_tcp(make_block_store_compute(lay)) for _ in range(2)
        ]
        clients = [TcpArraysClient("127.0.0.1", p) for p in ports]
        try:
            bc = BlockedCholesky(lay, clients)
            l = bc.factor(a)
            np.testing.assert_allclose(
                l, np.linalg.cholesky(a), atol=1e-12
            )
            # In-band node refusals survive the wire as BlockError text.
            with pytest.raises(Exception, match="does not hold"):
                clients[0].evaluate(
                    encode_op_header(OPCODES["GET"]),
                    pack_coords([(0, 1)]),
                )
        finally:
            for c in clients:
                c.close()

    def test_cholesky_over_shm(self):
        from pytensor_federated_tpu.service.shm import ShmArraysClient

        a = _spd(8, seed=20)
        lay = BlockLayout(8, 8, 4, 4)
        port = _start_shm(make_block_store_compute(lay))
        client = ShmArraysClient("127.0.0.1", port, retries=0)
        try:
            l = BlockedCholesky(lay, [client]).factor(a)
            np.testing.assert_allclose(
                l, np.linalg.cholesky(a), atol=1e-12
            )
        finally:
            client.close()
