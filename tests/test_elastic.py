"""Elastic sampling: mid-run failure -> detect -> remesh -> resume.

The integration the subsystems exist for: a blackbox host node (the
reference's true federated case) DIES mid-sampling — the in-band
signal, like the reference's dropped stream (service.py:407-416) —
and ``elastic_sample`` recovers: optional heartbeat detection, mesh
rebuild, ``build_logp`` re-placement, and a checkpoint resume whose
draws are BIT-IDENTICAL to a never-interrupted run.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu import blackbox_logp_grad
from pytensor_federated_tpu.checkpoint import sample_checkpointed
from pytensor_federated_tpu.samplers import elastic_sample

DIM = 3


def _make_bomb_logp(fail_state, chunk0_path):
    """logp via the blackbox host path whose host fn raises ONCE, as
    soon as chunk 0's sidecar exists on disk — i.e. deterministically
    after at least one completed checkpoint chunk, wherever the eval
    count happens to land."""

    def host(x):
        x = np.asarray(x)
        if fail_state["armed"] and os.path.exists(chunk0_path):
            fail_state["armed"] = False
            fail_state["fired"] = True
            raise RuntimeError("injected node death")
        return -0.5 * np.sum((x - 2.0) ** 2), [-(x - 2.0)]

    spec = (jax.ShapeDtypeStruct((DIM,), jnp.float32),)
    op = blackbox_logp_grad(host, spec)

    def logp(params):
        return op(params["x"])[0]

    return logp


def _clean_blackbox_logp():
    """The SAME blackbox host math as the bomb logp, never armed — the
    bit-identical oracle must share the eval path exactly (the host
    computes grads in float64 numpy; f32 autodiff of the same formula
    differs in the last bits and the trajectories diverge)."""
    return _make_bomb_logp(
        {"armed": False, "fired": False}, "/nonexistent"
    )


SAMPLE_KW = dict(
    num_warmup=100,
    num_samples=90,
    num_chains=2,
    checkpoint_every=30,
    jitter=0.5,
)


class TestElasticSample:
    def test_failure_recovery_bit_identical(self, tmp_path):
        """Kill the node mid-draws; the elastic run's draws must equal
        an uninterrupted clean run's exactly (same key discipline)."""
        key = jax.random.PRNGKey(7)
        init = {"x": jnp.zeros(DIM)}

        clean_path = str(tmp_path / "clean.ckpt")
        res_clean = sample_checkpointed(
            _clean_blackbox_logp(),
            init,
            key=key,
            checkpoint_path=clean_path,
            **SAMPLE_KW,
        )

        el_path = str(tmp_path / "elastic.ckpt")
        fail_state = {"armed": True, "fired": False}
        meshes_seen = []

        def build_logp(mesh):
            meshes_seen.append(mesh)
            return _make_bomb_logp(
                fail_state, el_path + ".chunk0000.npz"
            )

        res = elastic_sample(
            build_logp,
            init,
            key=key,
            checkpoint_path=el_path,
            **SAMPLE_KW,
        )
        assert fail_state["fired"], "the injected failure never fired"
        assert len(meshes_seen) == 2  # initial build + one recovery
        np.testing.assert_array_equal(
            np.asarray(res.samples["x"]),
            np.asarray(res_clean.samples["x"]),
        )

    def test_mesh_policy_and_detection_feed_recovery(self, tmp_path):
        """On failure the heartbeat verdict reaches the recovery policy
        and the rebuilt mesh reaches build_logp."""
        from pytensor_federated_tpu.parallel import make_mesh

        devices = jax.devices("cpu")[:8]
        mesh8 = make_mesh({"shards": 8}, devices=devices)
        mesh4 = make_mesh({"shards": 4}, devices=devices[:4])
        el_path = str(tmp_path / "mesh.ckpt")
        fail_state = {"armed": True, "fired": False}
        meshes_seen = []
        policy_calls = []

        def build_logp(mesh):
            meshes_seen.append(mesh)
            return _make_bomb_logp(
                fail_state, el_path + ".chunk0000.npz"
            )

        def on_failure(mesh, dead):
            policy_calls.append((mesh, tuple(dead)))
            return mesh4

        res = elastic_sample(
            build_logp,
            {"x": jnp.zeros(DIM)},
            key=jax.random.PRNGKey(1),
            checkpoint_path=el_path,
            mesh=mesh8,
            peers={7: ("127.0.0.1", 1)},  # port 1: provably dead
            on_failure=on_failure,
            **SAMPLE_KW,
        )
        assert fail_state["fired"]
        assert policy_calls == [(mesh8, (7,))]
        assert meshes_seen == [mesh8, mesh4]
        assert np.asarray(res.samples["x"]).shape == (2, 90, DIM)

    def test_process_restart_resumes_bit_identical(self, tmp_path):
        """The PROCESS-RESTART tier (see elastic.py docstring): a
        failure wedging a cross-device collective aborts the process —
        nothing in-process can catch it — so recovery is re-running the
        same call.  Child 1 hard-dies mid-draws (os._exit from the
        blackbox host, after chunk 0 persisted); child 2 resumes from
        the checkpoint and must produce draws bit-identical to an
        uninterrupted run in a third, clean process."""
        import subprocess
        import sys as _sys

        driver = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "elastic_proc.py"
        )
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("JAX_PLATFORMS", None)

        def run(ckpt, out, mode, expect):
            proc = subprocess.run(
                [_sys.executable, driver, ckpt, out, mode],
                env=env,
                capture_output=True,
                text=True,
                timeout=600,
            )
            assert proc.returncode == expect, (
                mode,
                proc.returncode,
                proc.stdout + proc.stderr,
            )
            return proc

        ckpt = str(tmp_path / "restart.ckpt")
        out = str(tmp_path / "restart.npz")
        run(ckpt, out, "crash", expect=42)
        assert os.path.exists(ckpt + ".chunk0000.npz")
        assert not os.path.exists(out)
        run(ckpt, out, "run", expect=0)  # fresh process resumes

        clean_ckpt = str(tmp_path / "clean.ckpt")
        clean_out = str(tmp_path / "clean.npz")
        run(clean_ckpt, clean_out, "run", expect=0)

        w_resumed = np.load(out)["w"]
        w_clean = np.load(clean_out)["w"]
        np.testing.assert_array_equal(w_resumed, w_clean)
        assert abs(float(np.mean(w_clean)) - 1.5) < 0.05

    def test_failure_budget_exhausted_reraises(self, tmp_path):
        def build_logp(mesh):
            def logp(params):
                raise RuntimeError("always broken")

            return logp

        with pytest.raises(RuntimeError, match="always broken"):
            elastic_sample(
                build_logp,
                {"x": jnp.zeros(DIM)},
                key=jax.random.PRNGKey(0),
                checkpoint_path=str(tmp_path / "x.ckpt"),
                max_failures=2,
                **SAMPLE_KW,
            )
