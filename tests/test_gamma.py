"""Gamma GLM family: scipy golden, inference, overflow, mesh parity."""

import jax
import jax.numpy as jnp
import numpy as np
import scipy.stats

from pytensor_federated_tpu.models.gamma import (
    FederatedGammaGLM,
    gamma_logpdf,
    generate_gamma_data,
)


def test_logpdf_matches_scipy():
    rng = np.random.default_rng(0)
    y = rng.gamma(3.0, 1.5, size=60).astype(np.float32)
    eta = rng.normal(0.3, 0.8, size=60).astype(np.float32)
    alpha = 2.5
    ours = np.asarray(gamma_logpdf(jnp.asarray(y), jnp.asarray(eta), alpha))
    # scipy: shape=alpha, scale=mu/alpha
    golden = scipy.stats.gamma.logpdf(
        y, alpha, scale=np.exp(eta) / alpha
    )
    np.testing.assert_allclose(ours, golden, rtol=2e-4, atol=2e-4)


def test_extreme_proposals_stay_finite():
    y = jnp.asarray([0.0, 2.0])  # includes a padded-style zero
    X = jnp.asarray([[1.0], [0.0]])

    def lp(w):
        return jnp.sum(gamma_logpdf(y, X @ w, 3.0))

    for w in (jnp.asarray([-300.0]), jnp.asarray([300.0])):
        v, g = jax.value_and_grad(lp)(w)
        assert np.isfinite(float(v)) or float(v) < 0  # never NaN
        assert not np.isnan(float(v))
        assert not np.any(np.isnan(np.asarray(g)))


def test_map_recovers_truth():
    data, truth = generate_gamma_data(8, n_obs=96, n_features=3, seed=5)
    m = FederatedGammaGLM(data)
    est = m.find_map()
    np.testing.assert_allclose(np.asarray(est["w"]), truth["w"], atol=0.15)
    alpha_est = float(jnp.exp(est["log_alpha"]))
    assert abs(alpha_est - truth["alpha"]) < 1.5


def test_nuts_converges():
    data, truth = generate_gamma_data(4, n_obs=64, n_features=2, seed=7)
    m = FederatedGammaGLM(data)
    res = m.sample(
        key=jax.random.PRNGKey(2),
        num_warmup=300,
        num_samples=300,
        num_chains=2,
    )
    summ = res.summary()
    # 2 chains x 300 draws: split-rhat noise floor is ~1.05-1.1
    assert float(np.max(np.asarray(summ["rhat"]["w"]))) < 1.1
    w_mean = np.asarray(res.samples["w"]).mean(axis=(0, 1))
    np.testing.assert_allclose(w_mean, truth["w"], atol=0.2)


def test_predictive_calibrated():
    data, truth = generate_gamma_data(4, n_obs=64, n_features=3, seed=11)
    m = FederatedGammaGLM(data)
    est = m.find_map()
    (X, y), mask = data.tree()
    sim = m.predictive(est, jax.random.PRNGKey(1))
    sim_mean = float(jnp.sum(sim) / jnp.sum(mask))
    obs_mean = float(jnp.sum(y * mask) / jnp.sum(mask))
    assert abs(sim_mean - obs_mean) / obs_mean < 0.25


def test_on_mesh(devices8):
    from pytensor_federated_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"shards": 8}, devices=devices8)
    data, _ = generate_gamma_data(8, n_obs=32, n_features=2, seed=9)
    m_mesh = FederatedGammaGLM(data, mesh=mesh)
    m_local = FederatedGammaGLM(data)
    p0 = m_local.init_params()
    np.testing.assert_allclose(
        float(m_mesh.logp(p0)), float(m_local.logp(p0)), rtol=5e-4
    )


def test_large_y_extreme_proposal_no_nan():
    # y ~ 8e3 with eta ~ -300: rate*y overflows f32 unless the whole
    # exponent is clamped (round-2 review: logp=-inf with NaN grad).
    y = jnp.asarray([8000.0, 1.0])
    X = jnp.asarray([[1.0], [1.0]])

    def lp(w):
        return jnp.sum(gamma_logpdf(y, X @ w, 3.0))

    v, g = jax.value_and_grad(lp)(jnp.asarray([-300.0]))
    assert np.isfinite(float(v))
    assert np.all(np.isfinite(np.asarray(g)))
