"""Pool-placement chaos smoke (ISSUE 6 satellite): a ``PoolPlacement``
``fed_map`` rides a 2-replica pool and one replica is SIGKILLed MID
pipelined window.  The exactly-one-correct-reply invariant must hold
through the primitive lane: every shard's logp comes back once and
correct (the dead replica's un-replied tail re-queues onto the
survivor — the test_pool_e2e contract, now entered through
``fed.program`` instead of a hand-built request list).
"""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest
from conftest import spawn_node_procs, wait_nodes_up

from pytensor_federated_tpu import fed
from pytensor_federated_tpu.routing import NodePool, PooledArraysClient
from pytensor_federated_tpu.telemetry import flightrec

BASE_PORT = 29590
N_SHARDS = 32
COMPUTE_DELAY_S = 0.02


def _serve_fed_node(port, delay):
    """Module-level (spawn needs a picklable target): the fed node-side
    logp+grad compute with a per-call delay, so the pipelined window is
    genuinely in flight when the kill lands."""
    import logging
    import time as _time

    logging.basicConfig(level=logging.WARNING)

    import jax.numpy as _jnp

    from pytensor_federated_tpu import fed as _fed
    from pytensor_federated_tpu.service import run_node

    def shard_logp(p, x, y):
        return -_jnp.sum((y - p[0] - p[1] * x) ** 2)

    base = _fed.make_node_compute(shard_logp)

    def compute(*arrays):
        _time.sleep(delay)
        return base(*arrays)

    run_node(compute, "127.0.0.1", port)


def _shard_logp(p, x, y):
    return -jnp.sum((y - p[0] - p[1] * x) ** 2)


@pytest.mark.slow
def test_midwindow_kill_exactly_one_correct_reply():
    ports = [BASE_PORT, BASE_PORT + 1]
    procs = spawn_node_procs(
        _serve_fed_node, [(p, COMPUTE_DELAY_S) for p in ports]
    )
    pool = NodePool(
        [("127.0.0.1", p) for p in ports],
        breaker_kwargs=dict(failure_threshold=1, backoff_s=30.0),
    )
    client = PooledArraysClient(pool)
    try:
        wait_nodes_up(ports)
        rng = np.random.default_rng(17)  # one chaos_run-style seed
        x = jnp.asarray(rng.normal(size=(N_SHARDS, 8)).astype(np.float32))
        y = jnp.asarray(rng.normal(size=(N_SHARDS, 8)).astype(np.float32))
        params = jnp.asarray(np.float32([0.2, -0.6]))

        def model(p):
            pb = fed.fed_broadcast(p, N_SHARDS)
            return fed.fed_map(
                lambda s: _shard_logp(s[0], s[1], s[2]), (pb, x, y)
            )

        run = fed.program(model, fed.PoolPlacement(client, window=8))
        expected = np.asarray(
            [_shard_logp(params, x[i], y[i]) for i in range(N_SHARDS)]
        )

        # Warm both replicas (connect + EWMA) so the killed window is a
        # steady-state spread, then kill replica 0 mid-window.
        first = np.asarray(run(params))
        np.testing.assert_allclose(first, expected, rtol=1e-5)

        flightrec.clear()
        victim = procs[0]
        killer = threading.Timer(4 * COMPUTE_DELAY_S, victim.kill)
        killer.start()
        t0 = time.perf_counter()
        lps = np.asarray(run(params))
        wall = time.perf_counter() - t0
        killer.join()

        # exactly-one-correct-reply: every shard's logp is present and
        # equals its reference — nothing lost, nothing double-assigned,
        # nothing hung.
        assert lps.shape == (N_SHARDS,)
        np.testing.assert_allclose(lps, expected, rtol=1e-5)

        kinds = {e["kind"] for e in flightrec.events()}
        assert "pool.failover" in kinds, sorted(kinds)
        assert "fed.fused_window" in kinds
        assert wall < 30.0  # settled promptly, no wedge
        assert not procs[0].is_alive()
    finally:
        client.close()
        pool.close()
        for p in procs:
            p.terminate()
        for p in procs:
            p.join(timeout=10)
