"""SMC / ensemble / ADVI samplers — accuracy against closed forms.

Pattern: posterior-accuracy assertions with fixed seeds (reference:
test_wrapper_ops.py:105-117 asserts posterior median slope = 2 ± 0.1).
Ground truth here is analytic (Gaussian conjugacy), which is stronger.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu.samplers import (
    advi_fit,
    ensemble_sample,
    smc_sample,
)


def make_gaussian_target(dim=3, seed=0):
    """Correlated Gaussian: logp = -0.5 (x-m)^T P (x-m); known mean/cov."""
    rng = np.random.default_rng(seed)
    m = rng.normal(size=dim).astype(np.float32)
    a = rng.normal(size=(dim, dim)).astype(np.float32)
    cov = a @ a.T + dim * np.eye(dim, dtype=np.float32)
    prec = np.linalg.inv(cov)
    m_j, prec_j = jnp.asarray(m), jnp.asarray(prec)

    def logp(params):
        d = params["x"] - m_j
        return -0.5 * d @ prec_j @ d

    return logp, m, cov


class TestSMC:
    def test_gaussian_moments_and_evidence(self):
        logp, m, cov = make_gaussian_target(dim=3, seed=1)
        res = smc_sample(
            logp,
            {"x": jnp.zeros(3)},
            key=jax.random.PRNGKey(0),
            n_particles=4096,
            n_mutations=8,
            init_jitter=3.0,
        )
        assert float(res.final_beta) == 1.0
        assert int(res.n_stages) < 50
        xs = np.asarray(res.samples["x"])
        np.testing.assert_allclose(xs.mean(0), m, atol=0.25)
        np.testing.assert_allclose(
            np.cov(xs.T), cov, atol=0.2 * np.abs(cov).max() + 0.3
        )
        # Normalizing constant of exp(-0.5 d^T P d) is (2pi)^{d/2}|cov|^{1/2}.
        want_log_z = 0.5 * 3 * np.log(2 * np.pi) + 0.5 * np.linalg.slogdet(cov)[1]
        assert abs(float(res.log_evidence) - want_log_z) < 0.5

    def test_accept_rate_sane(self):
        logp, _, _ = make_gaussian_target(dim=2, seed=2)
        res = smc_sample(
            logp,
            {"x": jnp.zeros(2)},
            key=jax.random.PRNGKey(1),
            n_particles=1024,
        )
        assert 0.05 < float(res.accept_rate) <= 1.0


class TestEnsemble:
    def test_gaussian_moments(self):
        logp, m, cov = make_gaussian_target(dim=3, seed=3)
        res = ensemble_sample(
            logp,
            {"x": jnp.zeros(3)},
            key=jax.random.PRNGKey(2),
            n_walkers=64,
            num_warmup=1500,
            num_samples=1500,
            init_jitter=1.0,
        )
        xs = np.asarray(res.samples["x"]).reshape(-1, 3)
        np.testing.assert_allclose(xs.mean(0), m, atol=0.3)
        sd_want = np.sqrt(np.diag(cov))
        np.testing.assert_allclose(xs.std(0), sd_want, rtol=0.35)
        assert 0.1 < float(res.accept_rate) < 0.9

    def test_validation(self):
        logp, _, _ = make_gaussian_target(dim=4)
        with pytest.raises(ValueError, match="even"):
            ensemble_sample(
                logp, {"x": jnp.zeros(4)}, key=jax.random.PRNGKey(0), n_walkers=7
            )
        with pytest.raises(ValueError, match="2\\*dim"):
            ensemble_sample(
                logp, {"x": jnp.zeros(4)}, key=jax.random.PRNGKey(0), n_walkers=6
            )

    def test_gradient_free(self):
        """Works on a logp JAX cannot differentiate (uses stop_gradient +
        rounding) — the capability NUTS lacks."""

        def logp(params):
            x = params["x"]
            return -0.5 * jnp.sum(jax.lax.stop_gradient(x) ** 2)

        res = ensemble_sample(
            logp,
            {"x": jnp.zeros(2)},
            key=jax.random.PRNGKey(3),
            n_walkers=32,
            num_warmup=500,
            num_samples=500,
        )
        xs = np.asarray(res.samples["x"]).reshape(-1, 2)
        np.testing.assert_allclose(xs.mean(0), 0.0, atol=0.3)


class TestADVI:
    def test_gaussian_recovery(self):
        logp, m, cov = make_gaussian_target(dim=3, seed=4)
        res, unravel = advi_fit(
            logp,
            {"x": jnp.zeros(3)},
            key=jax.random.PRNGKey(4),
            num_steps=3000,
            n_mc=16,
            learning_rate=2e-2,
        )
        np.testing.assert_allclose(np.asarray(res.mean["x"]), m, atol=0.15)
        # Mean-field sd underestimates marginal sd for correlated targets;
        # it matches 1/sqrt(diag(precision)).
        want_sd = 1.0 / np.sqrt(np.diag(np.linalg.inv(cov)))
        np.testing.assert_allclose(
            np.asarray(res.sd["x"]), want_sd, rtol=0.25
        )
        # ELBO improved and converged.
        elbo = np.asarray(res.elbo_trace)
        assert elbo[-100:].mean() > elbo[:100].mean()

    def test_sample_shapes(self):
        logp, _, _ = make_gaussian_target(dim=2, seed=5)
        res, unravel = advi_fit(
            logp, {"x": jnp.zeros(2)}, key=jax.random.PRNGKey(5), num_steps=200
        )
        draws = res.sample(jax.random.PRNGKey(6), 128, unravel)
        assert draws["x"].shape == (128, 2)


class TestFederatedIntegration:
    def test_smc_on_federated_logp(self, mesh8):
        """SMC over the sharded psum evaluator — sampler and collective
        compose in one program."""
        from pytensor_federated_tpu.models.linear import (
            FederatedLinearRegression,
            generate_node_data,
        )

        data, _offsets = generate_node_data(8, n_obs=32, seed=9, slope=2.0)
        model = FederatedLinearRegression(data, mesh=mesh8)
        res = smc_sample(
            model.logp,
            model.init_params(),
            key=jax.random.PRNGKey(7),
            n_particles=512,
            n_mutations=5,
            init_jitter=0.5,
        )
        slope = float(np.median(np.asarray(res.samples["slope"])))
        assert abs(slope - 2.0) < 0.25, slope


class TestFullRankADVI:
    def test_recovers_correlated_gaussian_exactly(self):
        """For a Gaussian target the full-rank optimum IS the target:
        mean AND full covariance (incl. off-diagonal) recovered —
        which mean-field structurally cannot do."""
        from pytensor_federated_tpu.samplers import (
            advi_fit,
            fullrank_advi_fit,
        )

        rho = 0.8
        cov = jnp.asarray([[1.0, rho], [rho, 2.0]])
        prec = jnp.linalg.inv(cov)
        mu_true = jnp.asarray([1.0, -0.5])

        def logp(p):
            d = p["x"] - mu_true
            return -0.5 * d @ prec @ d

        res, unravel = fullrank_advi_fit(
            logp,
            {"x": jnp.zeros(2)},
            key=jax.random.PRNGKey(0),
            num_steps=4000,
        )
        np.testing.assert_allclose(
            np.asarray(res.mean["x"]), np.asarray(mu_true), atol=0.1
        )
        np.testing.assert_allclose(
            np.asarray(res.covariance), np.asarray(cov), atol=0.3
        )
        # off-diagonal really captured (mean-field's covariance is
        # diagonal by construction)
        assert abs(float(res.covariance[0, 1]) - rho) < 0.3

        # and the full-rank ELBO beats mean-field's on this target
        res_mf, _ = advi_fit(
            logp,
            {"x": jnp.zeros(2)},
            key=jax.random.PRNGKey(0),
            num_steps=4000,
        )
        tail = lambda r: float(jnp.mean(r.elbo_trace[-200:]))
        assert tail(res) > tail(res_mf)

    def test_sample_has_fitted_covariance(self):
        from pytensor_federated_tpu.samplers import fullrank_advi_fit

        cov = jnp.asarray([[1.0, 0.6], [0.6, 1.0]])
        prec = jnp.linalg.inv(cov)

        def logp(p):
            return -0.5 * p["x"] @ prec @ p["x"]

        res, unravel = fullrank_advi_fit(
            logp,
            {"x": jnp.zeros(2)},
            key=jax.random.PRNGKey(1),
            num_steps=3000,
        )
        draws = res.sample(jax.random.PRNGKey(2), 5000, unravel)
        got = np.cov(np.asarray(draws["x"]).T)
        np.testing.assert_allclose(got, np.asarray(cov), atol=0.3)


def test_doubly_stochastic_advi_matches_full():
    """advi_fit(stochastic_logp_fn=...) with federated shard
    subsampling converges to (approximately) the same posterior as the
    full-logp fit — the minibatch ELBO gradient is unbiased."""
    from pytensor_federated_tpu.models.linear import (
        FederatedLinearRegression,
        generate_node_data,
    )
    from pytensor_federated_tpu.samplers import advi_fit

    data, _ = generate_node_data(16, n_obs=32, seed=7)
    model = FederatedLinearRegression(data)
    fed = model.fed

    def full_logp(p):
        return model.logp(p)

    res_full, unravel = advi_fit(
        full_logp,
        model.init_params(),
        key=jax.random.PRNGKey(0),
        num_steps=2000,
    )

    def mb_logp(p, k):
        return model.prior_logp(p) + fed.logp_minibatch(
            p, k, num_shards=4
        )

    res_mb, _ = advi_fit(
        full_logp,
        model.init_params(),
        key=jax.random.PRNGKey(0),
        num_steps=4000,  # noisier gradients: more steps
        stochastic_logp_fn=mb_logp,
    )
    for k in res_full.mean:
        np.testing.assert_allclose(
            np.asarray(res_mb.mean[k]),
            np.asarray(res_full.mean[k]),
            atol=0.15,
        )
