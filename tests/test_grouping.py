"""Property tests for the fusion grouping algorithm (no pytensor needed).

The fusion rewrite (bridge/fusion.py) can only execute where PyTensor
is installed; its core risk — grouping two applies whose fusion would
create a graph cycle — lives entirely in ``group_independent``, which
is pure and tested here on randomized DAGs.
"""

import random

import pytest

from pytensor_federated_tpu.bridge.grouping import group_independent


def random_dag(rng, n_nodes, p_edge, p_candidate):
    """Nodes 0..n-1 in topological order; edges only point forward."""
    parents = {i: set() for i in range(n_nodes)}
    for j in range(n_nodes):
        for i in range(j):
            if rng.random() < p_edge:
                parents[j].add(i)
    candidates = {i for i in range(n_nodes) if rng.random() < p_candidate}
    return parents, candidates


def transitive_ancestors(parents, n):
    seen = set()
    stack = list(parents[n])
    while stack:
        m = stack.pop()
        if m in seen:
            continue
        seen.add(m)
        stack.extend(parents[m])
    return seen


def run(parents, candidates, n_nodes):
    return group_independent(
        range(n_nodes),
        parents=lambda n: parents[n],
        is_candidate=lambda n: n in candidates,
    )


@pytest.mark.parametrize("seed", range(25))
def test_random_dags(seed):
    rng = random.Random(seed)
    n = rng.randrange(2, 40)
    parents, candidates = random_dag(rng, n, rng.uniform(0.05, 0.4),
                                     rng.uniform(0.2, 0.8))
    groups = run(parents, candidates, n)

    # every candidate appears in exactly one group
    flat = [c for g in groups for c in g]
    assert sorted(flat) == sorted(candidates)

    anc = {i: transitive_ancestors(parents, i) for i in range(n)}
    for g in groups:
        # members pairwise independent: no member is an ancestor of
        # another (fusing them can then never create a cycle)
        for a in g:
            for b in g:
                if a != b:
                    assert a not in anc[b] and b not in anc[a]
        # members listed in topological order
        assert g == sorted(g)


@pytest.mark.parametrize("seed", range(25))
def test_fused_graph_is_acyclic(seed):
    # Simulate the actual fusion: contract each group to one node and
    # check the contracted graph has no cycle (the property that
    # ReplaceValidate would enforce at runtime).
    rng = random.Random(seed + 1000)
    n = rng.randrange(2, 40)
    parents, candidates = random_dag(rng, n, rng.uniform(0.05, 0.4),
                                     rng.uniform(0.2, 0.8))
    groups = [g for g in run(parents, candidates, n) if len(g) > 1]
    rep = {}
    for gi, g in enumerate(groups):
        for m in g:
            rep[m] = ("fused", gi)
    contracted = {}
    for j in range(n):
        src = rep.get(j, j)
        contracted.setdefault(src, set())
        for i in parents[j]:
            pi = rep.get(i, i)
            if pi != src:
                contracted[src].add(pi)
                contracted.setdefault(pi, set())
    # cycle check via DFS with colors
    WHITE, GREY, BLACK = 0, 1, 2
    color = {v: WHITE for v in contracted}

    def visit(v):
        color[v] = GREY
        for u in contracted[v]:
            if color[u] == GREY:
                raise AssertionError(f"cycle through {v} and {u}")
            if color[u] == WHITE:
                visit(u)
        color[v] = BLACK

    for v in list(contracted):
        if color[v] == WHITE:
            visit(v)


def test_layered_graph_fuses_per_layer():
    # Two independent layer-1 nodes feeding one layer-2 node: the
    # classic reference topology (test_op_async.py:153-195).
    parents = {0: set(), 1: set(), 2: {0, 1}}
    groups = run(parents, {0, 1, 2}, 3)
    assert [0, 1] in groups and [2] in groups


def test_chain_never_groups():
    parents = {0: set(), 1: {0}, 2: {1}}
    groups = run(parents, {0, 1, 2}, 3)
    assert groups == [[0], [1], [2]]


def test_independence_through_noncandidate_intermediary():
    # 0 -> (non-candidate 1) -> 2: 2 transitively depends on 0 and must
    # not group with it even though no direct edge exists.
    parents = {0: set(), 1: {0}, 2: {1}}
    groups = run(parents, {0, 2}, 3)
    assert groups == [[0], [2]]


def test_no_candidates():
    assert run({0: set()}, set(), 1) == []
