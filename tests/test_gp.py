"""Federated sparse GP (models/gp.py).

Golden-model pattern (reference: test_demo_node.py:29-65): the
psum-reduced per-shard statistics formulation must equal the dense
single-device VFE bound computed with full n x n algebra.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu.models.gp import (
    FederatedSparseGP,
    dense_vfe_logp,
    generate_gp_data,
)
from pytensor_federated_tpu.parallel import make_mesh


@pytest.fixture(scope="module")
def gp_data():
    packed, dense = generate_gp_data(4, n_obs=32, seed=3)
    inducing = np.linspace(-2.0, 2.0, 16).astype(np.float32)
    return packed, dense, inducing


def params_at(lv=0.1, ll=-0.5, ln=-1.2):
    return {
        "log_variance": jnp.asarray(lv),
        "log_lengthscale": jnp.asarray(ll),
        "log_noise": jnp.asarray(ln),
    }


class TestEquivalence:
    def test_federated_matches_dense(self, gp_data):
        packed, dense, inducing = gp_data
        model = FederatedSparseGP(packed, inducing)
        p = params_at()
        got = float(model.logp(p))
        want = float(dense_vfe_logp(p, dense[0], dense[1], inducing))
        np.testing.assert_allclose(got, want, rtol=2e-4)

    def test_sharded_matches_single_device(self, gp_data, devices8):
        packed, _, inducing = gp_data
        mesh = make_mesh({"shards": 4}, devices=devices8[:4])
        sharded = FederatedSparseGP(packed, inducing, mesh=mesh)
        local = FederatedSparseGP(packed, inducing)
        p = params_at(0.3, -0.2, -1.0)
        np.testing.assert_allclose(
            float(sharded.logp(p)), float(local.logp(p)), rtol=1e-5
        )
        v_s, g_s = sharded.logp_and_grad(p)
        v_l, g_l = local.logp_and_grad(p)
        for k in p:
            np.testing.assert_allclose(
                float(g_s[k]), float(g_l[k]), rtol=1e-3, atol=1e-4
            )

    def test_ragged_shards_match_dense(self):
        """Unequal shard sizes: the mask/padding path must reproduce the
        dense bound exactly (the federation-specific subtlety)."""
        rng = np.random.default_rng(11)
        n = 128
        x = rng.uniform(-2, 2, size=n).astype(np.float32)
        y = np.sin(2 * x).astype(np.float32) + 0.1 * rng.normal(size=n).astype(
            np.float32
        )
        splits = np.split(np.arange(n), [40, 80, 110])  # 40/40/30/18
        from pytensor_federated_tpu.parallel import pack_shards

        packed = pack_shards([(x[s], y[s]) for s in splits])
        assert packed.mask.sum() == n and (packed.mask == 0).any()
        inducing = np.linspace(-2, 2, 12).astype(np.float32)
        model = FederatedSparseGP(packed, inducing)
        p = params_at(0.2, -0.4, -1.5)
        got = float(model.logp(p))
        want = float(dense_vfe_logp(p, x, y, inducing))
        np.testing.assert_allclose(got, want, rtol=5e-4)

    def test_gradients_match_dense(self, gp_data):
        packed, dense, inducing = gp_data
        model = FederatedSparseGP(packed, inducing)
        p = params_at()
        _, grads = model.logp_and_grad(p)
        dense_grads = jax.grad(
            lambda q: dense_vfe_logp(q, dense[0], dense[1], inducing)
        )(p)
        for k in p:
            np.testing.assert_allclose(
                float(grads[k]), float(dense_grads[k]), rtol=5e-3, atol=5e-3
            )


class TestInference:
    def test_map_recovers_hyperparams(self, gp_data):
        """MAP over the VFE bound lands near the generating values
        (lengthscale 0.4, noise 0.1, variance 1.0 — loose tolerances,
        finite data)."""
        from pytensor_federated_tpu.samplers import find_map

        packed, _, inducing = gp_data
        model = FederatedSparseGP(packed, inducing)
        opt = find_map(
            model.logp,
            model.init_params(),
            num_steps=400,
            learning_rate=0.05,
        )
        ls = float(jnp.exp(opt["log_lengthscale"]))
        noise = float(jnp.exp(opt["log_noise"]))
        assert 0.2 < ls < 0.8, ls
        assert 0.05 < noise < 0.2, noise

    def test_nuts_runs(self, gp_data):
        from pytensor_federated_tpu.samplers import sample

        packed, _, inducing = gp_data
        model = FederatedSparseGP(packed, inducing)
        res = sample(
            model.logp,
            model.init_params(),
            key=jax.random.PRNGKey(0),
            num_warmup=100,
            num_samples=100,
            num_chains=2,
            max_depth=6,
            jitter=0.1,
        )
        assert res.samples["log_noise"].shape == (2, 100)
        assert float(jnp.mean(res.stats["accept_prob"])) > 0.5


class TestExactGP:
    """FederatedExactGP: padding exactness, golden, hyperparam MAP."""

    def _data(self, n_shards=4, n_obs=(24, 32, 17, 40), seed=2):
        # hand-built (not generate_gp_data): unequal per-shard sizes
        # exercise the padding-exactness correction
        rng = np.random.default_rng(seed)
        shards = []
        for n in n_obs[:n_shards]:
            x = np.sort(rng.uniform(-3, 3, size=n)).astype(np.float32)
            f = np.sin(1.3 * x) * 1.5
            y = (f + 0.1 * rng.normal(size=n)).astype(np.float32)
            shards.append((x, y))
        from pytensor_federated_tpu.parallel.packing import pack_shards

        return pack_shards(shards, pad_to_multiple=8), shards

    def test_masked_logp_equals_unpadded_dense(self):
        from pytensor_federated_tpu.models.gp import (
            FederatedExactGP,
            _sqexp,
            _unpack,
            _JITTER,
        )
        from pytensor_federated_tpu.utils import LOG_2PI

        packed, shards = self._data()
        m = FederatedExactGP(packed)
        params = {
            "log_variance": jnp.asarray(0.3),
            "log_lengthscale": jnp.asarray(-0.2),
            "log_noise": jnp.asarray(-1.5),
        }
        variance, lengthscale, noise = _unpack(params)
        dense = 0.0
        for x, y in shards:
            n = x.shape[0]
            k = np.asarray(
                _sqexp(jnp.asarray(x), jnp.asarray(x), variance, lengthscale)
            ) + (float(noise) ** 2 + _JITTER * float(variance)) * np.eye(n)
            sign, logdet = np.linalg.slogdet(k)
            alpha = np.linalg.solve(k, y)
            dense += -0.5 * (y @ alpha + logdet + n * LOG_2PI)
        # compare the data part: logp minus the hyperparameter prior
        from pytensor_federated_tpu.models.gp import FederatedSparseGP

        data_ll = float(m.logp(params)) - float(
            FederatedSparseGP._prior_logp(params)
        )
        np.testing.assert_allclose(data_ll, dense, rtol=5e-4)

    def test_map_recovers_lengthscale_order(self):
        from pytensor_federated_tpu.models.gp import FederatedExactGP

        packed, _ = self._data()
        m = FederatedExactGP(packed)
        est = m.find_map()
        ls = float(jnp.exp(est["log_lengthscale"]))
        noise = float(jnp.exp(est["log_noise"]))
        assert 0.2 < ls < 3.0  # sin(1.3x) wiggles on O(1) scale
        assert noise < 0.4

    def test_posterior_interpolates(self):
        from pytensor_federated_tpu.models.gp import FederatedExactGP

        packed, shards = self._data()
        m = FederatedExactGP(packed)
        est = m.find_map()
        xs = jnp.linspace(-2.5, 2.5, 21)
        mean, var = m.posterior(est, xs)
        assert mean.shape == (4, 21) and var.shape == (4, 21)
        # posterior mean tracks the true function on observed support
        truth = np.sin(1.3 * np.asarray(xs)) * 1.5
        err = np.abs(np.asarray(mean) - truth[None, :]).mean()
        assert err < 0.25
        assert np.all(np.asarray(var) > -1e-4)

    def test_on_mesh(self, devices8):
        from pytensor_federated_tpu.models.gp import FederatedExactGP
        from pytensor_federated_tpu.parallel.mesh import make_mesh

        packed, _ = self._data(n_shards=4)
        # 4 shards over a 4-device submesh
        mesh = make_mesh({"shards": 4}, devices=devices8[:4])
        m_mesh = FederatedExactGP(packed, mesh=mesh)
        m_local = FederatedExactGP(packed)
        p0 = m_local.init_params()
        np.testing.assert_allclose(
            float(m_mesh.logp(p0)), float(m_local.logp(p0)), rtol=5e-4
        )


class TestARD:
    """Multi-dimensional inputs + per-dimension lengthscales."""

    def test_2d_kernel_matches_broadcast_form(self):
        from pytensor_federated_tpu.models.gp import _sqexp

        rng = np.random.default_rng(0)
        x1 = jnp.asarray(rng.normal(size=(12, 3)).astype(np.float32))
        x2 = jnp.asarray(rng.normal(size=(9, 3)).astype(np.float32))
        ls = jnp.asarray([0.5, 1.0, 2.0])
        k = np.asarray(_sqexp(x1, x2, 1.3, ls))
        d2 = np.sum(
            ((np.asarray(x1)[:, None, :] - np.asarray(x2)[None, :, :])
             / np.asarray(ls)) ** 2,
            axis=2,
        )
        golden = 1.3 * np.exp(-0.5 * d2)
        np.testing.assert_allclose(k, golden, rtol=1e-4, atol=1e-5)

    def test_ard_prunes_irrelevant_dimension(self):
        # f depends only on dim 0; the fitted lengthscale for dim 1
        # must grow far beyond dim 0's.
        from pytensor_federated_tpu.models.gp import FederatedExactGP
        from pytensor_federated_tpu.parallel.packing import pack_shards
        from pytensor_federated_tpu.samplers import find_map

        rng = np.random.default_rng(1)
        shards = []
        for _ in range(4):
            x = rng.uniform(-2, 2, size=(40, 2)).astype(np.float32)
            y = (np.sin(2.0 * x[:, 0]) + 0.05 * rng.normal(size=40)).astype(
                np.float32
            )
            shards.append((x, y))
        packed = pack_shards(shards, pad_to_multiple=8)
        m = FederatedExactGP(packed)
        init = {
            "log_variance": jnp.zeros(()),
            "log_lengthscale": jnp.zeros((2,)),  # ARD: one per dim
            "log_noise": jnp.asarray(-1.5),
        }
        est = find_map(m.logp, init)
        ls = np.exp(np.asarray(est["log_lengthscale"]))
        assert ls[1] > 3.0 * ls[0], ls


def test_kernel_shape_mismatches_fail_loudly():
    import pytest as _pytest

    from pytensor_federated_tpu.models.gp import _sqexp

    with _pytest.raises(ValueError, match="matching ndim"):
        _sqexp(jnp.zeros(5), jnp.zeros((5, 2)), 1.0, 1.0)
    with _pytest.raises(ValueError, match="scalar lengthscale"):
        _sqexp(jnp.zeros(4), jnp.zeros(3), 1.0, jnp.ones(3))


class TestMaternKernels:
    def test_matern_closed_forms(self):
        from pytensor_federated_tpu.models.gp import _matern32, _matern52

        x1 = jnp.asarray([0.0, 1.0])
        x2 = jnp.asarray([0.0, 2.5])
        r = np.abs(
            np.asarray(x1)[:, None] - np.asarray(x2)[None, :]
        ) / 0.7
        for fn, nu_fn in (
            (_matern32, lambda r: (1 + np.sqrt(3) * r) * np.exp(-np.sqrt(3) * r)),
            (_matern52, lambda r: (1 + np.sqrt(5) * r + 5 * r**2 / 3)
             * np.exp(-np.sqrt(5) * r)),
        ):
            k = np.asarray(fn(x1, x2, 2.0, 0.7))
            np.testing.assert_allclose(k, 2.0 * nu_fn(r), rtol=1e-5)

    def test_exact_gp_with_matern_fits(self):
        from pytensor_federated_tpu.models.gp import FederatedExactGP
        from pytensor_federated_tpu.parallel.packing import pack_shards

        rng = np.random.default_rng(3)
        shards = []
        for _ in range(4):
            x = np.sort(rng.uniform(-2, 2, size=30)).astype(np.float32)
            y = (np.sin(2 * x) + 0.1 * rng.normal(size=30)).astype(np.float32)
            shards.append((x, y))
        packed = pack_shards(shards, pad_to_multiple=8)
        m = FederatedExactGP(packed, kernel="matern52")
        est = m.find_map()
        # posterior with the SAME kernel must track the function
        xs = jnp.linspace(-1.5, 1.5, 15)
        mean, var = m.posterior(est, xs)
        err = np.abs(
            np.asarray(mean) - np.sin(2 * np.asarray(xs))[None, :]
        ).mean()
        assert err < 0.3
        assert np.all(np.asarray(var) > -1e-4)

    def test_unknown_kernel_fails_loudly(self):
        import pytest as _pytest

        from pytensor_federated_tpu.models.gp import get_kernel

        with _pytest.raises(ValueError, match="unknown kernel"):
            get_kernel("rbf")

    def test_matern_zero_distance_gradients_finite(self):
        from pytensor_federated_tpu.models.gp import _matern32

        x = jnp.asarray([[0.5, 0.5], [0.5, 0.5]])  # duplicate points

        def total(ls):
            return jnp.sum(_matern32(x, x, 1.0, ls))

        g = jax.grad(total)(jnp.ones(2))
        assert np.all(np.isfinite(np.asarray(g)))


def test_sparse_gp_matern_matches_dense_vfe():
    from pytensor_federated_tpu.models.gp import (
        FederatedSparseGP,
        dense_vfe_logp,
        generate_gp_data,
    )

    packed, pool = generate_gp_data(4, n_obs=32, seed=7)
    inducing = np.linspace(-1.8, 1.8, 12).astype(np.float32)
    m = FederatedSparseGP(packed, inducing, kernel="matern52")
    params = {
        "log_variance": jnp.asarray(0.2),
        "log_lengthscale": jnp.asarray(-0.5),
        "log_noise": jnp.asarray(-1.2),
    }
    golden = float(
        dense_vfe_logp(
            params, pool[0], pool[1], inducing, kernel="matern52"
        )
    )
    np.testing.assert_allclose(float(m.logp(params)), golden, rtol=5e-4)


class TestSparsePosterior:
    def test_matches_dense_sgpr_predictive(self):
        """Golden model: the federated whitened-statistics posterior
        must equal the textbook dense SGPR predictive computed on the
        pooled data with full n x n algebra."""
        import jax.numpy as jnp
        import jax.scipy.linalg as jsl

        from pytensor_federated_tpu.models.gp import (
            _JITTER,
            FederatedSparseGP,
            _sqexp,
            generate_gp_data,
        )

        data, pool = generate_gp_data(4, n_obs=48, seed=11)
        x_all, y_all = pool[0], pool[1]
        z = np.linspace(-2.0, 2.0, 12).astype(np.float32)
        sgp = FederatedSparseGP(data, z)
        params = {
            "log_variance": jnp.asarray(0.2),
            "log_lengthscale": jnp.asarray(-0.5),
            "log_noise": jnp.asarray(-1.2),
        }
        xs = np.linspace(-1.8, 1.8, 9).astype(np.float32)
        mean, var = sgp.posterior(params, xs)

        # dense reference: mu* = k*z (s2 Kzz + Kzf Kfz)^-1 Kzf y
        variance = float(jnp.exp(params["log_variance"]))
        ls = float(jnp.exp(params["log_lengthscale"]))
        s2 = float(jnp.exp(params["log_noise"])) ** 2
        kzz = np.asarray(
            _sqexp(jnp.asarray(z), jnp.asarray(z), variance, ls)
        ) + _JITTER * variance * np.eye(len(z))
        kzf = np.asarray(
            _sqexp(jnp.asarray(z), jnp.asarray(x_all), variance, ls)
        )
        ksz = np.asarray(
            _sqexp(jnp.asarray(xs), jnp.asarray(z), variance, ls)
        )
        sigma = np.linalg.inv(kzz + kzf @ kzf.T / s2)
        mean_ref = ksz @ sigma @ (kzf @ y_all) / s2
        var_ref = (
            variance
            - np.einsum("ij,jk,ik->i", ksz, np.linalg.inv(kzz), ksz)
            + np.einsum("ij,jk,ik->i", ksz, sigma, ksz)
        )
        np.testing.assert_allclose(np.asarray(mean), mean_ref, rtol=2e-3,
                                   atol=2e-3)
        np.testing.assert_allclose(np.asarray(var), var_ref, rtol=2e-3,
                                   atol=2e-3)
        # posterior variance is a variance
        assert np.all(np.asarray(var) > 0)

    def test_posterior_tracks_latent(self):
        """Near the data, the global sparse posterior mean must track
        the pooled observations far better than the prior does."""
        import jax.numpy as jnp

        from pytensor_federated_tpu.models.gp import (
            FederatedSparseGP,
            generate_gp_data,
        )

        data, pool = generate_gp_data(6, n_obs=64, seed=3)
        x_all, y_all = pool[0], pool[1]
        z = np.linspace(-2.0, 2.0, 24).astype(np.float32)
        sgp = FederatedSparseGP(data, z)
        params = {
            "log_variance": jnp.zeros(()),
            "log_lengthscale": jnp.asarray(-1.0),
            "log_noise": jnp.asarray(-1.5),
        }
        mean, var = sgp.posterior(params, x_all[::8])
        resid = np.asarray(mean) - y_all[::8]
        assert np.sqrt(np.mean(resid**2)) < 0.5 * np.std(y_all)

    def test_posterior_on_mesh(self, devices8):
        """Same numbers when the statistics reduce over a mesh."""
        import jax.numpy as jnp

        from pytensor_federated_tpu.models.gp import (
            FederatedSparseGP,
            generate_gp_data,
        )
        from pytensor_federated_tpu.parallel.mesh import make_mesh

        data, _ = generate_gp_data(8, n_obs=16, seed=5)
        z = np.linspace(-2.0, 2.0, 8).astype(np.float32)
        xs = np.linspace(-1.0, 1.0, 5).astype(np.float32)
        single = FederatedSparseGP(data, z)
        meshed = FederatedSparseGP(
            data, z, mesh=make_mesh({"shards": 8}, devices=devices8)
        )
        p = single.init_params()
        m0, v0 = single.posterior(p, xs)
        m1, v1 = meshed.posterior(p, xs)
        np.testing.assert_allclose(np.asarray(m0), np.asarray(m1), rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(v0), np.asarray(v1), rtol=1e-4,
                                   atol=1e-5)


class TestCompositeKernels:
    def test_spec_parsing_and_shapes(self):
        from pytensor_federated_tpu.models.gp import (
            kernel_components,
            kernel_hyper_shape,
        )

        assert kernel_components("sqexp") == ["sqexp"]
        assert kernel_components("sqexp+linear") == ["sqexp", "linear"]
        assert kernel_components("sqexp*matern32") == ["sqexp", "matern32"]
        assert kernel_hyper_shape("sqexp") == ()
        assert kernel_hyper_shape("sqexp+linear+matern52") == (3,)
        with pytest.raises(ValueError, match="mixes"):
            kernel_components("sqexp+linear*matern32")
        with pytest.raises(ValueError, match="unknown kernel"):
            kernel_components("sqexp+warp")

    def test_composite_equals_manual_combination(self):
        import jax.numpy as jnp

        from pytensor_federated_tpu.models.gp import (
            _linear,
            _matern32,
            _sqexp,
            get_kernel,
        )

        x1 = jnp.linspace(-1, 1, 7)
        x2 = jnp.linspace(-0.5, 1.5, 5)
        v = jnp.asarray([0.7, 1.3])
        ls = jnp.asarray([0.4, 2.0])
        ksum = get_kernel("sqexp+linear")(x1, x2, v, ls)
        manual = _sqexp(x1, x2, v[0], ls[0]) + _linear(x1, x2, v[1], ls[1])
        np.testing.assert_allclose(np.asarray(ksum), np.asarray(manual),
                                   rtol=1e-6)
        kprod = get_kernel("sqexp*matern32")(x1, x2, v, ls)
        manual_p = _sqexp(x1, x2, v[0], ls[0]) * _matern32(
            x1, x2, v[1], ls[1]
        )
        np.testing.assert_allclose(np.asarray(kprod), np.asarray(manual_p),
                                   rtol=1e-6)
        # scalar hypers broadcast to every component
        kb = get_kernel("sqexp+matern32")(x1, x2, 1.0, 0.5)
        manual_b = _sqexp(x1, x2, 1.0, 0.5) + _matern32(x1, x2, 1.0, 0.5)
        np.testing.assert_allclose(np.asarray(kb), np.asarray(manual_b),
                                   rtol=1e-6)

    def test_stationary_prior_diag(self):
        import jax.numpy as jnp

        from pytensor_federated_tpu.models.gp import stationary_prior_diag

        v = jnp.asarray([2.0, 3.0])
        assert float(stationary_prior_diag("sqexp+matern32", v)) == 5.0
        assert float(stationary_prior_diag("sqexp*matern32", v)) == 6.0
        assert float(stationary_prior_diag("sqexp", 2.0)) == 2.0
        with pytest.raises(ValueError, match="linear"):
            stationary_prior_diag("sqexp+linear", v)

    def test_exact_gp_trend_plus_wiggle(self):
        """sqexp+linear on trending data: the composite must out-fit
        plain sqexp at MAP (the trend otherwise eats the lengthscale),
        and the posterior must track the trend outside the data."""
        import jax.numpy as jnp

        from pytensor_federated_tpu.models.gp import FederatedExactGP
        from pytensor_federated_tpu.parallel.packing import pack_shards

        rng = np.random.default_rng(8)
        shards = []
        for _ in range(4):
            x = np.sort(rng.uniform(-2, 2, size=48)).astype(np.float32)
            y = (1.5 * x + 0.5 * np.sin(4 * x)
                 + 0.05 * rng.normal(size=48)).astype(np.float32)
            shards.append((x, y))
        data = pack_shards(shards)
        base = FederatedExactGP(data)
        comp = FederatedExactGP(data, kernel="sqexp+linear")
        assert comp.init_params()["log_variance"].shape == (2,)
        map_b = base.find_map(num_steps=200)
        map_c = comp.find_map(num_steps=200)
        assert float(comp.logp(map_c)) > float(base.logp(map_b))
        mean, var = comp.posterior(map_c, np.float32([2.5, 3.0]))
        # extrapolated mean keeps climbing with the trend
        assert np.all(np.asarray(mean)[:, 1] > np.asarray(mean)[:, 0])
        assert np.all(np.asarray(var) > 0)

    def test_sparse_gp_composite_matches_dense_golden(self):
        import jax.numpy as jnp

        from pytensor_federated_tpu.models.gp import (
            FederatedSparseGP,
            dense_vfe_logp,
            generate_gp_data,
        )

        data, pool = generate_gp_data(4, n_obs=32, seed=13)
        z = np.linspace(-2, 2, 10).astype(np.float32)
        spec = "sqexp+matern32"
        sgp = FederatedSparseGP(data, z, kernel=spec)
        p = {
            "log_variance": jnp.asarray([0.1, -0.3]),
            "log_lengthscale": jnp.asarray([-0.5, 0.2]),
            "log_noise": jnp.asarray(-1.0),
        }
        v_fed = float(sgp.logp(p))
        v_dense = float(
            dense_vfe_logp(p, pool[0], pool[1], z, kernel=spec)
        )
        np.testing.assert_allclose(v_fed, v_dense, rtol=2e-3)

    def test_sparse_gp_rejects_linear(self):
        from pytensor_federated_tpu.models.gp import (
            FederatedSparseGP,
            generate_gp_data,
        )

        data, _ = generate_gp_data(2, n_obs=8, seed=1)
        z = np.linspace(-1, 1, 4).astype(np.float32)
        with pytest.raises(ValueError, match="linear"):
            FederatedSparseGP(data, z, kernel="sqexp+linear")


def test_linear_kernel_rejects_vector_lengthscale_on_1d():
    import jax.numpy as jnp
    import pytest as _pytest

    from pytensor_federated_tpu.models.gp import _linear

    x = jnp.linspace(-1, 1, 4)
    with _pytest.raises(ValueError, match="scalar lengthscale"):
        _linear(x, x, 1.0, jnp.ones(4))


def test_jitter_scale_covers_product_composites():
    import jax.numpy as jnp

    from pytensor_federated_tpu.models.gp import _jitter_scale

    # product diag ~49 needs jitter scaled to ~49, not 14
    assert float(_jitter_scale(jnp.asarray([7.0, 7.0]))) == 49.0
    # single kernels bit-identical to the scalar case
    assert float(_jitter_scale(2.0)) == 2.0
    # sum-composites with sub-unit slots keep the sum bound
    assert float(_jitter_scale(jnp.asarray([0.5, 0.25]))) == 0.75


class TestPosteriorCovAndSampling:
    def test_exact_cov_diag_matches_var(self):
        from pytensor_federated_tpu.models.gp import (
            FederatedExactGP,
            generate_gp_data,
        )

        data, _ = generate_gp_data(3, n_obs=24, seed=6)
        gp = FederatedExactGP(data)
        p = gp.init_params()
        xs = np.linspace(-1.5, 1.5, 6).astype(np.float32)
        mean_d, var = gp.posterior(p, xs)
        mean_c, cov = gp.posterior(p, xs, return_cov=True)
        np.testing.assert_allclose(np.asarray(mean_d), np.asarray(mean_c),
                                   rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(var),
            np.diagonal(np.asarray(cov), axis1=1, axis2=2),
            rtol=1e-3, atol=1e-5,
        )
        # PSD: every shard's covariance has nonnegative eigenvalues
        eig = np.linalg.eigvalsh(np.asarray(cov))
        assert eig.min() > -1e-4

    def test_exact_sample_moments(self):
        from pytensor_federated_tpu.models.gp import (
            FederatedExactGP,
            generate_gp_data,
        )

        data, _ = generate_gp_data(2, n_obs=32, seed=9)
        gp = FederatedExactGP(data)
        p = gp.init_params()
        xs = np.linspace(-1, 1, 4).astype(np.float32)
        draws = gp.posterior_sample(
            p, jax.random.PRNGKey(0), xs, num_draws=4000
        )
        assert draws.shape == (4000, 2, 4)
        mean, var = gp.posterior(p, xs)
        np.testing.assert_allclose(
            draws.mean(axis=0), np.asarray(mean), atol=0.05
        )
        np.testing.assert_allclose(
            draws.var(axis=0), np.asarray(var), rtol=0.15, atol=0.01
        )

    def test_sparse_cov_diag_and_sampling(self):
        from pytensor_federated_tpu.models.gp import (
            FederatedSparseGP,
            generate_gp_data,
        )

        data, _ = generate_gp_data(4, n_obs=32, seed=4)
        z = np.linspace(-2, 2, 12).astype(np.float32)
        sgp = FederatedSparseGP(data, z)
        p = sgp.init_params()
        xs = np.linspace(-1.5, 1.5, 5).astype(np.float32)
        mean_d, var = sgp.posterior(p, xs)
        mean_c, cov = sgp.posterior(p, xs, return_cov=True)
        np.testing.assert_allclose(np.asarray(mean_d), np.asarray(mean_c),
                                   rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(var), np.diag(np.asarray(cov)), rtol=1e-3,
            atol=1e-5,
        )
        assert np.linalg.eigvalsh(np.asarray(cov)).min() > -1e-4
        draws = sgp.posterior_sample(
            p, jax.random.PRNGKey(1), xs, num_draws=3000
        )
        assert draws.shape == (3000, 5)
        np.testing.assert_allclose(
            draws.mean(axis=0), np.asarray(mean_d), atol=0.05
        )


class TestBlockedPosteriorChol:
    """ISSUE 19 equality gate: the posterior-draw Cholesky dispatches
    concrete large covariances onto the blocked factorization
    (``linalg.cholesky``) — the two paths must agree on the SAME
    matrix, and traced callers must always get the jnp fallback."""

    def _spd(self, n, seed=0):
        rng = np.random.default_rng(seed)
        m = rng.normal(size=(n, n)).astype(np.float32)
        return (m @ m.T / n + np.eye(n, dtype=np.float32))

    def test_blocked_path_matches_jnp_path(self, monkeypatch):
        from pytensor_federated_tpu.models import gp as gp_mod

        cov = jnp.asarray(self._spd(40, seed=21))
        vjit = jnp.float32(1e-4)
        ref = np.asarray(
            jnp.linalg.cholesky(cov + vjit * jnp.eye(40, dtype=cov.dtype))
        )
        monkeypatch.setattr(gp_mod, "_BLOCKED_CHOL_MIN", 8)
        blocked = np.asarray(gp_mod._posterior_chol(cov, vjit, block=16))
        np.testing.assert_allclose(blocked, ref, rtol=1e-4, atol=1e-5)

    def test_sparse_sample_identical_through_dispatch(self, monkeypatch):
        """The actual consumer: identical draws (same key) whether the
        covariance factors on the jnp or the blocked path."""
        from pytensor_federated_tpu.models import gp as gp_mod
        from pytensor_federated_tpu.models.gp import (
            FederatedSparseGP,
            generate_gp_data,
        )

        data, _ = generate_gp_data(4, n_obs=32, seed=4)
        z = np.linspace(-2, 2, 12).astype(np.float32)
        sgp = FederatedSparseGP(data, z)
        p = sgp.init_params()
        xs = np.linspace(-1.5, 1.5, 9).astype(np.float32)
        key = jax.random.PRNGKey(7)

        monkeypatch.setattr(gp_mod, "_BLOCKED_CHOL_MIN", 10**9)
        via_jnp = np.asarray(sgp.posterior_sample(p, key, xs, num_draws=3))
        monkeypatch.setattr(gp_mod, "_BLOCKED_CHOL_MIN", 2)
        via_blocked = np.asarray(
            sgp.posterior_sample(p, key, xs, num_draws=3)
        )
        np.testing.assert_allclose(
            via_blocked, via_jnp, rtol=1e-4, atol=1e-5
        )

    def test_traced_caller_gets_the_jnp_fallback(self, monkeypatch):
        from pytensor_federated_tpu.models import gp as gp_mod

        monkeypatch.setattr(gp_mod, "_BLOCKED_CHOL_MIN", 2)
        cov = jnp.asarray(self._spd(12, seed=22))
        vjit = jnp.float32(1e-4)
        eager = np.asarray(gp_mod._posterior_chol(cov, vjit))
        jitted = np.asarray(
            jax.jit(gp_mod._posterior_chol)(cov, vjit)
        )
        np.testing.assert_allclose(jitted, eager, rtol=1e-4, atol=1e-6)

    def test_batched_covariance_takes_fallback(self, monkeypatch):
        from pytensor_federated_tpu.models import gp as gp_mod

        monkeypatch.setattr(gp_mod, "_BLOCKED_CHOL_MIN", 2)
        cov = jnp.stack([jnp.asarray(self._spd(6, seed=s)) for s in (1, 2)])
        out = np.asarray(gp_mod._posterior_chol(cov, jnp.float32(1e-4)))
        assert out.shape == (2, 6, 6)
