"""FLOP accounting sanity: XLA's cost-model count vs closed-form counts.

The bench suite quotes ``flops_per_eval`` from XLA's cost analysis of
the compiled executable (flopcount.py).  These tests pin that number
against programs simple enough to count by hand, so a silent change in
the cost-model contract (units, fusion accounting) fails loudly instead
of corrupting every MFU in BENCH_SUITE.json.
"""

import jax
import jax.numpy as jnp
import pytest

from pytensor_federated_tpu.flopcount import (
    measured_matmul_peak,
    mfu,
    peak_flops,
    xla_flops_per_eval,
)


def test_matmul_exact_count():
    # (n,n) @ (n,n) is 2n^3 FLOPs by the standard convention.
    n = 128
    fl = xla_flops_per_eval(lambda a: a @ a, jnp.ones((n, n)))
    assert fl is not None
    assert fl == pytest.approx(2 * n**3, rel=0.02)


def test_batched_matvec_count():
    # vmapped (n,d) @ (d,) over c chains == one (n,d) @ (d,c): 2ndc.
    n, d, c = 256, 64, 8
    X = jnp.ones((n, d))
    fn = jax.vmap(lambda w: X @ w)
    fl = xla_flops_per_eval(fn, jnp.ones((c, d)))
    assert fl == pytest.approx(2 * n * d * c, rel=0.05)


def test_value_and_grad_adds_one_cotangent_matmul():
    # For loss(w) = sum((A @ w)^2) reverse mode adds exactly one
    # transposed matmul (grad = 2 A^T (A w), with A w reused from the
    # forward pass), so value_and_grad is ~2x the forward count.  Pins
    # that the cost model sees through jax's AD instead of re-deriving
    # the primal.
    n = 128
    A = jnp.ones((n, n))

    def loss(w):
        return jnp.sum((A @ w) ** 2)

    fwd = xla_flops_per_eval(loss, jnp.ones((n, n)))
    vg = xla_flops_per_eval(jax.value_and_grad(loss), jnp.ones((n, n)))
    assert 1.8 * fwd < vg < 2.5 * fwd


def test_flagship_model_flops_are_plausible():
    # The 8-shard flagship: 8 shards x 64 padded obs, a handful of
    # FLOPs per observation, times ~3 for the gradient — order kFLOP.
    # Guards against the count silently becoming per-chain-batch or
    # per-element.
    from jax.flatten_util import ravel_pytree

    from pytensor_federated_tpu.models.linear import (
        FederatedLinearRegression,
        generate_node_data,
    )

    data, _ = generate_node_data(8, n_obs=64, seed=123)
    model = FederatedLinearRegression(data)
    flat0, unravel = ravel_pytree(model.init_params())

    def fn(x):
        return jax.value_and_grad(lambda v: model.logp(unravel(v)))(x)

    fl = xla_flops_per_eval(fn, flat0)
    assert 2_000 < fl < 200_000


def test_mfu_fields_complete_and_unavailable_path():
    fields = mfu(1e6, 1000.0)
    assert fields["flops_per_sec"] == 1e9
    assert 0 < fields["mfu"] < 1
    assert "FLOP/s" in fields["mfu_basis"]
    none_fields = mfu(None, 1000.0)
    assert none_fields["mfu"] is None
    assert none_fields["flops_per_eval"] is None
    assert "unavailable" in none_fields["mfu_basis"]


def test_measured_peak_caches_and_is_positive():
    p1 = measured_matmul_peak(n=256)
    p2 = measured_matmul_peak(n=256)
    assert p1 == p2 > 1e9  # any machine does >1 GFLOP/s dense matmul
    peak, basis = peak_flops("cpu")
    assert peak > 1e9 and "roofline" in basis
