"""Fault-injection subsystem: plans, predicates, determinism, and the
in-process halves of every shim (the cross-process lanes are covered by
tests/test_chaos_e2e.py and test_native_node.py)."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from pytensor_federated_tpu import faultinject as fi
from pytensor_federated_tpu import telemetry
from pytensor_federated_tpu.telemetry import flightrec
from pytensor_federated_tpu.telemetry import spans as tspans

HERE = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    prev = tspans.set_enabled(True)
    prev_rec = flightrec.set_enabled(True)
    flightrec.clear()
    fi.uninstall()
    yield
    fi.uninstall()
    tspans.set_enabled(prev)
    flightrec.set_enabled(prev_rec)
    flightrec.clear()


# -- FaultPlan / FaultRule --------------------------------------------------


class TestPlan:
    def test_json_roundtrip(self):
        plan = fi.FaultPlan(
            [
                fi.FaultRule("delay", point="tcp.send", nth=3, delay_s=0.1),
                fi.FaultRule(
                    "corrupt_bytes", point="grpc.*", prob=0.5,
                    max_fires=2, peer="127.0.0.1:9",
                ),
            ],
            seed=11,
        )
        clone = fi.FaultPlan.from_json(plan.to_json())
        assert clone.to_dict() == plan.to_dict()
        assert clone.plan_id == plan.plan_id and clone.seed == 11

    def test_from_spec_file(self, tmp_path):
        plan = fi.FaultPlan([fi.FaultRule("disconnect", nth=1)], seed=2)
        path = tmp_path / "plan.json"
        path.write_text(plan.to_json())
        assert fi.FaultPlan.from_spec(str(path)).to_dict() == plan.to_dict()
        assert (
            fi.FaultPlan.from_spec(plan.to_json()).to_dict() == plan.to_dict()
        )

    def test_unknown_kind_and_field_are_loud(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            fi.FaultRule("meteor_strike")
        with pytest.raises(ValueError, match="unknown FaultRule fields"):
            fi.FaultRule.from_dict({"kind": "delay", "sneaky": 1})
        with pytest.raises(ValueError, match="rules"):
            fi.FaultPlan.from_json("{}")

    def test_nth_fires_exactly_once(self):
        plan = fi.FaultPlan([fi.FaultRule("delay", point="p", nth=3)])
        hits = [plan.decide("p") for _ in range(6)]
        assert [h is not None for h in hits] == [
            False, False, True, False, False, False,
        ]

    def test_every_and_max_fires(self):
        plan = fi.FaultPlan(
            [fi.FaultRule("delay", point="p", every=2, max_fires=2)]
        )
        hits = [plan.decide("p") is not None for _ in range(8)]
        assert hits == [False, True, False, True, False, False, False, False]

    def test_prob_is_seed_deterministic(self):
        def fires(seed):
            plan = fi.FaultPlan(
                [fi.FaultRule("delay", point="p", prob=0.5, max_fires=None)],
                seed=seed,
            )
            return [plan.decide("p") is not None for _ in range(32)]

        a, b, c = fires(7), fires(7), fires(8)
        assert a == b  # same seed, same schedule
        assert a != c  # different seed, different schedule
        assert any(a) and not all(a)

    def test_peer_and_point_patterns(self):
        rule = fi.FaultRule("delay", point="tcp.*", peer="127.0.0.1:90")
        plan = fi.FaultPlan([rule])
        assert plan.decide("grpc.send", "127.0.0.1:9000") is None
        assert plan.decide("tcp.send", "10.0.0.1:9000") is None
        assert plan.decide("tcp.send", "127.0.0.1:9000") is rule

    def test_one_fault_per_call_and_accounting(self):
        """Two rules covering the same call: only one APPLIES (earlier
        rules take priority), and ``fires`` counts applied faults —
        the invariant the chaos harness reconciles against fault.*
        events."""
        third = fi.FaultRule("disconnect", point="p", nth=3)
        always = fi.FaultRule("delay", point="p", max_fires=3)
        plan = fi.FaultPlan([third, always])
        fired = [plan.decide("p") for _ in range(6)]
        assert [f.kind if f else None for f in fired] == [
            "delay", "delay", "disconnect", "delay", None, None,
        ]
        assert plan.total_fires == 4

    def test_snapshot_counters(self):
        plan = fi.FaultPlan([fi.FaultRule("delay", point="p", nth=2)])
        plan.decide("p")
        plan.decide("p")
        snap = plan.snapshot()
        assert snap["total_fires"] == 1
        (r,) = snap["rules"]
        assert r["matches"] == 2 and r["fires"] == 1 and r["remaining"] == 0

    def test_native_spec_subset(self):
        plan = fi.FaultPlan(
            [
                fi.FaultRule("delay", nth=2, delay_s=0.05),
                fi.FaultRule("disconnect", nth=4),
                fi.FaultRule("truncate_frame", nth=6, cut_frac=0.25),
                fi.FaultRule("compute_error", nth=1),  # not native
                fi.FaultRule("delay", every=3),  # no nth anchor
            ]
        )
        assert plan.native_spec() == "delay:2:50,disconnect:4,truncate:6:25"


# -- runtime install / events ----------------------------------------------


class TestRuntime:
    def test_install_uninstall_and_events(self):
        plan = fi.FaultPlan([fi.FaultRule("delay", point="p", nth=1,
                                          delay_s=0.0)])
        assert fi.runtime.active_plan is None
        fi.install(plan)
        assert fi.runtime.active_plan is plan
        assert fi.decide("p") is not None
        fi.uninstall()
        assert fi.runtime.active_plan is None
        kinds = [e["kind"] for e in flightrec.events()]
        assert "fault.plan_installed" in kinds
        assert "fault.delay" in kinds
        assert "fault.plan_uninstalled" in kinds
        ev = next(
            e for e in flightrec.events() if e["kind"] == "fault.delay"
        )
        assert ev["plan"] == plan.plan_id and ev["point"] == "p"

    def test_env_activation_in_subprocess(self):
        """The cross-process lane: a child process importing the
        package with PFTPU_FAULT_PLAN set runs the plan."""
        plan = fi.FaultPlan(
            [fi.FaultRule("compute_error", point="server.compute", nth=1)],
            seed=5,
            plan_id="env-test",
        )
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["PFTPU_FAULT_PLAN"] = plan.to_json()
        out = subprocess.run(
            [
                sys.executable,
                "-c",
                "from pytensor_federated_tpu.faultinject import runtime;"
                "print(runtime.active_plan.plan_id)",
            ],
            env=env,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip() == "env-test"

    def test_malformed_env_plan_is_loud(self):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["PFTPU_FAULT_PLAN"] = "{not json"
        out = subprocess.run(
            [sys.executable, "-c", "import pytensor_federated_tpu.faultinject"],
            env=env,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert out.returncode != 0  # testing nothing must not look green

    def test_inapplicable_kind_is_loud(self):
        fi.install(
            fi.FaultPlan([fi.FaultRule("getload_garbage", point="p")])
        )
        with pytest.raises(fi.FaultPlanError):
            fi.runtime.filter_bytes("p", b"x")

    def test_getload_garbage_is_rejected_by_the_probe_decoder(self):
        """The injected GetLoad garbage is exactly the shape the PR-4
        guard exists for: unknown-fields-only proto that leniency would
        decode to the all-zero load."""
        from pytensor_federated_tpu.service.npproto_codec import (
            decode_get_load_result,
        )
        from pytensor_federated_tpu.service.npwire import WireError

        with pytest.raises(WireError):
            decode_get_load_result(fi.runtime.GETLOAD_GARBAGE)

    def test_probe_filter_forces_failed_probe_without_dialing(self):
        from pytensor_federated_tpu.routing import NodePool

        fi.install(
            fi.FaultPlan(
                [fi.FaultRule("drop", point="pool.probe", max_fires=4)]
            )
        )
        # A port nobody listens on: with the shim the probe fails FAST
        # (no dial, no timeout) and still feeds the breaker.
        pool = NodePool(
            [("127.0.0.1", 1)],
            breaker_kwargs=dict(failure_threshold=1, backoff_s=30.0),
            probe_timeout_s=30.0,
        )
        t0 = time.perf_counter()
        up = pool.probe_once()
        assert time.perf_counter() - t0 < 5.0  # never dialed
        assert up == 0
        (replica,) = pool.replicas
        assert replica.breaker.state == "open"
        assert any(
            e["kind"] == "fault.drop" for e in flightrec.events()
        )
        pool.close()


# -- TCP lane shims (in-process server thread) ------------------------------


def _start_tcp_server(compute=None, **kw):
    from pytensor_federated_tpu.service.tcp import serve_tcp_once

    if compute is None:
        def compute(x):
            return [2.0 * np.asarray(x)]

    holder = {}
    ready = threading.Event()

    def cb(p):
        holder["port"] = p
        ready.set()

    t = threading.Thread(
        target=serve_tcp_once,
        args=(compute,),
        kwargs=dict(ready_callback=cb, **kw),
        daemon=True,
    )
    t.start()
    assert ready.wait(10)
    return holder["port"]


class TestTcpShims:
    def test_delay_and_stall_are_bounded_and_recorded(self):
        port = _start_tcp_server(max_connections=1)
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        fi.install(
            fi.FaultPlan(
                [
                    fi.FaultRule("delay", point="tcp.send", nth=1,
                                 delay_s=0.05),
                    fi.FaultRule("stall", point="tcp.send", nth=2,
                                 stall_s=0.3),
                ]
            )
        )
        client = TcpArraysClient("127.0.0.1", port, retries=0)
        t0 = time.perf_counter()
        out = client.evaluate(np.arange(3.0))  # delayed
        np.testing.assert_array_equal(out[0], 2.0 * np.arange(3.0))
        assert time.perf_counter() - t0 >= 0.05
        t0 = time.perf_counter()
        out = client.evaluate(np.arange(3.0))  # mid-frame stall
        np.testing.assert_array_equal(out[0], 2.0 * np.arange(3.0))
        assert time.perf_counter() - t0 >= 0.3
        kinds = [e["kind"] for e in flightrec.events()]
        assert "fault.delay" in kinds and "fault.stall" in kinds
        client.close()

    def test_disconnect_fails_over_to_reconnect(self):
        port = _start_tcp_server(max_connections=2)
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        fi.install(
            fi.FaultPlan(
                [fi.FaultRule("disconnect", point="tcp.send", nth=1)]
            )
        )
        client = TcpArraysClient("127.0.0.1", port, retries=1)
        out = client.evaluate(np.arange(3.0))  # retry reconnects
        np.testing.assert_array_equal(out[0], 2.0 * np.arange(3.0))
        assert any(
            e["kind"] == "rpc.drop" for e in flightrec.events()
        ), "the injected disconnect should surface as a transport drop"
        client.close()

    def test_corrupt_request_header_yields_loud_error_reply(self):
        port = _start_tcp_server(max_connections=1)
        from pytensor_federated_tpu.service.tcp import (
            RemoteComputeError,
            TcpArraysClient,
        )

        fi.install(
            fi.FaultPlan(
                [fi.FaultRule("corrupt_bytes", point="tcp.send", nth=1)],
                seed=3,
            )
        )
        client = TcpArraysClient("127.0.0.1", port, retries=0)
        # Corrupted header region: either the server answers an in-band
        # decode-error reply (RemoteComputeError) or the uuid no longer
        # correlates (RuntimeError) — LOUD either way, never silence.
        with pytest.raises((RemoteComputeError, RuntimeError)):
            client.evaluate(np.arange(3.0))
        client.close()

    def test_truncated_reply_raises_wire_error(self):
        port = _start_tcp_server(max_connections=1)
        from pytensor_federated_tpu.service.npwire import WireError
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        fi.install(
            fi.FaultPlan(
                [fi.FaultRule("truncate_frame", point="tcp.recv", nth=1)]
            )
        )
        client = TcpArraysClient("127.0.0.1", port, retries=0)
        with pytest.raises(WireError):
            client.evaluate(np.arange(3.0))
        assert client._sock is None, (
            "a corrupt reply must close the connection (stale frames)"
        )
        client.close()

    def test_server_compute_error_is_in_band(self):
        port = _start_tcp_server(max_connections=1)
        from pytensor_federated_tpu.service.tcp import (
            RemoteComputeError,
            TcpArraysClient,
        )

        fi.install(
            fi.FaultPlan(
                [
                    fi.FaultRule(
                        "compute_error", point="server.compute", nth=1,
                        error="chaos says no",
                    )
                ]
            )
        )
        client = TcpArraysClient("127.0.0.1", port, retries=0)
        with pytest.raises(RemoteComputeError, match="chaos says no"):
            client.evaluate(np.arange(3.0))
        # The connection survives an in-band error:
        out = client.evaluate(np.arange(3.0))
        np.testing.assert_array_equal(out[0], 2.0 * np.arange(3.0))
        client.close()

    def test_duplicate_reply_desync_is_caught_by_correlation(self):
        port = _start_tcp_server(max_connections=1)
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        fi.install(
            fi.FaultPlan(
                [
                    fi.FaultRule(
                        "duplicate_reply", point="tcp.server.send", nth=1
                    )
                ]
            )
        )
        client = TcpArraysClient("127.0.0.1", port, retries=0)
        out = client.evaluate(np.arange(3.0))  # first copy correlates
        np.testing.assert_array_equal(out[0], 2.0 * np.arange(3.0))
        # The duplicate is now a stale frame ahead of the next reply:
        # the uuid check must refuse it and reset the connection.
        with pytest.raises(RuntimeError, match="uuid mismatch"):
            client.evaluate(np.ones(2))
        assert client._sock is None
        client.close()

    def test_corrupt_request_does_not_crash_the_server(self):
        """Robustness hardening that chaos forced: a mangled frame gets
        an in-band decode-error reply and the SAME connection keeps
        serving (previously the pure-Python server crashed)."""
        port = _start_tcp_server(max_connections=1)
        import socket as sk
        import struct
        import uuid as uuid_mod

        from pytensor_federated_tpu.service.npwire import (
            decode_arrays_all,
            encode_arrays,
        )

        with sk.create_connection(("127.0.0.1", port), timeout=10) as s:
            garbage = b"NOTAFRAME-at-all"
            s.sendall(struct.pack("<I", len(garbage)) + garbage)
            hdr = s.recv(4)
            (n,) = struct.unpack("<I", hdr)
            reply = b""
            while len(reply) < n:
                reply += s.recv(n - len(reply))
            _arr, _uuid, error, _t, _sp = decode_arrays_all(reply)
            assert error and "decode error" in error
            # same connection still serves real work
            uid = uuid_mod.uuid4().bytes
            req = encode_arrays([np.arange(3.0)], uuid=uid)
            s.sendall(struct.pack("<I", len(req)) + req)
            hdr = s.recv(4)
            (n,) = struct.unpack("<I", hdr)
            reply = b""
            while len(reply) < n:
                reply += s.recv(n - len(reply))
            arr, ruid, error, _t, _sp = decode_arrays_all(reply)
            assert error is None and ruid == uid
            np.testing.assert_array_equal(arr[0], 2.0 * np.arange(3.0))


# -- batcher seam -----------------------------------------------------------


class TestBatchSeam:
    def test_wrong_shape_falls_back_to_scalar_isolation(self):
        from pytensor_federated_tpu.service.batching import (
            execute_window_sync,
        )

        calls = {"batch": 0}

        def compute(x):
            return [2.0 * np.asarray(x)]

        def batch_fn(reqs):
            calls["batch"] += 1
            return [[2.0 * np.asarray(r[0])] for r in reqs]

        fi.install(
            fi.FaultPlan(
                [
                    fi.FaultRule(
                        "compute_wrong_shape",
                        point="server.compute_batch",
                        nth=1,
                    )
                ]
            )
        )
        reqs = [(np.full(2, float(i)),) for i in range(4)]
        outcomes = execute_window_sync(compute, batch_fn, reqs)
        assert calls["batch"] == 1  # the vectorized path ran (and lied)
        for i, out in enumerate(outcomes):
            assert not isinstance(out, Exception)
            np.testing.assert_array_equal(out[0], 2.0 * np.full(2, float(i)))
        kinds = [e["kind"] for e in flightrec.events()]
        assert "fault.compute_wrong_shape" in kinds
        assert "server.batch_fallback" in kinds, (
            "the wrong-count batch must take the scalar-fallback path"
        )


# -- incident bundle embedding ----------------------------------------------


class TestBundleEmbedding:
    def test_bundle_embeds_plan_and_report_renders_it(self, tmp_path):
        from pytensor_federated_tpu.telemetry.watchdog import (
            write_incident_bundle,
        )

        sys.path.insert(0, os.path.join(HERE, os.pardir, "tools"))
        try:
            import incident_report
        finally:
            sys.path.pop(0)

        plan = fi.FaultPlan(
            [fi.FaultRule("stall", point="tcp.send", nth=2, stall_s=1.0)],
            seed=9,
            plan_id="bundle-test",
        )
        fi.install(plan)
        plan.decide("tcp.send")
        plan.decide("tcp.send")  # fires
        path = write_incident_bundle("unit-test", dir=str(tmp_path))
        bundle = json.load(open(path))
        assert bundle["fault_plan"]["plan_id"] == "bundle-test"
        (rule,) = bundle["fault_plan"]["rules"]
        assert rule["fires"] == 1 and rule["remaining"] == 0

        md = incident_report.render_markdown(bundle)
        assert "Fault plan" in md and "bundle-test" in md and "stall" in md
        jl = incident_report.render_jsonl(bundle)
        first = json.loads(jl.splitlines()[0])
        assert first["fault_plan"]["plan_id"] == "bundle-test"

    def test_no_plan_keeps_bundles_clean(self, tmp_path):
        from pytensor_federated_tpu.telemetry.watchdog import (
            write_incident_bundle,
        )

        path = write_incident_bundle("unit-test", dir=str(tmp_path))
        assert "fault_plan" not in json.load(open(path))


class TestAsyncShimTwins:
    """Regression for the graftlint ``async-blocking`` findings: the
    GetLoad and probe shims used to be called SYNC from grpc.aio
    handlers, so a chaos ``delay`` rule slept on the event loop and
    froze every concurrent RPC (the PR-5 bug class).  The async twins
    must (a) match the sync shims' semantics and (b) actually yield."""

    def test_getload_filter_async_parity(self):
        import asyncio

        plan = fi.FaultPlan(
            [fi.FaultRule("getload_garbage", point="server.getload")],
            seed=0,
        )
        fi.install(plan)
        out = asyncio.run(fi.runtime.getload_filter_async())
        assert out == fi.runtime.GETLOAD_GARBAGE
        fi.uninstall()
        assert asyncio.run(fi.runtime.getload_filter_async()) is None

    def test_probe_filter_async_parity(self):
        import asyncio

        plan = fi.FaultPlan(
            [fi.FaultRule("drop", point="pool.probe")], seed=0
        )
        fi.install(plan)
        assert asyncio.run(fi.runtime.probe_filter_async("h:1")) is False
        fi.uninstall()
        assert asyncio.run(fi.runtime.probe_filter_async("h:1")) is True

    def test_async_twins_keep_the_loop_alive_through_delay(self):
        """A concurrent ticker must keep running WHILE the chaos delay
        is pending — the sync shims provably froze it (time.sleep)."""
        import asyncio

        plan = fi.FaultPlan(
            [
                fi.FaultRule(
                    "delay", point="server.getload", nth=1, delay_s=0.2
                ),
                fi.FaultRule(
                    "delay", point="pool.probe", nth=1, delay_s=0.2
                ),
            ],
            seed=1,
        )
        fi.install(plan)

        async def main():
            ticks = 0
            done = False

            async def ticker():
                nonlocal ticks
                while not done:
                    ticks += 1
                    await asyncio.sleep(0.01)

            t = asyncio.ensure_future(ticker())
            assert await fi.runtime.getload_filter_async() is None
            assert await fi.runtime.probe_filter_async("h:1") is True
            done = True
            await t
            return ticks

        ticks = asyncio.run(main())
        # two 0.2 s awaited delays -> the 10 ms ticker gets dozens of
        # turns; the old sync path would have allowed ~0.
        assert ticks >= 10


class TestCallShimmedAsync:
    """Regression for the graftflow transitive ``async-blocking``
    findings (PR 8): async handlers called the sync codecs inline, and
    the codecs hold ``filter_bytes`` seams whose delay kinds
    ``time.sleep`` — the PR-5 bug class, three frames down.
    ``call_shimmed_async`` is the fix: direct call on the production
    path, executor handoff whenever a plan is active (or the caller
    asks for the executor explicitly)."""

    def test_inline_fast_path_runs_in_caller_thread(self):
        import asyncio

        assert fi.runtime.active_plan is None

        async def main():
            return await fi.runtime.call_shimmed_async(
                threading.get_ident
            )

        assert asyncio.run(main()) == threading.get_ident()

    def test_active_plan_routes_to_executor(self):
        import asyncio

        plan = fi.FaultPlan(
            [fi.FaultRule("delay", point="nowhere", delay_s=0.0)], seed=0
        )
        fi.install(plan)
        try:

            async def main():
                return await fi.runtime.call_shimmed_async(
                    threading.get_ident
                )

            assert asyncio.run(main()) != threading.get_ident()
        finally:
            fi.uninstall()

    def test_inline_false_always_uses_executor(self):
        import asyncio

        async def main():
            return await fi.runtime.call_shimmed_async(
                threading.get_ident, inline=False
            )

        assert asyncio.run(main()) != threading.get_ident()

    def test_args_kwargs_and_exceptions_propagate(self):
        import asyncio

        def f(a, b=0):
            if b:
                raise ValueError("boom")
            return a + 1

        async def main():
            assert await fi.runtime.call_shimmed_async(f, 1) == 2
            with pytest.raises(ValueError, match="boom"):
                await fi.runtime.call_shimmed_async(f, 1, b=2)

        asyncio.run(main())

    def test_executor_hop_carries_contextvars(self):
        """The executor handoff must run under the caller's context
        (copy_context): the codecs read the ambient telemetry trace id
        (`_encode_request` -> spans.current_trace_id), and a bare
        worker thread would silently encode trace_id=None exactly
        during chaos runs — killing trace reunion when it matters
        most."""
        import asyncio

        plan = fi.FaultPlan(
            [fi.FaultRule("delay", point="nowhere", delay_s=0.0)], seed=0
        )
        fi.install(plan)
        try:

            async def main():
                with tspans.span("rpc.ctx_test"):
                    tid = tspans.current_trace_id()
                    hop = await fi.runtime.call_shimmed_async(
                        tspans.current_trace_id
                    )
                    return tid, hop

            tid, hop = asyncio.run(main())
            assert tid is not None
            assert hop == tid
        finally:
            fi.uninstall()

    def test_codec_delay_keeps_the_loop_alive(self):
        """The end-to-end shape of the fixed bug: a chaos delay at a
        codec byte seam must not freeze a concurrent ticker on the
        same loop."""
        import asyncio

        from pytensor_federated_tpu.service.npwire import encode_arrays

        plan = fi.FaultPlan(
            [
                fi.FaultRule(
                    "delay", point="npwire.encode", nth=1, delay_s=0.2
                )
            ],
            seed=2,
        )
        fi.install(plan)
        try:

            async def main():
                ticks = 0
                done = False

                async def ticker():
                    nonlocal ticks
                    while not done:
                        ticks += 1
                        await asyncio.sleep(0.01)

                t = asyncio.ensure_future(ticker())
                reply = await fi.runtime.call_shimmed_async(
                    encode_arrays, [np.zeros(2, np.float32)]
                )
                assert isinstance(reply, bytes)
                done = True
                await t
                return ticks

            assert asyncio.run(main()) >= 10
        finally:
            fi.uninstall()

    def test_transform_bytes_is_the_sleep_free_half(self):
        """The apply_to_bytes split: transform_bytes handles every
        non-sleeping kind identically and rejects delay/stall (those
        belong to sync apply_to_bytes / the awaited twins)."""
        rule = fi.FaultRule("truncate_frame", point="p", cut_frac=0.5)
        plan = fi.FaultPlan([rule], seed=0)
        (r,) = plan.rules
        out = fi.runtime.transform_bytes(r, b"abcdefgh", "p")
        assert out == b"abcdefgh"[: r.cut_at(8)]
        delay = fi.FaultPlan(
            [fi.FaultRule("delay", point="p", delay_s=9.0)], seed=0
        ).rules[0]
        with pytest.raises(fi.FaultPlanError):
            fi.runtime.transform_bytes(delay, b"x", "p")
