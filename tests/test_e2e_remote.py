"""End-to-end: MCMC over real gRPC nodes through differentiable ops.

The reference's crown integration test — PyMC sampling against a gRPC
server in a child process with posterior-accuracy assertions
(reference: test_wrapper_ops.py:80-118, slope = 2 +/- 0.1) — rebuilt on
this framework's stack: node pool -> LogpGradServiceClient ->
blackbox/fan-out op -> all-JAX sampler.
"""

import numpy as np
import pytest

PORTS = [29600, 29601]


def _serve_demo_node(port):
    from pytensor_federated_tpu.demos.demo_node import _run_one

    _run_one("127.0.0.1", port, 0.0)


@pytest.fixture(scope="module")
def demo_pool():
    from conftest import spawn_node_procs, wait_nodes_up

    procs = spawn_node_procs(_serve_demo_node, [(p,) for p in PORTS])
    wait_nodes_up(PORTS, timeout=60)
    yield PORTS
    for p in procs:
        p.terminate()
    for p in procs:
        p.join(timeout=5)


def test_remote_grad_matches_local_finite_difference(demo_pool):
    """The remote node's reported gradient must match finite differences
    of its reported logp (server-side autodiff sanity)."""
    from pytensor_federated_tpu.service import LogpGradServiceClient

    client = LogpGradServiceClient("127.0.0.1", demo_pool[0])
    i0, s0 = np.float32(1.0), np.float32(2.0)
    logp, (gi, gs) = client(i0, s0)
    eps = 1e-3
    logp_i, _ = client(np.float32(i0 + eps), s0)
    logp_s, _ = client(i0, np.float32(s0 + eps))
    np.testing.assert_allclose((logp_i - logp) / eps, gi, rtol=0.05, atol=0.5)
    np.testing.assert_allclose((logp_s - logp) / eps, gs, rtol=0.05, atol=0.5)


def test_mcmc_over_grpc_recovers_slope(demo_pool):
    """Posterior median slope = 2 +/- 0.15 sampling over the wire
    (reference: test_wrapper_ops.py:105-117)."""
    from pytensor_federated_tpu.demos.demo_model import run_remote

    res = run_remote("127.0.0.1", demo_pool, draws=400, parallel=True)
    slope = np.asarray(res.samples["slope"])
    assert abs(np.median(slope) - 2.0) < 0.15


def test_gradient_sampler_over_grpc(demo_pool):
    """HMC (gradient-using) kernel driven by remote grads."""
    import jax
    import jax.numpy as jnp

    from pytensor_federated_tpu.ops import ParallelLogpGrad
    from pytensor_federated_tpu.samplers import sample
    from pytensor_federated_tpu.service import LogpGradServiceClient

    cpu = jax.devices("cpu")[0]
    spec = (
        jax.ShapeDtypeStruct((), jnp.float32),
        jax.ShapeDtypeStruct((), jnp.float32),
    )
    clients = [
        LogpGradServiceClient("127.0.0.1", p).evaluate for p in demo_pool
    ]
    fanout = ParallelLogpGrad(clients, [spec] * len(clients))

    def logp(params):
        args = [(params["intercept"], params["slope"])] * len(clients)
        return fanout.total_logp(args)

    with jax.default_device(cpu):
        res = sample(
            logp,
            {"intercept": jnp.zeros(()), "slope": jnp.zeros(())},
            key=jax.random.PRNGKey(1),
            num_warmup=40,
            num_samples=40,
            num_chains=1,
            kernel="hmc",
            num_hmc_steps=4,
            jitter=0.3,
        )
    slope = np.asarray(res.samples["slope"])
    assert abs(np.median(slope) - 2.0) < 0.3
