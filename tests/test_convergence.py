"""Convergence-diagnostic known-answer tests.

The reference's workflow ends in an arviz summary over PyMC draws
(reference: test_wrapper_ops.py:112-117); these pin our on-device
split-R̂ / ESS / summary against cases with known behavior: iid draws
(R̂≈1, ESS≈N), an AR(1) chain with strong autocorrelation (ESS ≪ N,
near the closed-form N(1-ρ)/(1+ρ)), and separated chains (R̂ ≫ 1).
"""

import jax

from pytensor_federated_tpu._compat import enable_x64
import jax.numpy as jnp
import numpy as np

from pytensor_federated_tpu.samplers import (
    effective_sample_size,
    split_rhat,
    summary,
)

C, N = 4, 2000


def test_iid_draws_rhat_one_ess_full():
    rng = np.random.default_rng(0)
    draws = jnp.asarray(rng.normal(size=(C, N)), jnp.float32)
    r = float(split_rhat(draws))
    ess = float(effective_sample_size(draws))
    assert abs(r - 1.0) < 0.01, r
    # iid: ESS within ~25% of the true sample count.
    assert 0.75 * C * N < ess < 1.3 * C * N, ess


def test_ar1_ess_matches_closed_form():
    rho = 0.9
    rng = np.random.default_rng(1)
    x = np.zeros((C, N))
    eps = rng.normal(size=(C, N)) * np.sqrt(1 - rho**2)
    for t in range(1, N):
        x[:, t] = rho * x[:, t - 1] + eps[:, t]
    ess = float(effective_sample_size(jnp.asarray(x, jnp.float32)))
    expected = C * N * (1 - rho) / (1 + rho)  # ≈ 421
    assert 0.5 * expected < ess < 2.0 * expected, (ess, expected)
    assert ess < 0.15 * C * N  # far below the nominal count


def test_separated_chains_rhat_large():
    rng = np.random.default_rng(2)
    draws = rng.normal(size=(C, N)) + np.arange(C)[:, None] * 5.0
    r = float(split_rhat(jnp.asarray(draws, jnp.float32)))
    assert r > 2.0, r


def test_pytree_and_event_shapes():
    rng = np.random.default_rng(3)
    samples = {
        "scalar": jnp.asarray(rng.normal(size=(C, N)), jnp.float32),
        "vec": jnp.asarray(rng.normal(size=(C, N, 3)), jnp.float32),
    }
    s = summary(samples)
    assert s["rhat"]["scalar"].shape == ()
    assert s["rhat"]["vec"].shape == (3,)
    assert s["ess"]["vec"].shape == (3,)
    np.testing.assert_allclose(np.asarray(s["mean"]["scalar"]), 0.0, atol=0.05)
    for r in np.asarray(s["rhat"]["vec"]):
        assert abs(r - 1.0) < 0.02


def test_diagnostics_on_real_sampler_output():
    """End of the pipeline: NUTS draws from a correct sampler over a
    simple posterior should pass the standard thresholds."""
    from pytensor_federated_tpu.samplers import sample

    logp = lambda p: -0.5 * jnp.sum(p["x"] ** 2)
    res = sample(
        logp,
        {"x": jnp.zeros((2,))},
        key=jax.random.PRNGKey(0),
        num_warmup=300,
        num_samples=500,
        num_chains=4,
        jitter=0.5,
    )
    s = summary(res.samples)
    rhat = np.asarray(s["rhat"]["x"])
    ess = np.asarray(s["ess"]["x"])
    assert (rhat < 1.05).all(), rhat
    assert (ess > 200).all(), ess


def test_x64_large_location_small_scale():
    """Under enable_x64, diagnostics must not downcast: location ~1e5
    with sd ~1e-3 quantizes to garbage in float32."""
    with enable_x64():
        rng = np.random.default_rng(4)
        draws = jnp.asarray(
            1e5 + 1e-3 * rng.normal(size=(C, N)), jnp.float64
        )
        r = float(split_rhat(draws))
        ess = float(effective_sample_size(draws))
        assert abs(r - 1.0) < 0.01, r
        assert 0.75 * C * N < ess < 1.3 * C * N, ess


class TestHDI:
    def test_matches_normal_quantiles(self):
        # For a symmetric unimodal sample the HDI ~ central interval.
        rng = np.random.default_rng(0)
        draws = rng.normal(2.0, 1.0, size=(4, 5000))
        from pytensor_federated_tpu.samplers import hdi

        lo, hi = np.asarray(hdi(jnp.asarray(draws), 0.94))
        assert abs(lo - (2.0 - 1.881)) < 0.1   # z_{0.03} = 1.881
        assert abs(hi - (2.0 + 1.881)) < 0.1

    def test_skewed_hdi_narrower_than_central(self):
        rng = np.random.default_rng(1)
        draws = rng.gamma(2.0, 1.0, size=(2, 8000))
        from pytensor_federated_tpu.samplers import hdi

        lo, hi = np.asarray(hdi(jnp.asarray(draws), 0.9))
        q_lo, q_hi = np.quantile(draws, [0.05, 0.95])
        assert (hi - lo) < (q_hi - q_lo)
        assert lo >= 0.0 - 1e-6

    def test_vector_components_and_summary_key(self):
        rng = np.random.default_rng(2)
        samples = {"w": jnp.asarray(rng.normal(size=(2, 500, 3)))}
        from pytensor_federated_tpu.samplers import hdi, summary

        h = hdi(samples)
        assert h["w"].shape == (3, 2)
        s = summary(samples)
        assert "hdi" in s and s["hdi"]["w"].shape == (3, 2)
        assert np.all(np.asarray(h["w"][:, 0]) < np.asarray(h["w"][:, 1]))

    def test_invalid_prob_raises(self):
        import pytest as _pytest

        from pytensor_federated_tpu.samplers import hdi

        with _pytest.raises(ValueError):
            hdi({"x": jnp.zeros((2, 10))}, prob=1.5)


class TestRankNormalized:
    def test_agrees_on_wellbehaved_chains(self):
        rng = np.random.default_rng(0)
        samples = {"x": jnp.asarray(rng.normal(size=(4, 1000)))}
        from pytensor_federated_tpu.samplers import split_rhat

        plain = float(np.asarray(split_rhat(samples)["x"]))
        ranked = float(
            np.asarray(split_rhat(samples, rank_normalized=True)["x"])
        )
        assert abs(plain - ranked) < 0.01
        assert abs(ranked - 1.0) < 0.02

    def test_robust_to_infinite_variance(self):
        # Cauchy draws: plain R-hat is dominated by tail noise; the
        # rank-normalized version must still read "converged" for
        # well-mixed chains and detect a genuinely stuck chain.
        rng = np.random.default_rng(1)
        good = rng.standard_cauchy(size=(4, 2000))
        from pytensor_federated_tpu.samplers import split_rhat

        r_good = float(
            np.asarray(
                split_rhat({"x": jnp.asarray(good)}, rank_normalized=True)[
                    "x"
                ]
            )
        )
        assert r_good < 1.02

        bad = good.copy()
        bad[0] = bad[0] * 0.01 + 50.0  # one chain stuck far away
        r_bad = float(
            np.asarray(
                split_rhat({"x": jnp.asarray(bad)}, rank_normalized=True)[
                    "x"
                ]
            )
        )
        assert r_bad > 1.2  # far above the ~1.01 convergence line

    def test_rank_normalized_ess_positive(self):
        rng = np.random.default_rng(2)
        samples = {"x": jnp.asarray(rng.standard_cauchy(size=(2, 1000)))}
        from pytensor_federated_tpu.samplers import effective_sample_size

        ess = float(
            np.asarray(
                effective_sample_size(samples, rank_normalized=True)["x"]
            )
        )
        assert 100 < ess <= 2200


def test_tied_draws_do_not_inflate_rank_rhat():
    # Metropolis-style duplicated draws: average ranks keep z-scores
    # identical across chains; ordinal ranks would fabricate
    # between-chain variance.
    rng = np.random.default_rng(3)
    base = np.round(rng.normal(size=(1, 800)), 1)  # many ties
    samples = {"x": jnp.asarray(np.concatenate([base, base, base, base]))}
    from pytensor_federated_tpu.samplers import split_rhat

    r = float(np.asarray(split_rhat(samples, rank_normalized=True)["x"]))
    assert r < 1.01


def test_nan_draws_still_alarm_when_rank_normalized():
    rng = np.random.default_rng(4)
    draws = rng.normal(size=(4, 500))
    draws[2, 100:] = np.nan
    from pytensor_federated_tpu.samplers import split_rhat

    r = np.asarray(
        split_rhat({"x": jnp.asarray(draws)}, rank_normalized=True)["x"]
    )
    assert np.isnan(r)


class TestTailESS:
    def test_iid_chains_have_healthy_tail_ess(self):
        rng = np.random.default_rng(5)
        samples = {"x": jnp.asarray(rng.normal(size=(4, 1000)))}
        from pytensor_federated_tpu.samplers import tail_ess

        t = float(np.asarray(tail_ess(samples)["x"]))
        assert t > 1000  # iid: ESS ~ total draws

    def test_sticky_tails_detected(self):
        # Bulk mixes fine but tail excursions are long-lived: an AR(1)
        # process whose extremes persist. Tail ESS must be far below
        # the bulk ESS.
        rng = np.random.default_rng(6)
        n, c, rho = 4000, 4, 0.99
        eps = rng.normal(size=(c, n))
        x = np.zeros((c, n))
        for t_ in range(1, n):
            x[:, t_] = rho * x[:, t_ - 1] + np.sqrt(1 - rho**2) * eps[:, t_]
        from pytensor_federated_tpu.samplers import (
            effective_sample_size,
            tail_ess,
        )

        samples = {"x": jnp.asarray(x)}
        te = float(np.asarray(tail_ess(samples)["x"]))
        total = c * n
        assert te < 0.05 * total  # strongly autocorrelated tails

    def test_summary_includes_ess_tail(self):
        rng = np.random.default_rng(7)
        samples = {"x": jnp.asarray(rng.normal(size=(2, 400)))}
        from pytensor_federated_tpu.samplers import summary

        s = summary(samples)
        assert "ess_tail" in s and float(np.asarray(s["ess_tail"]["x"])) > 0


def test_tail_ess_nan_alarm():
    rng = np.random.default_rng(8)
    draws = rng.normal(size=(4, 500))
    draws[1, 300:] = np.nan
    from pytensor_federated_tpu.samplers import tail_ess

    t = np.asarray(tail_ess({"x": jnp.asarray(draws)})["x"])
    assert np.isnan(t)


def test_summary_rank_normalized_consistent_with_direct():
    rng = np.random.default_rng(9)
    samples = {"x": jnp.asarray(rng.standard_cauchy(size=(2, 600)))}
    from pytensor_federated_tpu.samplers import split_rhat, summary

    s = summary(samples, rank_normalized=True)
    direct = split_rhat(samples, rank_normalized=True)
    np.testing.assert_allclose(
        np.asarray(s["rhat"]["x"]), np.asarray(direct["x"]), rtol=1e-6
    )
