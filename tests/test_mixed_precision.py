"""bf16 matmul / f32 accumulate option on the GLM families.

The MXU's native format is bfloat16; ``compute_dtype=jnp.bfloat16``
runs the X @ w contraction (where the FLOPs are) in bf16 with float32
accumulation and keeps everything else float32.  These tests pin the
accuracy contract — ~1e-2 relative divergence from the pure-f32 path
(bf16 has 8 mantissa bits) — and that inference still works end to end.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu.models.countdata import (
    FederatedPoissonGLM,
    generate_count_data,
)
from pytensor_federated_tpu.models.logistic import (
    FederatedLogisticRegression,
    HierarchicalLogisticRegression,
    generate_hier_logistic_data,
    generate_logistic_data,
)
from pytensor_federated_tpu.models.robust import (
    FederatedRobustRegression,
    generate_robust_data,
)
from pytensor_federated_tpu.models.survival import (
    FederatedWeibullAFT,
    generate_survival_data,
)


def _perturbed(params, seed=3, scale=0.3):
    leaves, treedef = jax.tree_util.tree_flatten(params)
    key = jax.random.PRNGKey(seed)
    keys = jax.random.split(key, len(leaves))
    return jax.tree_util.tree_unflatten(
        treedef,
        [
            l + scale * jax.random.normal(k, jnp.shape(l))
            for l, k in zip(leaves, keys)
        ],
    )


CASES = [
    (
        FederatedLogisticRegression,
        lambda: generate_logistic_data(n_shards=8, n_obs=64, n_features=16),
    ),
    (
        HierarchicalLogisticRegression,
        lambda: generate_hier_logistic_data(8, n_obs=64, n_features=16),
    ),
    (
        FederatedPoissonGLM,
        lambda: generate_count_data(8, n_obs=64, n_features=8),
    ),
    (
        FederatedRobustRegression,
        lambda: generate_robust_data(8, n_obs=64, n_features=8),
    ),
    (
        FederatedWeibullAFT,
        lambda: generate_survival_data(8, n_obs=64, n_features=8),
    ),
]


@pytest.mark.parametrize(
    "cls,gen", CASES, ids=[c[0].__name__ for c in CASES]
)
def test_bf16_close_to_f32(cls, gen):
    data, _truth = gen()
    m32 = cls(data)
    m16 = cls(data, compute_dtype=jnp.bfloat16)
    p = _perturbed(m32.init_params())
    v32, g32 = m32.logp_and_grad(p)
    v16, g16 = m16.logp_and_grad(p)
    # bf16 matmul: ~1e-2 relative on the data term.
    np.testing.assert_allclose(float(v16), float(v32), rtol=2e-2)
    for k in g32:
        np.testing.assert_allclose(
            np.asarray(g16[k]),
            np.asarray(g32[k]),
            rtol=5e-2,
            atol=5e-2 * (1.0 + float(jnp.max(jnp.abs(g32[k])))),
        )


def test_bf16_map_still_recovers_truth():
    data, truth = generate_count_data(8, n_obs=96, n_features=3, seed=5)
    m = FederatedPoissonGLM(data, compute_dtype=jnp.bfloat16)
    est = m.find_map()
    np.testing.assert_allclose(np.asarray(est["w"]), truth["w"], atol=0.2)


def test_bf16_on_mesh(devices8):
    from pytensor_federated_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"shards": 8}, devices=devices8)
    data, _ = generate_logistic_data(n_shards=8, n_obs=32, n_features=8)
    m_mesh = FederatedLogisticRegression(
        data, mesh=mesh, compute_dtype=jnp.bfloat16
    )
    m_local = FederatedLogisticRegression(data, compute_dtype=jnp.bfloat16)
    p0 = m_local.init_params()
    np.testing.assert_allclose(
        float(m_mesh.logp(p0)), float(m_local.logp(p0)), rtol=1e-3
    )
