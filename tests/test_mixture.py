"""Federated Gaussian mixture: scipy golden, identifiability, recovery."""

import jax
import jax.numpy as jnp
import numpy as np
import scipy.stats

from pytensor_federated_tpu.models.mixture import (
    FederatedGaussianMixture,
    generate_mixture_data,
    mixture_loglik,
)


def test_loglik_matches_scipy_mixture():
    rng = np.random.default_rng(0)
    y = rng.normal(0, 2, size=50).astype(np.float32)
    mu = np.array([-1.0, 0.5, 2.0], np.float32)
    sigma = np.array([0.5, 1.0, 0.7], np.float32)
    w = np.array([0.2, 0.5, 0.3], np.float32)
    ours = np.asarray(
        mixture_loglik(
            jnp.asarray(y), jnp.log(jnp.asarray(w)), jnp.asarray(mu),
            jnp.asarray(sigma),
        )
    )
    dens = sum(
        wk * scipy.stats.norm.pdf(y, mk, sk)
        for wk, mk, sk in zip(w, mu, sigma)
    )
    np.testing.assert_allclose(ours, np.log(dens), rtol=2e-4, atol=2e-4)


def test_means_always_ordered():
    data, _ = generate_mixture_data(4, n_obs=64)
    m = FederatedGaussianMixture(data, n_components=3)
    rng = np.random.default_rng(1)
    for _ in range(5):
        p = jax.tree_util.tree_map(
            lambda a: jnp.asarray(
                np.asarray(a) + rng.normal(0, 2.0, np.shape(a)),
                jnp.result_type(a),
            ),
            m.init_params(),
        )
        mu, _sigma = m._components(p)
        assert np.all(np.diff(np.asarray(mu)) > 0)


def test_map_recovers_components_and_weights():
    data, truth = generate_mixture_data(8, n_obs=256, seed=3)
    m = FederatedGaussianMixture(data, n_components=3)
    est = m.find_map(num_steps=2000)
    mu, sigma = m._components(est)
    np.testing.assert_allclose(np.asarray(mu), truth["mu"], atol=0.3)
    np.testing.assert_allclose(np.asarray(sigma), truth["sigma"], atol=0.25)
    w_est = np.asarray(m.weights(est))
    np.testing.assert_allclose(w_est, truth["weights"], atol=0.12)


def test_per_shard_weights_differ():
    # the point of the family: sites can have different mixes
    data, truth = generate_mixture_data(8, n_obs=256, seed=5)
    m = FederatedGaussianMixture(data, n_components=3)
    est = m.find_map(num_steps=2000)
    w = np.asarray(m.weights(est))
    spread = w.max(axis=0) - w.min(axis=0)
    assert spread.max() > 0.15  # truly shard-specific, not collapsed


def test_predictive_and_pointwise_contracts():
    data, _ = generate_mixture_data(4, n_obs=64, seed=7)
    m = FederatedGaussianMixture(data, n_components=3)
    p0 = m.init_params()
    (y,), mask = data.tree()
    sim = m.predictive(p0, jax.random.PRNGKey(0))
    assert sim.shape == y.shape
    assert np.all(np.asarray(sim)[np.asarray(mask) == 0] == 0.0)
    ll = m.pointwise_loglik(p0)
    assert np.all(np.isfinite(np.asarray(ll)[np.asarray(mask) == 1]))
    assert np.all(np.asarray(ll)[np.asarray(mask) == 0] == 0.0)


def test_nuts_posterior_covers_truth():
    data, truth = generate_mixture_data(4, n_obs=192, seed=11)
    m = FederatedGaussianMixture(data, n_components=3)
    res = m.sample(
        key=jax.random.PRNGKey(2),
        num_warmup=300,
        num_samples=300,
        num_chains=2,
    )
    mus = np.stack(
        [
            np.asarray(m._components(p)[0])
            for p in _iter_draws(res.samples, 100)
        ]
    )
    np.testing.assert_allclose(mus.mean(axis=0), truth["mu"], atol=0.4)


def _iter_draws(samples, n):
    leaves, treedef = jax.tree_util.tree_flatten(samples)
    c, d = leaves[0].shape[:2]
    idx = np.linspace(0, c * d - 1, n).astype(int)
    flat = [np.asarray(a).reshape((c * d,) + a.shape[2:]) for a in leaves]
    for i in idx:
        yield jax.tree_util.tree_unflatten(
            treedef, [jnp.asarray(a[i]) for a in flat]
        )


def test_on_mesh(devices8):
    from pytensor_federated_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"shards": 8}, devices=devices8)
    data, _ = generate_mixture_data(8, n_obs=64, seed=13)
    m_mesh = FederatedGaussianMixture(data, n_components=3, mesh=mesh)
    m_local = FederatedGaussianMixture(data, n_components=3)
    p0 = m_local.init_params()
    np.testing.assert_allclose(
        float(m_mesh.logp(p0)), float(m_local.logp(p0)), rtol=5e-4
    )
