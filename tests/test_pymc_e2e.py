"""PyMC end-to-end parity — skips cleanly when pymc is not installed.

The reference's hardest integration is a PyMC model driving federated
ops through ``pm.Potential`` + ``find_MAP`` + MCMC.  These tests mirror
its coverage one-to-one:

- logp/dlogp equivalence between the federated model and a natively
  built PyMC model at several points (reference: test_demo_node.py:68-110);
- ``find_MAP`` equivalence (same reference test);
- end-to-end MCMC with posterior assertions against the true
  parameters (reference: test_wrapper_ops.py:291-317);
- posterior parity against this framework's own native NUTS sampler on
  the same data (net-new: the two stacks must agree, not just both
  "converge").

Both linker paths are exercised: the ``perform`` host-callable path
(default C/py linkers) and the ``jax_fn`` path.
"""

import numpy as np
import pytest

pm = pytest.importorskip("pymc")

from pytensor_federated_tpu.demos.demo_pymc import (  # noqa: E402
    build_model,
    build_native_model,
)
from pytensor_federated_tpu.models.linear import generate_node_data  # noqa: E402

N_SHARDS = 4
N_OBS = 48


@pytest.fixture(scope="module")
def data():
    packed, _offsets = generate_node_data(N_SHARDS, n_obs=N_OBS, seed=123)
    return packed


@pytest.fixture(scope="module", params=[True, False], ids=["jax_fn", "perform"])
def fed_model(request, data):
    return build_model(data, use_jax_fn=request.param)


@pytest.fixture(scope="module")
def native_model(data):
    return build_native_model(data)


def _test_points(model, n=4, seed=7):
    rng = np.random.default_rng(seed)
    ip = model.initial_point()
    points = [ip]
    for _ in range(n - 1):
        points.append(
            {k: v + rng.normal(0, 0.1, size=np.shape(v)) for k, v in ip.items()}
        )
    return points


class TestLogpParity:
    # Tolerances: PyMC computes in float64; the federated boundary is
    # float32 by TPU-first design (SURVEY §7 "hard parts" names this
    # dtype seam).  |logp| is O(100) here, so float32 gives ~1e-5
    # relative — tolerances sit an order of magnitude above that.

    def test_logp_matches_native(self, fed_model, native_model):
        f_logp = fed_model.compile_logp()
        n_logp = native_model.compile_logp()
        for pt_ in _test_points(fed_model):
            np.testing.assert_allclose(
                f_logp(pt_), n_logp(pt_), rtol=2e-4, atol=1e-3
            )

    def test_dlogp_matches_native(self, fed_model, native_model):
        f_dlogp = fed_model.compile_dlogp()
        n_dlogp = native_model.compile_dlogp()
        for pt_ in _test_points(fed_model):
            np.testing.assert_allclose(
                f_dlogp(pt_), n_dlogp(pt_), rtol=1e-3, atol=1e-2
            )


class TestFindMAP:
    def test_find_map_matches_native(self, fed_model, native_model):
        with fed_model:
            fed_map = pm.find_MAP(progressbar=False)
        with native_model:
            nat_map = pm.find_MAP(progressbar=False)
        for name in ("intercept", "slope", "sigma"):
            # float32 gradients shift the optimizer's stopping point a
            # little; parameter-scale agreement is what parity means.
            np.testing.assert_allclose(
                fed_map[name], nat_map[name], rtol=5e-3, atol=5e-3
            )

    def test_find_map_recovers_truth(self, fed_model):
        # generate_node_data truth: intercept 1.5, slope 2.0, sigma 0.5
        with fed_model:
            est = pm.find_MAP(progressbar=False)
        assert abs(float(est["slope"]) - 2.0) < 0.15
        assert abs(float(est["intercept"]) - 1.5) < 0.3
        assert abs(float(est["sigma"]) - 0.5) < 0.2


class TestEndToEndSampling:
    def test_mcmc_posterior(self, data):
        # Reference asserts the posterior median slope within +-0.1 of
        # truth after a short chain (test_wrapper_ops.py:291-317).
        model = build_model(data, use_jax_fn=True)
        with model:
            idata = pm.sample(
                draws=300,
                tune=300,
                chains=2,
                cores=1,
                progressbar=False,
                random_seed=42,
                compute_convergence_checks=False,
            )
        post = idata.posterior
        assert abs(float(post["slope"].median()) - 2.0) < 0.1
        assert abs(float(post["intercept"].median()) - 1.5) < 0.3

    def test_posterior_matches_native_framework_sampler(self, data):
        # The PyMC-driven posterior and this framework's own NUTS must
        # agree on the same data — cross-stack parity, not just
        # convergence.
        import jax

        from pytensor_federated_tpu.models.linear import (
            FederatedLinearRegression,
        )

        model = build_model(data, use_jax_fn=True)
        with model:
            idata = pm.sample(
                draws=400,
                tune=400,
                chains=2,
                cores=1,
                progressbar=False,
                random_seed=42,
                compute_convergence_checks=False,
            )
        post = idata.posterior

        fed = FederatedLinearRegression(data)
        res = fed.sample(
            key=jax.random.PRNGKey(5),
            num_warmup=400,
            num_samples=400,
            num_chains=2,
        )
        slope_native = np.asarray(res.samples["slope"]).mean()
        slope_pymc = float(post["slope"].mean())
        # Means agree within a couple posterior SDs of each other.
        sd = float(post["slope"].std()) + 1e-6
        assert abs(slope_pymc - slope_native) < 3 * sd


class TestDemoCLI:
    def test_demo_main_runs(self, data, monkeypatch):
        from pytensor_federated_tpu.demos import demo_pymc

        idata = demo_pymc.main(
            ["--n-shards", "2", "--n-obs", "32", "--draws", "50",
             "--tune", "50", "--chains", "1"]
        )
        assert "slope" in idata.posterior
