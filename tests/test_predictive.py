"""Predictive-sampling tests: posterior predictive over real NUTS draws
recovers the data distribution; prior predictive spans the prior."""

import jax
import jax.numpy as jnp
import numpy as np

from pytensor_federated_tpu.samplers import (
    posterior_predictive,
    prior_predictive,
    sample,
)


def test_posterior_predictive_recovers_data_distribution():
    """Conjugate-ish check: y ~ N(mu, 1), flat-ish prior; predictive
    draws should match the data's mean and spread."""
    rng = np.random.default_rng(0)
    y = jnp.asarray(rng.normal(3.0, 1.0, size=200), jnp.float32)

    logp = lambda p: jnp.sum(-0.5 * (y - p["mu"]) ** 2) - 0.5 * p["mu"] ** 2 / 100.0
    res = sample(
        logp,
        {"mu": jnp.zeros(())},
        key=jax.random.PRNGKey(1),
        num_warmup=200,
        num_samples=300,
        num_chains=2,
        jitter=0.2,
    )

    def predictive(params, key):
        return params["mu"] + jax.random.normal(key, (50,))

    sims = posterior_predictive(predictive, res.samples, jax.random.PRNGKey(2))
    assert sims.shape == (2 * 300, 50)
    assert abs(float(jnp.mean(sims)) - 3.0) < 0.15
    assert abs(float(jnp.std(sims)) - 1.0) < 0.1

    sub = posterior_predictive(
        predictive, res.samples, jax.random.PRNGKey(3), num_draws=100
    )
    assert sub.shape == (100, 50)
    assert abs(float(jnp.mean(sub)) - 3.0) < 0.2


def test_prior_predictive_spans_prior():
    def sample_prior(key):
        return {"mu": 5.0 * jax.random.normal(key)}

    def predictive(params, key):
        return params["mu"] + 0.1 * jax.random.normal(key, (10,))

    sims = prior_predictive(
        sample_prior, predictive, jax.random.PRNGKey(0), num_draws=2000
    )
    assert sims.shape == (2000, 10)
    # Spread dominated by the prior sd of 5.
    assert 4.0 < float(jnp.std(jnp.mean(sims, axis=1))) < 6.0


def test_predictive_pytree_output():
    samples = {"a": jnp.ones((2, 5)), "b": jnp.zeros((2, 5, 3))}

    def predictive(params, key):
        return {"y": params["a"] + jnp.sum(params["b"]), "n": jnp.ones(())}

    out = posterior_predictive(predictive, samples, jax.random.PRNGKey(0))
    assert out["y"].shape == (10,)
    assert out["n"].shape == (10,)
