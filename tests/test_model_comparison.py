"""WAIC / PSIS-LOO: analytic golden, diagnostics, and model ranking.

Golden: for iid Normal(mu, s2_known) data with a conjugate
Normal(mu0, t2) prior, the posterior and every leave-one-out posterior
are analytic, so the EXACT loo elpd is computable in closed form and
the PSIS estimate (from exact posterior draws) must match it.
"""

import jax
import numpy as np
import scipy.stats

from pytensor_federated_tpu.samplers.model_comparison import (
    compare,
    pointwise_loglik_matrix,
    psis_loo,
    waic,
)

S2 = 1.0  # known obs variance
T2 = 4.0  # prior variance
MU0 = 0.0


def _posterior(y):
    n = y.size
    prec = 1.0 / T2 + n / S2
    mean = (MU0 / T2 + y.sum() / S2) / prec
    return mean, 1.0 / prec


def _exact_loo_elpd(y):
    # leave-one-out posterior predictive of y_i is Normal with
    # moments from the posterior computed WITHOUT y_i.
    total = 0.0
    for i in range(y.size):
        y_rest = np.delete(y, i)
        m, v = _posterior(y_rest)
        total += scipy.stats.norm.logpdf(y[i], m, np.sqrt(v + S2))
    return total


def _draws_and_ll(y, n_draws=4000, seed=0):
    rng = np.random.default_rng(seed)
    m, v = _posterior(y)
    mus = rng.normal(m, np.sqrt(v), size=n_draws)
    # (n_draws, n_points) pointwise log-likelihoods
    ll = scipy.stats.norm.logpdf(
        y[None, :], mus[:, None], np.sqrt(S2)
    )
    return ll


def test_psis_loo_matches_exact_loo():
    rng = np.random.default_rng(42)
    y = rng.normal(1.2, np.sqrt(S2), size=40)
    ll = _draws_and_ll(y)
    res = psis_loo(ll)
    exact = _exact_loo_elpd(y)
    assert abs(res["elpd_loo"] - exact) < 0.3, (res["elpd_loo"], exact)
    # well-specified conjugate model: every Pareto k comfortably small
    assert res["n_bad_k"] == 0
    assert np.all(res["pareto_k"] < 0.5)


def test_waic_close_to_loo_for_regular_model():
    rng = np.random.default_rng(3)
    y = rng.normal(0.5, 1.0, size=60)
    ll = _draws_and_ll(y, seed=1)
    w = waic(ll)
    l_ = psis_loo(ll)
    # asymptotically equivalent; tight here because the model is iid
    assert abs(w["elpd_waic"] - l_["elpd_loo"]) < 0.3
    # effective parameter count ~ 1 (one scalar mean)
    assert 0.5 < w["p_waic"] < 1.8
    assert 0.5 < l_["p_loo"] < 1.8


def test_compare_ranks_true_model_first():
    rng = np.random.default_rng(7)
    y = rng.normal(0.8, 1.0, size=50)
    ll_good = _draws_and_ll(y, seed=2)
    # a deliberately wrong model: fixed mu = -3 (no posterior spread)
    mus_bad = np.full(4000, -3.0) + rng.normal(0, 0.01, size=4000)
    ll_bad = scipy.stats.norm.logpdf(y[None, :], mus_bad[:, None], 1.0)
    rows = compare({"true": ll_good, "wrong": ll_bad})
    assert rows[0]["model"] == "true"
    assert rows[1]["d_elpd"] < -5.0  # decisively worse
    assert rows[1]["d_se"] > 0


def test_end_to_end_on_a_family():
    from pytensor_federated_tpu.models.countdata import (
        FederatedNegBinGLM,
        FederatedPoissonGLM,
        generate_count_data,
    )

    data, _ = generate_count_data(4, n_obs=48, n_features=2, seed=5)
    mask = data.tree()[1]
    models = {}
    for name, cls in (
        ("poisson", FederatedPoissonGLM),
        ("negbin", FederatedNegBinGLM),
    ):
        m = cls(data)
        res = m.sample(
            key=jax.random.PRNGKey(1),
            num_warmup=150,
            num_samples=150,
            num_chains=2,
        )
        models[name] = pointwise_loglik_matrix(
            m.pointwise_loglik, res.samples, mask=mask
        )
    rows = compare(models)
    # Poisson data: Poisson must win or tie (NB nests it, so the elpd
    # difference must be small either way — well within 3 SEs).
    by_name = {r["model"]: r for r in rows}
    assert abs(by_name["negbin"]["d_elpd"]) < max(
        3.0 * by_name["negbin"]["d_se"], 3.0 * by_name["poisson"]["d_se"], 4.0
    )
    # point counts consistent: every kept point, no padding
    assert models["poisson"].shape[1] == int(np.asarray(mask).sum())


def test_gpd_fit_recovers_known_shape():
    # The smoothing and the k>0.7 diagnostic both live or die on this
    # fit being in the xi convention and weighted by +likelihood
    # (round-2 review caught a transposed weight matrix producing
    # k ~ -2.4 on data with true shape +0.4).
    from pytensor_federated_tpu.samplers.model_comparison import _gpd_fit

    rng = np.random.default_rng(0)
    for true_xi in (0.1, 0.4, 0.7):
        x = np.sort(
            scipy.stats.genpareto.rvs(
                true_xi, scale=1.0, size=4000, random_state=rng
            )
        )
        xi, sigma = _gpd_fit(x)
        assert abs(xi - true_xi) < 0.12, (true_xi, xi)
        assert abs(sigma - 1.0) < 0.25


def test_tie_heavy_tail_flags_instead_of_nan():
    # Duplicated draws (routine under Metropolis rejection) put >=25% of
    # the tail exceedances exactly at the cutoff; the Zhang-Stephens fit
    # then divides by a ~0 quartile, bs explodes and log1p(-bs*x) goes
    # NaN — and NaN pareto_k silently PASSES the k>0.7 check (NaN > 0.7
    # is False).  The guard must return k=inf so the point is flagged
    # and elpd_loo stays finite (round-2 advisor finding).
    from pytensor_federated_tpu.samplers.model_comparison import (
        _gpd_fit,
        _psis_smooth_tail,
    )

    # exceedances clamped at the floor in the lower quartile, a few real
    xi, sigma = _gpd_fit(
        np.sort(np.concatenate([np.full(60, 1e-30), [0.5, 1.0, 2.0]]))
    )
    assert np.isinf(xi)

    rng = np.random.default_rng(3)
    y = rng.normal(1.0, 1.0, size=20)
    ll = _draws_and_ll(y, n_draws=500, seed=4)
    # Metropolis-style duplication: one point's ratios take only 3 values
    ll[:, 0] = np.repeat([-0.3, -0.2, 2.5], [300, 195, 5])[:500]
    smoothed, k = _psis_smooth_tail(np.ascontiguousarray(ll[:, 0]))
    assert np.all(np.isfinite(smoothed))
    res = psis_loo(ll)
    assert np.isfinite(res["elpd_loo"])
    assert not np.any(np.isnan(res["pareto_k"]))


def test_pareto_k_flags_heavy_tails():
    # A point whose importance ratios are genuinely heavy-tailed must
    # produce a large k — the diagnostic must be able to fire (the
    # round-2 review found the sign/weight bugs made that impossible).
    rng = np.random.default_rng(5)
    y = rng.normal(1.0, 1.0, size=30)
    ll = _draws_and_ll(y, n_draws=2000, seed=9)  # well-behaved points
    # one pathological point: log-ratios with a Cauchy right tail
    t = rng.standard_cauchy(size=2000)
    ll[:, 0] = -np.abs(t) * 3.0
    res = psis_loo(ll)
    assert res["pareto_k"][0] > 0.7
    assert res["n_bad_k"] >= 1
    # conjugate-model points stay comfortably reliable
    assert np.median(res["pareto_k"][1:]) < 0.5
