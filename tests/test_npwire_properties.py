"""Property-based wire-format tests (hypothesis).

The reference pins its wire format with a 7-case dtype matrix
(reference: test_npproto.py:11-31); these properties cover the whole
space: any numeric/structured/datetime dtype, any shape incl. 0-d and
zero-length axes, any slicing (non-contiguity), and arbitrary byte
mutations must either round-trip exactly or fail loudly as WireError —
never return silently wrong arrays for a *truncated* payload.
"""

import numpy as np
import pytest

pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402
from hypothesis.extra import numpy as hnp  # noqa: E402

from pytensor_federated_tpu.service.npwire import (
    WireError,
    decode_arrays,
    decode_arrays_ex,
    encode_arrays,
)

# Cap example counts: the suite runs this file alongside slow
# distributed tests; 50 examples per property is plenty here.
COMMON = settings(max_examples=50, deadline=None)

_dtypes = st.one_of(
    hnp.integer_dtypes(endianness="="),
    hnp.unsigned_integer_dtypes(endianness="="),
    hnp.floating_dtypes(endianness="=", sizes=(32, 64)),
    hnp.complex_number_dtypes(endianness="="),
    hnp.datetime64_dtypes(endianness="="),
    hnp.timedelta64_dtypes(endianness="="),
    st.just(np.dtype("bool")),
)

_arrays = _dtypes.flatmap(
    lambda dt: hnp.arrays(
        dtype=dt,
        shape=hnp.array_shapes(min_dims=0, max_dims=4, min_side=0, max_side=8),
    )
)


@COMMON
@given(arrs=st.lists(_arrays, min_size=0, max_size=5))
def test_roundtrip_any_arrays(arrs):
    enc = encode_arrays(arrs)
    dec, uuid, error = decode_arrays(enc)
    assert error is None and len(uuid) == 16
    assert len(dec) == len(arrs)
    for a, b in zip(arrs, dec):
        assert a.dtype == b.dtype
        assert a.shape == b.shape
        np.testing.assert_array_equal(a, b)


@COMMON
@given(arr=_arrays, data=st.data())
def test_roundtrip_noncontiguous_views(arr, data):
    if arr.ndim == 0 or arr.size == 0:
        view = arr
    else:
        axis = data.draw(st.integers(0, arr.ndim - 1))
        sl = [slice(None)] * arr.ndim
        sl[axis] = slice(None, None, 2)
        view = arr[tuple(sl)].T  # strided + transposed
    (dec,), _, _ = decode_arrays(encode_arrays([view]))
    np.testing.assert_array_equal(np.ascontiguousarray(view), dec)
    assert dec.flags["C_CONTIGUOUS"] or dec.ndim == 0 or dec.size <= 1


@COMMON
@given(
    arrs=st.lists(_arrays, min_size=1, max_size=3),
    cut=st.floats(min_value=0.0, max_value=0.999),
)
def test_truncation_never_silently_wrong(arrs, cut):
    """Any strict prefix decodes to WireError, not garbage arrays."""
    enc = encode_arrays(arrs)
    prefix = enc[: int(len(enc) * cut)]
    if prefix == enc:  # pragma: no cover - cut<1 guarantees strict prefix
        return
    with pytest.raises(WireError):
        decode_arrays(prefix)


@COMMON
@given(arrs=st.lists(_arrays, min_size=0, max_size=3), err=st.text(max_size=200))
def test_error_frames_roundtrip(arrs, err):
    dec, _, error = decode_arrays(encode_arrays(arrs, error=err))
    assert error == err
    assert len(dec) == len(arrs)


@COMMON
@given(
    arrs=st.lists(_arrays, min_size=0, max_size=3),
    trace=st.binary(min_size=16, max_size=16),
    err=st.none() | st.text(max_size=100),
)
def test_trace_id_rides_and_is_ignorable(arrs, trace, err):
    """The telemetry trace block (flag bit 2) must round-trip through
    the extended decoder AND be consumed-and-dropped by the historical
    3-tuple decoder — for any arrays, any 16-byte id, with or without
    a coexisting error block."""
    enc = encode_arrays(arrs, error=err, trace_id=trace)
    dec, uuid, error, tid = decode_arrays_ex(enc)
    assert tid == trace and error == err and len(dec) == len(arrs)
    legacy_dec, legacy_uuid, legacy_err = decode_arrays(enc)
    assert legacy_uuid == uuid and legacy_err == err
    for a, b in zip(arrs, legacy_dec):
        np.testing.assert_array_equal(a, b)
    # absent trace id -> byte-identical pre-telemetry frame
    assert encode_arrays(arrs, uuid=uuid, error=err) == encode_arrays(
        arrs, uuid=uuid, error=err, trace_id=None
    )


@COMMON
@given(
    arrs=st.lists(_arrays, min_size=0, max_size=2),
    trace=st.binary(min_size=16, max_size=16),
    cut=st.floats(min_value=0.0, max_value=0.999),
)
def test_traced_truncation_never_silently_wrong(arrs, trace, cut):
    """Truncation anywhere in a trace-bearing frame — including inside
    the trace block itself — stays a loud WireError."""
    enc = encode_arrays(arrs, trace_id=trace)
    prefix = enc[: int(len(enc) * cut)]
    if prefix == enc:  # pragma: no cover - cut<1 guarantees strict prefix
        return
    with pytest.raises(WireError):
        decode_arrays_ex(prefix)


def test_structured_dtype_roundtrip():
    dt = np.dtype([("a", "<i4"), ("b", "<f8"), ("s", "S3")])
    arr = np.array([(1, 2.5, b"xy"), (-3, 0.0, b"zzz")], dtype=dt)
    (dec,), _, _ = decode_arrays(encode_arrays([arr]))
    assert dec.dtype == dt
    np.testing.assert_array_equal(arr, dec)


def test_subarray_structured_dtype_roundtrip():
    dt = np.dtype([("pos", "<f4", (3,)), ("id", "<i8")])
    arr = np.zeros(4, dtype=dt)
    arr["pos"] = np.arange(12.0).reshape(4, 3)
    arr["id"] = [7, 8, 9, 10]
    (dec,), _, _ = decode_arrays(encode_arrays([arr]))
    assert dec.dtype == dt
    np.testing.assert_array_equal(arr, dec)
