"""Flight recorder (telemetry/flightrec.py): ring semantics, the
open-span pinning/eviction contract (property-tested), span hooks,
dump lanes (demand / signal / crash), and the disabled fast path.
"""

import json
import os
import signal
import time

import pytest

from pytensor_federated_tpu import telemetry
from pytensor_federated_tpu.telemetry import flightrec
from pytensor_federated_tpu.telemetry import spans as tspans


@pytest.fixture(autouse=True)
def _clean(tmp_path, monkeypatch):
    monkeypatch.setenv("PFTPU_INCIDENT_DIR", str(tmp_path / "incidents"))
    prev = tspans.set_enabled(True)
    prev_rec = flightrec.set_enabled(True)
    flightrec.clear()
    flightrec.set_capacity(512)
    telemetry.clear_traces()
    yield
    tspans.set_enabled(prev)
    flightrec.set_enabled(prev_rec)
    flightrec.clear()
    flightrec.set_capacity(512)
    telemetry.clear_traces()


class TestRecord:
    def test_events_carry_seq_ts_kind_and_attrs(self):
        flightrec.record("unit.demo", a=1, b="x")
        (ev,) = flightrec.events()
        assert ev["kind"] == "unit.demo" and ev["a"] == 1 and ev["b"] == "x"
        assert ev["seq"] >= 1 and ev["ts"] > 0

    def test_active_trace_id_is_stamped(self):
        with telemetry.span("op"):
            tid = tspans.current_trace_id().hex()
            flightrec.record("unit.traced")
        traced = [
            e for e in flightrec.events() if e["kind"] == "unit.traced"
        ]
        assert traced[0]["trace_id"] == tid

    def test_ring_caps_and_keeps_newest(self):
        flightrec.set_capacity(8)
        for i in range(50):
            flightrec.record("unit.n", i=i)
        evs = flightrec.events()
        assert len(evs) == 8
        assert [e["i"] for e in evs] == list(range(42, 50))

    def test_disabled_records_nothing(self):
        flightrec.set_enabled(False)
        flightrec.record("unit.gone")
        with telemetry.span("op"):  # span hooks must also stand down
            pass
        assert flightrec.events() == []
        flightrec.set_enabled(True)
        # master telemetry switch wins even with the recorder on
        tspans.set_enabled(False)
        flightrec.record("unit.gone2")
        assert flightrec.events() == []

    def test_reserved_keys_survive_attr_collision(self):
        flightrec.record("unit.a")
        flightrec.record("unit.forged", seq=-1, ts=0.0, trace_id="spoof")
        with telemetry.span("op"):
            flightrec.record("unit.forged2", seq=-2, trace_id="spoof")
            real_tid = tspans.current_trace_id().hex()
        a, forged, forged2 = flightrec.events()[:3]
        assert forged["kind"] == "unit.forged"
        assert forged["seq"] == a["seq"] + 1  # monotonic, not -1
        assert forged["ts"] > 0
        # no ambient trace: the forged trace_id attr survives as data,
        # but under a live trace the AMBIENT id wins
        assert forged2["trace_id"] == real_tid

    def test_events_n_tail(self):
        for i in range(10):
            flightrec.record("unit.n", i=i)
        assert [e["i"] for e in flightrec.events(3)] == [7, 8, 9]


class TestSpanHooks:
    def test_open_close_pairs_in_order(self):
        with telemetry.span("outer"):
            with telemetry.span("inner"):
                pass
        kinds_names = [
            (e["kind"], e.get("name")) for e in flightrec.events()
        ]
        assert kinds_names == [
            ("span.open", "outer"),
            ("span.open", "inner"),
            ("span.close", "inner"),
            ("span.close", "outer"),
        ]

    def test_close_event_carries_duration_and_error(self):
        with pytest.raises(ValueError):
            with telemetry.span("boom"):
                raise ValueError("pop")
        (close,) = [
            e for e in flightrec.events() if e["kind"] == "span.close"
        ]
        assert close["duration_s"] >= 0
        assert close["error"] == "ValueError: pop"

    def test_still_open_span_visible_in_events(self):
        cm = telemetry.span("held")
        cm.__enter__()
        try:
            opens = [
                e
                for e in flightrec.events()
                if e["kind"] == "span.open" and e["name"] == "held"
            ]
            assert opens, "open event of a live span must be readable"
        finally:
            cm.__exit__(None, None, None)


class TestEvictionPinning:
    """The eviction contract: the span.open events of every still-open
    span — hence of a still-open span's whole ancestor chain — survive
    any amount of ring pressure."""

    def test_open_ancestry_survives_heavy_eviction(self):
        flightrec.set_capacity(4)
        outer = telemetry.span("anc.outer")
        outer.__enter__()
        mid = telemetry.span("anc.mid")
        mid.__enter__()
        try:
            for i in range(100):  # 100 noise events through a 4-ring
                flightrec.record("noise", i=i)
            open_names = {
                e["name"]
                for e in flightrec.events()
                if e["kind"] == "span.open"
            }
            assert {"anc.outer", "anc.mid"} <= open_names
        finally:
            mid.__exit__(None, None, None)
            outer.__exit__(None, None, None)

    def test_tail_trim_keeps_pinned_opens(self):
        """events(n) trims the RING tail but never the pinned opens —
        the incident-bundle path (flightrec_tail=256) must still show
        how a long-stuck operation started."""
        cm = telemetry.span("tail.open")
        cm.__enter__()
        try:
            for i in range(50):
                flightrec.record("noise", i=i)
            evs = flightrec.events(5)
            assert any(
                e["kind"] == "span.open" and e["name"] == "tail.open"
                for e in evs
            ), "tail-trim dropped the still-open span's start"
            # and the newest ring events are the trimmed tail
            noise = [e["i"] for e in evs if e["kind"] == "noise"]
            assert noise == list(range(45, 50))
        finally:
            cm.__exit__(None, None, None)

    def test_disable_while_open_still_unpins_on_close(self):
        """set_enabled(False) mid-span must not strand the pinned open
        event (it would report a closed span as open forever)."""
        cm = telemetry.span("leak.probe")
        cm.__enter__()
        flightrec.set_enabled(False)
        cm.__exit__(None, None, None)
        flightrec.set_enabled(True)
        flightrec.record("after")
        names = {
            e.get("name")
            for e in flightrec.events()
            if e["kind"] == "span.open"
        }
        assert "leak.probe" not in names

    def test_closed_spans_lose_pinning(self):
        flightrec.set_capacity(4)
        with telemetry.span("short"):
            pass
        for i in range(50):
            flightrec.record("noise", i=i)
        names = {
            e.get("name")
            for e in flightrec.events()
            if e["kind"] == "span.open"
        }
        assert "short" not in names  # evicted like any ring event

    @staticmethod
    def _check_interleaving(ops, cap):
        """Drive one open/close/noise interleaving and assert the
        invariant: every still-open span's open event (ancestors
        included — they are by construction still open) is present in
        events(), whatever the ring pressure."""
        flightrec.clear()
        flightrec.set_capacity(cap)
        stack = []  # the open-span chain (innermost last)
        counter = [0]
        try:
            for op in ops:
                if op == "open":
                    counter[0] += 1
                    cm = telemetry.span(f"p{counter[0]}")
                    cm.__enter__()
                    stack.append(cm)
                elif op == "close" and stack:
                    stack.pop().__exit__(None, None, None)
                else:
                    flightrec.record("noise")
            open_ids = {cm.span.span_id for cm in stack}
            seen_ids = {
                e["span_id"]
                for e in flightrec.events()
                if e["kind"] == "span.open"
            }
            assert open_ids <= seen_ids, (
                f"evicted open events of live spans (cap={cap}, "
                f"ops={ops}): {open_ids - seen_ids}"
            )
        finally:
            while stack:
                stack.pop().__exit__(None, None, None)

    def test_property_open_ancestors_never_evicted_seeded(self):
        """Seeded-random interleavings — runs in every environment
        (hypothesis is importorskip-gated in this container, same as
        tests/test_npwire_properties.py)."""
        import random

        rng = random.Random(20260802)
        for _ in range(120):
            cap = rng.randint(1, 6)
            ops = rng.choices(
                ["open", "close", "noise"],
                weights=[2, 1, 4],
                k=rng.randint(1, 120),
            )
            self._check_interleaving(ops, cap)

    def test_property_open_ancestors_never_evicted_hypothesis(self):
        """The same invariant under hypothesis shrinking, where
        available."""
        pytest.importorskip("hypothesis")
        from hypothesis import given, settings
        from hypothesis import strategies as st

        ops = st.lists(
            st.sampled_from(["open", "close", "noise"]),
            min_size=1,
            max_size=120,
        )

        @settings(max_examples=60, deadline=None)
        @given(ops=ops, cap=st.integers(min_value=1, max_value=6))
        def run(ops, cap):
            self._check_interleaving(ops, cap)

        run()


class TestDumpLanes:
    def test_dump_degrades_non_json_attrs(self, tmp_path):
        import numpy as np

        flightrec.record("unit.np", accept=np.float32(0.61))
        path = tmp_path / "np.jsonl"
        assert flightrec.dump_jsonl(str(path)) == 1
        (rec,) = [json.loads(l) for l in path.read_text().splitlines()]
        assert rec["accept"] == "0.61"  # default=str, never TypeError

    def test_dump_jsonl_appends_and_roundtrips(self, tmp_path):
        flightrec.record("unit.a", x=1)
        flightrec.record("unit.b")
        path = tmp_path / "rec.jsonl"
        n = flightrec.dump_jsonl(str(path))
        assert n == 2
        lines = [json.loads(l) for l in path.read_text().splitlines()]
        assert [l["kind"] for l in lines] == ["unit.a", "unit.b"]
        flightrec.dump_jsonl(str(path))  # append-mode
        assert len(path.read_text().splitlines()) == 4

    def test_signal_and_crash_handlers(self, tmp_path):
        import sys

        dump = tmp_path / "sig.jsonl"
        got = flightrec.install_handlers(str(dump), on_exit=False)
        assert got == str(dump)
        flightrec.record("unit.sig")
        os.kill(os.getpid(), signal.SIGUSR2)
        # The handler only SPAWNS the dumping thread (taking the
        # recorder lock in the handler frame could deadlock) — wait.
        deadline = time.time() + 10
        while not dump.exists() and time.time() < deadline:
            time.sleep(0.05)
        assert dump.exists(), "SIGUSR2 did not dump the flight record"
        assert any(
            json.loads(l)["kind"] == "unit.sig"
            for l in dump.read_text().splitlines()
        )
        # crash lane: the chained excepthook writes an incident bundle
        from pytensor_federated_tpu.telemetry import watchdog

        before = watchdog.last_incident_path()
        sys.excepthook(ValueError, ValueError("boom"), None)
        after = watchdog.last_incident_path()
        assert after and after != before
        with open(after) as fh:
            bundle = json.load(fh)
        assert bundle["reason"] == "crash"
        assert bundle["attrs"]["exc_type"] == "ValueError"
        # idempotent: a second install is a no-op returning a path
        assert flightrec.install_handlers(str(dump)) == str(dump)
