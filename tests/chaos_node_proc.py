"""Child process for tests/test_chaos_e2e.py: a plain npwire TCP node
(a script FILE, not a heredoc — CLAUDE.md spawn pitfall) computing
``2*x``.  Fault plans reach it ONLY via ``PFTPU_FAULT_PLAN`` in its
environment — the cross-process activation lane under test.

stdout protocol: ``PORT <n>`` once listening.
"""

import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from pytensor_federated_tpu.service.tcp import serve_tcp_once  # noqa: E402


def compute(*arrays):
    x = np.asarray(arrays[0], dtype=np.float64)
    return [2.0 * x]


def main() -> int:
    serve_tcp_once(
        compute,
        ready_callback=lambda port: print(f"PORT {port}", flush=True),
        concurrent=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
