"""Observability module (diagnostics.py)."""

import json
import logging
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu import diagnostics
from pytensor_federated_tpu.diagnostics import (
    Metrics,
    instrument_logp,
    log_device_load,
    profile_trace,
)


class TestMetrics:
    def test_counters_and_timers(self):
        m = Metrics()
        m.count("evals", 3)
        m.count("evals")
        with m.timed("step"):
            pass
        with m.timed("step"):
            pass
        snap = m.snapshot()
        assert snap["counters"]["evals"] == 4
        assert snap["timers"]["step"]["calls"] == 2
        assert snap["timers"]["step"]["total_s"] >= 0.0
        m.reset()
        assert m.snapshot() == {"counters": {}, "timers": {}}

    def test_thread_safety(self):
        m = Metrics()

        def worker():
            for _ in range(1000):
                m.count("n")

        ts = [threading.Thread(target=worker) for _ in range(8)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert m.snapshot()["counters"]["n"] == 8000

    def test_global_registry(self):
        diagnostics.metrics.reset()
        diagnostics.count("x")
        with diagnostics.timed("t"):
            pass
        snap = diagnostics.metrics.snapshot()
        assert snap["counters"]["x"] == 1
        assert "t" in snap["timers"]
        diagnostics.metrics.reset()


class TestInstrumentLogp:
    def test_counts_and_times(self):
        m = Metrics()

        def logp(params):
            return -0.5 * jnp.sum(params["x"] ** 2)

        wrapped = instrument_logp(jax.jit(logp), "logp", registry=m, block=True)
        p = {"x": jnp.ones(4)}
        for _ in range(5):
            wrapped(p)
        snap = m.snapshot()
        assert snap["counters"]["logp.evals"] == 5
        assert snap["timers"]["logp"]["calls"] == 5
        # Value passes through unchanged.
        np.testing.assert_allclose(float(wrapped(p)), -2.0)

    def test_composes_with_samplers(self):
        """Instrumented logp drives a sampler; counters reflect host
        dispatches (trace-time calls under jit)."""
        from pytensor_federated_tpu.samplers import ensemble_sample

        m = Metrics()

        def logp(params):
            return -0.5 * jnp.sum(params["x"] ** 2)

        wrapped = instrument_logp(logp, "fed", registry=m)
        res = ensemble_sample(
            wrapped,
            {"x": jnp.zeros(2)},
            key=jax.random.PRNGKey(0),
            n_walkers=16,
            num_warmup=50,
            num_samples=50,
        )
        assert res.samples["x"].shape == (50, 16, 2)
        # Under jit the wrapper sees trace-time calls only — but they
        # must be visible (>0) and finite.
        assert m.snapshot()["counters"]["fed.evals"] > 0


class TestLoadAndProfile:
    def test_log_device_load(self, caplog):
        logger = logging.getLogger("test_load")
        with caplog.at_level(logging.INFO, logger="test_load"):
            loads = log_device_load(logger)
        assert len(loads) == len(jax.devices())
        line = [r for r in caplog.records if "device_load" in r.message][0]
        payload = json.loads(line.message.split("device_load ")[1])
        assert "device_id" in payload and "platform" in payload

    def test_profile_trace_writes_files(self, tmp_path):
        d = str(tmp_path / "prof")
        with profile_trace(d):
            jax.block_until_ready(jnp.ones(16) * 2.0)
        # A trace directory with at least one event file must exist.
        found = []
        for root, _dirs, files in os.walk(d):
            found += [os.path.join(root, f) for f in files]
        assert found, "profiler produced no trace files"
