"""Demo CLI coverage: the collapsed on-mesh driver path.

The remote (gRPC) demo path is exercised end-to-end in
test_e2e_remote.py; this file covers the ``--local`` path — the
reference's two-process demo pair collapsed into one SPMD program
(reference: demo_node.py + demo_model.py) — and the argparse entry
point itself, so the installed ``pft-demo-model`` script can't rot.
"""

import numpy as np


def test_run_local_recovers_slope():
    from pytensor_federated_tpu.demos.demo_model import run_local

    res = run_local(n_shards=8, draws=150)
    slope = np.median(np.asarray(res.samples["slope"]))
    assert abs(slope - 2.0) < 0.15


def test_demo_model_main_local():
    from pytensor_federated_tpu.demos import demo_model

    demo_model.main(["--local", "--draws", "60"])


def test_demo_node_main_parses():
    """Node CLI parses args without binding (smoke for the entry point:
    run_node_pool is exercised for real by test_e2e_remote's pool)."""
    import pytest

    from pytensor_federated_tpu.demos import demo_node

    with pytest.raises(SystemExit) as e:
        demo_node.main(["--ports"])  # missing value
    assert e.value.code != 0
