"""Demo CLI coverage: the collapsed on-mesh driver path.

The remote (gRPC) demo path is exercised end-to-end in
test_e2e_remote.py; this file covers the ``--local`` path — the
reference's two-process demo pair collapsed into one SPMD program
(reference: demo_node.py + demo_model.py) — and the argparse entry
point itself, so the installed ``pft-demo-model`` script can't rot.
"""

import numpy as np


def test_run_local_recovers_slope():
    from pytensor_federated_tpu.demos.demo_model import run_local

    res = run_local(n_shards=8, draws=150)
    slope = np.median(np.asarray(res.samples["slope"]))
    assert abs(slope - 2.0) < 0.15


def test_demo_model_main_local():
    from pytensor_federated_tpu.demos import demo_model

    demo_model.main(["--local", "--draws", "60"])


def test_demo_node_main_parses():
    """Node CLI parses args without binding (smoke for the entry point:
    run_node_pool is exercised for real by test_e2e_remote's pool)."""
    import pytest

    from pytensor_federated_tpu.demos import demo_node

    with pytest.raises(SystemExit) as e:
        demo_node.main(["--ports"])  # missing value
    assert e.value.code != 0


def test_node_pool_npproto_wire():
    """pft-demo-node --getload-wire npproto: the pool serves
    reference-format GetLoad AND a reference-wire client evaluates
    against it (balancing included)."""
    import multiprocessing as mp
    import socket

    import numpy as np
    from conftest import scrubbed_child_env

    from pytensor_federated_tpu.demos.demo_node import run_node_pool
    from pytensor_federated_tpu.service import ArraysToArraysServiceClient

    # Both probe sockets stay open until BOTH ports are drawn, else the
    # kernel can hand the second bind the port the first just released
    # (the test_native_node._free_ports pattern).
    socks = [socket.socket(), socket.socket()]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    with scrubbed_child_env():
        ctx = mp.get_context("spawn")
        proc = ctx.Process(
            target=run_node_pool,
            args=("127.0.0.1", ports),
            kwargs={"getload_wire": "npproto"},
            daemon=False,
        )
        proc.start()
    try:
        from conftest import wait_nodes_up

        wait_nodes_up(ports, timeout=60)
        client = ArraysToArraysServiceClient(
            hosts_and_ports=[("127.0.0.1", p) for p in ports],
            codec="npproto",
        )
        out = client.evaluate(np.float64(1.5), np.float64(2.0))
        # [logp, dlogp/dintercept, dlogp/dslope] at the true params
        assert len(out) == 3 and np.shape(out[0]) == ()
        assert np.isfinite(float(out[0]))
    finally:
        proc.terminate()
        proc.join(timeout=10)
