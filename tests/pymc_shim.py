"""Test-only fake ``pymc`` — executes demo_pymc.py without pymc.

Builds on :mod:`pytensor_shim`.  The fake implements exactly the pymc
surface ``demos/demo_pymc.py`` touches — ``Model`` (context manager),
``Normal`` / ``HalfNormal`` free RVs, observed ``Normal`` likelihoods,
``Potential``, ``find_MAP``, ``sample`` — by RECORDING the model and
delegating the actual numerics to this framework's own machinery:

- graphs lower to JAX through the shim's ``compile_graph_to_jax``,
  which consumes the bridge's REAL ``jax_funcify`` registrations the
  same way pytensor's JAX linker would (what ``pm.sample(...,
  nuts_sampler="numpyro")`` exercises in the real stack);
- ``find_MAP`` delegates to ``samplers.mcmc.find_map`` (Adam);
- ``sample`` delegates to ``samplers.mcmc.sample`` (NUTS) in
  unconstrained space — HalfNormal RVs get the log transform with its
  Jacobian term, the same reparameterization pymc applies.

WHAT THIS PROVES: that demo_pymc's model-building and driver code
executes and yields the right posterior against the framework's own
samplers.  It does NOT prove real-pymc compatibility (transform
conventions, RV naming, idata layout are all simplified here).
"""

from __future__ import annotations

import math
import sys
import types
from contextlib import contextmanager

import numpy as np

import pytensor_shim as pts

_LOG_2PI = math.log(2.0 * math.pi)

# ---------------------------------------------------------------------------
# model recording
# ---------------------------------------------------------------------------

_MODEL_STACK: list = []


def _current_model():
    if not _MODEL_STACK:
        raise TypeError("No model on context stack")
    return _MODEL_STACK[-1]


class _FreeRV:
    def __init__(self, name, var, shape, transform, logprior):
        self.name = name
        self.var = var  # shim Variable, CONSTRAINED value
        self.shape = shape
        self.transform = transform  # "identity" | "log"
        self.logprior = logprior  # constrained value -> scalar (jnp)


class Model:
    def __init__(self):
        self.free_rvs: list[_FreeRV] = []
        self.potentials: list = []  # shim Variables (scalar)
        self.observed: list = []  # (mu_var, sigma_var, data)

    def __enter__(self):
        _MODEL_STACK.append(self)
        return self

    def __exit__(self, *exc):
        _MODEL_STACK.pop()
        return False

    # -- lowering to a JAX logp over the unconstrained space ----------------

    def _compiled_graph_parts(self):
        """One compile of every graph output the logp needs:
        [*potentials, *observed mu, *observed sigma], as a function of
        the free RVs' CONSTRAINED values."""
        jax_funcify = sys.modules["pytensor.link.jax.dispatch"].jax_funcify
        inputs = [rv.var for rv in self.free_rvs]
        outputs = list(self.potentials)
        for mu, sigma, _ in self.observed:
            outputs.append(pts.as_tensor_variable(mu))
            outputs.append(pts.as_tensor_variable(sigma))
        return pts.compile_graph_to_jax(outputs, inputs, jax_funcify)

    def logp_fn(self):
        """Unconstrained param dict -> total model logp (jax scalar)."""
        import jax.numpy as jnp

        graph_fn = self._compiled_graph_parts()
        free_rvs = list(self.free_rvs)
        observed = list(self.observed)
        n_pot = len(self.potentials)

        def logp(u):
            total = 0.0
            constrained = []
            for rv in free_rvs:
                val = u[rv.name]
                if rv.transform == "log":
                    x = jnp.exp(val)
                    # |dx/du| = e^u: the standard log-transform Jacobian
                    total = total + jnp.sum(val)
                else:
                    x = val
                constrained.append(x)
                total = total + rv.logprior(x)
            parts = graph_fn(*constrained)
            for p in parts[:n_pot]:
                total = total + jnp.sum(p)
            for k, (_, _, data) in enumerate(observed):
                mu = parts[n_pot + 2 * k]
                sigma = parts[n_pot + 2 * k + 1]
                z = (jnp.asarray(data) - mu) / sigma
                total = total + jnp.sum(
                    -0.5 * z * z - jnp.log(sigma) - 0.5 * _LOG_2PI
                )
            return total

        return logp

    def initial_unconstrained(self):
        init = {}
        for rv in self.free_rvs:
            init[rv.name] = np.zeros(rv.shape, dtype=np.float32)
        return init

    def constrain(self, u):
        """Map an unconstrained draw dict to constrained values."""
        out = {}
        for rv in self.free_rvs:
            val = np.asarray(u[rv.name])
            out[rv.name] = np.exp(val) if rv.transform == "log" else val
        return out


# ---------------------------------------------------------------------------
# distributions
# ---------------------------------------------------------------------------


def _shape_tuple(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


def Normal(name, mu=0.0, sigma=1.0, shape=None, observed=None):
    model = _current_model()
    if observed is not None:
        # Observed likelihood: mu/sigma may be graph expressions over
        # the free RVs (build_native_model's per-shard likelihoods).
        model.observed.append(
            (pts.as_tensor_variable(mu), pts.as_tensor_variable(sigma),
             np.asarray(observed))
        )
        return None
    if not isinstance(mu, (int, float)) or not isinstance(sigma, (int, float)):
        raise NotImplementedError("shim prior params must be scalars")
    shp = _shape_tuple(shape)
    var = pts.TensorType("float32", shp)(name=name)

    def logprior(x, mu=float(mu), sigma=float(sigma)):
        import jax.numpy as jnp

        z = (x - mu) / sigma
        return jnp.sum(-0.5 * z * z - jnp.log(sigma) - 0.5 * _LOG_2PI)

    model.free_rvs.append(_FreeRV(name, var, shp, "identity", logprior))
    return var


def HalfNormal(name, sigma=1.0, shape=None):
    model = _current_model()
    shp = _shape_tuple(shape)
    var = pts.TensorType("float32", shp)(name=name)

    def logprior(x, sigma=float(sigma)):
        import jax.numpy as jnp

        # HalfNormal(sigma) on the CONSTRAINED value x > 0.
        return jnp.sum(
            0.5 * math.log(2.0 / math.pi)
            - jnp.log(sigma)
            - 0.5 * (x / sigma) ** 2
        )

    model.free_rvs.append(_FreeRV(name, var, shp, "log", logprior))
    return var


def Potential(name, var):
    model = _current_model()
    model.potentials.append(var)
    return var


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------


def find_MAP(progressbar=True, model=None):
    model = model or _current_model()
    from pytensor_federated_tpu.samplers.mcmc import find_map

    logp = model.logp_fn()
    u = find_map(
        logp, model.initial_unconstrained(), num_steps=600,
        learning_rate=0.05,
    )
    out = {}
    for name, val in model.constrain(u).items():
        val = np.asarray(val)
        out[name] = float(val) if val.ndim == 0 else val
    return out


class _PostArray:
    def __init__(self, arr):
        self.arr = np.asarray(arr)  # (chains, draws, *shape)

    def median(self):
        return np.median(self.arr)

    def mean(self):
        return np.mean(self.arr)

    def __array__(self, dtype=None):
        a = self.arr
        return a.astype(dtype) if dtype is not None else a


class _InferenceData:
    def __init__(self, posterior):
        self.posterior = posterior


def sample(
    draws=1000,
    tune=1000,
    chains=4,
    cores=None,
    progressbar=True,
    random_seed=None,
    model=None,
    **kwargs,
):
    model = model or _current_model()
    import jax

    from pytensor_federated_tpu.samplers.mcmc import sample as pft_sample

    logp = model.logp_fn()
    key = jax.random.PRNGKey(0 if random_seed is None else int(random_seed))
    res = pft_sample(
        logp,
        model.initial_unconstrained(),
        key=key,
        num_warmup=int(tune),
        num_samples=int(draws),
        num_chains=int(chains),
        kernel="nuts",
    )
    posterior = {}
    for rv in model.free_rvs:
        arr = np.asarray(res.samples[rv.name])  # (chains, draws, *shape)
        if rv.transform == "log":
            arr = np.exp(arr)
        posterior[rv.name] = _PostArray(arr)
    return _InferenceData(posterior)


# ---------------------------------------------------------------------------
# installation
# ---------------------------------------------------------------------------


@contextmanager
def demo_pymc_under_shims():
    """pytensor shim + fake pymc + a fresh import of the REAL
    demos/demo_pymc.py; yields (demo module, bridge namespace)."""
    import importlib

    with pts.bridge_under_shim() as ns:
        pymc = types.ModuleType("pymc")
        pymc.Model = Model
        pymc.Normal = Normal
        pymc.HalfNormal = HalfNormal
        pymc.Potential = Potential
        pymc.find_MAP = find_MAP
        pymc.sample = sample
        sys.modules["pymc"] = pymc
        try:
            demo = importlib.import_module(
                "pytensor_federated_tpu.demos.demo_pymc"
            )
            yield types.SimpleNamespace(demo=demo, pymc=pymc, bridge=ns)
        finally:
            sys.modules.pop("pymc", None)
