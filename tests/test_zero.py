"""ZeRO-style sharded-gradient evaluator tests.

Pins the reduce-scattered path against the replicated psum path (same
numbers, different byte placement) — the redesign of the reference's
always-dense gradient exchange (reference: common.py:26-49) following
the cross-replica weight-update sharding recipe (PAPERS.md,
arXiv:2004.13336).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu.parallel import (
    FederatedLogp,
    ZeroShardedLogpGrad,
    make_mesh,
)

D = 37  # deliberately not divisible by 8: exercises padding


def _per_shard(params, shard):
    Xs, ys = shard
    return -0.5 * jnp.sum((ys - (Xs @ params["w"] + params["b"])) ** 2)


def _data(n_shards, seed=0):
    rng = np.random.default_rng(seed)
    X = jnp.asarray(rng.normal(size=(n_shards, 16, D)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(n_shards, 16)), jnp.float32)
    return X, y


P0 = {"w": jnp.zeros((D,)), "b": jnp.zeros(())}


def test_scattered_grad_matches_replicated(mesh8):
    X, y = _data(8)
    fed = FederatedLogp(_per_shard, (X, y), mesh=mesh8)
    v_ref, g_ref = fed.logp_and_grad(P0)

    z = ZeroShardedLogpGrad(_per_shard, (X, y), P0, mesh=mesh8)
    sg = z.logp_and_scattered_grad(P0)
    np.testing.assert_allclose(float(sg.logp), float(v_ref), rtol=1e-5)
    # Device slices really are sharded along the axis.
    assert sg.grad_slices.shape == (z.padded_dim,)
    assert z.padded_dim == 40 and z.dim == D + 1
    g_full = z.gather_grad(sg)
    np.testing.assert_allclose(
        np.asarray(g_full["w"]), np.asarray(g_ref["w"]), rtol=1e-4
    )
    np.testing.assert_allclose(
        float(g_full["b"]), float(g_ref["b"]), rtol=1e-4
    )


def test_multiple_shards_per_device(mesh8):
    """n_shards > axis size: each device vmaps its local block."""
    X, y = _data(16, seed=1)
    fed = FederatedLogp(_per_shard, (X, y), mesh=mesh8)
    _, g_ref = fed.logp_and_grad(P0)
    z = ZeroShardedLogpGrad(_per_shard, (X, y), P0, mesh=mesh8)
    g_full = z.gather_grad(z.logp_and_scattered_grad(P0))
    np.testing.assert_allclose(
        np.asarray(g_full["w"]), np.asarray(g_ref["w"]), rtol=1e-4
    )


def test_sharded_sgd_matches_replicated_loop(mesh8):
    X, y = _data(8)
    z = ZeroShardedLogpGrad(_per_shard, (X, y), P0, mesh=mesh8)
    final, logps = z.sgd_steps(P0, learning_rate=1e-3, num_steps=60)
    assert float(logps[-1]) > float(logps[0])

    fed = FederatedLogp(_per_shard, (X, y), mesh=mesh8)
    p = P0
    for _ in range(60):
        _, g = fed.logp_and_grad(p)
        p = jax.tree_util.tree_map(lambda a, b: a + 1e-3 * b, p, g)
    np.testing.assert_allclose(
        np.asarray(final["w"]), np.asarray(p["w"]), rtol=1e-3, atol=1e-5
    )
    np.testing.assert_allclose(
        float(final["b"]), float(p["b"]), rtol=1e-3, atol=1e-5
    )


def test_shard_count_validation(mesh8):
    X, y = _data(6)  # 6 not divisible by 8
    with pytest.raises(ValueError, match="not divisible"):
        ZeroShardedLogpGrad(_per_shard, (X, y), P0, mesh=mesh8)


def test_sharded_adam_matches_replicated_adam(mesh8):
    """Adam with sharded moments == replicated Adam, step for step."""
    X, y = _data(8)
    z = ZeroShardedLogpGrad(_per_shard, (X, y), P0, mesh=mesh8)
    final, logps = z.adam_steps(P0, learning_rate=0.05, num_steps=40)
    assert float(logps[-1]) > float(logps[0])

    # Replicated reference Adam on the same flat vector.
    from jax.flatten_util import ravel_pytree

    fed = FederatedLogp(_per_shard, (X, y), mesh=mesh8)
    vec, unravel = ravel_pytree(P0)
    m = np.zeros_like(vec)
    v = np.zeros_like(vec)
    for t in range(1, 41):
        _, g = fed.logp_and_grad(unravel(jnp.asarray(vec)))
        g, _ = ravel_pytree(g)
        g = np.asarray(g)
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        mhat = m / (1 - 0.9**t)
        vhat = v / (1 - 0.999**t)
        vec = np.asarray(vec) + 0.05 * mhat / (np.sqrt(vhat) + 1e-8)
    ref = unravel(jnp.asarray(vec))
    np.testing.assert_allclose(
        np.asarray(final["w"]), np.asarray(ref["w"]), rtol=1e-3, atol=1e-4
    )
