"""Property-based fleet-merge correctness (hypothesis) — ISSUE 11
satellite.

THE property the fleet plane rests on: merging N disjoint per-replica
snapshots (each replica observed its own slice of the traffic into its
own registry) is EXACTLY what one registry would have recorded had it
observed the union.  Counters must sum and histograms must merge
bucket-wise with no observation lost, double-counted, or re-bucketed —
for arbitrary label sets, arbitrary observation values (including
bucket-boundary-exact ones, where a bisect off-by-one would silently
shift a count), and arbitrary splits of the traffic across replicas.
"""

import pytest

pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from pytensor_federated_tpu.telemetry import metrics as m  # noqa: E402
from pytensor_federated_tpu.telemetry.collector import (  # noqa: E402
    FleetMergeError,
    merge_metric_snapshots,
)

COMMON = settings(max_examples=60, deadline=None)

_LABELS = ("a", "b", "c")
_BUCKETS = (1e-3, 1e-2, 1e-1, 1.0)

# One observation: (kind, label, value).  Values deliberately include
# the exact bucket bounds (bisect edge) and out-of-ladder extremes.
_obs = st.tuples(
    st.sampled_from(("counter", "histogram")),
    st.sampled_from(_LABELS),
    st.sampled_from(
        (0.0, 1e-4, 1e-3, 5e-3, 1e-2, 9e-2, 1e-1, 0.5, 1.0, 7.5)
    ),
)


def _observe(registry: m.Registry, kind: str, label: str, value: float):
    if kind == "counter":
        registry.counter(
            "pftpu_prop_total", "p", ("k",)
        ).labels(k=label).inc(value)
    else:
        registry.histogram(
            "pftpu_prop_seconds", "p", ("k",), buckets=_BUCKETS
        ).labels(k=label).observe(value)


def _canon(snapshot: dict) -> dict:
    """Label-keyed children, exemplars dropped (per-process by
    design), insertion order ignored."""
    out = {}
    for name, fam in snapshot.items():
        children = {}
        for child in fam["children"]:
            key = tuple(sorted((child.get("labels") or {}).items()))
            children[key] = {
                k: v
                for k, v in child.items()
                if k not in ("labels", "exemplar")
            }
        out[name] = {"type": fam["type"], "children": children}
    return out


@COMMON
@given(
    per_replica=st.lists(
        st.lists(_obs, min_size=0, max_size=20),
        min_size=1,
        max_size=4,
    )
)
def test_merge_of_disjoint_snapshots_equals_union_registry(per_replica):
    union = m.Registry()
    snapshots = {}
    for i, observations in enumerate(per_replica):
        replica = m.Registry()
        for kind, label, value in observations:
            _observe(replica, kind, label, value)
            _observe(union, kind, label, value)
        snapshots[f"replica-{i}"] = m.snapshot(replica)
    merged = merge_metric_snapshots(snapshots)
    assert _canon(merged) == _canon(m.snapshot(union))


@COMMON
@given(
    split=st.lists(
        st.integers(min_value=0, max_value=30), min_size=2, max_size=5
    )
)
def test_histogram_count_and_sum_are_conserved(split):
    snapshots = {}
    for i, n in enumerate(split):
        registry = m.Registry()
        h = registry.histogram(
            "pftpu_prop_seconds", "p", buckets=_BUCKETS
        )
        for j in range(n):
            h.observe(0.003 * (j + 1))
        snapshots[f"r{i}"] = m.snapshot(registry)
    merged = merge_metric_snapshots(snapshots)
    fam = merged.get("pftpu_prop_seconds")
    if sum(split) == 0:
        (child,) = fam["children"]
        assert child["count"] == 0
        return
    (child,) = fam["children"]
    assert child["count"] == sum(split)
    # every observation landed in exactly one bucket or past the ladder
    assert sum(child["buckets"].values()) <= child["count"]
    assert child["sum"] == pytest.approx(
        sum(
            0.003 * (j + 1)
            for n in split
            for j in range(n)
        )
    )


def test_ladder_mismatch_always_raises():
    r1, r2 = m.Registry(), m.Registry()
    r1.histogram("pftpu_prop_seconds", "p", buckets=(0.1,)).observe(0.05)
    r2.histogram("pftpu_prop_seconds", "p", buckets=(0.2,)).observe(0.05)
    with pytest.raises(FleetMergeError):
        merge_metric_snapshots(
            {"a": m.snapshot(r1), "b": m.snapshot(r2)}
        )
