"""fed/ primitives: dense semantics, the DrJAX autodiff identities,
and the trace-time plumbing (closure lifting, batching-pass planning).

The federated MapReduce algebra as REAL JAX primitives (ISSUE 6): the
identities under test are the reason they are primitives at all —
transpose(broadcast) = sum, transpose(sum) = broadcast, and
transpose(map) = map of the per-shard transposed function with
replicated-operand cotangents fed_sum-reduced (the mark_varying
pvary/psum invariant as a structural IR property).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu import fed
from pytensor_federated_tpu.parallel import make_mesh

N = 8


@pytest.fixture(scope="module")
def shard_xy():
    rng = np.random.default_rng(7)
    x = rng.normal(size=(N, 16)).astype(np.float32)
    y = (0.5 + 1.5 * x + 0.1 * rng.normal(size=(N, 16))).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(y)


@pytest.fixture(scope="module")
def params():
    return jnp.asarray(np.float32([0.3, -0.7, 0.2]))


def _shard_logp(p, xs, ys):
    pred = p[0] + p[1] * xs + p[2] * xs**2
    return -jnp.sum((ys - pred) ** 2)


def _model(p, x, y):
    pb = fed.fed_broadcast(p, N)
    lps = fed.fed_map(lambda s: _shard_logp(s[0], s[1], s[2]), (pb, x, y))
    return fed.fed_sum(lps)


def _reference(p, x, y):
    return sum(_shard_logp(p, x[i], y[i]) for i in range(N))


class TestDenseSemantics:
    def test_map_matches_vmap(self, shard_xy):
        x, y = shard_xy
        out = fed.fed_map(lambda s: jnp.sum(s[0] * s[1]), (x, y))
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(jax.vmap(lambda a, b: jnp.sum(a * b))(x, y)),
            rtol=1e-6,
        )

    def test_sum_broadcast_roundtrip(self):
        v = jnp.asarray(np.float32([[1.0, 2.0], [3.0, 4.0]]))
        np.testing.assert_allclose(np.asarray(fed.fed_sum(v)), [4.0, 6.0])
        b = fed.fed_broadcast(jnp.float32(2.0), 4)
        assert b.shape == (4,)
        np.testing.assert_allclose(float(fed.fed_sum(b)), 8.0)

    def test_mean_weighted_and_validated(self):
        vals = jnp.asarray([[1.0], [3.0]])
        np.testing.assert_allclose(
            np.asarray(fed.fed_mean(vals)), [2.0]
        )
        np.testing.assert_allclose(
            np.asarray(fed.fed_mean(vals, jnp.asarray([3.0, 1.0]))), [1.5]
        )
        # The silent-broadcast bug: a length-1 weights vector is
        # broadcast-compatible but weights the WRONG axis — must raise.
        with pytest.raises(ValueError, match="one weight per shard"):
            fed.fed_mean(vals, jnp.ones((1,)))
        with pytest.raises(ValueError, match="one weight per shard"):
            fed.fed_mean(vals, jnp.ones((2, 1)))

    def test_jit_and_vmap(self, shard_xy, params):
        x, y = shard_xy
        ref = _reference(params, x, y)
        np.testing.assert_allclose(
            float(jax.jit(_model)(params, x, y)), float(ref), rtol=1e-5
        )
        batch = jnp.stack([params, params + 0.1])
        got = jax.vmap(lambda p: _model(p, x, y))(batch)
        want = jnp.stack(
            [_reference(params, x, y), _reference(params + 0.1, x, y)]
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4
        )


class TestAutodiffIdentities:
    def test_transpose_of_broadcast_is_sum(self):
        f = lambda v: fed.fed_broadcast(v, 4)
        (ct,) = jax.linear_transpose(f, jnp.zeros((3,), jnp.float32))(
            jnp.ones((4, 3), jnp.float32)
        )
        np.testing.assert_allclose(np.asarray(ct), np.full((3,), 4.0))

    def test_transpose_of_sum_is_broadcast(self):
        f = lambda v: fed.fed_sum(v)
        (ct,) = jax.linear_transpose(f, jnp.zeros((4, 3), jnp.float32))(
            jnp.ones((3,), jnp.float32)
        )
        np.testing.assert_allclose(np.asarray(ct), np.ones((4, 3)))

    def test_grad_matches_unsharded(self, shard_xy, params):
        x, y = shard_xy
        g = jax.grad(_model)(params, x, y)
        g_ref = jax.grad(_reference)(params, x, y)
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(g_ref), rtol=1e-4
        )

    def test_grad_through_closure_consts(self, shard_xy, params):
        """Replicated params captured by CLOSURE: map's transpose must
        fed_sum the per-shard cotangents of the unmapped operand."""
        x, y = shard_xy

        def model(p):
            lps = fed.fed_map(
                lambda s: _shard_logp(p, s[0], s[1]), (x, y)
            )
            return fed.fed_sum(lps)

        np.testing.assert_allclose(
            np.asarray(jax.grad(model)(params)),
            np.asarray(jax.grad(_reference)(params, x, y)),
            rtol=1e-4,
        )

    def test_grad_wrt_mapped_data(self, shard_xy, params):
        x, y = shard_xy
        gx = jax.grad(lambda xx: _model(params, xx, y))(x)
        gx_ref = jax.grad(lambda xx: _reference(params, xx, y))(x)
        np.testing.assert_allclose(
            np.asarray(gx), np.asarray(gx_ref), rtol=1e-4
        )

    def test_jvp(self, shard_xy, params):
        x, y = shard_xy
        t = jnp.ones_like(params)
        _, d = jax.jvp(lambda p: _model(p, x, y), (params,), (t,))
        _, d_ref = jax.jvp(lambda p: _reference(p, x, y), (params,), (t,))
        np.testing.assert_allclose(float(d), float(d_ref), rtol=1e-4)

    def test_second_order(self, shard_xy, params):
        x, y = shard_xy
        h = jax.hessian(lambda p: _model(p, x, y))(params)
        h_ref = jax.hessian(lambda p: _reference(p, x, y))(params)
        np.testing.assert_allclose(
            np.asarray(h), np.asarray(h_ref), rtol=1e-3, atol=1e-2
        )

    def test_int_data_leaves(self, params):
        """Integer mapped leaves (count data) must not break autodiff:
        their tangents/cotangents are symbolic zeros."""
        rng = np.random.default_rng(0)
        counts = jnp.asarray(rng.poisson(3.0, size=(N, 16)).astype(np.int32))

        def model(p):
            lps = fed.fed_map(
                lambda s: jnp.sum(
                    s[0] * p[0] - jnp.exp(p[0]) - 0.0 * p[1] * p[2]
                ),
                (counts,),
            )
            return fed.fed_sum(lps)

        def ref(p):
            return jnp.sum(counts * p[0] - jnp.exp(p[0]))

        np.testing.assert_allclose(
            float(model(params)), float(ref(params)), rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(jax.grad(model)(params)),
            np.asarray(jax.grad(ref)(params)),
            rtol=1e-4,
            atol=1e-6,
        )


class TestMeshPlacement:
    def test_forward_and_grad_match_dense(self, shard_xy, params, devices8):
        x, y = shard_xy
        mesh = make_mesh({"shards": 8}, devices=devices8)
        run = fed.program(
            lambda p: _model(p, x, y), fed.MeshPlacement(mesh)
        )
        np.testing.assert_allclose(
            float(run(params)), float(_model(params, x, y)), rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(jax.grad(run)(params)),
            np.asarray(jax.grad(_model)(params, x, y)),
            rtol=1e-4,
        )

    def test_closure_consts_marked_varying(self, shard_xy, params, devices8):
        """The CLAUDE.md invariant, through the primitive lane: params
        reach the shard body as replicated closure consts and user code
        grads internally — without mark_varying the psum would sum all
        shards' gradients into each local result."""
        x, y = shard_xy
        mesh = make_mesh({"shards": 8}, devices=devices8)

        def model(p):
            def local_step(s):
                g = jax.grad(_shard_logp)(p, s[0], s[1])
                return jnp.sum(g**2)

            return fed.fed_sum(fed.fed_map(local_step, (x, y)))

        run = fed.program(model, fed.MeshPlacement(mesh))
        np.testing.assert_allclose(
            float(run(params)), float(model(params)), rtol=2e-4
        )


class TestBatchingPlan:
    def test_independent_maps_group(self, shard_xy, params):
        x, y = shard_xy

        def model(p):
            pb = fed.fed_broadcast(p, N)
            a = fed.fed_sum(
                fed.fed_map(lambda s: _shard_logp(*s), (pb, x, y))
            )
            b = fed.fed_sum(
                fed.fed_map(lambda s: _shard_logp(*s), (pb, x + 1, y))
            )
            return a + b

        jaxpr = jax.make_jaxpr(model)(params).jaxpr
        plan = fed.plan_windows(jaxpr)
        groups = {tuple(g) for g in plan.values()}
        assert len(groups) == 1
        (group,) = groups
        assert len(group) == 2

    def test_dependent_maps_do_not_group(self, shard_xy, params):
        x, y = shard_xy

        def model(p):
            pb = fed.fed_broadcast(p, N)
            a = fed.fed_map(lambda s: _shard_logp(*s), (pb, x, y))
            # second map CONSUMES the first's output: dependent.
            b = fed.fed_map(lambda s: s[0] * 2.0, (a,))
            return fed.fed_sum(b)

        jaxpr = jax.make_jaxpr(model)(params).jaxpr
        assert fed.plan_windows(jaxpr) == {}


def test_program_without_placement_is_identity(shard_xy, params):
    x, y = shard_xy
    fn = lambda p: _model(p, x, y)
    assert fed.program(fn, None) is fn
