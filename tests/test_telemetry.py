"""Telemetry subsystem (pytensor_federated_tpu/telemetry/): span trees,
metrics registry, Prometheus exposition, and end-to-end driver<->node
trace correlation over the in-repo gRPC and TCP services.

Covers the ISSUE 1 acceptance path explicitly: a federated evaluation
over the real service produces a correlated driver+node span tree and
nonzero RPC histograms, renderable as valid Prometheus text format
(golden-file + structural validation), with the trace id ignorable by
the OFFICIAL protobuf runtime (reference-codec compatibility).
"""

import asyncio
import json
import socket
import struct
import threading
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from pytensor_federated_tpu import telemetry
from pytensor_federated_tpu.telemetry import metrics as tmetrics
from pytensor_federated_tpu.telemetry import spans as tspans

GOLDEN = Path(__file__).resolve().parent / "data" / "telemetry_exposition.txt"


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Telemetry is process-global; every test starts zeroed + enabled."""
    prev = tspans.set_enabled(True)
    telemetry.REGISTRY.reset()
    telemetry.clear_traces()
    yield
    tspans.set_enabled(prev)
    telemetry.REGISTRY.reset()
    telemetry.clear_traces()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


class TestSpans:
    def test_nesting_builds_tree(self):
        with telemetry.span("root", kind="demo") as r:
            with telemetry.span("child_a"):
                with telemetry.span("leaf"):
                    pass
            with telemetry.span("child_b") as b:
                b.set_attr("note", "late")
        assert r.span.duration > 0
        (tree,) = telemetry.recent_traces()
        assert tree["name"] == "root"
        assert tree["attrs"] == {"kind": "demo"}
        names = [c["name"] for c in tree["children"]]
        assert names == ["child_a", "child_b"]
        assert tree["children"][0]["children"][0]["name"] == "leaf"
        assert tree["children"][1]["attrs"]["note"] == "late"
        # one trace id threads the whole tree
        assert {tree["trace_id"]} == {
            c["trace_id"] for c in tree["children"]
        }

    def test_exception_recorded_never_swallowed(self):
        with pytest.raises(ValueError, match="boom"):
            with telemetry.span("failing"):
                raise ValueError("boom")
        (tree,) = telemetry.recent_traces()
        assert tree["error"] == "ValueError: boom"

    def test_trace_context_adopts_wire_id(self):
        """The node-side correlation primitive: spans opened under an
        adopted trace id form a SEPARATE root carrying the driver's id."""
        wire_id = telemetry.new_trace_id()
        with telemetry.trace_context(wire_id):
            with telemetry.span("node.evaluate"):
                pass
        (tree,) = telemetry.recent_traces()
        assert tree["trace_id"] == wire_id.hex()
        # None (no id on the wire) is a no-op
        with telemetry.trace_context(None):
            with telemetry.span("solo"):
                pass
        assert telemetry.recent_traces()[-1]["name"] == "solo"

    def test_disabled_is_shared_noop(self):
        tspans.set_enabled(False)
        cm1, cm2 = telemetry.span("a"), telemetry.span("b", x=1)
        assert cm1 is cm2  # no allocation on the disabled path
        with cm1 as s:
            assert s.span is None
            s.set_attr("ignored", True)
        assert telemetry.recent_traces() == []

    def test_ring_buffer_capacity(self):
        tspans.set_trace_capacity(4)
        try:
            for i in range(7):
                with telemetry.span(f"s{i}"):
                    pass
            names = [t["name"] for t in telemetry.recent_traces()]
            assert names == ["s3", "s4", "s5", "s6"]  # newest kept
            with pytest.raises(ValueError):
                tspans.set_trace_capacity(0)
        finally:
            tspans.set_trace_capacity(64)


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------


class TestMetrics:
    def test_counter(self):
        c = telemetry.counter("t_requests_total", "demo", ("method",))
        c.labels(method="a").inc()
        c.labels(method="a").inc(2.5)
        c.labels(method="b").inc()
        assert c.labels(method="a").value == 3.5
        with pytest.raises(ValueError, match="increase"):
            c.labels(method="a").inc(-1)
        with pytest.raises(ValueError, match="expected labels"):
            c.labels(wrong="a")

    def test_gauge(self):
        g = telemetry.gauge("t_inflight", "demo")
        g.set(5)
        g.inc()
        g.dec(2)
        assert g.value == 4.0

    def test_histogram_buckets_and_quantile(self):
        h = telemetry.histogram(
            "t_latency_seconds", "demo", buckets=(0.01, 0.1, 1.0)
        )
        for v in (0.005, 0.05, 0.05, 0.5, 5.0):
            h.observe(v)
        assert h.count == 5
        assert h.sum == pytest.approx(5.605)
        assert h.approx_quantile(0.5) == 0.1  # 3rd of 5 lands in le=0.1
        assert h.approx_quantile(1.0) == float("inf")
        import math

        assert math.isnan(
            telemetry.histogram(
                "t_empty_seconds", "demo"
            ).approx_quantile(0.5)
        )

    def test_reregistration_conflicts_raise(self):
        telemetry.counter("t_conflict_total", "demo")
        # same declaration merges
        telemetry.counter("t_conflict_total", "demo")
        with pytest.raises(ValueError, match="already registered"):
            telemetry.gauge("t_conflict_total", "demo")
        with pytest.raises(ValueError, match="already registered"):
            telemetry.counter("t_conflict_total", "demo", ("extra",))
        telemetry.histogram("t_conflict_seconds", "demo", buckets=(1.0,))
        with pytest.raises(ValueError, match="buckets"):
            telemetry.histogram(
                "t_conflict_seconds", "demo", buckets=(2.0,)
            )

    def test_invalid_names_raise(self):
        with pytest.raises(ValueError, match="invalid"):
            telemetry.counter("bad name", "demo")
        with pytest.raises(ValueError, match="invalid"):
            telemetry.counter("1leading", "demo")

    def test_disabled_mutators_are_noops(self):
        c = telemetry.counter("t_gate_total", "demo")
        h = telemetry.histogram("t_gate_seconds", "demo")
        tspans.set_enabled(False)
        c.inc()
        h.observe(1.0)
        tspans.set_enabled(True)
        assert c.value == 0.0 and h.count == 0

    def test_reset_zeroes_but_keeps_registrations(self):
        c = telemetry.counter("t_reset_total", "demo")
        c.inc(7)
        telemetry.REGISTRY.reset()
        assert c.value == 0.0  # the SAME object an instrumented
        c.inc()  # module still holds keeps working
        assert telemetry.REGISTRY.get("t_reset_total").value == 1.0

    def test_exemplar_links_to_trace(self):
        h = telemetry.histogram("t_exemplar_seconds", "demo")
        with telemetry.span("op"):
            h.observe(0.25)
            tid = tspans.current_trace_id().hex()
        snap = tmetrics.snapshot()["t_exemplar_seconds"]["children"][0]
        assert snap["exemplar"] == {"value": 0.25, "trace_id": tid}


# ---------------------------------------------------------------------------
# Prometheus rendering: golden file + structural validation
# ---------------------------------------------------------------------------


def _golden_registry() -> telemetry.Registry:
    """A FIXED observation sequence (fresh registry, no global state)."""
    reg = telemetry.Registry()
    c = reg.counter("demo_requests_total", "RPCs served", ("method",))
    c.labels(method="evaluate").inc(3)
    c.labels(method="get_load").inc()
    g = reg.gauge("demo_inflight_requests", "Evaluate RPCs in flight")
    g.set(2)
    h = reg.histogram(
        "demo_latency_seconds",
        'Latency with "quoted" help and a \\ backslash',
        ("transport",),
        buckets=(0.001, 0.01, 0.1),
    )
    for v in (0.0005, 0.005, 0.005, 0.05, 1.5):
        h.labels(transport="grpc").observe(v)
    return reg


def validate_prometheus_text(text: str) -> dict:
    """Structural check of classic exposition format 0.0.4; returns
    {family: [(name, labels_str, value)]}."""
    families, current = {}, None
    for line in text.splitlines():
        assert line.strip() == line and line, f"bad line framing: {line!r}"
        if line.startswith("# HELP "):
            current = line.split()[2]
            families[current] = []
        elif line.startswith("# TYPE "):
            parts = line.split()
            assert parts[2] == current, "TYPE must follow its HELP"
            assert parts[3] in ("counter", "gauge", "histogram", "untyped")
        else:
            name, _, rest = line.partition("{")
            if rest:
                labels, _, value = rest.rpartition("} ")
            else:
                name, _, value = line.rpartition(" ")
                labels = ""
            float(value)  # must parse (+Inf/NaN are valid spellings)
            assert name.startswith(current), (
                f"sample {name!r} outside its family {current!r}"
            )
            families[current].append((name, labels, value))
    return families


class TestPrometheusText:
    def test_golden_file(self):
        text = telemetry.render_prometheus(_golden_registry())
        assert text == GOLDEN.read_text(), (
            "exposition text drifted from the golden file; if the "
            "change is intentional, regenerate tests/data/"
            "telemetry_exposition.txt"
        )

    def test_structure_and_histogram_invariants(self):
        text = telemetry.render_prometheus(_golden_registry())
        fams = validate_prometheus_text(text)
        rows = fams["demo_latency_seconds"]
        buckets = [r for r in rows if r[0].endswith("_bucket")]
        counts = [float(v) for _, _, v in buckets]
        assert counts == sorted(counts), "buckets must be cumulative"
        assert buckets[-1][1].endswith('le="+Inf"')
        (count_row,) = [
            r for r in rows if r[0] == "demo_latency_seconds_count"
        ]
        assert float(count_row[2]) == counts[-1] == 5.0
        (sum_row,) = [r for r in rows if r[0] == "demo_latency_seconds_sum"]
        assert float(sum_row[2]) == pytest.approx(1.5605)
        # label escaping survived
        assert 'transport="grpc"' in buckets[0][1]

    def test_deterministic(self):
        a = telemetry.render_prometheus(_golden_registry())
        b = telemetry.render_prometheus(_golden_registry())
        assert a == b


# ---------------------------------------------------------------------------
# exposition lane: snapshot / JSONL / HTTP exporter
# ---------------------------------------------------------------------------


class TestExport:
    def test_snapshot_shape(self):
        telemetry.counter("t_snap_total", "demo").inc()
        with telemetry.span("snap.op"):
            pass
        snap = telemetry.snapshot()
        assert snap["enabled"] is True
        assert snap["metrics"]["t_snap_total"]["children"][0]["value"] == 1
        assert snap["traces"][-1]["name"] == "snap.op"

    def test_dump_jsonl_appends(self, tmp_path):
        path = tmp_path / "t.jsonl"
        telemetry.dump_jsonl(str(path))
        telemetry.dump_jsonl(str(path))
        lines = path.read_text().splitlines()
        assert len(lines) == 2
        rec = json.loads(lines[1])
        assert rec["ts"] > 0 and "metrics" in rec

    def test_http_exporter_serves_all_routes(self):
        telemetry.counter("t_http_total", "demo").inc(2)
        with telemetry.span("http.op"):
            pass
        with telemetry.start_exporter(port=0) as exporter:
            base = f"http://127.0.0.1:{exporter.port}"

            def get(path):
                with urllib.request.urlopen(base + path, timeout=5) as r:
                    return r.headers.get("Content-Type"), r.read()

            ctype, body = get("/metrics")
            assert ctype.startswith("text/plain; version=0.0.4")
            assert b"t_http_total 2" in body
            validate_prometheus_text(body.decode())

            ctype, body = get("/snapshot")
            assert ctype == "application/json"
            assert json.loads(body)["enabled"] is True

            _, body = get("/traces")
            assert any(t["name"] == "http.op" for t in json.loads(body))

            with pytest.raises(urllib.error.HTTPError) as exc:
                get("/nope")
            assert exc.value.code == 404
        # closed: the port no longer answers
        with pytest.raises((ConnectionError, urllib.error.URLError, OSError)):
            urllib.request.urlopen(base + "/metrics", timeout=1)

    def test_metrics_dump_tool_roundtrip(self, tmp_path, capsys):
        import sys

        sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
        try:
            from tools import metrics_dump
        except ImportError:  # tools/ has no __init__; import by path
            import importlib.util

            spec = importlib.util.spec_from_file_location(
                "metrics_dump",
                Path(__file__).resolve().parent.parent
                / "tools"
                / "metrics_dump.py",
            )
            metrics_dump = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(metrics_dump)
        finally:
            sys.path.pop(0)

        telemetry.counter("t_tool_total", "demo").inc(5)
        out = tmp_path / "scrape.jsonl"
        with telemetry.start_exporter(port=0) as exporter:
            rc = metrics_dump.main(
                ["--port", str(exporter.port), "--out", str(out)]
            )
            assert rc == 0
            rc = metrics_dump.main(["--port", str(exporter.port), "--text"])
            assert rc == 0
        rec = json.loads(out.read_text())
        assert (
            rec["metrics"]["t_tool_total"]["children"][0]["value"] == 5
        )
        assert "t_tool_total 5" in capsys.readouterr().out
        # unreachable endpoint: exit 1, not a traceback
        assert metrics_dump.main(["--port", str(_free_port())]) == 1

    def test_metrics_dump_fleet_merges_and_fails_loud(self, capsys):
        """--fleet renders the merged multi-replica table; ANY
        unreachable replica makes the exit nonzero (the --pool
        semantics — a half-scraped fleet is a loud failure, never a
        silently partial table) (ISSUE 11 tooling satellite)."""
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "metrics_dump",
            Path(__file__).resolve().parent.parent
            / "tools"
            / "metrics_dump.py",
        )
        metrics_dump = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(metrics_dump)

        from pytensor_federated_tpu.service import _node_metrics

        _node_metrics.REQUESTS.labels(method="evaluate").inc(7)
        with telemetry.start_exporter(port=0) as exporter:
            live = f"127.0.0.1:{exporter.port}"
            rc = metrics_dump.main(["--fleet", live])
            out = capsys.readouterr().out
            assert rc == 0
            assert live in out and "fleet (1/1 up)" in out
            assert "7" in out  # the merged requests column
            # one dead replica: its row is loud and the exit nonzero
            dead = f"127.0.0.1:{_free_port()}"
            rc = metrics_dump.main(["--fleet", f"{live},{dead}"])
            out = capsys.readouterr().out
            assert rc == 1
            assert "NO" in out and "fleet (1/2 up)" in out

    def test_metrics_dump_grep_prints_batcher_families(
        self, tmp_path, capsys
    ):
        """--grep batch narrows both output modes to the micro-batcher
        families (ISSUE 3 tooling satellite)."""
        import importlib.util
        import sys

        spec = importlib.util.spec_from_file_location(
            "metrics_dump",
            Path(__file__).resolve().parent.parent
            / "tools"
            / "metrics_dump.py",
        )
        metrics_dump = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(metrics_dump)

        # Register + populate the batcher families.
        from pytensor_federated_tpu.service.batching import MicroBatcher

        mb = MicroBatcher(lambda x: [x], None, max_batch=4, inline=True)
        asyncio.run(mb.submit((np.zeros(2),)))
        with telemetry.start_exporter(port=0) as exporter:
            rc = metrics_dump.main(
                ["--port", str(exporter.port), "--text", "--grep", "batch"]
            )
            assert rc == 0
            text = capsys.readouterr().out
            assert "pftpu_server_batch_size" in text
            assert "pftpu_server_batches_total" in text
            # the filter really filters: unrelated families are gone
            assert "pftpu_server_requests_total" not in text
            out = tmp_path / "batch.jsonl"
            rc = metrics_dump.main(
                [
                    "--port", str(exporter.port),
                    "--grep", "batch", "--out", str(out),
                ]
            )
            assert rc == 0
        rec = json.loads(out.read_text())
        assert all("batch" in k for k in rec["metrics"])
        assert "pftpu_server_batch_size" in rec["metrics"]


# ---------------------------------------------------------------------------
# trace id on the wire
# ---------------------------------------------------------------------------


class TestWireTraceId:
    def test_npwire_roundtrip_and_legacy_decode(self):
        from pytensor_federated_tpu.service.npwire import (
            WireError,
            decode_arrays,
            decode_arrays_ex,
            encode_arrays,
        )

        tid = telemetry.new_trace_id()
        uid = b"u" * 16
        arrs = [np.arange(3.0), np.float64(7.0)]
        enc = encode_arrays(arrs, uuid=uid, trace_id=tid)
        dec, ruid, err, rtid = decode_arrays_ex(enc)
        assert (ruid, err, rtid) == (uid, None, tid)
        np.testing.assert_array_equal(dec[0], arrs[0])
        # the historical 3-tuple decoder consumes-and-drops the block
        dec2, ruid2, err2 = decode_arrays(enc)
        assert ruid2 == uid and err2 is None and len(dec2) == 2
        # error + trace coexist
        enc_e = encode_arrays([], uuid=uid, error="boom", trace_id=tid)
        _, _, err_e, tid_e = decode_arrays_ex(enc_e)
        assert err_e == "boom" and tid_e == tid
        # no trace -> byte-identical pre-telemetry frame
        assert encode_arrays(arrs, uuid=uid) == encode_arrays(
            arrs, uuid=uid, trace_id=None
        )
        # malformed inputs fail loudly
        with pytest.raises(WireError, match="16 bytes"):
            encode_arrays(arrs, uuid=uid, trace_id=b"short")
        with pytest.raises(WireError, match="trace block"):
            decode_arrays_ex(enc[: 4 + 1 + 1 + 16 + 4 + 8])

    def test_npproto_field15_roundtrip_and_skip(self):
        from pytensor_federated_tpu.service.npproto_codec import (
            WireError,
            decode_arrays_msg,
            decode_arrays_msg_ex,
            encode_arrays_msg,
        )

        tid = telemetry.new_trace_id()
        arrs = [np.arange(4, dtype=np.int32)]
        enc = encode_arrays_msg(arrs, uuid="abc", trace_id=tid)
        dec, uuid, rtid = decode_arrays_msg_ex(enc)
        assert uuid == "abc" and rtid == tid
        np.testing.assert_array_equal(dec[0], arrs[0])
        # the historical 2-tuple decoder skips field 15 like any
        # unknown field
        dec2, uuid2 = decode_arrays_msg(enc)
        assert uuid2 == "abc" and len(dec2) == 1
        assert encode_arrays_msg(arrs, uuid="abc") == encode_arrays_msg(
            arrs, uuid="abc", trace_id=None
        )
        with pytest.raises(WireError, match="16 bytes"):
            encode_arrays_msg(arrs, uuid="abc", trace_id=b"xy")

    def test_npproto_trace_ignorable_by_official_runtime(self):
        """THE reference-codec compatibility property: the OFFICIAL
        protobuf runtime, built against the reference schema (which
        has no field 15), must parse a trace-bearing InputArrays to
        the same arrays+uuid — unknown field skipped by wire type."""
        pytest.importorskip("google.protobuf", reason="cross-check")
        from google.protobuf import (
            descriptor_pb2,
            descriptor_pool,
            message_factory,
        )

        from pytensor_federated_tpu.service.npproto_codec import (
            encode_arrays_msg,
        )

        pool = descriptor_pool.DescriptorPool()
        fdp = descriptor_pb2.FileDescriptorProto()
        fdp.name = "tel.proto"
        fdp.package = "tel"
        fdp.syntax = "proto3"
        F = descriptor_pb2.FieldDescriptorProto
        nd = fdp.message_type.add()
        nd.name = "ndarray"
        for name, num, ftype, label in [
            ("data", 1, F.TYPE_BYTES, F.LABEL_OPTIONAL),
            ("dtype", 2, F.TYPE_STRING, F.LABEL_OPTIONAL),
            ("shape", 3, F.TYPE_INT64, F.LABEL_REPEATED),
            ("strides", 4, F.TYPE_INT64, F.LABEL_REPEATED),
        ]:
            f = nd.field.add()
            f.name, f.number, f.type, f.label = name, num, ftype, label
        m = fdp.message_type.add()
        m.name = "InputArrays"
        f = m.field.add()
        f.name, f.number, f.type, f.label = (
            "items", 1, F.TYPE_MESSAGE, F.LABEL_REPEATED,
        )
        f.type_name = ".tel.ndarray"
        f = m.field.add()
        f.name, f.number, f.type, f.label = (
            "uuid", 2, F.TYPE_STRING, F.LABEL_OPTIONAL,
        )
        pool.Add(fdp)
        InputArrays = message_factory.GetMessageClass(
            pool.FindMessageTypeByName("tel.InputArrays")
        )

        arr = np.linspace(0, 1, 5)
        enc = encode_arrays_msg(
            [arr], uuid="ref-uuid", trace_id=telemetry.new_trace_id()
        )
        msg = InputArrays()
        msg.ParseFromString(enc)  # must not choke on field 15
        assert msg.uuid == "ref-uuid"
        assert len(msg.items) == 1
        got = np.frombuffer(
            msg.items[0].data, dtype=np.dtype(msg.items[0].dtype)
        ).reshape(tuple(msg.items[0].shape))
        np.testing.assert_array_equal(got, arr)


# ---------------------------------------------------------------------------
# end-to-end correlation over the real services (acceptance criteria)
# ---------------------------------------------------------------------------


def _server_histogram_counts():
    reg = telemetry.REGISTRY
    return {
        name: sum(
            c._count for c in reg.get(name)._children.values()
        )
        for name in (
            "pftpu_server_decode_seconds",
            "pftpu_server_queue_wait_seconds",
            "pftpu_server_compute_seconds",
            "pftpu_server_encode_seconds",
        )
    }


class TestEndToEndCorrelation:
    def _roots_by_name(self, name):
        return [t for t in telemetry.recent_traces() if t["name"] == name]

    @pytest.mark.parametrize("codec", ["npwire", "npproto"])
    def test_grpc_driver_and_node_spans_correlate(self, codec):
        from pytensor_federated_tpu.service import (
            ArraysToArraysServiceClient,
        )
        from pytensor_federated_tpu.service.server import (
            ArraysToArraysService,
            serve,
        )

        def compute(x):
            return [np.asarray(-np.sum(np.asarray(x) ** 2))]

        async def main():
            port = _free_port()
            service = ArraysToArraysService(compute)
            server = await serve(None, "127.0.0.1", port, service=service)
            try:
                client = ArraysToArraysServiceClient(
                    "127.0.0.1", port, codec=codec
                )
                out = await client.evaluate_async(np.array([1.0, 2.0]))
                np.testing.assert_allclose(float(np.asarray(out[0])), -5.0)
            finally:
                await server.stop(None)

        asyncio.run(main())

        # Driver root + node root share ONE wire-carried trace id.
        (drv,) = self._roots_by_name("rpc.evaluate")
        (node,) = self._roots_by_name("node.evaluate")
        assert drv["trace_id"] == node["trace_id"]
        assert drv["attrs"]["transport"] == "grpc"
        assert node["attrs"]["wire"] == codec
        drv_children = [c["name"] for c in drv["children"]]
        assert drv_children == ["encode", "call", "decode"]
        node_children = [c["name"] for c in node["children"]]
        assert node_children == ["compute", "encode"]
        # the driver's call envelope covers the node's whole service time
        call_s = drv["children"][drv_children.index("call")]["duration_s"]
        assert call_s >= node["duration_s"] * 0.5

        # Nonzero RPC histograms on both sides…
        for name, count in _server_histogram_counts().items():
            assert count >= 1, f"{name} never observed"
        call_hist = telemetry.REGISTRY.get("pftpu_client_call_seconds")
        assert call_hist.labels(transport="grpc", mode="stream").count >= 1
        # …renderable as valid Prometheus text.
        validate_prometheus_text(telemetry.render_prometheus())

    def test_tcp_lane_correlates_too(self):
        from pytensor_federated_tpu.service import (
            TcpArraysClient,
            serve_tcp_once,
        )

        port_box, ready = {}, threading.Event()

        def ready_cb(port):
            port_box["port"] = port
            ready.set()

        t = threading.Thread(
            target=serve_tcp_once,
            args=(lambda *a: [2.0 * x for x in a],),
            kwargs={"ready_callback": ready_cb, "max_connections": 1},
            daemon=True,
        )
        t.start()
        assert ready.wait(10)
        client = TcpArraysClient("127.0.0.1", port_box["port"])
        out = client.evaluate(np.arange(3.0))
        np.testing.assert_array_equal(out[0], 2.0 * np.arange(3.0))
        client.close()
        t.join(timeout=10)

        (drv,) = self._roots_by_name("rpc.evaluate")
        (node,) = self._roots_by_name("node.evaluate")
        assert drv["trace_id"] == node["trace_id"]
        assert drv["attrs"]["transport"] == "tcp"
        assert node["attrs"]["transport"] == "tcp"
        call_hist = telemetry.REGISTRY.get("pftpu_client_call_seconds")
        assert (
            call_hist.labels(transport="tcp", mode="lockstep").count == 1
        )

    def test_disabled_means_no_trace_on_wire_and_no_metrics(self):
        from pytensor_federated_tpu.service.npwire import decode_arrays_ex
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        seen = {}

        def server():
            from pytensor_federated_tpu.service.npwire import encode_arrays
            from pytensor_federated_tpu.service.tcp import (
                _recv_frame,
                _send_frame,
            )

            srv = socket.socket()
            srv.bind(("127.0.0.1", 0))
            srv.listen(1)
            seen["port"] = srv.getsockname()[1]
            ready.set()
            conn, _ = srv.accept()
            with conn, srv:
                payload = _recv_frame(conn)
                arrays, uid, _err, tid = decode_arrays_ex(payload)
                seen["trace_id"] = tid
                _send_frame(conn, encode_arrays(arrays, uuid=uid))

        ready = threading.Event()
        t = threading.Thread(target=server, daemon=True)
        t.start()
        assert ready.wait(10)
        tspans.set_enabled(False)
        client = TcpArraysClient("127.0.0.1", seen["port"])
        client.evaluate(np.ones(2))
        client.close()
        t.join(timeout=10)
        assert seen["trace_id"] is None  # telemetry off -> bare wire
        tspans.set_enabled(True)
        assert telemetry.recent_traces() == []
        call_hist = telemetry.REGISTRY.get("pftpu_client_call_seconds")
        assert call_hist.labels(transport="tcp", mode="lockstep").count == 0


# determine_load needs served traffic to have quantiles; probe helper
async def _probe(service):
    from pytensor_federated_tpu.service.npwire import encode_arrays

    req = encode_arrays([np.ones(2)], uuid=b"p" * 16)
    await service.evaluate(req, None)


def test_getload_rpc_summary_and_npproto_reply_unchanged():
    from pytensor_federated_tpu.service.npproto_codec import (
        decode_get_load_result,
        encode_get_load_result,
    )
    from pytensor_federated_tpu.service.server import ArraysToArraysService

    service = ArraysToArraysService(lambda x: [x], inline_compute=True)
    asyncio.run(_probe(service))
    load = service.determine_load()
    assert load["rpc"]["requests_total"] >= 1
    assert load["rpc"]["inflight"] == 0
    assert load["rpc"]["compute_p50_s"] is not None
    # reference fields stay top-level for balancing
    assert {"n_clients", "percent_cpu", "percent_ram"} <= set(load)
    # the npproto GetLoad reply carries ONLY the three reference fields
    wire = encode_get_load_result(load["n_clients"], 12.5, 37.5)
    assert set(decode_get_load_result(wire)) == {
        "n_clients", "percent_cpu", "percent_ram",
    }
    # disabled -> the rpc sub-dict disappears entirely
    tspans.set_enabled(False)
    assert "rpc" not in service.determine_load()
    tspans.set_enabled(True)


# ---------------------------------------------------------------------------
# fanout + sampler instrumentation
# ---------------------------------------------------------------------------


def test_fanout_span_tree_and_straggler_gap():
    import time as time_mod

    from pytensor_federated_tpu.fanout_exec import (
        MemberExecutorPool,
        run_members,
    )

    delays = [0.0, 0.05, 0.0]

    def make_member(i):
        def member(sub_inputs, sub_storage):
            time_mod.sleep(delays[i])
            sub_storage[0][0] = sub_inputs[0] + i

        return member

    pool = MemberExecutorPool(3)
    storage = [[None], [None], [None]]
    run_members(
        [make_member(i) for i in range(3)],
        [1, 1, 1], [1, 1, 1], [10, 20, 30], storage, pool,
    )
    pool.shutdown()
    assert [c[0] for c in storage] == [10, 21, 32]

    (tree,) = [
        t for t in telemetry.recent_traces() if t["name"] == "fanout"
    ]
    assert tree["attrs"]["width"] == 3
    # members crossed the thread pool but parent under the fanout span
    members = [c for c in tree["children"] if c["name"] == "fanout.member"]
    assert sorted(m["attrs"]["idx"] for m in members) == [0, 1, 2]
    assert tree["attrs"]["straggler_gap_s"] >= 0.03
    width = telemetry.REGISTRY.get("pftpu_fanout_width")
    assert width.count == 1
    gap = telemetry.REGISTRY.get("pftpu_fanout_straggler_seconds")
    assert gap.sum >= 0.03
    assert telemetry.REGISTRY.get("pftpu_fanout_member_seconds").count == 3


def test_fanout_disabled_path_unchanged():
    from pytensor_federated_tpu.fanout_exec import (
        MemberExecutorPool,
        run_members,
    )

    tspans.set_enabled(False)
    pool = MemberExecutorPool(2)
    storage = [[None], [None]]
    run_members(
        [
            lambda i, s: s[0].__setitem__(0, i[0]),
            lambda i, s: s[0].__setitem__(0, i[0]),
        ],
        [1, 1], [1, 1], [1, 2], storage, pool,
    )
    pool.shutdown()
    assert [c[0] for c in storage] == [1, 2]
    tspans.set_enabled(True)
    assert telemetry.recent_traces() == []
    assert telemetry.REGISTRY.get("pftpu_fanout_width").count == 0


def test_mcmc_sample_records_step_timing():
    import jax
    import jax.numpy as jnp

    from pytensor_federated_tpu.samplers.mcmc import sample

    res = sample(
        lambda p: -0.5 * jnp.sum(p["x"] ** 2),
        {"x": jnp.zeros(2)},
        key=jax.random.PRNGKey(0),
        num_warmup=10,
        num_samples=5,
        num_chains=2,
        kernel="metropolis",
    )
    assert res.samples["x"].shape == (2, 5, 2)
    draws = telemetry.REGISTRY.get("pftpu_sampler_draws_total")
    assert draws.labels(kernel="metropolis").value == 10  # 2 chains x 5
    run_h = telemetry.REGISTRY.get("pftpu_sampler_run_seconds")
    assert run_h.labels(kernel="metropolis").count == 1
    step_h = telemetry.REGISTRY.get("pftpu_sampler_step_seconds")
    child = step_h.labels(kernel="metropolis")
    assert child.count == 1
    # derived per-transition time: wall / (2 chains * 15 transitions)
    assert 0 < child.sum < run_h.labels(kernel="metropolis").sum
    (tree,) = [
        t
        for t in telemetry.recent_traces()
        if t["name"] == "mcmc.sample"
    ]
    assert tree["attrs"]["kernel"] == "metropolis"


# ---------------------------------------------------------------------------
# satellites: connection hygiene + retry classification + heartbeat bind
# ---------------------------------------------------------------------------


class TestTcpUuidMismatchHygiene:
    """ADVICE r5 #3: a mismatched per-call reply must close the socket
    BEFORE raising, so the cached connection cannot stay desynchronized."""

    def test_mismatch_closes_then_next_call_reconnects_clean(self):
        from pytensor_federated_tpu.service.npwire import (
            decode_arrays_ex,
            encode_arrays,
        )
        from pytensor_federated_tpu.service.tcp import (
            TcpArraysClient,
            _recv_frame,
            _send_frame,
        )

        state = {"n": 0}
        ready = threading.Event()

        def server():
            srv = socket.socket()
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind(("127.0.0.1", 0))
            srv.listen(4)
            state["port"] = srv.getsockname()[1]
            ready.set()
            with srv:
                for _ in range(2):  # original + post-mismatch reconnect
                    conn, _ = srv.accept()
                    with conn:
                        while True:
                            try:
                                payload = _recv_frame(conn)
                            except (ConnectionError, OSError):
                                break
                            arrays, uid, _e, _t = decode_arrays_ex(payload)
                            state["n"] += 1
                            reply_uid = (
                                b"\xff" * 16 if state["n"] == 1 else uid
                            )
                            _send_frame(
                                conn,
                                encode_arrays(arrays, uuid=reply_uid),
                            )

        t = threading.Thread(target=server, daemon=True)
        t.start()
        assert ready.wait(10)
        client = TcpArraysClient("127.0.0.1", state["port"], retries=0)
        with pytest.raises(RuntimeError, match="uuid mismatch"):
            client.evaluate(np.arange(2.0))
        # the poisoned connection was dropped, not cached
        assert client._sock is None
        drops = telemetry.REGISTRY.get(
            "pftpu_client_connection_drops_total"
        )
        assert drops.labels(transport="tcp").value >= 1
        # and the next call reconnects and succeeds
        out = client.evaluate(np.arange(2.0))
        np.testing.assert_array_equal(out[0], np.arange(2.0))
        client.close()
        t.join(timeout=10)


class TestStreamDecodeFailureHygiene:
    """ADVICE r5 #1: a corrupt reply mid-batch (replies still in
    flight) must drop the cached gRPC connection before re-raising."""

    def test_corrupt_midbatch_reply_drops_connection(self):
        import grpc

        from pytensor_federated_tpu.service import (
            ArraysToArraysServiceClient,
        )
        from pytensor_federated_tpu.service.client import (
            _privates,
            thread_pid_id,
        )
        from pytensor_federated_tpu.service.npwire import (
            WireError,
            decode_arrays_ex,
            encode_arrays,
        )

        async def evaluate_stream(request_iterator, context):
            i = 0
            async for req in request_iterator:
                _arrs, uid, _e, _t = decode_arrays_ex(req)
                i += 1
                if i == 2:
                    yield b"NPW1\x01"  # truncated header -> WireError
                else:
                    yield encode_arrays([np.zeros(1)], uuid=uid)

        async def get_load(request, context):
            return b""

        async def main():
            ident = lambda b: b  # noqa: E731
            server = grpc.aio.server()
            handlers = {
                "EvaluateStream": grpc.stream_stream_rpc_method_handler(
                    evaluate_stream,
                    request_deserializer=ident,
                    response_serializer=ident,
                ),
                "GetLoad": grpc.unary_unary_rpc_method_handler(
                    get_load,
                    request_deserializer=ident,
                    response_serializer=ident,
                ),
            }
            server.add_generic_rpc_handlers((
                grpc.method_handlers_generic_handler(
                    "ArraysToArraysService", handlers
                ),
            ))
            port = server.add_insecure_port("127.0.0.1:0")
            await server.start()
            try:
                client = ArraysToArraysServiceClient(
                    "127.0.0.1", port, retries=0
                )
                reqs = [(np.ones(1),) for _ in range(4)]
                with pytest.raises(WireError):
                    await client.evaluate_many_async(reqs, window=4)
                prefix = thread_pid_id(client)
                live = [k for k in _privates if k[:3] == prefix]
                assert live == [], (
                    "corrupt mid-batch reply left the desynchronized "
                    "connection cached"
                )
            finally:
                await server.stop(None)

        asyncio.run(main())
        drops = telemetry.REGISTRY.get(
            "pftpu_client_connection_drops_total"
        )
        assert drops.labels(transport="grpc").value >= 1


class TestDeterministicErrorsNotRetried:
    """ADVICE r5 #2: a deterministic server compute error must raise
    after ONE server execution, both codecs, instead of re-running the
    failing compute retries+1 times."""

    def _serve_and_call(self, codec, use_stream):
        import grpc

        from pytensor_federated_tpu.service import (
            ArraysToArraysServiceClient,
        )
        from pytensor_federated_tpu.service.server import (
            ArraysToArraysService,
            serve,
        )

        calls = {"n": 0}

        def compute(x):
            calls["n"] += 1
            raise ValueError("deterministic failure")

        async def main():
            port = _free_port()
            service = ArraysToArraysService(compute, inline_compute=True)
            server = await serve(None, "127.0.0.1", port, service=service)
            try:
                client = ArraysToArraysServiceClient(
                    "127.0.0.1",
                    port,
                    codec=codec,
                    use_stream=use_stream,
                    retries=3,
                )
                with pytest.raises(
                    (RuntimeError, grpc.aio.AioRpcError)
                ) as exc:
                    await client.evaluate_async(np.ones(2))
                return exc
            finally:
                await server.stop(None)

        exc = asyncio.run(main())
        return calls["n"], exc

    def test_npwire_inband_error_single_execution(self):
        n_calls, exc = self._serve_and_call("npwire", use_stream=True)
        assert n_calls == 1
        assert "deterministic failure" in str(exc.value)
        retries = telemetry.REGISTRY.get("pftpu_client_retries_total")
        assert retries.labels(transport="grpc").value == 0

    def test_npproto_status_abort_single_execution(self):
        import grpc

        n_calls, exc = self._serve_and_call("npproto", use_stream=False)
        assert n_calls == 1
        assert isinstance(exc.value, grpc.aio.AioRpcError)
        assert exc.value.code() not in (
            grpc.StatusCode.UNAVAILABLE,
            grpc.StatusCode.DEADLINE_EXCEEDED,
        )
        retries = telemetry.REGISTRY.get("pftpu_client_retries_total")
        assert retries.labels(transport="grpc").value == 0

    def test_transport_errors_stay_retryable(self):
        import grpc

        from pytensor_federated_tpu.service.client import _is_retryable

        assert _is_retryable(ConnectionResetError("peer gone"))
        assert _is_retryable(OSError("network unreachable"))

        class _FakeRpcError(grpc.aio.AioRpcError):
            def __init__(self, code):
                self._fake_code = code

            def code(self):
                return self._fake_code

        assert _is_retryable(_FakeRpcError(grpc.StatusCode.UNAVAILABLE))
        assert not _is_retryable(_FakeRpcError(grpc.StatusCode.UNKNOWN))
        assert not _is_retryable(
            _FakeRpcError(grpc.StatusCode.INVALID_ARGUMENT)
        )


class TestHeartbeatBindPosture:
    """ADVICE r5 #4: loopback by default; externally routable binds are
    an explicit opt-in."""

    def test_default_is_loopback(self):
        from pytensor_federated_tpu.parallel.multihost import (
            HeartbeatServer,
            probe_peer,
        )

        hb = HeartbeatServer(process_index=1)
        try:
            assert hb.address[0] == "127.0.0.1"
            assert probe_peer(
                ("127.0.0.1", hb.port), expect_process_index=1
            )
        finally:
            hb.stop()

    def test_external_requires_opt_in(self):
        from pytensor_federated_tpu.parallel.multihost import (
            HeartbeatServer,
        )

        with pytest.raises(ValueError, match="allow_external"):
            HeartbeatServer("0.0.0.0")
        hb = HeartbeatServer(allow_external=True)
        try:
            assert hb.address[0] == "0.0.0.0"
        finally:
            hb.stop()

    def test_explicit_loopback_still_fine(self):
        from pytensor_federated_tpu.parallel.multihost import (
            HeartbeatServer,
        )

        hb = HeartbeatServer("127.0.0.1", process_index=0)
        try:
            assert hb.port > 0
        finally:
            hb.stop()


# ---------------------------------------------------------------------------
# ISSUE 2: concurrent scrapes + metrics_dump --traces/--snapshot modes
# ---------------------------------------------------------------------------


def _load_metrics_dump():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "metrics_dump",
        Path(__file__).resolve().parent.parent / "tools" / "metrics_dump.py",
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_concurrent_scrapes_mid_workload():
    """Two clients hammer /metrics + /snapshot WHILE a workload mutates
    the registry and span ring: every response must be well-formed (no
    torn renders, no 500s) — the exporter reads live shared state under
    the instrument locks, and this pins that down."""
    stop = threading.Event()
    errors = []

    c = telemetry.counter("t_conc_total", "concurrency probe")
    h = telemetry.histogram("t_conc_seconds", "concurrency probe")

    def workload():
        i = 0
        while not stop.is_set():
            with telemetry.span("conc.op", i=i):
                c.inc()
                h.observe(0.001 * (i % 7))
            i += 1

    def scraper(base, route):
        try:
            for _ in range(25):
                with urllib.request.urlopen(base + route, timeout=10) as r:
                    body = r.read()
                    assert r.status == 200
                if route == "/metrics":
                    validate_prometheus_text(body.decode())
                else:
                    snap = json.loads(body)
                    assert "metrics" in snap and "traces" in snap
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append((route, e))

    with telemetry.start_exporter(port=0) as exporter:
        base = f"http://127.0.0.1:{exporter.port}"
        w = threading.Thread(target=workload, daemon=True)
        w.start()
        scrapers = [
            threading.Thread(target=scraper, args=(base, "/metrics")),
            threading.Thread(target=scraper, args=(base, "/snapshot")),
        ]
        for t in scrapers:
            t.start()
        for t in scrapers:
            t.join(timeout=60)
            assert not t.is_alive(), "scraper wedged"
        stop.set()
        w.join(timeout=10)
    assert not errors, errors


class TestMetricsDumpModes:
    def test_traces_mode_scrapes_span_trees(self, tmp_path, capsys):
        metrics_dump = _load_metrics_dump()
        with telemetry.span("md.traced"):
            pass
        out = tmp_path / "traces.jsonl"
        with telemetry.start_exporter(port=0) as exporter:
            rc = metrics_dump.main(
                ["--port", str(exporter.port), "--traces",
                 "--out", str(out)]
            )
            assert rc == 0
            rc = metrics_dump.main(["--port", str(exporter.port), "--traces"])
            assert rc == 0
        rec = json.loads(out.read_text())
        assert any(t["name"] == "md.traced" for t in rec["traces"])
        assert '"md.traced"' in capsys.readouterr().out

    def test_snapshot_mode_explicit(self, tmp_path):
        metrics_dump = _load_metrics_dump()
        telemetry.counter("t_md_total", "demo").inc(7)
        out = tmp_path / "snap.jsonl"
        with telemetry.start_exporter(port=0) as exporter:
            rc = metrics_dump.main(
                ["--port", str(exporter.port), "--snapshot",
                 "--out", str(out)]
            )
            assert rc == 0
        rec = json.loads(out.read_text())
        assert rec["metrics"]["t_md_total"]["children"][0]["value"] == 7

    def test_modes_are_mutually_exclusive(self, capsys):
        metrics_dump = _load_metrics_dump()
        with pytest.raises(SystemExit):
            metrics_dump.main(["--port", "1", "--traces", "--text"])
        capsys.readouterr()

    def test_unreachable_and_malformed_exit_nonzero(self, capsys):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        metrics_dump = _load_metrics_dump()
        # unreachable
        port = _free_port()
        assert metrics_dump.main(["--port", str(port), "--traces"]) == 1
        assert metrics_dump.main(["--port", str(port), "--snapshot"]) == 1

        # malformed: an endpoint answering garbage on every route
        class Garbage(BaseHTTPRequestHandler):
            def do_GET(self):
                body = b"<html>not telemetry</html>"
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), Garbage)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            gport = str(httpd.server_address[1])
            assert metrics_dump.main(["--port", gport, "--traces"]) == 1
            assert metrics_dump.main(["--port", gport, "--snapshot"]) == 1
            assert metrics_dump.main(["--port", gport, "--text"]) == 1
        finally:
            httpd.shutdown()
            httpd.server_close()
        capsys.readouterr()

    def test_wrong_shape_json_exits_nonzero(self, capsys):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        metrics_dump = _load_metrics_dump()

        class WrongShape(BaseHTTPRequestHandler):
            def do_GET(self):
                # valid JSON, wrong shape for BOTH routes: /traces gets
                # a dict, /snapshot a metrics-less dict
                body = b'{"oops": true}'
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), WrongShape)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            gport = str(httpd.server_address[1])
            assert metrics_dump.main(["--port", gport, "--traces"]) == 1
            assert metrics_dump.main(["--port", gport, "--snapshot"]) == 1
        finally:
            httpd.shutdown()
            httpd.server_close()
        capsys.readouterr()
