"""Laplace approximation (samplers/laplace.py).

Oracle 1: a Gaussian posterior, where Laplace is exact.  Oracle 2: the
federated linear-regression posterior, where the Laplace moments must
agree with the (near-Gaussian) NUTS posterior — and the Hessian is
taken straight through FederatedLogp's vmap/psum machinery, the
second-order capability the reference's boundary forbids
(reference: wrapper_ops.py:123-125).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu.samplers import laplace_approximation


class TestGaussianExact:
    def test_recovers_exact_moments(self):
        """For a Gaussian log-density Laplace is exact."""
        A = jnp.asarray([[2.0, 0.5], [0.5, 1.0]])
        mu = jnp.asarray([1.0, -2.0])

        def logp(p):
            d = p["x"] - mu
            return -0.5 * d @ A @ d

        res = laplace_approximation(
            logp, {"x": jnp.zeros(2)}, num_steps=2000, learning_rate=0.1
        )
        np.testing.assert_allclose(
            np.asarray(res.mean_flat), np.asarray(mu), atol=1e-3
        )
        np.testing.assert_allclose(
            np.asarray(res.cov_flat), np.linalg.inv(np.asarray(A)), atol=1e-3
        )

    def test_draws_and_stddev(self):
        def logp(p):
            return -0.5 * jnp.sum(p["x"] ** 2) - 0.5 * (p["y"] / 2.0) ** 2

        res = laplace_approximation(
            logp,
            {"x": jnp.zeros(3), "y": jnp.asarray(0.0)},
            num_steps=500,
            learning_rate=0.2,
        )
        draws = res.sample(jax.random.PRNGKey(0), num_draws=4000)
        assert draws["x"].shape == (4000, 3)
        np.testing.assert_allclose(
            float(jnp.std(draws["y"])), 2.0, rtol=0.1
        )
        sd = res.stddev()
        np.testing.assert_allclose(float(sd["y"]), 2.0, atol=1e-3)

    def test_nan_hessian_raises_distinct_error(self):
        """A diverged mode (NaN logp there) must be reported as a
        non-finite Hessian, not misdiagnosed as non-PD."""

        def logp(p):
            # sqrt of a negative: NaN value AND NaN derivatives.
            return jnp.sqrt(p["x"].sum())

        with pytest.raises(ValueError, match="non-finite Hessian"):
            laplace_approximation(
                logp,
                {"x": -jnp.ones(2)},
                mode={"x": -jnp.ones(2)},
            )

    def test_non_pd_raises(self):
        """Expanding around a saddle/maximum-free point must fail
        loudly, not emit NaN draws."""

        def logp(p):
            return 0.5 * jnp.sum(p["x"] ** 2)  # convex: no maximum

        with pytest.raises(ValueError, match="not positive definite"):
            laplace_approximation(
                logp, {"x": jnp.ones(2)}, mode={"x": jnp.ones(2)}
            )


class TestFederatedPosterior:
    def test_matches_nuts_moments(self):
        """Laplace through the full federated evaluator (Hessian through
        vmap + psum) agrees with NUTS on the near-Gaussian posterior."""
        from pytensor_federated_tpu.models.linear import (
            FederatedLinearRegression,
            generate_node_data,
        )

        data, _ = generate_node_data(4, n_obs=64, seed=7)
        model = FederatedLinearRegression(data)
        res = laplace_approximation(
            model.logp,
            model.init_params(),
            num_steps=1500,
            learning_rate=0.05,
        )
        nuts = model.sample(
            num_warmup=300,
            num_samples=300,
            num_chains=2,
            key=jax.random.PRNGKey(2),
        )
        lap_sd = res.stddev()
        for name in ("intercept", "slope"):
            post = nuts.samples[name]
            np.testing.assert_allclose(
                float(jnp.mean(post)),
                float(res.mode[name]),
                atol=4.0 * float(jnp.std(post)) / np.sqrt(post.size) + 0.02,
                err_msg=name,
            )
            np.testing.assert_allclose(
                float(jnp.std(post)),
                float(lap_sd[name]),
                rtol=0.3,
                err_msg=name,
            )
