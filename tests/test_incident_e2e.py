"""ISSUE 2 acceptance: a node wedges mid-batch across a REAL process
boundary and the driver — with zero manual steps — produces a merged
incident bundle containing BOTH sides' spans for the same trace id,
the flight-recorder tail, and an all-thread traceback; plus the wire
invariant that an untraced frame stays byte-identical to the PR-1
format under both codecs.

The child (tests/wedge_node_proc.py) is a plain npwire TCP node whose
compute blocks forever on a poison request — the stand-in for the
tunneled runtime's silent-wedge mode.  The driver's pipelined batch
arms the hang watchdog (service/tcp.py), so the wedge fires an
incident bundle while the batch is still stuck; the test then SIGKILLs
the node to unblock and assert the bundle's contents.
"""

import asyncio
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from pytensor_federated_tpu import telemetry
from pytensor_federated_tpu.telemetry import flightrec, reunion, watchdog
from pytensor_federated_tpu.telemetry import spans as tspans

HERE = os.path.dirname(os.path.abspath(__file__))
NODE = os.path.join(HERE, "wedge_node_proc.py")


@pytest.fixture(autouse=True)
def _clean_telemetry(tmp_path, monkeypatch):
    """Telemetry is process-global; isolate and point incidents at
    tmp_path so bundles never leak between tests."""
    monkeypatch.setenv("PFTPU_INCIDENT_DIR", str(tmp_path / "incidents"))
    # The per-arm-point bundle throttle is process-global state: an
    # earlier test in the same session (e.g. the chaos e2e) may have
    # fired the SAME arm point within the default 60 s gap, which would
    # silently suppress this test's bundle.
    monkeypatch.setenv("PFTPU_WATCHDOG_MIN_BUNDLE_GAP_S", "0")
    prev = tspans.set_enabled(True)
    prev_rec = flightrec.set_enabled(True)
    telemetry.REGISTRY.reset()
    telemetry.clear_traces()
    flightrec.clear()
    reunion.clear()
    yield
    tspans.set_enabled(prev)
    flightrec.set_enabled(prev_rec)
    telemetry.REGISTRY.reset()
    telemetry.clear_traces()
    flightrec.clear()
    reunion.clear()


def _spawn_node():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"  # the child imports the package, not jax
    proc = subprocess.Popen(
        [sys.executable, NODE],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    line = proc.stdout.readline().strip()
    assert line.startswith("PORT "), line
    return proc, int(line.split()[1])


@pytest.mark.slow
def test_wedged_node_midbatch_yields_merged_incident_bundle(
    tmp_path, monkeypatch
):
    from pytensor_federated_tpu.service.tcp import TcpArraysClient

    # A test-scale deadline: the watchdog must fire while the batch is
    # still wedged (the node sleeps 3600 s, the client socket times out
    # after 30 s — 1.5 s sits far below both).
    monkeypatch.setenv("PFTPU_WATCHDOG_RPC_S", "1.5")

    proc, port = _spawn_node()
    try:
        client = TcpArraysClient("127.0.0.1", port, retries=0)

        # 1) One HEALTHY call: its reply piggybacks the node's span
        #    tree, so the reunion store holds both halves of this trace
        #    BEFORE the incident — what the bundle must contain.
        out = client.evaluate(np.arange(3.0))
        np.testing.assert_array_equal(out[0], 2.0 * np.arange(3.0))
        (drv,) = [
            t
            for t in telemetry.recent_traces()
            if t["name"] == "rpc.evaluate"
        ]
        tid = drv["trace_id"]
        remote = reunion.remote_traces(tid)
        assert remote, "reply piggyback never reached the reunion store"
        assert remote[0]["name"] == "node.evaluate"

        # 2) Mid-batch WEDGE: request 2 of the pipelined window carries
        #    the poison value; the node blocks forever and the driver's
        #    batch read hangs inside the armed window.
        batch_err = {}

        def run_batch():
            try:
                client.evaluate_many(
                    [
                        (np.ones(2),),
                        (np.array([-1.0, 0.0]),),
                        (np.ones(2),),
                    ],
                    window=3,
                )
            except Exception as e:  # noqa: BLE001 - recorded for assert
                batch_err["exc"] = e

        # last_incident_path is process-global — wait for it to CHANGE
        # (an earlier test in the same process may have written one).
        before = watchdog.last_incident_path()
        t = threading.Thread(target=run_batch, daemon=True)
        t.start()

        # 3) ZERO manual steps: the incident bundle appears on its own.
        deadline = time.time() + 15
        bundle_path = None
        while time.time() < deadline:
            bundle_path = watchdog.last_incident_path()
            if bundle_path and bundle_path != before:
                break
            time.sleep(0.1)
        assert bundle_path and bundle_path != before, (
            "watchdog never produced an incident bundle"
        )
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    t.join(timeout=30)
    assert not t.is_alive(), "batch thread still stuck after node kill"
    assert isinstance(
        batch_err.get("exc"), (ConnectionError, OSError)
    ), batch_err

    with open(bundle_path, "r", encoding="utf-8") as fh:
        bundle = json.load(fh)

    # -- the acceptance assertions -------------------------------------
    assert bundle["reason"] == "watchdog:tcp.batch_window"

    # all-thread traceback, including the thread stuck in the batch read
    stacks = [
        "\n".join(th["stack"]) for th in bundle["threads"]
    ]
    assert any(
        "_evaluate_many_once" in s or "_read_frame" in s for s in stacks
    ), "no thread dump shows the wedged batch window"

    # the last N flight-recorder events, ending at the incident
    events = bundle["flightrec"]
    assert isinstance(events, list) and events
    kinds = {e["kind"] for e in events}
    assert "span.open" in kinds  # the still-open batch span is pinned
    open_names = {
        e.get("name") for e in events if e["kind"] == "span.open"
    }
    assert "rpc.evaluate_many" in open_names

    # merged driver+node spans for the SAME trace id
    merged = {
        tr["trace_id"]: tr for tr in bundle["trace_reunion"]
    }
    assert tid in merged, "healthy call's trace id missing from reunion"
    assert merged[tid]["driver"], "driver-side spans missing"
    assert merged[tid]["remote"], "node-side spans missing"
    assert merged[tid]["remote"][0]["name"] == "node.evaluate"
    assert merged[tid]["driver"][0]["name"] == "rpc.evaluate"

    # and the metrics snapshot rode along
    assert "metrics" in bundle["telemetry"]


class TestUntracedFramesByteIdentical:
    """Acceptance: with no active trace, request AND reply bytes are
    identical to the PR-1 wire format under both codecs — the reunion
    piggyback must be invisible until a trace asks for it."""

    def _serve_once(self, request: bytes) -> bytes:
        from pytensor_federated_tpu.service.server import (
            ArraysToArraysService,
        )

        service = ArraysToArraysService(lambda x: [2.0 * x])
        return asyncio.run(service.evaluate(request, None))

    def test_npwire_untraced_bytes_unchanged(self):
        from pytensor_federated_tpu.service.npwire import encode_arrays

        x = np.arange(4.0)
        uid = b"u" * 16
        request = encode_arrays([x], uuid=uid)  # no trace_id: PR-1 frame
        # telemetry fully ON — absence of a trace alone must keep the
        # wire clean...
        reply = self._serve_once(request)
        assert reply == encode_arrays([2.0 * x], uuid=uid)
        # ...and with telemetry OFF, byte-for-byte the same again.
        prev = tspans.set_enabled(False)
        try:
            reply_off = self._serve_once(request)
        finally:
            tspans.set_enabled(prev)
        assert reply_off == reply

    def test_npproto_untraced_bytes_unchanged(self):
        from pytensor_federated_tpu.service import npproto_codec as npc

        x = np.arange(4.0)
        request = npc.encode_arrays_msg([x], uuid="corr-1")
        reply = self._serve_once(request)
        assert reply == npc.encode_arrays_msg([2.0 * x], uuid="corr-1")
        prev = tspans.set_enabled(False)
        try:
            reply_off = self._serve_once(request)
        finally:
            tspans.set_enabled(prev)
        assert reply_off == reply

    def test_traced_npwire_reply_carries_spans_and_correlates(self):
        """The flip side: a TRACED request gets the piggyback, and the
        ingested node tree carries the driver's trace id."""
        from pytensor_federated_tpu.service.npwire import (
            decode_arrays_all,
            encode_arrays,
        )

        x = np.arange(4.0)
        tid = tspans.new_trace_id()
        request = encode_arrays([x], uuid=b"v" * 16, trace_id=tid)
        reply = self._serve_once(request)
        _arr, _uuid, _err, _rt, spans = decode_arrays_all(reply)
        assert spans and spans[0]["name"] == "node.evaluate"
        assert spans[0]["trace_id"] == tid.hex()

    def test_numpy_span_attrs_do_not_fail_the_reply(self):
        """The sidecar must never fail the RPC that carried results: a
        compute_fn opening its own span with a numpy attr (documented
        public API) still gets its reply through — the attr degrades
        to its string form in the piggybacked JSON."""
        from pytensor_federated_tpu.service.npwire import (
            decode_arrays_all,
            encode_arrays,
        )
        from pytensor_federated_tpu.service.server import (
            ArraysToArraysService,
        )

        def compute(x):
            with tspans.span("user.step", val=np.float32(0.5)):
                return [2.0 * x]

        x = np.arange(4.0)
        tid = tspans.new_trace_id()
        request = encode_arrays([x], uuid=b"n" * 16, trace_id=tid)
        # inline_compute: the user span must PARENT under the node tree
        # (the thread executor would not propagate the contextvars).
        service = ArraysToArraysService(compute, inline_compute=True)
        reply = asyncio.run(service.evaluate(request, None))
        arr, _u, _e, _t, spans = decode_arrays_all(reply)
        np.testing.assert_array_equal(arr[0], 2.0 * x)
        (tree,) = spans

        def find(node, name):
            if node.get("name") == name:
                return node
            for c in node.get("children", ()):
                got = find(c, name)
                if got is not None:
                    return got
            return None

        user = find(tree, "user.step")
        assert user is not None, tree
        assert user["attrs"]["val"] == "0.5"  # default=str degraded

    def test_ship_spans_false_keeps_traced_reply_clean(self):
        from pytensor_federated_tpu.service.npwire import (
            decode_arrays_all,
            encode_arrays,
        )
        from pytensor_federated_tpu.service.server import (
            ArraysToArraysService,
        )

        x = np.arange(4.0)
        tid = tspans.new_trace_id()
        request = encode_arrays([x], uuid=b"w" * 16, trace_id=tid)
        service = ArraysToArraysService(
            lambda a: [2.0 * a], ship_spans=False
        )
        reply = asyncio.run(service.evaluate(request, None))
        assert decode_arrays_all(reply)[4] is None
        assert reply == encode_arrays([2.0 * x], uuid=b"w" * 16)


def test_getload_traces_pull_reaches_reunion():
    """The PULL half of reunion: spans stranded on a live node (their
    reply already consumed without a trace... or lost) come home via
    GetLoad b"traces"."""
    from pytensor_federated_tpu.service import get_node_traces
    from pytensor_federated_tpu.service.npwire import encode_arrays
    from pytensor_federated_tpu.service.server import (
        ArraysToArraysService,
        serve,
    )

    import socket

    def _free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    tid = tspans.new_trace_id()

    async def main():
        port = _free_port()
        service = ArraysToArraysService(lambda x: [x + 1.0])
        server = await serve(None, "127.0.0.1", port, service=service)
        try:
            # Seed one traced node-side span WITHOUT a driver-side
            # decode of the reply (simulate a stranded trace).
            req = encode_arrays(
                [np.ones(2)], uuid=b"z" * 16, trace_id=tid
            )
            await service.evaluate(req, None)
            reunion.clear()  # the piggyback never reached any driver
            from pytensor_federated_tpu.service.client import (
                get_node_traces_async,
            )

            return await get_node_traces_async("127.0.0.1", port)
        finally:
            await server.stop(None)

    traces = asyncio.run(main())
    assert any(t["trace_id"] == tid.hex() for t in traces)
    assert reunion.remote_traces(tid.hex()), (
        "pulled traces were not ingested into the reunion store"
    )
    assert callable(get_node_traces)  # sync wrapper exported
