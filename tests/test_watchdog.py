"""Hang watchdog + incident bundles (telemetry/watchdog.py) and the
postmortem renderer (tools/incident_report.py)."""

import importlib.util
import json
import os
import threading
import time
from pathlib import Path

import pytest

from pytensor_federated_tpu import telemetry
from pytensor_federated_tpu.telemetry import flightrec, reunion, watchdog
from pytensor_federated_tpu.telemetry import spans as tspans

TOOLS = Path(__file__).resolve().parent.parent / "tools"


@pytest.fixture(autouse=True)
def _clean(tmp_path, monkeypatch):
    monkeypatch.setenv("PFTPU_INCIDENT_DIR", str(tmp_path / "incidents"))
    prev = tspans.set_enabled(True)
    prev_rec = flightrec.set_enabled(True)
    flightrec.clear()
    reunion.clear()
    telemetry.clear_traces()
    yield
    tspans.set_enabled(prev)
    flightrec.set_enabled(prev_rec)
    flightrec.clear()
    reunion.clear()
    telemetry.clear_traces()


def _load_incident_report():
    spec = importlib.util.spec_from_file_location(
        "incident_report", TOOLS / "incident_report.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestArming:
    def test_disarm_before_deadline_never_fires(self):
        with watchdog.armed("unit.fast", 0.25) as tok:
            pass  # exits (disarms) immediately
        time.sleep(0.6)
        assert not tok.fired and tok.bundle is None

    def test_expiry_fires_and_writes_bundle(self):
        tok = watchdog.arm("unit.hang", 0.2, site="test")
        time.sleep(0.8)
        assert tok.fired
        assert tok.bundle and os.path.exists(tok.bundle)
        assert watchdog.last_incident_path() == tok.bundle
        with open(tok.bundle) as fh:
            bundle = json.load(fh)
        assert bundle["reason"] == "watchdog:unit.hang"
        assert bundle["attrs"] == {"site": "test"}
        # the firing itself is flight-recorded
        kinds = [e["kind"] for e in flightrec.events()]
        assert "watchdog.fired" in kinds and "incident.bundle" in kinds

    def test_zero_timeout_and_disabled_telemetry_are_noops(self):
        tok = watchdog.arm("unit.off", 0.0)
        assert tok is not None and not tok.fired
        tspans.set_enabled(False)
        try:
            tok2 = watchdog.arm("unit.off2", 0.05)
        finally:
            tspans.set_enabled(True)
        time.sleep(0.2)
        assert not tok2.fired

    def test_same_name_refires_throttled(self, monkeypatch):
        """A re-armed point firing again within the bundle gap is
        flight-recorded but must NOT write a second bundle."""
        monkeypatch.setenv("PFTPU_WATCHDOG_MIN_BUNDLE_GAP_S", "60")
        first = watchdog.arm("unit.refire", 0.15)
        time.sleep(0.6)
        assert first.fired and first.bundle
        second = watchdog.arm("unit.refire", 0.15)
        time.sleep(0.6)
        assert second.fired and second.bundle is None
        fires = [
            e for e in flightrec.events()
            if e["kind"] == "watchdog.fired" and e["name"] == "unit.refire"
        ]
        assert [f["throttled"] for f in fires] == [False, True]

    def test_same_second_bundles_get_distinct_paths(self):
        p1 = watchdog.write_incident_bundle("same-sec")
        p2 = watchdog.write_incident_bundle("same-sec")
        assert p1 != p2 and os.path.exists(p1) and os.path.exists(p2)

    def test_nested_arms_fire_independently(self):
        slow = watchdog.arm("unit.slow", 30.0)
        fast = watchdog.arm("unit.fast", 0.2)
        time.sleep(0.8)
        assert fast.fired and not slow.fired
        watchdog.disarm(slow)

    def test_armed_default_reads_env(self, monkeypatch):
        monkeypatch.setenv("PFTPU_WATCHDOG_RPC_S", "123.5")
        assert watchdog.rpc_timeout_s() == 123.5
        monkeypatch.setenv("PFTPU_WATCHDOG_RPC_S", "0")
        with watchdog.armed("unit.env") as tok:
            pass
        assert not tok.fired  # 0 = disarmed -> noop token


class TestBundleContents:
    def test_bundle_sections(self):
        with telemetry.span("bundle.op"):
            flightrec.record("unit.pre_incident", hint=1)
            path = watchdog.write_incident_bundle(
                "unit-test", attrs={"k": "v"}
            )
        with open(path) as fh:
            bundle = json.load(fh)
        assert bundle["reason"] == "unit-test"
        assert bundle["pid"] == os.getpid()
        # all-thread dump includes THIS thread, by name
        me = threading.current_thread().name
        assert any(t["name"] == me for t in bundle["threads"])
        assert any(t["stack"] for t in bundle["threads"])
        # flight record tail rode along (the open span is pinned)
        kinds = {e["kind"] for e in bundle["flightrec"]}
        assert {"unit.pre_incident", "span.open"} <= kinds
        # metrics + reunion sections exist
        assert "metrics" in bundle["telemetry"]
        assert isinstance(bundle["trace_reunion"], list)

    def test_bundle_merges_reunion_traces(self):
        with telemetry.span("merge.op"):
            tid = tspans.current_trace_id().hex()
        reunion.ingest(
            [{"name": "node.evaluate", "trace_id": tid, "duration_s": 1.0}]
        )
        path = watchdog.write_incident_bundle("unit-merge")
        with open(path) as fh:
            bundle = json.load(fh)
        merged = {t["trace_id"]: t for t in bundle["trace_reunion"]}
        assert tid in merged
        assert merged[tid]["driver"] and merged[tid]["remote"]


class TestIncidentReportTool:
    def _bundle(self):
        with telemetry.span("report.op"):
            flightrec.record("unit.ev", n=3)
            tid = tspans.current_trace_id().hex()
        reunion.ingest([{"name": "node.evaluate", "trace_id": tid}])
        telemetry.counter("t_report_total", "demo").inc(2)
        return watchdog.write_incident_bundle("render-me")

    def test_markdown_render(self, tmp_path, capsys):
        mod = _load_incident_report()
        path = self._bundle()
        assert mod.main([path]) == 0
        out = capsys.readouterr().out
        assert "# Incident: render-me" in out
        assert "## All-thread traceback" in out
        assert "`unit.ev`" in out
        assert "node.evaluate" in out
        assert "t_report_total" in out

    def test_jsonl_render_and_outfile(self, tmp_path):
        mod = _load_incident_report()
        path = self._bundle()
        out = tmp_path / "post.jsonl"
        assert mod.main([path, "--jsonl", "-o", str(out)]) == 0
        lines = [json.loads(l) for l in out.read_text().splitlines()]
        assert lines[0]["record"] == "incident"
        assert lines[0]["reason"] == "render-me"
        assert any(
            l["record"] == "event" and l["kind"] == "unit.ev"
            for l in lines[1:]
        )

    def test_bad_inputs_exit_nonzero(self, tmp_path, capsys):
        mod = _load_incident_report()
        assert mod.main([str(tmp_path / "missing.json")]) == 1
        bad = tmp_path / "bad.json"
        bad.write_text("{\"not\": \"a bundle\"}")
        assert mod.main([str(bad)]) == 1
        capsys.readouterr()


class TestReunionStore:
    def test_ingest_bounds_and_filters(self):
        assert reunion.ingest([{"no_trace": 1}, "garbage", None]) == 0
        n = reunion.ingest(
            [{"name": "a", "trace_id": "t1"}, {"name": "b", "trace_id": "t1"}]
        )
        assert n == 2
        assert len(reunion.remote_traces("t1")) == 2
        assert reunion.remote_traces("t1")[0]["source"] == "node"

    def test_merged_lines_up_both_sides(self):
        with telemetry.span("pair.op"):
            tid = tspans.current_trace_id().hex()
        reunion.ingest([{"name": "node.evaluate", "trace_id": tid}])
        m = reunion.merged(tid)
        assert m["driver"][0]["name"] == "pair.op"
        assert m["remote"][0]["name"] == "node.evaluate"

    def test_disabled_telemetry_ingests_nothing(self):
        tspans.set_enabled(False)
        try:
            assert reunion.ingest([{"name": "x", "trace_id": "t"}]) == 0
        finally:
            tspans.set_enabled(True)
        assert reunion.remote_traces("t") == []

    def test_capacity_evicts_oldest_trace(self, monkeypatch):
        # cap applies per trace-id bucket creation
        monkeypatch.setattr(reunion, "_CAP", 3)
        for i in range(5):
            reunion.ingest([{"name": "n", "trace_id": f"cap{i}"}])
        assert reunion.remote_traces("cap0") == []
        assert reunion.remote_traces("cap4")

    def test_repeated_ingest_dedups(self):
        """The GetLoad pull lane re-delivers the same trees every poll;
        re-ingesting identical content must be a no-op (the store's
        bounded claim depends on it)."""
        tree = {"name": "node.evaluate", "trace_id": "dup1",
                "duration_s": 0.5}
        assert reunion.ingest([tree]) == 1
        for _ in range(10):
            assert reunion.ingest([tree]) == 0
        assert len(reunion.remote_traces("dup1")) == 1
        # distinct content still accumulates (bounded per bucket)
        assert reunion.ingest([{**tree, "duration_s": 0.7}]) == 1
        assert len(reunion.remote_traces("dup1")) == 2

    def test_bucket_cap_bounds_per_trace_growth(self, monkeypatch):
        monkeypatch.setattr(reunion, "_BUCKET_CAP", 4)
        for i in range(10):
            reunion.ingest(
                [{"name": "n", "trace_id": "bcap", "i": i}]
            )
        assert len(reunion.remote_traces("bcap")) == 4
