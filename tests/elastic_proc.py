"""Child driver for the PROCESS-RESTART tier of elastic sampling.

Launched as ``python elastic_proc.py <ckpt> <out_npz> <mode>`` by
tests/test_elastic.py (a FILE on purpose: CLAUDE.md spawn pitfall).
``mode``:

- ``crash``  — the blackbox host node hard-kills the PROCESS
  (``os._exit(42)``) as soon as chunk 0's sidecar exists: the abrupt
  death stands in for the collective-wedge abort, whose recovery
  contract is identical (nothing graceful runs either way).
- ``run``    — no bomb: runs to completion (a fresh process resumes
  from whatever checkpoint exists) and saves the draws to out_npz.

The logp spans a REAL 8-virtual-device mesh psum (FederatedLogp) plus
a blackbox host term — the composition whose in-process recovery is
impossible (a failing participant wedges the collective), i.e. exactly
the case the restart tier exists for.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main():
    ckpt, out_npz, mode = sys.argv[1], sys.argv[2], sys.argv[3]
    sys.path.insert(0, REPO)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
    from pytensor_federated_tpu.utils import force_cpu_backend

    force_cpu_backend()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytensor_federated_tpu import blackbox_logp_grad, pack_shards
    from pytensor_federated_tpu.parallel import make_mesh
    from pytensor_federated_tpu.parallel.sharded import FederatedLogp
    from pytensor_federated_tpu.samplers import elastic_sample

    rng = np.random.default_rng(0)
    shards = []
    for _ in range(8):
        x = rng.normal(size=(32,)).astype(np.float32)
        shards.append((x, (1.5 * x + 0.1).astype(np.float32)))
    data = pack_shards(shards)

    def bomb_host(x):
        if mode == "crash" and os.path.exists(ckpt + ".chunk0000.npz"):
            os._exit(42)  # the process dies; nothing graceful runs
        return np.float32(0.0), [np.zeros_like(x)]

    bomb = blackbox_logp_grad(
        bomb_host, (jax.ShapeDtypeStruct((1,), jnp.float32),)
    )

    def build_logp(mesh):
        fed = FederatedLogp(
            lambda p, shard: -0.5
            * jnp.sum((shard[0][1] - p["w"] * shard[0][0]) ** 2 * shard[1]),
            data.tree(),
            mesh=mesh,
        )

        def logp(params):
            return fed.logp(params) + bomb(params["w"][None])[0]

        return logp

    res = elastic_sample(
        build_logp,
        {"w": jnp.asarray(0.0)},
        key=jax.random.PRNGKey(3),
        checkpoint_path=ckpt,
        mesh=make_mesh({"shards": 8}),
        num_warmup=100,
        num_samples=90,
        num_chains=2,
        checkpoint_every=30,
    )
    np.savez(out_npz, w=np.asarray(res.samples["w"]))
    print(f"DONE w_mean={float(np.mean(np.asarray(res.samples['w']))):.4f}")
    # os._exit: a dead-collective thread in atexit must not hang a
    # SUCCESSFUL run's exit (same policy as multihost_proc.py).
    sys.stdout.flush()
    os._exit(0)


if __name__ == "__main__":
    main()
