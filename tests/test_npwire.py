"""Wire-format round-trip tests (reference: test_npproto.py:11-31)."""

import numpy as np
import pytest

from pytensor_federated_tpu.service.npwire import (
    WireError,
    decode_arrays,
    encode_arrays,
)

CASES = [
    np.float32(4.5),  # 0-d
    np.array([1, 2, 3], dtype=np.int64),
    np.random.default_rng(0).normal(size=(4, 5)),  # 2-D float64
    np.array(["hello", "wire"]),  # unicode
    np.array([np.datetime64("2026-07-29"), np.datetime64("2000-01-01")]),
    np.arange(20, dtype=np.float32).reshape(4, 5)[:, ::2],  # non-contiguous
    np.zeros((0, 3), dtype=np.float32),  # empty
    np.array(True),  # bool scalar
]


@pytest.mark.parametrize("arr", CASES, ids=lambda a: f"{a.dtype}-{a.shape}")
def test_roundtrip(arr):
    buf = encode_arrays([arr], uuid=b"u" * 16)
    out, uuid, error = decode_arrays(buf)
    assert uuid == b"u" * 16
    assert error is None
    np.testing.assert_array_equal(out[0], arr)
    assert out[0].dtype == arr.dtype
    assert out[0].shape == np.shape(arr)  # 0-d must stay 0-d


def test_multiple_arrays_one_message():
    arrays = [np.ones(3), np.int32(7), np.zeros((2, 2))]
    out, _, _ = decode_arrays(encode_arrays(arrays))
    assert len(out) == 3
    for a, b in zip(arrays, out):
        np.testing.assert_array_equal(a, b)


def test_error_message_roundtrip():
    buf = encode_arrays([], error="boom: bad input")
    out, _, error = decode_arrays(buf)
    assert out == []
    assert error == "boom: bad input"


def test_object_dtype_rejected():
    """The reference admits object dtype 'doesn't work' but serializes
    pointers anyway (reference: README.md:30); here it's a hard error."""
    with pytest.raises(WireError, match="object"):
        encode_arrays([np.array([object()])])


def test_truncated_rejected():
    buf = encode_arrays([np.ones(100)])
    with pytest.raises(WireError):
        decode_arrays(buf[: len(buf) // 2])
    with pytest.raises(WireError, match="magic"):
        decode_arrays(b"XXXX" + buf[4:])


def test_bad_uuid_length():
    with pytest.raises(WireError, match="uuid"):
        encode_arrays([], uuid=b"short")


def test_invalid_utf8_dtype_is_wire_error():
    """A bit-flipped dtype descriptor must fail as WireError, not leak
    UnicodeDecodeError."""
    import numpy as np
    import pytest

    from pytensor_federated_tpu.service.npwire import (
        WireError,
        decode_arrays,
        encode_arrays,
    )

    enc = bytearray(encode_arrays([np.zeros(3, np.float32)]))
    # dtype string starts right after header(26) + dtlen(2).
    enc[28] = 0xFF
    enc[29] = 0xFE
    with pytest.raises(WireError):
        decode_arrays(bytes(enc))


def test_unknown_flag_bits_rejected():
    """Regression (graftlint wire-registry): a frame carrying a flag
    bit outside the declared mask must fail LOUDLY — parsing around an
    unknown block would silently mis-read everything after it (the
    version-skew hazard the loud-failure contract exists for)."""
    from pytensor_federated_tpu.service.npwire import (
        _FLAGS_OFF,
        decode_arrays,
        decode_batch,
        encode_arrays,
        encode_batch,
    )

    # ISSUE 16 saturated the flag byte (128 = VERSION), so no
    # undeclared bit remains to flip — the loud-failure posture now
    # shows as a corrupt-block refusal: a flag claiming a block the
    # frame does not carry must fail as WireError, never mis-parse.
    enc = bytearray(encode_arrays([]))
    enc[_FLAGS_OFF] |= 0x80  # VERSION flag with no version block
    with pytest.raises(WireError, match="truncated version block"):
        decode_arrays(bytes(enc))

    batch = bytearray(encode_batch([]))
    batch[_FLAGS_OFF] |= 0x80  # VERSION flag with no version block
    with pytest.raises(WireError, match="truncated"):
        decode_batch(bytes(batch))

    # The guard itself still fires on a mask wider than one byte can
    # carry (future-proofing the helper, not the wire).
    from pytensor_federated_tpu.service.npwire import _check_flags
    with pytest.raises(WireError, match="unknown flag bits"):
        _check_flags(0x100)


def test_known_flag_combinations_still_decode():
    """The rejection must not over-reach: every declared flag
    combination keeps decoding (error + trace on a plain frame)."""
    from pytensor_federated_tpu.service.npwire import (
        decode_arrays_ex,
        encode_arrays,
    )

    enc = encode_arrays(
        [np.ones(2)], error="boom", trace_id=b"t" * 16
    )
    arrays, _uuid, error, trace_id = decode_arrays_ex(enc)
    assert error == "boom" and trace_id == b"t" * 16
    np.testing.assert_array_equal(arrays[0], np.ones(2))
