"""Robust regression: scipy golden, outlier resistance, inference.

The headline property test: on shards with 10% gross (Cauchy-scaled)
outliers, the t-likelihood recovers the true slopes where the Gaussian
model is dragged away — the reason the family exists.
"""

import jax
import jax.numpy as jnp
import numpy as np
import scipy.stats

from pytensor_federated_tpu.models.robust import (
    FederatedRobustRegression,
    generate_robust_data,
    student_t_logpdf,
)


def test_logpdf_matches_scipy():
    rng = np.random.default_rng(0)
    y = rng.normal(0, 3, size=60).astype(np.float32)
    loc = rng.normal(0, 1, size=60).astype(np.float32)
    ours = np.asarray(
        student_t_logpdf(jnp.asarray(y), jnp.asarray(loc), 0.7, 4.5)
    )
    golden = scipy.stats.t.logpdf(y, df=4.5, loc=loc, scale=0.7)
    np.testing.assert_allclose(ours, golden, rtol=2e-4, atol=2e-4)


def test_large_nu_approaches_gaussian():
    y = jnp.linspace(-3, 3, 13)
    t_ll = student_t_logpdf(y, 0.0, 1.0, 1e4)
    g_ll = -0.5 * y**2 - 0.5 * jnp.log(2 * jnp.pi)
    np.testing.assert_allclose(np.asarray(t_ll), np.asarray(g_ll), atol=2e-3)


def test_map_resists_outliers_where_gaussian_fails():
    data, truth = generate_robust_data(
        8, n_obs=96, n_features=3, outlier_frac=0.1, outlier_scale=20.0,
        seed=42,
    )
    robust = FederatedRobustRegression(data)
    est = robust.find_map()
    err_robust = float(np.abs(np.asarray(est["w"]) - truth["w"]).max())

    # Gaussian comparator: the SAME model with nu pinned huge (the
    # t-density at nu=1e4 is Gaussian to 4 decimals, pinned above).
    from pytensor_federated_tpu.samplers import find_map

    def gauss_logp(p):
        q = dict(p)
        q["log_numinus1"] = jnp.asarray(float(np.log(1e4)))
        return robust.logp(q)

    p_g = find_map(gauss_logp, robust.init_params())
    err_gauss = float(np.abs(np.asarray(p_g["w"]) - truth["w"]).max())

    assert err_robust < 0.15, f"robust MAP err {err_robust}"
    # The Gaussian fit must be measurably worse — this is the point.
    assert err_gauss > 1.5 * err_robust, (err_gauss, err_robust)


def test_nu_learns_tails():
    # Clean data -> large nu; contaminated data -> small nu.
    clean, _ = generate_robust_data(4, n_obs=96, outlier_frac=0.0, seed=1)
    dirty, _ = generate_robust_data(4, n_obs=96, outlier_frac=0.15, seed=1)
    m_clean = FederatedRobustRegression(clean)
    m_dirty = FederatedRobustRegression(dirty)
    nu_clean = float(m_clean.nu(m_clean.find_map()))
    nu_dirty = float(m_dirty.nu(m_dirty.find_map()))
    assert nu_dirty < nu_clean


def test_nuts_converges():
    data, truth = generate_robust_data(4, n_obs=64, n_features=2, seed=3)
    m = FederatedRobustRegression(data)
    res = m.sample(
        key=jax.random.PRNGKey(4),
        num_warmup=300,
        num_samples=300,
        num_chains=2,
    )
    summ = res.summary()
    assert float(np.max(np.asarray(summ["rhat"]["w"]))) < 1.06
    w_mean = np.asarray(res.samples["w"]).mean(axis=(0, 1))
    np.testing.assert_allclose(w_mean, truth["w"], atol=0.2)


def test_on_mesh(devices8):
    from pytensor_federated_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"shards": 8}, devices=devices8)
    data, _ = generate_robust_data(8, n_obs=32, n_features=2, seed=9)
    m_mesh = FederatedRobustRegression(data, mesh=mesh)
    m_local = FederatedRobustRegression(data)
    p0 = m_local.init_params()
    np.testing.assert_allclose(
        float(m_mesh.logp(p0)), float(m_local.logp(p0)), rtol=5e-4
    )
