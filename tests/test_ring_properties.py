"""Property-based seqlock-ring tests (hypothesis) — ISSUE 18 satellite.

The ring's loud-failure surface, explored exhaustively: random frame
sizes (single-record, spanning, wraparound laps) must round-trip
byte-exact through produce/recv, and a single torn seqlock WORD — any
scribble that changes a committed record's sequence stamp — must
surface as :class:`WireError`, never a hang (the producer's published
counter makes a not-ready stamp definitively torn) and never silently
wrong bytes.  The integrity the arena slots get from generations, the
descriptor rings get from the seqlock stamps; these tests are its pin.
"""

import struct

import pytest

from pytensor_federated_tpu.service.arena import Arena
from pytensor_federated_tpu.service.npwire import WireError
from pytensor_federated_tpu.service.ring import (
    Ring,
    _RING_RECORDS_OFFSET,
    _U64,
    init_ring_header,
)

# Hypothesis-optional (the round-16 posture): the fuzz lanes below are
# importorskip-gated; their deterministic seed twins — single torn
# word, roundtrip across laps, future-lap/wrong-slot/zeroed stamps —
# always run in tests/test_ring_transport.py::TestRingProtocol.
hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

COMMON = settings(max_examples=50, deadline=None)

_SLOTS = 8
_RECORD_BYTES = 128
_PAYLOAD_CAP = _RECORD_BYTES - 16


def _fresh_rings(tmp_path, name):
    arena = Arena.create(
        1 << 20,
        path=str(tmp_path / name),
        ring_slots=_SLOTS,
        ring_record_bytes=_RECORD_BYTES,
    )
    init_ring_header(arena)
    return (
        arena,
        Ring(arena, role="producer"),
        Ring(arena, role="consumer"),
    )


@COMMON
@given(
    sizes=st.lists(
        st.integers(1, _PAYLOAD_CAP * _SLOTS), min_size=1, max_size=12
    ),
    seed=st.integers(0, 2**32 - 1),
)
def test_frames_roundtrip_any_size(tmp_path_factory, sizes, seed):
    """Every admissible frame size — sub-record, exact-cap, spanning,
    whole-ring — round-trips byte-exact, in order, across laps."""
    tmp = tmp_path_factory.mktemp("ringprop")
    arena, prod, cons = _fresh_rings(tmp, "rt.shm")
    try:
        for i, n in enumerate(sizes):
            frame = bytes((seed + i * 131 + j * 7) % 256 for j in range(n))
            assert prod.try_produce(frame)
            assert cons.recv(timeout_s=5.0) == frame
    finally:
        arena.close(unlink=True)


@COMMON
@given(
    size=st.integers(1, _PAYLOAD_CAP * 3),
    record_idx=st.integers(0, 2),
    word=st.integers(0, 2**64 - 1),
)
def test_single_torn_seq_word_is_loud(
    tmp_path_factory, size, record_idx, word
):
    """Scribbling ONE committed record's seqlock word with any value
    that changes it yields WireError — never a silently wrong frame,
    never an unbounded wait (the published produced counter converts
    'mid-write' observations into torn-write classifications)."""
    tmp = tmp_path_factory.mktemp("ringprop")
    arena, prod, cons = _fresh_rings(tmp, "torn.shm")
    try:
        frame = bytes(j % 256 for j in range(size))
        nrec = -(-size // _PAYLOAD_CAP)
        idx = min(record_idx, nrec - 1)
        assert prod.try_produce(frame)
        rec = _RING_RECORDS_OFFSET + idx * _RECORD_BYTES
        committed = _U64.unpack_from(arena.mm, rec)[0]
        if word == committed:
            word ^= 1  # ensure the scribble actually changes the stamp
        _U64.pack_into(arena.mm, rec, word)
        with pytest.raises(WireError):
            cons.recv(timeout_s=10.0)
    finally:
        arena.close(unlink=True)


@COMMON
@given(total=st.integers(0, 2**32 - 1))
def test_corrupt_length_word_never_overreads(tmp_path_factory, total):
    """A scribbled record-0 LENGTH word either reproduces a legal
    shorter read, raises WireError (out of ring bounds), or times out
    bounded on never-committed continuations — it can never read
    beyond the ring or hang."""
    tmp = tmp_path_factory.mktemp("ringprop")
    arena, prod, cons = _fresh_rings(tmp, "len.shm")
    try:
        assert prod.try_produce(b"x" * 40)
        struct.pack_into(
            "<I", arena.mm, _RING_RECORDS_OFFSET + 8, total
        )
        try:
            out = cons.recv(timeout_s=0.5)
            assert len(out) == total  # consistent with the scribble
        except (WireError, TimeoutError):
            pass  # loud: oob length or never-committed continuation
    finally:
        arena.close(unlink=True)
