"""Property-based fed autodiff gate (hypothesis; CI-gated like
test_npproto_properties.py — skips where hypothesis is not installed).

The invariant (ISSUE 6 satellite): for random pytrees,
``jax.grad`` through ``fed_sum(fed_map(f, x))`` equals the unsharded
``jax.grad(lambda x: sum_i f(x_i))`` — on one device AND the 8-device
virtual mesh, including the replicated-params case (params reach the
shard body as closure constants, the configuration that requires
``mark_varying`` / the fed_sum-of-cotangents transpose).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from pytensor_federated_tpu import fed  # noqa: E402
from pytensor_federated_tpu.parallel import make_mesh  # noqa: E402

N = 8  # fixed shard count: divides the virtual mesh axis
_PROP = settings(max_examples=15, deadline=None)

_dims = st.integers(min_value=1, max_value=4)
_param_shapes = st.lists(
    st.lists(_dims, min_size=0, max_size=2).map(tuple),
    min_size=1,
    max_size=2,
)
_data_shapes = st.lists(
    st.lists(_dims, min_size=1, max_size=2).map(tuple),
    min_size=1,
    max_size=3,
)


def _make_case(seed, param_shapes, data_shapes):
    rng = np.random.default_rng(seed)
    params = tuple(
        jnp.asarray(rng.normal(size=s).astype(np.float32))
        for s in param_shapes
    )
    data = {
        f"d{i}": jnp.asarray(
            rng.normal(size=(N,) + s).astype(np.float32)
        )
        for i, s in enumerate(data_shapes)
    }
    return params, data


def _per_shard(params, shard):
    acc = jnp.float32(0.0)
    scale = jnp.float32(1.0)
    for p in params:
        scale = scale + jnp.sum(jnp.tanh(p))
    for leaf in shard.values():
        acc = acc + jnp.sum(jnp.sin(leaf) * scale + 0.1 * leaf**2)
    return acc


def _reference(params, data):
    return sum(
        _per_shard(params, {k: v[i] for k, v in data.items()})
        for i in range(N)
    )


def _assert_grads_match(fed_fn, ref_fn, params):
    v, g = jax.value_and_grad(fed_fn, argnums=tuple(range(len(params))))(
        *params
    )
    v_ref, g_ref = jax.value_and_grad(
        ref_fn, argnums=tuple(range(len(params)))
    )(*params)
    np.testing.assert_allclose(float(v), float(v_ref), rtol=2e-4, atol=1e-4)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-4
        )


@_PROP
@given(
    seed=st.integers(min_value=0, max_value=2**31 - 1),
    param_shapes=_param_shapes,
    data_shapes=_data_shapes,
)
def test_grad_map_sum_matches_unsharded_single_device(
    seed, param_shapes, data_shapes
):
    params, data = _make_case(seed, param_shapes, data_shapes)

    def fed_broadcast_form(*ps):
        pb = fed.fed_broadcast(tuple(ps), N)
        lps = fed.fed_map(lambda s: _per_shard(s[0], s[1]), (pb, data))
        return fed.fed_sum(lps)

    def fed_closure_form(*ps):
        lps = fed.fed_map(lambda s: _per_shard(ps, s), data)
        return fed.fed_sum(lps)

    ref = lambda *ps: _reference(ps, data)
    _assert_grads_match(fed_broadcast_form, ref, params)
    _assert_grads_match(fed_closure_form, ref, params)


@_PROP
@given(
    seed=st.integers(min_value=0, max_value=2**31 - 1),
    param_shapes=_param_shapes,
    data_shapes=_data_shapes,
)
def test_grad_map_sum_matches_unsharded_mesh8(
    devices8, seed, param_shapes, data_shapes
):
    params, data = _make_case(seed, param_shapes, data_shapes)
    placement = fed.MeshPlacement(
        make_mesh({"shards": 8}, devices=devices8)
    )

    def model_broadcast(*ps):
        pb = fed.fed_broadcast(tuple(ps), N)
        lps = fed.fed_map(lambda s: _per_shard(s[0], s[1]), (pb, data))
        return fed.fed_sum(lps)

    def model_closure(*ps):
        # Replicated params as closure constants: the mark_varying /
        # summed-cotangent configuration.
        lps = fed.fed_map(lambda s: _per_shard(ps, s), data)
        return fed.fed_sum(lps)

    ref = lambda *ps: _reference(ps, data)
    _assert_grads_match(
        fed.program(model_broadcast, placement), ref, params
    )
    _assert_grads_match(
        fed.program(model_closure, placement), ref, params
    )
